"""Metrics pipeline (ISSUE 2): MetricsRegistry determinism, kernel
telemetry on the device conflict engine, latency-chain reassembly, and
the status/CLI surfacing.

Ref: flow/Stats.h traceCounters, the CommitDebug/TransactionDebug
g_traceBatch chains, Status.actor.cpp's qos latency percentiles.
"""

import json

import pytest

from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.flow.knobs import g_knobs
from foundationdb_tpu.flow.latency_chain import (
    COMMIT_CHAIN,
    latency_summary,
    percentile,
    summarize_stages,
)
from foundationdb_tpu.flow.metrics import (
    BoundedHistogram,
    MetricsRegistry,
    emit_metrics,
)
from foundationdb_tpu.flow.trace import global_collector
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.status import cluster_status
from foundationdb_tpu.tools.cli import CliProcessor

pytestmark = pytest.mark.metrics


@pytest.fixture(autouse=True)
def _sampled_clean():
    saved = g_knobs.client.latency_sample_rate
    g_knobs.client.latency_sample_rate = 1.0
    global_collector().clear()
    yield
    g_knobs.client.latency_sample_rate = saved
    set_event_loop(None)


def _drive(c, db, cli, line):
    return c.loop.run_until(
        db.process.spawn(cli.run_command(line)), timeout_vt=60.0
    )


def _run_workload(seed: int):
    """One full sim run; returns (resolver snapshot json, proxy snapshot
    json, latency summary) — everything the determinism gate compares."""
    global_collector().clear()
    c = SimCluster(seed=seed)
    db = c.database("det")

    async def load():
        for i in range(12):
            tr = db.create_transaction()
            tr.set(b"d%03d" % (i % 5), b"v%d" % i)
            await tr.commit()
        await c.loop.delay(6.0)  # one emitter interval

    c.run_until(db.process.spawn(load(), "load"), timeout_vt=1000.0)
    now = c.loop.now()
    out = (
        c.resolver.metrics.snapshot_json(now=now),
        c.proxy.metrics.snapshot_json(now=now),
        latency_summary(global_collector().events),
    )
    set_event_loop(None)
    return out


def test_same_seed_snapshots_byte_identical():
    """The acceptance gate: two same-seed runs produce byte-identical
    registry snapshots and identical latency-chain summaries — i.e. the
    whole pipeline observes only virtual time + DeterministicRandom."""
    r1, p1, l1 = _run_workload(4201)
    r2, p2, l2 = _run_workload(4201)
    assert r1 == r2
    assert p1 == p2
    assert l1 == l2
    # And the run actually produced signal, not vacuous empties.
    snap = json.loads(r1)
    assert snap["counters"]["committed"] >= 12
    assert snap["histograms"]["batch_size"]["count"] >= 1
    assert l1["commit"]["total"]["count"] >= 1
    # A different seed must be allowed to differ (the comparison is not
    # trivially constant).
    r3, _p3, _l3 = _run_workload(4202)
    assert json.loads(r3)["counters"]["committed"] >= 12
    assert r3 != r1


def test_registry_snapshot_shape_and_wall_exclusion():
    reg = MetricsRegistry("X")
    reg.counter("c").add(3)
    reg.gauge("g").set(7)
    reg.histogram("h").add(1.0)
    reg.histogram("h").add(3.0)
    reg.record_wall("disp", 0.25)
    snap = reg.snapshot()
    # No loop set: no timestamp at all — never a wall-clock fallback.
    assert "time" not in snap
    assert snap["counters"] == {"c": 3}
    assert snap["gauges"] == {"g": 7}
    h = snap["histograms"]["h"]
    assert h["count"] == 2 and h["min"] == 1.0 and h["max"] == 3.0
    assert h["mean"] == 2.0
    # rng-less histogram: aggregates only, no percentile keys.
    assert "median" not in h
    # Wall namespace excluded from the deterministic view...
    assert "wall" not in snap
    # ...but reachable for real-mode tooling.
    w = reg.snapshot(include_wall=True)["wall"]["disp"]
    assert w == {"count": 1, "seconds": 0.25}


def test_histogram_percentiles_with_rng():
    from foundationdb_tpu.flow import DeterministicRandom

    h = BoundedHistogram("h", rng=DeterministicRandom(7))
    for i in range(100):
        h.add(float(i))
    s = h.summary()
    assert s["count"] == 100 and s["min"] == 0.0 and s["max"] == 99.0
    assert 30 <= s["median"] <= 70
    assert s["p99"] >= s["p90"] >= s["median"]


def test_emit_metrics_actor_traces_registry():
    c = SimCluster(seed=4210)
    reg = MetricsRegistry("EmitTest", rng=c.loop.rng)
    reg.counter("ticks").add(5)
    reg.gauge("depth").set(2)
    reg.histogram("sz").add(4.0)
    proc = c.net.process("emit_test")
    proc.spawn(emit_metrics(reg, proc, interval=1.0), "emit")
    db = c.database()

    async def idle():
        await c.loop.delay(3.5)

    c.run_until(db.process.spawn(idle(), "idle"), timeout_vt=100.0)
    evs = global_collector().find("EmitTestMetrics")
    assert len(evs) >= 3
    ev = evs[0]
    assert ev["ticks"] == 5
    # Lazy rate baseline (flow/stats.py fix): the FIRST emission has no
    # prior observation span, so its rate is 0.0 — not value/now.
    assert ev["ticksRate"] == 0.0
    assert ev["depth"] == 2
    assert ev["szCount"] == 1 and ev["szMean"] == 4.0


# ---------------------------------------------------------------------------
# Kernel telemetry on the device engine
# ---------------------------------------------------------------------------


def _kernel_txns(n):
    from foundationdb_tpu.conflict.types import TransactionConflictInfo as T

    def k(i):
        return b"%06d" % i

    return [
        T(
            read_snapshot=0,
            read_ranges=[(k(10 * i), k(10 * i + 1))],
            write_ranges=[(k(10 * i), k(10 * i + 1))],
        )
        for i in range(n)
    ]


@pytest.mark.slow  # tier-1 headroom (ISSUE 4): tier-1 recompile-storm gate now lives in test_perf_smoke (cheaper shapes)
def test_kernel_retraces_equal_distinct_buckets_and_occupancy():
    """The acceptance gate for kernel telemetry: mixed batch sizes through
    JaxConflictSet; the retrace counter equals the number of distinct
    PackedBatch.bucket() shapes (no silent recompile storms), and padding
    occupancy is reported per batch."""
    from foundationdb_tpu.conflict.engine_jax import (
        JaxConflictSet,
        PackedBatch,
    )

    cs = JaxConflictSet(key_words=2, h_cap=256, bucket_mins=(4, 4, 4))
    seen_buckets = set()
    now = 100
    sizes = [1, 2, 3, 4, 3, 1, 6, 5]  # (4,4,4) for n<=4, (8,8,8) for 5..6
    for n in sizes:
        pb = PackedBatch.from_transactions(
            _kernel_txns(n), cs.key_words, 4, 4, 4
        )
        seen_buckets.add(pb.bucket())
        cs.detect_packed(pb, now, 0)
        now += 10
        # Padding occupancy reported per batch, exact.
        occ = cs.last_occupancy
        assert occ["txn"] == n / pb.txn_cap
        assert occ["read"] == n / pb.rr_cap
        assert occ["write"] == n / pb.wr_cap
    assert len(seen_buckets) == 2, seen_buckets
    snap = cs.metrics.snapshot()
    assert snap["counters"]["retraces"] == len(seen_buckets)
    assert snap["counters"]["batches"] == len(sizes)
    assert snap["counters"]["transactions"] == sum(sizes)
    # Fixpoint rounds surfaced from the while_loop carry: at least one
    # round per batch.
    assert snap["counters"]["fixpoint_rounds"] >= len(sizes)
    assert snap["histograms"]["fixpoint_rounds_per_batch"]["count"] == len(
        sizes
    )
    # Boundary count tracked after every synced batch.
    assert snap["gauges"]["boundary_count"] == cs.boundary_count
    # Occupancy distributions cover every batch.
    assert snap["histograms"]["txn_occupancy"]["count"] == len(sizes)
    # Dispatch wall cost recorded — in the wall namespace ONLY.
    assert "wall" not in snap
    wall = cs.metrics.snapshot(include_wall=True)["wall"]
    assert wall["dispatch_seconds"]["count"] == len(sizes)
    # Re-dispatching a seen shape is NOT a retrace.
    pb = PackedBatch.from_transactions(_kernel_txns(2), cs.key_words, 4, 4, 4)
    cs.detect_packed(pb, now, 0)
    assert cs.metrics.snapshot()["counters"]["retraces"] == len(seen_buckets)


@pytest.mark.slow  # tier-1 headroom (ISSUE 4): tier-1 recompile-storm gate now lives in test_perf_smoke (cheaper shapes)
def test_kernel_grow_event_counted():
    from foundationdb_tpu.conflict.engine_jax import JaxConflictSet

    cs = JaxConflictSet(key_words=2, h_cap=64, bucket_mins=(4, 4, 4))
    now = 100
    # Enough distinct write ranges to exhaust the 64-row history (4 new
    # boundaries per batch, window never expires them): capacity must
    # grow, and the grow event must be counted.
    for b in range(20):
        txns = _kernel_txns(2)
        # Shift keys per batch so boundaries accumulate.
        for t in txns:
            # Disjoint, non-adjacent ranges: 4 fresh boundaries per batch.
            t.write_ranges = [
                (b"%06d" % (1000 * b + 2 * i), b"%06d" % (1000 * b + 2 * i + 1))
                for i in range(2)
            ]
        cs.detect(txns, now, 0)
        now += 10
    assert cs.h_cap > 64
    snap = cs.metrics.snapshot()
    assert snap["counters"]["grows"] >= 1
    assert snap["gauges"]["boundary_count"] > 0


def test_device_metrics_through_conflict_set_api():
    from foundationdb_tpu.conflict.api import ConflictSet

    cs = ConflictSet(backend="cpu")
    assert cs.device_metrics() is None
    # hybrid: device engine exists but small batches stay on the CPU —
    # telemetry is live with zero retraces (and no XLA compile here).
    hs = ConflictSet(backend="hybrid")
    dm = hs.device_metrics()
    assert dm is not None
    assert dm["counters"]["retraces"] == 0
    assert dm["h_cap"] > 0


# ---------------------------------------------------------------------------
# Latency-chain reassembly
# ---------------------------------------------------------------------------


def _ev(type_, loc, did, t):
    return {"Type": type_, "Location": loc, "ID": did, "Time": t}


def test_latency_chain_unit_math():
    events = []
    # Two commit chains with known stage times.
    for did, base in (("a", 10.0), ("b", 20.0)):
        events += [
            _ev("CommitDebug", "NativeAPI.commit.Before", did, base),
            _ev("CommitDebug", "MasterProxyServer.commitBatch.Before",
                did, base + 1),
            _ev("CommitDebug",
                "MasterProxyServer.commitBatch.GotCommitVersion",
                did, base + 2),
            _ev("CommitDebug", "Resolver.resolveBatch.Before", did, base + 2.5),
            _ev("CommitDebug", "Resolver.resolveBatch.After", did, base + 3),
            _ev("CommitDebug",
                "MasterProxyServer.commitBatch.AfterResolution",
                did, base + 4),
            _ev("CommitDebug", "MasterProxyServer.commitBatch.AfterLogPush",
                did, base + 6),
            _ev("CommitDebug", "NativeAPI.commit.After", did, base + 7),
        ]
    out = summarize_stages(events, "CommitDebug", COMMIT_CHAIN)
    assert out["client->proxy"]["count"] == 2
    assert out["client->proxy"]["p50"] == 1.0
    assert out["resolver"]["p50"] == 0.5
    assert out["tlog"]["max"] == 2.0
    assert out["total"]["p99"] == 7.0
    # Unknown ids / missing stages contribute nothing.
    partial = [_ev("CommitDebug", "NativeAPI.commit.Before", "x", 1.0)]
    out2 = summarize_stages(partial, "CommitDebug", COMMIT_CHAIN)
    assert out2["total"]["count"] == 0 and out2["total"]["p50"] is None


def test_latency_chain_multi_role_uses_slowest_replica():
    # Two resolvers answering the same batch: stage spans first(Before) ->
    # last(After), the replica the proxy actually waited on.
    events = [
        _ev("CommitDebug", "Resolver.resolveBatch.Before", "a", 1.0),
        _ev("CommitDebug", "Resolver.resolveBatch.Before", "a", 1.1),
        _ev("CommitDebug", "Resolver.resolveBatch.After", "a", 1.5),
        _ev("CommitDebug", "Resolver.resolveBatch.After", "a", 2.0),
    ]
    out = summarize_stages(events, "CommitDebug", COMMIT_CHAIN)
    assert out["resolver"]["p50"] == 1.0


def test_percentile_rule_matches_continuous_sample():
    assert percentile([], 0.5) is None
    s = [float(i) for i in range(10)]
    assert percentile(s, 0.5) == 5.0
    assert percentile(s, 0.99) == 9.0


def test_live_cluster_chain_reassembles_every_stage():
    c = SimCluster(seed=4233)
    db = c.database("lat")

    async def load():
        for i in range(6):
            tr = db.create_transaction()
            tr.set(b"lc%02d" % i, b"v")
            await tr.commit()

    c.run_until(db.process.spawn(load(), "load"), timeout_vt=1000.0)
    summary = latency_summary(global_collector().events)
    for stage in ("client->proxy", "resolver", "tlog", "total"):
        st = summary["commit"][stage]
        assert st["count"] >= 1, (stage, summary["commit"])
        assert st["p50"] is not None and st["p50"] >= 0.0
        assert st["p99"] >= st["p50"]
    assert summary["grv"]["total"]["count"] >= 1


# ---------------------------------------------------------------------------
# Status + CLI surfacing
# ---------------------------------------------------------------------------


def test_status_json_has_resolver_section_and_cli_commands():
    c = SimCluster(seed=4240)
    db = c.database("cli")
    cli = CliProcessor(c, db)

    async def load():
        for i in range(5):
            tr = db.create_transaction()
            tr.set(b"s%02d" % i, b"v")
            await tr.commit()

    c.run_until(db.process.spawn(load(), "load"), timeout_vt=1000.0)

    out = _drive(c, db, cli, "status --format=json")
    doc = json.loads("\n".join(out))
    sec = doc["cluster"]["resolver"]
    assert sec["count"] == 1
    assert sec["backends"] == ["cpu"]
    assert sec["total_resolved"] >= 5
    rsnap = sec["resolvers"]["resolver"]
    assert rsnap["counters"]["committed"] >= 5
    assert rsnap["histograms"]["batch_size"]["count"] >= 1

    # Text status renders the resolver row.
    text = "\n".join(_drive(c, db, cli, "status"))
    assert "Resolver" in text

    # latency: default reads the SPAN layer (ISSUE 12) — per-role stage
    # percentiles; --chains keeps the debug-id chain reassembly.
    lat_text = "\n".join(_drive(c, db, cli, "latency"))
    assert "per-stage span latency" in lat_text and "p50=" in lat_text
    assert "p90=" in lat_text and "p99=" in lat_text
    chain_text = "\n".join(_drive(c, db, cli, "latency --chains"))
    assert "commit pipeline" in chain_text
    lat = json.loads(
        "\n".join(_drive(c, db, cli, "latency --chains --format=json"))
    )
    assert lat["commit"]["total"]["count"] >= 1

    # metrics: registry snapshots, text + json.
    met_text = "\n".join(_drive(c, db, cli, "metrics"))
    assert "resolvers:" in met_text and "proxies:" in met_text
    met = json.loads("\n".join(_drive(c, db, cli, "metrics --format=json")))
    assert met["resolvers"]["resolver"]["counters"]["batches"] >= 1
    assert met["proxies"]["proxy0"]["histograms"]["commit_batch_size"][
        "count"
    ] >= 1


def test_status_tpu_section_with_hybrid_backend():
    c = SimCluster(seed=4241, conflict_backend="hybrid")
    db = c.database()

    async def load():
        for i in range(3):
            tr = db.create_transaction()
            tr.set(b"h%02d" % i, b"v")
            await tr.commit()

    c.run_until(db.process.spawn(load(), "load"), timeout_vt=1000.0)
    doc = cluster_status(c)
    sec = doc["cluster"]["resolver"]
    assert sec["backends"] == ["hybrid"]
    # Device engine exists -> tpu section present; small batches stayed on
    # the CPU, so zero retraces (and zero device batches).
    tpu = sec["tpu"]["resolver"]
    assert tpu["counters"]["retraces"] == 0
    assert tpu["distinct_shapes"] == 0
    # The whole section is JSON-serializable (the CLI path).
    json.dumps(doc, default=str)


def test_durable_cluster_status_has_resolver_section():
    # Durable SimCluster sets .resolver (singular) only; the section must
    # not silently vanish.
    c = SimCluster(seed=4242, durable=True)
    db = c.database()

    async def load():
        tr = db.create_transaction()
        tr.set(b"dk", b"v")
        await tr.commit()

    c.run_until(db.process.spawn(load(), "load"), timeout_vt=1000.0)
    sec = cluster_status(c)["cluster"]["resolver"]
    assert sec["count"] == 1
    assert sec["resolvers"]["resolver"]["counters"]["committed"] >= 1
    cli = CliProcessor(c, db)
    met = json.loads("\n".join(_drive(c, db, cli, "metrics --format=json")))
    assert met["resolvers"]["resolver"]["counters"]["batches"] >= 1


def test_dynamic_cluster_metrics_cmd_finds_worker_roles():
    from foundationdb_tpu.server.dynamic_cluster import DynamicCluster

    c = DynamicCluster(seed=4243)
    db = c.database()

    async def load(tr):
        tr.set(b"dyn", b"v")

    c.run_all([(db, db.run(load))], timeout_vt=300.0)
    cli = CliProcessor(c, db)
    met = json.loads("\n".join(_drive(c, db, cli, "metrics --format=json")))
    # Worker-recruited roles discovered (not the SimCluster attrs).
    assert met.get("resolvers"), met.keys()
    assert any(
        s["counters"]["batches"] >= 1 for s in met["resolvers"].values()
    )
    assert met.get("proxies")
    # And the status doc agrees.
    doc = cluster_status(c)
    assert doc["cluster"]["resolver"]["count"] >= 1


def test_lock_rejected_txn_not_counted_committed():
    """A committable-but-lock-rejected transaction counts as
    rejected_locked in BOTH telemetry surfaces, never committed (the
    client saw database_locked)."""
    from foundationdb_tpu.client import management as mgmt

    c = SimCluster(seed=4244, buggify=False)
    db = c.database()

    async def scenario():
        # GRV taken BEFORE the lock, so the commit reaches the proxy's
        # commit path (not the GRV-side rejection) and is turned away by
        # the lock fence there.
        tr = db.create_transaction()
        await tr.get_read_version()
        await mgmt.lock_database(db)
        tr.set(b"lk", b"v")
        try:
            await tr.commit()
        except Exception:
            pass
        return (
            c.proxy.metrics.snapshot()["counters"],
            c.proxy.stats.snapshot(),
        )

    counters, stats = c.run_until(
        db.process.spawn(scenario(), "sc"), timeout_vt=1000.0
    )
    assert counters["rejected_locked"] >= 1
    assert counters["rejected_locked"] == stats["rejected_locked"]
    assert counters["committed"] == stats["committed"]


# ---------------------------------------------------------------------------
# Satellite regressions: Counter rate + file-backed TraceCollector
# ---------------------------------------------------------------------------


def test_counter_rate_first_call_has_no_time_zero_skew():
    from foundationdb_tpu.flow.stats import Counter

    c = Counter("x")
    c.add(100)
    # First query at t=50: with the old eager _last_t=0.0 this reported
    # 100/50 = 2.0/s; the lazy baseline reports 0.0 (no span yet).
    assert c.rate_since_last(50.0) == 0.0
    c.add(10)
    assert c.rate_since_last(55.0) == pytest.approx(2.0)
    # Zero/negative spans stay 0.0, not inf.
    c.add(1)
    assert c.rate_since_last(55.0) == 0.0


def test_file_backed_collector_find_uses_bounded_recent_ring(tmp_path, monkeypatch):
    """ISSUE 10 satellite: a file-backed collector keeps a BOUNDED
    recent-events ring (FDB_TPU_TRACE_RECENT), so find() works on the
    recent window instead of raising; the spool stays the durable
    record, memory stays bounded, clear() leaves the disk log intact."""
    from foundationdb_tpu.flow.trace import TraceCollector, TraceEvent

    monkeypatch.setenv("FDB_TPU_TRACE_RECENT", "4")
    p = tmp_path / "trace.jsonl"
    col = TraceCollector(path=str(p))
    assert col.recent_maxlen == 4
    for i in range(6):
        TraceEvent("Spooled", collector=col).detail("i", i).log(now=float(i))
    # find() answers from the recent window: only the last 4 of 6.
    found = col.find("Spooled")
    assert [e["i"] for e in found] == [2, 3, 4, 5]
    # counts is still the COMPLETE tally — the window bound is visible.
    assert col.counts["Spooled"] == 6
    assert len(col.recent_events()) == 4
    col.close()
    # The spool holds everything: retention on disk is not the ring's job.
    lines = [json.loads(ln) for ln in p.read_text().splitlines()]
    assert [e["i"] for e in lines] == list(range(6))
    # clear() resets counts + ring but leaves the on-disk record intact.
    col2 = TraceCollector(path=str(p))
    TraceEvent("More", collector=col2).log(now=7.0)
    col2.clear()
    assert col2.counts == {} and col2.recent_events() == []
    assert col2.find("More") == []
    col2.close()
    assert len(p.read_text().splitlines()) == 7
    # In-memory collectors: find() stays FULL retention (events list),
    # while the recent ring mirrors the bounded tail for the recorder.
    mem = TraceCollector()
    for i in range(6):
        TraceEvent("M", collector=mem).detail("i", i).log(now=float(i))
    assert len(mem.find("M")) == 6
    assert [e["i"] for e in mem.recent_events()] == [2, 3, 4, 5]
    mem.clear()
    assert mem.find("M") == [] and mem.recent_events() == []
