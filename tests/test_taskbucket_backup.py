"""TaskBucket (leased distributed task queue) + snapshot backup/restore.

Ref: fdbclient/TaskBucket.actor.cpp (claim/lease/finish, timeout
reclamation), fdbclient/FileBackupAgent.actor.cpp (range-dump task chain),
BackupContainer.actor.cpp (page files + manifest).
"""

import pytest

from foundationdb_tpu.fileio import SimFileSystem
from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.layers import (
    BackupContainer,
    FileBackupAgent,
    Subspace,
    TaskBucket,
    TaskBucketExecutor,
)
from foundationdb_tpu.server import SimCluster


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def make_bucket(lease_seconds=5.0):
    return TaskBucket(
        Subspace(raw_prefix=b"\xff\x02/tb/"), lease_seconds=lease_seconds
    )


def test_taskbucket_chain_runs_exactly_once():
    """A 15-link task chain executed by 3 concurrent agents: every link
    runs, the chain never forks (finish+followon atomicity)."""
    c = SimCluster(seed=130)
    bucket = make_bucket()
    db0 = c.database()

    async def submit(tr):
        tr.options["access_system_keys"] = True
        bucket.add(tr, {b"type": b"link", b"n": b"15"})

    c.run_all([(db0, db0.run(submit))])

    async def link(db, task):
        n = int(task.params[b"n"])

        async def mark(tr):
            prev = await tr.get(b"chain/%02d" % n)
            tr.set(b"chain/%02d" % n, b"x")
            return prev

        await db.run(mark)
        if n > 1:
            return [{b"type": b"link", b"n": b"%d" % (n - 1)}]
        return []

    execs = [
        TaskBucketExecutor(c.database(), bucket, {"link": link})
        for _ in range(3)
    ]
    c.run_all(
        [(e.db, e.run(until_empty=True)) for e in execs], timeout_vt=5000.0
    )

    out = {}

    async def check(tr):
        out["rows"] = await tr.get_range(b"chain/", b"chain0")

    c.run_all([(db0, db0.run(check))])
    assert len(out["rows"]) == 15
    assert sum(e.executed for e in execs) == 15  # chain never forked


def test_taskbucket_lease_expiry_reclaims():
    """An executor that claims and dies: after the lease expires another
    executor reclaims and completes the task."""
    c = SimCluster(seed=131)
    bucket = make_bucket(lease_seconds=0.5)
    db0 = c.database()

    async def submit(tr):
        tr.options["access_system_keys"] = True
        bucket.add(tr, {b"type": b"work", b"v": b"1"})

    c.run_all([(db0, db0.run(submit))])

    # Claim without ever finishing (the crashed agent).
    async def claim_and_die():
        db = c.database()

        async def claim(tr):
            tr.options["access_system_keys"] = True
            return await bucket.claim_one(tr)

        task = await db.run(claim)
        assert task is not None

    c.run_until(db0.process.spawn(claim_and_die()), timeout_vt=100.0)

    async def work(db, task):
        async def mark(tr):
            tr.set(b"done", b"1")

        await db.run(mark)
        return []

    ex = TaskBucketExecutor(c.database(), bucket, {"work": work})

    async def drive():
        # The lease (0.5s of versions) must expire before reclaim succeeds.
        await c.loop.delay(0.7)
        while not await ex.run_one():
            await c.loop.delay(0.1)

    c.run_all([(ex.db, drive())], timeout_vt=1000.0)
    out = {}

    async def check(tr):
        out["done"] = await tr.get(b"done")

    c.run_all([(db0, db0.run(check))])
    assert out["done"] == b"1"
    assert ex.executed == 1


def fill(c, db, n, prefix=b"data/"):
    for base in range(0, n, 500):
        async def txn(tr, base=base):
            for i in range(base, min(base + 500, n)):
                tr.set(prefix + b"%05d" % i, b"v%d" % i)

        c.run_all([(db, db.run(txn))])


def test_backup_restore_roundtrip():
    c = SimCluster(seed=132)
    fs = SimFileSystem(c.net)
    db = c.database()
    fill(c, db, 2500)

    agent = FileBackupAgent(db, fs)
    container = agent.container("bk1")

    async def drive():
        await agent.submit_backup(container, b"data/", b"data0")
        ex = agent.executor(c.database())
        await ex.run(until_empty=True)

    c.run_until(db.process.spawn(drive()), timeout_vt=5000.0)

    # Wipe and restore.
    async def wipe(tr):
        tr.clear_range(b"data/", b"data0")

    c.run_all([(db, db.run(wipe))])

    async def rest():
        return await agent.restore(container)

    n = c.run_until(db.process.spawn(rest()), timeout_vt=5000.0)
    assert n == 2500

    out = {}

    async def check(tr):
        out["first"] = await tr.get(b"data/00000")
        out["last"] = await tr.get(b"data/02499")
        rows = await tr.get_range(b"data/", b"data0", limit=1 << 20)
        out["count"] = len(rows)

    c.run_all([(db, db.run(check))])
    assert out["count"] == 2500
    assert out["first"] == b"v0" and out["last"] == b"v2499"


def test_backup_is_point_in_time_under_writes():
    """Writers keep rotating a cycle ring during the backup; the RESTORED
    image must be a valid ring — i.e. one consistent snapshot, not a fuzzy
    mix of versions."""
    c = SimCluster(seed=133)
    fs = SimFileSystem(c.net)
    db = c.database()
    N = 8

    async def init(tr):
        for i in range(N):
            tr.set(b"ring/%03d" % i, b"%03d" % ((i + 1) % N))

    c.run_all([(db, db.run(init))])

    agent = FileBackupAgent(db, fs)
    container = agent.container("bk2")
    stop = []

    async def writer():
        wdb = c.database()
        rng = c.loop.rng
        while not stop:
            async def op(tr):
                a = int(rng.random_int(0, N))
                ka = b"ring/%03d" % a
                b = int((await tr.get(ka)).decode())
                kb = b"ring/%03d" % b
                cc = int((await tr.get(kb)).decode())
                kc = b"ring/%03d" % cc
                d = int((await tr.get(kc)).decode())
                tr.set(ka, b"%03d" % cc)
                tr.set(kc, b"%03d" % b)
                tr.set(kb, b"%03d" % d)

            await wdb.run(op)
            await c.loop.delay(0.002)

    async def drive():
        await agent.submit_backup(container, b"ring/", b"ring0")
        ex = agent.executor(c.database())
        await ex.run(until_empty=True)
        stop.append(True)

    c.run_all([(db, writer()), (db, drive())], timeout_vt=5000.0)

    async def wipe(tr):
        tr.clear_range(b"ring/", b"ring0")

    c.run_all([(db, db.run(wipe))])

    async def rest():
        return await agent.restore(container)

    c.run_until(db.process.spawn(rest()), timeout_vt=5000.0)

    out = {}

    async def check(tr):
        out["rows"] = await tr.get_range(b"ring/", b"ring0")

    c.run_all([(db, db.run(check))])
    ring = {k: int(v.decode()) for k, v in out["rows"]}
    assert len(ring) == N
    seen, cur = set(), 0
    for _ in range(N):
        assert cur not in seen
        seen.add(cur)
        cur = ring[b"ring/%03d" % cur]
    assert cur == 0 and len(seen) == N


def test_continuous_backup_point_in_time_restore():
    """Snapshot + mutation log: restore at an INTERMEDIATE version yields
    exactly the state as of that version; restore at the latest yields the
    final state (ref: FileBackupAgent range dumps + mutation logs +
    applyMutations)."""
    from foundationdb_tpu.layers.backup import (
        BackupContainer,
        ContinuousBackupAgent,
    )

    c = SimCluster(seed=77, n_tlogs=2)
    db = c.database()
    fs = __import__(
        "foundationdb_tpu.fileio", fromlist=["SimFileSystem"]
    ).SimFileSystem(c.net)
    store_proc = c.net.process("backup_store")
    container = BackupContainer(fs, store_proc, "bk1")
    agent = ContinuousBackupAgent(
        db, fs, [t.interface() for t in c.tlogs], container
    )
    state = {}

    async def scenario():
        async def phase1(tr):
            for i in range(10):
                tr.set(b"cb%03d" % i, b"one")

        await db.run(phase1)
        await agent.start()

        # Phase 2: mutations AFTER the snapshot (incl. clear + atomic).
        async def phase2(tr):
            for i in range(10):
                tr.set(b"cb%03d" % i, b"two")
            tr.set(b"cb_new", b"added")
            tr.clear_range(b"cb000", b"cb002")

        await db.run(phase2)
        for _ in range(100):
            if await agent.tail_once() == 0 and agent.logged_through > 0:
                break
        mid_version = agent.logged_through

        # Phase 3: more mutations the mid-restore must NOT include.
        async def phase3(tr):
            tr.set(b"cb_late", b"late")
            tr.clear_range(b"cb005", b"cb007")

        await db.run(phase3)
        for _ in range(100):
            if await agent.tail_once() == 0:
                break

        # PITR at mid_version: phase1+2 state, NO phase3.
        await agent.restore(target_version=mid_version)
        out = {}

        async def read(tr):
            out["rows"] = dict(await tr.get_range(b"cb", b"cc"))

        await db.run(read)
        rows = out["rows"]
        assert rows.get(b"cb_new") == b"added"
        assert b"cb_late" not in rows
        assert b"cb000" not in rows and b"cb001" not in rows  # phase2 clear
        assert rows.get(b"cb005") == b"two"  # phase3 clear NOT applied
        assert rows.get(b"cb009") == b"two"

        # Restore at the latest: phase3 included.
        await agent.restore()
        await db.run(read)
        rows = out["rows"]
        assert rows.get(b"cb_late") == b"late"
        assert b"cb005" not in rows and b"cb006" not in rows
        state["ok"] = True

    c.run_until(db.process.spawn(scenario(), "sc"), timeout_vt=20000.0)
    assert state.get("ok")


def test_continuous_backup_subrange_clear_clamps_low_edge():
    """A source clear_range STARTING below the backup's begin bound must
    still delete the overlapping part of the backed-up range at restore
    (regression: the low edge used to be dropped entirely)."""
    from foundationdb_tpu.layers.backup import (
        BackupContainer,
        ContinuousBackupAgent,
    )

    c = SimCluster(seed=78)
    db = c.database()
    fs = __import__(
        "foundationdb_tpu.fileio", fromlist=["SimFileSystem"]
    ).SimFileSystem(c.net)
    container = BackupContainer(fs, c.net.process("bk_store2"), "bk2")
    agent = ContinuousBackupAgent(
        db, fs, [t.interface() for t in c.tlogs], container
    )
    state = {}

    async def scenario():
        async def fill(tr):
            for k in (b"a1", b"m1", b"m2", b"n1"):
                tr.set(k, b"v")

        await db.run(fill)
        await agent.start(begin=b"m", end=b"o")

        async def wide_clear(tr):
            tr.clear_range(b"a", b"n")  # starts BELOW the backup's begin

        await db.run(wide_clear)
        for _ in range(100):
            if await agent.tail_once() == 0:
                break
        await agent.restore()
        out = {}

        async def read(tr):
            out["rows"] = dict(await tr.get_range(b"m", b"o"))

        await db.run(read)
        assert b"m1" not in out["rows"] and b"m2" not in out["rows"], (
            "clear starting below the backup bound was dropped"
        )
        assert out["rows"].get(b"n1") == b"v"
        state["ok"] = True

    c.run_until(db.process.spawn(scenario(), "sc"), timeout_vt=20000.0)
    assert state.get("ok")
