"""perfcheck's runtime twin (ISSUE 20): @hot_path declarations, the
FDB_TPU_TRANSFER_GUARD dynamic guard, and the static<->dynamic
acceptance pair.

The headline acceptance: a planted implicit device->host sync inside
the depth-2 dispatch->sync window is caught BOTH statically (HOT001
names the taint chain through the CallGraph) AND dynamically (a
guard-on run raises TransferGuardError at the offending read), while a
same-seed replay with the guard armed is byte-identical to the guard-
off run — the guard only ever raises or is a no-op.

Shape discipline (1-core CI host): key_words=3 + bucket_mins=(32, 128,
64) + h_cap=1<<10 — the same static shapes test_resolver_pipeline
compiles, so this module's marginal compile cost in a full run is near
zero.

Run alone: pytest -m perfcheck
"""

import numpy as np
import pytest

from foundationdb_tpu.conflict.api import ConflictSet
from foundationdb_tpu.conflict.engine_cpu import CpuConflictSet
from foundationdb_tpu.conflict.types import TransactionConflictInfo as T
from foundationdb_tpu.flow import DeterministicRandom, set_event_loop
from foundationdb_tpu.flow.hotpath import (
    HOT_BOUNDS,
    GuardedDeviceValue,
    TransferGuardError,
    g_hostguard,
    hot_path,
    hot_registry,
)
from foundationdb_tpu.tools.fdblint import lint_source

pytestmark = pytest.mark.perfcheck

WINDOW = 40


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def k(i: int) -> bytes:
    return b"%08d" % i


def _random_stream(seed, keyspace, batches, txns_per_batch, snap_lag=25):
    rng = DeterministicRandom(seed)
    version = 10
    out = []
    for _ in range(batches):
        txns = []
        for _ in range(rng.random_int(1, txns_per_batch + 1)):
            tr = T(read_snapshot=max(0, version - rng.random_int(0, snap_lag)))
            for _ in range(rng.random_int(0, 4)):
                a = rng.random_int(0, keyspace)
                b = a + 1 + rng.random_int(0, max(1, keyspace // 8))
                tr.read_ranges.append((k(a), k(b)))
            for _ in range(rng.random_int(0, 3)):
                a = rng.random_int(0, keyspace)
                b = a + 1 + rng.random_int(0, max(1, keyspace // 8))
                tr.write_ranges.append((k(a), k(b)))
            txns.append(tr)
        version += rng.random_int(1, 10)
        out.append((txns, version, max(0, version - WINDOW)))
    return out


def _device_set(monkeypatch, depth, guard=False):
    monkeypatch.setenv("FDB_TPU_PIPELINE_DEPTH", str(depth))
    if guard:
        monkeypatch.setenv("FDB_TPU_TRANSFER_GUARD", "1")
    else:
        monkeypatch.delenv("FDB_TPU_TRANSFER_GUARD", raising=False)
    return ConflictSet(backend="jax", key_words=3,
                       bucket_mins=(32, 128, 64), h_cap=1 << 10)


def _drive_pipelined(cs, stream, depth):
    entries = []
    for txns, now, nov in stream:
        entries.append(cs.pipeline_submit(txns, now, nov))
        while cs.pipeline_inflight > depth - 1:
            cs.pipeline_complete_oldest()
    cs.pipeline_drain()
    assert all(e.done for e in entries)
    return [e.statuses for e in entries]


def _exported_state(cs):
    mirror = (list(cs._cpu.keys), list(cs._cpu.vers), cs._cpu.oldest_version)
    export = CpuConflictSet()
    cs._jax.store_to(export)
    return mirror, (list(export.keys), list(export.vers),
                    export.oldest_version)


# ---------------------------------------------------------------------------
# @hot_path declarations
# ---------------------------------------------------------------------------


def test_hot_path_registers_and_validates_bounds():
    @hot_path(bound="chunks")
    def _probe_fn():
        return 1

    assert _probe_fn() == 1  # the decorator is a pure tag
    assert _probe_fn.__hot_path_bound__ == "chunks"
    reg = hot_registry()
    assert reg[f"{_probe_fn.__module__}.{_probe_fn.__qualname__}"] == "chunks"
    assert set(reg.values()) <= set(HOT_BOUNDS)
    with pytest.raises(ValueError):
        hot_path(bound="rows")


def test_hot_registry_covers_the_engine_hot_set():
    # Importing the conflict stack registers the per-batch hot set; the
    # declared bounds are what perfcheck's HOT002 statically polices.
    # (api loads engine_jax lazily at first device construction.)
    import foundationdb_tpu.conflict.engine_jax  # noqa: F401

    reg = hot_registry()
    want = {
        "foundationdb_tpu.conflict.engine_jax.JaxConflictSet.dispatch_txns":
            "batch",
        "foundationdb_tpu.conflict.engine_jax.JaxConflictSet.sync_ticket":
            "batch",
        "foundationdb_tpu.conflict.engine_jax.JaxConflictSet.note_synced":
            "chunks",
        "foundationdb_tpu.conflict.keys.encode_keys": "batch",
        "foundationdb_tpu.conflict.engine_cpu.CpuConflictSet.apply_batch":
            "chunks",
        "foundationdb_tpu.conflict.api.ConflictSet._pipeline_dispatch":
            "batch",
    }
    for qual, bound in want.items():
        assert reg.get(qual) == bound, (qual, reg.get(qual))


# ---------------------------------------------------------------------------
# GuardedDeviceValue semantics
# ---------------------------------------------------------------------------


def test_guarded_value_raises_on_implicit_materialization():
    g = GuardedDeviceValue(np.arange(4), "DispatchTicket.statuses")
    for op in (lambda: int(g[0] if False else g),  # __int__ via int()
               lambda: float(g),
               lambda: bool(g),
               lambda: len(g),
               lambda: list(g),
               lambda: g[0],
               lambda: g.item(),
               lambda: g.tolist(),
               lambda: np.asarray(g)):
        with pytest.raises(TransferGuardError) as ei:
            op()
        assert "sanctioned sync point" in str(ei.value)
    # Forwarding without materializing is always allowed.
    assert g.unwrap() is not None and "statuses" in repr(g)


def test_guarded_value_delegates_inside_sanctioned_scope():
    g = GuardedDeviceValue(np.arange(4), "DispatchTicket.iters")
    with g_hostguard.allowed():
        assert not g_hostguard.blocking()
        assert np.asarray(g).sum() == 6
        assert list(g) == [0, 1, 2, 3]
        assert len(g) == 4
        # Reentrant: nested sanctioned scopes unwind correctly.
        with g_hostguard.allowed():
            assert g.tolist() == [0, 1, 2, 3]
        assert not g_hostguard.blocking()
    assert g_hostguard.blocking()
    with pytest.raises(TransferGuardError):
        np.asarray(g)


# ---------------------------------------------------------------------------
# The acceptance pair: one planted sync, caught statically AND dynamically
# ---------------------------------------------------------------------------

# The planted violation, as source (for the static half): a helper two
# frames below the dispatch call materializes an in-flight ticket field.
_PLANTED = '''\
import numpy as np


def _peek(ticket):
    return np.asarray(ticket.statuses)


def drive(engine, txns):
    ticket = engine.dispatch_txns(txns, 0, 0)
    return _peek(ticket), engine.sync_ticket(ticket)
'''


@pytest.mark.lint
def test_planted_sync_caught_statically_with_chain():
    findings = [f for f in lint_source(_PLANTED, "window.py")
                if f.rule == "HOT001" and not f.suppressed]
    assert len(findings) == 1, findings
    msg = findings[0].message
    # The finding names the depth-2 dispatch->sync window chain.
    assert "drive -> _peek" in msg, msg
    assert "np.asarray()" in msg and "sanctioned sync point" in msg


def test_planted_sync_caught_dynamically_by_transfer_guard(monkeypatch):
    cs = _device_set(monkeypatch, depth=2, guard=True)
    assert cs._jax._transfer_guard
    stream = _random_stream(7, 60, 4, 8)
    txns, now, nov = stream[0]
    entry = cs.pipeline_submit(txns, now, nov)
    assert cs.pipeline_inflight == 1 and not entry.done
    # The planted consumer: peeking at the parked ticket's statuses
    # inside the dispatch->sync window — exactly what HOT001 flags
    # statically — raises loudly instead of silently serializing.
    with pytest.raises(TransferGuardError) as ei:
        np.asarray(entry.ticket.statuses)
    assert "DispatchTicket.statuses" in str(ei.value)
    with pytest.raises(TransferGuardError):
        int(entry.ticket.hcount)
    # The sanctioned path still completes the batch normally.
    cs.pipeline_drain()
    assert entry.done and cs.pipeline_inflight == 0


def test_guard_on_replay_is_byte_identical(monkeypatch):
    # Same-seed, depth-2 pipelined runs with the guard off vs on: the
    # guard only ever raises or is a no-op, so verdicts AND exported
    # device/mirror state match exactly.
    stream = _random_stream(11, 60, 12, 8)
    base = _device_set(monkeypatch, depth=2, guard=False)
    want = _drive_pipelined(base, stream, 2)
    want_state = _exported_state(base)

    guarded = _device_set(monkeypatch, depth=2, guard=True)
    got = _drive_pipelined(guarded, stream, 2)
    assert got == want
    assert _exported_state(guarded) == want_state
    dm = guarded.device_metrics()
    assert dm["counters"]["pipeline_dispatches"] == len(stream)
    # Every completed batch entered its sanctioned sync scopes.
    assert dm["counters"]["host_syncs"] >= len(stream)
