"""Dynamic cluster: election, recruitment, recovery on role failure.

The reference's equivalents: simulation workloads with Attrition (kill) +
the master recovery state machine.  The invariant tested throughout:
committed-acknowledged data stays readable across any single role-process
failure, and the cluster keeps accepting commits after recovery.
"""

import pytest

from foundationdb_tpu.flow import FdbError, set_event_loop
from foundationdb_tpu.server.dynamic_cluster import DynamicCluster


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def bootstrap(seed=1, **kw):
    c = DynamicCluster(seed=seed, **kw)
    db = c.database()

    async def ready(tr):
        tr.set(b"boot", b"1")

    c.run_all([(db, db.run(ready))], timeout_vt=300.0)
    return c, db


def test_cluster_bootstraps_and_serves():
    c, db = bootstrap(seed=21)
    out = {}

    async def rw(tr):
        tr.set(b"hello", b"world")
        out["v"] = await tr.get(b"hello")

    c.run_all([(db, db.run(rw))], timeout_vt=300.0)
    assert out["v"] == b"world"
    assert c.acting_controller().generation >= 1


@pytest.mark.parametrize("role", ["proxy", "resolver", "sequencer", "tlog", "storage"])
def test_any_role_failure_recovers(role):
    # zlib.crc32, not hash(): PYTHONHASHSEED would randomize the sim seed.
    import zlib

    c, db = bootstrap(seed=zlib.crc32(role.encode()) % 1000)
    committed = {}

    async def w1(tr):
        tr.set(b"before", b"crash")

    c.run_all([(db, db.run(w1))], timeout_vt=300.0)
    committed[b"before"] = b"crash"
    committed[b"boot"] = b"1"

    proc = c.kill_role_process(role)
    # Reboot the process so its worker (and any disk state) can return; the
    # CC must re-recruit and recover a new generation.
    from foundationdb_tpu.fileio import SimFileSystem  # noqa: F401

    c.fs.crash_machine(proc.machine.machine_id)
    proc.reboot()
    from foundationdb_tpu.server.worker import WorkerServer, run_worker_registration
    from foundationdb_tpu.flow.asyncvar import AsyncVar
    from foundationdb_tpu.server.coordination import monitor_leader

    w = WorkerServer(proc, c.fs)
    leader_var = AsyncVar(None)
    proc.spawn(monitor_leader(proc, c.coord_ifaces, leader_var), "leader_mon")
    proc.spawn(run_worker_registration(w, leader_var), "registration")

    out = {}

    async def after(tr):
        tr.set(b"after", b"recovery")
        out["before"] = await tr.get(b"before")
        out["boot"] = await tr.get(b"boot")

    c.run_all([(db, db.run(after))], timeout_vt=600.0)
    assert out["before"] == b"crash"
    assert out["boot"] == b"1"

    async def check(tr):
        out["after"] = await tr.get(b"after")

    c.run_all([(db, db.run(check))], timeout_vt=300.0)
    assert out["after"] == b"recovery"


def test_recovery_waits_for_stateful_machine():
    """If the storage machine is down, recovery must WAIT for it, not
    recruit an empty storage elsewhere (which would silently lose all
    acknowledged data).  The machine returns late; data must be intact."""
    c, db = bootstrap(seed=101)

    async def w(tr):
        tr.set(b"precious", b"data")

    c.run_all([(db, db.run(w))], timeout_vt=300.0)

    proc = c.kill_role_process("storage")

    # Let the CC notice and try to recover with the machine still down.
    idle = c.net.process("idler")

    async def wait_vt():
        await c.loop.delay(15.0)

    c.run_until(idle.spawn(wait_vt()), timeout_vt=600.0)
    # No generation may have been published that serves without the data.
    cc = c.acting_controller()
    assert cc.client_info.get().generation < cc.generation or (
        cc.client_info.get().storage is not None
    )

    # Machine returns; recovery completes; data intact.
    c.fs.crash_machine(proc.machine.machine_id)
    proc.reboot()
    from foundationdb_tpu.flow.asyncvar import AsyncVar
    from foundationdb_tpu.server.coordination import monitor_leader
    from foundationdb_tpu.server.worker import WorkerServer, run_worker_registration

    w2 = WorkerServer(proc, c.fs)
    lv = AsyncVar(None)
    proc.spawn(monitor_leader(proc, c.coord_ifaces, lv), "lm")
    proc.spawn(run_worker_registration(w2, lv), "reg")

    out = {}

    async def check(tr):
        out["v"] = await tr.get(b"precious")

    c.run_all([(db, db.run(check))], timeout_vt=600.0)
    assert out["v"] == b"data"


def test_controller_failover():
    c, db = bootstrap(seed=77, n_controllers=2)
    cc0 = c.acting_controller()
    cc0.process.kill()
    out = {}

    async def rw(tr):
        tr.set(b"x", b"after-cc-failover")
        out["v"] = await tr.get(b"x")

    c.run_all([(db, db.run(rw))], timeout_vt=600.0)
    assert out["v"] == b"after-cc-failover"

    # The standby controller must win the election (may lag the workload:
    # clients don't need a live CC for steady-state operation).
    async def wait_new_cc():
        while True:
            try:
                if c.acting_controller() is not cc0:
                    return
            except RuntimeError:
                pass
            await c.loop.delay(0.25)

    driver = c.net.process("driver")
    c.run_until(driver.spawn(wait_new_cc()), timeout_vt=120.0)
    assert c.acting_controller() is not cc0


def test_dynamic_determinism():
    def run(seed):
        c, db = bootstrap(seed=seed)
        hist = []

        async def w(tr):
            tr.set(b"k", b"v")

        c.run_all([(db, db.run(w))], timeout_vt=300.0)
        hist.append(round(c.loop.now(), 9))
        c.kill_role_process("proxy")
        c.run_all([(db, db.run(w))], timeout_vt=600.0)
        hist.append(round(c.loop.now(), 9))
        set_event_loop(None)
        return hist

    assert run(33) == run(33)


def test_whole_cluster_crash_recovers_from_coordinator_disks():
    """Power-loss test (VERDICT r1 item 5): kill EVERY server process
    including coordinators, corrupt unsynced writes, reboot.  The manifest
    (generation + stateful-role placement) must come back from coordinator
    disks alone; acknowledged data must survive; the epoch chain must stay
    monotone (new generation > pre-crash generation)."""
    c, db = bootstrap(seed=55)
    out = {}

    async def w(tr):
        tr.set(b"durable", b"yes")

    c.run_all([(db, db.run(w))], timeout_vt=300.0)
    gen_before = c.acting_controller().generation

    c.crash_and_recover()

    async def check(tr):
        out["v"] = await tr.get(b"durable")
        tr.set(b"post-crash", b"written")

    c.run_all([(db, db.run(check))], timeout_vt=900.0)
    assert out["v"] == b"yes"
    assert c.acting_controller().generation > gen_before

    async def check2(tr):
        out["post"] = await tr.get(b"post-crash")

    c.run_all([(db, db.run(check2))], timeout_vt=300.0)
    assert out["post"] == b"written"


def test_repeated_whole_cluster_crashes():
    """Crash the whole cluster several times in a row; the generation chain
    must be strictly monotone and data cumulative."""
    c, db = bootstrap(seed=56)
    gens = [c.acting_controller().generation]
    for round_i in range(3):
        key = b"round%d" % round_i
        out = {}

        async def w(tr, key=key):
            tr.set(key, b"v")

        c.run_all([(db, db.run(w))], timeout_vt=600.0)
        c.crash_and_recover()

        async def check(tr):
            for r in range(round_i + 1):
                out[b"round%d" % r] = await tr.get(b"round%d" % r)

        c.run_all([(db, db.run(check))], timeout_vt=900.0)
        for r in range(round_i + 1):
            assert out[b"round%d" % r] == b"v", (round_i, r)
        gens.append(c.acting_controller().generation)
    assert gens == sorted(set(gens)), gens


@pytest.mark.parametrize("role", ["tlog0", "tlog1", "storage0", "storage1"])
def test_replicated_role_failure_recovers(role):
    """Replicated topology (2 tlogs, 2 storages): killing any stateful
    process triggers a recovery over the tag-partitioned topology
    (lock-all, min-durable epoch cut, fast-forward) with zero acked-data
    loss (ref: the epochEnd protocol, TagPartitionedLogSystem.actor.cpp)."""
    import zlib

    c, db = bootstrap(
        seed=zlib.crc32(role.encode()) % 1000 + 7,
        n_workers=6,
        n_tlogs=2,
        n_storages=2,
    )
    committed = {b"boot": b"1"}

    async def w1(tr):
        for i in range(10):
            tr.set(b"r%02d" % i, b"x%d" % i)

    c.run_all([(db, db.run(w1))], timeout_vt=300.0)
    for i in range(10):
        committed[b"r%02d" % i] = b"x%d" % i

    proc = c.kill_role_process(role)
    # Reboot the machine (disk survives, unsynced writes resolve per the
    # corruption model) and its worker agent so recovery can re-recruit.
    c.fs.crash_machine(proc.machine.machine_id)
    proc.reboot()
    from foundationdb_tpu.server.worker import (
        WorkerServer,
        run_worker_registration,
    )
    from foundationdb_tpu.flow.asyncvar import AsyncVar
    from foundationdb_tpu.server.coordination import monitor_leader

    w = WorkerServer(proc, c.fs)
    leader_var = AsyncVar(None)
    proc.spawn(monitor_leader(proc, c.coord_ifaces, leader_var), "leader_mon")
    proc.spawn(run_worker_registration(w, leader_var), "registration")

    async def w2(tr):
        tr.set(b"after", b"recovery")

    c.run_all([(db, db.run(w2))], timeout_vt=2000.0)
    committed[b"after"] = b"recovery"

    out = {}

    async def readback(tr):
        for k in committed:
            out[k] = await tr.get(k)

    c.run_all([(db, db.run(readback))], timeout_vt=2000.0)
    assert out == committed


def test_replicated_whole_cluster_crash():
    """Whole-cluster power loss with 2 tlogs + 2 storages: manifest, both
    log disks, and both storage disks must reassemble; the epoch cut is
    min(recovered durables) so acked data survives and un-acked orphans
    are truncated consistently."""
    c, db = bootstrap(seed=77, n_workers=6, n_tlogs=2, n_storages=2)

    async def w(tr):
        for i in range(20):
            tr.set(b"c%02d" % i, b"v%d" % i)

    c.run_all([(db, db.run(w))], timeout_vt=300.0)

    async def settle():
        await c.loop.delay(0.3)  # let storages fold durable state

    c.run_until(db.process.spawn(settle()), timeout_vt=100.0)
    c.crash_and_recover()
    db2 = c.database()
    out = {}

    async def readback(tr):
        rows = await tr.get_range(b"c", b"d")
        out["rows"] = rows

    c.run_all([(db2, db2.run(readback))], timeout_vt=3000.0)
    assert len(out["rows"]) == 20
    assert out["rows"][5] == (b"c05", b"v5")


def _respawn_worker(c, proc):
    """Reboot a worker process and re-attach its agent (disk survives per
    the corruption model)."""
    from foundationdb_tpu.flow.asyncvar import AsyncVar
    from foundationdb_tpu.server.coordination import monitor_leader
    from foundationdb_tpu.server.worker import (
        WorkerServer,
        run_worker_registration,
    )

    c.fs.crash_machine(proc.machine.machine_id)
    proc.reboot()
    w = WorkerServer(proc, c.fs)
    leader_var = AsyncVar(None)
    proc.spawn(monitor_leader(proc, c.coord_ifaces, leader_var), "leader_mon")
    proc.spawn(run_worker_registration(w, leader_var), "registration")
    return w


def test_permanent_tlog_loss_recovers_from_survivors():
    """A tlog machine that NEVER returns: after the grace period, recovery
    proceeds from the surviving replica (every acked mutation is durable on
    every log, so one survivor covers all acked data) and recruits a fresh
    replacement log at the same ring slot (ref: epochEnd proceeding when
    the policy is satisfiable without the lost replica,
    TagPartitionedLogSystem.actor.cpp)."""
    c, db = bootstrap(seed=81, n_workers=7, n_tlogs=2, n_storages=2)
    committed = {b"boot": b"1"}

    async def w1(tr):
        for i in range(10):
            tr.set(b"p%02d" % i, b"x%d" % i)

    c.run_all([(db, db.run(w1))], timeout_vt=300.0)
    for i in range(10):
        committed[b"p%02d" % i] = b"x%d" % i

    dead = c.kill_role_process("tlog0")  # machine never comes back

    async def w2(tr):
        tr.set(b"after", b"loss")

    c.run_all([(db, db.run(w2))], timeout_vt=2000.0)
    committed[b"after"] = b"loss"

    out = {}

    async def readback(tr):
        for k in committed:
            out[k] = await tr.get(k)

    c.run_all([(db, db.run(readback))], timeout_vt=2000.0)
    assert out == committed
    # The replacement is a different machine, recorded in the new manifest.
    assert c.acting_controller()._role_addrs["tlog0"] != dead.address


def test_permanent_tlog_loss_storage_replays_from_survivor():
    """The hazard case: a storage rebooting AFTER the lost log was replaced
    must replay its pre-recovery tail from the SURVIVING replica — the
    fresh log refuses peeks below its begin version (peek_below_begin)
    instead of silently skipping old versions."""
    c, db = bootstrap(seed=82, n_workers=7, n_tlogs=2, n_storages=2)
    committed = {b"boot": b"1"}

    async def w1(tr):
        for i in range(12):
            tr.set(b"q%02d" % i, b"y%d" % i)

    c.run_all([(db, db.run(w1))], timeout_vt=300.0)
    for i in range(12):
        committed[b"q%02d" % i] = b"y%d" % i

    # Lose tlog0 forever AND bounce a storage machine at the same time: the
    # rebooted storage replays its log tail across the epoch boundary.
    c.kill_role_process("tlog0")
    sproc = c.kill_role_process("storage0")
    _respawn_worker(c, sproc)

    async def w2(tr):
        tr.set(b"after", b"replay")

    c.run_all([(db, db.run(w2))], timeout_vt=2000.0)
    committed[b"after"] = b"replay"

    out = {}

    async def readback(tr):
        for k in committed:
            out[k] = await tr.get(k)

    c.run_all([(db, db.run(readback))], timeout_vt=2000.0)
    assert out == committed


def test_permanent_storage_loss_recovers_from_teammate():
    """A storage machine that never returns: recovery proceeds after the
    grace with the surviving teammate (replication >= 2 keeps every shard
    covered); the dead machine is dropped from the manifest so later
    recoveries don't wait for it either."""
    c, db = bootstrap(seed=83, n_workers=7, n_tlogs=2, n_storages=2)
    committed = {b"boot": b"1"}

    async def w1(tr):
        for i in range(10):
            tr.set(b"s%02d" % i, b"z%d" % i)

    c.run_all([(db, db.run(w1))], timeout_vt=300.0)
    for i in range(10):
        committed[b"s%02d" % i] = b"z%d" % i

    dead = c.kill_role_process("storage0")

    async def w2(tr):
        tr.set(b"after", b"team")

    c.run_all([(db, db.run(w2))], timeout_vt=2000.0)
    committed[b"after"] = b"team"

    out = {}

    async def readback(tr):
        for k in committed:
            out[k] = await tr.get(k)

    c.run_all([(db, db.run(readback))], timeout_vt=2000.0)
    assert out == committed

    # Kill ANOTHER role to force a second recovery: it must not wait for
    # the long-dead storage machine.
    proc = c.kill_role_process("proxy0")
    _respawn_worker(c, proc)

    async def w3(tr):
        tr.set(b"after2", b"second")

    c.run_all([(db, db.run(w3))], timeout_vt=2000.0)
    out2 = {}

    async def check2(tr):
        out2["v"] = await tr.get(b"after2")

    c.run_all([(db, db.run(check2))], timeout_vt=2000.0)
    assert out2["v"] == b"second"
    assert dead.address not in c.acting_controller()._role_addrs.values()


def test_sequencer_fences_stale_epoch_grants():
    """A previous generation's proxy reaching the new sequencer (same
    well-known token on a rebooted machine) must get an error, not a
    version grant: serving it would punch a permanent hole in the
    prevVersion chain and wedge every later batch at the resolvers (ref:
    the master serving only its own registered proxies, getVersion
    masterserver.actor.cpp:783)."""
    from foundationdb_tpu.flow.eventloop import EventLoop, set_event_loop
    from foundationdb_tpu.rpc.network import SimNetwork
    from foundationdb_tpu.server.sequencer import Sequencer

    loop = EventLoop(seed=5)
    set_event_loop(loop)
    net = SimNetwork(loop)
    sp = net.process("seq")
    client = net.process("client")
    seq = Sequencer(sp, epoch_begin_version=100, epoch=2)
    out = {}

    async def go():
        iface = seq.interface()
        try:
            await iface.get_commit_version.get_reply(client, 1)  # stale
            out["stale"] = "granted"
        except FdbError as e:
            out["stale"] = e.name
        rep = await iface.get_commit_version.get_reply(client, 2)  # current
        out["current"] = (rep.version, rep.prev_version)

    loop.run_until(client.spawn(go()), timeout_vt=50.0)
    assert out["stale"] == "operation_failed"
    assert out["current"][1] == 100 and out["current"][0] > 100
