"""Dynamic resolver split: load-driven boundary moves at a commit version.

Ref: ResolverInterface.h:108-131 (ResolutionMetrics/SplitRequest),
Resolver.actor.cpp:146-151 (iopsSample), :276-284 (serving both), and the
master's resolution balancing; the proxies' keyResolvers transition keeps
boundary ranges going to BOTH owners for an MVCC window.
"""

import pytest

from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.workloads import CycleWorkload, run_workloads


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def test_metrics_and_split_service():
    """Resolvers sample conflict-range keys and answer split queries."""
    c = SimCluster(seed=101, n_resolvers=1)
    db = c.database()

    async def load():
        for i in range(30):

            async def op(tr, i=i):
                await tr.get(b"hot/%03d" % (i % 5))
                tr.set(b"hot/%03d" % (i % 5), b"x")

            await db.run(op)

    c.run_all([(db, load())], timeout_vt=2000.0)

    out = {}

    async def query():
        from foundationdb_tpu.server.interfaces import ResolutionSplitRequest

        iface = c.resolvers[0].interface()
        rep = await iface.metrics.get_reply(db.process, None)
        out["ops"] = rep.ops
        out["split"] = await iface.split.get_reply(
            db.process, ResolutionSplitRequest(begin=b"", end=None, fraction=0.5)
        )

    c.run_until(db.process.spawn(query()), timeout_vt=100.0)
    assert out["ops"] > 0
    assert out["split"] is not None and out["split"].startswith(b"hot/")


def test_skewed_load_moves_the_split():
    """All traffic below the initial 0x80 boundary: the balancer must move
    the boundary into the hot region, splitting its mass."""
    c = SimCluster(seed=102, n_resolvers=2)
    assert c.split_keys == [b"\x80"]
    db = c.database()

    async def load():
        for i in range(60):

            async def op(tr, i=i):
                k = b"hot/%03d" % (i % 20)
                await tr.get(k)
                tr.set(k, b"x%d" % i)

            await db.run(op)

    c.run_all([(db, load())], timeout_vt=4000.0)

    bal = c.resolver_balancer(min_ops=20, ratio=1.5)
    moved = c.run_until(
        db.process.spawn(bal.run_once()), timeout_vt=1000.0
    )
    assert moved is not None and moved[0].startswith(b"hot/"), moved
    # Every proxy applied the new partition (possibly after its idle tick).
    settle = c.database()

    async def nudge(tr):
        tr.set(b"nudge", b"1")

    c.run_all([(settle, settle.run(nudge))], timeout_vt=1000.0)
    for p in c.proxies:
        assert p.resolver_bounds[0][1].startswith(b"hot/"), (
            p.proxy_id,
            p.resolver_bounds,
        )


def test_serializability_across_split_moves():
    """Cycle invariant holds while the balancer keeps moving the boundary
    through the hot region — the overlap window must hand conflict history
    to the new owner before the old one stops seeing the range."""
    c = SimCluster(seed=103, n_resolvers=2, n_proxies=2)
    db = c.database()

    bal = c.resolver_balancer(min_ops=10, ratio=1.2)
    stop = []

    async def balance_loop():
        while not stop:
            await bal.run_once()
            await c.loop.delay(0.15)

    bal_task = db.process.spawn(balance_loop(), "balancer")

    run_workloads(c, [CycleWorkload(nodes=8, ops=30, actors=4)])
    stop.append(True)
    c.run_until(bal_task, timeout_vt=2000.0)
    # The point of the test is correctness under moves; require at least
    # one move actually happened so the transition path was exercised.
    assert bal.moves >= 1
