"""Double-buffered async resolver pipeline (ISSUE 11).

The headline invariants:

1. Same-seed verdict AND exported-state identity between
   FDB_TPU_PIPELINE_DEPTH=1 (the synchronous resolve path) and depth >= 2
   across seeds — the pipeline defers only host-side work (mirror apply,
   encode, reply); the carried device history advances in commit order at
   dispatch, so batch N+1 always decides against batch N's committed
   writes.
2. Mid-pipeline device faults (scripted DeviceFaultInjector plans firing
   while batches are parked) drain the pipeline onto the authoritative
   mirror with bit-identical verdicts and a byte-identical breaker
   transition log across same-seed replays.
3. Admission-control honesty: parked batches count in the resolver's
   queue_depth (what the PR-7 ratekeeper rides), and a sustained
   zero-overlap state leaves a flight-recorder artifact.

Shape discipline (1-core CI host): key_words=3 + bucket_mins=(32, 128,
64) + h_cap=1<<10, the same static shapes test_device_faults compiles —
the in-process jit cache makes this module's marginal compile cost near
zero in a full run.
"""

import json

import pytest

from foundationdb_tpu.conflict.api import ConflictSet
from foundationdb_tpu.conflict.device_faults import DeviceFaultInjector
from foundationdb_tpu.conflict.engine_cpu import CpuConflictSet
from foundationdb_tpu.conflict.types import TransactionConflictInfo as T
from foundationdb_tpu.flow import DeterministicRandom, set_event_loop
from foundationdb_tpu.flow.knobs import g_knobs

WINDOW = 40


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def k(i: int) -> bytes:
    return b"%08d" % i


def _random_stream(seed, keyspace, batches, txns_per_batch, snap_lag=25):
    """(txns, now, new_oldest) batches from a seeded rng (regenerable for
    a second engine; twin of test_device_faults._random_stream)."""
    rng = DeterministicRandom(seed)
    version = 10
    out = []
    for _ in range(batches):
        txns = []
        for _ in range(rng.random_int(1, txns_per_batch + 1)):
            tr = T(read_snapshot=max(0, version - rng.random_int(0, snap_lag)))
            for _ in range(rng.random_int(0, 4)):
                a = rng.random_int(0, keyspace)
                b = a + 1 + rng.random_int(0, max(1, keyspace // 8))
                tr.read_ranges.append((k(a), k(b)))
            for _ in range(rng.random_int(0, 3)):
                a = rng.random_int(0, keyspace)
                b = a + 1 + rng.random_int(0, max(1, keyspace // 8))
                tr.write_ranges.append((k(a), k(b)))
            txns.append(tr)
        version += rng.random_int(1, 10)
        out.append((txns, version, max(0, version - WINDOW)))
    return out


def _device_set(monkeypatch, depth, **kw):
    monkeypatch.setenv("FDB_TPU_PIPELINE_DEPTH", str(depth))
    kw.setdefault("backend", "jax")
    kw.setdefault("key_words", 3)
    kw.setdefault("bucket_mins", (32, 128, 64))
    kw.setdefault("h_cap", 1 << 10)
    return ConflictSet(**kw)


def _drive_sync(cs, stream):
    out = []
    for txns, now, nov in stream:
        b = cs.new_batch()
        for t in txns:
            b.add_transaction(t)
        out.append(b.detect_conflicts(now, nov))
    return out


def _drive_pipelined(cs, stream, depth, drain_every=0):
    """The resolver's submit-then-complete discipline: dispatch, then
    retire oldest entries until the pipeline is back under its depth
    bound; `drain_every` adds periodic full drains (the idle flush) to
    vary completion interleavings."""
    entries = []
    for i, (txns, now, nov) in enumerate(stream):
        entries.append(cs.pipeline_submit(txns, now, nov))
        while cs.pipeline_inflight > depth - 1:
            cs.pipeline_complete_oldest()
        if drain_every and i % drain_every == drain_every - 1:
            cs.pipeline_drain()
    cs.pipeline_drain()
    assert all(e.done for e in entries)
    return [e.statuses for e in entries]


def _exported_state(cs):
    """(mirror keys/vers/oldest, device-export keys/vers/oldest) — the
    store_to identity the acceptance criteria pin."""
    mirror = (list(cs._cpu.keys), list(cs._cpu.vers), cs._cpu.oldest_version)
    export = CpuConflictSet()
    cs._jax.store_to(export)
    device = (list(export.keys), list(export.vers), export.oldest_version)
    return mirror, device


# ---------------------------------------------------------------------------
# 1. sync-vs-pipelined differential: verdicts AND exported state
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [3, 5, 9])
@pytest.mark.parametrize("depth", [2, 3])
def test_pipeline_verdicts_and_state_match_sync(monkeypatch, seed, depth):
    stream = _random_stream(seed, 60, 16, 8)
    sync_cs = _device_set(monkeypatch, 1)
    want = _drive_sync(sync_cs, stream)
    want_state = _exported_state(sync_cs)

    cs = _device_set(monkeypatch, depth)
    got = _drive_pipelined(cs, stream, depth, drain_every=5)
    assert got == want, "pipelined verdicts diverged from the sync path"
    assert _exported_state(cs) == want_state
    dm = cs.device_metrics()
    assert dm["counters"]["pipeline_dispatches"] == len(stream)
    assert dm["pipeline"]["depth"] == depth
    assert dm["pipeline"]["inflight"] == 0


def test_pipeline_tiered_history_matches_sync(monkeypatch):
    """Tiered mode under the pipeline: the per-ticket dcount copy and
    the no-bound-tightening rule (sync_ticket) must keep compaction
    planning exact with batches in flight."""
    monkeypatch.setenv("FDB_TPU_HISTORY", "tiered")
    monkeypatch.setenv("FDB_TPU_DELTA_CAP", "128")
    monkeypatch.setenv("FDB_TPU_EVICT_EVERY", "2")
    stream = _random_stream(5, 60, 16, 8)
    sync_cs = _device_set(monkeypatch, 1)
    assert sync_cs._jax.tiered
    want = _drive_sync(sync_cs, stream)
    want_state = _exported_state(sync_cs)
    cs = _device_set(monkeypatch, 3)
    got = _drive_pipelined(cs, stream, 3)
    assert got == want
    assert _exported_state(cs) == want_state
    assert cs.device_metrics()["counters"]["major_compactions"] >= 2


def test_pipeline_hybrid_small_batch_routing_drains(monkeypatch):
    """Hybrid routing mid-stream: small batches route to the CPU, which
    must see a CURRENT mirror — the submit drains the pipeline first.
    Verdicts stay identical to the sync hybrid run."""
    old_min = g_knobs.server.conflict_device_min_batch
    g_knobs.server.conflict_device_min_batch = 4
    try:
        stream = _random_stream(23, 60, 18, 8)  # sizes straddle the min
        want = _drive_sync(_device_set(monkeypatch, 1, backend="hybrid"),
                           stream)
        cs = _device_set(monkeypatch, 2, backend="hybrid")
        got = _drive_pipelined(cs, stream, 2)
        assert got == want
    finally:
        g_knobs.server.conflict_device_min_batch = old_min


# ---------------------------------------------------------------------------
# 2. mid-pipeline faults: mirror replay, breaker-log byte identity
# ---------------------------------------------------------------------------


def test_mid_pipeline_fault_replays_parked_batches(monkeypatch):
    """A scripted dispatch fault fires while two batches are parked
    (depth 3): the pipeline drains onto the mirror with verdicts
    identical to the CPU-only run, the replay is counted, the breaker
    log replays byte-identically, and the device recovers."""
    stream = _random_stream(11, 60, 24, 8)
    cpu = CpuConflictSet()
    want = [cpu.detect(t, n, v) for t, n, v in stream]

    def run():
        inj = DeviceFaultInjector()
        # Dispatch check #6: batches 1-5 dispatched; with depth 3 the
        # submit of batch 6 finds 2 parked entries (3, 4 completed by
        # the bound) — the fault must replay both plus serve batch 6
        # degraded.  Three more consecutive faults open the circuit.
        for at in (6, 7, 8, 9):
            inj.script("dispatch", at=at)
        cs = _device_set(monkeypatch, 3, fault_injector=inj)
        got = _drive_pipelined(cs, stream, 3)
        return got, cs.device_metrics(), inj.injected

    got, dm, log = run()
    assert got == want, "fault-window verdicts diverged from CPU-only"
    assert dm["counters"]["pipeline_replayed_batches"] == 2
    assert dm["counters"]["device_faults"] >= 4
    assert dm["backend_state"] == "ok", dm["breaker"]
    got2, dm2, log2 = run()
    assert got2 == got and log2 == log and log
    assert json.dumps(dm2["breaker"]) == json.dumps(dm["breaker"])


def test_sync_surfacing_faults_open_the_breaker(monkeypatch):
    """Faults that surface only at the SYNC (the dominant real-hardware
    mode under async dispatch) must still walk the breaker: success is
    credited at the verified sync, never at dispatch, so consecutive
    sync faults reach the threshold and open the circuit — and verdicts
    still never diverge (mirror replay absorbs each faulted tail)."""
    from foundationdb_tpu.conflict.device_faults import DeviceUnavailable
    from foundationdb_tpu.conflict.engine_jax import JaxConflictSet

    stream = _random_stream(37, 60, 20, 6)
    cpu = CpuConflictSet()
    want = [cpu.detect(t, n, v) for t, n, v in stream]
    cs = _device_set(monkeypatch, 2)
    real_sync = JaxConflictSet.sync_ticket
    state = {"n": 0}

    def flaky_sync(self, ticket):
        state["n"] += 1
        if 4 <= state["n"] <= 6:  # three consecutive sync-time faults
            raise DeviceUnavailable("injected sync fault", site="dispatch")
        return real_sync(self, ticket)

    monkeypatch.setattr(JaxConflictSet, "sync_ticket", flaky_sync)
    got = _drive_pipelined(cs, stream, 2)
    assert got == want
    dm = cs.device_metrics()
    assert dm["counters"]["breaker_opens"] >= 1, dm["breaker"]
    assert dm["backend_state"] == "ok", dm["breaker"]  # probe recovered


def test_fixpoint_divergence_mid_pipeline_replays(monkeypatch):
    """A fixpoint divergence reported at the SYNC of a parked batch (the
    deferred analog of detect_packed's undecided fallback) marks the
    device stale and replays the whole in-flight tail on the mirror —
    verdicts identical, later batches rehydrate and agree."""
    from foundationdb_tpu.conflict.engine_jax import JaxConflictSet

    stream = _random_stream(31, 60, 16, 8)
    cpu = CpuConflictSet()
    want = [cpu.detect(t, n, v) for t, n, v in stream]

    cs = _device_set(monkeypatch, 3)
    real_sync = JaxConflictSet.sync_ticket
    fired = {"n": 0}

    def fake_sync(self, ticket):
        statuses, diverged = real_sync(self, ticket)
        if fired["n"] == 0 and len(cs._pipe) >= 2:
            fired["n"] += 1
            return None, True  # planted divergence with a parked tail
        return statuses, diverged

    monkeypatch.setattr(JaxConflictSet, "sync_ticket", fake_sync)
    got = _drive_pipelined(cs, stream, 3)
    assert fired["n"] == 1, "the planted divergence never fired"
    assert got == want
    dm = cs.device_metrics()
    assert dm["counters"]["pipeline_replayed_batches"] >= 2
    assert dm["counters"]["rehydrates"] >= 1  # the next submit reloaded


# ---------------------------------------------------------------------------
# 3. the pipelined Resolver role: verdict streams across depths, faults,
#    queue-depth honesty, duplicate replies, stall artifact
# ---------------------------------------------------------------------------


def _resolver_rig(seed, depth, monkeypatch, fault_script=()):
    """EventLoop + SimNetwork + one jax-backed Resolver + a driver
    process; returns (loop, resolver, driver_process, injector)."""
    from foundationdb_tpu.flow.eventloop import EventLoop
    from foundationdb_tpu.rpc.network import SimNetwork
    from foundationdb_tpu.server.resolver import Resolver

    monkeypatch.setenv("FDB_TPU_PIPELINE_DEPTH", str(depth))
    loop = EventLoop(seed)
    set_event_loop(loop)
    net = SimNetwork(loop)
    inj = DeviceFaultInjector()
    for site, at in fault_script:
        inj.script(site, at=at)
    cs = ConflictSet(
        backend="jax", key_words=3, bucket_mins=(32, 128, 64),
        h_cap=1 << 10, fault_injector=inj,
    )
    r = Resolver(net.process("resolver"), conflict_set=cs)
    return loop, r, net.process("driver"), inj


def _drive_resolver(loop, resolver, dproc, stream, cadence=0.002):
    """Send the scripted batch stream at a fixed virtual-time cadence
    WITHOUT awaiting each reply (overlapping requests are what the
    pipeline overlaps); returns the ordered reply verdict lists."""
    from foundationdb_tpu.server.interfaces import (
        ResolveTransactionBatchRequest,
    )

    iface = resolver.interface()

    async def drive():
        prev = 0
        futs = []
        for txns, now, _nov in stream:
            futs.append(iface.resolve.get_reply(
                dproc,
                ResolveTransactionBatchRequest(
                    prev_version=prev, version=now,
                    last_received_version=prev, transactions=txns,
                    proxy_id="p0",
                ),
            ))
            prev = now
            await loop.delay(cadence)
        return [(await f).committed for f in futs]

    return loop.run_until(dproc.spawn(drive(), "drive"), timeout_vt=600.0)


@pytest.mark.parametrize("seed", [3, 5, 9])
def test_resolver_verdict_stream_identical_across_depths(monkeypatch, seed):
    """The acceptance gate at the role level: the reply verdict stream
    and the exported conflict-set state are identical for depth 1 (sync)
    and depths 2/3, same seed, same scripted arrivals."""
    stream = _random_stream(seed, 60, 14, 8)
    results, states = {}, {}
    for depth in (1, 2, 3):
        loop, r, dproc, _ = _resolver_rig(seed, depth, monkeypatch)
        results[depth] = _drive_resolver(loop, r, dproc, stream)
        states[depth] = _exported_state(r.conflicts)
        set_event_loop(None)
    assert results[2] == results[1] and results[3] == results[1]
    assert states[2] == states[1] and states[3] == states[1]


def test_resolver_pipelined_fault_matches_sync(monkeypatch):
    """Scripted dispatch faults land mid-pipeline under the role (batch
    N faulted while N-1's apply is pending and N+1 arrives): the reply
    stream still matches the synchronous run, and the breaker log
    replays byte-identically."""
    stream = _random_stream(7, 60, 16, 8)
    script = (("dispatch", 5), ("dispatch", 6))

    def run(depth):
        loop, r, dproc, inj = _resolver_rig(7, depth, monkeypatch,
                                            fault_script=script)
        verdicts = _drive_resolver(loop, r, dproc, stream)
        dm = r.conflicts.device_metrics()
        set_event_loop(None)
        return verdicts, dm, inj.injected

    v1, dm1, log1 = run(1)
    v2, dm2, log2 = run(2)
    assert v2 == v1
    assert log2 == log1 and log1
    v2b, dm2b, _ = run(2)
    assert v2b == v2
    assert json.dumps(dm2b["breaker"]) == json.dumps(dm2["breaker"])


def test_queue_depth_counts_pipelined_parked_batches(monkeypatch):
    """Admission-control honesty (the PR-7 ratekeeper rides
    queue_depth): batches parked in the pipeline still count, in the
    property, the signals reply, and the registry gauge."""
    old_flush = g_knobs.server.resolver_pipeline_flush_seconds
    g_knobs.server.resolver_pipeline_flush_seconds = 5.0  # park visibly
    try:
        stream = _random_stream(13, 60, 2, 6)
        loop, r, dproc, _ = _resolver_rig(13, 3, monkeypatch)
        from foundationdb_tpu.server.interfaces import (
            ResolveTransactionBatchRequest,
        )

        iface = r.interface()
        seen = {}

        async def drive():
            prev = 0
            futs = []
            for txns, now, _nov in stream:
                futs.append(iface.resolve.get_reply(
                    dproc,
                    ResolveTransactionBatchRequest(
                        prev_version=prev, version=now,
                        last_received_version=prev, transactions=txns,
                        proxy_id="p0",
                    ),
                ))
                prev = now
                await loop.delay(0.002)
            await loop.delay(0.05)  # well under the 5s flush
            seen["parked"] = r.conflicts.pipeline_inflight
            seen["queue_depth"] = r.queue_depth
            seen["signals"] = r.signal_snapshot().queue_depth
            seen["gauge"] = r.metrics.gauge("pipeline_occupancy").value
            seen["replied"] = sum(1 for f in futs if f.is_ready())
            return [await f for f in futs]

        replies = loop.run_until(dproc.spawn(drive(), "drive"),
                                 timeout_vt=600.0)
        assert seen["parked"] == 2, seen
        assert seen["queue_depth"] == 2, seen
        assert seen["signals"] == 2
        assert seen["gauge"] == 2
        assert seen["replied"] == 0, "parked batches must not have replied"
        assert len(replies) == 2  # the idle flush drained the tail
        assert r.queue_depth == 0
        snap = r.metrics.snapshot()
        assert snap["counters"]["pipeline_host_stalls"] >= 1
        assert snap["histograms"]["pipeline_inflight_depth"]["max"] == 2
    finally:
        g_knobs.server.resolver_pipeline_flush_seconds = old_flush


def test_state_txn_retention_survives_parked_gc(monkeypatch):
    """Regression: last_version advances at SUBMIT, so the retention GC
    running at an earlier batch's COMPLETION must not delete state
    transactions a still-parked batch's reply (built later) needs.
    Proxy A resolves v3, proxy B resolves v5 WITH state txns, proxy A
    resolves v9 — A's v9 reply must carry v5's state mutations even
    though v9's submit bumped A.last_version past the GC horizon while
    v5 was still completing."""
    old_flush = g_knobs.server.resolver_pipeline_flush_seconds
    g_knobs.server.resolver_pipeline_flush_seconds = 0.05
    try:
        from foundationdb_tpu.flow.eventloop import EventLoop
        from foundationdb_tpu.rpc.network import SimNetwork
        from foundationdb_tpu.server.interfaces import (
            ResolveTransactionBatchRequest,
        )
        from foundationdb_tpu.server.resolver import Resolver

        monkeypatch.setenv("FDB_TPU_PIPELINE_DEPTH", "2")
        loop = EventLoop(21)
        set_event_loop(loop)
        net = SimNetwork(loop)
        cs = ConflictSet(backend="jax", key_words=3,
                         bucket_mins=(32, 128, 64), h_cap=1 << 10)
        r = Resolver(net.process("resolver"), conflict_set=cs, n_proxies=2)
        dproc = net.process("driver")
        iface = r.interface()
        wtxn = T(read_snapshot=0, write_ranges=[(k(1), k(2))])

        async def drive():
            f1 = iface.resolve.get_reply(dproc, ResolveTransactionBatchRequest(
                prev_version=0, version=3, transactions=[wtxn],
                proxy_id="pA"))
            f2 = iface.resolve.get_reply(dproc, ResolveTransactionBatchRequest(
                prev_version=3, version=5, transactions=[wtxn],
                state_txns=[(0, [("set", b"\xffk", b"v")])], proxy_id="pB"))
            f3 = iface.resolve.get_reply(dproc, ResolveTransactionBatchRequest(
                prev_version=5, version=9, transactions=[wtxn],
                proxy_id="pA"))
            return await f1, await f2, await f3

        r1, r2, r3 = loop.run_until(dproc.spawn(drive(), "drive"),
                                    timeout_vt=600.0)
        assert [v for v, _m in r3.state_mutations] == [5], (
            "v9's reply lost v5's state transactions to the parked GC"
        )
    finally:
        g_knobs.server.resolver_pipeline_flush_seconds = old_flush


def test_duplicate_request_while_parked_waits_for_cache(monkeypatch):
    """A proxy retry for a version still parked in the pipeline must get
    the SAME reply (via the per-proxy cache after completion), never
    operation_failed."""
    old_flush = g_knobs.server.resolver_pipeline_flush_seconds
    g_knobs.server.resolver_pipeline_flush_seconds = 0.05
    try:
        stream = _random_stream(17, 60, 1, 6)
        txns, now, _ = stream[0]
        loop, r, dproc, _ = _resolver_rig(17, 2, monkeypatch)
        from foundationdb_tpu.server.interfaces import (
            ResolveTransactionBatchRequest,
        )

        iface = r.interface()

        async def drive():
            req = ResolveTransactionBatchRequest(
                prev_version=0, version=now, last_received_version=0,
                transactions=txns, proxy_id="p0",
            )
            f1 = iface.resolve.get_reply(dproc, req)
            await loop.delay(0.002)  # original is parked (flush at 50ms)
            assert not f1.is_ready()
            f2 = iface.resolve.get_reply(dproc, req)  # the retry
            return (await f1), (await f2)

        r1, r2 = loop.run_until(dproc.spawn(drive(), "drive"),
                                timeout_vt=600.0)
        assert r1.committed == r2.committed
        assert r.metrics.counter("cache_hits").value == 1
    finally:
        g_knobs.server.resolver_pipeline_flush_seconds = old_flush


def test_sustained_stall_freezes_flight_recorder_artifact(monkeypatch):
    """Zero-overlap operation (every batch drained by the idle flush)
    for resolver_pipeline_stall_batches in a row leaves a black-box
    artifact tagged pipeline_stall."""
    from foundationdb_tpu.flow.flight_recorder import (
        FlightRecorder,
        global_flight_recorder,
        set_global_flight_recorder,
    )

    old_stall = g_knobs.server.resolver_pipeline_stall_batches
    g_knobs.server.resolver_pipeline_stall_batches = 3
    old_rec = global_flight_recorder()
    set_global_flight_recorder(FlightRecorder())
    try:
        stream = _random_stream(19, 60, 6, 6)
        loop, r, dproc, _ = _resolver_rig(19, 2, monkeypatch)
        # Arrivals far apart (50ms >> the 5ms flush): every batch parks,
        # no successor ever pushes it out — the flush drains each one.
        _drive_resolver(loop, r, dproc, stream, cadence=0.05)
        rec = global_flight_recorder()
        assert any(c["trigger"] == "pipeline_stall" for c in rec.captures), (
            rec.status_section()
        )
        snap = r.metrics.snapshot()
        assert snap["counters"]["pipeline_host_stalls"] >= 3
    finally:
        g_knobs.server.resolver_pipeline_stall_batches = old_stall
        set_global_flight_recorder(old_rec)


def test_cluster_commits_engage_the_pipeline(monkeypatch):
    """End-to-end smoke: a SimCluster with a jax resolver at the default
    depth serves live commit traffic through the pipelined path (the
    dispatch counter proves engagement) with every commit answered."""
    monkeypatch.setenv("FDB_TPU_PIPELINE_DEPTH", "2")
    from foundationdb_tpu.server import SimCluster

    c = SimCluster(seed=4321, conflict_backend="jax")
    db = c.database()
    committed = []

    async def commits():
        for i in range(8):
            tr = db.create_transaction()
            tr.set(b"pl/%02d" % i, b"v")
            committed.append(await tr.commit())

    c.run_until(db.process.spawn(commits(), "commits"), timeout_vt=5000.0)
    assert len(committed) == 8 and all(v is not None for v in committed)
    dm = c.resolver.conflicts.device_metrics()
    assert dm["counters"]["pipeline_dispatches"] >= 1
    assert dm["pipeline"]["inflight"] == 0  # idle flush drained the tail
    snap = c.resolver.metrics.snapshot()
    assert (
        snap["counters"]["pipeline_device_stalls"]
        + snap["counters"]["pipeline_host_stalls"]
        >= 1
    )
