"""Native C++ key-value engine: correctness, compaction, crash durability.

Ref: fdbserver/KeyValueStoreMemory.actor.cpp (the WAL+snapshot memory
engine contract: committed data survives any crash; uncommitted data may
vanish; recovery truncates the torn WAL tail).
"""

import os
import signal
import subprocess
import sys
import textwrap

import pytest

from foundationdb_tpu.fileio.kvstore_native import NativeKeyValueStore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_async(coro):
    import asyncio

    return asyncio.get_event_loop_policy().new_event_loop().run_until_complete(coro)


def test_basic_crud_and_reopen(tmp_path):
    d = str(tmp_path / "kv")
    kv = NativeKeyValueStore(d)
    for i in range(100):
        kv.set(b"k%03d" % i, b"v%d" % i)
    kv.clear_range(b"k020", b"k040")
    run_async(kv.commit())
    assert kv.read_value(b"k010") == b"v10"
    assert kv.read_value(b"k025") is None
    rows = kv.read_range(b"k", b"l", limit=5)
    assert [k for k, _ in rows] == [b"k000", b"k001", b"k002", b"k003", b"k004"]
    rows_r = kv.read_range(b"k", b"l", limit=3, reverse=True)
    assert [k for k, _ in rows_r] == [b"k099", b"k098", b"k097"]
    assert kv.count() == 80
    kv.close()

    # Reopen: WAL replay restores everything committed.
    kv2 = NativeKeyValueStore(d)
    assert kv2.count() == 80
    assert kv2.read_value(b"k050") == b"v50"
    assert kv2.read_value(b"k030") is None
    kv2.close()


def test_compaction_preserves_data(tmp_path):
    d = str(tmp_path / "kv")
    kv = NativeKeyValueStore(d, compact_threshold=1)  # compact every commit
    for i in range(50):
        kv.set(b"c%03d" % i, b"x" * 100)
    run_async(kv.commit())
    for i in range(0, 50, 2):
        kv.clear_range(b"c%03d" % i, b"c%03d\x00" % i)
    run_async(kv.commit())
    kv.close()
    kv2 = NativeKeyValueStore(d)
    assert kv2.count() == 25
    assert kv2.read_value(b"c001") == b"x" * 100
    assert kv2.read_value(b"c002") is None
    kv2.close()
    # Old generations were removed.
    files = sorted(os.listdir(d))
    assert len([f for f in files if f.startswith("snapshot")]) == 1
    assert len([f for f in files if f.startswith("wal")]) == 1


def test_uncommitted_writes_do_not_survive(tmp_path):
    d = str(tmp_path / "kv")
    kv = NativeKeyValueStore(d)
    kv.set(b"durable", b"1")
    run_async(kv.commit())
    kv.set(b"volatile", b"1")  # never committed
    kv.close()
    kv2 = NativeKeyValueStore(d)
    assert kv2.read_value(b"durable") == b"1"
    assert kv2.read_value(b"volatile") is None
    kv2.close()


def test_sigkill_crash_durability(tmp_path):
    """A real OS crash (SIGKILL mid-stream): every COMMITTED write must
    survive; the torn WAL tail must not corrupt recovery."""
    d = str(tmp_path / "kv")
    script = textwrap.dedent(
        f"""
        import sys
        sys.path.insert(0, {REPO!r})
        import asyncio, os, signal
        from foundationdb_tpu.fileio.kvstore_native import NativeKeyValueStore

        kv = NativeKeyValueStore({d!r})
        async def main():
            for i in range(10000):
                kv.set(b"s%05d" % i, b"val%d" % i)
                if i % 100 == 99:
                    await kv.commit()
                    print(i, flush=True)
        asyncio.new_event_loop().run_until_complete(main())
        """
    )
    p = subprocess.Popen(
        [sys.executable, "-c", script],
        stdout=subprocess.PIPE,
        text=True,
    )
    # Wait until a few commits are acked, then SIGKILL mid-flight.
    acked = 0
    for line in p.stdout:
        acked = int(line.strip())
        if acked >= 1999:
            break
    os.kill(p.pid, signal.SIGKILL)
    p.wait()

    kv = NativeKeyValueStore(d)
    # Every key up to the last acked commit is present.
    for i in range(0, acked + 1, 37):
        assert kv.read_value(b"s%05d" % i) == b"val%d" % i, i
    assert kv.count() >= acked + 1
    kv.close()
