"""Counters + MetricLogger + sim_validation.

Ref: flow/Stats.h:55-111 (Counter/traceCounters),
fdbclient/MetricLogger.actor.cpp (metrics persisted into \xff/metrics),
fdbrpc/sim_validation (durability promises checked loudly).
"""

import pytest

from foundationdb_tpu.client.metric_logger import (
    log_metrics_once,
    read_metrics,
)
from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.flow.trace import global_collector
from foundationdb_tpu.server import SimCluster


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def test_proxy_counters_and_trace_emission():
    c = SimCluster(seed=150)
    db = c.database()

    async def load():
        for i in range(10):

            async def op(tr, i=i):
                tr.set(b"s%02d" % i, b"v")

            await db.run(op)
        await c.loop.delay(6.0)  # one traceCounters interval

    c.run_all([(db, load())], timeout_vt=1000.0)
    assert c.proxy.stats["committed"] >= 10
    assert c.proxy.stats["batches"] >= 1
    evs = global_collector().find("Proxyproxy0Metrics")
    assert evs, "traceCounters emitted nothing"
    assert evs[-1]["committed"] >= 10


def test_metric_logger_roundtrip():
    c = SimCluster(seed=151)
    db = c.database()

    async def load():
        for i in range(5):

            async def op(tr, i=i):
                tr.set(b"m%02d" % i, b"v")

            await db.run(op)
        await log_metrics_once(db, [c.proxy.stats])
        return await read_metrics(db, c.proxy.stats.name)

    metrics = c.run_until(db.process.spawn(load()), timeout_vt=1000.0)
    assert "committed" in metrics
    series = metrics["committed"]
    assert series and series[-1][1] >= 5


def test_sim_validation_catches_acked_loss():
    """Force the invariant recorder to fire: pretend a commit beyond the
    epoch cut was acked; the next recovery must fail loudly."""
    from foundationdb_tpu.flow import sim_validation

    class FakeLoop:
        pass

    loop = FakeLoop()
    sim_validation.mark_at_least(loop, "acked_commit", 500)
    sim_validation.expect_at_least(loop, "acked_commit", 600)  # fine
    with pytest.raises(AssertionError, match="promised 500"):
        sim_validation.expect_at_least(loop, "acked_commit", 400)
