"""Counters + MetricLogger + sim_validation.

Ref: flow/Stats.h:55-111 (Counter/traceCounters),
fdbclient/MetricLogger.actor.cpp (metrics persisted into \xff/metrics),
fdbrpc/sim_validation (durability promises checked loudly).
"""

import pytest

from foundationdb_tpu.client.metric_logger import (
    log_metrics_once,
    read_metrics,
)
from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.flow.trace import global_collector
from foundationdb_tpu.server import SimCluster


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def test_proxy_counters_and_trace_emission():
    c = SimCluster(seed=150)
    db = c.database()

    async def load():
        for i in range(10):

            async def op(tr, i=i):
                tr.set(b"s%02d" % i, b"v")

            await db.run(op)
        await c.loop.delay(6.0)  # one traceCounters interval

    c.run_all([(db, load())], timeout_vt=1000.0)
    assert c.proxy.stats["committed"] >= 10
    assert c.proxy.stats["batches"] >= 1
    evs = global_collector().find("Proxyproxy0Metrics")
    assert evs, "traceCounters emitted nothing"
    assert evs[-1]["committed"] >= 10


def test_metric_logger_roundtrip():
    c = SimCluster(seed=151)
    db = c.database()

    async def load():
        for i in range(5):

            async def op(tr, i=i):
                tr.set(b"m%02d" % i, b"v")

            await db.run(op)
        await log_metrics_once(db, [c.proxy.stats])
        return await read_metrics(db, c.proxy.stats.name)

    metrics = c.run_until(db.process.spawn(load()), timeout_vt=1000.0)
    assert "committed" in metrics
    series = metrics["committed"]
    assert series and series[-1][1] >= 5


def test_sim_validation_catches_acked_loss():
    """Force the invariant recorder to fire: pretend a commit beyond the
    epoch cut was acked; the next recovery must fail loudly."""
    from foundationdb_tpu.flow import sim_validation

    class FakeLoop:
        pass

    loop = FakeLoop()
    sim_validation.mark_at_least(loop, "acked_commit", 500)
    sim_validation.expect_at_least(loop, "acked_commit", 600)  # fine
    with pytest.raises(AssertionError, match="promised 500"):
        sim_validation.expect_at_least(loop, "acked_commit", 400)


def test_system_monitor_emits_process_metrics():
    """ProcessMetrics events on a cadence (ref: flow/SystemMonitor.cpp)."""
    from foundationdb_tpu.flow.system_monitor import run_system_monitor
    from foundationdb_tpu.flow.trace import TraceCollector, set_global_collector

    col = TraceCollector()
    set_global_collector(col)
    try:
        c = SimCluster(seed=88)
        db = c.database()
        db.process.spawn(run_system_monitor(db.process, interval=0.5), "sm")

        async def idle():
            await c.loop.delay(2.0)

        c.run_until(db.process.spawn(idle(), "idle"), timeout_vt=100.0)
        evs = col.find("ProcessMetrics")
        assert len(evs) >= 3
        assert evs[0]["tasks_run_delta"] >= 0
        assert "live_actors" in evs[0] and "heap_events" in evs[0]
    finally:
        set_global_collector(TraceCollector())
    set_event_loop(None)


def test_slow_task_profiler_fires():
    """A single step hogging the reactor beyond the threshold traces a
    SlowTask (ref: Net2 slow-task profiling)."""
    import time

    from foundationdb_tpu.flow.trace import TraceCollector, set_global_collector

    col = TraceCollector()
    set_global_collector(col)
    try:
        c = SimCluster(seed=89)
        c.loop.slow_task_threshold = 0.01
        db = c.database()

        async def hog():
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < 0.02:
                pass  # burn wall clock inside ONE step

        c.run_until(db.process.spawn(hog(), "hog"), timeout_vt=100.0)
        assert col.find("SlowTask"), "slow step never traced"
    finally:
        set_global_collector(TraceCollector())
    set_event_loop(None)


def test_system_monitor_wall_metrics_gated():
    """wall_metrics=False (the sim default) keeps every rusage-derived
    field out of the trace stream — tracing wall values under simulation
    would break same-seed trace byte-identity; wall_metrics=True (real
    deployments) adds them (ref: flow/SystemMonitor.cpp's machineMetrics
    split)."""
    from foundationdb_tpu.flow.system_monitor import run_system_monitor
    from foundationdb_tpu.flow.trace import TraceCollector, set_global_collector

    col = TraceCollector()
    set_global_collector(col)
    try:
        c = SimCluster(seed=90)
        db = c.database()
        db.process.spawn(run_system_monitor(db.process, interval=0.5), "sm")
        wall_proc = c.net.process("wallmon")
        wall_proc.spawn(
            run_system_monitor(wall_proc, interval=0.5, wall_metrics=True),
            "sm_wall",
        )

        async def idle():
            await c.loop.delay(2.0)

        c.run_until(db.process.spawn(idle(), "idle"), timeout_vt=100.0)
        evs = col.find("ProcessMetrics")
        sim_evs = [e for e in evs if e["process"] != "wallmon"]
        wall_evs = [e for e in evs if e["process"] == "wallmon"]
        assert sim_evs and wall_evs
        for e in sim_evs:  # NO wall-derived fields in the sim cadence
            assert "max_rss_kb" not in e and "cpu_user_s" not in e
        # Real-mode cadence carries rusage (where the platform has it).
        assert any("max_rss_kb" in e for e in wall_evs)
        # Virtual-time pacing: timestamps advance by the interval exactly.
        times = [e["Time"] for e in sim_evs]
        assert times == sorted(times)
        assert all(
            abs((t2 - t1) - 0.5) < 1e-9
            for t1, t2 in zip(times, times[1:])
        )
    finally:
        set_global_collector(TraceCollector())
    set_event_loop(None)


@pytest.mark.slow  # tier-1 headroom (ISSUE 4): multi-resolution soak
def test_metric_levels_multi_resolution():
    """TDMetric-style levels: level 0 records every flush; higher levels
    thin out by 4x per level (flow/TDMetric.actor.h:168)."""
    from foundationdb_tpu.client.metric_logger import (
        BASE_RESOLUTION,
        read_metric_levels,
    )

    c = SimCluster(seed=152)
    db = c.database()

    async def drive():
        async def op(tr):
            tr.set(b"lvl", b"x")

        # Flush every BASE_RESOLUTION for ~20 periods of virtual time.
        for _ in range(20):
            await op_and_flush(op)
        return await read_metric_levels(db, c.proxy.stats.name, "committed")

    async def op_and_flush(op):
        await db.run(op)
        await log_metrics_once(db, [c.proxy.stats])
        await c.loop.delay(BASE_RESOLUTION)

    levels = c.run_until(db.process.spawn(drive()), timeout_vt=5000.0)
    assert len(levels) == 4
    n0, n1, n2 = len(levels[0]), len(levels[1]), len(levels[2])
    assert n0 == 20
    # Level 1 samples every 4 periods, level 2 every 16: strictly coarser.
    assert 4 <= n1 <= 7 and n1 < n0, (n0, n1)
    assert 1 <= n2 <= 3, n2
    # Monotone timestamps, monotone counter values within each level.
    for series in levels:
        ts = [t for t, _v in series]
        assert ts == sorted(ts)
