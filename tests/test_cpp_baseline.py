"""Differential test: the C++ skiplist baseline must make byte-identical
decisions with the in-repo authority (engine_cpu.CpuConflictSet) on random
batch streams — same discipline as the JAX-vs-CPU differential suite.

Ref: the baseline mirrors fdbserver skipListTest semantics
(SkipList.cpp:1412-1502); cpp/skiplist_baseline.cpp --selftest speaks a
line protocol over stdin/stdout.
"""

import os
import subprocess

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(REPO, "cpp", "skiplist_baseline.cpp")
BIN = os.path.join(REPO, "cpp", "skiplist_baseline")


def build():
    if os.path.exists(BIN) and os.path.getmtime(BIN) >= os.path.getmtime(SRC):
        return
    subprocess.run(
        ["g++", "-O2", "-o", BIN, SRC], check=True, capture_output=True
    )


def int_key(v: int) -> bytes:
    return int(v).to_bytes(4, "big")


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_cpp_baseline_differential(seed):
    from foundationdb_tpu.conflict.engine_cpu import CpuConflictSet
    from foundationdb_tpu.conflict.types import TransactionConflictInfo

    build()
    rng = np.random.default_rng(seed)
    KEYSPACE = 5000  # small keyspace => dense collisions
    WINDOW = 4
    n_batches = 30
    lines = []
    py_batches = []
    for i in range(n_batches):
        ntxn = int(rng.integers(1, 20))
        lines.append(f"B {i + WINDOW} {i} {ntxn}")
        txns = []
        for _t in range(ntxn):
            nr = int(rng.integers(0, 3))
            nw = int(rng.integers(0, 3))
            # snapshots sometimes stale enough to be too old / conflicting
            snap = int(max(0, i - rng.integers(0, WINDOW + 3)))
            lines.append(f"{snap} {nr} {nw}")
            rr, wr = [], []
            for _ in range(nr):
                b = int(rng.integers(0, KEYSPACE))
                e = b + 1 + int(rng.integers(0, 12))
                lines.append(f"r {b} {e}")
                rr.append((int_key(b), int_key(e)))
            for _ in range(nw):
                b = int(rng.integers(0, KEYSPACE))
                e = b + 1 + int(rng.integers(0, 12))
                lines.append(f"w {b} {e}")
                wr.append((int_key(b), int_key(e)))
            txns.append(
                TransactionConflictInfo(
                    read_snapshot=snap, read_ranges=rr, write_ranges=wr
                )
            )
        py_batches.append(txns)

    proc = subprocess.run(
        [BIN, "--selftest"],
        input="\n".join(lines) + "\n",
        capture_output=True,
        text=True,
        check=True,
        timeout=60,
    )
    cpp_out = [
        [int(x) for x in line.split()]
        for line in proc.stdout.strip().split("\n")
    ]

    cs = CpuConflictSet()
    for i, txns in enumerate(py_batches):
        want = cs.detect(txns, now=i + WINDOW, new_oldest_version=i)
        assert cpp_out[i] == want, (
            f"seed {seed} batch {i}: cpp={cpp_out[i]} py={want}"
        )


def test_cpp_baseline_bench_runs():
    build()
    out = subprocess.run(
        [BIN, "--batches", "10", "--per-batch", "500"],
        capture_output=True,
        text=True,
        check=True,
        timeout=60,
    ).stdout
    import json

    doc = json.loads(out)
    assert doc["value"] > 0
