"""Multi-region: satellite logs, log routers, two-DC failover, multi-log DR.

Ref: fdbserver/LogRouter.actor.cpp:172 (pullAsyncData re-serving the
primary stream in a remote DC), the satellite TLog design (synchronous
full-stream logs in the commit ack set — the zero-loss failover source),
and DatabaseBackupAgent's merged log cursors (multi-log DR sources).
"""

import pytest

from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.interfaces import GetKeyValuesRequest
from foundationdb_tpu.server.log_router import LogRouter
from foundationdb_tpu.server.storage import StorageServer


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def test_log_router_reserves_stream_to_remote_storage():
    """A remote storage consuming ONLY from a log router converges to the
    primary's state; router floors forward to the primary log."""
    c = SimCluster(seed=9400, n_tlogs=2)
    db = c.database()
    remote_proc = c.net.process("remote1", machine_id="remote1")
    router = LogRouter(
        remote_proc,
        [t.interface() for t in c.tlogs],
        router_id="r1",
    )
    remote_ss = StorageServer(
        remote_proc,
        [router.interface()],
        storage_id="ss0",  # paired with the primary's tag
        owned_all=True,
    )

    async def scenario():
        async def fill(tr):
            for i in range(30):
                tr.set(b"lr%03d" % i, b"v%03d" % i)

        await db.run(fill)
        # Remote convergence: the router pulls, the remote storage applies.
        target = c.tlogs[0].durable.get()
        for _ in range(600):
            if remote_ss.version.get() >= target:
                break
            await c.loop.delay(0.01)
        assert remote_ss.version.get() >= target, (
            remote_ss.version.get(),
            target,
        )
        rep = await remote_ss.interface().get_key_values.get_reply(
            db.process,
            GetKeyValuesRequest(
                begin=b"lr",
                end=b"ls",
                version=remote_ss.version.get(),
                limit=100,
            ),
        )
        assert len(rep.data) == 30
        assert rep.data[7] == (b"lr%03d" % 7, b"v%03d" % 7)
        # The router forwarded its consumers' floors to the primary.
        for _ in range(200):
            if all(
                t.popped_tags.get(router.router_tag, 0) > 0 for t in c.tlogs
            ):
                break
            await c.loop.delay(0.05)
        assert all(
            t.popped_tags.get(router.router_tag, 0) > 0 for t in c.tlogs
        ), "router never forwarded remote floors to the primary"

    c.run_until(db.process.spawn(scenario(), "sc"), timeout_vt=5000.0)


def test_two_dc_failover_zero_acked_loss():
    """usable_regions=2 shape: primary DC (logs+pipeline) + satellite log
    (in the ack set, full stream) + remote DC (router + storage replica).
    Kill the WHOLE primary DC: everything acked must be readable from the
    remote replica once it drains the satellite — zero acked-commit loss
    (the satellite is why; an async-only remote would lose the tail)."""
    c = SimCluster(seed=9401, n_tlogs=2, n_satellite_tlogs=1)
    db = c.database()
    satellite = c.tlogs[-1]
    remote_proc = c.net.process("remote1", machine_id="remote1")
    router = LogRouter(
        remote_proc, [satellite.interface()], router_id="r1"
    )
    remote_ss = StorageServer(
        remote_proc, [router.interface()], storage_id="ss0", owned_all=True
    )
    state = {}

    async def scenario():
        last_commit = 0
        for i in range(25):
            tr = db.create_transaction()
            tr.set(b"fo%03d" % i, b"val%03d" % i)
            last_commit = await tr.commit()
        state["acked_through"] = last_commit
        # Remote may be arbitrarily behind at this instant; that's the
        # point of the test.
        # --- kill the ENTIRE primary DC (satellite + remote survive) ---
        for p in (
            [c.master_proc, c.resolver_proc, c.proxy_proc, c.storage_proc]
            + c.tlog_procs[:-1]
        ):
            p.kill()
        # The remote replica drains the surviving satellite through every
        # acked version (acks REQUIRED satellite durability).
        assert satellite.durable.get() >= last_commit
        for _ in range(1000):
            if remote_ss.version.get() >= last_commit:
                break
            await c.loop.delay(0.01)
        assert remote_ss.version.get() >= last_commit, (
            f"remote stuck at {remote_ss.version.get()} < acked "
            f"{last_commit}"
        )
        rep = await remote_ss.interface().get_key_values.get_reply(
            db.process,
            GetKeyValuesRequest(
                begin=b"fo",
                end=b"fp",
                version=remote_ss.version.get(),
                limit=100,
            ),
        )
        got = dict(rep.data)
        for i in range(25):
            assert got.get(b"fo%03d" % i) == b"val%03d" % i, (
                f"acked key fo{i:03d} lost in failover"
            )
        state["ok"] = True

    c.run_until(db.process.spawn(scenario(), "sc"), timeout_vt=5000.0)
    assert state.get("ok")


def test_dr_multi_log_source():
    """The DR agent tails a TWO-log source through the merge cursor (the
    v1 single-log assert is gone); destination converges byte-exact."""
    from foundationdb_tpu.layers.dr import DRAgent

    src = SimCluster(seed=9402, n_tlogs=2, n_storages=2)
    sdb = src.database("src_client")
    # buggify is process-global: False here runs BOTH clusters fault-free
    # deliberately (this is a convergence test, not a chaos test).
    dst = SimCluster(
        seed=9403, loop=src.loop, buggify=False
    )
    ddb = dst.database("dst_client")
    agent = DRAgent(
        sdb, ddb, [t.interface() for t in src.tlogs]
    )
    state = {}

    async def scenario():
        async def fill(tr):
            for i in range(20):
                tr.set(b"dr%03d" % i, b"v%03d" % i)

        await sdb.run(fill)
        await agent.start()

        async def more(tr):
            for i in range(20, 40):
                tr.set(b"dr%03d" % i, b"v%03d" % i)
            tr.clear_range(b"dr000", b"dr005")

        await sdb.run(more)
        # Tail until the destination reflects the source.
        for _ in range(400):
            await agent.tail_once()
            out = {}

            async def read(tr):
                out["rows"] = await tr.get_range(b"dr", b"ds")

            await ddb.run(read)
            want = [
                (b"dr%03d" % i, b"v%03d" % i) for i in range(5, 40)
            ]
            if out["rows"] == want:
                state["ok"] = True
                return
            await src.loop.delay(0.01)
        raise AssertionError(f"destination never converged: {out['rows'][:6]}")

    src.run_until(sdb.process.spawn(scenario(), "sc"), timeout_vt=5000.0)
    assert state.get("ok")
