"""Cluster crash-recovery: committed data survives full-cluster kills with
disk corruption of unsynced writes (the sim_validation property: everything
acknowledged as committed must be readable after recovery)."""

import pytest

from foundationdb_tpu.client.types import MutationType
from foundationdb_tpu.flow import FdbError, set_event_loop
from foundationdb_tpu.server import SimCluster


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


@pytest.mark.parametrize("seed", range(6))
def test_committed_data_survives_cluster_crash(seed):
    c = SimCluster(seed=seed, durable=True)
    db = c.database()
    committed = {}

    def writer_round(r):
        async def go():
            rng = c.loop.rng
            for i in range(int(rng.random_int(2, 6))):
                async def op(tr, r=r, i=i):
                    k = b"key/%d" % int(rng.random_int(0, 12))
                    v = b"r%d-i%d" % (r, i)
                    tr.set(k, v)
                    return k, v

                tr = db.create_transaction()
                k, v = await op(tr)
                await tr.commit()
                committed[k] = v

        return go()

    for crash_round in range(3):
        c.run_all([(db, writer_round(crash_round))], timeout_vt=500.0)
        c.crash_and_recover()
        out = {}

        async def check(tr):
            out["state"] = dict(await tr.get_range(b"key/", b"key0"))

        c.run_all([(db, db.run(check))], timeout_vt=500.0)
        assert out["state"] == committed, f"after crash {crash_round}"


def test_cluster_keeps_working_after_recovery():
    c = SimCluster(seed=42, durable=True)
    db = c.database()

    async def w1(tr):
        tr.set(b"a", b"1")
        tr.atomic_op(MutationType.ADD_VALUE, b"n", (7).to_bytes(4, "little"))

    c.run_all([(db, db.run(w1))])
    c.crash_and_recover()

    async def w2(tr):
        tr.set(b"b", b"2")
        tr.atomic_op(MutationType.ADD_VALUE, b"n", (5).to_bytes(4, "little"))

    c.run_all([(db, db.run(w2))])
    out = {}

    async def check(tr):
        out["a"] = await tr.get(b"a")
        out["b"] = await tr.get(b"b")
        out["n"] = int.from_bytes(await tr.get(b"n"), "little")

    c.run_all([(db, db.run(check))])
    assert out == {"a": b"1", "b": b"2", "n": 12}


def test_stale_snapshot_too_old_after_recovery():
    """A transaction whose snapshot predates the recovery epoch must fail
    with a retryable error, not read stale state."""
    c = SimCluster(seed=9, durable=True)
    db = c.database()

    async def w(tr):
        tr.set(b"x", b"1")

    c.run_all([(db, db.run(w))])

    tr = db.create_transaction()

    async def grab_version():
        await tr.get_read_version()

    c.run_all([(db, grab_version())])
    c.crash_and_recover()

    result = {}

    async def stale_write():
        try:
            # Use the pre-crash snapshot for a conflict-checked read+write.
            v = await tr.get(b"x")
            tr.set(b"x", b"2")
            await tr.commit()
            result["r"] = "committed"
        except FdbError as e:
            result["r"] = e.name

    c.run_all([(db, stale_write())], timeout_vt=500.0)
    assert result["r"] in ("transaction_too_old", "future_version")


def test_broken_proxy_pipeline_triggers_recovery():
    """A commit batch dying mid-phase (e.g. a transient transport error on
    a live resolver) leaves a permanent hole in the prevVersion chain —
    the logs wait forever for the missing version.  The proxy must mark
    itself broken and role_check must surface it, so the CC runs a
    recovery even though every PROCESS is alive and pinging (ref: the
    reference proxy actor dying on commitBatch errors)."""
    from foundationdb_tpu.flow import testprobe
    from foundationdb_tpu.flow.error import FdbError
    from foundationdb_tpu.server.dynamic_cluster import DynamicCluster

    probes_before = {
        n: testprobe.hit_sites.get(n, 0)
        for n in ("proxy_pipeline_broken", "stale_role_retired")
    }
    c = DynamicCluster(seed=930, n_workers=7, n_proxies=1, n_storages=2)
    db = c.database()

    async def w(tr):
        tr.set(b"pb/seed", b"1")

    c.run_all([(db, db.run(w))])
    cc = c.acting_controller()
    gen0 = cc.generation

    # Force one batch to die mid-phase: patch the impl to raise once.
    proxy = next(
        w.roles["proxy"] for w in c.workers if "proxy" in w.roles
    )
    orig = proxy._commit_batch_impl
    state = {"raised": False}

    async def flaky(batch, local_batch, ctx=None):
        if not state["raised"]:
            state["raised"] = True
            # Die AFTER phase 1: the consumed (prev, version) pair is the
            # chain hole — without the broken flag, every later batch
            # wedges at the log push forever.
            await proxy._batch_resolving.when_at_least(local_batch - 1)
            await proxy.sequencer.get_commit_version.get_reply(
                proxy.process, proxy.epoch
            )
            proxy._batch_resolving.set(local_batch)
            raise FdbError("connection_failed")
        return await orig(batch, local_batch, ctx)

    proxy._commit_batch_impl = flaky

    out = {}

    async def drive():
        loop = c.loop
        try:
            async def w2(tr):
                tr.set(b"pb/x", b"y")

            await db.run(w2)
        except FdbError:
            pass  # unknown result for the broken batch is fine
        # The CC must notice the broken proxy and recover; post-recovery
        # commits must succeed (the new proxy has a clean chain).
        for _ in range(400):
            try:
                async def w3(tr):
                    tr.set(b"pb/after", b"ok")

                await db.run(w3)
                out["done"] = True
                return
            except FdbError:
                await loop.delay(0.1)

    c.run_until(db.process.spawn(drive(), "pb"), timeout_vt=3000.0)
    assert state["raised"], "patched batch never ran"
    assert proxy.broken, "proxy did not mark itself broken"
    for n, before in probes_before.items():
        assert testprobe.hit_sites.get(n, 0) > before, (
            f"probe {n} did not fire IN THIS TEST"
        )
    assert out.get("done"), "commits never succeeded after the break"
    assert c.acting_controller().generation > gen0, "no recovery happened"
