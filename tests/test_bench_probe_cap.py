"""bench.py device-probe spend cap (ISSUE 4 satellite).

A dead axon tunnel must not ride the whole device budget into the
driver's rc=124 kill (BENCH_SESSION_NOTE.json: 7 probe attempts ate the
run): probing stops after BENCH_PROBE_MAX_FAILS consecutive failures or
BENCH_PROBE_BUDGET_FRAC of the device budget in probe wall time, and the
final JSON carries an explicit `device_skipped` field.  Stubbed probe —
no device, no jax, milliseconds.
"""

import contextlib
import io
import sys
import time

import pytest

from conftest import REPO_ROOT

sys.path.insert(0, REPO_ROOT)
import bench  # noqa: E402


@pytest.fixture(autouse=True)
def _fast_probe_env(monkeypatch):
    monkeypatch.setenv("BENCH_PROBE_INTERVAL", "1")
    monkeypatch.setenv("BENCH_PROBE_TIMEOUT", "2")
    yield


def _dead_probe(timeout):
    raise RuntimeError("tunnel dead")


def test_consecutive_failure_cap(monkeypatch):
    monkeypatch.setattr(bench, "probe_device", _dead_probe)
    out, errors = {"value": 0.0}, []
    ps = {"spent_s": 0.0, "consecutive_fails": 0, "budget_s": 900.0,
          "max_consecutive_fails": 2}
    with contextlib.redirect_stdout(io.StringIO()):
        ok = bench.wait_for_device(out, errors, time.perf_counter() + 60, ps)
    assert not ok
    assert "consecutive probe failures" in ps["skipped"]
    assert out["probe_attempts"] == 2  # exactly the cap, not the budget


def test_probe_spend_budget_cap(monkeypatch):
    monkeypatch.setattr(bench, "probe_device", _dead_probe)
    out, errors = {"value": 0.0}, []
    # Sub-second budget: the first inter-attempt sleep crosses it (sleep
    # time counts as probe spend — fast-fail loops must not probe
    # forever just because each attempt is cheap).
    ps = {"spent_s": 0.0, "consecutive_fails": 0, "budget_s": 0.5,
          "max_consecutive_fails": 99}
    with contextlib.redirect_stdout(io.StringIO()):
        ok = bench.wait_for_device(out, errors, time.perf_counter() + 60, ps)
    assert not ok
    assert "probe spend cap" in ps["skipped"]


def test_success_resets_consecutive_fails(monkeypatch):
    calls = {"n": 0}

    def flaky_probe(timeout):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("one blip")

    monkeypatch.setattr(bench, "probe_device", flaky_probe)
    out, errors = {"value": 0.0}, []
    ps = {"spent_s": 0.0, "consecutive_fails": 0, "budget_s": 900.0,
          "max_consecutive_fails": 2}
    with contextlib.redirect_stdout(io.StringIO()):
        ok = bench.wait_for_device(out, errors, time.perf_counter() + 60, ps)
    assert ok
    # A later re-probe (tunnel flap) starts from a clean slate on BOTH
    # caps: the budget bounds unproductive probing, so a healthy-but-slow
    # tunnel's successful ~2-min probes across many variant attempts
    # never trip the dead-tunnel cap.
    assert ps["consecutive_fails"] == 0
    assert ps["spent_s"] == 0.0


def test_device_skipped_field_defaults_false():
    """device_phase initializes device_skipped=False so the field is
    ALWAYS present in the final JSON (explicit signal, not absence)."""
    src = open(bench.__file__).read()
    assert 'out["device_skipped"] = False' in src
    assert 'out["device_skipped"] = probe_state["skipped"]' in src
