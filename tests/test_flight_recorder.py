"""Time-series telemetry + flight recorder (ISSUE 10 tentpole): delta
math, bounded rings, same-seed byte-identical windows and artifacts,
trigger wiring through the breaker and the ratekeeper, and the
status/CLI surfaces (`flightrec`, `metrics --diff`)."""

import json

import pytest

from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.flow.flight_recorder import (
    FlightRecorder,
    artifact_json,
    global_flight_recorder,
    maybe_trigger,
    set_global_flight_recorder,
)
from foundationdb_tpu.flow.knobs import g_env, g_knobs
from foundationdb_tpu.flow.metrics import MetricsRegistry
from foundationdb_tpu.flow.spans import (
    SpanHub,
    global_span_hub,
    set_global_span_hub,
)
from foundationdb_tpu.flow.timeseries import (
    TimeSeriesHub,
    global_timeseries,
    set_global_timeseries,
    snapshot_delta,
)
from foundationdb_tpu.flow.trace import (
    TraceCollector,
    TraceEvent,
    global_collector,
    set_global_collector,
)

pytestmark = pytest.mark.metrics


@pytest.fixture(autouse=True)
def _fresh_globals():
    """Every test runs against its own hub/recorder/collector and leaves
    the process-globals as it found them."""
    old_hub, old_rec, old_col, old_spans = (
        global_timeseries(),
        global_flight_recorder(),
        global_collector(),
        global_span_hub(),
    )
    set_global_timeseries(TimeSeriesHub())
    set_global_flight_recorder(FlightRecorder())
    set_global_collector(TraceCollector())
    set_global_span_hub(SpanHub())
    yield
    set_global_timeseries(old_hub)
    set_global_flight_recorder(old_rec)
    set_global_collector(old_col)
    set_global_span_hub(old_spans)
    set_event_loop(None)


# ---------------------------------------------------------------------------
# delta math + ring semantics
# ---------------------------------------------------------------------------


def test_snapshot_delta_counters_histograms_gauges():
    reg = MetricsRegistry("X")
    reg.counter("c").add(3)
    reg.gauge("g").set(7)
    reg.histogram("h").add(2.0)
    s1 = reg.snapshot()
    reg.counter("c").add(5)
    reg.gauge("g").set(9)
    reg.histogram("h").add(4.0)
    reg.histogram("h").add(6.0)
    s2 = reg.snapshot()
    d = snapshot_delta(s1, s2)
    assert d["counters"] == {"c": 5}
    assert d["gauges"] == {"g": 9}  # gauges are values, not deltas
    assert d["histograms"]["h"]["count"] == 2
    assert d["histograms"]["h"]["sum"] == 10.0
    # No baseline: the delta IS the total.
    d0 = snapshot_delta(None, s2)
    assert d0["counters"] == {"c": 8}
    assert d0["histograms"]["h"]["count"] == 3


def test_hub_ring_bound_and_source_change_reset():
    hub = TimeSeriesHub(window=4)
    reg = MetricsRegistry("R")
    reg.counter("c")
    for i in range(10):
        reg.counter("c").add(1)
        hub.record("R", reg, now=float(i))
    ts = hub.series["R"]
    assert len(ts.samples) == 4  # bounded
    assert all(s["counters"]["c"] == 1 for s in ts.samples)  # deltas
    # A DIFFERENT registry under the same name resets the baseline —
    # no negative deltas against the predecessor's totals.
    reg2 = MetricsRegistry("R")
    reg2.counter("c").add(2)
    s = hub.record("R", reg2, now=99.0)
    assert s["counters"]["c"] == 2
    assert ts.resets == 1 and len(ts.samples) == 1


def test_wall_namespace_never_sampled():
    hub = TimeSeriesHub(window=4)
    reg = MetricsRegistry("W")
    reg.record_wall("disp", 0.5)
    s = hub.record("W", reg, now=1.0)
    assert "wall" not in json.dumps(s)


def test_window_json_byte_identical_for_same_inputs():
    def build():
        hub = TimeSeriesHub(window=8)
        reg = MetricsRegistry("A")
        for i in range(5):
            reg.counter("n").add(i)
            reg.histogram("h").add(float(i))
            hub.record("A", reg, now=float(i))
        return hub.window_json()

    assert build() == build()


# ---------------------------------------------------------------------------
# recorder: capture shape, cooldown, bounded ring, determinism
# ---------------------------------------------------------------------------


def test_capture_contains_window_events_and_transitions():
    hub = global_timeseries()
    reg = MetricsRegistry("A")
    reg.counter("n").add(1)
    hub.record("A", reg, now=1.0)
    TraceEvent("Incident").detail("k", 1).log(now=1.5)
    rec = global_flight_recorder()
    art = rec.capture(
        "unit", detail={"why": "test"},
        transitions=[[1, "ok", "degraded", "r"]], now=2.0,
    )
    assert art["trigger"] == "unit" and art["time"] == 2.0
    assert art["timeseries"]["A"][0]["counters"]["n"] == 1
    assert art["recent_events"][-1]["Type"] == "Incident"
    assert art["transitions"] == [[1, "ok", "degraded", "r"]]
    # Canonical bytes round-trip.
    assert json.loads(artifact_json(art)) == art


def test_trigger_cooldown_and_capture_ring_bound():
    from foundationdb_tpu.flow.eventloop import EventLoop

    set_event_loop(EventLoop(seed=1))  # cooldown needs a virtual clock
    rec = FlightRecorder(max_captures=2, window=4, cooldown=5.0)
    set_global_flight_recorder(rec)
    assert maybe_trigger("kind_a") is not None
    assert maybe_trigger("kind_a") is None  # inside cooldown (vt 0.0)
    assert maybe_trigger("kind_b") is not None  # per-kind cooldowns
    assert rec.trigger_counts == {"kind_a": 2, "kind_b": 1}
    # Ring bound: explicit captures bypass the cooldown and rotate.
    for i in range(5):
        rec.capture(f"c{i}")
    assert len(rec.captures) == 2
    assert [c["trigger"] for c in rec.captures] == ["c3", "c4"]
    assert rec.capture_seq == 7
    sec = rec.status_section()
    assert sec["captures"] == 2 and sec["last_capture"]["trigger"] == "c4"
    # A transitions THUNK is resolved only for admitted captures.
    resolved = []
    art = rec.trigger("kind_c", transitions=lambda: resolved.append(1) or [[1]])
    assert art["transitions"] == [[1]] and resolved == [1]
    assert rec.trigger("kind_c", transitions=lambda: resolved.append(1)) is None
    assert resolved == [1]  # suppressed trigger never built the copy
    # Distinct SOURCES are distinct incidents, not a flap: each gets its
    # own cooldown key (two breakers opening simultaneously must both
    # be captured).
    assert rec.trigger("kind_d", source=1) is not None
    assert rec.trigger("kind_d", source=2) is not None
    assert rec.trigger("kind_d", source=1) is None


def test_trigger_cooldown_clock_edges():
    from foundationdb_tpu.flow.eventloop import EventLoop

    rec = FlightRecorder(max_captures=8, window=4, cooldown=5.0)
    set_global_flight_recorder(rec)
    # No loop set: no meaningful clock — triggers are never suppressed
    # (real mode must not swallow the second incident forever).
    assert maybe_trigger("k") is not None
    assert maybe_trigger("k") is not None
    # Virtual time RESTARTS (a new run in the same process): the old
    # run's stamp must not suppress the new run's first incident.
    loop = EventLoop(seed=1)
    set_event_loop(loop)
    loop._now = 300.0
    assert maybe_trigger("k") is not None
    set_event_loop(EventLoop(seed=2))  # fresh run, vt back to 0.0
    assert maybe_trigger("k") is not None  # backwards stamp => capture
    assert maybe_trigger("k") is None  # same-run cooldown still holds


def test_flightrec_env_kill_switch(monkeypatch):
    monkeypatch.setenv("FDB_TPU_FLIGHTREC", "0")
    assert maybe_trigger("anything") is None
    assert global_flight_recorder().captures.maxlen == 16
    assert len(global_flight_recorder().captures) == 0


def test_env_flags_registered():
    """ENV001 satellite discipline: the ISSUE 10 flag family is declared
    in g_env with defaults and help strings."""
    decl = g_env.declared()
    for name in (
        "FDB_TPU_TIMESERIES", "FDB_TPU_TIMESERIES_INTERVAL",
        "FDB_TPU_TIMESERIES_WINDOW", "FDB_TPU_TRACE_RECENT",
        "FDB_TPU_FLIGHTREC", "FDB_TPU_FLIGHTREC_CAPTURES",
        "FDB_TPU_FLIGHTREC_COOLDOWN", "FDB_TPU_FLIGHTREC_WINDOW",
        "FDB_TPU_PROGRAM_COSTS",
    ):
        _default, help_ = decl[name]
        assert help_ != "", name


# ---------------------------------------------------------------------------
# trigger wiring: breaker open captures the incident window
# ---------------------------------------------------------------------------


def _write_txns(i, n=1):
    from foundationdb_tpu.conflict.types import TransactionConflictInfo as T

    return [
        T(read_snapshot=0,
          write_ranges=[(b"%06d" % (100 * i + 2 * j),
                         b"%06d" % (100 * i + 2 * j + 1))])
        for j in range(n)
    ]


def test_breaker_open_triggers_capture_with_transition():
    """The acceptance shape: a breaker open yields a capture whose window
    contains the triggering transition, the surrounding time-series
    deltas, and the recent trace events."""
    from foundationdb_tpu.conflict.api import ConflictSet
    from foundationdb_tpu.conflict.device_faults import DeviceFaultInjector

    inj = DeviceFaultInjector()
    cs = ConflictSet(backend="jax", fault_injector=inj)
    hub = global_timeseries()
    now = 100
    for i in range(3):
        cs._detect(_write_txns(i), now, 0)
        hub.record("JaxConflict.unit", cs._jax.metrics, now=float(now))
        now += 10
    inj.begin_outage("dispatch")
    for i in range(3, 7):
        cs._detect(_write_txns(i), now, 0)
        now += 10
    inj.end_outage("dispatch")
    rec = global_flight_recorder()
    opens = [c for c in rec.captures if c["trigger"] == "breaker_open"]
    assert len(opens) == 1
    cap = opens[0]
    # The triggering transition is IN the artifact...
    assert cap["transitions"][-1][1:3] == ["ok", "degraded"]
    assert cap["detail"]["reason"].startswith("threshold:")
    # ...with the surrounding time-series deltas...
    samples = cap["timeseries"]["JaxConflict.unit"]
    assert samples and samples[0]["counters"]["batches"] >= 1
    # ...and the recent trace events, including the state change itself.
    assert any(
        e["Type"] == "DeviceBackendStateChange"
        for e in cap["recent_events"]
    )
    # A probe failure re-opening the circuit is NOT a fresh open trigger.
    assert rec.trigger_counts.get("breaker_open", 0) == 1


def test_breaker_open_artifacts_byte_identical_across_runs():
    """Same-seed determinism at the unit level: two identical runs of
    the scripted-outage scenario produce byte-identical artifacts."""

    def run():
        from foundationdb_tpu.conflict.api import ConflictSet
        from foundationdb_tpu.conflict.device_faults import (
            DeviceFaultInjector,
        )
        from foundationdb_tpu.flow.eventloop import EventLoop

        # A loop must be set so trace events stamp VIRTUAL time (the
        # wall fallback is for real-mode tools only; under simulation a
        # loop always exists).
        set_event_loop(EventLoop(seed=1))
        set_global_timeseries(TimeSeriesHub())
        set_global_flight_recorder(FlightRecorder())
        set_global_collector(TraceCollector())
        set_global_span_hub(SpanHub())  # captures embed the span window
        inj = DeviceFaultInjector()
        inj.script("dispatch", at=4, persist=4)
        cs = ConflictSet(backend="jax", fault_injector=inj)
        now = 100
        for i in range(8):
            cs._detect(_write_txns(i), now, 0)
            global_timeseries().record(
                "JaxConflict.unit", cs._jax.metrics, now=float(now)
            )
            now += 10
        return [
            artifact_json(c) for c in global_flight_recorder().captures
        ]

    a, b = run(), run()
    assert a and a == b


# ---------------------------------------------------------------------------
# cluster surfaces: sampler actors, status section, CLI commands
# ---------------------------------------------------------------------------


def _drive(c, db, cli, line):
    return c.loop.run_until(
        db.process.spawn(cli.run_command(line)), timeout_vt=60.0
    )


def test_cluster_samplers_status_and_cli():
    from foundationdb_tpu.server import SimCluster
    from foundationdb_tpu.server.status import cluster_status
    from foundationdb_tpu.tools.cli import CliProcessor

    saved = g_knobs.client.latency_sample_rate
    g_knobs.client.latency_sample_rate = 1.0
    try:
        c = SimCluster(seed=5150)
        db = c.database("fr")
        cli = CliProcessor(c, db)

        async def load():
            for i in range(6):
                tr = db.create_transaction()
                tr.set(b"fr%02d" % i, b"v")
                await tr.commit()
            await c.loop.delay(3.0)  # > 2 sampler intervals

        c.run_until(db.process.spawn(load(), "load"), timeout_vt=1000.0)
        # Resolver + proxy sampler actors populated the hub.
        hub = global_timeseries()
        assert "Resolver.resolver" in hub.series
        assert any(n.startswith("Proxy") for n in hub.series)
        total_committed = sum(
            s["counters"].get("committed", 0)
            for s in hub.series["Resolver.resolver"].samples
        )
        assert total_committed >= 6  # deltas sum back to the total

        # Status carries the recorder inventory.
        sec = cluster_status(c)["cluster"]["flight_recorder"]
        assert sec["captures"] == 0 and sec["last_capture"] is None

        # cli flightrec: empty inventory, then a capture shows up.
        assert _drive(c, db, cli, "flightrec")[0].startswith(
            "flight recorder: no captures"
        )
        global_flight_recorder().capture(
            "manual", detail={"via": "test"}, now=c.loop.now()
        )
        text = "\n".join(_drive(c, db, cli, "flightrec"))
        assert "1 capture(s)" in text and "manual" in text
        doc = json.loads(
            "\n".join(_drive(c, db, cli, "flightrec --format=json"))
        )
        assert doc["status"]["captures"] == 1
        assert doc["captures"][0]["trigger"] == "manual"
        assert doc["captures"][0]["timeseries"]["Resolver.resolver"]

        # metrics --diff: second call shows only the in-between window.
        _drive(c, db, cli, "metrics")

        async def one_more():
            tr = db.create_transaction()
            tr.set(b"frx", b"v")
            await tr.commit()

        c.run_until(db.process.spawn(one_more(), "m"), timeout_vt=500.0)
        diff = json.loads(
            "\n".join(_drive(c, db, cli, "metrics --diff --format=json"))
        )
        assert diff["resolvers"]["resolver"]["counters"]["committed"] == 1
        # Non-registry keys pass through the diff view unchanged (the
        # tpu section's backend_state/breaker/mirror blocks etc.).
        assert diff["resolvers"]["resolver"]["name"] == "Resolver.resolver"
        text = "\n".join(_drive(c, db, cli, "metrics --diff"))
        assert text.startswith("(deltas since previous metrics command)")
    finally:
        g_knobs.client.latency_sample_rate = saved


def test_timeseries_disabled_by_env(monkeypatch):
    from foundationdb_tpu.server import SimCluster

    monkeypatch.setenv("FDB_TPU_TIMESERIES", "0")
    c = SimCluster(seed=5151)
    db = c.database("off")

    async def load():
        tr = db.create_transaction()
        tr.set(b"k", b"v")
        await tr.commit()
        await c.loop.delay(3.0)

    c.run_until(db.process.spawn(load(), "load"), timeout_vt=1000.0)
    assert global_timeseries().series == {}
