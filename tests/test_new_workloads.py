"""Round-5 workload additions, chaos-composed.

Ref: fdbserver/workloads/AtomicOps.actor.cpp, VersionStamp.actor.cpp,
Serializability.actor.cpp, ConfigureDatabase.actor.cpp,
RemoveServersSafely.actor.cpp, TargetedKill.actor.cpp — each run plain and
under the clogging/attrition chaos stack with the trailing consistency gate
(tester.actor.cpp:819).
"""

import pytest

from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.flow.knobs import g_knobs
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.workloads import (
    AtomicOpsWorkload,
    ConfigureDatabaseWorkload,
    ConsistencyChecker,
    CycleWorkload,
    RandomCloggingWorkload,
    AttritionWorkload,
    RemoveServersSafelyWorkload,
    SerializabilityWorkload,
    TargetedKillWorkload,
    VersionStampWorkload,
    run_workloads,
)


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def test_atomic_ops_versionstamp_serializability_plain():
    c = SimCluster(seed=510, n_proxies=2, n_storages=2)
    run_workloads(
        c,
        [
            AtomicOpsWorkload(groups=2, actors=3, ops=8),
            VersionStampWorkload(actors=3, ops=6),
            SerializabilityWorkload(registers=6, actors=3, ops=8),
        ],
        timeout_vt=20000.0,
    )


@pytest.mark.parametrize("seed", [520, 521, 522])
def test_atomic_ops_versionstamp_serializability_chaos(seed):
    """The invariant trio under swizzled clogging: retries, stale location
    caches, and recoveries must not break ledger sums, stamp ordering, or
    the serial replay."""
    from foundationdb_tpu.server.dynamic_cluster import DynamicCluster

    c = DynamicCluster(seed=seed, n_workers=7, n_proxies=2, n_storages=2,
                       n_tlogs=2)
    run_workloads(
        c,
        [
            AtomicOpsWorkload(groups=2, actors=2, ops=6),
            VersionStampWorkload(actors=2, ops=5),
            SerializabilityWorkload(registers=5, actors=2, ops=6),
            RandomCloggingWorkload(duration=2.0),
            ConsistencyChecker(require_comparisons=True),
        ],
        timeout_vt=30000.0,
        quiet=True,
    )


def test_serializability_detects_lost_update():
    """The replay check itself must catch a violation: forge a record
    claiming a read that serial order contradicts."""
    c = SimCluster(seed=530, n_proxies=1, n_storages=1)
    wl = SerializabilityWorkload(registers=4, actors=2, ops=6)
    run_workloads(c, [wl], timeout_vt=20000.0)
    # Sabotage: rewrite one record's reads to a value that was never
    # current at its read version.
    assert wl.records
    rv, cv, tn, ident, reads, writes = wl.records[0]
    forged = dict(reads)
    forged[next(iter(forged))] = b"NEVER_WRITTEN"
    wl.records[0] = (rv, cv, tn, ident, forged, writes)
    db = c.database("forge")
    ok = c.run_until(
        db.process.spawn(wl.check(db, c)), timeout_vt=5000.0
    )
    assert ok is False


@pytest.mark.parametrize("seed", [540, 541])
def test_configure_database_under_chaos(seed):
    """Live proxy/resolver count churn + clogging while Cycle runs; the
    final configuration must match the last change and the ring must
    survive every regeneration (ConfigureDatabase.actor.cpp)."""
    from foundationdb_tpu.server.dynamic_cluster import DynamicCluster

    c = DynamicCluster(seed=seed, n_workers=7, n_proxies=1, n_storages=1)
    run_workloads(
        c,
        [
            ConfigureDatabaseWorkload(changes=3, delay_between=0.6),
            CycleWorkload(nodes=5, ops=12, actors=2),
            RandomCloggingWorkload(duration=1.5),
        ],
        timeout_vt=30000.0,
    )


def test_remove_servers_safely(request):
    """Exclude -> DD drains -> kill: zero data loss, full-width teams on
    the survivors (RemoveServersSafely.actor.cpp)."""
    saved = g_knobs.server.dd_tracker_interval
    g_knobs.server.dd_tracker_interval = 0.5
    request.addfinalizer(
        lambda: setattr(g_knobs.server, "dd_tracker_interval", saved)
    )

    c = SimCluster(seed=550, n_storages=4, n_tlogs=2)
    db = c.database()

    async def fill(tr):
        for i in range(40):
            tr.set(b"rs%03d" % i, b"v%d" % i)

    c.run_all([(db, db.run(fill))])
    dd = c.data_distributor()

    async def place():
        await dd.register_storages(dd.storages)
        await dd.seed(["ss0"])
        await dd.split(b"rs020")
        await dd.split(b"\xff")
        await dd.move(b"", ["ss0", "ss1"])
        await dd.move(b"rs020", ["ss1", "ss2"])

    c.run_until(db.process.spawn(place()), timeout_vt=500.0)
    role = c.dd_role(dd)

    victim_proc = c.storages[1].process
    wl = RemoveServersSafelyWorkload(
        victim="ss1", dd=dd, kill_process=victim_proc
    )
    run_workloads(
        c,
        [wl, CycleWorkload(nodes=5, ops=10, actors=2)],
        timeout_vt=30000.0,
    )
    assert wl.drained and not victim_proc.alive

    # Everything is still readable through normal routing.
    out = {}

    async def read(tr):
        out["rows"] = await tr.get_range(b"rs", b"rt")

    c.run_all([(db, db.run(read))], timeout_vt=2000.0)
    assert len(out["rows"]) == 40
    role.stop()


def test_storefront_unreadable_lock_workloads():
    """Round-5 batch two: inventory accounting, unreadable stamp ranges,
    and a lock/unlock cycle racing Cycle traffic (Storefront.actor.cpp,
    Unreadable.actor.cpp, LockDatabase.actor.cpp)."""
    from foundationdb_tpu.workloads import (
        LockDatabaseWorkload,
        StorefrontWorkload,
        UnreadableWorkload,
    )

    c = SimCluster(seed=570, n_proxies=2, n_storages=2)
    wl = LockDatabaseWorkload(at=0.6, hold=0.8)
    run_workloads(
        c,
        [
            StorefrontWorkload(items=4, actors=3, purchases=8),
            UnreadableWorkload(rounds=6),
            CycleWorkload(nodes=5, ops=12, actors=2),
            wl,
        ],
        timeout_vt=30000.0,
    )
    assert wl.checked_while_locked


@pytest.mark.parametrize("seed", [575, 576])
def test_storefront_under_chaos(seed):
    from foundationdb_tpu.workloads import StorefrontWorkload

    c = SimCluster(seed=seed, n_proxies=2, n_tlogs=2)
    run_workloads(
        c,
        [
            StorefrontWorkload(items=3, actors=2, purchases=6),
            RandomCloggingWorkload(duration=2.0),
        ],
        timeout_vt=30000.0,
    )


@pytest.mark.parametrize("role", ["storage0", "tlog0", "proxy0"])
def test_targeted_kill_each_role(role):
    """Killing each named role mid-load exercises a distinct recovery path;
    the ring and a fresh probe must survive all of them
    (TargetedKill.actor.cpp)."""
    from foundationdb_tpu.server.dynamic_cluster import DynamicCluster

    seed = 560 + ["storage0", "tlog0", "proxy0"].index(role)
    c = DynamicCluster(seed=seed, n_workers=7, n_tlogs=2, n_storages=2)
    run_workloads(
        c,
        [
            TargetedKillWorkload(role=role, at=0.8, reboot=True),
            CycleWorkload(nodes=5, ops=12, actors=2),
        ],
        timeout_vt=30000.0,
    )


@pytest.mark.parametrize("seed", [580, 581])
def test_backup_correctness_under_chaos(seed):
    """Continuous backup tailing through clogging + live traffic; the
    restored image must equal the live database byte for byte
    (BackupAndRestoreCorrectness.actor.cpp)."""
    from foundationdb_tpu.workloads import BackupCorrectnessWorkload

    c = SimCluster(seed=seed, n_proxies=2, n_tlogs=1)
    wl = BackupCorrectnessWorkload(duration=1.5)
    run_workloads(
        c,
        [
            wl,
            CycleWorkload(nodes=5, ops=12, actors=2),
            RandomCloggingWorkload(duration=1.5),
        ],
        timeout_vt=30000.0,
        quiet=True,
    )
    assert wl.restored_rows > 0
