"""Round-5 workload additions, chaos-composed.

Ref: fdbserver/workloads/AtomicOps.actor.cpp, VersionStamp.actor.cpp,
Serializability.actor.cpp, ConfigureDatabase.actor.cpp,
RemoveServersSafely.actor.cpp, TargetedKill.actor.cpp — each run plain and
under the clogging/attrition chaos stack with the trailing consistency gate
(tester.actor.cpp:819).
"""

import pytest

from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.flow.knobs import g_knobs
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.workloads import (
    AtomicOpsWorkload,
    ConflictRangeWorkload,
    InventoryWorkload,
    QueuePushWorkload,
    ConfigureDatabaseWorkload,
    ConsistencyChecker,
    CycleWorkload,
    RandomCloggingWorkload,
    AttritionWorkload,
    RemoveServersSafelyWorkload,
    SerializabilityWorkload,
    TargetedKillWorkload,
    VersionStampWorkload,
    run_workloads,
)


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def test_atomic_ops_versionstamp_serializability_plain():
    c = SimCluster(seed=510, n_proxies=2, n_storages=2)
    run_workloads(
        c,
        [
            AtomicOpsWorkload(groups=2, actors=3, ops=8),
            VersionStampWorkload(actors=3, ops=6),
            SerializabilityWorkload(registers=6, actors=3, ops=8),
        ],
        timeout_vt=20000.0,
    )


@pytest.mark.parametrize("seed", [520, 521, 522])
def test_atomic_ops_versionstamp_serializability_chaos(seed):
    """The invariant trio under swizzled clogging: retries, stale location
    caches, and recoveries must not break ledger sums, stamp ordering, or
    the serial replay."""
    from foundationdb_tpu.server.dynamic_cluster import DynamicCluster

    c = DynamicCluster(seed=seed, n_workers=7, n_proxies=2, n_storages=2,
                       n_tlogs=2)
    run_workloads(
        c,
        [
            AtomicOpsWorkload(groups=2, actors=2, ops=6),
            VersionStampWorkload(actors=2, ops=5),
            SerializabilityWorkload(registers=5, actors=2, ops=6),
            RandomCloggingWorkload(duration=2.0),
            ConsistencyChecker(require_comparisons=True),
        ],
        timeout_vt=30000.0,
        quiet=True,
    )


def test_serializability_detects_lost_update():
    """The replay check itself must catch a violation: forge a record
    claiming a read that serial order contradicts."""
    c = SimCluster(seed=530, n_proxies=1, n_storages=1)
    wl = SerializabilityWorkload(registers=4, actors=2, ops=6)
    run_workloads(c, [wl], timeout_vt=20000.0)
    # Sabotage: rewrite one record's reads to a value that was never
    # current at its read version.
    assert wl.records
    rv, cv, tn, ident, reads, writes = wl.records[0]
    forged = dict(reads)
    forged[next(iter(forged))] = b"NEVER_WRITTEN"
    wl.records[0] = (rv, cv, tn, ident, forged, writes)
    db = c.database("forge")
    ok = c.run_until(
        db.process.spawn(wl.check(db, c)), timeout_vt=5000.0
    )
    assert ok is False


@pytest.mark.parametrize("seed", [540, 541])
def test_configure_database_under_chaos(seed):
    """Live proxy/resolver count churn + clogging while Cycle runs; the
    final configuration must match the last change and the ring must
    survive every regeneration (ConfigureDatabase.actor.cpp)."""
    from foundationdb_tpu.server.dynamic_cluster import DynamicCluster

    c = DynamicCluster(seed=seed, n_workers=7, n_proxies=1, n_storages=1)
    run_workloads(
        c,
        [
            ConfigureDatabaseWorkload(changes=3, delay_between=0.6),
            CycleWorkload(nodes=5, ops=12, actors=2),
            RandomCloggingWorkload(duration=1.5),
        ],
        timeout_vt=30000.0,
    )


def test_remove_servers_safely(request):
    """Exclude -> DD drains -> kill: zero data loss, full-width teams on
    the survivors (RemoveServersSafely.actor.cpp)."""
    saved = g_knobs.server.dd_tracker_interval
    g_knobs.server.dd_tracker_interval = 0.5
    request.addfinalizer(
        lambda: setattr(g_knobs.server, "dd_tracker_interval", saved)
    )

    c = SimCluster(seed=550, n_storages=4, n_tlogs=2)
    db = c.database()

    async def fill(tr):
        for i in range(40):
            tr.set(b"rs%03d" % i, b"v%d" % i)

    c.run_all([(db, db.run(fill))])
    dd = c.data_distributor()

    async def place():
        await dd.register_storages(dd.storages)
        await dd.seed(["ss0"])
        await dd.split(b"rs020")
        await dd.split(b"\xff")
        await dd.move(b"", ["ss0", "ss1"])
        await dd.move(b"rs020", ["ss1", "ss2"])

    c.run_until(db.process.spawn(place()), timeout_vt=500.0)
    role = c.dd_role(dd)

    victim_proc = c.storages[1].process
    wl = RemoveServersSafelyWorkload(
        victim="ss1", dd=dd, kill_process=victim_proc
    )
    run_workloads(
        c,
        [wl, CycleWorkload(nodes=5, ops=10, actors=2)],
        timeout_vt=30000.0,
    )
    assert wl.drained and not victim_proc.alive

    # Everything is still readable through normal routing.
    out = {}

    async def read(tr):
        out["rows"] = await tr.get_range(b"rs", b"rt")

    c.run_all([(db, db.run(read))], timeout_vt=2000.0)
    assert len(out["rows"]) == 40
    role.stop()


def test_storefront_unreadable_lock_workloads():
    """Round-5 batch two: inventory accounting, unreadable stamp ranges,
    and a lock/unlock cycle racing Cycle traffic (Storefront.actor.cpp,
    Unreadable.actor.cpp, LockDatabase.actor.cpp)."""
    from foundationdb_tpu.workloads import (
        LockDatabaseWorkload,
        StorefrontWorkload,
        UnreadableWorkload,
    )

    c = SimCluster(seed=570, n_proxies=2, n_storages=2)
    wl = LockDatabaseWorkload(at=0.6, hold=0.8)
    run_workloads(
        c,
        [
            StorefrontWorkload(items=4, actors=3, purchases=8),
            UnreadableWorkload(rounds=6),
            CycleWorkload(nodes=5, ops=12, actors=2),
            wl,
        ],
        timeout_vt=30000.0,
    )
    assert wl.checked_while_locked


@pytest.mark.parametrize("seed", [575, 576])
def test_storefront_under_chaos(seed):
    from foundationdb_tpu.workloads import StorefrontWorkload

    c = SimCluster(seed=seed, n_proxies=2, n_tlogs=2)
    run_workloads(
        c,
        [
            StorefrontWorkload(items=3, actors=2, purchases=6),
            RandomCloggingWorkload(duration=2.0),
        ],
        timeout_vt=30000.0,
    )


@pytest.mark.parametrize("role", ["storage0", "tlog0", "proxy0"])
def test_targeted_kill_each_role(role):
    """Killing each named role mid-load exercises a distinct recovery path;
    the ring and a fresh probe must survive all of them
    (TargetedKill.actor.cpp)."""
    from foundationdb_tpu.server.dynamic_cluster import DynamicCluster

    seed = 560 + ["storage0", "tlog0", "proxy0"].index(role)
    c = DynamicCluster(seed=seed, n_workers=7, n_tlogs=2, n_storages=2)
    run_workloads(
        c,
        [
            TargetedKillWorkload(role=role, at=0.8, reboot=True),
            CycleWorkload(nodes=5, ops=12, actors=2),
        ],
        timeout_vt=30000.0,
    )


@pytest.mark.parametrize("seed", [580, 581])
def test_backup_correctness_under_chaos(seed):
    """Continuous backup tailing through clogging + live traffic; the
    restored image must equal the live database byte for byte
    (BackupAndRestoreCorrectness.actor.cpp)."""
    from foundationdb_tpu.workloads import BackupCorrectnessWorkload

    c = SimCluster(seed=seed, n_proxies=2, n_tlogs=1)
    wl = BackupCorrectnessWorkload(duration=1.5)
    run_workloads(
        c,
        [
            wl,
            CycleWorkload(nodes=5, ops=12, actors=2),
            RandomCloggingWorkload(duration=1.5),
        ],
        timeout_vt=30000.0,
        quiet=True,
    )
    assert wl.restored_rows > 0


def test_conflict_range_exactness():
    """Conflicts occur exactly when the mutation intersects the OBSERVED
    read extent — both spurious and missed conflicts fail (ref:
    workloads/ConflictRange.actor.cpp)."""
    c = SimCluster(seed=540, n_proxies=2, n_storages=2)
    wl = ConflictRangeWorkload(iterations=40)
    run_workloads(c, [wl], timeout_vt=30000.0)
    assert wl.conflicts > 0 and wl.checked > wl.conflicts


def test_inventory_and_queue_push_plain():
    c = SimCluster(seed=541, n_proxies=2, n_storages=2)
    run_workloads(
        c,
        [
            InventoryWorkload(products=6, actors=3, moves=10),
            QueuePushWorkload(actors=4, pushes=6),
        ],
        timeout_vt=30000.0,
    )


@pytest.mark.parametrize("seed", [545, 546])
def test_inventory_queue_push_chaos(seed):
    """Conservation + dense-queue invariants through clogging/attrition,
    with the trailing consistency gate."""
    from foundationdb_tpu.server.dynamic_cluster import DynamicCluster

    c = DynamicCluster(seed=seed, n_workers=7, n_proxies=2, n_storages=2,
                       n_tlogs=2)
    run_workloads(
        c,
        [
            InventoryWorkload(products=5, actors=2, moves=8),
            QueuePushWorkload(actors=3, pushes=5),
            RandomCloggingWorkload(duration=6.0),
            AttritionWorkload(kills=1),
            ConsistencyChecker(),
        ],
        timeout_vt=60000.0,
        quiet=True,
    )


def test_time_keeper_correctness():
    """The CC's timeKeeper map: monotone samples, and timestamp->version
    mapping never points past versions observed at that time (ref:
    workloads/TimeKeeperCorrectness.actor.cpp)."""
    from foundationdb_tpu.server.dynamic_cluster import DynamicCluster
    from foundationdb_tpu.workloads import TimeKeeperWorkload

    c = DynamicCluster(seed=550, n_workers=7, n_proxies=2, n_storages=2)
    run_workloads(c, [TimeKeeperWorkload(duration=12.0)], timeout_vt=30000.0)


def test_restore_to_timestamp_uses_time_keeper():
    """`fdbbackup restore --timestamp` semantics: map a wall-clock time
    through the timeKeeper samples to a version, then PITR-restore at it
    (ref: backup.actor.cpp:1828 timeKeeperVersionFromDatetime)."""
    from foundationdb_tpu.client.management import version_from_timestamp
    from foundationdb_tpu.flow.error import FdbError

    from foundationdb_tpu.server.dynamic_cluster import DynamicCluster

    old_delay = g_knobs.server.time_keeper_delay
    g_knobs.server.time_keeper_delay = 0.5
    c = DynamicCluster(seed=551, n_workers=7, n_proxies=2, n_storages=2)
    db = c.database()
    marks = {}

    async def drive():
        loop = c.loop

        async def w1(tr):
            tr.set(b"tk/a", b"early")

        await db.run(w1)
        # Let the timekeeper lay down samples around the mark.  The MVCC
        # window is ~5 virtual seconds (5M versions at 1M/s), so the whole
        # mark->read span must stay well inside it.
        await loop.delay(2.0)
        marks["t_mid"] = loop.now()
        await loop.delay(1.0)

        async def w2(tr):
            tr.set(b"tk/a", b"late")
            tr.set(b"tk/b", b"new")

        await db.run(w2)
        await loop.delay(0.5)
        v_mid = await version_from_timestamp(db, marks["t_mid"])
        marks["v_mid"] = v_mid
        # A read AT the mapped version sees the early state only.
        tr = db.create_transaction()
        tr.set_read_version(v_mid)
        rows = await tr.get_range(b"tk/", b"tk0")
        marks["rows_at_mid"] = rows
        # Before the first sample: loudly unmappable.
        try:
            await version_from_timestamp(db, -1.0)
            marks["early_raises"] = False
        except FdbError as e:
            marks["early_raises"] = e.name == "restore_error"

    try:
        c.run_until(db.process.spawn(drive(), "tk"), timeout_vt=60000.0)
    finally:
        g_knobs.server.time_keeper_delay = old_delay
    assert marks["rows_at_mid"] == [(b"tk/a", b"early")]
    assert marks["early_raises"] is True


def test_ryow_watchandwait_bulkload_plain():
    """Single-txn ordered RYW semantics vs model, mass watches, batched
    bulk load (ref: RyowCorrectness / WatchAndWait / BulkLoad)."""
    from foundationdb_tpu.workloads import (
        BulkLoadWorkload,
        RyowCorrectnessWorkload,
        WatchAndWaitWorkload,
    )

    c = SimCluster(seed=560, n_proxies=2, n_storages=2)
    run_workloads(
        c,
        [
            RyowCorrectnessWorkload(txns=8, ops_per_txn=20),
            WatchAndWaitWorkload(watches=12),
            BulkLoadWorkload(rows=200, batch=40),
        ],
        timeout_vt=60000.0,
    )


@pytest.mark.parametrize("seed", [565, 566])
def test_status_lowlatency_under_chaos(seed):
    """Status schema holds on every poll and interactive latency stays
    bounded while clogging churns (ref: StatusWorkload / LowLatency)."""
    from foundationdb_tpu.server.dynamic_cluster import DynamicCluster
    from foundationdb_tpu.workloads import (
        BulkLoadWorkload,
        LowLatencyWorkload,
        StatusWorkload,
    )

    c = DynamicCluster(seed=seed, n_workers=7, n_proxies=2, n_storages=2)
    run_workloads(
        c,
        [
            LowLatencyWorkload(ops=30),
            StatusWorkload(duration=6.0),
            BulkLoadWorkload(rows=150, batch=30),
            RandomCloggingWorkload(duration=4.0),
            ConsistencyChecker(),
        ],
        timeout_vt=60000.0,
        quiet=True,
    )


@pytest.mark.parametrize("seed", [570, 571])
def test_ryow_under_chaos(seed):
    """The ordered-semantics model must hold through retries and
    recoveries (unknown results disambiguated by per-txn markers)."""
    from foundationdb_tpu.server.dynamic_cluster import DynamicCluster
    from foundationdb_tpu.workloads import RyowCorrectnessWorkload

    c = DynamicCluster(seed=seed, n_workers=7, n_proxies=2, n_storages=2,
                       n_tlogs=2)
    run_workloads(
        c,
        [
            RyowCorrectnessWorkload(txns=6, ops_per_txn=15),
            RandomCloggingWorkload(duration=3.0),
            AttritionWorkload(kills=1),
            ConsistencyChecker(),
        ],
        timeout_vt=60000.0,
        quiet=True,
    )


@pytest.mark.slow  # tier-1 headroom (ISSUE 4): profiler soak
def test_slowtask_metriclogging_plain():
    """Aux-subsystem workloads: the slow-task profiler catches a
    deliberate reactor hog; TDMetric series flush into \\xff/metrics and
    read back with the multi-resolution contract intact (ref:
    SlowTaskWorkload / MetricLogging workloads)."""
    from foundationdb_tpu.workloads import (
        MetricLoggingWorkload,
        SlowTaskWorkload,
    )

    c = SimCluster(seed=580, n_proxies=2, n_storages=2)
    run_workloads(
        c,
        [SlowTaskWorkload(), MetricLoggingWorkload(flushes=5)],
        timeout_vt=60000.0,
    )


def test_dd_metrics_through_status():
    """DD split/move activity driven by a hot range is visible through
    the status document (ref: DDMetrics workload)."""
    from foundationdb_tpu.server.dynamic_cluster import DynamicCluster
    from foundationdb_tpu.workloads import DDMetricsWorkload

    c = DynamicCluster(seed=585, n_workers=7, n_proxies=2, n_storages=2)
    run_workloads(c, [DDMetricsWorkload()], timeout_vt=60000.0)


def test_commitbug_fastwatches_backgroundselectors_plain():
    """Commit causality/exactly-once probes, prompt watch fires, and
    churn-proof selector resolution (ref: CommitBugCheck /
    FastTriggeredWatches / BackgroundSelectors workloads)."""
    from foundationdb_tpu.workloads import (
        BackgroundSelectorsWorkload,
        CommitBugWorkload,
        FastTriggeredWatchesWorkload,
    )

    c = SimCluster(seed=590, n_proxies=2, n_storages=2)
    run_workloads(
        c,
        [
            CommitBugWorkload(iterations=20),
            FastTriggeredWatchesWorkload(rounds=6),
            BackgroundSelectorsWorkload(probes=15),
        ],
        timeout_vt=60000.0,
    )


@pytest.mark.parametrize("seed", [595, 596])
def test_commit_bug_under_chaos(seed):
    """Exactly-once + own-commit visibility must hold through clogging
    and attrition (the original bugs were recovery-window races)."""
    from foundationdb_tpu.server.dynamic_cluster import DynamicCluster
    from foundationdb_tpu.workloads import CommitBugWorkload

    c = DynamicCluster(seed=seed, n_workers=7, n_proxies=2, n_storages=2,
                       n_tlogs=2)
    run_workloads(
        c,
        [
            CommitBugWorkload(iterations=12),
            RandomCloggingWorkload(duration=3.0),
            AttritionWorkload(kills=1),
            ConsistencyChecker(),
        ],
        timeout_vt=60000.0,
        quiet=True,
    )


def test_dd_balance_converges():
    """Shard counts converge within tolerance across storages under
    sim-scaled thresholds (ref: DDBalance workload).  Knob overrides are
    owned HERE with try/finally so an abandoned run cannot leak them."""
    from foundationdb_tpu.server.dynamic_cluster import DynamicCluster
    from foundationdb_tpu.workloads import DDBalanceWorkload

    old = (g_knobs.server.dd_shard_max_bytes,
           g_knobs.server.dd_shard_min_bytes)
    g_knobs.server.dd_shard_max_bytes = 2500
    g_knobs.server.dd_shard_min_bytes = 0
    try:
        c = DynamicCluster(seed=598, n_workers=8, n_proxies=2,
                           n_storages=3)
        run_workloads(c, [DDBalanceWorkload()], timeout_vt=90000.0)
    finally:
        (g_knobs.server.dd_shard_max_bytes,
         g_knobs.server.dd_shard_min_bytes) = old


def test_atomic_restore_on_live_cluster():
    """atomicRestore: lock -> lock-aware restore -> unlock; observers see
    pre- or post-restore state only (torn mixes impossible), restored
    range byte-exact, traffic resumes (ref: AtomicRestore workload)."""
    from foundationdb_tpu.workloads import AtomicRestoreWorkload

    c = SimCluster(seed=610, n_proxies=2, n_storages=2)
    wl = AtomicRestoreWorkload()
    run_workloads(c, [wl], timeout_vt=60000.0)
    assert wl.locked_seen > 0, "observer never hit the lock window"
    assert getattr(wl, "observed_scans", 0) > 0, (
        "observer never read a non-empty range — torn detection vacuous"
    )


@pytest.mark.parametrize("seed", [615, 616])
def test_index_scan_through_shard_moves(seed):
    """Paged scans stay byte-exact while RandomMoveKeys churns the shard
    layout under them (ref: IndexScan workload + shard-move chaos)."""
    from foundationdb_tpu.workloads import (
        IndexScanWorkload,
        RandomMoveKeysWorkload,
    )

    c = SimCluster(seed=seed, n_proxies=2, n_storages=3)
    run_workloads(
        c,
        [
            IndexScanWorkload(rows=100, scans=8),
            RandomMoveKeysWorkload(moves=6),
            ConsistencyChecker(),
        ],
        timeout_vt=90000.0,
        quiet=True,
    )


def test_perf_workloads_measure_and_publish():
    """Throughput / WriteBandwidth / StreamingRead / Ping measure
    virtual-time rates, gate sanity bounds, and publish into
    \\xff/metrics readable back through ordinary transactions (ref: the
    reference's perf corpus reporting via getMetrics)."""
    from foundationdb_tpu.workloads import (
        PingWorkload,
        StreamingReadWorkload,
        ThroughputWorkload,
        WriteBandwidthWorkload,
    )

    c = SimCluster(seed=620, n_proxies=2, n_storages=2)
    run_workloads(
        c,
        [
            ThroughputWorkload(),
            WriteBandwidthWorkload(),
            StreamingReadWorkload(),
            PingWorkload(),
        ],
        timeout_vt=60000.0,
    )
