"""Multi-resolver range sharding at the proxy (ref: keyResolvers +
ResolutionRequestBuilder + min-combine; the process-level counterpart of the
device-mesh sharded resolver in parallel/)."""

import pytest

from foundationdb_tpu.flow import FdbError, set_event_loop
from foundationdb_tpu.server import SimCluster


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def run_workload(seed, n_resolvers):
    c = SimCluster(seed=seed, n_resolvers=n_resolvers)
    dbs = [c.database() for _ in range(3)]
    history = []

    def w(db, i):
        async def go():
            rng = c.loop.rng
            for j in range(8):
                tr = db.create_transaction()
                try:
                    # keys spread across the whole byte space so ranges
                    # actually land on different resolvers
                    k = bytes([int(rng.random_int(0, 250))]) + b"/k"
                    v = await tr.get(k)
                    tr.set(k, (v or b"") + b"%d" % i)
                    await tr.commit()
                    history.append((i, j, "ok"))
                except FdbError as e:
                    history.append((i, j, e.name))

        return go()

    c.run_all([(db, w(db, i)) for i, db in enumerate(dbs)], timeout_vt=2000.0)
    out = {}

    async def check(tr):
        out["state"] = await tr.get_range(b"", b"\xff")

    c.run_all([(dbs[0], dbs[0].run(check))])
    resolved = [r.total_resolved for r in c.resolvers]
    return history, out["state"], resolved


def test_no_lost_updates_across_resolvers():
    """Serializability invariant under 4-way resolver sharding: every
    committed read-modify-write append survives (a missed cross-resolver
    conflict would lose one), and every resolver participates."""
    for n_resolvers in (1, 4):
        history, state, resolved = run_workload(55, n_resolvers)
        committed = sum(1 for (_i, _j, s) in history if s == "ok")
        appended = sum(len(v) for _k, v in state)
        assert appended == committed, (n_resolvers, history, state)
        assert all(r == resolved[0] for r in resolved) and resolved[0] > 0


def test_cross_boundary_conflicts_detected():
    """A transaction spanning a resolver boundary must still conflict with a
    write on the far side (the min-combine across resolvers)."""
    c = SimCluster(seed=56, n_resolvers=4)
    db1, db2 = c.database(), c.database()
    results = []

    def make(db, me, key):
        async def go():
            tr = db.create_transaction()
            try:
                # read a range spanning all resolver boundaries
                await tr.get_range(b"\x10", b"\xf0", limit=5)
                tr.set(key, b"x")
                await tr.commit()
                results.append((me, "committed"))
            except FdbError as e:
                results.append((me, e.name))

        return go()

    # Both transactions read overlapping cross-boundary ranges and write
    # keys on different resolvers: classic write-skew, exactly one commits.
    c.run_all(
        [(db1, make(db1, 1, b"\x20k")), (db2, make(db2, 2, b"\xe0k"))],
        timeout_vt=500.0,
    )
    assert sorted(s for _, s in results) == ["committed", "not_committed"]
