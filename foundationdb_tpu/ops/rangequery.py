"""Multiword binary search + sparse-table range max/min.

The conflict engine's history is a step function over byte-string keys
digitized as fixed-width vectors of uint32 words (see conflict/keys.py).
These helpers answer, fully vectorized:

  - searchsorted_words: rank of each query key among sorted history keys
    (replaces the reference skip list's Finger descent, SkipList.cpp:345)
  - range_max over a sparse table: max version within a contiguous index
    span (replaces CheckMax's pyramid walk, SkipList.cpp:772-830)

Sparse tables cost O(N log N) to build per batch and O(1) per query; the
whole batch of queries runs as a handful of gathers on device.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def lex_less(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a < b lexicographically over trailing word axis; [..., W] uint32."""
    lt = jnp.zeros(a.shape[:-1], dtype=bool)
    for w in range(a.shape[-1] - 1, -1, -1):
        aw, bw = a[..., w], b[..., w]
        lt = (aw < bw) | ((aw == bw) & lt)
    return lt


def lex_leq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    leq = jnp.ones(a.shape[:-1], dtype=bool)
    for w in range(a.shape[-1] - 1, -1, -1):
        aw, bw = a[..., w], b[..., w]
        leq = (aw < bw) | ((aw == bw) & leq)
    return leq


def searchsorted_words(keys: jnp.ndarray, q: jnp.ndarray, side: str) -> jnp.ndarray:
    """Insertion ranks of q [M, W] into sorted keys [N, W].

    side='left':  count of keys strictly < q
    side='right': count of keys <= q
    Fixed log2(N)+1 binary-search iterations of vectorized gathers.
    """
    n, _w = keys.shape
    m = q.shape[0]
    lo = jnp.zeros((m,), jnp.int32)
    hi = jnp.full((m,), n, jnp.int32)
    steps = max(1, math.ceil(math.log2(max(n, 2))) + 1)
    cmp = lex_less if side == "left" else lex_leq
    for _ in range(steps):
        active = lo < hi
        mid = (lo + hi) // 2
        kmid = keys[jnp.clip(mid, 0, n - 1)]
        go_right = cmp(kmid, q)
        lo = jnp.where(active & go_right, mid + 1, lo)
        hi = jnp.where(active & ~go_right, mid, hi)
    return lo


def floor_log2(x: jnp.ndarray) -> jnp.ndarray:
    """floor(log2(x)) for x >= 1, int32."""
    return 31 - jax.lax.clz(jnp.maximum(x, 1).astype(jnp.int32))


def _build_table(values: jnp.ndarray, op) -> jnp.ndarray:
    """Stacked sparse table [L+1, N]; table[l][i] covers [i, i + 2^l)."""
    n = values.shape[0]
    levels = [values]
    span = 1
    lmax = max(1, math.ceil(math.log2(max(n, 2))))
    for _ in range(lmax):
        prev = levels[-1]
        idx = jnp.minimum(jnp.arange(n, dtype=jnp.int32) + span, n - 1)
        levels.append(op(prev, prev[idx]))
        span *= 2
    return jnp.stack(levels)


def build_max_table(values: jnp.ndarray) -> jnp.ndarray:
    return _build_table(values, jnp.maximum)


def build_min_table(values: jnp.ndarray) -> jnp.ndarray:
    return _build_table(values, jnp.minimum)


def _range_query(table: jnp.ndarray, i: jnp.ndarray, j: jnp.ndarray, op) -> jnp.ndarray:
    """op over values[i..j] inclusive; requires i <= j elementwise."""
    length = j - i + 1
    lev = floor_log2(length)
    left = table[lev, i]
    right = table[lev, j - (1 << lev) + 1]
    return op(left, right)


def range_max(table, i, j):
    return _range_query(table, i, j, jnp.maximum)


def range_min(table, i, j):
    return _range_query(table, i, j, jnp.minimum)
