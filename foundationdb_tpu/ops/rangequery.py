"""Multiword binary search + sparse-table range max/min (word-major layout).

The conflict engine's history is a step function over byte-string keys
digitized as fixed-width vectors of uint32 words (see conflict/keys.py).
These helpers answer, fully vectorized:

  - searchsorted_words: rank of each query key among sorted history keys
    (replaces the reference skip list's Finger descent, SkipList.cpp:345)
  - range_max over a sparse table: max version within a contiguous index
    span (replaces CheckMax's pyramid walk, SkipList.cpp:772-830)

Key tensors are WORD-MAJOR [W, N] (word index leading): TPU tiling pads the
minor dimension to 128 lanes, so the row-major [N, W] form with W=3..5
occupies ~43x its logical size and turns every row access into a padded
512-byte fetch (measured: 1M-row gathers/scatters at ~40x bandwidth waste,
and h_cap=8M OOMs outright).  Word-major keeps N on the lanes.

Word significance: index 0 is MOST significant; the trailing word (the key
length) is the least significant tie-break — matching conflict/keys.py.

Sparse tables cost O(N log N) to build per batch and O(1) per query; builds
are pure slice+pad streaming (no gather).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def lex_less(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a < b lexicographically over the LEADING word axis; [W, ...] uint32.

    Processes trailing (least significant) words first, so word 0 — the
    most significant — decides last and dominates."""
    lt = jnp.zeros(a.shape[1:], dtype=bool)
    for w in range(a.shape[0] - 1, -1, -1):
        aw, bw = a[w], b[w]
        lt = (aw < bw) | ((aw == bw) & lt)
    return lt


def lex_leq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    leq = jnp.ones(a.shape[1:], dtype=bool)
    for w in range(a.shape[0] - 1, -1, -1):
        aw, bw = a[w], b[w]
        leq = (aw < bw) | ((aw == bw) & leq)
    return leq


from ..flow.knobs import g_env

# Search strategy for big tables (perf experiment; decisions identical):
#   ""        flat binary search (default)
#   "2level"  coarse sampled-table bracket, then fine steps — the coarse
#             table (one column per SAMPLE_STRIDE) is small enough for the
#             compiler to keep on-chip, so only the fine log2(stride)
#             steps gather from the full HBM-resident table.
SEARCH_MODE = g_env.get("FDB_TPU_SEARCH")
SAMPLE_STRIDE = g_env.get_int("FDB_TPU_SEARCH_STRIDE")
_2LEVEL_MIN = 1 << 16  # below this a flat search wins (coarse build cost)


def _searchsorted_words_flat(keys, q, side, lo=None, hi=None):
    _w, n = keys.shape
    m = q.shape[1]
    lo = jnp.zeros((m,), jnp.int32) if lo is None else lo
    hi = jnp.full((m,), n, jnp.int32) if hi is None else hi
    steps = max(1, math.ceil(math.log2(max(n, 2))) + 1)
    cmp = lex_less if side == "left" else lex_leq
    for _ in range(steps):
        active = lo < hi
        mid = (lo + hi) // 2
        kmid = keys[:, jnp.clip(mid, 0, n - 1)]
        go_right = cmp(kmid, q)
        lo = jnp.where(active & go_right, mid + 1, lo)
        hi = jnp.where(active & ~go_right, mid, hi)
    return lo


def _searchsorted_words_2level(keys, q, side):
    """Coarse-then-fine: bracket each query in a sampled table first, then
    run only log2(stride) fine steps against the big table."""
    _w, n = keys.shape
    m = q.shape[1]
    stride = SAMPLE_STRIDE
    coarse = keys[:, ::stride]  # [W, ceil(n/stride)]
    nc = coarse.shape[1]
    cmp = lex_less if side == "left" else lex_leq
    clo = jnp.zeros((m,), jnp.int32)
    chi = jnp.full((m,), nc, jnp.int32)
    for _ in range(max(1, math.ceil(math.log2(max(nc, 2))) + 1)):
        active = clo < chi
        mid = (clo + chi) // 2
        kmid = coarse[:, jnp.clip(mid, 0, nc - 1)]
        go_right = cmp(kmid, q)
        clo = jnp.where(active & go_right, mid + 1, clo)
        chi = jnp.where(active & ~go_right, mid, chi)
    # Bracket in the full table: rank is in [ (clo-1)*stride, clo*stride ].
    lo = jnp.clip((clo - 1) * stride, 0, n).astype(jnp.int32)
    hi = jnp.minimum(clo * stride, n).astype(jnp.int32)
    steps = max(1, math.ceil(math.log2(stride)) + 1)
    for _ in range(steps):
        active = lo < hi
        mid = (lo + hi) // 2
        kmid = keys[:, jnp.clip(mid, 0, n - 1)]
        go_right = cmp(kmid, q)
        lo = jnp.where(active & go_right, mid + 1, lo)
        hi = jnp.where(active & ~go_right, mid, hi)
    return lo


def searchsorted_words(keys: jnp.ndarray, q: jnp.ndarray, side: str) -> jnp.ndarray:
    """Insertion ranks of q [W, M] into sorted keys [W, N].

    side='left':  count of keys strictly < q
    side='right': count of keys <= q
    Fixed log2(N)+1 binary-search iterations of vectorized gathers along the
    lane axis (or the coarse-then-fine variant under FDB_TPU_SEARCH=2level).
    """
    if SEARCH_MODE == "2level" and keys.shape[1] >= _2LEVEL_MIN:
        return _searchsorted_words_2level(keys, q, side)
    return _searchsorted_words_flat(keys, q, side)


def searchsorted_1d(keys: jnp.ndarray, q: jnp.ndarray, side: str) -> jnp.ndarray:
    """Insertion ranks of int queries q into 1-D sorted int keys — the
    single-word fast path of searchsorted_words (jnp.searchsorted lowers
    poorly on TPU; this fixed-step loop of 1-D gathers measures ~1000x
    faster at 64k queries into 128k keys)."""
    n = keys.shape[0]
    lo = jnp.zeros(q.shape, jnp.int32)
    hi = jnp.full(q.shape, n, jnp.int32)
    for _ in range(max(1, math.ceil(math.log2(max(n, 2))) + 1)):
        # The active guard stops converged lanes: without it, one extra
        # iteration past lo==hi==n keeps incrementing lo for queries at or
        # beyond the last key whenever n is not a power of two.
        active = lo < hi
        mid = (lo + hi) // 2
        kmid = keys[jnp.clip(mid, 0, n - 1)]
        go_right = (kmid <= q) if side == "right" else (kmid < q)
        lo = jnp.where(active & go_right, mid + 1, lo)
        hi = jnp.where(active & ~go_right, mid, hi)
    return lo


def floor_log2(x: jnp.ndarray) -> jnp.ndarray:
    """floor(log2(x)) for x >= 1, int32."""
    return 31 - jax.lax.clz(jnp.maximum(x, 1).astype(jnp.int32))


def _build_table(values, op, xp=jnp):
    """Stacked sparse table [L+1, N]; table[l][i] covers [i, i + 2^l).

    The shifted self-combine is expressed as slice + edge-pad (NOT a
    clamped-index gather): XLA lowers slices/pads to pure streaming copies,
    while a gather with computed indices runs orders of magnitude slower on
    TPU.  Measured on v5e at N=1M: 262ms (gather) -> ~2ms (slice).

    `xp` selects the array module: the tiered conflict engine seeds its
    CARRIED base max-table host-side (numpy) at init/load_from/grow; one
    shared implementation keeps the host table's level layout identical to
    what range_max expects by construction."""
    n = values.shape[0]
    levels = [values]
    span = 1
    lmax = max(1, math.ceil(math.log2(max(n, 2))))
    for _ in range(lmax):
        prev = levels[-1]
        shifted = xp.concatenate(
            [prev[span:], xp.broadcast_to(prev[-1:], (min(span, n),))]
        )
        levels.append(op(prev, shifted))
        span *= 2
    return xp.stack(levels)


def build_max_table(values: jnp.ndarray) -> jnp.ndarray:
    return _build_table(values, jnp.maximum)


def build_min_table(values: jnp.ndarray) -> jnp.ndarray:
    return _build_table(values, jnp.minimum)


def build_max_table_np(values):
    """Host (numpy) twin of build_max_table — same layout by construction
    (shared _build_table body)."""
    import numpy as np

    return _build_table(values, np.maximum, xp=np)


def _range_query(table: jnp.ndarray, i: jnp.ndarray, j: jnp.ndarray, op) -> jnp.ndarray:
    """op over values[i..j] inclusive; requires i <= j elementwise."""
    length = j - i + 1
    lev = floor_log2(length)
    left = table[lev, i]
    right = table[lev, j - (1 << lev) + 1]
    return op(left, right)


def range_max(table, i, j):
    return _range_query(table, i, j, jnp.maximum)


def range_min(table, i, j):
    return _range_query(table, i, j, jnp.minimum)
