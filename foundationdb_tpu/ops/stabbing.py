"""Dyadic segment-tree interval stabbing: per-slot min over covering intervals.

Given M intervals [lo_i, hi_i) over N = 2^k slots, each with an int32 weight,
computes for every slot the minimum weight among intervals covering it
(+INF where uncovered).  This is how the conflict engine answers, for every
point of the key space at once, "what is the earliest transaction whose write
covers this point?" — the vectorized replacement for the reference's ordered
MiniConflictSet scan (SkipList.cpp:1133 checkIntraBatchConflicts), where the
batch-order constraint 's earlier than t' becomes 'min covering writer < t'.

Build: each interval min-updates its O(log N) dyadic cover nodes (the classic
iterative segment-tree range update, vectorized across all intervals); a
top-down push then folds node values onto leaves.  O((M + N) log N) total,
all scatters/gathers.
"""

from __future__ import annotations

import jax.numpy as jnp

# Plain Python int so importing this module never touches a JAX backend
# (a module-level jnp.int32() would device-commit at import time; with a
# broken TPU tunnel that init can hang for ~25 min — observed round 3).
# jnp ops cast it where used; the explicit dtype=jnp.int32 sites keep the
# arrays int32.
INF32 = 2**31 - 1


def stabbing_min(
    lo: jnp.ndarray,
    hi: jnp.ndarray,
    weight: jnp.ndarray,
    valid: jnp.ndarray,
    n_log2: int,
) -> jnp.ndarray:
    """Per-slot min weight over covering intervals.

    lo, hi: int32 [M] half-open slot intervals, 0 <= lo <= hi <= N
    weight: int32 [M]; valid: bool [M] (invalid intervals ignored)
    returns int32 [N] (INF32 where uncovered), N = 2^n_log2.
    """
    n = 1 << n_log2
    # Flat tree: node 1 is root, leaves are [n, 2n); index 2n is a dummy
    # slot for masked-off scatters.
    tree = jnp.full((2 * n + 1,), INF32, dtype=jnp.int32)
    w = jnp.where(valid, weight.astype(jnp.int32), INF32)
    li = jnp.where(valid, lo + n, 2 * n).astype(jnp.int32)
    ri = jnp.where(valid, hi + n, 2 * n).astype(jnp.int32)
    for _ in range(n_log2 + 1):
        active = li < ri
        upd_l = active & (li % 2 == 1)
        tree = tree.at[jnp.where(upd_l, li, 2 * n)].min(jnp.where(upd_l, w, INF32))
        li = li + upd_l
        upd_r = active & (ri % 2 == 1)
        ri = ri - upd_r
        tree = tree.at[jnp.where(upd_r, ri, 2 * n)].min(jnp.where(upd_r, w, INF32))
        li = li // 2
        ri = ri // 2
    # Push node minima down to leaves, level by level.
    for d in range(n_log2):
        lvl_start = 1 << d
        parents = tree[lvl_start : 2 * lvl_start]
        children = tree[2 * lvl_start : 4 * lvl_start]
        children = jnp.minimum(children, jnp.repeat(parents, 2))
        tree = tree.at[2 * lvl_start : 4 * lvl_start].set(children)
    return tree[n : 2 * n]
