"""Vectorized query primitives for the TPU data plane.

These are the XLA-friendly building blocks the conflict engine composes:
multiword lexicographic binary search, sparse-table range max/min, and a
dyadic segment-tree interval-stabbing query.  All shapes are static; all
control flow is unrolled or lax loops, so everything jits onto the TPU
without host round-trips.
"""

from .rangequery import (
    lex_less,
    lex_leq,
    searchsorted_words,
    build_max_table,
    build_min_table,
    range_max,
    range_min,
    floor_log2,
)
from .stabbing import stabbing_min

__all__ = [
    "lex_less",
    "lex_leq",
    "searchsorted_words",
    "build_max_table",
    "build_min_table",
    "range_max",
    "range_min",
    "floor_log2",
    "stabbing_min",
]
