"""SystemMonitor: periodic per-process metrics into the trace stream.

Ref: flow/SystemMonitor.cpp — systemMonitor() emits ProcessMetrics /
MachineMetrics TraceEvents on a cadence (CPU seconds, memory, network
counters); dashboards and the status doc read them.  The rebuild's
per-process numbers: event-loop throughput, live actor/endpoint counts,
heap depth, and (real deployments) rusage CPU + max RSS.

The slow-task profiler half (ref: Net2's slow-task profiling via
setProfilingEnabled) lives in the event loop: see
EventLoop.slow_task_threshold — any single task step exceeding it emits a
SlowTask event with the task's wall-clock cost.
"""

from __future__ import annotations

from .trace import TraceEvent


async def run_system_monitor(
    process, interval: float = 5.0, wall_metrics: bool = False
):
    """Per-process metrics cadence (ref: systemMonitor's delay loop).

    wall_metrics=True adds rusage CPU seconds + max RSS — REAL deployments
    only: those values are wall-clock-derived and would break the
    simulator's bit-reproducibility if traced in sim runs (the
    cross-interpreter byte-identity gate compares trace output)."""
    loop = process.network.loop
    last_tasks = loop.tasks_run
    while True:
        await loop.delay(interval)
        ev = (
            TraceEvent("ProcessMetrics")
            .detail("process", process.name)
            .detail("address", process.address)
            .detail("tasks_run_delta", loop.tasks_run - last_tasks)
            .detail("live_actors", len(process._tasks))
            .detail("endpoints", len(process._endpoints))
            .detail("heap_events", len(loop._heap))
        )
        last_tasks = loop.tasks_run
        if wall_metrics:
            try:
                import resource

                ru = resource.getrusage(resource.RUSAGE_SELF)
                ev.detail("max_rss_kb", ru.ru_maxrss)
                ev.detail("cpu_user_s", round(ru.ru_utime, 3))
                ev.detail("cpu_sys_s", round(ru.ru_stime, 3))
            except Exception:  # pragma: no cover - platform without rusage  # fdblint: ignore[ERR001]: rusage details are optional; the event still logs without them
                pass
        ev.log(now=loop.now())
