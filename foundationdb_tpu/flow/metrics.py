"""MetricsRegistry: counters, gauges, and bounded histograms with
deterministic sim-time snapshots and a periodic trace emitter actor.

Ref: flow/Stats.h — `Counter`/`CounterCollection` :55-63 plus the
`traceCounters` actor :111 — and Status.actor.cpp's qos section, which
folds ContinuousSample percentiles into the status doc.  The registry is
the pipeline's collection point: roles (resolver, proxy) and the device
conflict engine record into one, the emitter actor periodically turns it
into a TraceEvent, and `server/status.py` / `tools/cli.py` read
`snapshot()` directly.

Determinism contract (the property the whole pipeline is gated on):
`snapshot()` contains ONLY values derived from the simulation — counter
values, loop-virtual-time timestamps, and histogram aggregates whose
reservoir sampling flows through the loop's DeterministicRandom.  Two
same-seed runs therefore produce byte-identical `snapshot_json()` output.
Wall-clock measurements (real device dispatch cost, rusage) go through
`record_wall()` into a SEPARATE namespace that `snapshot()` excludes by
default — the same discipline as `system_monitor.py`'s `wall_metrics`
flag: real-mode observability must never leak into sim-compared output.
"""

from __future__ import annotations

from typing import Dict, Optional

from .stats import ContinuousSample, Counter
from .trace import TraceEvent


def wall_now() -> float:
    """REAL clock read for wall-namespace measurements (`record_wall`).
    Centralized here so call sites measuring device dispatch cost don't
    each carry a determinism pragma; the value must never feed virtual
    time or a sim-compared snapshot."""
    import time

    return time.perf_counter()  # fdblint: ignore[DET001]: wall namespace only — record_wall output is excluded from sim snapshots by design


class Gauge:
    """Last-write-wins instantaneous value (ref: the status doc's point-in-
    time fields, e.g. worst_queue_bytes)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def set(self, v):
        self.value = v

    def add(self, n=1):
        self.value += n


class BoundedHistogram:
    """Distribution of a metric, bounded in memory.

    Always maintains exact deterministic aggregates (count/sum/min/max);
    with an rng (the loop's DeterministicRandom) it additionally keeps a
    ContinuousSample reservoir for percentile queries.  Without an rng the
    summary simply omits percentiles — callers that cannot reach a loop
    rng (the device engine constructed before any loop exists) stay fully
    deterministic."""

    __slots__ = ("name", "count", "total", "_min", "_max", "_sample")

    def __init__(self, name: str, rng=None, size: int = 500):
        self.name = name
        self.count = 0
        self.total = 0.0
        self._min = None
        self._max = None
        self._sample = ContinuousSample(rng, size) if rng is not None else None

    def add(self, x: float):
        self.count += 1
        self.total += x
        self._min = x if self._min is None else min(self._min, x)
        self._max = x if self._max is None else max(self._max, x)
        if self._sample is not None:
            self._sample.add(x)

    def summary(self) -> dict:
        out = {
            "count": self.count,
            "sum": self.total,
            "mean": self.total / self.count if self.count else None,
            "min": self._min,
            "max": self._max,
        }
        if self._sample is not None:
            out["median"] = self._sample.percentile(0.5)
            out["p90"] = self._sample.percentile(0.90)
            out["p99"] = self._sample.percentile(0.99)
        return out


class MetricsRegistry:
    """Named counters + gauges + histograms for one subsystem.

    `rng` (the loop's DeterministicRandom) enables histogram percentiles;
    it must never be a wall-seeded source in sim code paths."""

    def __init__(self, name: str, rng=None):
        self.name = name
        self.rng = rng
        self.counters: Dict[str, Counter] = {}
        self.gauges: Dict[str, Gauge] = {}
        self.histograms: Dict[str, BoundedHistogram] = {}
        # Wall-clock namespace: (count, total seconds) per name.  Written
        # by real-mode measurements only; excluded from sim snapshots.
        self.wall: Dict[str, list] = {}

    # -- instrument factories (get-or-create, like CounterCollection) --
    def counter(self, name: str) -> Counter:
        c = self.counters.get(name)
        if c is None:
            c = self.counters[name] = Counter(name)
        return c

    def adopt(self, counter: Counter) -> Counter:
        """Register an EXISTING Counter (e.g. one owned by a role's
        CounterCollection) under its own name, so both surfaces read ONE
        underlying value — call sites increment once and the two views
        can never drift.  The adopter must be the counter's only rate
        emitter (rate_since_last resets a shared baseline)."""
        self.counters[counter.name] = counter
        return counter

    def gauge(self, name: str) -> Gauge:
        g = self.gauges.get(name)
        if g is None:
            g = self.gauges[name] = Gauge(name)
        return g

    def histogram(self, name: str, size: int = 500) -> BoundedHistogram:
        h = self.histograms.get(name)
        if h is None:
            h = self.histograms[name] = BoundedHistogram(
                name, rng=self.rng, size=size
            )
        return h

    def record_wall(self, name: str, seconds: float):
        """Accumulate a REAL-clock measurement (device dispatch cost and
        the like).  Lives outside the deterministic snapshot; surfaced
        only via snapshot(include_wall=True) for real-mode tooling."""
        ent = self.wall.setdefault(name, [0, 0.0])
        ent[0] += 1
        ent[1] += seconds

    # -- snapshots --
    def snapshot(
        self, now: Optional[float] = None, include_wall: bool = False
    ) -> dict:
        """Deterministic point-in-time view.  The timestamp comes from
        loop virtual time ONLY: explicit `now`, else the current loop's
        clock, else no timestamp at all — a wall-clock fallback here would
        silently break byte-identical same-seed snapshots."""
        if now is None:
            from .eventloop import _current_loop

            now = _current_loop.now() if _current_loop is not None else None
        out: dict = {"name": self.name}
        if now is not None:
            out["time"] = now
        out["counters"] = {
            k: c.value for k, c in sorted(self.counters.items())
        }
        out["gauges"] = {k: g.value for k, g in sorted(self.gauges.items())}
        out["histograms"] = {
            k: h.summary() for k, h in sorted(self.histograms.items())
        }
        if include_wall:
            out["wall"] = {
                k: {"count": v[0], "seconds": v[1]}
                for k, v in sorted(self.wall.items())
            }
        return out

    def snapshot_json(
        self, now: Optional[float] = None, include_wall: bool = False
    ) -> str:
        """Canonical byte form of snapshot() — what the determinism gate
        compares across same-seed runs."""
        import json

        return json.dumps(
            self.snapshot(now=now, include_wall=include_wall),
            sort_keys=True,
            separators=(",", ":"),
        )


async def emit_metrics(
    registry: MetricsRegistry, process, interval: float = 5.0
):
    """Periodic emitter actor (ref: traceCounters flow/Stats.h:111): one
    `<Name>Metrics` TraceEvent per interval carrying every counter (with
    rate), gauge, and histogram summary.  Virtual-time paced; emits
    nothing wall-derived, so the trace stream stays seed-reproducible."""
    loop = process.network.loop
    while True:
        await loop.delay(interval)
        now = loop.now()
        ev = TraceEvent(f"{registry.name}Metrics")
        for name, c in sorted(registry.counters.items()):
            ev.detail(name, c.value)
            ev.detail(f"{name}Rate", round(c.rate_since_last(now), 3))
        for name, g in sorted(registry.gauges.items()):
            ev.detail(name, g.value)
        for name, h in sorted(registry.histograms.items()):
            s = h.summary()
            ev.detail(f"{name}Count", s["count"])
            ev.detail(f"{name}Mean", s["mean"])
            ev.detail(f"{name}Max", s["max"])
        ev.log(now=now)
