"""Futures/promises: single-assignment variables with callback chains.

Ref: flow/flow.h — SAV :347, Future :591, Promise :705, FutureStream :756,
PromiseStream :833.  The reference's futures are single-threaded and fire
callbacks synchronously when set; ours do the same (no thread safety needed:
one event loop thread, like the reference's one-network-thread rule).

A Future here is awaitable from coroutines driven by the EventLoop.  Unlike
asyncio futures, set() delivers *synchronously* to plain callbacks, while
awaiting coroutines are resumed via the loop's ready queue at a task priority,
mirroring how flow delivers to actor callbacks through task priorities.
"""

from __future__ import annotations

import weakref
from typing import Any, Callable, Generator, Optional

from .error import ActorCancelled, FdbError

_PENDING = 0
_VALUE = 1
_ERROR = 2

# Test-only bookkeeping behind flow/sim_validation's orphaned-wait check
# (the dynamic twin of fdblint PRM001/PRM002): when on, every Future
# remembers its paired Promise by WEAK reference, so teardown checks can
# tell "parked on a promise somebody still holds" from "parked on a
# promise that was dropped — zero remaining senders".  Off by default:
# promises are hot-path objects and the weakref is pure diagnostics.
_TRACK_REFS = False


def track_promise_refs(on: bool):
    """Enable/disable Promise weakref bookkeeping.  Must be on BEFORE the
    scenario under test creates its promises (sim_validation's
    expect_no_orphaned_waits guards against the forgotten call)."""
    global _TRACK_REFS
    _TRACK_REFS = bool(on)


def promise_tracking_enabled() -> bool:
    return _TRACK_REFS


class Future:
    __slots__ = ("_state", "_result", "_callbacks", "priority", "timer_cell",
                 "promise_ref", "__weakref__")

    def __init__(self, priority: Optional[int] = None):
        self._state = _PENDING
        self._result: Any = None
        self._callbacks: list[Callable[["Future"], None]] = []
        # Priority at which awaiting coroutines resume; None = inherit.
        self.priority = priority
        # Set by EventLoop.delay so pending timers can be cancelled.
        self.timer_cell = None
        # weakref to the paired Promise (only under track_promise_refs).
        self.promise_ref = None

    # -- inspection --
    def is_ready(self) -> bool:
        return self._state != _PENDING

    def is_error(self) -> bool:
        return self._state == _ERROR

    def get(self):
        """Value if ready, raising if error; ref Future::get()."""
        if self._state == _VALUE:
            return self._result
        if self._state == _ERROR:
            raise self._result
        raise FdbError("future_version")  # get() on not-ready is a logic error

    def error(self) -> Optional[BaseException]:
        return self._result if self._state == _ERROR else None

    # -- assignment (normally via Promise) --
    def _set(self, value):
        assert self._state == _PENDING, "Future already set"
        self._state = _VALUE
        self._result = value
        self._fire()

    def _set_error(self, err: BaseException):
        assert self._state == _PENDING, "Future already set"
        self._state = _ERROR
        self._result = err
        self._fire()

    def _fire(self):
        cbs, self._callbacks = self._callbacks, []
        for cb in cbs:
            cb(self)

    def add_callback(self, cb: Callable[["Future"], None]):
        if self._state != _PENDING:
            cb(self)
        else:
            self._callbacks.append(cb)

    def remove_callback(self, cb):
        try:
            self._callbacks.remove(cb)
        except ValueError:
            pass

    # -- awaitable protocol --
    def __await__(self) -> Generator["Future", None, Any]:
        if self._state == _PENDING:
            yield self  # Task.step picks this up and subscribes
        return self.get()


class Promise:
    """Write side of a Future; ref flow/flow.h:705."""

    __slots__ = ("future", "__weakref__")

    def __init__(self, priority: Optional[int] = None):
        self.future = Future(priority)
        if _TRACK_REFS:
            self.future.promise_ref = weakref.ref(self)

    def send(self, value=None):
        self.future._set(value)

    def send_error(self, err: BaseException):
        self.future._set_error(err)

    def is_set(self) -> bool:
        return self.future.is_ready()

    def __repr__(self):
        return f"Promise(ready={self.future.is_ready()})"


def ready_future(value=None) -> Future:
    f = Future()
    f._set(value)
    return f


def error_future(err: BaseException) -> Future:
    f = Future()
    f._set_error(err)
    return f


class FutureStream:
    """Read side of a PromiseStream; ref flow/flow.h:756.

    pop() returns a Future for the next element.  Elements are queued; an
    error (e.g. end_of_stream) is delivered after all queued values.
    """

    __slots__ = ("_queue", "_waiters", "_error")

    def __init__(self):
        self._queue: list = []
        self._waiters: list[Promise] = []
        self._error: Optional[BaseException] = None

    def pop(self) -> Future:
        if self._queue:
            return ready_future(self._queue.pop(0))
        if self._error is not None:
            return error_future(self._error)
        p = Promise()
        self._waiters.append(p)
        return p.future

    def is_ready(self) -> bool:
        return bool(self._queue) or self._error is not None

    def _push(self, value):
        if self._waiters:
            self._waiters.pop(0).send(value)
        else:
            self._queue.append(value)

    def _push_error(self, err: BaseException):
        self._error = err
        waiters, self._waiters = self._waiters, []
        for w in waiters:
            w.send_error(err)


class PromiseStream:
    """Write side: send() any number of values; ref flow/flow.h:833."""

    __slots__ = ("_stream",)

    def __init__(self):
        self._stream = FutureStream()

    @property
    def future_stream(self) -> FutureStream:
        return self._stream

    def send(self, value=None):
        self._stream._push(value)

    def send_error(self, err: BaseException):
        self._stream._push_error(err)

    def pop(self) -> Future:
        return self._stream.pop()

    def is_ready(self) -> bool:
        return self._stream.is_ready()
