"""Dynamic shared-state sanitizer: the runtime twin of fdblint RACE001-004.

The static pass (tools/lint/races.py) proves lost-update shapes from the
ASTs; this sanitizer observes the same condition at runtime.  Audited
shared dicts record every keyed read and write as (task, await-epoch) —
the epoch bumps once per event-loop step, so two accesses at the same
epoch cannot have had another task run between them.  A write by task T
whose value derives from T's earlier read of the same key, with an OTHER
task's write landing between the read and the write, is a
stale-read→write pair: the dynamic signature of a lost update (T's write
was computed without the interleaved value and stomps it).

State hangs off the event loop (like sim_validation) so concurrent
simulated clusters in one test process do not interfere.  Everything is
gated on FDB_TPU_STATE_SANITIZER: with the flag off, ``audited_dict``
returns a plain dict and the runtime cost is zero.  Like the static pass,
the check under-approximates — blind writes (no prior read) and
cross-key derivations are not flagged; what it does flag is a real
interleaving that happened, not a may-happen.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

from .knobs import g_env

# A task label: (name, id).  The id disambiguates same-named actor
# instances; messages print only the name.
_TaskLabel = Tuple[str, int]


class StateSanitizer:
    """Per-loop recorder of audited-object accesses and violations."""

    def __init__(self, loop):
        self.loop = loop
        # (dict_name, key) -> {task: epoch of that task's last read}
        self._reads: Dict[Tuple[str, Any], Dict[_TaskLabel, int]] = {}
        # (dict_name, key) -> (task, epoch) of the last write
        self._writes: Dict[Tuple[str, Any], Tuple[_TaskLabel, int]] = {}
        self.violations: List[str] = []
        self.names: set = set()  # audited object names, for blindness check

    def _who(self) -> _TaskLabel:
        t = self.loop.current_task
        return (t.name, id(t)) if t is not None else ("<loop>", 0)

    def on_read(self, name: str, key):
        self._reads.setdefault((name, key), {})[self._who()] = (
            self.loop.await_epoch
        )

    def on_write(self, name: str, key):
        who = self._who()
        epoch = self.loop.await_epoch
        slot = (name, key)
        read_at = self._reads.get(slot, {}).get(who)
        last = self._writes.get(slot)
        # Stale-read→write: our read predates another task's write that
        # itself predates (or shares) this step.  Same-epoch interference
        # is impossible (one task per step), so the strict `<` is exact.
        if (
            read_at is not None
            and last is not None
            and last[0] != who
            and read_at < last[1] <= epoch
        ):
            self.violations.append(
                f"{name}[{key!r}]: task {who[0]!r} wrote at epoch {epoch} "
                f"from its read at epoch {read_at}, but task "
                f"{last[0][0]!r} wrote at epoch {last[1]} in between "
                f"(lost update)"
            )
        self._writes[slot] = (who, epoch)
        # The write refreshes the writer's own knowledge of the key (the
        # re-check-after-await discipline reads, then writes, in one step).
        self._reads.setdefault(slot, {})[who] = epoch


class AuditedDict(dict):
    """dict reporting every keyed read/write to the loop's sanitizer.

    Keyed accessors only: iteration (keys/values/items) is not audited —
    the violation condition is per-key, and auditing scans would drown
    the signal.  Under-approximate, like everything else in this file.
    """

    def __init__(self, san: StateSanitizer, name: str, init=()):
        super().__init__(init)
        self._san = san
        self._name = name

    # -- reads --
    def __getitem__(self, key):
        self._san.on_read(self._name, key)
        return super().__getitem__(key)

    def get(self, key, default=None):
        self._san.on_read(self._name, key)
        return super().get(key, default)

    def __contains__(self, key):
        self._san.on_read(self._name, key)
        return super().__contains__(key)

    # -- writes --
    def __setitem__(self, key, value):
        self._san.on_write(self._name, key)
        super().__setitem__(key, value)

    def __delitem__(self, key):
        self._san.on_write(self._name, key)
        super().__delitem__(key)

    def pop(self, key, *default):
        self._san.on_write(self._name, key)
        return super().pop(key, *default)

    def setdefault(self, key, default=None):
        self._san.on_read(self._name, key)
        if not super().__contains__(key):
            self._san.on_write(self._name, key)
        return super().setdefault(key, default)

    def update(self, *args, **kwargs):
        staged = dict(*args, **kwargs)
        for k in staged:
            self._san.on_write(self._name, k)
        super().update(staged)

    def clear(self):
        for k in list(super().keys()):
            self._san.on_write(self._name, k)
        super().clear()


def audited_dict(loop, name: str, init=None) -> dict:
    """A shared dict to audit under the sanitizer.

    Plain dict when FDB_TPU_STATE_SANITIZER is off (zero overhead); an
    AuditedDict bound to the loop's sanitizer (created on first use) when
    on.  `name` labels the object in violation reports.
    """
    if not g_env.get("FDB_TPU_STATE_SANITIZER"):
        return dict(init or ())
    san = getattr(loop, "_state_sanitizer", None)
    if san is None:
        san = loop._state_sanitizer = StateSanitizer(loop)
    san.names.add(name)
    return AuditedDict(san, name, init or ())


def expect_clean_shared_state(loop, context: str = ""):
    """Sim-shutdown assertion: no audited shared object saw a
    stale-read→write pair during the run.  No-op unless
    FDB_TPU_STATE_SANITIZER is truthy (test-only — see flow/knobs.py);
    raises if the flag is set but no audited object was ever constructed
    on this loop, so the check can't silently pass while blind."""
    if not g_env.get("FDB_TPU_STATE_SANITIZER"):
        return
    san = getattr(loop, "_state_sanitizer", None)
    if san is None or not san.names:
        raise AssertionError(
            "state_sanitizer: FDB_TPU_STATE_SANITIZER is set but no "
            "audited_dict was constructed on this loop — the check would "
            "be blind"
        )
    if san.violations:
        head = "; ".join(sorted(san.violations)[:8])
        raise AssertionError(
            f"state_sanitizer: {len(san.violations)} stale-read→write "
            f"pair(s) on audited shared state: {head}"
            + (f" ({context})" if context else "")
        )
