"""Simulation-only invariant recorder.

Ref: fdbrpc/sim_validation.{h,cpp} — production code records promises the
simulation later checks ("this version was acknowledged durable"); a
violation is a loud simulation failure, not a silent wrong answer.  State
hangs off the event loop so concurrent simulated clusters in one test
process do not interfere.
"""

from __future__ import annotations

import gc
from typing import List, Tuple


def _state(loop) -> dict:
    st = getattr(loop, "_sim_validation", None)
    if st is None:
        st = loop._sim_validation = {}
    return st


def mark_at_least(loop, key: str, value: int):
    """Record a monotone promise, e.g. 'commits through V were acked'."""
    st = _state(loop)
    if value > st.get(key, -(1 << 62)):
        st[key] = value


def marked(loop, key: str) -> int:
    return _state(loop).get(key, -(1 << 62))


def expect_at_least(loop, key: str, value: int, context: str = ""):
    """The checking side: `value` must cover every marked promise (e.g. a
    recovery's epoch cut must not truncate below an acked commit)."""
    m = _state(loop).get(key, None)
    if m is not None and value < m:
        raise AssertionError(
            f"sim_validation: {key} promised {m} but observed {value}"
            + (f" ({context})" if context else "")
        )


# ---------------------------------------------------------------------------
# Orphaned-wait teardown check: the DYNAMIC twin of fdblint PRM001/PRM002.
#
# The static pass proves "no reachable code can send to this promise" from
# the ASTs; this check observes the same condition at runtime: a Task still
# parked on a future whose paired Promise has been garbage-collected has
# ZERO remaining senders — nothing can ever wake it (the reference would
# have delivered broken_promise from the Promise destructor; our rebuild
# has no destructor backstop, which is exactly why both checks exist).
# Needs flow.future.track_promise_refs(True) BEFORE the scenario builds its
# promises; the assertion itself is gated on FDB_TPU_CHECK_ORPHANED_WAITS
# so production/bench runs pay nothing.
# ---------------------------------------------------------------------------


def orphaned_waits(loop) -> List[Tuple[str, str]]:
    """[(task_name, description)] for live tasks parked on a future whose
    paired Promise was dropped.  Futures with a live pending timer are
    excluded (the loop would have fired them had it kept running); tasks
    awaiting futures with no recorded promise (timers, other Tasks) are
    skipped — the check under-approximates, like the static pass.  Empty
    when track_promise_refs is off."""
    # Snapshot STRONG references before collecting: a fire-and-forget
    # task parked on a dropped promise is itself only reachable through
    # the task<->future callback cycle, and gc.collect() would reap it
    # out of the WeakSet before the scan — silently missing exactly the
    # dropped-handle orphan class this check exists for.  The collect
    # still runs (after the snapshot) so a dropped PROMISE held only by
    # a cycle reads as dead.
    tasks = list(getattr(loop, "_spawned", ()))
    gc.collect()
    out: List[Tuple[str, str]] = []
    for t in tasks:
        if t.is_ready():
            continue
        f = getattr(t, "_waiting_on", None)
        if f is None or f.is_ready():
            continue
        cell = getattr(f, "timer_cell", None)
        if cell is not None and cell[0] is not None:
            continue  # live timer: would fire
        ref = getattr(f, "promise_ref", None)
        if ref is not None and ref() is None:
            out.append((t.name, "promise dropped; zero remaining senders"))
    out.sort()
    return out


def expect_no_orphaned_waits(loop, context: str = ""):
    """Loop-teardown assertion: no task may still be parked on a future
    with zero remaining senders at sim shutdown.  No-op unless the
    FDB_TPU_CHECK_ORPHANED_WAITS env flag is truthy (test-only — see
    flow/knobs.py); raises if the flag is set but promise tracking was
    never enabled, so the check can't silently pass while blind."""
    from .knobs import g_env

    if not g_env.get("FDB_TPU_CHECK_ORPHANED_WAITS"):
        return
    from .future import promise_tracking_enabled

    if not promise_tracking_enabled():
        raise AssertionError(
            "sim_validation: FDB_TPU_CHECK_ORPHANED_WAITS is set but "
            "flow.future.track_promise_refs(True) was not called before "
            "the scenario — the check would be blind"
        )
    orphans = orphaned_waits(loop)
    if orphans:
        names = "; ".join(f"{n} ({w})" for n, w in orphans[:8])
        raise AssertionError(
            f"sim_validation: {len(orphans)} task(s) parked on futures "
            f"with zero remaining senders at shutdown: {names}"
            + (f" ({context})" if context else "")
        )
