"""Simulation-only invariant recorder.

Ref: fdbrpc/sim_validation.{h,cpp} — production code records promises the
simulation later checks ("this version was acknowledged durable"); a
violation is a loud simulation failure, not a silent wrong answer.  State
hangs off the event loop so concurrent simulated clusters in one test
process do not interfere.
"""

from __future__ import annotations


def _state(loop) -> dict:
    st = getattr(loop, "_sim_validation", None)
    if st is None:
        st = loop._sim_validation = {}
    return st


def mark_at_least(loop, key: str, value: int):
    """Record a monotone promise, e.g. 'commits through V were acked'."""
    st = _state(loop)
    if value > st.get(key, -(1 << 62)):
        st[key] = value


def marked(loop, key: str) -> int:
    return _state(loop).get(key, -(1 << 62))


def expect_at_least(loop, key: str, value: int, context: str = ""):
    """The checking side: `value` must cover every marked promise (e.g. a
    recovery's epoch cut must not truncate below an acked commit)."""
    m = _state(loop).get(key, None)
    if m is not None and value < m:
        raise AssertionError(
            f"sim_validation: {key} promised {m} but observed {value}"
            + (f" ({context})" if context else "")
        )
