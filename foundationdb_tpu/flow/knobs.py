"""Knobs: flat name -> typed config registry; ref flow/Knobs.h:31.

The reference registers ~433 knobs across FlowKnobs (flow/Knobs.cpp),
ClientKnobs (fdbclient/Knobs.cpp) and ServerKnobs (fdbserver/Knobs.cpp),
overridable via --knob_name=value.  We keep the three-class split and the
string-keyed override API; only knobs the rebuild actually consults are
declared (grown as subsystems land).
"""

from __future__ import annotations

import os


class Knobs:
    """Attribute-style knobs with string override (set_knob("name", "1.5"))."""

    def __init__(self):
        self._names: dict[str, type] = {}

    def _init(self, name: str, default):
        setattr(self, name, default)
        self._names[name.lower()] = type(default)

    def set_knob(self, name: str, value: str):
        key = name.lower()
        if key not in self._names:
            raise KeyError(f"unknown knob {name}")
        ty = self._names[key]
        if ty is bool:
            parsed = value.lower() in ("1", "true", "yes")
        else:
            parsed = ty(value)
        setattr(self, key, parsed)

    def all(self) -> dict:
        return {k: getattr(self, k) for k in self._names}


class FlowKnobs(Knobs):
    def __init__(self):
        super().__init__()
        # ref flow/Knobs.cpp — delays and buggification
        self._init("min_delay_cpu_effects", 0.001)
        self._init("max_buggified_delay", 0.2)
        self._init("buggify_activated_probability", 0.25)
        self._init("buggify_fired_probability", 0.25)
        self._init("slowtask_profiling_interval", 0.125)


class ClientKnobs(Knobs):
    def __init__(self):
        super().__init__()
        # ref fdbclient/Knobs.cpp
        self._init("default_transaction_timeout", 0.0)  # unlimited, like ref
        self._init("max_retry_delay", 1.0)
        # commit_unknown_result fence: attempts before surfacing the
        # unknown result unfenced (ref: commitDummyTransaction's retry loop,
        # NativeAPI.actor.cpp:2315).
        self._init("dummy_commit_max_retries", 120)
        self._init("initial_retry_delay", 0.01)
        self._init("grv_batch_interval", 0.005)  # MAX_BATCH_INTERVAL
        self._init("grv_max_batch_size", 1024)
        # Probability a transaction carries a debug id through the commit /
        # GRV pipelines (ref: CLIENT_KNOBS latency-sample rates feeding
        # g_traceBatch); tests raise it to 1.0.
        self._init("latency_sample_rate", 0.01)
        self._init("location_cache_size", 300000)
        self._init("key_size_limit", 10000)
        self._init("value_size_limit", 100000)
        self._init("transaction_size_limit", 10 * 1024 * 1024)


class ServerKnobs(Knobs):
    def __init__(self):
        super().__init__()
        # ref fdbserver/Knobs.cpp
        self._init("commit_transaction_batch_interval", 0.002)
        self._init("commit_transaction_batch_count_max", 32768)
        self._init("max_write_transaction_life_versions", 5_000_000)
        self._init("versions_per_second", 1_000_000)
        self._init("max_versions_in_flight", 100_000_000)
        self._init("storage_durability_lag", 0.05)
        self._init("tlog_spill_threshold", 1 << 30)
        self._init("resolver_state_memory_limit", 1 << 30)
        # TPU conflict engine knobs (new to the rebuild)
        self._init("conflict_device_min_batch", 256)  # below: CPU fallback
        self._init("conflict_device_key_words", 4)  # uint32 words per key
        self._init("conflict_max_device_key_bytes", 16)  # > this: CPU fallback
        self._init("conflict_history_capacity", 1 << 20)
        self._init("max_watches", 10000)  # ref: MAX_STORAGE_SERVER_WATCHES
        self._init("fetch_shard_page_rows", 5000)  # ref: FETCH_BLOCK_BYTES analog
        # Replication (ref: DatabaseConfiguration tLogReplicationFactor /
        # storageTeamSize; clamped to the available process count)
        self._init("log_replication_factor", 2)
        self._init("storage_team_size", 2)
        # How long recovery waits for a manifest machine to return before
        # declaring it lost and recovering from the surviving replicas
        # (possible only while the lost-count stays under the replication
        # factor; ref: the required/desired TLog policy satisfaction wait in
        # epochEnd, TagPartitionedLogSystem.actor.cpp).
        self._init("recovery_missing_machine_grace", 4.0)
        # Idle proxies still cut empty commit batches at this cadence so
        # they receive other proxies' state transactions from the resolvers
        # and the resolver's retention GC advances (ref: the
        # COMMIT_TRANSACTION_BATCH_INTERVAL_MIN empty-batch tick in
        # MasterProxyServer.actor.cpp commitBatcher).
        self._init("commit_batch_idle_interval", 0.25)
        # Storage read stall bound (ref: FUTURE_VERSION_DELAY — waitForVersion
        # throws future_version after this rather than parking forever on a
        # stalled log stream).
        self._init("future_version_delay", 1.0)
        # Fresh-cluster recruitment waits for worker registrations to stop
        # arriving for this long before choosing disk homes.
        self._init("recruitment_stabilize_window", 0.75)
        # Ratekeeper (ref: Ratekeeper.actor.cpp knobs, distilled).  Byte
        # targets are sim-scaled versions of TARGET_BYTES_PER_STORAGE_SERVER
        # / SPRING_BYTES_STORAGE_SERVER (:251-340) and the TLog equivalents.
        self._init("ratekeeper_max_tps", 100000.0)
        self._init("ratekeeper_min_tps", 10.0)
        self._init("ratekeeper_target_lag_versions", 500_000)
        self._init("ratekeeper_spring_lag_versions", 2_000_000)
        self._init("ratekeeper_target_ss_queue_bytes", 4 << 20)
        self._init("ratekeeper_spring_ss_queue_bytes", 2 << 20)
        self._init("ratekeeper_target_tlog_queue_bytes", 8 << 20)
        self._init("ratekeeper_spring_tlog_queue_bytes", 4 << 20)
        # Disk-free spring (ref: MIN_FREE_SPACE / MIN_FREE_SPACE_RATIO):
        # below target free bytes the rate compresses; at min it floors.
        self._init("ratekeeper_min_free_bytes", 4 << 20)
        self._init("ratekeeper_target_free_bytes", 16 << 20)
        # Simulated disk capacity per machine (the sim has no real device).
        self._init("sim_disk_capacity_bytes", 1 << 30)
        # Batch-priority lane: same springs at this fraction of the targets
        # (ref: the separate batch limiter with lower TARGET_BYTES_*_BATCH).
        self._init("ratekeeper_batch_target_fraction", 0.5)
        # Overload-aware springs (ISSUE 8): the stack's actual bottleneck is
        # the resolver/TPU conflict path, which the reference's SS/TLog-only
        # signals never see.  Queue depth counts resolve batches in flight
        # or parked on the prevVersion chain; latency targets are virtual
        # seconds from the resolver's resolve_seconds window and the
        # latency_chain commit totals.
        self._init("ratekeeper_target_resolver_queue", 8)
        self._init("ratekeeper_spring_resolver_queue", 16)
        self._init("ratekeeper_target_resolve_p99", 0.25)
        self._init("ratekeeper_spring_resolve_p99", 0.5)
        self._init("ratekeeper_target_commit_p99", 0.5)
        self._init("ratekeeper_spring_commit_p99", 1.0)
        # Degraded device backend (PR-3 breaker open / CPU takeover): the
        # TPS limit contracts to this fraction of max so the GRV lane stops
        # piling requests onto the CPU mirror.  With
        # ratekeeper_use_measured_cpu_tps (real deployments; wall-clock
        # derived, so OFF in sim where rate decisions must replay from the
        # seed) the cap additionally clamps to 80% of the measured
        # CPU-mirror throughput from ConflictSet.backend_signal().
        self._init("ratekeeper_degraded_tps_fraction", 0.25)
        self._init("ratekeeper_use_measured_cpu_tps", False)
        # Proxy-side GRV admission queue bound: beyond this many queued
        # read-version requests the proxy SHEDS deterministically instead
        # of queueing without limit — the batch-priority lane starves first
        # (batch_transaction_throttled), then the default lane
        # (proxy_memory_limit_exceeded); both are retryable, and clients
        # back off exponentially with DeterministicRandom jitter (ref: the
        # proxy memory-limit rejection in transactionStarter).
        self._init("ratekeeper_grv_queue_max", 2048)
        # Self-driving DataDistribution (ref: DataDistribution.actor.cpp
        # teamTracker + DataDistributionTracker cadences + the queue's
        # RELOCATION_PARALLELISM_PER_SOURCE_SERVER; byte thresholds are
        # sim-scaled versions of SHARD_MAX_BYTES / SHARD_MIN_BYTES).
        self._init("dd_ping_interval", 0.5)
        self._init("dd_ping_timeout", 0.4)
        self._init("dd_failure_detections", 4)  # consecutive misses
        self._init("dd_tracker_interval", 2.0)
        self._init("dd_move_parallelism", 2)
        self._init("dd_shard_max_bytes", 1 << 20)
        self._init("dd_shard_min_bytes", 16 << 10)
        # TimeKeeper (ref: ServerKnobs TIME_KEEPER_DELAY=10 /
        # TIME_KEEPER_MAX_ENTRIES=3600*24*30/10; sim-scaled): the CC's
        # wall-clock->version sample cadence and retained history bound.
        self._init("time_keeper_delay", 2.0)
        self._init("time_keeper_max_entries", 4096)
        # Pipelined resolver (ISSUE 11): how long a dispatched batch may
        # stay parked (virtual seconds) waiting for a successor to push it
        # out of the double buffer before the owner drains it itself — the
        # idle-tail flush that bounds reply latency when traffic pauses.
        # Sized a little above commit_transaction_batch_interval so steady
        # proxy traffic keeps the pipeline occupied across arrivals.
        self._init("resolver_pipeline_flush_seconds", 0.005)
        # Consecutive flush-drained (host-stalled) batches before the
        # resolver freezes a flight-recorder artifact: a pipeline that is
        # ON but achieving zero overlap for this many batches in a row is
        # a perf incident worth a black box (cooldown-gated per resolver).
        self._init("resolver_pipeline_stall_batches", 12)
        # Contention explorer (ISSUE 17).  The contended-range sample
        # decays by halving once per this many CONFLICT-bearing batches —
        # batch-driven, never time-driven, so a quiescent cluster's top-K
        # holds steady between soak phases instead of silently emptying.
        self._init("resolver_witness_decay_batches", 64)
        # Per-batch abort-timeline ring length: the per-range contention
        # history `cli contention` joins against span rings and the
        # decayed top-K.
        self._init("resolver_contention_ring", 128)
        # Sustained-contention flight recorder: freeze a black box once
        # the abort fraction stays at or above the ratio for this many
        # consecutive batches (cooldown-gated per resolver, like the
        # pipeline-stall trigger).
        self._init("resolver_contention_spike_ratio", 0.5)
        self._init("resolver_contention_spike_batches", 8)


class KnobSet:
    def __init__(self):
        self.flow = FlowKnobs()
        self.client = ClientKnobs()
        self.server = ServerKnobs()

    def set_knob(self, name: str, value: str):
        for k in (self.flow, self.client, self.server):
            try:
                k.set_knob(name, value)
                return
            except KeyError:
                continue
        raise KeyError(f"unknown knob {name}")


g_knobs = KnobSet()


class EnvFlags:
    """FDB_TPU_* process-environment flags, the registry ENV001 enforces.

    Unlike knobs (typed runtime config, overridable per test), env flags
    select process-wide BUILD/ENGINE variants read at import or
    engine-construction time (codec backend, search strategy, history
    layout).  Scattered ``os.environ.get("FDB_TPU_...")`` reads are how
    config drift happens — a flag renamed in one module keeps silently
    defaulting in another — so every flag is declared here once, with its
    default and meaning, and every read goes through ``g_env``; fdblint's
    ENV001 rejects FDB_TPU_* environment reads anywhere else.

    ``g_env.get()`` consults ``os.environ`` at CALL time; whether a flag
    is live or frozen is decided by where its one call site sits, exactly
    as the raw read it replaced: the engine flags (``FDB_TPU_HISTORY``,
    ``FDB_TPU_DELTA_CAP``, ``FDB_TPU_EVICT_EVERY``, ``FDB_TPU_ABLATE``)
    are read at engine construction, so monkeypatching the environment
    before building an engine works — while ``FDB_TPU_WIRE_PY``
    (rpc/wire.py) and ``FDB_TPU_SEARCH``/``FDB_TPU_SEARCH_STRIDE``
    (ops/rangequery.py) are module-level process configuration frozen at
    first import; override those before the module loads (subprocess
    env, as tests/test_engine_experiments.py does)."""

    def __init__(self):
        self._decl: dict[str, tuple[str, str]] = {}

    def declare(self, name: str, default: str = "", help: str = ""):
        if not name.startswith("FDB_TPU_"):
            raise ValueError(f"env flags are FDB_TPU_*-namespaced: {name}")
        self._decl[name] = (default, help)

    def get(self, name: str) -> str:
        """Current value (environment over declared default).  Undeclared
        names raise: an ad-hoc flag must be registered first."""
        if name not in self._decl:
            raise KeyError(f"undeclared env flag {name} (declare it here)")
        return os.environ.get(name, self._decl[name][0])

    def get_int(self, name: str) -> int:
        return int(self.get(name))

    def override(self, name: str, value):
        """Set (str) or clear (None) a DECLARED flag in the process
        environment — the harness-side twin of get(), for A/B arms that
        toggle a live flag between same-process runs (e.g. the soak's
        witness-guided vs blind retry comparison).  Returns the previous
        raw environment value (None = was unset) so callers can restore.
        Lives here so ENV001 keeps every environment access in this
        module; only meaningful for flags read at CALL time (see the
        class docstring's live-vs-frozen discussion)."""
        if name not in self._decl:
            raise KeyError(f"undeclared env flag {name} (declare it here)")
        prev = os.environ.get(name)
        if value is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = str(value)
        return prev

    def declared(self) -> dict:
        """name -> (default, help) for docs/status enumeration."""
        return dict(self._decl)


g_env = EnvFlags()
g_env.declare("FDB_TPU_WIRE_PY", "",
              help="truthy: force the pure-Python wire codec (A/B baselines, "
                   "debugging); default uses the C codec when loadable")
g_env.declare("FDB_TPU_SEARCH", "",
              help="rangequery search strategy: '' flat binary search, "
                   "'2level' coarse sampled-table bracket then fine steps")
g_env.declare("FDB_TPU_SEARCH_STRIDE", "512",
              help="2level search: columns per coarse-table sample")
g_env.declare("FDB_TPU_ABLATE", "",
              help="comma list of conflict-kernel ablations (perf "
                   "experiments; engine asserts the combination is legal)")
g_env.declare("FDB_TPU_HISTORY", "",
              help="conflict-history layout: '' flat, 'tiered' frozen base "
                   "+ delta tier with major compactions (PR 4)")
g_env.declare("FDB_TPU_DELTA_CAP", "0",
              help="tiered history: delta-tier capacity (0 = h_cap/8)")
g_env.declare("FDB_TPU_EVICT_EVERY", "1",
              help="evict cadence in batches; in tiered mode the alias "
                   "for major-compaction cadence")
# Pallas fused kernels (ISSUE 14, ROADMAP item 1): the merge/evict
# compaction and phase-1 search hot paths as streaming TPU kernels.
g_env.declare("FDB_TPU_KERNELS", "",
              help="Pallas kernel routing for the conflict step's hot "
                   "phases (merge/evict compaction + phase-1 search): "
                   "''/'auto' kernels on the TPU backend only, '1' "
                   "kernels everywhere (interpret-mode Pallas off-TPU — "
                   "the CPU differential-gating arm), 'interpret' force "
                   "the interpreter even on TPU, '0' XLA fallback "
                   "everywhere (the A/B arm).  Decision-identical in "
                   "every mode (tests/test_kernels.py)")
# Abort-witness provenance (ISSUE 17): per-txn (conflicting version,
# losing read range) from device phase-1 to the client retry hint.
g_env.declare("FDB_TPU_WITNESS", "1",
              help="emit per-transaction abort witnesses (conflicting "
                   "write version + losing read-range ordinal) from the "
                   "conflict engines: a static jit arg, so '0' restores "
                   "the witness-free device program byte-for-byte.  "
                   "Witnesses are bit-identical across the XLA/Pallas "
                   "arms, the CPU mirror, and the sharded step "
                   "(tests/test_witness.py differential gate)")
g_env.declare("FDB_TPU_WITNESS_RETRY", "1",
              help="client-side witness-guided retry: on a structured "
                   "not_committed cause, Transaction.on_error seeds the "
                   "next attempt's read version at the witnessed "
                   "conflicting version instead of paying a fresh GRV "
                   "round-trip.  '0' = blind retry (the A/B soak arm)")
g_env.declare("FDB_TPU_H_CAP", "0",
              help="device history capacity override, in rows, for any "
                   "ConflictSet constructed WITHOUT an explicit h_cap "
                   "(0 = each caller's built-in default: 65536 for "
                   "api.ConflictSet, 3145728 for bench.py's device "
                   "arms = 2.87M live boundaries at window 50 + ~10% "
                   "headroom — PERF_NOTES lever 2).  Setting it "
                   "applies to EVERY such set in the process, sim "
                   "resolvers included — size accordingly.  Values are "
                   "rounded UP to a 256-row multiple (the Pallas "
                   "kernels' tile; api.env_h_cap).  Always safe to "
                   "drop: the engine's must-fit guard syncs and grows "
                   "when a live set outruns the cap, never truncates")
g_env.declare("FDB_TPU_JAXCHECK_DIR", "",  # fdblint: ignore[ENV002]: read by the jaxcheck pass itself (tools/lint/jaxir.py), which the scan skips as linter-internal
              help="jaxcheck fingerprint baseline directory override "
                   "(default: tests/jax_fingerprints next to the package)")
# Batch-update snapshot mirror (ISSUE 9): the chunked CPU engine behind
# the device circuit breaker and its live consistency check.
g_env.declare("FDB_TPU_MIRROR_ENGINE", "",
              help="CPU mirror engine: '' chunked batch-update snapshot "
                   "engine (engine_cpu), 'flat' the pre-ISSUE-9 flat "
                   "array (engine_cpu_flat; A/B arm + escape hatch) — "
                   "decision- and state-identical by differential gate")
g_env.declare("FDB_TPU_MIRROR_CHUNK", "256",
              help="target boundaries per immutable mirror chunk (the "
                   "batch-update node size; smaller = finer copy-on-write "
                   "granularity, more chunk overhead)")
g_env.declare("FDB_TPU_MIRROR_COALESCE", "0",
              help="coalesce committed-write mirror folds: accumulate "
                   "per-batch unions and replay them into the chunked "
                   "mirror once per K batches ('auto' ties K to "
                   "FDB_TPU_PIPELINE_DEPTH; 0/1 applies per batch). "
                   "Every mirror read settles pending folds first, so "
                   "reads stay bit-exact and same-seed replay is "
                   "byte-identical")
g_env.declare("FDB_TPU_ENCODE_STAGING", "auto",
              help="reusable packed-blob staging ring in the batch "
                   "encoder: 'auto' sizes the per-blob-length ring to "
                   "pipeline depth + 1 (so encoding batch N+1 never "
                   "aliases batch N's in-flight blob), an integer "
                   "forces the ring size, 0 disables reuse (fresh "
                   "allocation per dispatch)")
g_env.declare("FDB_TPU_MIRROR_CHECK_SECONDS", "10",
              help="period of the resolver's mirror consistency-check "
                   "actor (virtual seconds in sim): diffs a live mirror "
                   "snapshot against the device export and opens the "
                   "breaker on confirmed divergence; 0 disables")
g_env.declare("FDB_TPU_SHARD_BALANCE_SECONDS", "0",
              help="period of the resolver's shard-balancer actor "
                   "(virtual seconds in sim): evaluates per-shard "
                   "occupancy + contention skew and migrates split "
                   "points live (ShardBalancer over "
                   "ShardedJaxConflictSet.reshard); 0 disables")
# Soak-harness defaults (workloads/soak.py via `cli soak` and the
# slow-marked soak test).  CLI arguments override these; the env flags
# exist so CI/bench drivers can retune the soak without editing argv.
g_env.declare("FDB_TPU_SOAK_MINUTES", "1",
              help="soak length in SIM minutes (virtual time) for the "
                   "slow soak test and the cli soak default; raise for "
                   "bench-driver runs (1 sim-minute of a dynamic-cluster "
                   "jax soak costs ~5 real minutes on the 1-core CI host)")
g_env.declare("FDB_TPU_SOAK_SEED", "1",
              help="soak DeterministicRandom seed (same seed => "
                   "byte-identical ratekeeper/breaker transition logs)")
g_env.declare("FDB_TPU_SOAK_TPS", "80",
              help="open-loop arrival rate (txn/s of virtual time) at the "
                   "soak's peak phase; ramp phases scale from it")
g_env.declare("FDB_TPU_SOAK_KEYS", "512",
              help="distinct keys in the soak keyspace (Zipf-skewed)")
g_env.declare("FDB_TPU_SOAK_THETA", "0.9",
              help="Zipf skew exponent for soak keys (0 = uniform)")
g_env.declare("FDB_TPU_SOAK_BACKEND", "jax",
              help="conflict backend for the soak cluster resolvers "
                   "(cpu|jax|hybrid; device-outage faults need jax/hybrid)")
# Time-series telemetry + flight recorder (ISSUE 10): bounded-memory
# history behind the point-in-time metrics/status surfaces.
g_env.declare("FDB_TPU_TIMESERIES", "1",
              help="0 disables the per-role time-series sampler actors "
                   "(flow/timeseries.py); default on — the sampler is "
                   "read-only and virtual-time paced")
g_env.declare("FDB_TPU_TIMESERIES_INTERVAL", "1.0",
              help="time-series sample cadence in VIRTUAL seconds")
g_env.declare("FDB_TPU_TIMESERIES_WINDOW", "240",
              help="samples retained per role series (ring buffer "
                   "maxlen; 240 x 1s = a 4-sim-minute window)")
g_env.declare("FDB_TPU_TRACE_RECENT", "512",
              help="TraceCollector recent-events ring bound (most recent "
                   "N emitted events kept in memory in BOTH collector "
                   "modes; what find() searches on a file-backed "
                   "collector and the flight recorder dumps)")
g_env.declare("FDB_TPU_FLIGHTREC", "1",
              help="0 disables flight-recorder trigger captures "
                   "(flow/flight_recorder.py); explicit capture() calls "
                   "still work")
g_env.declare("FDB_TPU_FLIGHTREC_CAPTURES", "16",
              help="captured artifacts retained (ring buffer maxlen)")
g_env.declare("FDB_TPU_FLIGHTREC_COOLDOWN", "5.0",
              help="min VIRTUAL seconds between trigger captures of the "
                   "same kind (a flapping ratekeeper signal must not "
                   "churn the capture ring); explicit capture() ignores it")
g_env.declare("FDB_TPU_FLIGHTREC_WINDOW", "64",
              help="time-series samples and trace events included per "
                   "capture (the last-N window of each)")
# Commit-path span tracing (ISSUE 12): structured begin/end intervals
# over client GRV/commit, proxy batch assembly, resolver pipeline
# stages, tlog push — flow/spans.py + the Perfetto export
# (flow/trace_export.py, `cli trace-export`).
g_env.declare("FDB_TPU_SPANS", "1",
              help="0 disables commit-path span recording "
                   "(flow/spans.py); default on — spans observe virtual "
                   "time and a monotonic event counter only, never the "
                   "loop rng, so recording perturbs no sim decision")
g_env.declare("FDB_TPU_SPANS_PER_ROLE", "4096",
              help="completed spans retained per role track (bounded "
                   "ring maxlen on the global SpanHub); the Perfetto "
                   "export, flight-recorder span windows, and `cli "
                   "latency` stage percentiles all read this ring")
# Double-buffered async resolver pipeline (ISSUE 11): overlap the host
# phases (mirror apply of batch N-1, pack/encode of batch N+1) with
# device compute of batch N.
g_env.declare("FDB_TPU_DONATE", "",
              help="carried-buffer donation in the conflict step "
                   "programs: '' auto (donate everywhere except the CPU "
                   "backend, whose runtime executes donated programs "
                   "synchronously and would serialize the pipeline's "
                   "dispatch), '1' force donation, '0' force the "
                   "non-donated twins.  Decision-identical either way; "
                   "the jaxcheck donation audit + fingerprints pin the "
                   "DEVICE_ENTRY_POINTS (donated) wrappers regardless")
g_env.declare("FDB_TPU_PIPELINE_DEPTH", "2",
              help="resolver pipeline depth: max batches dispatched to "
                   "the device and not yet synced.  1 = today's fully "
                   "synchronous resolve path; 2 (default) = double "
                   "buffering — while the device resolves batch N the "
                   "host applies batch N-1's verdicts to the mirror and "
                   "encodes batch N+1.  Verdict streams are bit-identical "
                   "across depths (the device history advances in commit "
                   "order either way; only host-side work is deferred)")
g_env.declare("FDB_TPU_TRANSFER_GUARD", "",
              help="truthy: arm the dispatch->sync transfer guard "
                   "(HOT001's dynamic twin, ISSUE 20).  DispatchTicket "
                   "device fields are wrapped in GuardedDeviceValue "
                   "proxies (flow/hotpath.py) that raise "
                   "TransferGuardError on any implicit device->host "
                   "materialization outside the sanctioned sync points "
                   "(sync_ticket / store_to / breaker replay), and the "
                   "pipelined dispatch additionally runs under "
                   "jax.transfer_guard_device_to_host('disallow') for "
                   "real accelerators.  The guard only ever raises or "
                   "is a no-op, so same-seed replay is byte-identical "
                   "with it on")
g_env.declare("FDB_TPU_PROGRAM_COSTS", "",
              help="truthy: device_metrics()/status tpu eagerly compile "
                   "+ cost-account every DEVICE_ENTRY_POINTS program "
                   "(engine_jax.program_cost_table; ~15s of XLA compile "
                   "on first call, cached).  Default lazy: the programs "
                   "block appears once the table has been computed "
                   "(tools/perf_experiments.py --programs, tests)")
g_env.declare("FDB_TPU_STATE_SANITIZER", "",
              help="truthy: flow.state_sanitizer audits shared dicts — "
                   "every keyed read/write recorded as (task, "
                   "await-epoch) — and expect_clean_shared_state raises "
                   "at sim shutdown on any stale-read→write pair (a "
                   "lost update that actually interleaved).  The "
                   "test-only dynamic twin of fdblint RACE001-004; off "
                   "by default, audited_dict() degrades to a plain dict")
g_env.declare("FDB_TPU_SCHED_FUZZ", "",
              help="integer: perturb the event loop's pick order among "
                   "equal-(time, priority) heap entries with draws from "
                   "a DeterministicRandom forked from (seed, fuzz) — "
                   "the orderings the scheduling contract leaves "
                   "unspecified.  Same (seed, fuzz) replays "
                   "byte-identically; different fuzz values explore "
                   "different LEGAL interleavings (the "
                   "scheduler-perturbation replay gate, ref sim2/"
                   "BUGGIFY task jitter).  '' = stable FIFO tie-break")
g_env.declare("FDB_TPU_CHECK_ORPHANED_WAITS", "",
              help="truthy: sim_validation.expect_no_orphaned_waits "
                   "asserts at sim shutdown that no task is still parked "
                   "on a future whose paired Promise was dropped (zero "
                   "remaining senders) — the test-only dynamic twin of "
                   "fdblint PRM001/PRM002.  Requires "
                   "flow.future.track_promise_refs(True) before the "
                   "scenario builds its promises")
