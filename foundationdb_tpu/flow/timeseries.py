"""Deterministic time-series telemetry: bounded ring-buffer history of
each role's MetricsRegistry (ISSUE 10 tentpole, layer 2 of 3).

The reference ships always-on trace spooling precisely so incidents are
diagnosable after the fact; our point-in-time surfaces (metrics/status,
ISSUE 2) answer "what is the counter NOW" but not "what was it doing in
the thirty seconds before the breaker opened".  This module closes that
gap without unbounded memory: each role's registry is sampled on a
virtual-time cadence into a fixed-size ring of DELTAS —

    sample = {time, counters: {name: delta since last sample},
              gauges: {name: value},
              histograms: {name: {count/sum deltas + current quantiles}}}

Determinism contract (inherited from MetricsRegistry.snapshot): samples
observe only virtual time and registry state, so two same-seed runs
produce byte-identical `window_json()` output — the property the flight
recorder's artifact gate pins.  Wall-namespace measurements
(`record_wall`) are never sampled.

Wiring: resolver/proxy/ratekeeper spawn `sample_loop` actors at
construction (behind the FDB_TPU_TIMESERIES_* g_env knobs); the actors
write into the process-global `TimeSeriesHub` (swap it per run with
`set_global_timeseries`, exactly like the global trace collector).  A
series keyed by a name resets whenever a DIFFERENT registry object
starts reporting under that name (a re-recruited generation's fresh role
must not produce negative deltas against its predecessor's totals).
"""

from __future__ import annotations

import json
from collections import deque
from typing import Dict, List, Optional

from .knobs import g_env

# Histogram quantile keys carried per sample when the registry's
# histograms have an rng-backed reservoir (see BoundedHistogram.summary).
_QUANTILES = ("median", "p90", "p99")


def snapshot_delta(prev: Optional[dict], cur: dict) -> dict:
    """Delta between two MetricsRegistry.snapshot() dicts: counter value
    deltas, histogram count/sum deltas (+ the CURRENT quantiles — a
    reservoir has no subtractable form), gauges as-is.  `prev=None`
    means "no baseline": every delta is the current total.  Shared by
    the sampler and `cli metrics --diff` so the two can never disagree
    about what a delta is."""
    pc = prev.get("counters", {}) if prev else {}
    ph = prev.get("histograms", {}) if prev else {}
    out: dict = {
        "counters": {
            k: v - pc.get(k, 0) for k, v in cur.get("counters", {}).items()
        },
        "gauges": dict(cur.get("gauges", {})),
        "histograms": {},
    }
    for k, h in cur.get("histograms", {}).items():
        p = ph.get(k, {})
        d = {
            "count": h["count"] - p.get("count", 0),
            "sum": h["sum"] - p.get("sum", 0.0),
        }
        for q in _QUANTILES:
            if q in h:
                d[q] = h[q]
        out["histograms"][k] = d
    return out


class TimeSeries:
    """One role's bounded sample history + the previous-snapshot baseline
    the next delta is computed against."""

    __slots__ = ("name", "samples", "_prev", "_source", "resets")

    def __init__(self, name: str, window: int):
        self.name = name
        self.samples: deque = deque(maxlen=window)
        self._prev: Optional[dict] = None
        # The registry object the baseline belongs to — held as a STRONG
        # reference (one registry per series name, trivial memory): an
        # `id()` comparison would miss the reset when the predecessor is
        # garbage-collected and CPython reuses its address, producing
        # negative deltas against a dead generation's totals.
        self._source = None
        self.resets = 0  # source-object changes observed (diagnostic)

    def record(self, registry, now: Optional[float]) -> dict:
        if self._source is not None and self._source is not registry:
            # A different registry object took this name (re-recruit, or a
            # second cluster in one process): restart the delta baseline.
            self.samples.clear()
            self._prev = None
            self.resets += 1
        self._source = registry
        snap = registry.snapshot(now=now)
        sample = snapshot_delta(self._prev, snap)
        sample["time"] = snap.get("time")
        self._prev = snap
        self.samples.append(sample)
        return sample


class TimeSeriesHub:
    """name -> TimeSeries, the process-global collection point (swap per
    run like the global trace collector)."""

    def __init__(self, window: Optional[int] = None):
        self.window = (
            window
            if window is not None
            else max(2, g_env.get_int("FDB_TPU_TIMESERIES_WINDOW"))
        )
        self.series: Dict[str, TimeSeries] = {}

    def record(self, name: str, registry, now: Optional[float] = None) -> dict:
        ts = self.series.get(name)
        if ts is None:
            ts = self.series[name] = TimeSeries(name, self.window)
        return ts.record(registry, now)

    def window_dict(self, last_n: Optional[int] = None) -> dict:
        """name -> [sample, ...] (oldest first), optionally only the last
        N samples of each series — the flight recorder's capture shape."""
        out: Dict[str, List[dict]] = {}
        for name in sorted(self.series):
            samples = list(self.series[name].samples)
            if last_n is not None:
                samples = samples[-last_n:]
            out[name] = samples
        return out

    def window_json(self, last_n: Optional[int] = None) -> str:
        """Canonical byte form — what the same-seed determinism gate
        compares."""
        return json.dumps(
            self.window_dict(last_n=last_n),
            sort_keys=True,
            separators=(",", ":"),
        )

    def clear(self):
        self.series.clear()


_global_hub = TimeSeriesHub()


def set_global_timeseries(hub: TimeSeriesHub):
    global _global_hub
    _global_hub = hub


def global_timeseries() -> TimeSeriesHub:
    return _global_hub


def timeseries_enabled() -> bool:
    return g_env.get("FDB_TPU_TIMESERIES") not in ("", "0")


async def sample_loop(name: str, registry, process):
    """Periodic sampler actor: one delta sample of `registry` into the
    CURRENT global hub per FDB_TPU_TIMESERIES_INTERVAL virtual seconds.
    Read-only and rng-free, so spawning it perturbs no sim decision; it
    re-reads the global hub each tick so a harness that swaps in a fresh
    hub (soak, tests) starts collecting immediately."""
    loop = process.network.loop
    interval = max(0.05, float(g_env.get("FDB_TPU_TIMESERIES_INTERVAL")))
    while True:
        await loop.delay(interval)
        global_timeseries().record(name, registry, now=loop.now())


def spawn_sampler(process, name: str, registry):
    """Spawn the sampler actor for one role registry unless disabled by
    FDB_TPU_TIMESERIES=0.  Returns the task (or None when disabled)."""
    if not timeseries_enabled():
        return None
    return process.spawn(sample_loop(name, registry, process), f"ts:{name}")
