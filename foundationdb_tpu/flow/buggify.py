"""BUGGIFY: randomized rare-path activation, simulation only.

Ref: flow/flow.h:50-67.  Each BUGGIFY call site is independently "activated"
with probability 0.25 the first time it is evaluated in a simulation run;
an activated site then fires with probability 0.25 per evaluation.  Sites
are keyed by an explicit name (the reference keys by __FILE__:__LINE__).
"""

from __future__ import annotations

from typing import Optional

from .knobs import g_knobs
from .rng import DeterministicRandom

_enabled = False
_rng: Optional[DeterministicRandom] = None
_site_activated: dict[str, bool] = {}
fired_sites: set[str] = set()


def set_buggify_enabled(enabled: bool, rng: Optional[DeterministicRandom] = None):
    global _enabled, _rng
    _enabled = enabled
    _rng = rng
    _site_activated.clear()
    fired_sites.clear()


def buggify(site: str) -> bool:
    """True randomly, only when buggification is on (i.e. in simulation)."""
    if not _enabled or _rng is None:
        return False
    if site not in _site_activated:
        _site_activated[site] = (
            _rng.random01() < g_knobs.flow.buggify_activated_probability
        )
    if not _site_activated[site]:
        return False
    fired = _rng.random01() < g_knobs.flow.buggify_fired_probability
    if fired:
        fired_sites.add(site)
    return fired
