"""BUGGIFY: randomized rare-path activation, simulation only.

Ref: flow/flow.h:50-67.  Each BUGGIFY call site is independently "activated"
with probability 0.25 the first time it is evaluated in a simulation run;
an activated site then fires with probability 0.25 per evaluation
(``BUGGIFY_WITH_PROB`` lets the caller pick the per-evaluation
probability).  Sites are keyed by an explicit name (the reference keys by
__FILE__:__LINE__).

Coverage accounting: every activation decision and fire is counted, so a
chaos run can report WHICH fault sites its seed actually exercised
(``publish_coverage`` folds the counts into a MetricsRegistry at sim end
— a run that never fired its device-fault sites proved nothing about the
degraded path).
"""

from __future__ import annotations

from typing import Dict, Optional

from .knobs import g_knobs
from .rng import DeterministicRandom

_enabled = False
_rng: Optional[DeterministicRandom] = None
_site_activated: dict[str, bool] = {}
fired_sites: set[str] = set()
fired_counts: Dict[str, int] = {}


def set_buggify_enabled(enabled: bool, rng: Optional[DeterministicRandom] = None):
    global _enabled, _rng
    _enabled = enabled
    _rng = rng
    _site_activated.clear()
    fired_sites.clear()
    fired_counts.clear()


def buggify_with_prob(site: str, p: float) -> bool:
    """BUGGIFY_WITH_PROB (ref flow.h:66): activated like any site, then
    fires with probability `p` per evaluation.  False outside simulation."""
    if not _enabled or _rng is None:
        return False
    if site not in _site_activated:
        _site_activated[site] = (
            _rng.random01() < g_knobs.flow.buggify_activated_probability
        )
    if not _site_activated[site]:
        return False
    fired = _rng.random01() < p
    if fired:
        fired_sites.add(site)
        fired_counts[site] = fired_counts.get(site, 0) + 1
    return fired


def buggify(site: str) -> bool:
    """True randomly, only when buggification is on (i.e. in simulation)."""
    return buggify_with_prob(site, g_knobs.flow.buggify_fired_probability)


def coverage() -> dict:
    """Point-in-time fault-site coverage: how many sites this run SAW,
    how many the seed activated, and per-site fire counts."""
    return {
        "sites_seen": len(_site_activated),
        "sites_activated": sum(1 for v in _site_activated.values() if v),
        "sites_fired": len(fired_sites),
        "fired_counts": dict(sorted(fired_counts.items())),
    }


def publish_coverage(registry) -> dict:
    """Fold the run's coverage into MetricsRegistry gauges (called at sim
    end, e.g. by run_workloads): chaos runs report which fault sites they
    exercised, and the deterministic snapshot carries it."""
    cov = coverage()
    registry.gauge("buggify_sites_seen").set(cov["sites_seen"])
    registry.gauge("buggify_sites_activated").set(cov["sites_activated"])
    registry.gauge("buggify_sites_fired").set(cov["sites_fired"])
    for site, n in cov["fired_counts"].items():
        registry.gauge(f"fired:{site}").set(n)
    return cov
