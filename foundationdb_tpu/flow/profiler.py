"""Sampling CPU profiler with a runtime toggle.

Ref: flow/Profiler.actor.cpp:99 (SIGPROF-driven PC sampling into an
output file, enabled/disabled at runtime :175) and the CpuProfiler
workload (fdbserver/workloads/CpuProfiler.actor.cpp) that toggles it over
RPC.  The rebuild samples Python stacks from a timer thread (the portable
analog of SIGPROF — signal-based itimers cannot interrupt C-level waits
in CPython any more reliably than a thread can observe them), aggregating
frame counts; the complementary slow-task profiler lives in the event
loop (eventloop.py).

Wall-clock based by design: profiling measures REAL execution cost, so it
is a real-mode tool; under simulation it still works (samples whatever
the interpreter is doing) but is excluded from determinism checks.
"""

from __future__ import annotations

import sys
import threading
from collections import Counter
from typing import Dict, List, Optional, Tuple


class SamplingProfiler:
    """Periodic whole-interpreter stack sampler.

    start()/stop() may be called repeatedly (the runtime toggle);
    report() aggregates by (function, file:line) like the reference's
    profile output keyed by PC."""

    def __init__(self, interval: float = 0.005, max_depth: int = 64):
        self.interval = interval
        self.max_depth = max_depth
        self.samples: Counter = Counter()  # leaf (func, file, line) -> hits
        self.stacks: Counter = Counter()  # full stack tuple -> hits
        self.total_samples = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._lock = threading.Lock()

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self):
        if self.running:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="sampling_profiler", daemon=True
        )
        self._thread.start()

    def stop(self):
        if not self.running:
            return
        self._stop.set()
        self._thread.join(timeout=2.0)
        self._thread = None

    def _run(self):
        main_id = threading.main_thread().ident
        own_id = threading.get_ident()
        while not self._stop.wait(self.interval):
            frames = sys._current_frames()
            frame = frames.get(main_id)
            if frame is None or own_id == main_id:
                continue
            stack: List[Tuple[str, str, int]] = []
            f = frame
            while f is not None and len(stack) < self.max_depth:
                code = f.f_code
                stack.append(
                    (code.co_name, code.co_filename, f.f_lineno)
                )
                f = f.f_back
            with self._lock:
                self.total_samples += 1
                if stack:
                    self.samples[stack[0]] += 1
                    self.stacks[tuple(stack)] += 1

    def clear(self):
        with self._lock:
            self.samples.clear()
            self.stacks.clear()
            self.total_samples = 0

    def report(self, top: int = 20) -> Dict:
        """Aggregated hot functions (leaf samples) + hottest stacks."""
        with self._lock:
            hot = [
                {
                    "function": fn,
                    "file": fi,
                    "line": ln,
                    "samples": n,
                    "fraction": n / max(1, self.total_samples),
                }
                for (fn, fi, ln), n in self.samples.most_common(top)
            ]
            return {
                "total_samples": self.total_samples,
                "interval": self.interval,
                "running": self.running,
                "hot_functions": hot,
            }


# Process-wide instance the runtime toggle drives (ref: the profiler
# being a per-process singleton enabled via ProfilerRequest).
_profiler: Optional[SamplingProfiler] = None


def get_profiler() -> SamplingProfiler:
    global _profiler
    if _profiler is None:
        _profiler = SamplingProfiler()
    return _profiler


def profiler_toggle(enabled: bool, interval: Optional[float] = None) -> dict:
    """The runtime toggle (ref: Profiler.actor.cpp:175 enableProfiler /
    ProfilerRequest handling in worker.actor.cpp)."""
    p = get_profiler()
    if interval is not None:
        p.interval = interval
    if enabled:
        p.start()
    else:
        p.stop()
    return {"running": p.running, "interval": p.interval}
