"""Structured commit-path spans: first-class begin/end intervals over the
whole commit pipeline (ISSUE 12 tentpole, the layer the reference's
CommitDebug/TransactionDebug trace-point chains approximate by joining
point events on debug ids after the fact).

A Span is (name, role, parent, start/end in loop-virtual time, a pair of
monotonic event-sequence stamps, attributes).  Roles are tracks — one per
instrumented role object (Resolver.res0, Proxyproxy0, TLog.tlog0,
client, ...) — and parent links make the per-batch stage structure
explicit: a resolver batch span owns its encode/dispatch/device/sync/
apply/reply children, and two overlapping device spans on one resolver
ARE the pipeline overlap ISSUE 11 built.

Two clocks, one discipline (the PR-2 `record_wall` split):

* ``start``/``stop`` are loop-virtual time and ``seq``/``end_seq`` are
  the hub's monotonic event counter — both deterministic, so same-seed
  runs produce byte-identical ``spans_json()`` (the acceptance gate).
  The seq pair matters because virtual time does not advance during
  synchronous host work: host-phase spans are vt-instantaneous, and the
  sequence counter is the interleaving clock that still shows batch
  N+1's encode running strictly inside batch N's device window.
* ``wall_start``/``wall_end`` are real perf_counter reads for real-mode
  timing (bench, perf_experiments).  They are EXCLUDED from
  ``to_dict()``/``spans_json()``/the Perfetto export by default — wall
  values in a sim-compared artifact would break byte identity.

Parenting uses an explicit argument OR the hub's current-span stack.
The stack is only valid across SYNCHRONOUS sections: ``with`` a span (or
``use_span``) around code that never awaits; a span that must outlive an
await (a proxy phase, a parked pipeline batch, the device in-flight
window) is held by reference and ``.end()``ed explicitly.  flowcheck's
SPN001 rejects a ``begin_span()`` result that is neither context-managed
nor ``.end()``ed nor stored (a leaked open span — TRC001's span-layer
mirror).

Completed spans land in a bounded per-role ring on the global SpanHub
(swap per run with ``set_global_span_hub``, exactly like the trace
collector and the time-series hub); open spans are never exported.
Span ids fork from the run's seed: the hub stamps the current loop's
DeterministicRandom seed (read, never drawn from — recording a span
must not perturb one sim decision) into the json header, and ids are
the hub's deterministic begin-order sequence.
"""

from __future__ import annotations

import json
from collections import deque
from typing import Dict, List, Optional

from .knobs import g_env
from .metrics import wall_now


def _vt_now() -> float:
    """Span timestamp: the current loop's virtual time; 0.0 without a
    loop (bench/tools — the seq counter and wall clocks carry timing
    there) so spans_json never contains a wall-derived stamp."""
    from .eventloop import _current_loop

    return _current_loop.now() if _current_loop is not None else 0.0


class Span:
    """One interval.  Begin via ``begin_span``/``span_hub().begin``; end
    via ``.end()`` or by using the span as a context manager (which also
    pushes it on the hub's current-span stack for child parenting)."""

    __slots__ = ("span_id", "parent_id", "name", "role", "start", "stop",
                 "seq", "end_seq", "attrs", "wall_start", "wall_end",
                 "_hub")

    def __init__(self, hub, span_id, parent_id, name, role, start, seq,
                 attrs):
        self._hub = hub
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.role = role
        self.start = start
        self.seq = seq
        self.stop = None
        self.end_seq = None
        self.attrs: dict = attrs if attrs is not None else {}
        self.wall_start = wall_now()
        self.wall_end = None

    @property
    def done(self) -> bool:
        return self.stop is not None

    def annotate(self, key: str, value) -> "Span":
        self.attrs[key] = value
        return self

    def end(self, attrs: Optional[dict] = None) -> None:
        """Close the span and commit it to the hub's per-role ring.
        Idempotent: the first end wins (a fault path and its cleanup may
        both try)."""
        if self.stop is not None:
            return
        if attrs:
            self.attrs.update(attrs)
        self._hub._finish(self)

    def to_dict(self, include_wall: bool = False) -> dict:
        out = {
            "id": self.span_id,
            "parent": self.parent_id,
            "name": self.name,
            "role": self.role,
            "start": self.start,
            "end": self.stop,
            "seq": self.seq,
            "end_seq": self.end_seq,
            "attrs": dict(self.attrs),
        }
        if include_wall:
            out["wall_start"] = self.wall_start
            out["wall_end"] = self.wall_end
        return out

    # -- context-manager form: push/pop the hub stack, end on exit -------
    def __enter__(self) -> "Span":
        self._hub._push(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        self._hub._pop(self)
        if exc is not None and "error" not in self.attrs:
            self.attrs["error"] = type(exc).__name__
        self.end()
        return False


class _NullSpan:
    """Inert stand-in returned while spans are disabled (FDB_TPU_SPANS=0)
    so call sites need no branches.  Shared singleton; every operation is
    a no-op."""

    __slots__ = ()
    span_id = None
    parent_id = None
    name = role = ""
    start = stop = None
    seq = end_seq = None
    wall_start = wall_end = None
    attrs: dict = {}
    done = True

    def annotate(self, key, value):
        return self

    def end(self, attrs=None):
        pass

    def to_dict(self, include_wall: bool = False) -> dict:
        return {}

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


NULL_SPAN = _NullSpan()


class SpanHub:
    """Per-role bounded rings of COMPLETED spans + the current-span stack
    + the monotonic event-sequence counter (the interleaving clock)."""

    def __init__(self, per_role: Optional[int] = None):
        self.per_role = (
            per_role
            if per_role is not None
            else max(16, g_env.get_int("FDB_TPU_SPANS_PER_ROLE"))
        )
        self.rings: Dict[str, deque] = {}
        self._stack: List[Span] = []
        self._seq = 0
        self.begun = 0  # lifetime spans begun (rings may have dropped)
        self.seed: Optional[int] = None  # stamped from the loop's rng

    # -- lifecycle -------------------------------------------------------
    def begin(self, name: str, role: Optional[str] = None,
              parent: Optional[Span] = None,
              attrs: Optional[dict] = None) -> Span:
        if self.seed is None:
            from .eventloop import _current_loop

            if _current_loop is not None:
                # READ the seed only — drawing from the rng here would
                # shift every downstream sim decision by one sample.
                self.seed = getattr(_current_loop.rng, "seed", None)
        if parent is None and self._stack:
            parent = self._stack[-1]
        if isinstance(parent, _NullSpan):
            parent = None
        if role is None:
            role = parent.role if parent is not None else "span"
        self._seq += 1
        self.begun += 1
        return Span(
            self, self.begun,
            parent.span_id if parent is not None else None,
            name, role, _vt_now(), self._seq, attrs,
        )

    def _finish(self, span: Span) -> None:
        self._seq += 1
        span.end_seq = self._seq
        span.stop = _vt_now()
        span.wall_end = wall_now()
        ring = self.rings.get(span.role)
        if ring is None:
            ring = self.rings[span.role] = deque(maxlen=self.per_role)
        ring.append(span)

    # -- current-span stack (synchronous sections ONLY) ------------------
    def _push(self, span: Span) -> None:
        self._stack.append(span)

    def _pop(self, span: Span) -> None:
        if self._stack and self._stack[-1] is span:
            self._stack.pop()
        elif span in self._stack:  # tolerate mismatched exits
            self._stack.remove(span)

    def current(self) -> Optional[Span]:
        return self._stack[-1] if self._stack else None

    # -- read surfaces ---------------------------------------------------
    def spans(self, role: Optional[str] = None,
              name: Optional[str] = None) -> List[Span]:
        """Completed spans, oldest first — one role's ring, or every
        ring in sorted role order; optionally filtered by span name."""
        if role is not None:
            out = list(self.rings.get(role, ()))
        else:
            out = [s for r in sorted(self.rings) for s in self.rings[r]]
        if name is not None:
            out = [s for s in out if s.name == name]
        return out

    def window_dict(self, last_n: Optional[int] = None,
                    include_wall: bool = False) -> dict:
        """role -> [span dict, ...] (oldest first), optionally the last N
        per role — the flight recorder's capture shape."""
        out: Dict[str, List[dict]] = {}
        for role in sorted(self.rings):
            spans = list(self.rings[role])
            if last_n is not None:
                spans = spans[-last_n:]
            out[role] = [s.to_dict(include_wall=include_wall) for s in spans]
        return out

    def spans_json(self, last_n: Optional[int] = None) -> str:
        """Canonical byte form — what the same-seed determinism gate
        compares.  Wall fields are excluded by construction."""
        return json.dumps(
            {"seed": self.seed, "spans": self.window_dict(last_n=last_n)},
            sort_keys=True,
            separators=(",", ":"),
        )

    def status_section(self) -> dict:
        return {
            "roles": {r: len(ring) for r, ring in sorted(self.rings.items())},
            "begun": self.begun,
            "per_role": self.per_role,
        }

    def clear(self) -> None:
        self.rings.clear()
        self._stack.clear()
        self._seq = 0
        self.begun = 0
        self.seed = None


_global_hub = SpanHub()


def set_global_span_hub(hub: SpanHub) -> None:
    global _global_hub
    _global_hub = hub


def global_span_hub() -> SpanHub:
    return _global_hub


def spans_enabled() -> bool:
    return g_env.get("FDB_TPU_SPANS") not in ("", "0")


def begin_span(name: str, role: Optional[str] = None,
               parent: Optional[Span] = None,
               attrs: Optional[dict] = None):
    """Begin one span on the CURRENT global hub (the instrumentation
    entry point).  Returns NULL_SPAN when spans are disabled, so call
    sites carry no enable branches.  The result must be context-managed,
    ``.end()``ed, or stored for a later end — flowcheck SPN001 flags a
    dropped result as a leaked open span."""
    if not spans_enabled():
        return NULL_SPAN
    return _global_hub.begin(name, role=role, parent=parent, attrs=attrs)


def current_span() -> Optional[Span]:
    """The innermost span pushed by a ``with`` block on the current hub
    (None outside any).  Synchronous sections only — see module doc."""
    return _global_hub.current()


class use_span:
    """Push an EXISTING (still-open) span for a synchronous section so
    nested ``begin_span`` calls parent to it — WITHOUT ending it on exit
    (unlike the span's own context-manager form).  ``use_span(None)`` is
    a no-op, so completion paths need no branches."""

    __slots__ = ("_span",)

    def __init__(self, span: Optional[Span]):
        self._span = (
            None if span is None or isinstance(span, _NullSpan) else span
        )

    def __enter__(self):
        if self._span is not None:
            self._span._hub._push(self._span)
        return self._span

    def __exit__(self, exc_type, exc, tb):
        if self._span is not None:
            self._span._hub._pop(self._span)
        return False


def instant(name: str, role: Optional[str] = None,
            attrs: Optional[dict] = None) -> None:
    """Zero-width marker span (breaker/ratekeeper transitions): begins
    and ends immediately, landing in the ring like any completed span."""
    sp = begin_span(name, role=role, attrs=attrs)
    sp.end()


# ---------------------------------------------------------------------------
# Derived metrics: pipeline overlap efficiency + span-based latency stages
# ---------------------------------------------------------------------------


def interval_overlap(intervals: List[tuple]) -> tuple:
    """(total, union) measure of a list of (begin, end) intervals.  The
    pipeline overlap-efficiency metric is (total - union) / total: the
    fraction of device time during which ANOTHER device interval was
    also open (0.0 for a synchronous depth-1 stream, approaching 0.5 for
    a perfectly double-buffered one)."""
    total = 0.0
    union = 0.0
    hwm = None
    for b, e in sorted(intervals):
        d = e - b
        if d <= 0:
            continue
        total += d
        if hwm is None or b >= hwm:
            union += d
            hwm = e
        elif e > hwm:
            union += e - hwm
            hwm = e
    return total, union


def overlap_efficiency(spans: List[Span], axis: str = "seq") -> float:
    """Overlapped device time / total device time over the given spans.
    axis="seq" uses the hub's deterministic event-sequence stamps (the
    sim clock that still advances during synchronous host work — the
    byte-identical gauge); axis="wall" uses real perf_counter reads (the
    bench/PERF_NOTES number); axis="vt" uses loop-virtual time."""
    keys = {
        "seq": lambda s: (s.seq, s.end_seq),
        "wall": lambda s: (s.wall_start, s.wall_end),
        "vt": lambda s: (s.start, s.stop),
    }[axis]
    intervals = [keys(s) for s in spans
                 if s.done and keys(s)[0] is not None]
    total, union = interval_overlap(intervals)
    if total <= 0:
        return 0.0
    return (total - union) / total


def span_latency_summary(hub: Optional[SpanHub] = None,
                         axis: str = "vt") -> dict:
    """role -> span name -> {count, p50, p90, p99, max} over completed
    spans' durations — `cli latency`'s default source (the latency_chain
    reassembly stays for trace-file-only inputs).  Virtual-time
    durations: host-synchronous stages read 0 in sim by construction
    (virtual time does not advance without an await); the stages that
    matter for admission — resolve_batch, device, proxy phases, client
    commit/GRV — all cross awaits and carry real virtual durations."""
    from .latency_chain import percentile

    hub = hub if hub is not None else _global_hub
    out: Dict[str, dict] = {}
    for role in sorted(hub.rings):
        by_name: Dict[str, List[float]] = {}
        for s in hub.rings[role]:
            if not s.done:
                continue
            if axis == "wall":
                d = (s.wall_end - s.wall_start
                     if s.wall_end is not None else None)
            else:
                d = s.stop - s.start if s.stop is not None else None
            if d is None:
                continue
            by_name.setdefault(s.name, []).append(d)
        stages = {}
        for name in sorted(by_name):
            samples = by_name[name]
            stages[name] = {
                "count": len(samples),
                "p50": percentile(samples, 0.5),
                "p90": percentile(samples, 0.90),
                "p99": percentile(samples, 0.99),
                "max": max(samples),
            }
        out[role] = stages
    return out
