"""Chrome trace-event / Perfetto export of the span layer (ISSUE 12).

Turns the SpanHub's completed spans into one canonical trace-event JSON
artifact loadable by ui.perfetto.dev / chrome://tracing:

* one PROCESS (pid) per span role, named via "M" (metadata) events —
  pids are assigned by sorted role name, so they are stable per role
  within an artifact and identical across same-seed runs;
* B/E duration-event pairs per span.  The timestamp axis is
  ``vt_microseconds + seq * 1e-3``: virtual time carries the real
  ordering, and the hub's event-sequence stamp breaks the ties that
  virtual time cannot (synchronous host work is vt-instantaneous), so
  every B strictly precedes its E and nesting is well defined;
* tids are LANES assigned greedily per pid: a span nests into the
  innermost open span that contains it, otherwise it opens the first
  free lane — which is exactly how two overlapping pipeline batches of
  one resolver land on separate lanes with their stage children nested
  under them (the "pipeline overlap is visible" requirement), while a
  synchronous depth-1 stream stays on one lane.

Determinism: the artifact is built from deterministic span fields only
(vt, seq, role, name, attrs) unless ``include_wall=True`` explicitly
opts wall durations into the args — so ``perfetto_json()`` of a
same-seed run is byte-identical (the acceptance gate).

``validate_perfetto`` is the schema gate the tests pin: every B has a
matching E (same pid/tid/name, properly nested), pids are stable per
role, and every pid carries exactly one process_name metadata event.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from .spans import SpanHub, global_span_hub


def _ts(vt: float, seq: int) -> float:
    """Trace timestamp in microseconds: virtual seconds scaled, with the
    event-sequence stamp as a sub-microsecond tiebreak (1ns per event)
    so equal-vt events keep their true order and B < E always holds."""
    return round(vt * 1e6 + seq * 1e-3, 6)


def _assign_lanes(spans: List) -> Dict[int, int]:
    """span_id -> lane (tid) for one role's spans.

    Parent-aware: a span goes to its PARENT's lane whenever it still
    nests there (stage children under their own batch slice — a purely
    geometric first-fit would nest batch N+1's encode, which begins
    inside batch N's window, under the WRONG batch).  Roots only take a
    lane that is EMPTY at their begin (two concurrent pipelined batches
    are siblings side by side, never one inside the other), else open a
    new lane.  A non-root whose parent is unknown (ring-dropped) falls
    back to geometric nesting.  Every placement is checked against the
    lane's open stack, so B/E nesting stays valid by construction."""
    lanes: List[List[float]] = []  # per lane: stack of open spans' end ts
    out: Dict[int, int] = {}
    order = sorted(spans, key=lambda s: (_ts(s.start, s.seq),
                                         -_ts(s.stop, s.end_seq)))
    for sp in order:
        b, e = _ts(sp.start, sp.seq), _ts(sp.stop, sp.end_seq)
        for stack in lanes:
            while stack and stack[-1] <= b:
                stack.pop()

        def _fits(stack):
            return not stack or e <= stack[-1]

        placed = None
        parent_lane = out.get(sp.parent_id)
        if parent_lane is not None and _fits(lanes[parent_lane]):
            placed = parent_lane
        if placed is None:
            for li, stack in enumerate(lanes):
                if sp.parent_id is None:
                    if not stack:  # roots never nest under another span
                        placed = li
                        break
                elif _fits(stack):
                    placed = li
                    break
        if placed is None:
            lanes.append([])
            placed = len(lanes) - 1
        lanes[placed].append(e)
        out[sp.span_id] = placed
    return out


def perfetto_trace(hub: Optional[SpanHub] = None,
                   include_wall: bool = False,
                   last_n: Optional[int] = None) -> dict:
    """Build the trace-event document from the hub's completed spans."""
    hub = hub if hub is not None else global_span_hub()
    roles = sorted(hub.rings)
    events: List[dict] = []
    for pid, role in enumerate(roles, start=1):
        spans = list(hub.rings[role])
        if last_n is not None:
            spans = spans[-last_n:]
        spans = [s for s in spans if s.done]
        if not spans:
            continue
        events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": role},
        })
        lanes = _assign_lanes(spans)
        for sp in spans:
            tid = lanes[sp.span_id] + 1
            args = {"span": sp.span_id, **sp.attrs}
            if sp.parent_id is not None:
                args["parent"] = sp.parent_id
            if include_wall and sp.wall_end is not None:
                args["wall_ms"] = round(
                    (sp.wall_end - sp.wall_start) * 1e3, 4
                )
            events.append({
                "ph": "B", "name": sp.name, "cat": role, "pid": pid,
                "tid": tid, "ts": _ts(sp.start, sp.seq), "args": args,
            })
            events.append({
                "ph": "E", "name": sp.name, "cat": role, "pid": pid,
                "tid": tid, "ts": _ts(sp.stop, sp.end_seq),
            })
    # Global ts order (metadata events lead their pid: ts absent sorts
    # first via the (pid, is-not-meta, ts) key).
    events.sort(key=lambda e: (e["pid"], e["ph"] != "M", e.get("ts", 0.0)))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "source": "foundationdb_tpu spans (flow/spans.py)",
            "seed": hub.seed,
            "spans": sum(1 for e in events if e["ph"] == "B"),
        },
    }


def perfetto_json(hub: Optional[SpanHub] = None,
                  include_wall: bool = False,
                  last_n: Optional[int] = None) -> str:
    """Canonical byte form of the artifact — what the same-seed gate
    compares (sort_keys orders dict keys only; the event array keeps its
    deterministic order)."""
    return json.dumps(
        perfetto_trace(hub=hub, include_wall=include_wall, last_n=last_n),
        sort_keys=True,
        separators=(",", ":"),
    )


def validate_perfetto(doc: dict) -> List[str]:
    """Schema gate: returns a list of violations (empty = valid).
    Checks B/E pairing + nesting per (pid, tid), name matches on E,
    one process_name per pid, and a stable role -> pid mapping."""
    errors: List[str] = []
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents missing or not a list"]
    stacks: Dict[tuple, List[dict]] = {}
    names_by_pid: Dict[int, List[str]] = {}
    role_pid: Dict[str, int] = {}
    last_ts: Dict[tuple, float] = {}
    for i, e in enumerate(events):
        ph = e.get("ph")
        if ph == "M":
            if e.get("name") == "process_name":
                names_by_pid.setdefault(e["pid"], []).append(
                    e["args"]["name"]
                )
            continue
        if ph not in ("B", "E"):
            errors.append(f"event {i}: unexpected ph {ph!r}")
            continue
        key = (e.get("pid"), e.get("tid"))
        ts = e.get("ts")
        if ts is None:
            errors.append(f"event {i}: missing ts")
            continue
        if last_ts.get(key, float("-inf")) > ts:
            errors.append(f"event {i}: ts not monotonic within {key}")
        last_ts[key] = ts
        if ph == "B":
            role = e.get("cat")
            if role is not None:
                prev = role_pid.setdefault(role, e["pid"])
                if prev != e["pid"]:
                    errors.append(
                        f"role {role!r} spans pids {prev} and {e['pid']}"
                    )
            stacks.setdefault(key, []).append(e)
        else:
            stack = stacks.get(key)
            if not stack:
                errors.append(f"event {i}: E with empty stack on {key}")
                continue
            b = stack.pop()
            if b.get("name") != e.get("name"):
                errors.append(
                    f"event {i}: E name {e.get('name')!r} closes B "
                    f"{b.get('name')!r} on {key}"
                )
    for key, stack in stacks.items():
        if stack:
            errors.append(
                f"{len(stack)} unclosed B event(s) on {key}: "
                f"{[b.get('name') for b in stack]}"
            )
    for pid, names in names_by_pid.items():
        if len(names) != 1:
            errors.append(f"pid {pid} has {len(names)} process_name events")
    return errors
