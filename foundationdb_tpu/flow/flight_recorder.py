"""Flight recorder: triggered black-box capture of the telemetry window
around an incident (ISSUE 10 tentpole, layer 3 of 3).

When the breaker opens or the ratekeeper starts throttling mid-soak, the
point-in-time surfaces show the aftermath; the window of history that
explains WHY is gone.  This module is the reference's "trace spool +
status history" analog in bounded memory: on a trigger it freezes one
deterministic JSON artifact —

    {trigger, time, detail, transitions,
     timeseries:    last-N window of every TimeSeriesHub series,
     recent_events: last-N ring of the global TraceCollector}

— into a bounded capture ring, surfaced via `cli flightrec`, the status
doc's `flight_recorder` section, and per-fault-window captures in
`workloads/soak.py`.

Trigger sites (the four transition-log owners):
  breaker open        DeviceCircuitBreaker._transition (ok -> degraded)
  mirror_divergence   ConflictSet.mirror_check confirmed divergence
  ratekeeper_limiting Ratekeeper._update_loop binding-signal change
  slo_breach          soak report: a phase missed its SLO

All call `maybe_trigger(kind, ...)`, which applies a per-kind
virtual-time cooldown (FDB_TPU_FLIGHTREC_COOLDOWN) and no-ops when
FDB_TPU_FLIGHTREC=0; explicit `capture()` calls (the soak's
fault-window captures) bypass both.

Determinism contract: artifacts contain only virtual-time stamps,
registry deltas, trace events, and transition logs — `artifact_json()`
is byte-identical across same-seed runs (the acceptance gate).  The
global recorder is swappable per run (`set_global_flight_recorder`),
exactly like the trace collector and the time-series hub.
"""

from __future__ import annotations

import json
from collections import deque
from typing import Dict, Optional

from .knobs import g_env


def _vt_now() -> Optional[float]:
    """Capture timestamp: the current loop's virtual time, else None —
    NEVER wall clock (an artifact must replay byte-identical).  None
    means there is no meaningful clock to base a cooldown on."""
    from .eventloop import _current_loop

    return _current_loop.now() if _current_loop is not None else None


def artifact_json(artifact: dict) -> str:
    """Canonical byte form of one capture — what the same-seed gate
    compares."""
    return json.dumps(artifact, sort_keys=True, separators=(",", ":"))


class FlightRecorder:
    """Bounded ring of incident captures + per-kind trigger cooldowns."""

    def __init__(
        self,
        max_captures: Optional[int] = None,
        window: Optional[int] = None,
        cooldown: Optional[float] = None,
    ):
        self.window = (
            window
            if window is not None
            else max(1, g_env.get_int("FDB_TPU_FLIGHTREC_WINDOW"))
        )
        self.cooldown = (
            cooldown
            if cooldown is not None
            else float(g_env.get("FDB_TPU_FLIGHTREC_COOLDOWN"))
        )
        n = (
            max_captures
            if max_captures is not None
            else max(1, g_env.get_int("FDB_TPU_FLIGHTREC_CAPTURES"))
        )
        self.captures: deque = deque(maxlen=n)
        self.capture_seq = 0  # lifetime count (ring may have dropped some)
        self.trigger_counts: Dict[str, int] = {}
        self._last_trigger_time: Dict[str, float] = {}

    # -- capture ----------------------------------------------------------
    def capture(
        self,
        trigger: str,
        detail=None,
        transitions=None,
        now: Optional[float] = None,
    ) -> dict:
        """Freeze one artifact NOW (no cooldown, no enable gate): the
        last-N time-series window, the recent trace events, the recent
        span window (ISSUE 12), the caller's transition-log snapshot,
        and the trigger context."""
        from .spans import global_span_hub
        from .timeseries import global_timeseries
        from .trace import global_collector

        if now is None:
            now = _vt_now()
            if now is None:
                now = 0.0
        if callable(transitions):
            # Lazily-built transition snapshot (see trigger): resolve it
            # only for captures that actually happen.
            transitions = transitions()
        self.capture_seq += 1
        artifact = {
            "capture_seq": self.capture_seq,
            "trigger": trigger,
            "time": now,
            "detail": detail,
            "transitions": transitions,
            "timeseries": global_timeseries().window_dict(
                last_n=self.window
            ),
            "recent_events": global_collector().recent_events()[
                -self.window:
            ],
            # Deterministic by construction (wall fields excluded by
            # Span.to_dict) — the artifact stays byte-identical per seed.
            "spans": global_span_hub().window_dict(last_n=self.window),
        }
        self.captures.append(artifact)
        return artifact

    def trigger(
        self, kind: str, detail=None, transitions=None, source=None
    ) -> Optional[dict]:
        """Cooldown-gated capture: at most one capture per (kind,
        source) per FDB_TPU_FLIGHTREC_COOLDOWN virtual seconds (a
        FLAPPING signal must not churn the whole ring — but two DISTINCT
        sources degrading simultaneously are two incidents, so call
        sites pass their identity as `source` and each gets its own
        cooldown).  Suppressed triggers still count.  `transitions` may
        be a zero-arg callable — it is only resolved for captures the
        cooldown lets through, so flapping call sites don't pay a log
        copy per suppressed trigger.  The cooldown only applies with a
        loop set AND a non-decreasing stamp: no loop means no meaningful
        clock (never suppress), and a stamp that went BACKWARDS means a
        new run's virtual time restarted in this process (a real
        incident of the new run must not be swallowed by the old run's
        stamp)."""
        self.trigger_counts[kind] = self.trigger_counts.get(kind, 0) + 1
        now = _vt_now()
        if now is not None:
            key = (kind, source)
            last = self._last_trigger_time.get(key)
            if last is not None and 0 <= now - last < self.cooldown:
                return None
            self._last_trigger_time[key] = now
        return self.capture(kind, detail=detail, transitions=transitions, now=now)

    # -- surfaces ---------------------------------------------------------
    def status_section(self) -> dict:
        """The status doc's `flight_recorder` block: capture inventory,
        never the (large) artifacts themselves — `cli flightrec` dumps
        those."""
        return {
            "captures": len(self.captures),
            "total_triggers": dict(sorted(self.trigger_counts.items())),
            "capture_seq": self.capture_seq,
            "window": self.window,
            "last_capture": (
                {
                    "trigger": self.captures[-1]["trigger"],
                    "time": self.captures[-1]["time"],
                    "capture_seq": self.captures[-1]["capture_seq"],
                }
                if self.captures
                else None
            ),
        }

    def clear(self):
        self.captures.clear()
        self.trigger_counts.clear()
        self._last_trigger_time.clear()
        self.capture_seq = 0


_global_recorder = FlightRecorder()


def set_global_flight_recorder(rec: FlightRecorder):
    global _global_recorder
    _global_recorder = rec


def global_flight_recorder() -> FlightRecorder:
    return _global_recorder


def maybe_trigger(
    kind: str, detail=None, transitions=None, source=None
) -> Optional[dict]:
    """The trigger-site entry point: no-op when FDB_TPU_FLIGHTREC=0,
    else a cooldown-gated capture on the CURRENT global recorder.  Call
    sites (breaker/mirror/ratekeeper/soak) pass their own transition-log
    snapshot (or a thunk building it) so the artifact carries the
    triggering transition, and their own identity as `source` so
    simultaneous incidents from distinct objects don't share one
    cooldown."""
    if g_env.get("FDB_TPU_FLIGHTREC") in ("", "0"):
        return None
    return _global_recorder.trigger(
        kind, detail=detail, transitions=transitions, source=source
    )
