"""AsyncVar: an observable value cell (ref: flow/genericactors.actor.h
AsyncVar<T> — get() + onChange() future, used everywhere for pushed state:
ServerDBInfo broadcasts, failure states, NotifiedVersion waits)."""

from __future__ import annotations

from typing import Any, Generic, TypeVar

from .future import Future, Promise

T = TypeVar("T")


class AsyncVar(Generic[T]):
    __slots__ = ("_value", "_change")

    def __init__(self, value: T = None):
        self._value = value
        self._change = Promise()

    def get(self) -> T:
        return self._value

    def on_change(self) -> Future:
        """Fires (with the new value) at the next set(); one-shot per call site."""
        return self._change.future

    def set(self, value: T):
        if value == self._value:
            return
        self._value = value
        prev, self._change = self._change, Promise()
        prev.send(value)

    def trigger(self):
        """Force waiters to wake even if the value is unchanged."""
        prev, self._change = self._change, Promise()
        prev.send(self._value)


class NotifiedVersion:
    """Monotone version with when_at_least() waits (ref: flow NotifiedVersion;
    the resolver's prevVersion ordering chain, Resolver.actor.cpp:104-115)."""

    __slots__ = ("_value", "_waiters")

    def __init__(self, value: int = 0):
        self._value = value
        self._waiters: list[tuple[int, Promise]] = []

    def get(self) -> int:
        return self._value

    def when_at_least(self, version: int) -> Future:
        if self._value >= version:
            from .future import ready_future

            return ready_future(self._value)
        p = Promise()
        self._waiters.append((version, p))
        return p.future

    def set(self, version: int):
        assert version >= self._value, "NotifiedVersion must be monotone"
        self._value = version
        still = []
        for v, p in self._waiters:
            if v <= version:
                p.send(version)
            else:
                still.append((v, p))
        self._waiters = still
