"""Deterministic virtual-time event loop: the rebuild of Net2's run loop.

Ref: flow/Net2.actor.cpp:117 (Net2), flow/network.h:194 (INetwork), task
priority bands flow/network.h:31-64.  The reference runs one cooperative
thread per process; timers and ready tasks are ordered by (time, priority).
In simulation (fdbrpc/sim2.actor.cpp) time is virtual and advances to the
next event instantly; randomness flows through DeterministicRandom so runs
are reproducible from the seed.

This loop is simulation-first: time is always virtual.  A wall-clock-paced
driver can wrap `run_one` and sleep to align virtual and real time; the role
code is identical either way, preserving the reference's single most
load-bearing design decision (same actors on Sim2 or Net2 — see SURVEY.md §1).

Coroutines ("actors") are driven by Task.  `await future` suspends until the
future is set; resumption goes through the loop's queue at a task priority,
never synchronously, so event ordering is fully determined by (time,
priority, insertion sequence).

Scheduler-perturbation fuzz (FDB_TPU_SCHED_FUZZ=<int>): a DeterministicRandom
forked from (seed, fuzz) injects a tie-break between priority and insertion
sequence, permuting pick order among equal-(time, priority) entries — the
orderings the contract leaves unspecified.  Same (seed, fuzz) replays
byte-identically; a different fuzz explores a different LEGAL interleaving
(ref: sim2/BUGGIFY task-order jitter), which is what the differential replay
gates re-run under to flush latent ordering assumptions.
"""

from __future__ import annotations

import heapq
import weakref
from time import perf_counter as _perf_counter  # fdblint: ignore[DET001]: slow-task profiling measures REAL step cost; never feeds virtual time
from typing import Coroutine, Optional

from .error import ActorCancelled, FdbError, SimulationFailure
from .future import Future, Promise
from .knobs import g_env
from .rng import DeterministicRandom


class TaskPriority:
    """Numeric priority bands; higher runs first at equal time.

    Values mirror flow/network.h:31-64 (TaskMaxPriority = 1000000 ...
    TaskZeroPriority = 0); only the bands the rebuild uses are listed.
    """

    Max = 1000000
    RunCycleFunction = 20000
    FlushTrace = 10500
    WriteSocket = 10000
    PollEIO = 9900
    DiskIOComplete = 9150
    LoadBalancedEndpoint = 9000
    ReadSocket = 9000
    CoordinationReply = 8810
    Coordination = 8800
    FailureMonitor = 8700
    ResolutionMetrics = 8700
    ClusterController = 8650
    ProxyCommitDispatcher = 8640
    TLogQueuingMetrics = 8620
    TLogPop = 8610
    TLogPeekReply = 8600
    TLogPeek = 8590
    TLogCommitReply = 8580
    TLogCommit = 8570
    ProxyGetRawCommittedVersion = 8565
    ProxyResolverReply = 8560
    ProxyCommitBatcher = 8550
    ProxyCommit = 8540
    TLogConfirmRunningReply = 8530
    TLogConfirmRunning = 8520
    ProxyGetKeyServersLocations = 8515
    ProxyGRVTimer = 8510
    ProxyGetConsistentReadVersion = 8500
    DefaultPromiseEndpoint = 8000
    DefaultOnMainThread = 7500
    DefaultDelay = 7010
    DefaultYield = 7000
    DiskRead = 5010
    DefaultEndpoint = 5000
    UnknownEndpoint = 4000
    MoveKeys = 3550
    DataDistributionLaunch = 3530
    DataDistribution = 3500
    DiskWrite = 3010
    UpdateStorage = 3000
    BatchCopy = 2900
    Low = 2000
    Min = 1000
    Zero = 0


class Task(Future):
    """Drives a coroutine; the Task itself is a Future of the coroutine result.

    Ref: the actor compiler's generated Actor<T> classes (flow/flow.h:910);
    cancellation semantics follow flow: cancelling throws actor_cancelled
    inside the actor at its current wait point, synchronously.
    """

    __slots__ = ("_coro", "_loop", "name", "_waiting_on", "_cancelled",
                 "_started")

    def __init__(self, loop: "EventLoop", coro: Coroutine, name: str = ""):
        super().__init__()
        self._coro = coro
        self._loop = loop
        self.name = name or getattr(coro, "__name__", "actor")
        self._waiting_on: Optional[Future] = None
        self._cancelled = False
        self._started = False

    def __del__(self):
        # A task spawned but never driven (cluster built, loop never run)
        # holds a never-started coroutine; close it so collection doesn't
        # emit "coroutine was never awaited" — that warning must stay
        # meaningful for REAL dropped actors (the fdblint ACT001 class),
        # not fire for every lazily-constructed role.  close() on a
        # never-started coroutine just marks it closed (no GeneratorExit
        # runs), so no cleanup code executes at GC time.  Best-effort by
        # nature: when Task and coroutine die in one GC *cycle*, CPython
        # may order the coroutine's warning finalizer first (holding the
        # coroutine alive from a finalize registry instead would pin the
        # whole cycle — a leak, strictly worse); residual warnings stay
        # visible via pytest's warning summary rather than gating.
        if not self._started and not self._cancelled:
            try:
                self._coro.close()
            except RuntimeError:
                pass  # already running/closed — nothing to silence

    def _step(self, value=None, error: Optional[BaseException] = None):
        if self.is_ready():
            return
        self._started = True
        self._waiting_on = None
        loop = self._loop
        prev_task = loop.current_task
        loop.current_task = self
        try:
            try:
                if error is not None:
                    awaited = self._coro.throw(error)
                else:
                    awaited = self._coro.send(value)
            except StopIteration as stop:
                self._set(stop.value)
                return
            except BaseException as e:  # noqa: BLE001 - errors flow into the future
                self._set_error(e)
                loop._note_actor_failure(self.name, e)
                return
            # The coroutine yielded a Future it is waiting on.
            assert isinstance(awaited, Future), (
                f"actor {self.name} awaited a non-Future: {awaited!r}"
            )
            self._waiting_on = awaited
            awaited.add_callback(self._on_ready)
        finally:
            loop.current_task = prev_task

    def _on_ready(self, fut: Future):
        prio = fut.priority if fut.priority is not None else TaskPriority.DefaultOnMainThread
        if fut.is_error():
            err = fut.error()
            self._loop._schedule(prio, lambda: self._step(error=err))
        else:
            val = fut.get()
            self._loop._schedule(prio, lambda: self._step(value=val))

    def cancel(self):
        """Throw actor_cancelled into the coroutine now (ref: actor cancel).

        Cancellation is synchronous, as in flow (actor destruction runs the
        unwind immediately).  Waits during cancellation never complete: if
        cleanup code awaits (e.g. in a finally block), the await immediately
        re-raises actor_cancelled until the coroutine exits.  A real error
        raised during unwind propagates into the task's future.
        """
        if self.is_ready() or self._cancelled:
            return
        self._cancelled = True
        if self._waiting_on is not None:
            self._waiting_on.remove_callback(self._on_ready)
            self._waiting_on = None
        err: BaseException = ActorCancelled()
        try:
            for _ in range(1000):
                self._coro.throw(ActorCancelled())
            raise RuntimeError(f"actor {self.name} ignored cancellation")
        except StopIteration:
            pass
        except ActorCancelled:
            pass
        except BaseException as e:  # noqa: BLE001 - surfaced via the future
            err = e
        if not self.is_ready():
            self._set_error(err)
            self._loop._note_actor_failure(self.name, err)


class EventLoop:
    """Single-threaded deterministic event loop with virtual time."""

    def __init__(self, seed: int = 1):
        self.rng = DeterministicRandom(seed)
        self._now = 0.0
        self._seq = 0
        # Heap entries: (time, -priority, tie, seq, fn-cell).  `tie` is 0
        # unless FDB_TPU_SCHED_FUZZ is set, in which case it is a draw from
        # a rng forked from (seed, fuzz) — permuting pick order among
        # equal-(time, priority) entries, the orderings the scheduling
        # contract leaves unspecified (see module docstring).
        self._heap: list = []
        fuzz = g_env.get("FDB_TPU_SCHED_FUZZ")
        self._fuzz_rng = (
            DeterministicRandom((seed * 1000003 + int(fuzz)) & ((1 << 63) - 1))
            if fuzz
            else None
        )
        # Bumps once per run_one step: the state sanitizer's interleaving
        # clock — two accesses at the same epoch cannot have had another
        # task run between them (see flow/state_sanitizer.py).
        self.await_epoch = 0
        # The Task currently being stepped (None between steps / for plain
        # callbacks): audit attribution for the state sanitizer.
        self.current_task: Optional[Task] = None
        self._stopped = False
        self.tasks_run = 0
        # Slow-task profiler threshold in WALL seconds (None = off; the
        # simulator leaves it off — virtual time has no slow tasks; real
        # deployments enable it, ref: Net2 slow-task profiling).
        self.slow_task_threshold = None
        # (actor name, exception) for tasks that died with a non-FdbError
        # exception: genuine bugs, surfaced as SimulationFailure by run_until.
        self.failed_actors: list = []
        # Every task ever spawned, weakly: sim_validation's orphaned-wait
        # teardown check (and the ran-dry diagnostic below) scan it for
        # tasks parked on futures whose promise has been dropped.
        self._spawned: "weakref.WeakSet[Task]" = weakref.WeakSet()

    def _note_actor_failure(self, name: str, err: BaseException):
        """Record an actor crash that is a bug (Python error), not a
        simulated fault (FdbError / ActorCancelled flow through futures as
        expected distributed errors)."""
        if isinstance(err, FdbError):
            return
        if any(e is err for _n, e in self.failed_actors):
            return  # same exception propagating through an awaiter chain
        self.failed_actors.append((name, err))

    # --- time ---
    def now(self) -> float:
        return self._now

    # --- scheduling primitives ---
    def _schedule(self, priority: int, fn, at: Optional[float] = None) -> list:
        """Queue fn; returns a one-element cell usable to cancel the entry."""
        self._seq += 1
        t = self._now if at is None else at
        cell = [fn]
        tie = (
            self._fuzz_rng.random_int(0, 1 << 30)
            if self._fuzz_rng is not None
            else 0
        )
        heapq.heappush(self._heap, (t, -priority, tie, self._seq, cell))
        return cell

    def delay(self, seconds: float, priority: int = TaskPriority.DefaultDelay) -> Future:
        """Future that fires `seconds` of virtual time from now.

        Ref: INetwork::delay flow/network.h; ordering at equal deadlines is by
        priority then FIFO, matching Net2's timer/ready queues.
        """
        f = Future(priority)
        cell = self._schedule(priority, lambda: f._set(None), at=self._now + max(0.0, seconds))
        f.timer_cell = cell
        return f

    def cancel_timer(self, f: Future):
        """Drop a pending delay()'s heap entry (it never fires)."""
        cell = getattr(f, "timer_cell", None)
        if cell is not None:
            cell[0] = None

    def yield_(self, priority: int = TaskPriority.DefaultYield) -> Future:
        return self.delay(0.0, priority)

    def spawn(self, coro: Coroutine, name: str = "", priority: int = TaskPriority.DefaultOnMainThread) -> Task:
        task = Task(self, coro, name)
        self._spawned.add(task)
        self._schedule(priority, task._step)
        return task

    # --- run loop ---
    def run_one(self) -> bool:
        """Run the next event, advancing virtual time. False if none left."""
        while self._heap and not self._stopped:
            t, _negprio, _tie, _seq, cell = heapq.heappop(self._heap)
            fn = cell[0]
            if fn is None:  # cancelled timer
                continue
            if t > self._now:
                self._now = t
            self.tasks_run += 1
            self.await_epoch += 1
            # Captured BEFORE the step: the step itself may toggle the
            # profiler (a workload or the runtime-toggle RPC), and the
            # comparison below must use the threshold this step ran under.
            threshold = self.slow_task_threshold
            if threshold is None:
                fn()
                return True
            # Slow-task profiler (ref: Net2's slow task profiling): a
            # single step hogging the reactor is the #1 real-deployment
            # latency smell; surface it with its wall-clock cost.
            w0 = _perf_counter()  # fdblint: ignore[DET001]: measures the step's REAL cpu cost (profiling), not simulated time
            fn()
            dt = _perf_counter() - w0  # fdblint: ignore[DET001]: see above — wall delta is the profiler's measurement, virtual time untouched
            if dt >= threshold:
                from .trace import TraceEvent

                TraceEvent("SlowTask", severity=20).detail(
                    "wall_seconds", round(dt, 6)
                ).detail(
                    "fn", getattr(fn, "__qualname__", repr(fn))[:120]
                ).log(now=self._now)
            return True
        return False

    def run_until(self, future: Future, timeout_vt: Optional[float] = None):
        """Drive the loop until `future` is ready; returns its value."""
        deadline = None if timeout_vt is None else self._now + timeout_vt
        while not future.is_ready():
            if self.failed_actors:
                name, err = self.failed_actors[0]
                self.failed_actors = []
                raise SimulationFailure(
                    f"unhandled exception in actor {name!r}: {err!r}"
                ) from err
            if deadline is not None and self._heap and self._heap[0][0] > deadline:
                raise TimeoutError(
                    f"virtual-time deadline {deadline} exceeded (now={self._now})"
                )
            if not self.run_one():
                # Name the tasks parked on dropped promises (needs
                # track_promise_refs; empty otherwise): a dry loop with a
                # pending future is almost always THIS hang class.
                from .sim_validation import orphaned_waits

                orphans = orphaned_waits(self)
                detail = (
                    "; tasks parked on dropped promises: "
                    + ", ".join(name for name, _w in orphans[:5])
                    if orphans else ""
                )
                raise RuntimeError(
                    "event loop ran dry awaiting future" + detail
                )
        if future.is_error():
            # The awaited future's own error is observed by the caller via
            # get(); don't re-raise it as a SimulationFailure later.
            err = future.error()
            self.failed_actors = [
                (n, e) for n, e in self.failed_actors if e is not err
            ]
        return future.get()

    def run(self, max_events: Optional[int] = None):
        n = 0
        while self.run_one():
            n += 1
            if max_events is not None and n >= max_events:
                break

    def stop(self):
        self._stopped = True


# --- global loop access (ref: g_network global) ---
_current_loop: Optional[EventLoop] = None


def set_event_loop(loop: Optional[EventLoop]):
    global _current_loop
    _current_loop = loop


def current_loop() -> EventLoop:
    assert _current_loop is not None, "no event loop set (call set_event_loop)"
    return _current_loop


def g_network() -> EventLoop:
    return current_loop()


# --- combinators (ref: genericactors.actor.h) ---
def all_of(futures) -> Future:
    """Future of all values; errors immediately on the first error, like the
    reference's waitForAll (it does not wait out the other futures)."""
    futures = list(futures)
    out = Promise()
    remaining = [len(futures)]
    results = [None] * len(futures)
    cbs = []

    def unsubscribe():
        for f, cb in zip(futures, cbs):
            f.remove_callback(cb)

    def make_cb(i):
        def cb(f: Future):
            if out.is_set():
                return
            if f.is_error():
                out.send_error(f.error())
                unsubscribe()
                return
            results[i] = f.get()
            remaining[0] -= 1
            if remaining[0] == 0:
                out.send(results)

        return cb

    if not futures:
        out.send([])
        return out.future
    for i, f in enumerate(futures):
        cb = make_cb(i)
        cbs.append(cb)
        f.add_callback(cb)
        if out.is_set():
            break
    return out.future


async def wait_for_all(futures):
    """Wait for every future; first error propagates (ref: waitForAll)."""
    return await all_of(futures)


def first_of(*futures: Future) -> Future:
    """Future of (index, value) for whichever input fires first (ref:
    choose/when).  Losing futures are unsubscribed (not cancelled — the
    caller may still hold them)."""
    out = Promise()
    cbs: list = []

    def settle():
        for f, cb in zip(futures, cbs):
            f.remove_callback(cb)

    def make_cb(i):
        def cb(f: Future):
            if out.is_set():
                return
            if f.is_error():
                out.send_error(f.error())
            else:
                out.send((i, f.get()))
            settle()

        return cb

    for i, f in enumerate(futures):
        cb = make_cb(i)
        cbs.append(cb)
        f.add_callback(cb)
        if out.is_set():
            break
    return out.future


async def timeout_after(loop: EventLoop, fut: Future, seconds: float, default=None):
    """Value of fut, or `default` if `seconds` of virtual time elapse first.

    The internal timer is always cancelled once fut settles (value or error),
    so repeated timeouts on long waits don't accumulate dead heap entries;
    `fut` itself is only unsubscribed on timeout (the caller may still hold
    it).
    """
    timer = loop.delay(seconds)
    try:
        idx, val = await first_of(fut, timer)
    finally:
        loop.cancel_timer(timer)
    if idx == 0:
        return val
    return default
