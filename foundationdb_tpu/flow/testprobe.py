"""Code-coverage probes: the TEST() macro + coveragetool analog.

Ref: flow/UnitTest.h's TEST(condition) macro — a named probe at an
interesting code path (a rare branch the simulation is supposed to reach)
— and the coveragetool build step that fails CI when probes were never
hit across the test corpus.  Here: `test_probe("name")` counts hits per
site; tests/test_coverage.py runs a chaos corpus and asserts the required
probe set actually fired, so silently-dead rare paths are loud.
"""

from __future__ import annotations

from typing import Dict

hit_sites: Dict[str, int] = {}


def test_probe(name: str) -> None:
    """Mark an interesting code path as reached (cheap: one dict bump)."""
    hit_sites[name] = hit_sites.get(name, 0) + 1


def reset() -> None:
    hit_sites.clear()
