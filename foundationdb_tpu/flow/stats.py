"""Counters with periodic trace emission.

Ref: flow/Stats.h — `Counter` :55 (value + rate tracking),
`CounterCollection` :63, and `traceCounters` :111 (an actor emitting every
counter as a TraceEvent on an interval, resetting rates).
"""

from __future__ import annotations

from typing import Dict

from .trace import TraceEvent


class Counter:
    __slots__ = ("name", "value", "_last", "_last_t")

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self._last = 0
        self._last_t = 0.0

    def add(self, n: int = 1):
        self.value += n

    def rate_since_last(self, now: float) -> float:
        dt = now - self._last_t
        r = (self.value - self._last) / dt if dt > 0 else 0.0
        self._last = self.value
        self._last_t = now
        return r


class ContinuousSample:
    """Bounded reservoir of a metric's recent distribution with percentile
    queries (ref: fdbrpc/ContinuousSample.h — the structure behind the
    status doc's latency percentiles).

    Uses the caller's DeterministicRandom so sampling stays seed-
    reproducible in simulation (the global `random` module is banned in
    sim code paths)."""

    __slots__ = ("size", "rng", "samples", "n", "_min", "_max")

    def __init__(self, rng, size: int = 500):
        self.size = size
        self.rng = rng
        self.samples: list = []
        self.n = 0
        self._min = None
        self._max = None

    def add(self, x: float):
        self.n += 1
        self._min = x if self._min is None else min(self._min, x)
        self._max = x if self._max is None else max(self._max, x)
        if len(self.samples) < self.size:
            self.samples.append(x)
        elif self.rng.random01() < self.size / self.n:
            self.samples[int(self.rng.random_int(0, self.size))] = x

    def percentile(self, p: float):
        if not self.samples:
            return None
        s = sorted(self.samples)
        return s[min(len(s) - 1, int(p * len(s)))]

    @property
    def min(self):
        return self._min

    @property
    def max(self):
        return self._max

    def summary(self) -> dict:
        """The status-doc shape (ref: the latency_probe / *_latency fields
        in Status.actor.cpp's qos section)."""
        return {
            "count": self.n,
            "min": self._min,
            "median": self.percentile(0.5),
            "p90": self.percentile(0.90),
            "p99": self.percentile(0.99),
            "max": self._max,
        }


class CounterCollection:
    def __init__(self, name: str):
        self.name = name
        self.counters: Dict[str, Counter] = {}

    def counter(self, name: str) -> Counter:
        c = self.counters.get(name)
        if c is None:
            c = self.counters[name] = Counter(name)
        return c

    def add(self, name: str, n: int = 1):
        self.counter(name).add(n)

    def __getitem__(self, name: str) -> int:
        return self.counter(name).value

    def snapshot(self) -> Dict[str, int]:
        return {k: c.value for k, c in self.counters.items()}


async def trace_counters(
    collection: CounterCollection, process, interval: float = 5.0
):
    """Emit every counter periodically (ref: traceCounters flow/Stats.h:111
    — one event per collection with .detail per counter + rates)."""
    loop = process.network.loop
    while True:
        await loop.delay(interval)
        ev = TraceEvent(f"{collection.name}Metrics")
        now = loop.now()
        for name, c in sorted(collection.counters.items()):
            ev.detail(name, c.value)
            ev.detail(f"{name}Rate", round(c.rate_since_last(now), 3))
        ev.log()
