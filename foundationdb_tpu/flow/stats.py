"""Counters with periodic trace emission.

Ref: flow/Stats.h — `Counter` :55 (value + rate tracking),
`CounterCollection` :63, and `traceCounters` :111 (an actor emitting every
counter as a TraceEvent on an interval, resetting rates).
"""

from __future__ import annotations

from typing import Dict

from .trace import TraceEvent


class Counter:
    __slots__ = ("name", "value", "_last", "_last_t")

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self._last = 0
        self._last_t = 0.0

    def add(self, n: int = 1):
        self.value += n

    def rate_since_last(self, now: float) -> float:
        dt = now - self._last_t
        r = (self.value - self._last) / dt if dt > 0 else 0.0
        self._last = self.value
        self._last_t = now
        return r


class CounterCollection:
    def __init__(self, name: str):
        self.name = name
        self.counters: Dict[str, Counter] = {}

    def counter(self, name: str) -> Counter:
        c = self.counters.get(name)
        if c is None:
            c = self.counters[name] = Counter(name)
        return c

    def add(self, name: str, n: int = 1):
        self.counter(name).add(n)

    def __getitem__(self, name: str) -> int:
        return self.counter(name).value

    def snapshot(self) -> Dict[str, int]:
        return {k: c.value for k, c in self.counters.items()}


async def trace_counters(
    collection: CounterCollection, process, interval: float = 5.0
):
    """Emit every counter periodically (ref: traceCounters flow/Stats.h:111
    — one event per collection with .detail per counter + rates)."""
    loop = process.network.loop
    while True:
        await loop.delay(interval)
        ev = TraceEvent(f"{collection.name}Metrics")
        now = loop.now()
        for name, c in sorted(collection.counters.items()):
            ev.detail(name, c.value)
            ev.detail(f"{name}Rate", round(c.rate_since_last(now), 3))
        ev.log()
