"""Counters and bounded distribution samples.

Ref: flow/Stats.h — `Counter` :55 (value + rate tracking),
`CounterCollection` :63.  The `traceCounters` :111 periodic-emission role
lives in flow/metrics.py (`emit_metrics`), which emits every counter of a
MetricsRegistry — registries adopt these Counter objects directly.
"""

from __future__ import annotations

from typing import Dict


class Counter:
    __slots__ = ("name", "value", "_last", "_last_t")

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self._last = 0
        # Rate baseline is established LAZILY at the first rate query: an
        # eager 0.0 would make the first rate span "since time zero", which
        # for a counter created late in a long run reports a wildly diluted
        # rate (and a bogus large one for time-zero counters observed
        # early).
        self._last_t = None

    def add(self, n: int = 1):
        self.value += n

    def rate_since_last(self, now: float) -> float:
        if self._last_t is None:
            # First observation: no span to rate over yet.
            self._last = self.value
            self._last_t = now
            return 0.0
        dt = now - self._last_t
        r = (self.value - self._last) / dt if dt > 0 else 0.0
        self._last = self.value
        self._last_t = now
        return r


class ContinuousSample:
    """Bounded reservoir of a metric's recent distribution with percentile
    queries (ref: fdbrpc/ContinuousSample.h — the structure behind the
    status doc's latency percentiles).

    Uses the caller's DeterministicRandom so sampling stays seed-
    reproducible in simulation (the global `random` module is banned in
    sim code paths)."""

    __slots__ = ("size", "rng", "samples", "n", "_min", "_max")

    def __init__(self, rng, size: int = 500):
        self.size = size
        self.rng = rng
        self.samples: list = []
        self.n = 0
        self._min = None
        self._max = None

    def add(self, x: float):
        self.n += 1
        self._min = x if self._min is None else min(self._min, x)
        self._max = x if self._max is None else max(self._max, x)
        if len(self.samples) < self.size:
            self.samples.append(x)
        elif self.rng.random01() < self.size / self.n:
            self.samples[int(self.rng.random_int(0, self.size))] = x

    def percentile(self, p: float):
        if not self.samples:
            return None
        s = sorted(self.samples)
        return s[min(len(s) - 1, int(p * len(s)))]

    @property
    def min(self):
        return self._min

    @property
    def max(self):
        return self._max

    def summary(self) -> dict:
        """The status-doc shape (ref: the latency_probe / *_latency fields
        in Status.actor.cpp's qos section)."""
        return {
            "count": self.n,
            "min": self._min,
            "median": self.percentile(0.5),
            "p90": self.percentile(0.90),
            "p99": self.percentile(0.99),
            "max": self._max,
        }


class CounterCollection:
    def __init__(self, name: str):
        self.name = name
        self.counters: Dict[str, Counter] = {}

    def counter(self, name: str) -> Counter:
        c = self.counters.get(name)
        if c is None:
            c = self.counters[name] = Counter(name)
        return c

    def add(self, name: str, n: int = 1):
        self.counter(name).add(n)

    def __getitem__(self, name: str) -> int:
        return self.counter(name).value

    def snapshot(self) -> Dict[str, int]:
        return {k: c.value for k, c in self.counters.items()}
