"""Hot-path discipline: the runtime half of perfcheck (ISSUE 20).

PR 19 drove the resolver's host fraction from ~0.24 to ~0.06 (columnar
mirror apply + zero-copy batch encode), but nothing *enforced* those
wins: one innocent ``np.asarray(device_array)`` inside the pipelined
dispatch->sync window, or a per-row Python loop over mirror columns,
silently regresses the overlap the kernels x pipeline x shards campaign
depends on.  This module provides the two runtime pieces the static
pass (tools/lint/hotpath.py) twins with:

``@hot_path(bound=...)``
    Declares a function part of the per-batch hot set with an explicit
    complexity bound — ``"batch"`` (O(batch rows)), ``"chunks"``
    (O(chunks touched since last sync), the Jiffy mirror contract) or
    ``"const"`` (O(1), no data-dependent loops).  Zero runtime
    overhead: the decorator tags the function and records it in a
    registry; perfcheck's HOT002/HOT003/HOT004 check the declared bound
    against loop/allocation facts statically.

``GuardedDeviceValue`` / ``g_hostguard``
    The dynamic twin of HOT001.  With FDB_TPU_TRANSFER_GUARD on, the
    engine wraps every DispatchTicket device field in a proxy that
    raises TransferGuardError on any implicit host materialization
    (np.asarray / int() / float() / bool() / len() / iteration /
    .item() / indexing) outside a sanctioned sync scope.  This is
    deliberately NOT jax.transfer_guard: on the CPU backend device
    buffers alias host memory and jax's guard never fires (zero-copy
    reads are exempt), so sim runs would pass while TPU runs raise.
    The proxy raises identically on every backend; the engine
    ADDITIONALLY arms jax.transfer_guard_device_to_host around the
    dispatch window so real accelerators catch transfers on values the
    proxy does not wrap.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Dict

HOT_BOUNDS = ("batch", "chunks", "const")

# "module.qualname" -> declared bound, for diagnostics and tests.  The
# static pass does NOT import this (it matches the decorator by name in
# the AST); the registry exists so runtime tooling can enumerate the
# declared hot set.
_REGISTRY: Dict[str, str] = {}


def hot_path(bound: str = "batch"):
    """Declare a per-batch hot-path function with an explicit bound.

    bound="batch":  work is O(rows of the batch being served)
    bound="chunks": work is O(mirror chunks touched since last sync)
    bound="const":  no data-dependent Python loops at all
    """
    if bound not in HOT_BOUNDS:
        raise ValueError(
            f"hot_path bound must be one of {HOT_BOUNDS}, got {bound!r}"
        )

    def mark(fn):
        fn.__hot_path_bound__ = bound
        _REGISTRY[f"{fn.__module__}.{fn.__qualname__}"] = bound
        return fn

    return mark


def hot_registry() -> Dict[str, str]:
    """Snapshot of the declared hot set ("module.qualname" -> bound)."""
    return dict(_REGISTRY)


class TransferGuardError(RuntimeError):
    """An implicit device->host sync hit a guarded in-flight value."""


class HostSyncGuard:
    """Scope tracker for sanctioned device->host sync points.

    Guarded values block host materialization unless the read happens
    inside an ``allowed()`` scope — the engine enters one at each
    declared sync point (sync_ticket / store_to / the breaker's mirror
    replay path), which is exactly the HOT001 sanction set.  Reentrant;
    the simulator is single-threaded so a depth counter suffices."""

    def __init__(self):
        self._allow_depth = 0

    def blocking(self) -> bool:
        return self._allow_depth == 0

    @contextmanager
    def allowed(self):
        self._allow_depth += 1
        try:
            yield
        finally:
            self._allow_depth -= 1


g_hostguard = HostSyncGuard()


class GuardedDeviceValue:
    """Proxy around an in-flight device value (a DispatchTicket field).

    Any implicit host materialization outside a sanctioned sync scope
    raises TransferGuardError — the sim-deterministic analog of
    jax.transfer_guard("disallow") over the dispatch->sync window.
    Reads inside a sanctioned scope delegate to the wrapped value, so
    the declared sync points behave byte-identically with the guard on
    or off (the guard only ever raises or is a no-op)."""

    __slots__ = ("_v", "_label")

    def __init__(self, v, label: str):
        self._v = v
        self._label = label

    def unwrap(self):
        """The wrapped device value, without a guard check (for code
        that forwards the value WITHOUT materializing it host-side)."""
        return self._v

    def _read(self, op: str):
        if g_hostguard.blocking():
            raise TransferGuardError(
                f"implicit device->host sync: {op} on in-flight "
                f"{self._label} outside a sanctioned sync point "
                "(sync_ticket / store_to / breaker replay).  This is "
                "HOT001's dynamic twin (FDB_TPU_TRANSFER_GUARD): a "
                "hidden sync here blocks the host inside the pipelined "
                "dispatch->sync window and kills pipeline overlap."
            )
        return self._v

    # -- implicit host materializations ---------------------------------
    def __array__(self, dtype=None, copy=None):
        import numpy as np

        a = np.asarray(self._read(f"np.asarray({self._label})"))
        if dtype is not None:
            a = a.astype(dtype, copy=False)
        return a

    def __int__(self):
        return int(self._read(f"int({self._label})"))

    def __float__(self):
        return float(self._read(f"float({self._label})"))

    def __bool__(self):
        return bool(self._read(f"bool({self._label})"))

    def __index__(self):
        return int(self._read(f"index({self._label})"))

    def __len__(self):
        return len(self._read(f"len({self._label})"))

    def __iter__(self):
        return iter(self._read(f"iteration over {self._label}"))

    def __getitem__(self, idx):
        return self._read(f"indexing {self._label}")[idx]

    def item(self):
        return self._read(f"{self._label}.item()").item()

    def tolist(self):
        return self._read(f"{self._label}.tolist()").tolist()

    def __repr__(self):
        return f"GuardedDeviceValue({self._label})"
