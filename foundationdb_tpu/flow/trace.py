"""Structured trace events; ref flow/Trace.h:101 (TraceEvent builder).

The reference writes rolled XML trace files per process with severity,
throttling, and a builder API: TraceEvent("Name").detail("K", v).  We keep
the builder shape and collect events into an in-memory collector (optionally
spooling to JSON-lines files), which the simulator and tests can query.
"""

from __future__ import annotations

import json
import time
from typing import Any, Optional


def _event_now() -> float:
    """Timestamp for an emitted event: ALWAYS the current loop's virtual
    time when a loop is set — wall clock in a trace would break same-seed
    trace reproducibility (SURVEY.md §5).  The wall read below is the
    real-mode fallback for tools that trace before any loop exists."""
    from .eventloop import _current_loop

    if _current_loop is not None:
        return _current_loop.now()
    return time.time()  # fdblint: ignore[DET001]: real-mode fallback only; under simulation a loop is always set and the branch above wins


class Severity:
    Debug = 5
    Info = 10
    Warn = 20
    WarnAlways = 30
    Error = 40


class TraceCollector:
    """Destination for trace events (per process or global).

    Both modes additionally keep a BOUNDED recent-events ring (deque,
    maxlen = FDB_TPU_TRACE_RECENT at construction): the most recent N
    emitted events, in order.  It is what `find()` searches on a
    file-backed collector (the spool remains the durable record; the
    ring is the diagnosable window) and what the flight recorder dumps
    into incident captures."""

    def __init__(self, path: Optional[str] = None, min_severity: int = Severity.Info):
        from collections import deque

        from .knobs import g_env

        self.events: list[dict] = []
        self.path = path
        self.min_severity = min_severity
        self._fh = open(path, "a") if path else None  # fdblint: ignore[IO001]: trace spooling writes a real file by definition; sim tests use the in-memory collector (path=None)
        self.counts: dict[str, int] = {}
        self.recent_maxlen = max(1, g_env.get_int("FDB_TPU_TRACE_RECENT"))
        self.recent: deque = deque(maxlen=self.recent_maxlen)

    def emit(self, event: dict):
        if event["Severity"] < self.min_severity:
            return
        self.counts[event["Type"]] = self.counts.get(event["Type"], 0) + 1
        self.recent.append(event)
        if self._fh:
            # File-backed: spool only, so long runs stay bounded in memory
            # (the reference rolls trace files for the same reason); the
            # bounded `recent` ring above is the only retention.
            self._fh.write(json.dumps(event) + "\n")
        else:
            self.events.append(event)

    def find(self, type_: str) -> list[dict]:
        """Events of one type.  In-memory collectors search the full
        retained list; file-backed collectors search the bounded
        `recent` ring ONLY (the last FDB_TPU_TRACE_RECENT emitted
        events) — an event older than the ring is on disk, not here, so
        compare against `counts[type_]` when completeness matters."""
        if self.path is not None:
            return [e for e in self.recent if e["Type"] == type_]
        return [e for e in self.events if e["Type"] == type_]

    def recent_events(self) -> list[dict]:
        """The bounded most-recent window (both modes, oldest first) —
        the flight recorder's per-capture event dump."""
        return list(self.recent)

    def clear(self):
        """Reset the in-memory view (events + counts + recent ring).  For
        file-backed collectors the spool file is an append log and is
        deliberately left intact (clearing state must not destroy the
        on-disk record)."""
        self.events.clear()
        self.counts.clear()
        self.recent.clear()

    def close(self):
        if self._fh:
            self._fh.close()
            self._fh = None


_global_collector = TraceCollector()


def set_global_collector(c: TraceCollector):
    global _global_collector
    _global_collector = c


def global_collector() -> TraceCollector:
    return _global_collector


def trace_batch(type_: str, location: str, debug_id) -> None:
    """The g_traceBatch analog (ref: flow/Trace.h TraceBatch + the
    CommitDebug/TransactionDebug stage chains, NativeAPI.actor.cpp:2376,
    Resolver.actor.cpp:84): one event per pipeline stage, keyed by the
    SAMPLED transaction's debug id so the latency chain
    client -> proxy -> resolver -> log -> reply can be reassembled.
    No-op for unsampled work (debug_id None), which bounds volume."""
    if debug_id is None:
        return
    TraceEvent(type_).detail("ID", debug_id).detail("Location", location).log()


class TraceEvent:
    """Builder: TraceEvent("Name").detail("Key", value) — emits on context exit
    or explicitly via log(); auto-emits when garbage collected is NOT relied
    upon (unlike the reference's destructor emit) — call .log() or use `with`.
    """

    __slots__ = ("type", "severity", "fields", "_collector", "_emitted")

    def __init__(self, type_: str, severity: int = Severity.Info, collector: Optional[TraceCollector] = None):
        self.type = type_
        self.severity = severity
        self.fields: dict[str, Any] = {}
        self._collector = collector or _global_collector
        self._emitted = False

    def detail(self, key: str, value) -> "TraceEvent":
        self.fields[key] = value
        return self

    def error(self, err: BaseException) -> "TraceEvent":
        self.fields["Error"] = str(err)
        if self.severity < Severity.Error:
            self.severity = Severity.Error
        return self

    def log(self, now: Optional[float] = None):
        if self._emitted:
            return
        self._emitted = True
        if now is None:
            now = _event_now()
        ev = {"Type": self.type, "Severity": self.severity, "Time": now}
        ev.update(self.fields)
        self._collector.emit(ev)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is not None and "Error" not in self.fields:
            self.fields["Error"] = str(exc)
            self.severity = max(self.severity, Severity.Error)
        self.log()
        return False
