"""Error model mirroring the reference's flow/Error.h + error code registry.

The reference defines errors in flow/error_definitions.h as (name, code,
description) triples; errors propagate through futures and actors.  We keep
the same codes so behavior (retry classification, client API surface) matches.
"""

from __future__ import annotations

_ERRORS: dict[str, int] = {
    # name -> code  (subset of flow/error_definitions.h, codes verified
    # against the reference file)
    "success": 0,
    "end_of_stream": 1,
    "operation_failed": 1000,
    "wrong_shard_server": 1001,
    "timed_out": 1004,
    "coordinated_state_conflict": 1005,
    "all_alternatives_failed": 1006,
    "transaction_too_old": 1007,
    "no_more_servers": 1008,
    "future_version": 1009,
    "movekeys_conflict": 1010,
    "tlog_stopped": 1011,
    "server_request_queue_full": 1012,
    "not_committed": 1020,
    "commit_unknown_result": 1021,
    "transaction_cancelled": 1025,
    "connection_failed": 1026,
    "coordinators_changed": 1027,
    "new_coordinators_timed_out": 1028,
    "watch_cancelled": 1029,
    "request_maybe_delivered": 1030,
    "transaction_timed_out": 1031,
    "too_many_watches": 1032,
    "locality_information_unavailable": 1033,
    "watches_disabled": 1034,
    "accessed_unreadable": 1036,
    "process_behind": 1037,
    "database_locked": 1038,
    # Proxy GRV admission shedding (ref: proxy_memory_limit_exceeded /
    # batch_transaction_throttled in later error_definitions.h revisions):
    # the default lane sheds with the former, the batch-priority lane —
    # which starves first under overload — with the latter.  Both are
    # retryable; clients back off exponentially with jitter.
    "proxy_memory_limit_exceeded": 1042,
    "batch_transaction_throttled": 1051,
    "broken_promise": 1100,
    "actor_cancelled": 1101,  # reference name: operation_cancelled
    "recruitment_failed": 1200,
    "move_to_removed_server": 1201,
    "worker_removed": 1202,
    "master_recovery_failed": 1203,
    "master_max_versions_in_flight": 1204,
    "master_tlog_failed": 1205,
    "worker_recovery_failed": 1206,
    "please_reboot": 1207,
    "please_reboot_delete": 1208,
    "master_proxy_failed": 1209,
    "master_resolver_failed": 1210,
    # Rebuild-specific (no 6.0 analog code): a fresh replacement tlog was
    # asked for versions predating its recruitment; the peeker must fail
    # over to a surviving replica of its tag.
    "peek_below_begin": 1211,
    # Rebuild-specific: a coordinator quorum change named an address with
    # no registered worker — the request is unsatisfiable and rejected
    # (the 6.0 changeQuorum surfaces this as CoordinatorsResult, not an
    # error code).
    "no_such_worker": 1212,
    # Rebuild-specific: WRITING_CSTATE found a newer generation already
    # locked — this recovery must abort, not regress the chain (the 6.0
    # equivalent surfaces via coordinated_state_conflict in
    # MovableCoordinatedState).
    "recovery_superseded": 1213,
    # Directory-layer errors (rebuild-specific codes in an unused range;
    # the 6.0 bindings raise language exceptions for these, but the
    # rebuild keeps the one-error-type model).
    "directory_already_exists": 2131,
    "directory_does_not_exist": 2132,
    "directory_incompatible_layer": 2133,
    "directory_moved_under_itself": 2134,
    "directory_prefix_not_empty": 2135,
    "platform_error": 1500,
    "io_error": 1510,
    "file_not_found": 1511,
    "bind_failed": 1512,
    "file_not_readable": 1513,
    "file_not_writable": 1514,
    "file_too_large": 1516,
    "checksum_failed": 1520,
    "io_timeout": 1521,
    "file_corrupt": 1522,
    "client_invalid_operation": 2000,
    "commit_read_incomplete": 2002,
    "key_outside_legal_range": 2004,
    "inverted_range": 2005,
    "invalid_option_value": 2006,
    "invalid_option": 2007,
    "network_not_setup": 2008,
    "read_version_already_set": 2010,
    "version_invalid": 2011,
    "range_limits_invalid": 2012,
    "used_during_commit": 2017,
    "invalid_mutation_type": 2018,
    "transaction_invalid_version": 2020,
    "environment_variable_network_option_failed": 2022,
    "transaction_read_only": 2023,
    "incompatible_protocol_version": 2100,
    "key_too_large": 2102,
    "value_too_large": 2103,
    "unsupported_operation": 2108,
    "http_bad_response": 2150,
    "restore_error": 2301,
    "restore_invalid_version": 2315,
    # Internal: a shard fetch observed its AddingShard replaced mid-page
    # (storage._fetch_pages); consumed by the fetch retry loop only.
    "fetch_superseded": 2317,
    "internal_error": 4100,
}

_CODE_TO_NAME = {v: k for k, v in _ERRORS.items()}


def error_code(name: str) -> int:
    return _ERRORS[name]


class FdbError(Exception):
    """An error with a stable numeric code, as in the reference's Error class.

    `detail` is an optional structured cause riding the error (ISSUE 17) —
    the reference's Error carries only the code, and fdbserver reports a
    conflict as a bare not_committed; here the proxy attaches the combined
    abort witness {"version", "range", "range_index"} so the client's
    on_error can retry AT the conflicting version instead of paying a
    fresh GRV round-trip.  Absent (None) on every pre-witness error path:
    the wire format and equality of bare errors are unchanged."""

    __slots__ = ("code", "name", "detail")

    def __init__(self, name_or_code, detail=None):
        if isinstance(name_or_code, int):
            self.code = name_or_code
            self.name = _CODE_TO_NAME.get(name_or_code, f"error_{name_or_code}")
        else:
            self.name = name_or_code
            self.code = _ERRORS[name_or_code]
        self.detail = detail
        super().__init__(f"{self.name} ({self.code})")

    def is_retryable_in_transaction(self) -> bool:
        # Matches Transaction::onError's retry set (ref:
        # fdbclient/NativeAPI.actor.cpp onError): these reset and retry.
        return self.name in (
            "not_committed",
            "commit_unknown_result",
            "transaction_too_old",
            "future_version",
            "process_behind",
            "database_locked",
            "proxy_memory_limit_exceeded",
            "batch_transaction_throttled",
        )


class ActorCancelled(FdbError):
    """Raised inside a coroutine when its Task is cancelled.

    Subclasses BaseException semantics are not needed; flow treats
    actor_cancelled as an ordinary error that must not be swallowed.
    """

    def __init__(self):
        super().__init__("actor_cancelled")


class SimulationFailure(Exception):
    """An actor crashed with a non-FdbError exception (a genuine bug, not a
    simulated fault).  The event loop surfaces this immediately from
    run_until so a broken role constructor fails every test loudly instead
    of hanging the cluster (the reference crashes the process on broken
    invariants; determinism-as-sanitizer, SURVEY §5)."""


def internal_error(msg: str = "") -> FdbError:
    e = FdbError("internal_error")
    if msg:
        e.args = (f"internal_error (4100): {msg}",)
    return e
