"""DeterministicRandom: the single seeded RNG all simulation randomness uses.

Ref: flow/DeterministicRandom.h:30 (random01 :47, randomInt :53,
randomUniqueID, randomAlphaNumeric).  The reference routes *every* random
decision in simulation through g_random so runs are bit-reproducible from the
seed; we keep that property.  Each EventLoop owns one DeterministicRandom;
code must never use the global `random` module or wall-clock entropy in sim.
"""

from __future__ import annotations

import math
import random as _pyrandom  # fdblint: ignore[DET002]: this module IS the sanctioned wrapper — it only ever instantiates seeded Random objects


class UID:
    """128-bit unique id, as in flow/IRandom.h's UID."""

    __slots__ = ("first", "second")

    def __init__(self, first: int, second: int):
        self.first = first & 0xFFFFFFFFFFFFFFFF
        self.second = second & 0xFFFFFFFFFFFFFFFF

    def __repr__(self):
        return f"{self.first:016x}{self.second:016x}"

    def short_string(self):
        return f"{self.first:016x}"[:8]

    def __eq__(self, other):
        return (
            isinstance(other, UID)
            and self.first == other.first
            and self.second == other.second
        )

    def __hash__(self):
        return hash((self.first, self.second))

    def __lt__(self, other):
        return (self.first, self.second) < (other.first, other.second)


class DeterministicRandom:
    __slots__ = ("_r", "seed")

    def __init__(self, seed: int):
        self.seed = seed
        self._r = _pyrandom.Random(seed)  # fdblint: ignore[DET002]: a seeded private Random instance is the determinism mechanism itself

    # --- core API (mirrors flow/IRandom.h) ---
    def random01(self) -> float:
        return self._r.random()

    def random_int(self, lo: int, hi: int) -> int:
        """Uniform integer in [lo, hi). Matches randomInt's half-open range."""
        if hi <= lo:
            raise ValueError(f"random_int empty range [{lo},{hi})")
        return self._r.randrange(lo, hi)

    def random_int64(self, lo: int, hi: int) -> int:
        return self._r.randrange(lo, hi)

    def random_unique_id(self) -> UID:
        return UID(self._r.getrandbits(64), self._r.getrandbits(64))

    def random_alpha_numeric(self, length: int) -> str:
        chars = "abcdefghijklmnopqrstuvwxyz0123456789"
        return "".join(chars[self._r.randrange(0, 36)] for _ in range(length))

    def random_bytes(self, length: int) -> bytes:
        return bytes(self._r.getrandbits(8) for _ in range(length))

    def random_choice(self, seq):
        return seq[self._r.randrange(0, len(seq))]

    def random_shuffle(self, seq: list) -> None:
        self._r.shuffle(seq)

    def random_exp(self, mean: float) -> float:
        """Exponentially distributed, used for simulated latencies."""
        return -math.log(1.0 - self._r.random()) * mean

    def random_skewed_uint32(self, lo: int, hi: int) -> int:
        """Log-uniform in [lo, hi), as DeterministicRandom::randomSkewedUInt32."""
        lmin = math.log2(max(lo, 1))
        lmax = math.log2(hi)
        return min(hi - 1, max(lo, int(2 ** (lmin + self._r.random() * (lmax - lmin)))))

    def coinflip(self) -> bool:
        return self._r.random() < 0.5

    def split(self) -> "DeterministicRandom":
        """Derive an independent deterministic child stream."""
        return DeterministicRandom(self._r.getrandbits(63))
