"""Latency-chain reassembly: g_traceBatch events -> per-stage durations.

Ref: the CommitDebug/TransactionDebug trace-batch chains
(NativeAPI.actor.cpp:2376, Resolver.actor.cpp:84) and the tooling habit of
joining them by debug id to see where a sampled transaction spent its
time.  `trace_batch()` (flow/trace.py) emits one event per pipeline stage
keyed by the sampled transaction's debug id; this module joins those
events back into client -> proxy -> resolver -> tlog -> reply stage
durations with percentile summaries, consumed by `tools/cli.py latency`
and the test gates.

Everything here is pure computation over already-collected events:
percentiles are exact (full sort, same index rule as ContinuousSample),
so summaries are byte-identical across same-seed runs by construction.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

# (stage name, from location, to location) in pipeline order.  A stage's
# duration is last(to) - first(from) within one debug id's chain — `first`
# and `last` because multi-resolver/multi-log batches emit the same
# location once per role, and the slowest replica is what the client
# waited on.
COMMIT_CHAIN: List[Tuple[str, str, str]] = [
    ("client->proxy", "NativeAPI.commit.Before",
     "MasterProxyServer.commitBatch.Before"),
    ("proxy.getVersion", "MasterProxyServer.commitBatch.Before",
     "MasterProxyServer.commitBatch.GotCommitVersion"),
    ("resolver", "Resolver.resolveBatch.Before",
     "Resolver.resolveBatch.After"),
    ("proxy.resolution", "MasterProxyServer.commitBatch.GotCommitVersion",
     "MasterProxyServer.commitBatch.AfterResolution"),
    ("tlog", "MasterProxyServer.commitBatch.AfterResolution",
     "MasterProxyServer.commitBatch.AfterLogPush"),
    ("reply", "MasterProxyServer.commitBatch.AfterLogPush",
     "NativeAPI.commit.After"),
    ("total", "NativeAPI.commit.Before", "NativeAPI.commit.After"),
]

GRV_CHAIN: List[Tuple[str, str, str]] = [
    ("client->proxy", "NativeAPI.getConsistentReadVersion.Before",
     "MasterProxyServer.serveGrv.GotRequest"),
    ("proxy.grv", "MasterProxyServer.serveGrv.GotRequest",
     "MasterProxyServer.serveGrv.Replied"),
    ("reply", "MasterProxyServer.serveGrv.Replied",
     "NativeAPI.getConsistentReadVersion.After"),
    ("total", "NativeAPI.getConsistentReadVersion.Before",
     "NativeAPI.getConsistentReadVersion.After"),
]


def chains(events: List[dict], type_: str) -> Dict[str, List[Tuple[str, float]]]:
    """Join trace events of one batch type by debug id: id -> time-ordered
    [(location, time)].  Events without an ID (unsampled) are skipped."""
    out: Dict[str, List[Tuple[str, float]]] = {}
    for e in events:
        if e.get("Type") != type_:
            continue
        did = e.get("ID")
        loc = e.get("Location")
        if did is None or loc is None:
            continue
        out.setdefault(did, []).append((loc, e["Time"]))
    for seq in out.values():
        seq.sort(key=lambda lt: lt[1])
    return out


def stage_durations(
    events: List[dict], type_: str, spec: List[Tuple[str, str, str]]
) -> Dict[str, List[float]]:
    """Per-stage duration samples across every reassembled chain.  A chain
    missing either endpoint of a stage contributes nothing to that stage
    (e.g. a GRV-only debug id never reaches the commit stages)."""
    out: Dict[str, List[float]] = {name: [] for name, _f, _t in spec}
    for seq in chains(events, type_).values():
        first: Dict[str, float] = {}
        last: Dict[str, float] = {}
        for loc, t in seq:
            first.setdefault(loc, t)
            last[loc] = t
        for name, frm, to in spec:
            if frm in first and to in last and last[to] >= first[frm]:
                out[name].append(last[to] - first[frm])
    return out


def percentile(samples: List[float], p: float) -> Optional[float]:
    """Exact percentile, same index rule as ContinuousSample.percentile."""
    if not samples:
        return None
    s = sorted(samples)
    return s[min(len(s) - 1, int(p * len(s)))]


def summarize_stages(
    events: List[dict], type_: str, spec: List[Tuple[str, str, str]]
) -> Dict[str, dict]:
    """Stage -> {count, p50, p90, p99, max}; the shape `cli latency`
    prints and the status-adjacent tooling consumes."""
    out: Dict[str, dict] = {}
    for name, samples in stage_durations(events, type_, spec).items():
        out[name] = {
            "count": len(samples),
            "p50": percentile(samples, 0.5),
            "p90": percentile(samples, 0.90),
            "p99": percentile(samples, 0.99),
            "max": max(samples) if samples else None,
        }
    return out


def latency_summary(events: List[dict]) -> dict:
    """The full reassembly: commit + GRV chains, in pipeline stage order."""
    return {
        "commit": summarize_stages(events, "CommitDebug", COMMIT_CHAIN),
        "grv": summarize_stages(events, "TransactionDebug", GRV_CHAIN),
    }
