"""Deterministic actor runtime: the rebuild of the reference's flow/ layer.

The reference implements actors via a C# source-to-source compiler
(flow/actorcompiler/ActorCompiler.cs) generating C++ callback state machines.
Python has native coroutines, so the actor compiler's job is done by
async/await; this package supplies the rest of the runtime: a deterministic
virtual-time event loop (ref: flow/Net2.actor.cpp run loop), futures
(ref: flow/flow.h SAV/Future/Promise), a seeded RNG through which *all*
simulation randomness flows (ref: flow/DeterministicRandom.h), structured
trace events (ref: flow/Trace.h), the knobs registry (ref: flow/Knobs.h) and
BUGGIFY fault-injection hooks (ref: flow/flow.h:50-67).
"""

from .error import FdbError, error_code, ActorCancelled
from .rng import DeterministicRandom
from .future import Future, Promise, PromiseStream, FutureStream
from .eventloop import (
    EventLoop,
    Task,
    TaskPriority,
    g_network,
    set_event_loop,
    current_loop,
)
from .trace import TraceEvent, Severity, TraceCollector
from .knobs import Knobs, FlowKnobs, ClientKnobs, ServerKnobs, g_knobs
from .buggify import buggify, set_buggify_enabled

__all__ = [
    "FdbError",
    "error_code",
    "ActorCancelled",
    "DeterministicRandom",
    "Future",
    "Promise",
    "PromiseStream",
    "FutureStream",
    "EventLoop",
    "Task",
    "TaskPriority",
    "g_network",
    "set_event_loop",
    "current_loop",
    "TraceEvent",
    "Severity",
    "TraceCollector",
    "Knobs",
    "FlowKnobs",
    "ClientKnobs",
    "ServerKnobs",
    "g_knobs",
    "buggify",
    "set_buggify_enabled",
]
