"""Simulated async files with crash-durability fault injection.

Ref: fdbrpc/IAsyncFile.h:32-63 (the async read/write/sync/truncate
contract); fdbrpc/AsyncFileNonDurable.actor.h:169 (KillMode {NO_CORRUPTION,
DROP_ONLY, FULL_CORRUPTION}) and :468-484 (each unsynced write is
independently dropped, applied partially, or bit-corrupted when the owning
machine dies) — this is how the reference proves crash durability, and the
property our DiskQueue/KV-store recovery tests rely on.

Durability model: a file holds `durable` bytes plus a list of pending
(offset, data) writes; sync() folds pending into durable.  On machine kill,
pending writes are resolved randomly per KillMode via the loop's
DeterministicRandom (seed-reproducible chaos).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..flow.error import FdbError
from ..flow.eventloop import TaskPriority
from ..rpc.network import SimNetwork, SimProcess


class KillMode:
    NO_CORRUPTION = 0  # writes always survive (a perfect disk)
    DROP_ONLY = 1  # unsynced writes may vanish, never corrupt
    FULL_CORRUPTION = 2  # unsynced writes may vanish, truncate, or corrupt


class _SimFile:
    """On-"disk" state, owned by the machine (survives process kills)."""

    __slots__ = ("name", "durable", "pending", "open_handles")

    def __init__(self, name: str):
        self.name = name
        self.durable = bytearray()
        # (offset, bytes) in issue order; folded into durable on sync
        self.pending: List[Tuple[int, bytes]] = []
        self.open_handles = 0

    def _apply(self, offset: int, data: bytes):
        end = offset + len(data)
        if len(self.durable) < end:
            self.durable.extend(b"\x00" * (end - len(self.durable)))
        self.durable[offset:end] = data

    def view(self) -> bytes:
        """Contents as seen by readers (pending writes visible, like an OS
        page cache)."""
        img = bytearray(self.durable)
        for off, data in self.pending:
            end = off + len(data)
            if len(img) < end:
                img.extend(b"\x00" * (end - len(img)))
            img[off:end] = data
        return bytes(img)

    def sync(self):
        for off, data in self.pending:
            self._apply(off, data)
        self.pending = []

    def crash(self, rng, kill_mode: int):
        """Resolve pending writes per the kill mode (ref :468-484)."""
        pending, self.pending = self.pending, []
        if kill_mode == KillMode.NO_CORRUPTION:
            for off, data in pending:
                self._apply(off, data)
            return
        for off, data in pending:
            roll = rng.random01()
            if roll < 0.4:
                continue  # dropped entirely
            if kill_mode == KillMode.DROP_ONLY or roll < 0.7:
                if rng.coinflip():
                    self._apply(off, data)  # survived whole
                else:
                    n = rng.random_int(0, len(data) + 1)
                    self._apply(off, data[:n])  # torn write (prefix)
            else:
                # FULL_CORRUPTION: flip bytes somewhere in the write
                buf = bytearray(data)
                if not buf:
                    continue  # nothing to corrupt in a zero-length write
                for _ in range(rng.random_int(1, max(2, len(buf) // 8))):
                    buf[rng.random_int(0, len(buf))] = rng.random_int(0, 256)
                self._apply(off, bytes(buf))


class SimFileSystem:
    """All machines' disks; register with a SimNetwork to get kill hooks."""

    def __init__(self, network: SimNetwork, kill_mode: int = KillMode.FULL_CORRUPTION):
        self.network = network
        self.kill_mode = kill_mode
        # (machine_id, filename) -> _SimFile
        self._files: Dict[Tuple[str, str], _SimFile] = {}

    def open(
        self, process: SimProcess, filename: str, create: bool = True
    ) -> "SimAsyncFile":
        key = (process.machine.machine_id, filename)
        f = self._files.get(key)
        if f is None:
            if not create:
                raise FdbError("file_not_found")
            f = _SimFile(filename)
            self._files[key] = f
        f.open_handles += 1
        return SimAsyncFile(self, process, f)

    def exists(self, process: SimProcess, filename: str) -> bool:
        return (process.machine.machine_id, filename) in self._files

    def delete(self, process: SimProcess, filename: str):
        self._files.pop((process.machine.machine_id, filename), None)

    def crash_machine(self, machine_id: str):
        """Resolve unsynced writes on every file of the machine; call when
        killing a machine (the disk survives, the cache does not)."""
        rng = self.network.loop.rng
        for (mid, _name), f in self._files.items():
            if mid == machine_id:
                f.crash(rng, self.kill_mode)


class SimAsyncFile:
    """Per-process handle; I/O completes after a simulated disk latency
    (ref: IAsyncFile futures; latencies from Sim2's disk model)."""

    def __init__(self, fs: SimFileSystem, process: SimProcess, f: _SimFile):
        self.fs = fs
        self.process = process
        self._f = f

    def _disk_delay(self) -> float:
        rng = self.fs.network.loop.rng
        return 0.00005 + 0.0002 * rng.random01()

    async def read(self, offset: int, length: int) -> bytes:
        await self.fs.network.loop.delay(
            self._disk_delay(), TaskPriority.DiskRead
        )
        self._check_alive()
        return self._f.view()[offset : offset + length]

    def read_sync(self, offset: int, length: int) -> bytes:
        """Zero-virtual-latency page read for engines whose read path is
        synchronous (the btree engine; the reference charges such reads to
        coro threads that likewise block the storage actor)."""
        self._check_alive()
        return self._f.view()[offset : offset + length]

    async def write(self, offset: int, data: bytes):
        await self.fs.network.loop.delay(
            self._disk_delay(), TaskPriority.DiskWrite
        )
        self._check_alive()
        self._f.pending.append((offset, bytes(data)))

    async def sync(self):
        """Everything written before this call is durable after it (ref:
        IAsyncFile::sync ordering contract)."""
        await self.fs.network.loop.delay(
            0.0002 + 0.002 * self.fs.network.loop.rng.random01(),
            TaskPriority.DiskWrite,
        )
        self._check_alive()
        self._f.sync()

    async def truncate(self, size: int):
        """Clip durable and pending state to `size`; must NOT promote
        pending writes to durable (a real ftruncate is not a sync)."""
        await self.fs.network.loop.delay(
            self._disk_delay(), TaskPriority.DiskWrite
        )
        self._check_alive()
        del self._f.durable[size:]
        clipped = []
        for off, data in self._f.pending:
            if off >= size:
                continue
            clipped.append((off, data[: size - off]))
        self._f.pending = clipped

    def size(self) -> int:
        return len(self._f.view())

    def _check_alive(self):
        if not self.process.alive:
            raise FdbError("io_error")
