"""BlobStore: S3-style object store endpoint + HTTP client with rate control.

Ref: fdbrpc/BlobStore.h:34 (`BlobStoreEndpoint` — blobstore:// URLs, bucket
object CRUD, requests/sec + bytes/sec throttles, retries) and
fdbrpc/HTTP.actor.cpp (the hand-rolled HTTP/1.1 client it rides).  The
rebuild keeps the same layering: a small HTTP/1.1 codec, a socket client
with token-bucket rate control and bounded retries, and an endpoint
offering put/get/delete/list.  `BlobStoreServer` is the in-repo test
double (the reference talks to real S3; backup tests here need a live
target on localhost, like the real-transport suite spawns real sockets).

Determinism note: this is a REAL-deployment component (sockets + wall
clock).  Calls from simulation tests run blocking-synchronously between
virtual-time steps, so the sim's event interleaving is unaffected.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Dict, List, Optional, Tuple
from urllib.parse import parse_qs, quote, unquote, urlparse

from ..flow.error import FdbError

MAX_OBJECT_BYTES = 1 << 30


# --------------------------------------------------------------------------
# HTTP/1.1 codec (the HTTP.actor.cpp analog: just what an object store needs)
# --------------------------------------------------------------------------


def build_request(method: str, path: str, headers: Dict[str, str],
                  body: bytes = b"") -> bytes:
    lines = [f"{method} {path} HTTP/1.1"]
    h = dict(headers)
    h.setdefault("Content-Length", str(len(body)))
    h.setdefault("Connection", "keep-alive")
    for k, v in h.items():
        lines.append(f"{k}: {v}")
    return ("\r\n".join(lines) + "\r\n\r\n").encode() + body


def _recv_until(sock: socket.socket, buf: bytearray, marker: bytes) -> int:
    while marker not in buf:
        chunk = sock.recv(65536)
        if not chunk:
            raise ConnectionError("peer closed mid-response")
        buf.extend(chunk)
    return buf.index(marker)


def read_response(sock: socket.socket) -> Tuple[int, Dict[str, str], bytes]:
    """(status, headers, body); Content-Length framing only (the test
    double never chunks)."""
    buf = bytearray()
    head_end = _recv_until(sock, buf, b"\r\n\r\n")
    head = bytes(buf[:head_end]).decode("latin-1")
    rest = bytearray(buf[head_end + 4:])
    lines = head.split("\r\n")
    parts = lines[0].split(" ", 2)
    if len(parts) < 2 or not parts[0].startswith("HTTP/"):
        raise FdbError("http_bad_response")
    try:
        status = int(parts[1])
    except ValueError:
        # A garbage status line must surface as the codec's own error,
        # not a ValueError escaping the error model (and the caller
        # drops the now-desynced connection before retrying).
        raise FdbError("http_bad_response") from None
    headers = {}
    for ln in lines[1:]:
        if ":" in ln:
            k, v = ln.split(":", 1)
            headers[k.strip().lower()] = v.strip()
    try:
        n = int(headers.get("content-length", "0"))
    except ValueError:
        raise FdbError("http_bad_response") from None
    if n < 0 or n > MAX_OBJECT_BYTES:
        raise FdbError("http_bad_response")
    while len(rest) < n:
        chunk = sock.recv(65536)
        if not chunk:
            raise ConnectionError("peer closed mid-body")
        rest.extend(chunk)
    return status, headers, bytes(rest[:n])


def parse_request(data: bytes) -> Optional[Tuple[str, str, Dict[str, str], bytes, int]]:
    """(method, path, headers, body, consumed), None if incomplete, or
    ValueError on a malformed request (bad request line / content-length)
    — servers answer 400 and close."""
    idx = data.find(b"\r\n\r\n")
    if idx < 0:
        return None
    head = data[:idx].decode("latin-1")
    lines = head.split("\r\n")
    req_parts = lines[0].split(" ", 2)
    if len(req_parts) != 3:
        raise ValueError("malformed request line")
    method, path, _ver = req_parts
    headers = {}
    for ln in lines[1:]:
        if ":" in ln:
            k, v = ln.split(":", 1)
            headers[k.strip().lower()] = v.strip()
    n = int(headers.get("content-length", "0"))  # ValueError -> 400
    if n < 0 or n > MAX_OBJECT_BYTES:
        raise ValueError("bad content-length")
    total = idx + 4 + n
    if len(data) < total:
        return None
    return method, path, headers, data[idx + 4: total], total


def build_response(status: int, body: bytes = b"",
                   headers: Optional[Dict[str, str]] = None) -> bytes:
    reason = {200: "OK", 204: "No Content", 404: "Not Found",
              400: "Bad Request", 500: "Internal Server Error"}.get(status, "X")
    h = {"Content-Length": str(len(body)), "Connection": "keep-alive"}
    h.update(headers or {})
    head = f"HTTP/1.1 {status} {reason}\r\n" + "".join(
        f"{k}: {v}\r\n" for k, v in h.items()
    )
    return head.encode() + b"\r\n" + body


# --------------------------------------------------------------------------
# Rate control (ref: BlobStoreEndpoint's requests_per_second +
# bytes-per-second knobs via a token bucket)
# --------------------------------------------------------------------------


class TokenBucket:
    """Token bucket; acquire() blocks until the charge is covered.
    rate=None disables (unlimited).

    Debt model: a charge larger than the burst is granted once the bucket
    is full and drives the balance negative, delaying later acquires —
    so oversized bodies are paced rather than deadlocked (a strict
    'tokens >= n' wait could never be satisfied for n > burst).

    The clock/sleep pair is injectable (tests pace with fake time); the
    default is wall time because rate control meters a REAL network —
    these two lines are the module's only sanctioned wall-clock bindings."""

    def __init__(self, rate: Optional[float], burst: Optional[float] = None,
                 clock=None, sleep=None):
        self.rate = rate
        self.burst = burst if burst is not None else max(rate or 0, 1.0)
        self.tokens = self.burst
        self._clock = clock or time.monotonic  # fdblint: ignore[DET001]: rate control meters the real network; sim tests leave rate=None or inject a fake clock
        self._sleep = sleep or time.sleep  # fdblint: ignore[DET001]: see clock above; injectable for deterministic tests
        self.t = self._clock()
        self._lock = threading.Lock()

    def acquire(self, n: float = 1.0):
        if self.rate is None:
            return
        while True:
            with self._lock:
                now = self._clock()
                self.tokens = min(
                    self.burst, self.tokens + (now - self.t) * self.rate
                )
                self.t = now
                need_tokens = min(n, self.burst)
                if self.tokens >= need_tokens:
                    self.tokens -= n  # may go negative: the debt model
                    return
                need = (need_tokens - self.tokens) / self.rate
            self._sleep(min(need, 0.05))


# --------------------------------------------------------------------------
# Endpoint (ref: BlobStoreEndpoint, fdbrpc/BlobStore.h:34)
# --------------------------------------------------------------------------


class BlobStoreEndpoint:
    """Client for one blob store: blobstore://host:port/bucket with
    optional knobs in the query string (requests_per_second,
    read_bytes_per_second, write_bytes_per_second, retries)."""

    def __init__(self, host: str, port: int, bucket: str,
                 requests_per_second: Optional[float] = None,
                 read_bytes_per_second: Optional[float] = None,
                 write_bytes_per_second: Optional[float] = None,
                 retries: int = 4):
        self.host, self.port, self.bucket = host, port, bucket
        self.retries = retries
        self.req_bucket = TokenBucket(requests_per_second)
        self.read_bucket = TokenBucket(read_bytes_per_second)
        self.write_bucket = TokenBucket(write_bytes_per_second)
        # Injectable retry-backoff sleep (wall by default: it paces real
        # reconnects; tests stub it to run the retry chain instantly).
        self._backoff_sleep = time.sleep  # fdblint: ignore[DET001]: backoff paces real socket reconnects; injectable for tests
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    @classmethod
    def from_url(cls, url: str) -> "BlobStoreEndpoint":
        u = urlparse(url)
        if u.scheme != "blobstore":
            raise ValueError(f"not a blobstore url: {url}")
        q = parse_qs(u.query)

        def knob(name):
            return float(q[name][0]) if name in q else None

        return cls(
            u.hostname or "127.0.0.1",
            u.port or 80,
            u.path.strip("/").split("/")[0] or "backup",
            requests_per_second=knob("requests_per_second"),
            read_bytes_per_second=knob("read_bytes_per_second"),
            write_bytes_per_second=knob("write_bytes_per_second"),
            retries=int(q.get("retries", ["4"])[0]),
        )

    # -- connection management (keep-alive, reconnect on failure) --
    def _connect(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(
                (self.host, self.port), timeout=30
            )
        return self._sock

    def _drop(self):
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def _request(self, method: str, path: str, body: bytes = b""):
        """One request with rate control + bounded retries (ref: the retry
        loop with backoff in BlobStoreEndpoint::doRequest)."""
        self.req_bucket.acquire()
        if method == "PUT":
            self.write_bucket.acquire(max(1, len(body)))
        err = None
        for attempt in range(self.retries + 1):
            failed = False
            with self._lock:
                try:
                    s = self._connect()
                    s.sendall(build_request(
                        method, path, {"Host": self.host}, body
                    ))
                    status, headers, data = read_response(s)
                except (OSError, FdbError) as e:
                    # OSError: connection broke.  FdbError (only
                    # http_bad_response here): the stream is desynced —
                    # a stale keep-alive socket served by a restarted
                    # peer, or a corrupted hop.  Same treatment either
                    # way: drop the socket and retry on a fresh one.
                    self._drop()
                    err = e
                    failed = True
            if failed:
                # Backoff OUTSIDE the lock: other threads' independent
                # requests must not stall behind this one's retry chain.
                self._backoff_sleep(min(0.1 * (2 ** attempt), 2.0))
                continue
            if method == "GET" and data:
                self.read_bucket.acquire(len(data))
            return status, headers, data
        raise FdbError("connection_failed") from err

    # -- object API --
    def _obj_path(self, name: str) -> str:
        return f"/{quote(self.bucket)}/{quote(name, safe='')}"

    def put_object(self, name: str, data: bytes) -> None:
        status, _h, _b = self._request("PUT", self._obj_path(name), data)
        if status != 200:
            raise FdbError("io_error")

    def get_object(self, name: str) -> bytes:
        status, _h, data = self._request("GET", self._obj_path(name))
        if status == 404:
            raise FdbError("file_not_found")
        if status != 200:
            raise FdbError("io_error")
        return data

    def delete_object(self, name: str) -> None:
        status, _h, _b = self._request("DELETE", self._obj_path(name))
        if status not in (200, 204, 404):
            raise FdbError("io_error")

    def object_exists(self, name: str) -> bool:
        """HEAD — existence costs O(1), not a body download charged
        against the read budget."""
        status, _h, _b = self._request("HEAD", self._obj_path(name))
        if status == 404:
            return False
        if status != 200:
            raise FdbError("io_error")
        return True

    def list_objects(self, prefix: str = "") -> List[str]:
        status, _h, data = self._request(
            "GET", f"/{quote(self.bucket)}?prefix={quote(prefix, safe='')}"
        )
        if status != 200:
            raise FdbError("io_error")
        return [unquote(n) for n in data.decode().split("\n") if n]

    def close(self):
        self._drop()


# --------------------------------------------------------------------------
# Test-double server (S3 stand-in on localhost; memory-backed)
# --------------------------------------------------------------------------


class BlobStoreServer:
    """Minimal object-store server: PUT/GET/DELETE /bucket/object and
    GET /bucket?prefix= listing.  Threaded blocking sockets; keep-alive."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.objects: Dict[Tuple[str, str], bytes] = {}
        self._lock = threading.Lock()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(16)
        self.host, self.port = self._srv.getsockname()
        self._stop = False
        self._threads: List[threading.Thread] = []
        self._conns: List[socket.socket] = []
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)

    @property
    def url(self) -> str:
        return f"blobstore://{self.host}:{self.port}/backup"

    def _accept_loop(self):
        while not self._stop:
            try:
                conn, _addr = self._srv.accept()
            except OSError:
                return
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            )
            t.start()
            self._threads.append(t)

    def kick_connections(self):
        """Close every live connection (keep-alive breakage injection for
        the client's reconnect path)."""
        for conn in list(self._conns):
            try:
                conn.close()
            except OSError:
                pass
        self._conns.clear()

    def _serve_conn(self, conn: socket.socket):
        self._conns.append(conn)
        buf = bytearray()
        try:
            while not self._stop:
                parsed = parse_request(bytes(buf))
                if parsed is None:
                    chunk = conn.recv(65536)
                    if not chunk:
                        return
                    buf.extend(chunk)
                    continue
                method, path, _headers, body, consumed = parsed
                del buf[:consumed]
                conn.sendall(self._handle(method, path, body))
        except ValueError:
            # Malformed request: answer 400 and close (a real server's
            # behavior; silently dying desyncs pipelined clients).  The
            # response must SAY close — promising keep-alive on a socket
            # about to shut would strand the next pipelined request.
            try:
                conn.sendall(
                    build_response(400, headers={"Connection": "close"})
                )
            except OSError:
                pass
        except OSError:
            pass
        finally:
            conn.close()

    def _handle(self, method: str, path: str, body: bytes) -> bytes:
        u = urlparse(path)
        parts = [p for p in u.path.split("/") if p]
        if not parts:
            return build_response(400)
        bucket = unquote(parts[0])
        if len(parts) == 1:
            if method != "GET":
                return build_response(400)
            prefix = unquote(parse_qs(u.query).get("prefix", [""])[0])
            with self._lock:
                names = sorted(
                    n for (b, n) in self.objects
                    if b == bucket and n.startswith(prefix)
                )
            return build_response(
                200, "\n".join(quote(n, safe="") for n in names).encode()
            )
        name = unquote(parts[1])
        key = (bucket, name)
        if method == "PUT":
            with self._lock:
                self.objects[key] = body
            return build_response(200)
        if method == "HEAD":
            with self._lock:
                data = self.objects.get(key)
            if data is None:
                return build_response(404)
            # Status + Content-Length, no body (HEAD semantics; the
            # client frames on the header so body must be empty AND the
            # advertised length must be 0 to keep keep-alive in sync).
            return build_response(200)
        if method == "GET":
            with self._lock:
                data = self.objects.get(key)
            if data is None:
                return build_response(404)
            return build_response(200, data)
        if method == "DELETE":
            with self._lock:
                self.objects.pop(key, None)
            return build_response(204)
        return build_response(400)

    def close(self):
        self._stop = True
        try:
            self._srv.close()
        except OSError:
            pass
