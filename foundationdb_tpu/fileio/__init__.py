"""Virtualized file I/O: the rebuild of the reference's IAsyncFile stack.

Ref: fdbrpc/IAsyncFile.h:32 (read/write/sync/truncate contract),
AsyncFileNonDurable.actor.h (simulation-only crash-durability model: writes
are only guaranteed after sync(); on a simulated kill, unsynced writes are
independently dropped, partially applied, or corrupted).  Files live in a
SimFileSystem keyed by machine, so a rebooted process on the same machine
recovers whatever "disk" state survived.
"""

from .simfile import SimFileSystem, SimAsyncFile, KillMode
from .diskqueue import DiskQueue
from .kvstore import KeyValueStoreMemory, open_engine
from .btree import BTreeKeyValueStore

__all__ = [
    "SimFileSystem",
    "SimAsyncFile",
    "KillMode",
    "DiskQueue",
    "KeyValueStoreMemory",
    "BTreeKeyValueStore",
    "open_engine",
]
