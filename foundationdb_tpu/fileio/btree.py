"""Copy-on-write B+tree storage engine: datasets larger than RAM.

The ssd-class IKeyValueStore (ref: fdbserver/KeyValueStoreSQLite.actor.cpp
fills this role in the reference; fdbserver/IKeyValueStore.h:38 is the
contract).  This is NOT a sqlite port — it is a shadow-paging design in the
LMDB family, chosen because it needs no WAL/rollback journal and its crash
story maps exactly onto the simulator's crash model:

- Fixed-size pages; pages 0/1 are alternating header slots (generation,
  root page, page count, free list, CRC).  Recovery picks the valid header
  with the higher generation.
- Every commit copies each modified node to FRESH pages (never overwriting
  pages the previous durable tree references), syncs the data, then writes
  + syncs one header.  A crash at any point leaves the previous
  generation's tree fully intact.
- Pages freed while building generation G become allocatable at G+1 (once
  header G is durable, no valid recovery can need the G-1 tree).
- A node whose serialization exceeds one page spills into a chained page
  list, so correctness never depends on fit; the size-based split policy
  keeps chains rare (oversized keys/values are the exception, not the rule).
- Reads are synchronous (read_sync) against the durable file plus the
  uncommitted in-memory overlay; memory is bounded by an LRU cache of
  parsed nodes plus the overlay — the tree itself can exceed RAM.

Node pages use a STRICT fixed binary format (length-prefixed fields, CRC
per chunk) and the header body rides the versioned wire codec — a
corrupted or hostile page fails the schema/CRC check loudly instead of
deserializing arbitrary objects (ref: the reference's checksummed page
formats, e.g. sqlite page checksums in KeyValueStoreSQLite.actor.cpp's
role).  Other deviations from the reference engine, by design: no
underfull-node merging and no background vacuum (free-list reuse bounds
steady-state growth; `leaked_pages` counts free-list overflow), count()
is exact only between commits (its one caller is the status doc).
"""

from __future__ import annotations

import zlib
from bisect import bisect_left, bisect_right
from typing import Dict, List, Optional, Tuple

from ..flow.error import FdbError
from ..rpc.wire import WireDecodeError, decode_frame, encode_frame

PAGE_SIZE = 16384  # one 10KB key + node overhead must fit comfortably
HEADER_MAGIC = b"FDBTBT02"  # bumped: strict node format + chunk CRCs
MAX_FREE_IN_HEADER = 1024  # beyond this, pages leak (counted, not lost data)
NODE_FORMAT_V = 1


def _encode_node(leaf: bool, keys: list, vals: list) -> bytes:
    """Strict page body: version, leaf flag, counted length-prefixed keys,
    then leaf values (length-prefixed) or branch child page ids (8B)."""
    parts = [
        bytes((NODE_FORMAT_V, 1 if leaf else 0)),
        len(keys).to_bytes(4, "big"),
    ]
    for k in keys:
        parts.append(len(k).to_bytes(4, "big"))
        parts.append(k)
    if leaf:
        for v in vals:
            parts.append(len(v).to_bytes(4, "big"))
            parts.append(v)
    else:
        for v in vals:
            parts.append(int(v).to_bytes(8, "big"))
    return b"".join(parts)


def _decode_node(data: bytes) -> Tuple[bool, list, list]:
    """Inverse of _encode_node; every bound is checked — malformed input
    raises file_corrupt, never produces an undersized node silently."""
    try:
        if data[0] != NODE_FORMAT_V or data[1] not in (0, 1):
            raise ValueError("bad node header")
        leaf = data[1] == 1
        n = int.from_bytes(data[2:6], "big")
        off = 6
        keys = []
        for _ in range(n):
            ln = int.from_bytes(data[off : off + 4], "big")
            off += 4
            if off + ln > len(data):
                raise ValueError("key overruns page")
            keys.append(data[off : off + ln])
            off += ln
        vals = []
        if leaf:
            for _ in range(n):
                ln = int.from_bytes(data[off : off + 4], "big")
                off += 4
                if off + ln > len(data):
                    raise ValueError("value overruns page")
                vals.append(data[off : off + ln])
                off += ln
        else:
            for _ in range(n + 1):
                if off + 8 > len(data):
                    raise ValueError("child id overruns page")
                vals.append(int.from_bytes(data[off : off + 8], "big"))
                off += 8
        if off != len(data):
            raise ValueError("trailing bytes in node page")
        return leaf, keys, vals
    except (ValueError, IndexError) as e:
        raise FdbError("file_corrupt") from e


class _Node:
    __slots__ = ("leaf", "keys", "vals")

    def __init__(self, leaf: bool, keys: list, vals: list):
        self.leaf = leaf
        self.keys = keys
        # leaf: vals[i] = value bytes for keys[i]
        # branch: vals = len(keys)+1 children, each an int page id (clean,
        #         on disk) or a _Node (dirty, in memory).  Child i covers
        #         [keys[i-1], keys[i]) with -inf/+inf at the edges, matching
        #         bisect_right descent.
        self.vals = vals

    def size_estimate(self) -> int:
        s = 64 + 16 * len(self.keys) + sum(len(k) for k in self.keys)
        if self.leaf:
            s += sum(len(v) for v in self.vals)
        else:
            s += 8 * len(self.vals)
        return s


class BTreeKeyValueStore:
    """IKeyValueStore over a COW B+tree (see module docstring)."""

    def __init__(self, file, page_size: int = PAGE_SIZE, cache_pages: int = 512):
        self._file = file
        self._ps = page_size
        self._cache_cap = cache_pages
        self._cache: Dict[int, _Node] = {}  # clean nodes, LRU by dict order
        self._gen = 0
        self._root = None  # int pid | _Node (dirty) | None (empty tree)
        self._npages = 2  # pages 0/1 reserved for headers
        self._free: List[int] = []  # allocatable now
        self._freed_this: List[int] = []  # allocatable next generation
        self._leaked = 0
        self._n_keys = 0
        # Uncommitted overlay: ordered op log, applied to the tree at
        # commit(); reads resolve through it first.
        self._ops: List[Tuple[str, bytes, bytes]] = []
        # FIFO commit gate (same pattern as DiskQueue.commit): the tree
        # mutation + flush + header write is NOT reentrant — concurrent
        # commits must serialize, each taking whatever ops are buffered at
        # its turn.
        self._commit_chain = None

    # ---------- lifecycle ----------
    @classmethod
    async def open(cls, fs, process, filename: str,
                   page_size: int = PAGE_SIZE,
                   cache_pages: int = 512) -> "BTreeKeyValueStore":
        f = fs.open(process, filename)
        kv = cls(f, page_size=page_size, cache_pages=cache_pages)
        best = None
        for slot in (0, 1):
            hdr = kv._parse_header(f.read_sync(slot * kv._ps, kv._ps))
            if hdr is not None and (best is None or hdr["gen"] > best["gen"]):
                best = hdr
        if best is not None:
            kv._gen = best["gen"]
            kv._root = best["root"]
            kv._npages = best["npages"]
            kv._free = list(best["free"])
            kv._leaked = best["leaked"]
            kv._n_keys = best["n_keys"]
        else:
            # Fresh file: make generation 0 durable so a crash before the
            # first commit still recovers an (empty) store.
            await kv._write_header()
        return kv

    def _parse_header(self, raw: bytes) -> Optional[dict]:
        if len(raw) >= 8 and raw[:6] == b"FDBTBT" and raw[:8] != HEADER_MAGIC:
            # A RECOGNIZED older/newer format must refuse loudly: treating
            # it as "no header" would reinitialize an empty store over real
            # data (the WAL's counterpart raises file_corrupt likewise).
            raise FdbError("file_corrupt")
        if len(raw) < 16 or raw[:8] != HEADER_MAGIC:
            return None
        length = int.from_bytes(raw[8:12], "big")
        crc = int.from_bytes(raw[12:16], "big")
        body = raw[16 : 16 + length]
        if len(body) < length or zlib.crc32(body) != crc:
            return None
        try:
            hdr = decode_frame(body)
            if not isinstance(hdr, dict):
                return None
            return hdr
        except WireDecodeError:
            return None

    async def _write_header(self):
        assert isinstance(self._root, (int, type(None)))
        body = encode_frame(
            {
                "gen": self._gen,
                "root": self._root,
                "npages": self._npages,
                "free": self._free,
                "leaked": self._leaked,
                "n_keys": self._n_keys,
            }
        )
        raw = (
            HEADER_MAGIC
            + len(body).to_bytes(4, "big")
            + zlib.crc32(body).to_bytes(4, "big")
            + body
        )
        assert len(raw) <= self._ps, "header overflowed a page"
        await self._file.write((self._gen % 2) * self._ps, raw)
        await self._file.sync()

    # ---------- page I/O ----------
    def _alloc(self) -> int:
        if self._free:
            return self._free.pop()
        pid = self._npages
        self._npages += 1
        return pid

    def _free_page_chain(self, pid: int):
        """Free a node's first page and its continuation chain."""
        while pid is not None:
            if len(self._freed_this) + len(self._free) < MAX_FREE_IN_HEADER:
                self._freed_this.append(pid)
            else:
                self._leaked += 1
            raw = self._file.read_sync(pid * self._ps, 12)
            nxt = int.from_bytes(raw[4:12], "big")
            pid = (nxt - 1) if nxt else None

    def _cache_put(self, pid: int, node: _Node):
        self._cache[pid] = node
        while len(self._cache) > self._cache_cap:
            self._cache.pop(next(iter(self._cache)))

    def _read_node(self, pid: int) -> _Node:
        node = self._cache.pop(pid, None)
        if node is not None:
            self._cache[pid] = node  # LRU bump
            return node
        chunks = []
        p = pid
        seen = set()
        while p is not None:
            if p in seen:
                # A corrupted nxt pointer forming a cycle must fail, not
                # loop forever (the CRC covers the header too, but belt
                # and braces for a colliding checksum).
                raise FdbError("file_corrupt")
            seen.add(p)
            raw = self._file.read_sync(p * self._ps, self._ps)
            clen = int.from_bytes(raw[:4], "big")
            nxt = int.from_bytes(raw[4:12], "big")
            crc = int.from_bytes(raw[12:16], "big")
            if clen > self._ps - 16:
                raise FdbError("file_corrupt")
            chunk = raw[16 : 16 + clen]
            # CRC spans the chunk header (clen, nxt) AND the payload: a
            # flipped nxt must fail here, not wander the page file.
            if zlib.crc32(raw[:12] + chunk) != crc:
                raise FdbError("file_corrupt")
            chunks.append(chunk)
            p = (nxt - 1) if nxt else None
        leaf, keys, vals = _decode_node(b"".join(chunks))
        node = _Node(leaf, keys, vals)
        self._cache_put(pid, node)
        return node

    async def _write_node(self, node: _Node) -> int:
        assert node.leaf or not any(isinstance(c, _Node) for c in node.vals), (
            "dirty child leaked into serialization; _flush must resolve "
            "children first"
        )
        data = _encode_node(node.leaf, node.keys, node.vals)
        room = self._ps - 16
        chunks = [data[i : i + room] for i in range(0, len(data), room)] or [b""]
        pids = [self._alloc() for _ in chunks]
        if len(chunks) > 1:
            from ..flow.testprobe import test_probe

            test_probe("btree_chained_node")
        for i, chunk in enumerate(chunks):
            nxt = (pids[i + 1] + 1) if i + 1 < len(chunks) else 0
            hdr = len(chunk).to_bytes(4, "big") + nxt.to_bytes(8, "big")
            await self._file.write(
                pids[i] * self._ps,
                hdr + zlib.crc32(hdr + chunk).to_bytes(4, "big") + chunk,
            )
        self._cache_put(pids[0], node)
        return pids[0]

    def _child(self, ptr) -> _Node:
        return ptr if isinstance(ptr, _Node) else self._read_node(ptr)

    def _cow(self, ptr) -> _Node:
        """COW: loading a child for modification.  A clean (on-disk) child's
        pages are freed and a mutable copy returned; a dirty child is
        already exclusively ours."""
        if isinstance(ptr, _Node):
            return ptr
        node = self._read_node(ptr)
        self._cache.pop(ptr, None)
        self._free_page_chain(ptr)
        return _Node(node.leaf, list(node.keys), list(node.vals))

    # ---------- tree ops (in-memory COW, run inside commit) ----------
    def _split_if_needed(self, node: _Node) -> List[Tuple[bytes, _Node]]:
        """[(separator-or-b'', node)] — one entry, or two after a split."""
        if node.size_estimate() <= self._ps - 64 or len(node.keys) < 2:
            return [(b"", node)]
        mid = len(node.keys) // 2
        if node.leaf:
            left = _Node(True, node.keys[:mid], node.vals[:mid])
            right = _Node(True, node.keys[mid:], node.vals[mid:])
            sep = right.keys[0]
        else:
            left = _Node(False, node.keys[:mid], node.vals[: mid + 1])
            right = _Node(False, node.keys[mid + 1 :], node.vals[mid + 1 :])
            sep = node.keys[mid]
        return [(b"", left), (sep, right)]

    def _insert(self, ptr, key: bytes, value: bytes) -> List[Tuple[bytes, _Node]]:
        node = self._cow(ptr)
        if node.leaf:
            i = bisect_left(node.keys, key)
            if i < len(node.keys) and node.keys[i] == key:
                node.vals[i] = value
            else:
                node.keys.insert(i, key)
                node.vals.insert(i, value)
                self._n_keys += 1
            return self._split_if_needed(node)
        i = bisect_right(node.keys, key)
        parts = self._insert(node.vals[i], key, value)
        node.vals[i] = parts[0][1]
        if len(parts) == 2:
            node.keys.insert(i, parts[1][0])
            node.vals.insert(i + 1, parts[1][1])
        return self._split_if_needed(node)

    def _clear(self, ptr, begin: bytes, end: bytes):
        """Remove [begin, end) from the subtree at ptr.
        Returns (new_ptr_or_None, changed) — new_ptr may be the original
        ptr (unchanged), a dirty _Node, or None (subtree emptied)."""
        node = self._child(ptr)
        if node.leaf:
            i = bisect_left(node.keys, begin)
            j = bisect_left(node.keys, end)
            if i == j:
                return ptr, False
            node = self._cow(ptr)
            self._n_keys -= j - i
            del node.keys[i:j]
            del node.vals[i:j]
            return (node, True) if node.keys else (None, True)
        # Branch: child i covers [keys[i-1], keys[i]) (edges open).
        new_children: List = []
        dropped = False
        changed = False
        for ci, child in enumerate(node.vals):
            lo = node.keys[ci - 1] if ci > 0 else None
            hi = node.keys[ci] if ci < len(node.keys) else None
            intersects = (lo is None or lo < end) and (hi is None or hi > begin)
            if not intersects:
                new_children.append(child)
                continue
            sub, sub_changed = self._clear(child, begin, end)
            changed = changed or sub_changed
            if sub is None:
                dropped = True
            else:
                new_children.append(sub)
        if not changed:
            return ptr, False
        node = self._cow(ptr)
        if not new_children:
            return None, True
        if len(new_children) == 1:
            # Collapse the single-child branch: the child replaces us.
            return new_children[0], True
        node.vals = new_children
        if dropped:
            # Separators must be rebuilt: first key of each child from 1..
            # (valid: it is > every key in the preceding child and <= every
            # key in its own).
            node.keys = [self._subtree_first_key(c) for c in new_children[1:]]
        else:
            # No child vanished; the old separators still bound the
            # surviving children correctly — but only keep the ones between
            # surviving children (none vanished, so all of them).
            node.keys = node.keys[: len(new_children) - 1]
        return node, True

    def _subtree_first_key(self, ptr) -> bytes:
        node = self._child(ptr)
        while not node.leaf:
            node = self._child(node.vals[0])
        assert node.keys, "empty leaf survived a clear"
        return node.keys[0]

    # ---------- reads ----------
    def read_value(self, key: bytes) -> Optional[bytes]:
        for op, a, b in reversed(self._ops):  # newest overlay op wins
            if op == "set" and a == key:
                return b
            if op == "clear" and a <= key < b:
                return None
        return self._tree_get(key)

    def _tree_get(self, key: bytes) -> Optional[bytes]:
        if self._root is None:
            return None
        node = self._child(self._root)
        while not node.leaf:
            node = self._child(node.vals[bisect_right(node.keys, key)])
        i = bisect_left(node.keys, key)
        if i < len(node.keys) and node.keys[i] == key:
            return node.vals[i]
        return None

    def _tree_scan(self, begin: bytes, end: bytes, reverse: bool = False):
        """Yield (k, v) of [begin, end) from the durable tree, in order."""
        if self._root is None:
            return

        def rec(node):
            if node.leaf:
                i = bisect_left(node.keys, begin)
                j = bisect_left(node.keys, end)
                rng = range(j - 1, i - 1, -1) if reverse else range(i, j)
                for t in rng:
                    yield node.keys[t], node.vals[t]
                return
            order = range(len(node.vals))
            if reverse:
                order = reversed(order)
            for ci in order:
                lo = node.keys[ci - 1] if ci > 0 else None
                hi = node.keys[ci] if ci < len(node.keys) else None
                if (lo is None or lo < end) and (hi is None or hi > begin):
                    yield from rec(self._child(node.vals[ci]))

        yield from rec(self._child(self._root))

    def _overlay_view(self, begin: bytes, end: bytes):
        """Resolve the op log over [begin, end): surviving sets + the clear
        intervals (a tree key under any clear is masked unless re-set)."""
        sets: Dict[bytes, bytes] = {}
        clears: List[Tuple[bytes, bytes]] = []
        for op, a, b in self._ops:
            if op == "set":
                if begin <= a < end:
                    sets[a] = b
            else:
                lo, hi = max(a, begin), min(b, end)
                if lo < hi:
                    clears.append((lo, hi))
                    for k in [k for k in sets if lo <= k < hi]:
                        del sets[k]
        return sets, clears

    def read_range(
        self,
        begin: bytes,
        end: bytes,
        limit: int = 1 << 30,
        reverse: bool = False,
    ) -> List[Tuple[bytes, bytes]]:
        sets, clears = self._overlay_view(begin, end)
        masked = lambda k: any(lo <= k < hi for lo, hi in clears)  # noqa: E731
        out: List[Tuple[bytes, bytes]] = []
        set_keys = sorted(sets, reverse=reverse)
        si = 0

        def before(a: bytes, b: bytes) -> bool:
            return a < b if not reverse else a > b

        for k, v in self._tree_scan(begin, end, reverse):
            while si < len(set_keys) and before(set_keys[si], k):
                out.append((set_keys[si], sets[set_keys[si]]))
                si += 1
                if len(out) >= limit:
                    return out
            if si < len(set_keys) and set_keys[si] == k:
                out.append((k, sets[k]))
                si += 1
            elif not masked(k):
                out.append((k, v))
            if len(out) >= limit:
                return out
        while si < len(set_keys) and len(out) < limit:
            out.append((set_keys[si], sets[set_keys[si]]))
            si += 1
        return out

    def read_keys_page(
        self, begin: bytes, end: bytes, limit: int, reverse: bool = False
    ) -> List[bytes]:
        return [k for k, _v in self.read_range(begin, end, limit, reverse)]

    def count(self) -> int:
        return self._n_keys  # exact between commits (see module docstring)

    @property
    def leaked_pages(self) -> int:
        return self._leaked

    def file_pages(self) -> int:
        return self._npages

    # ---------- writes ----------
    def set(self, key: bytes, value: bytes):
        self._ops.append(("set", key, value))

    def clear_range(self, begin: bytes, end: bytes):
        self._ops.append(("clear", begin, end))

    async def commit(self):
        from ..flow.future import Promise

        prev = self._commit_chain
        gate = Promise()
        self._commit_chain = gate.future
        if prev is not None:
            await prev
        try:
            await self._commit_locked()
        finally:
            gate.send(None)
            if self._commit_chain is gate.future:
                self._commit_chain = None

    async def _commit_locked(self):
        ops, self._ops = self._ops, []
        for op, a, b in ops:
            if op == "set":
                if self._root is None:
                    self._root = _Node(True, [a], [b])
                    self._n_keys += 1
                    continue
                parts = self._insert(self._root, a, b)
                if len(parts) == 1:
                    self._root = parts[0][1]
                else:
                    self._root = _Node(
                        False, [parts[1][0]], [parts[0][1], parts[1][1]]
                    )
            elif self._root is not None:
                self._root, _changed = self._clear(self._root, a, b)
        if isinstance(self._root, _Node):
            self._root = await self._flush(self._root)  # fdblint: ignore[RACE001]: _commit_locked is serialized by the commit chain gate — _root has exactly one writer in flight
        await self._file.sync()  # data pages durable before the header
        self._gen += 1
        # Pages freed building this generation go INTO the new header's
        # free list: once that header is durable they are genuinely
        # unreferenced, and a crash BEFORE it recovers the old header
        # (which still references them and never saw this free list).
        # Extending the in-memory list here is safe — no allocation happens
        # between this point and the header write — and deferring it past
        # _write_header (the old ordering) permanently leaked every
        # commit's COW'd working set on each crash: the pages were in
        # neither the tree, nor the durable free list, nor `leaked`.
        self._free.extend(self._freed_this)
        self._freed_this = []
        await self._write_header()

    async def _flush(self, node: _Node) -> int:
        if not node.leaf:
            for i, c in enumerate(node.vals):
                if isinstance(c, _Node):
                    node.vals[i] = await self._flush(c)
        return await self._write_node(node)
