"""ctypes wrapper for the native C++ key-value engine (cpp/kvstore.cpp).

Ref: fdbserver/KeyValueStoreMemory.actor.cpp — the reference's memory
storage engine (RAM key space + WAL + snapshot compaction), implemented in
C++ and driven from the event loop through a C ABI (pybind11 is not in
this image; ctypes is).  Implements the same IKeyValueStore surface as the
simulated engine, but against REAL files — the persistence backend for
real-transport deployments (tools/real_node.py --datadir).

Build: compiled on demand with g++ into cpp/libfdbtpu_kv.so (cached by
mtime), same pattern as the skiplist baseline.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO, "cpp", "kvstore.cpp")
_LIB = os.path.join(_REPO, "cpp", "libfdbtpu_kv.so")
_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", _LIB, _SRC],
            check=True,
            capture_output=True,
        )
    lib = ctypes.CDLL(_LIB)
    lib.kv_open.restype = ctypes.c_void_p
    lib.kv_open.argtypes = [ctypes.c_char_p]
    lib.kv_close.argtypes = [ctypes.c_void_p]
    lib.kv_set.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
        ctypes.c_char_p, ctypes.c_uint32,
    ]
    lib.kv_clear_range.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
        ctypes.c_char_p, ctypes.c_uint32,
    ]
    lib.kv_commit.argtypes = [ctypes.c_void_p]
    lib.kv_commit.restype = ctypes.c_int
    lib.kv_compact.argtypes = [ctypes.c_void_p]
    lib.kv_compact.restype = ctypes.c_int
    lib.kv_get.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_uint32),
    ]
    lib.kv_get.restype = ctypes.c_int
    lib.kv_range_open.restype = ctypes.c_void_p
    lib.kv_range_open.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
        ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint32, ctypes.c_int,
    ]
    lib.kv_range_next.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_uint32),
    ]
    lib.kv_range_next.restype = ctypes.c_int
    lib.kv_range_close.argtypes = [ctypes.c_void_p]
    lib.kv_count.argtypes = [ctypes.c_void_p]
    lib.kv_count.restype = ctypes.c_uint64
    lib.kv_set_compact_threshold.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    _lib = lib
    return lib


class NativeKeyValueStore:
    """IKeyValueStore over the C++ engine (same surface as the simulated
    KeyValueStoreMemory: set / clear_range / commit / read_value /
    read_range)."""

    def __init__(self, directory: str, compact_threshold: Optional[int] = None):
        lib = _load()
        self._lib = lib
        self._h = lib.kv_open(directory.encode())
        if not self._h:
            raise RuntimeError(f"kv_open failed for {directory}")
        if compact_threshold is not None:
            lib.kv_set_compact_threshold(self._h, compact_threshold)

    def set(self, key: bytes, value: bytes):
        self._lib.kv_set(self._h, key, len(key), value, len(value))

    def clear_range(self, begin: bytes, end: bytes):
        self._lib.kv_clear_range(self._h, begin, len(begin), end, len(end))

    async def commit(self):
        # The fsync happens in-process; at memory-engine scale it is a
        # short syscall, acceptable on the reactor thread (the reference
        # memory engine commits through the disk queue similarly).
        if self._lib.kv_commit(self._h) != 0:
            raise OSError("kv_commit failed")

    def compact(self):
        if self._lib.kv_compact(self._h) != 0:
            raise OSError("kv_compact failed")

    def read_value(self, key: bytes) -> Optional[bytes]:
        out = ctypes.c_char_p()
        out_len = ctypes.c_uint32()
        if not self._lib.kv_get(
            self._h, key, len(key), ctypes.byref(out), ctypes.byref(out_len)
        ):
            return None
        return ctypes.string_at(out, out_len.value)

    def read_range(
        self,
        begin: bytes,
        end: bytes,
        limit: int = 1 << 30,
        reverse: bool = False,
    ) -> List[Tuple[bytes, bytes]]:
        it = self._lib.kv_range_open(
            self._h, begin, len(begin), end, len(end), min(limit, 1 << 30),
            1 if reverse else 0,
        )
        rows = []
        k = ctypes.c_char_p()
        kl = ctypes.c_uint32()
        v = ctypes.c_char_p()
        vl = ctypes.c_uint32()
        try:
            while self._lib.kv_range_next(
                it, ctypes.byref(k), ctypes.byref(kl),
                ctypes.byref(v), ctypes.byref(vl),
            ):
                rows.append(
                    (ctypes.string_at(k, kl.value), ctypes.string_at(v, vl.value))
                )
        finally:
            self._lib.kv_range_close(it)
        return rows

    def read_keys_page(
        self, begin: bytes, end: bytes, limit: int, reverse: bool = False
    ):
        return [k for k, _v in self.read_range(begin, end, limit, reverse)]

    def count(self) -> int:
        return self._lib.kv_count(self._h)

    def close(self):
        if self._h:
            self._lib.kv_close(self._h)
            self._h = None
