"""IKeyValueStore + the memory engine (RAM map, disk-queue WAL + snapshot).

Ref: fdbserver/IKeyValueStore.h:38 (set/clear/commit/readValue/readRange
contract: mutations are visible immediately, durable when commit()'s future
fires) and KeyValueStoreMemory.actor.cpp (in-RAM IndexedSet whose ops are
logged to a DiskQueue, with periodic full snapshots pushed into the same
queue so the log can be popped).
"""

from __future__ import annotations

import zlib
from bisect import bisect_left, insort
from typing import Dict, List, Optional, Tuple

from ..flow.error import FdbError
from ..rpc.network import SimProcess
from .diskqueue import DiskQueue
from .simfile import SimFileSystem

WAL_FORMAT_V = 1


def _enc_pairs(tag: bytes, rows, ops: bool) -> bytes:
    """Strict WAL frame: tag, format version, then length-prefixed pairs
    (op records carry a 1-byte opcode).  No pickle touches the disk — a
    corrupted or hostile record fails the bounds check, it never
    deserializes arbitrary objects (the DiskQueue CRC already covers
    accidental torn writes)."""
    parts = [tag, bytes((WAL_FORMAT_V,))]
    for row in rows:
        if ops:
            op, a, b = row
            parts.append(b"\x00" if op == "set" else b"\x01")
        else:
            a, b = row
        parts.append(len(a).to_bytes(4, "big"))
        parts.append(a)
        parts.append(len(b).to_bytes(4, "big"))
        parts.append(b)
    return b"".join(parts)


def _dec_pairs(payload: bytes, ops: bool):
    """Inverse of _enc_pairs (minus the tag byte, already dispatched)."""
    try:
        if payload[0] != WAL_FORMAT_V:
            raise ValueError("bad WAL format version")
        off = 1
        out = []
        n = len(payload)
        while off < n:
            if ops:
                code = payload[off]
                if code > 1:
                    raise ValueError("bad opcode")
                off += 1
            la = int.from_bytes(payload[off : off + 4], "big")
            off += 4
            if off + la > n:
                raise ValueError("field overruns record")
            a = payload[off : off + la]
            off += la
            lb = int.from_bytes(payload[off : off + 4], "big")
            off += 4
            if off + lb > n:
                raise ValueError("field overruns record")
            b = payload[off : off + lb]
            off += lb
            out.append(("set" if code == 0 else "clear", a, b) if ops else (a, b))
        return out
    except (ValueError, IndexError) as e:
        raise FdbError("file_corrupt") from e


class IKeyValueStore:
    """The storage-engine contract (ref IKeyValueStore.h:38)."""

    def set(self, key: bytes, value: bytes):
        raise NotImplementedError

    def clear_range(self, begin: bytes, end: bytes):
        raise NotImplementedError

    async def commit(self):
        raise NotImplementedError

    def read_value(self, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def read_range(
        self, begin: bytes, end: bytes, limit: int = 1 << 30
    ) -> List[Tuple[bytes, bytes]]:
        raise NotImplementedError


async def open_engine(engine: str, fs, process, filename: str):
    """Engine factory (ref: openKVStore's type dispatch,
    KeyValueStoreMemory.actor.cpp / KeyValueStoreSQLite.actor.cpp)."""
    if engine.endswith("+compress"):
        return CompressedKeyValueStore(
            await open_engine(engine[: -len("+compress")], fs, process, filename)
        )
    if engine == "memory":
        return await KeyValueStoreMemory.open(fs, process, filename)
    if engine == "btree":
        from .btree import BTreeKeyValueStore

        return await BTreeKeyValueStore.open(fs, process, filename)
    raise ValueError(f"unknown storage engine {engine!r}")


class KeyValueStoreMemory(IKeyValueStore):
    """RAM map + WAL; recovery = last snapshot + subsequent op records."""

    SNAPSHOT_EVERY_BYTES = 1 << 20

    def __init__(self, queue: DiskQueue):
        self._q = queue
        self._data: Dict[bytes, bytes] = {}
        self._keys: List[bytes] = []
        self._uncommitted: List[Tuple[str, bytes, bytes]] = []
        self._seq = queue.popped_seq
        self._bytes_since_snapshot = 0

    # -- lifecycle --
    @classmethod
    async def open(
        cls, fs: SimFileSystem, process: SimProcess, filename: str
    ) -> "KeyValueStoreMemory":
        queue, records = await DiskQueue.open(fs, process, filename)
        kv = cls(queue)
        # Find the last complete snapshot, replay ops after it.
        snap_idx = None
        for i, (_seq, payload) in enumerate(records):
            if payload[:1] == b"S":
                snap_idx = i
        start = 0
        if snap_idx is not None:
            kv._data = dict(_dec_pairs(records[snap_idx][1][1:], ops=False))
            start = snap_idx + 1
        for seq, payload in records[start:]:
            if payload[:1] != b"O":
                continue
            for op, k, v in _dec_pairs(payload[1:], ops=True):
                kv._apply(op, k, v)
        kv._keys = sorted(kv._data)
        kv._seq = records[-1][0] if records else queue.popped_seq
        return kv

    # -- writes --
    def set(self, key: bytes, value: bytes):
        self._uncommitted.append(("set", key, value))
        self._apply("set", key, value, maintain_index=True)

    def clear_range(self, begin: bytes, end: bytes):
        self._uncommitted.append(("clear", begin, end))
        self._apply("clear", begin, end, maintain_index=True)

    def _apply(self, op: str, a: bytes, b: bytes, maintain_index: bool = False):
        if op == "set":
            if maintain_index and a not in self._data:
                insort(self._keys, a)
            self._data[a] = b
        else:
            if maintain_index:
                i = bisect_left(self._keys, a)
                j = bisect_left(self._keys, b)
                for k in self._keys[i:j]:
                    del self._data[k]
                del self._keys[i:j]
            else:
                for k in [k for k in self._data if a <= k < b]:
                    del self._data[k]

    async def commit(self):
        """Durable when returned (ref IKeyValueStore.h:43)."""
        ops, self._uncommitted = self._uncommitted, []
        self._seq += 1
        payload = _enc_pairs(b"O", ops, ops=True)
        self._q.push(self._seq, payload)
        self._bytes_since_snapshot += len(payload)
        await self._q.commit()
        if self._bytes_since_snapshot >= self.SNAPSHOT_EVERY_BYTES:
            await self._snapshot()

    async def _snapshot(self):
        """Push the full map, then pop everything before it (ref: the memory
        engine's interleaved snapshot chunks).

        Two-phase on purpose: the pop (header write) must only become
        durable AFTER the snapshot frame is — the crash model resolves
        pending writes independently, and a surviving popped pointer with a
        dropped snapshot frame would discard acknowledged records.
        """
        self._seq += 1
        self._q.push(
            self._seq, _enc_pairs(b"S", list(self._data.items()), ops=False)
        )
        await self._q.commit()  # phase 1: snapshot frame durable
        self._q.pop(self._seq - 1)
        await self._q.commit()  # phase 2: popped pointer durable
        self._bytes_since_snapshot = 0

    # -- reads --
    def read_value(self, key: bytes) -> Optional[bytes]:
        return self._data.get(key)

    def read_keys_page(
        self, begin: bytes, end: bytes, limit: int, reverse: bool = False
    ) -> List[bytes]:
        """Up to `limit` keys of [begin, end) in scan order (the base-key
        feed for the storage's window-over-base merge)."""
        i = bisect_left(self._keys, begin)
        j = bisect_left(self._keys, end)
        if reverse:
            lo = max(i, j - limit)
            return self._keys[lo:j][::-1]
        return self._keys[i : min(j, i + limit)]

    def count(self) -> int:
        return len(self._keys)

    def read_range(
        self, begin: bytes, end: bytes, limit: int = 1 << 30
    ) -> List[Tuple[bytes, bytes]]:
        i = bisect_left(self._keys, begin)
        j = bisect_left(self._keys, end)
        out = []
        for k in self._keys[i : min(j, i + limit)]:
            out.append((k, self._data[k]))
        return out


class CompressedKeyValueStore(IKeyValueStore):
    """Value-compressing wrapper over any engine (ref: the
    KeyValueStoreCompressTestData wrapper, fdbserver/
    KeyValueStoreCompressTestData.actor.cpp — exercises every caller
    against values whose stored form differs from their logical form).
    Keys stay raw (ordering/range semantics untouched); values zlib."""

    _MAGIC = b"\x01z"  # prefix distinguishes compressed from empty

    def __init__(self, inner):
        self.inner = inner

    # -- writes --
    def set(self, key: bytes, value: bytes):
        self.inner.set(key, self._MAGIC + zlib.compress(value, 1))

    def clear_range(self, begin: bytes, end: bytes):
        self.inner.clear_range(begin, end)

    async def commit(self):
        await self.inner.commit()

    # -- reads --
    def _load(self, raw: Optional[bytes]) -> Optional[bytes]:
        if raw is None:
            return None
        if not raw.startswith(self._MAGIC):
            raise FdbError("file_corrupt")
        try:
            return zlib.decompress(raw[len(self._MAGIC):])
        except zlib.error as e:
            raise FdbError("file_corrupt") from e

    def read_value(self, key: bytes) -> Optional[bytes]:
        return self._load(self.inner.read_value(key))

    def read_range(
        self, begin: bytes, end: bytes, limit: int = 1 << 30
    ) -> List[Tuple[bytes, bytes]]:
        return [
            (k, self._load(v))
            for k, v in self.inner.read_range(begin, end, limit)
        ]

    def read_keys_page(self, *a, **kw):
        return self.inner.read_keys_page(*a, **kw)

    def count(self) -> int:
        return self.inner.count()
