"""Real-disk IAsyncFile: the same surface the simulator's files expose,
backed by actual file descriptors.

Ref: fdbrpc/IAsyncFile.h:32-63 (read/write/sync/truncate/size) and its real
implementations (AsyncFileEIO / AsyncFileKAIO).  Those push syscalls onto
thread pools or kernel AIO; here the syscalls run inline on the reactor —
correct, and acceptable at the log/engine write sizes this framework
issues (the native storage engine batches the bulk work; a thread-pool
offload is a drop-in once profiles demand it).

With this, every consumer written against the simulated filesystem
(DiskQueue, TLog.recover, KeyValueStoreMemory) runs unchanged on real
disks — the file half of the sim<->real swap point.
"""

from __future__ import annotations

import os
from typing import Dict

from ..flow.error import FdbError


class RealFileSystem:
    """open/exists/delete keyed by filename under one base directory; the
    `process` argument exists for SimFileSystem signature compatibility and
    is ignored (a real OS process has exactly one filesystem)."""

    def __init__(self, base_dir: str):
        self.base_dir = base_dir
        os.makedirs(base_dir, exist_ok=True)
        self._open: Dict[str, "RealAsyncFile"] = {}

    def _path(self, filename: str) -> str:
        return os.path.join(self.base_dir, filename)

    def open(self, process, filename: str, create: bool = True) -> "RealAsyncFile":
        f = self._open.get(filename)
        if f is not None and f._fd is not None:
            return f
        path = self._path(filename)
        if not create and not os.path.exists(path):
            raise FdbError("file_not_found")
        f = RealAsyncFile(path)
        self._open[filename] = f
        return f

    def exists(self, process, filename: str) -> bool:
        return os.path.exists(self._path(filename))

    def delete(self, process, filename: str):
        f = self._open.pop(filename, None)
        if f is not None:
            f.close()
        try:
            os.unlink(self._path(filename))
        except FileNotFoundError:
            pass


class RealAsyncFile:
    def __init__(self, path: str):
        self.path = path
        self._fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o644)

    async def read(self, offset: int, length: int) -> bytes:
        return os.pread(self._fd, length, offset)

    def read_sync(self, offset: int, length: int) -> bytes:
        return os.pread(self._fd, length, offset)

    async def write(self, offset: int, data: bytes):
        os.pwrite(self._fd, data, offset)

    async def sync(self):
        os.fdatasync(self._fd)

    async def truncate(self, size: int):
        os.ftruncate(self._fd, size)

    def size(self) -> int:
        return os.fstat(self._fd).st_size

    def close(self):
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None
