"""DiskQueue: durable framed log with prefix-durability commit.

Ref: fdbserver/IDiskQueue.h:28 (push/pop/commit contract: after commit(),
everything pushed before it is durable; after a crash, the recovered log is
a *prefix* of what was pushed, containing at least everything committed) and
DiskQueue.actor.cpp (the two-file ring).  The rebuild uses a single append
file of CRC-framed records plus a checksummed header page holding the popped
pointer; a torn or corrupted frame ends the recovery scan, which is exactly
what yields prefix durability over the NonDurable crash model.
"""

from __future__ import annotations

import struct
import zlib
from typing import List, Optional, Tuple

from ..rpc.network import SimProcess
from .simfile import SimAsyncFile, SimFileSystem

_MAGIC = 0xD1
_HEADER_SIZE = 64
_FRAME_HDR = struct.Struct("<BQI I")  # magic, seq, len, crc(seq||payload)
_HEADER = struct.Struct("<QQI")  # popped_seq, tail_hint, crc


def _frame_crc(seq: int, payload: bytes) -> int:
    return zlib.crc32(seq.to_bytes(8, "little") + payload) & 0xFFFFFFFF


class DiskQueue:
    def __init__(self, file: SimAsyncFile):
        self._file = file
        self._tail = _HEADER_SIZE  # next write offset
        self._pending: List[Tuple[int, bytes]] = []
        self.popped_seq = 0
        self._header_dirty = False
        # FIFO commit serialization: commit() snapshots _tail and then
        # awaits disk writes; a second commit entering during that await
        # would capture the same tail and clobber the first commit's frames
        # (acked-data loss after recovery).  Callers with multiple actors
        # (e.g. the coordinator's read/write serve loops) are safe.
        self._commit_chain = None

    # -- lifecycle --
    @classmethod
    async def open(
        cls, fs: SimFileSystem, process: SimProcess, filename: str
    ) -> Tuple["DiskQueue", List[Tuple[int, bytes]]]:
        """Open/create; returns (queue, recovered records beyond popped)."""
        f = fs.open(process, filename)
        q = cls(f)
        recovered: List[Tuple[int, bytes]] = []
        img = await f.read(0, f.size())
        if len(img) >= _HEADER.size:
            popped, _tail_hint, crc = _HEADER.unpack_from(img, 0)
            if zlib.crc32(img[:16]) & 0xFFFFFFFF == crc:
                q.popped_seq = popped
        off = _HEADER_SIZE
        while off + _FRAME_HDR.size <= len(img):
            magic, seq, length, crc = _FRAME_HDR.unpack_from(img, off)
            payload = img[off + _FRAME_HDR.size : off + _FRAME_HDR.size + length]
            if (
                magic != _MAGIC
                or len(payload) != length
                or _frame_crc(seq, payload) != crc
            ):
                break  # torn/corrupt frame: the durable prefix ends here
            if seq > q.popped_seq:
                recovered.append((seq, bytes(payload)))
            off += _FRAME_HDR.size + length
        q._tail = off
        # Discard any trash beyond the valid prefix so new frames are never
        # misread as a continuation of a torn one.
        await f.truncate(off)
        return q, recovered

    # -- IDiskQueue contract --
    def push(self, seq: int, payload: bytes):
        """Buffer a record; durable only after the next commit() returns."""
        self._pending.append((seq, payload))

    async def commit(self):
        """Write buffered frames + header, fsync; prefix-durable on return.
        Concurrent calls are serialized FIFO (see __init__)."""
        from ..flow.future import Promise

        prev = self._commit_chain
        gate = Promise()
        self._commit_chain = gate.future
        if prev is not None:
            await prev
        try:
            await self._commit_locked()
        finally:
            gate.send(None)
            if self._commit_chain is gate.future:
                self._commit_chain = None

    async def _commit_locked(self):
        writes = []
        off = self._tail
        for seq, payload in self._pending:
            frame = (
                _FRAME_HDR.pack(
                    _MAGIC, seq, len(payload), _frame_crc(seq, payload)
                )
                + payload
            )
            writes.append((off, frame))
            off += len(frame)
        self._pending = []
        for w_off, data in writes:
            await self._file.write(w_off, data)
        self._tail = off  # fdblint: ignore[RACE001]: _commit_locked is serialized by the commit chain gate; appends land in _pending, never move _tail
        if self._header_dirty:
            # Clear the flag BEFORE the write's await: a pop() landing
            # while the header is in flight re-dirties it and the NEXT
            # commit persists the newer popped_seq.  Clearing after the
            # await erased that mark — the pop's progress was silently
            # dropped until some unrelated future pop re-dirtied the flag.
            self._header_dirty = False
            body = struct.pack("<QQ", self.popped_seq, self._tail)
            hdr = body + struct.pack("<I", zlib.crc32(body) & 0xFFFFFFFF)
            await self._file.write(0, hdr)
        await self._file.sync()

    def pop(self, up_to_seq: int):
        """Logically discard records with seq <= up_to_seq (persisted with
        the next commit; space reclaim is a compaction concern, ref
        DiskQueue's file-ring recycling)."""
        if up_to_seq > self.popped_seq:
            self.popped_seq = up_to_seq
            self._header_dirty = True
