"""foundationdb_tpu: a from-scratch, TPU-native rebuild of FoundationDB 6.0.

Layering mirrors the reference's strict layer map (see SURVEY.md section 1):

  flow/      - deterministic actor runtime (ref: flow/)
  rpc/       - typed endpoints + simulated/real transport (ref: fdbrpc/)
  conflict/  - MVCC conflict-detection engines, the TPU north star
               (ref: fdbserver/SkipList.cpp behind fdbserver/ConflictSet.h)
  client/    - transaction API with read-your-writes (ref: fdbclient/)
  server/    - cluster roles: master, proxy, resolver, tlog, storage
               (ref: fdbserver/)
  sim/       - deterministic cluster simulation + workloads
               (ref: fdbrpc/sim2.actor.cpp, fdbserver/SimulatedCluster.actor.cpp)
  parallel/  - multi-device (Mesh/shard_map) sharding of the data plane
  ops/       - JAX/XLA kernel helpers (sorts, range-max, stabbing queries)

The compute hot path (whole-batch conflict resolution) runs on TPU via JAX;
the control plane is a deterministic single-threaded actor runtime, preserving
the reference's simulation-first testing property.
"""

__version__ = "0.1.0"
