from .rangemap import RangeMap

__all__ = ["RangeMap"]
