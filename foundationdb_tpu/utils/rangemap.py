"""RangeMap: a coalescing map from key ranges to values.

Ref: fdbclient/KeyRangeMap.h (krm* helpers over a coalesced map keyed by
range-begin; the value at key k is the value of the entry with the largest
begin <= k).  Used for the client location cache, storage ownership, the
proxy's key-server map, and DataDistribution's shard map.

Representation: sorted parallel arrays `begins` / `values`; begins[0] is
always b"" so every key has a value.  A range's extent runs to the next
begin (the last entry extends to +infinity).
"""

from __future__ import annotations

from bisect import bisect_right, bisect_left
from typing import Any, Iterator, List, Optional, Tuple


class RangeMap:
    __slots__ = ("begins", "values")

    def __init__(self, default: Any = None):
        self.begins: List[bytes] = [b""]
        self.values: List[Any] = [default]

    def __getitem__(self, key: bytes) -> Any:
        return self.values[bisect_right(self.begins, key) - 1]

    def range_containing(self, key: bytes) -> Tuple[bytes, Optional[bytes], Any]:
        """(begin, end_or_None, value) of the entry covering `key`."""
        i = bisect_right(self.begins, key) - 1
        end = self.begins[i + 1] if i + 1 < len(self.begins) else None
        return self.begins[i], end, self.values[i]

    def set_range(self, begin: bytes, end: Optional[bytes], value: Any):
        """Assign `value` on [begin, end); end=None means +infinity.
        Neighbouring equal values coalesce (ref: krmSetRangeCoalescing)."""
        assert end is None or begin < end, (begin, end)
        # Value that resumes at `end` (the old value there).
        if end is not None:
            resume = self[end]
        i0 = bisect_left(self.begins, begin)
        if end is None:
            i1 = len(self.begins)
        else:
            i1 = bisect_left(self.begins, end)
        new_b: List[bytes] = [begin]
        new_v: List[Any] = [value]
        if end is not None and not (i1 < len(self.begins) and self.begins[i1] == end):
            new_b.append(end)
            new_v.append(resume)
        self.begins[i0:i1] = new_b
        self.values[i0:i1] = new_v
        self._coalesce_around(i0, i0 + len(new_b))

    def _coalesce_around(self, lo: int, hi: int):
        """Merge equal-valued neighbours in begins[lo-1 : hi+1]."""
        i = max(1, lo - 1)
        stop = min(len(self.begins), hi + 1)
        while i < stop:
            if self.values[i] == self.values[i - 1]:
                del self.begins[i]
                del self.values[i]
                stop -= 1
            else:
                i += 1

    def insert_boundary(self, key: bytes, value: Any):
        """Boundary-entry semantics (ref: the krm* encoding of a range map as
        boundary keys): `value` applies from `key` up to the NEXT existing
        boundary, which is left intact.  Writers emit complete boundary sets
        (begin + resume entries) in one transaction, so applying each entry
        independently converges to the intended map."""
        i = bisect_left(self.begins, key)
        if i < len(self.begins) and self.begins[i] == key:
            self.values[i] = value
        else:
            self.begins.insert(i, key)
            self.values.insert(i, value)

    def intersecting(
        self, begin: bytes, end: Optional[bytes]
    ) -> Iterator[Tuple[bytes, Optional[bytes], Any]]:
        """Yield (clip_begin, clip_end_or_None, value) covering [begin, end),
        clipped to the query range, in key order."""
        i = bisect_right(self.begins, begin) - 1
        while i < len(self.begins):
            b = self.begins[i]
            e = self.begins[i + 1] if i + 1 < len(self.begins) else None
            if end is not None and b >= end:
                return
            cb = max(b, begin)
            ce = e if end is None else (min(e, end) if e is not None else end)
            if ce is None or cb < ce:
                yield cb, ce, self.values[i]
            i += 1

    def items(self) -> Iterator[Tuple[bytes, Optional[bytes], Any]]:
        return self.intersecting(b"", None)

    def __repr__(self):
        return f"RangeMap({list(self.items())!r})"
