"""IndexedSet: ordered map with subtree metric sums (order-statistic tree).

Ref: flow/IndexedSet.h — the reference's core container keeps a per-node
`total` of a metric over the subtree, giving O(log n) insert/erase,
range-sum (sumTo/sumRange), and metric-indexed search (index(metric) — the
key where a given amount of metric accumulates).  StorageMetrics' byte
sample rides exactly this to answer bytes-in-range and weighted split
points (StorageMetrics.actor.h:404).

Implementation: a treap (randomized BST) seeded by the caller's
DeterministicRandom so simulation stays seed-reproducible.  Each node
carries (key, weight) and aggregates subtree weight + count.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple


class _Node:
    __slots__ = ("key", "weight", "prio", "left", "right", "sum", "count")

    def __init__(self, key: bytes, weight: int, prio: int):
        self.key = key
        self.weight = weight
        self.prio = prio
        self.left: Optional[_Node] = None
        self.right: Optional[_Node] = None
        self.sum = weight
        self.count = 1


def _sum(n: Optional[_Node]) -> int:
    return n.sum if n is not None else 0


def _count(n: Optional[_Node]) -> int:
    return n.count if n is not None else 0


def _pull(n: _Node) -> _Node:
    n.sum = n.weight + _sum(n.left) + _sum(n.right)
    n.count = 1 + _count(n.left) + _count(n.right)
    return n


def _merge(a: Optional[_Node], b: Optional[_Node]) -> Optional[_Node]:
    """All keys in a < all keys in b."""
    if a is None:
        return b
    if b is None:
        return a
    if a.prio > b.prio:
        a.right = _merge(a.right, b)
        return _pull(a)
    b.left = _merge(a, b.left)
    return _pull(b)


def _split(n: Optional[_Node], key: bytes) -> Tuple[Optional[_Node], Optional[_Node]]:
    """(keys < key, keys >= key)."""
    if n is None:
        return None, None
    if n.key < key:
        lo, hi = _split(n.right, key)
        n.right = lo
        return _pull(n), hi
    lo, hi = _split(n.left, key)
    n.left = hi
    return lo, _pull(n)


class IndexedSet:
    """Ordered (key -> weight) with O(log n) everything the byte sample
    needs.  Requires an rng with random_int (flow.rng.DeterministicRandom)
    for treap priorities — determinism is a property, not an accident."""

    def __init__(self, rng):
        self.rng = rng
        self.root: Optional[_Node] = None
        self._weights: dict = {}  # key -> weight (O(1) membership)

    def __len__(self) -> int:
        return _count(self.root)

    def __contains__(self, key: bytes) -> bool:
        return key in self._weights

    def get(self, key: bytes) -> Optional[int]:
        return self._weights.get(key)

    # -- updates --
    def set(self, key: bytes, weight: int):
        if key in self._weights:
            self.erase(key)
        self._weights[key] = weight
        node = _Node(key, weight, int(self.rng.random_int(0, 1 << 62)))
        lo, hi = _split(self.root, key)
        self.root = _merge(_merge(lo, node), hi)

    def erase(self, key: bytes):
        if key not in self._weights:
            return
        del self._weights[key]
        lo, rest = _split(self.root, key)
        mid, hi = _split(rest, key + b"\x00")
        # mid holds exactly the erased key's node (keys are unique).
        self.root = _merge(lo, hi)

    def erase_range(self, begin: bytes, end: Optional[bytes]):
        """Drop every key in [begin, end) — O(log n + removed)."""
        lo, rest = _split(self.root, begin)
        if end is None:
            mid, hi = rest, None
        else:
            mid, hi = _split(rest, end)
        for k in _iter_keys(mid):
            del self._weights[k]
        self.root = _merge(lo, hi)

    # -- queries (ref: sumRange / index in IndexedSet.h) --
    def sum_range(self, begin: bytes, end: Optional[bytes]) -> int:
        """Total weight of keys in [begin, end)."""
        return self._sum_below(end) - self._sum_below(begin)

    def _sum_below(self, key: Optional[bytes]) -> int:
        """Total weight of keys strictly below `key` (None = all)."""
        if key is None:
            return _sum(self.root)
        total = 0
        n = self.root
        while n is not None:
            if n.key < key:
                total += n.weight + _sum(n.left)
                n = n.right
            else:
                n = n.left
        return total

    def count_range(self, begin: bytes, end: Optional[bytes]) -> int:
        return self._count_below(end) - self._count_below(begin)

    def _count_below(self, key: Optional[bytes]) -> int:
        if key is None:
            return _count(self.root)
        total = 0
        n = self.root
        while n is not None:
            if n.key < key:
                total += 1 + _count(n.left)
                n = n.right
            else:
                n = n.left
        return total

    def key_at_metric(self, begin: bytes, end: Optional[bytes],
                      metric: int) -> Optional[bytes]:
        """The first key in [begin, end) at which the accumulated weight
        from `begin` EXCEEDS `metric` (ref: IndexedSet::index — the
        weighted-split primitive).  None if the range's total never does."""
        if self.sum_range(begin, end) <= metric:
            return None
        target = self._sum_below(begin) + metric
        # Descend for the first key where sum-below(key inclusive) > target.
        n = self.root
        acc = 0
        result = None
        while n is not None:
            below_incl = acc + _sum(n.left) + n.weight
            if below_incl > target:
                result = n.key
                n = n.left
            else:
                acc = below_incl
                n = n.right
        return result

    def keys_in(self, begin: bytes, end: Optional[bytes]) -> List[bytes]:
        out: List[bytes] = []
        _collect(self.root, begin, end, out)
        return out


def _iter_keys(n: Optional[_Node]) -> Iterator[bytes]:
    if n is None:
        return
    yield from _iter_keys(n.left)
    yield n.key
    yield from _iter_keys(n.right)


def _collect(n: Optional[_Node], begin: bytes, end: Optional[bytes],
             out: List[bytes]):
    if n is None:
        return
    if n.key >= begin:
        _collect(n.left, begin, end, out)
        if end is None or n.key < end:
            out.append(n.key)
    if end is None or n.key < end:
        _collect(n.right, begin, end, out)
