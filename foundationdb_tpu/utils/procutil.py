"""Child-process hygiene helpers.

Orphaned `real_node` servers burned ~9% CPU each and depressed every
benchmark measured on this 1-core host by ~2.6x (round-3 verdict).  The
failure mode: a supervising process (monitor, pytest) is SIGKILLed, its
`finally`-block cleanup never runs, and the children reparent to init.

Fix: every child is spawned with PR_SET_PDEATHSIG so the KERNEL delivers
SIGKILL to the child the moment its parent dies — no cooperation from the
dying parent required.  Linux-only, which is the only platform here.

Ref: fdbmonitor/fdbmonitor.cpp kills its children on exit; this is the
uncooperative-death-proof equivalent.
"""

from __future__ import annotations

import ctypes
import signal

PR_SET_PDEATHSIG = 1

# Bound at import time: dlopen after fork() (inside preexec_fn) is not
# async-signal-safe and can deadlock a threaded spawner.
try:
    _libc = ctypes.CDLL("libc.so.6", use_errno=True)
except Exception:  # pragma: no cover - non-glibc platform
    _libc = None


def die_with_parent(sig: int = signal.SIGKILL) -> None:
    """Arrange for the kernel to send `sig` to the CALLING process when its
    parent dies.  Use as Popen(preexec_fn=die_with_parent) — it then runs in
    the child between fork and exec.  Best-effort: failures are ignored (a
    missing libc symbol must not break spawning)."""
    if _libc is None:
        return
    try:
        _libc.prctl(PR_SET_PDEATHSIG, int(sig), 0, 0, 0)
    except Exception:
        pass


def die_with_parent_term() -> None:
    """PDEATHSIG=SIGTERM variant: gives the child a chance to killpg its own
    helpers (PDEATHSIG is NOT inherited by grandchildren) before dying —
    see reap_group_on_term()."""
    die_with_parent(signal.SIGTERM)


def reap_group_on_term() -> None:
    """Install a SIGTERM handler that SIGKILLs the caller's whole process
    group (including helper grandchildren that PDEATHSIG does not cover)
    and exits.  Pair with die_with_parent_term() in the spawner: parent
    dies -> kernel TERMs the child -> child killpgs its session."""
    import os

    def _h(signum, frame):
        try:
            os.killpg(0, signal.SIGKILL)
        finally:  # pragma: no cover - killpg(0) includes ourselves
            os._exit(143)

    signal.signal(signal.SIGTERM, _h)


def install_graceful_term(stop_fn) -> None:
    """Graceful-then-hard SIGTERM ladder for long-running real-mode
    servers (real_node): the FIRST SIGTERM calls `stop_fn()` (e.g.
    RealNetwork.stop) so the reactor unwinds, the transport closes, and
    the process exits 0 — multi-process soak teardown sees an orderly
    shutdown instead of a kill -9 corpse.  A SECOND SIGTERM escalates to
    the reap_group_on_term() big hammer (SIGKILL the whole process group,
    exit 143), so a wedged shutdown can never leak orphans either."""
    import os

    state = {"termed": False}

    def _h(signum, frame):
        if state["termed"]:
            try:
                # killpg(0) only when WE lead the group: a spawner that
                # did not give us our own group (plain Popen) shares its
                # group with us, and nuking it would SIGKILL the test
                # session / soak driver itself.  Non-leaders exit alone —
                # their own children die via PDEATHSIG when they do.
                if os.getpid() == os.getpgrp():
                    os.killpg(0, signal.SIGKILL)
            finally:  # pragma: no cover - killpg(0) includes ourselves
                os._exit(143)
        state["termed"] = True
        try:
            stop_fn()
        except Exception:
            # Post-signal context: stopping failed, the second TERM (or
            # the spawner's PDEATHSIG) is the recovery path.
            pass

    signal.signal(signal.SIGTERM, _h)


def device_probe_argv(repo_root):
    """argv for a killable child that answers `jax.devices()` or dies at
    the caller's timeout — the ONLY safe way to test TPU-tunnel liveness on
    this host (in-process backend init can hang ~45 min).  Shared by
    bench.py's probe loop and tools/tunnel_watch.py."""
    import sys

    code = (
        f"import sys; sys.path.insert(0, {repo_root!r}); "
        "from foundationdb_tpu.utils.procutil import reap_group_on_term; "
        "reap_group_on_term(); "
        "import jax; print([str(d) for d in jax.devices()])"
    )
    return [sys.executable, "-c", code]


def run_killable(argv, timeout, stderr=None):
    """Run argv in its own session with a hard wall-clock timeout; on
    timeout SIGKILL the entire process group (pipes held open by helper
    grandchildren cannot extend the wait — the round-3 hang mode of
    subprocess.run).  Returns (returncode, stdout, stderr_text_or_None).
    Raises TimeoutError on timeout."""
    import os
    import subprocess

    proc = subprocess.Popen(
        argv,
        stdout=subprocess.PIPE,
        stderr=stderr if stderr is not None else subprocess.PIPE,
        text=True,
        start_new_session=True,
        preexec_fn=die_with_parent_term,
    )
    try:
        stdout, err = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, signal.SIGKILL)
        proc.wait()
        raise TimeoutError(f"{argv[0]} exceeded {timeout}s; process group killed")
    return proc.returncode, stdout, err
