"""Brute-force conflict oracle: obviously correct, O(batch * history).

Test-only differential baseline for the production engines.  Implements the
reference semantics (fdbserver/SkipList.cpp ConflictBatch) by direct
simulation: history is a flat list of committed write ranges with versions.
"""

from __future__ import annotations

from typing import List

from .types import CONFLICT, COMMITTED, TOO_OLD, TransactionConflictInfo, intersects


class OracleConflictSet:
    def __init__(self, oldest_version: int = 0):
        self.oldest_version = oldest_version
        # (begin, end, version) of every committed write still in the window
        self.history: list[tuple[bytes, bytes, int]] = []
        # Per-txn abort witness of the most recent detect() (ISSUE 17).
        self.last_witness: list = []

    def detect(
        self,
        transactions: List[TransactionConflictInfo],
        now: int,
        new_oldest_version: int,
    ) -> List[int]:
        statuses: list[int] = []
        # Abort witness (ISSUE 17), same rule as the production engines:
        # first conflicting read range; history conflicts report the max
        # committed version intersecting that range (== the step
        # function's range max), intra-batch conflicts report `now`.
        witness: list = []
        # Writes of in-batch committed txns, visible to later txns only.
        batch_writes: list[tuple[bytes, bytes]] = []
        for tr in transactions:
            # ref SkipList.cpp:985 addTransaction: tooOld needs read ranges
            if tr.read_snapshot < self.oldest_version and tr.read_ranges:
                statuses.append(TOO_OLD)
                witness.append(None)
                continue
            wtn = None
            for i, r in enumerate(tr.read_ranges):
                if any(
                    v > tr.read_snapshot and intersects(r, (b, e))
                    for (b, e, v) in self.history
                ):
                    wtn = (
                        max(
                            v
                            for (b, e, v) in self.history
                            if intersects(r, (b, e))
                        ),
                        i,
                    )
                    break
            if wtn is None:
                for i, r in enumerate(tr.read_ranges):
                    if any(intersects(r, w) for w in batch_writes):
                        wtn = (now, i)
                        break
            witness.append(wtn)
            if wtn is not None:
                statuses.append(CONFLICT)
            else:
                statuses.append(COMMITTED)
                batch_writes.extend(tr.write_ranges)
        self.last_witness = witness
        self.history.extend((b, e, now) for (b, e) in batch_writes)
        if new_oldest_version > self.oldest_version:
            self.oldest_version = new_oldest_version
            # Exact for queries with snapshot >= oldest: conflicts need v > snapshot
            self.history = [h for h in self.history if h[2] >= self.oldest_version]
        return statuses

    def clear(self, version: int):
        """Ref ConflictSet.h clearConflictSet."""
        self.history.clear()
        self.oldest_version = version
