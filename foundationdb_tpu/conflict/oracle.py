"""Brute-force conflict oracle: obviously correct, O(batch * history).

Test-only differential baseline for the production engines.  Implements the
reference semantics (fdbserver/SkipList.cpp ConflictBatch) by direct
simulation: history is a flat list of committed write ranges with versions.
"""

from __future__ import annotations

from typing import List

from .types import CONFLICT, COMMITTED, TOO_OLD, TransactionConflictInfo, intersects


class OracleConflictSet:
    def __init__(self, oldest_version: int = 0):
        self.oldest_version = oldest_version
        # (begin, end, version) of every committed write still in the window
        self.history: list[tuple[bytes, bytes, int]] = []

    def detect(
        self,
        transactions: List[TransactionConflictInfo],
        now: int,
        new_oldest_version: int,
    ) -> List[int]:
        statuses: list[int] = []
        # Writes of in-batch committed txns, visible to later txns only.
        batch_writes: list[tuple[bytes, bytes]] = []
        for tr in transactions:
            # ref SkipList.cpp:985 addTransaction: tooOld needs read ranges
            if tr.read_snapshot < self.oldest_version and tr.read_ranges:
                statuses.append(TOO_OLD)
                continue
            conflict = False
            for r in tr.read_ranges:
                for (b, e, v) in self.history:
                    if v > tr.read_snapshot and intersects(r, (b, e)):
                        conflict = True
                        break
                if conflict:
                    break
            if not conflict:
                for r in tr.read_ranges:
                    if any(intersects(r, w) for w in batch_writes):
                        conflict = True
                        break
            if conflict:
                statuses.append(CONFLICT)
            else:
                statuses.append(COMMITTED)
                batch_writes.extend(tr.write_ranges)
        self.history.extend((b, e, now) for (b, e) in batch_writes)
        if new_oldest_version > self.oldest_version:
            self.oldest_version = new_oldest_version
            # Exact for queries with snapshot >= oldest: conflicts need v > snapshot
            self.history = [h for h in self.history if h[2] >= self.oldest_version]
        return statuses

    def clear(self, version: int):
        """Ref ConflictSet.h clearConflictSet."""
        self.history.clear()
        self.oldest_version = version
