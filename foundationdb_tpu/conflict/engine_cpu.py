"""Host conflict engine: chunked step function with batch updates and
O(1) immutable snapshots (ISSUE 9, the Jiffy blueprint; columnar since
ISSUE 19).

Production CPU path AND the always-authoritative mirror behind the
device circuit breaker (api.ConflictSet).  Same data model as every
other engine — keys[i] starts the range [keys[i], keys[i+1)) whose
last-committed-write version is vers[i]; keys[0] is always b"" (the
floor) — but the flat sorted array is split into a sequence of IMMUTABLE
chunks (the batch-update skip-list nodes of Jiffy, "A Lock-free Skip
List with Batch Updates and Snapshots", PAPERS.md):

  - ``detect``/``apply_batch`` apply a batch's whole committed write
    union as ONE sweep: only chunks an interval touches are rewritten
    (copy-on-write), untouched chunks keep their identity.  No per-range
    O(H) list splices.
  - window eviction (ref SkipList::removeBefore) rewrites only chunks
    that actually hold a droppable boundary, decided from a per-chunk
    ``min_pair`` precomputed at chunk build time — when nothing is below
    the window the advance is an O(chunks) scan with ZERO rebuilds
    (``evict_skips`` counts them), not the flat engine's O(H) keep pass.
  - ``snapshot()`` is O(1): the chunk sequence is already an immutable
    tuple, so a snapshot is just a handle to it.  Snapshots taken every
    batch cost nothing; a handed-off snapshot can never observe a
    half-mutated mirror (the breaker's probe-rehydration safety).
  - ``boundary_count`` is an O(1) maintained count.

Columnar chunks (ISSUE 19): a chunk's boundaries are numpy COLUMNS —
``ek`` is the full device key encoding [n, key_words+1] uint32 (the
same array ``chunk_encoding`` used to cache per chunk; it is now the
primary representation, so device sync/rehydration re-encodes NOTHING
for chunks built at the engine's key_words), ``va`` the int64 versions,
and ``pfx`` an order-preserving uint64 prefix (the key's first 8 bytes,
big-endian, zero-padded).  Locates are ``np.searchsorted`` on ``pfx``
refined over full encoded rows only inside a prefix-tie run, and the
interval sweep / eviction assemble new chunks from column SLICES
instead of per-boundary Python list splices.  Byte keys materialize
lazily (``_Chunk.keys``) for diagnostics, flat views, and tie breaks on
unencodable queries; a chunk holding a key longer than 4*key_words
bytes stays bytes-primary (``ek is None``) and flips the engine onto
the verbatim per-boundary sweeps (``*_py``), which remain the
long-key/differential reference path.

Coalesced apply (ISSUE 19, ``FDB_TPU_MIRROR_COALESCE``): with
``coalesce_window`` > 1 the committed write unions of apply_batch()
queue in arrival order and fold into the chunk structure at the next
mirror READ (snapshot/detect/flat views/take_fresh_chunks/counts — the
barrier set) or every K batches, whichever comes first.  The fold
replays the queued batches SEQUENTIALLY: a merged one-sweep union is
NOT bit-exact, because batch k+1's end-boundary re-anchor values
(value_at(e)) and the eviction pair rule read the state batch k left
behind — and the device applies per batch, so the mirror must too
(mirror_check compares them byte-for-byte).  What coalescing buys is
every per-batch cost AROUND the sweep: O(1) apply_batch enqueue on the
serve path, one snapshot/sync-bookkeeping round per K batches instead
of K, and no intermediate fresh-chunk churn for the device encode-cache
walk.  Barriers make the deferral invisible: no reader can ever observe
a mirror that is missing a queued batch.

Chunk identity is the incremental-sync currency: the device engine
caches per-chunk key encodings on the chunk object itself
(engine_jax.note_synced / load_from), so probe rehydration re-encodes
only chunks created since the last device sync.

The pre-ISSUE-9 flat engine survives as engine_cpu_flat.FlatCpuConflictSet,
the differential oracle this engine is gated bit-identical against
(verdicts AND exported state) and the FDB_TPU_MIRROR_ENGINE=flat A/B arm.
"""

from __future__ import annotations

from bisect import bisect_left, bisect_right
from typing import List, Optional, Tuple

import numpy as np

from ..flow.hotpath import hot_path
from . import keys as keylib
from .engine_cpu_flat import (  # re-exported: the shared pieces
    FLOOR_VERSION,
    FlatCpuConflictSet,
    _IntervalSet,
)
from .types import CONFLICT, COMMITTED, TOO_OLD, TransactionConflictInfo

__all__ = [
    "CpuConflictSet",
    "FlatCpuConflictSet",
    "MirrorSnapshot",
    "FLOOR_VERSION",
    "slice_snapshot_chunks",
    "engine_from_handoff",
]

_PAIR_INF = 1 << 63  # "no droppable pair here" sentinel


def _default_key_words() -> int:
    from ..flow.knobs import g_knobs

    return g_knobs.server.conflict_device_key_words


def _pfx_of_key(k: bytes) -> np.uint64:
    """Order-preserving uint64 prefix: the key's first 8 bytes, big-endian,
    zero-padded.  a <= b (bytewise) implies pfx(a) <= pfx(b); ties (equal
    first 8 bytes) are refined over encoded rows or raw bytes.  Returned
    as np.uint64 so searchsorted never upcasts the comparison to float64
    (a python int > 2**63 would, silently losing low bits)."""
    return np.uint64(int.from_bytes(k[:8].ljust(8, b"\x00"), "big"))


def _pfx_from_ek(ek: np.ndarray) -> np.ndarray:
    """Vectorized prefix column from an encode_keys array: the first two
    data words ARE the first 8 bytes zero-padded (keys.py pads with
    b"\\x00"), so no byte round-trip is needed."""
    w0 = ek[:, 0].astype(np.uint64) << np.uint64(32)
    if ek.shape[1] >= 3:  # key_words >= 2: a second data word exists
        return w0 | ek[:, 1].astype(np.uint64)
    return w0  # key_words == 1: keys are <= 4 bytes, low half is zero


def _pfx_from_keys(keys: list) -> np.ndarray:
    buf = b"".join(k[:8].ljust(8, b"\x00") for k in keys)
    return np.frombuffer(buf, dtype=">u8").astype(np.uint64)


class _Chunk:
    """One immutable run of boundaries as numpy columns.  ``ek`` is the
    full device encoding [n, kw+1] uint32 (None only when the chunk holds
    a key longer than 4*kw bytes — then byte keys are primary), ``va``
    the int64 versions, ``pfx`` the uint64 order-preserving prefix
    column (always present).  All three are frozen after construction
    (copy-on-write: a mutation builds a new chunk).  ``min_pair`` is the
    smallest max(va[i-1], va[i]) over INTERNAL adjacent pairs — a
    boundary is evictable iff its pair-max is below the window, so a
    chunk whose min_pair is at or above the window provably holds
    nothing to drop (the cross-chunk first pair is checked by the
    caller, which knows the previous chunk's last version).  ``enc``
    holds device-encoding caches keyed by key_words (engine_jax) for
    key_words OTHER than the chunk's own — for the engine's own width,
    ``ek`` itself is the encoding and chunk_encoding returns it with
    zero work.  ``keys``/``vers`` materialize lazily (and cache) for
    flat views, diagnostics and unencodable-query tie breaks."""

    __slots__ = (
        "ek", "va", "pfx", "kw", "max_ver", "min_pair", "enc",
        "_keys", "_vers", "_key0",
    )

    def __init__(self, keys: list, vers: list, kw: Optional[int] = None):
        if kw is None:
            kw = _default_key_words()
        va = np.asarray(vers, dtype=np.int64)
        try:
            ek = keylib.encode_keys(keys, kw)
        except ValueError:
            ek = None  # long key: bytes stay primary
        pfx = _pfx_from_ek(ek) if ek is not None else _pfx_from_keys(keys)
        self._init_cols(ek, va, pfx, kw)
        self._keys = list(keys)
        self._key0 = self._keys[0]

    @classmethod
    def from_cols(
        cls, ek: np.ndarray, va: np.ndarray, pfx: np.ndarray, kw: int,
        mx: Optional[int] = None, mp: Optional[int] = None,
    ) -> "_Chunk":
        ch = object.__new__(cls)
        ch._init_cols(ek, va, pfx, kw, mx, mp)
        ch._keys = None
        ch._key0 = None
        return ch

    def _init_cols(self, ek, va, pfx, kw, mx=None, mp=None) -> None:
        self.ek = ek
        self.va = va
        self.pfx = pfx
        self.kw = kw
        # mx/mp: stats precomputed by the caller's bulk reduceat pass
        # (_flush_cols builds ~10^3 chunks per batch; per-chunk numpy
        # reductions here would dominate the rebuild cost).
        self.max_ver = int(va.max()) if mx is None else mx
        if mp is not None:
            self.min_pair = mp
        elif len(va) > 1:
            self.min_pair = int(np.maximum(va[:-1], va[1:]).min())
        else:
            self.min_pair = _PAIR_INF
        self.enc = None
        self._vers = None

    @property
    def keys(self) -> list:
        ks = self._keys
        if ks is None:
            ks = self._keys = keylib.decode_keys(self.ek, self.kw)
        return ks

    @property
    def vers(self) -> list:
        vs = self._vers
        if vs is None:
            vs = self._vers = self.va.tolist()
        return vs

    @property
    def key0(self) -> bytes:
        k0 = self._key0
        if k0 is None:
            if self._keys is not None:
                k0 = self._keys[0]
            else:
                k0 = keylib.decode_key(self.ek[0], self.kw)
            self._key0 = k0
        return k0

    @property
    def last_key(self) -> bytes:
        if self._keys is not None:
            return self._keys[-1]
        return keylib.decode_key(self.ek[-1], self.kw)

    def __len__(self):
        return len(self.va)


def _ch_bisect_rows(ch: _Chunk, qrow: np.ndarray, qpfx, side: str) -> int:
    """Row index where the ENCODED query row would insert (bisect_left /
    bisect_right semantics) — searchsorted on the prefix column, refined
    lexicographically over full encoded rows (words msw-first, length
    last == byte order, keys.py invariant) only inside a tie run.
    Requires ch.ek (the engine only takes this path when no chunk is
    bytes-primary)."""
    a = ch.pfx
    lo = int(np.searchsorted(a, qpfx, "left"))
    hi = int(np.searchsorted(a, qpfx, "right"))
    if lo == hi:
        return lo
    rows = ch.ek
    qt = qrow.tolist()
    if side == "left":
        while lo < hi:
            mid = (lo + hi) >> 1
            if rows[mid].tolist() < qt:
                lo = mid + 1
            else:
                hi = mid
    else:
        while lo < hi:
            mid = (lo + hi) >> 1
            if rows[mid].tolist() <= qt:
                lo = mid + 1
            else:
                hi = mid
    return lo


def _ch_bisect_key(ch: _Chunk, k: bytes, side: str) -> int:
    """Byte-key twin of _ch_bisect_rows for query keys that arrive as
    bytes (detect's read ranges, reshard cut points).  Tie runs refine
    over already-materialized byte keys when present, else by encoding
    the ONE query key (cheaper than decoding log(run) rows), falling
    back to byte materialization only for unencodable (long) queries."""
    a = ch.pfx
    qp = _pfx_of_key(k)
    lo = int(np.searchsorted(a, qp, "left"))
    hi = int(np.searchsorted(a, qp, "right"))
    if lo == hi:
        return lo
    if ch._keys is not None or ch.ek is None or len(k) > 4 * ch.kw:
        bis = bisect_left if side == "left" else bisect_right
        return bis(ch.keys, k, lo, hi)
    qrow = keylib.encode_keys([k], ch.kw)[0]
    return _ch_bisect_rows(ch, qrow, ch.pfx[lo], side)


class MirrorSnapshot:
    """O(1) immutable view of a CpuConflictSet at one instant.  Holding
    one is free (chunk refs are shared with the live engine and with
    every other snapshot); the live engine's later mutations replace
    chunks instead of editing them, so the view never changes.  ``stamp``
    increases with every mutation of the source engine — equal stamps
    mean identical state, and chunk identity across two snapshots means
    that key range did not change (the device sync diff)."""

    __slots__ = ("chunks", "oldest_version", "stamp", "boundary_count")

    def __init__(self, chunks: tuple, oldest_version: int, stamp: int,
                 boundary_count: int):
        self.chunks = chunks
        self.oldest_version = oldest_version
        self.stamp = stamp
        self.boundary_count = boundary_count

    def to_flat(self) -> Tuple[list, list]:
        """Materialize (keys, vers) lists — O(H), diagnostic/diff use."""
        ks: list = []
        vs: list = []
        for ch in self.chunks:
            ks.extend(ch.keys)
            vs.extend(ch.vers)
        return ks, vs


def _default_chunk_size() -> int:
    from ..flow.knobs import g_env

    return max(4, g_env.get_int("FDB_TPU_MIRROR_CHUNK"))


class CpuConflictSet:
    """Exact reference-semantics engine over chunked immutable runs.

    Decision- and state-identical to FlatCpuConflictSet (gated by
    tests/test_mirror_snapshot.py's differential fuzz); only the update
    cost model differs.  ``chunk`` is the target chunk size (default
    FDB_TPU_MIRROR_CHUNK); tests pass tiny values to force multi-chunk
    structures on small histories.  ``key_words`` fixes the columnar
    encoding width (default: the server knob, so the mirror's ``ek``
    columns ARE the device encoding and sync re-encodes nothing)."""

    def __init__(self, oldest_version: int = 0, chunk: Optional[int] = None,
                 key_words: Optional[int] = None):
        self._oldest = oldest_version
        self.chunk_size = chunk if chunk is not None else _default_chunk_size()
        self._kw = key_words if key_words is not None else _default_key_words()
        head = _Chunk([b""], [FLOOR_VERSION], self._kw)
        self._chunks: tuple = (head,)
        self._starts: list = [b""]
        self._count = 1
        self._any_long = head.ek is None
        self._stamp = 0
        self._flat: Optional[Tuple[list, list]] = None
        # Concatenated (ek, va, pfx, row offsets) over all chunks — the
        # vectorized sweep/locate workspace, invalidated by _set_chunks.
        self._g: Optional[tuple] = None
        # Per-txn abort witness of the most recent detect() (ISSUE 17).
        self.last_witness: list = []
        # Staged halves of a flat (keys, vers) adoption — see the property
        # setters: store_to-style callers assign .keys then .vers.
        self._staged_keys: Optional[list] = None
        # Coalesced apply (ISSUE 19): committed write unions queued by
        # apply_batch when coalesce_window > 1, folded (sequential
        # replay — see module docstring) at every read barrier or every
        # coalesce_window batches.
        self.coalesce_window = 1
        self._pending: list = []
        # Maintenance telemetry (deterministic ints, read by tests/bench/
        # device_metrics): batches that rewrote at least one chunk, chunks
        # rewritten, window advances that dropped nothing (the flat
        # engine's O(H) keep pass, skipped).
        self.chunks_rebuilt = 0
        self.evict_scans = 0
        self.evict_skips = 0
        # Chunks created since the last take_fresh_chunks(): the device
        # sync hint (engine_jax.note_synced encodes ONLY these instead of
        # walking every chunk).  Bounded: past _FRESH_CAP the list is
        # dropped and the consumer falls back to a full walk.
        self._fresh: list = []
        self._fresh_overflow = False

    _FRESH_CAP = 8192

    @property
    def oldest_version(self) -> int:
        # A queued (coalesced) batch may advance the window; _commit_writes
        # only ever advances _oldest to a LARGER new_oldest, so the
        # post-fold value is the max over the queue — report it without
        # forcing a flush (hot callers poll this per batch).
        if self._pending:
            return max(self._oldest, max(p[2] for p in self._pending))
        return self._oldest

    @oldest_version.setter
    def oldest_version(self, v: int) -> None:
        self._oldest = v

    @property
    def key_words(self) -> int:
        return self._kw

    def _track_fresh(self, ch: _Chunk) -> _Chunk:
        if not self._fresh_overflow:
            if len(self._fresh) >= self._FRESH_CAP:
                self._fresh_overflow = True
                self._fresh = []
            else:
                self._fresh.append(ch)
        return ch

    def _new_chunk(self, keys: list, vers: list) -> _Chunk:
        return self._track_fresh(_Chunk(keys, vers, self._kw))

    def _new_chunk_cols(self, ek, va, pfx, mx=None, mp=None) -> _Chunk:
        return self._track_fresh(
            _Chunk.from_cols(ek, va, pfx, self._kw, mx, mp)
        )

    @hot_path(bound="const")
    def take_fresh_chunks(self):
        """(chunks created since the last take, complete) — the device's
        incremental-sync hint.  complete=False means the backlog
        overflowed _FRESH_CAP and the consumer must fall back to a full
        walk.  Entries may already be dead (replaced/evicted since) —
        consumers treat the list as a superset hint, never as live
        state."""
        self._settle()
        fresh, overflow = self._fresh, self._fresh_overflow
        self._fresh, self._fresh_overflow = [], False
        return fresh, not overflow

    # -- snapshots --
    @hot_path(bound="const")
    def snapshot(self) -> MirrorSnapshot:
        """O(1): the chunk tuple is already immutable."""
        self._settle()
        return MirrorSnapshot(
            self._chunks, self._oldest, self._stamp, self._count
        )

    @property
    def stamp(self) -> int:
        # Passive read (telemetry): does NOT settle — a queued batch has
        # not mutated the chunk structure yet, so the stamp is honest.
        return self._stamp

    @property
    def chunk_count(self) -> int:
        self._settle()
        return len(self._chunks)

    @property
    def pending_batches(self) -> int:
        """Queued-but-unfolded apply_batch calls (coalesce telemetry;
        passive — reading it must not force the fold)."""
        return len(self._pending)

    # -- coalesce / adoption barriers --
    def _settle(self) -> None:
        """The read barrier: fold any staged flat adoption, then any
        queued coalesced batches.  Re-entrancy safe — both folds swap
        their queue out before running."""
        self._apply_staged()
        if self._pending:
            self._flush_pending()

    def _flush_pending(self) -> None:
        if not self._pending:
            return
        pend, self._pending = self._pending, []
        for active, now, new_oldest in pend:
            self._commit_writes(active, now, new_oldest)

    # -- flat views (compat with the store_to/load_from flat contract) --
    def _apply_staged(self) -> None:
        """Flush a pending keys-only assignment (the vers half never
        arrived before the next read/mutation): pair the staged keys
        with the old versions, padded — the flat engine's transiently-
        torn state, made visible at the same points."""
        if self._staged_keys is None:
            return
        ks, self._staged_keys = self._staged_keys, None
        vs = self._materialize()[1]
        n = len(ks)
        vs = list(vs[:n]) + [FLOOR_VERSION] * (n - len(vs))
        self._rebuild_from_flat(ks, vs)

    def _materialize(self) -> Tuple[list, list]:
        self._settle()
        if self._flat is None:
            ks: list = []
            vs: list = []
            for ch in self._chunks:
                ks.extend(ch.keys)
                vs.extend(ch.vers)
            self._flat = (ks, vs)
        return self._flat

    @property
    def keys(self) -> list:
        """Flat boundary-key list (READ-ONLY view; cached, O(H) on first
        access after a mutation).  Assigning it (store_to-style adoption)
        rebuilds the chunk structure."""
        return self._materialize()[0]

    @property
    def vers(self) -> list:
        return self._materialize()[1]

    @keys.setter
    def keys(self, new_keys):
        # store_to assigns .keys then .vers: STAGE the keys and rebuild
        # once when the matching vers arrive (one O(H) chunk build per
        # adoption, not two).  Any read or mutation before then flushes
        # the stage (_apply_staged), reproducing the flat engine's
        # transiently-torn keys-with-old-vers state at the same points.
        if self._pending:
            self._flush_pending()  # queued batches precede the adoption
        self._staged_keys = list(new_keys)

    @vers.setter
    def vers(self, new_vers):
        if self._pending:
            self._flush_pending()
        new_vers = list(new_vers)
        if (
            self._staged_keys is not None
            and len(self._staged_keys) == len(new_vers)
        ):
            ks, self._staged_keys = self._staged_keys, None
        else:
            self._apply_staged()  # mismatched halves: flush, then pair
            ks = list(self._materialize()[0][: len(new_vers)])
        self._rebuild_from_flat(ks, new_vers)

    def _rebuild_from_flat(self, ks: list, vs: list) -> None:
        assert ks and len(ks) == len(vs), "flat adoption needs paired lists"
        assert ks[0] == b"", "history floor boundary must be b''"
        c = self.chunk_size
        try:
            ek = keylib.encode_keys(ks, self._kw)
        except ValueError:
            chunks = [
                self._new_chunk(ks[i : i + c], vs[i : i + c])
                for i in range(0, len(ks), c)
            ]
            self._set_chunks(tuple(chunks))
            return
        va = np.asarray(vs, dtype=np.int64)
        pfx = _pfx_from_ek(ek)
        chunks = []
        for i in range(0, len(ks), c):
            ch = self._new_chunk_cols(
                ek[i : i + c], va[i : i + c], pfx[i : i + c]
            )
            ch._keys = ks[i : i + c]  # bytes already known: keep them
            ch._key0 = ch._keys[0]
            chunks.append(ch)
        self._set_chunks(tuple(chunks))

    def _set_chunks(self, chunks: tuple) -> None:
        self._chunks = chunks
        self._starts = [ch.key0 for ch in chunks]
        self._count = sum(len(ch) for ch in chunks)
        self._any_long = any(ch.ek is None for ch in chunks)
        self._stamp += 1
        self._flat = None
        self._g = None

    # -- global columns (the vectorized sweep/locate workspace) --
    def _gcols(self) -> tuple:
        """(ek_g, va_g, pfx_g, off): every chunk's columns concatenated,
        plus the chunk row-offset vector (off[c] is chunk c's first
        global row; off[-1] == boundary count).  Built lazily, O(H)
        memcpy, and reused until the chunk structure changes — one build
        serves every locate and the whole apply sweep of a batch.
        Requires not self._any_long (every chunk carries ek)."""
        g = self._g
        if g is not None:
            return g
        chunks = self._chunks
        if len(chunks) == 1:
            ch = chunks[0]
            ek_g, va_g, pfx_g = ch.ek, ch.va, ch.pfx
        else:
            ek_g = np.concatenate([ch.ek for ch in chunks])
            va_g = np.concatenate([ch.va for ch in chunks])
            pfx_g = np.concatenate([ch.pfx for ch in chunks])
        off = np.zeros(len(chunks) + 1, np.int64)
        np.cumsum(
            np.fromiter((len(ch) for ch in chunks), np.int64,
                        count=len(chunks)),
            out=off[1:],
        )
        g = self._g = (ek_g, va_g, pfx_g, off)
        return g

    def _g_bisect_rows(
        self, qrows: np.ndarray, qpfx: np.ndarray, side: str
    ) -> np.ndarray:
        """Vectorized global bisect of MANY encoded query rows at once:
        two searchsorted calls on the prefix column locate every query;
        only queries landing inside a prefix-tie run (rows sharing the
        query's first 8 bytes) are refined, each by a lexicographic
        binary search over full encoded rows."""
        ek_g, _, pfx_g, _ = self._gcols()
        pos = np.searchsorted(pfx_g, qpfx, side=side)
        alt = np.searchsorted(
            pfx_g, qpfx, side=("right" if side == "left" else "left")
        )
        ties = np.flatnonzero(pos != alt)
        if ties.size:
            left = side == "left"
            for t in ties:
                lo = int(min(pos[t], alt[t]))
                hi = int(max(pos[t], alt[t]))
                q = qrows[t].tolist()
                while lo < hi:
                    mid = (lo + hi) >> 1
                    r = ek_g[mid].tolist()
                    if (r < q) if left else (r <= q):
                        lo = mid + 1
                    else:
                        hi = mid
                pos[t] = lo
        return pos

    # -- history step function --
    def _loc_le(self, k: bytes) -> Tuple[int, int]:
        """(chunk, index) of the greatest boundary <= k."""
        self._settle()
        c = bisect_right(self._starts, k) - 1
        ch = self._chunks[c]
        return c, _ch_bisect_key(ch, k, "right") - 1

    def _loc_lt(self, k: bytes) -> Tuple[int, int]:
        """(chunk, index) of the greatest boundary < k; requires k > b""."""
        self._settle()
        c = bisect_left(self._starts, k) - 1
        ch = self._chunks[c]
        return c, _ch_bisect_key(ch, k, "left") - 1

    def _range_max(self, b: bytes, e: bytes) -> int:
        """Max version over [b, e); requires b < e.  Spanning chunks use
        the precomputed chunk max instead of walking rows."""
        ci, ii = self._loc_le(b)
        cj, jj = self._loc_lt(e)
        chunks = self._chunks
        if ci == cj:
            return int(chunks[ci].va[ii : jj + 1].max())
        m = int(chunks[ci].va[ii:].max())
        for c in range(ci + 1, cj):
            mv = chunks[c].max_ver
            if mv > m:
                m = mv
        mj = int(chunks[cj].va[: jj + 1].max())
        return m if m > mj else mj

    def _value_at(self, k: bytes) -> int:
        c, i = self._loc_le(k)
        return int(self._chunks[c].va[i])

    def _value_at_row(self, qrow: np.ndarray, qpfx, qkey: bytes) -> int:
        """_value_at for a pre-encoded query (the columnar sweep prelude):
        no byte decode even inside prefix-tie runs."""
        c = bisect_right(self._starts, qkey) - 1
        ch = self._chunks[c]
        i = _ch_bisect_rows(ch, qrow, qpfx, "right") - 1
        return int(ch.va[i])

    # -- ConflictSet ABI (ref fdbserver/ConflictSet.h) --
    def detect(
        self,
        transactions: List[TransactionConflictInfo],
        now: int,
        new_oldest_version: int,
    ) -> List[int]:
        self._settle()  # a mirror READ: queued batches must be visible
        statuses: list[int] = [COMMITTED] * len(transactions)
        # Abort witness (ISSUE 17): per txn, (conflicting write version,
        # losing read-range index into tr.read_ranges) — None unless the
        # final status is CONFLICT.  The device engine reproduces these
        # bit-identically; history conflicts take the FIRST conflicting
        # range and the max committed version inside it, intra-batch
        # conflicts take the first range intersecting the in-batch write
        # union at version `now`.
        witness: list = [None] * len(transactions)

        # Phase 1: too-old + history conflicts (ref checkReadConflictRanges).
        # Columnar fast path: every read-range endpoint bulk-encoded once
        # and located with two vectorized bisects over the global columns;
        # the reference per-range loop remains for long keys (and is the
        # semantics the fast path is fuzzed against).
        if self._any_long or not self._detect_phase1_cols(
            transactions, statuses, witness
        ):
            for t, tr in enumerate(transactions):
                if tr.read_snapshot < self._oldest and tr.read_ranges:
                    statuses[t] = TOO_OLD
                    continue
                for i, (rb, re_) in enumerate(tr.read_ranges):
                    if rb < re_:
                        m = self._range_max(rb, re_)
                        if m > tr.read_snapshot:
                            statuses[t] = CONFLICT
                            witness[t] = (m, i)
                            break

        # Phase 2: intra-batch, in order (ref checkIntraBatchConflicts)
        active = _IntervalSet()
        for t, tr in enumerate(transactions):
            if statuses[t] != COMMITTED:
                continue
            hit = next(
                (
                    i
                    for i, (rb, re_) in enumerate(tr.read_ranges)
                    if active.intersects(rb, re_)
                ),
                None,
            )
            if hit is not None:
                statuses[t] = CONFLICT
                witness[t] = (now, hit)
                continue
            for (wb, we) in tr.write_ranges:
                active.add(wb, we)

        self.last_witness = witness
        self._commit_writes(active, now, new_oldest_version)
        return statuses

    def _detect_phase1_cols(
        self, transactions, statuses: list, witness: list
    ) -> bool:
        """Vectorized phase 1.  Returns False when a query key is too
        long to digitize at the engine's key_words — the caller then
        runs the reference loop (TOO_OLD marks already applied here are
        key-independent and idempotent, so the rerun is safe).  Range
        maxes resolve as direct slices of the global version column:
        read ranges span few boundaries in practice, and even a full-
        keyspace read costs one O(H) vector max."""
        qb: list = []
        qe: list = []
        owner: list = []
        ridx: list = []
        for t, tr in enumerate(transactions):
            if tr.read_snapshot < self._oldest and tr.read_ranges:
                statuses[t] = TOO_OLD
                continue
            for i, (rb, re_) in enumerate(tr.read_ranges):
                if rb < re_:
                    qb.append(rb)
                    qe.append(re_)
                    owner.append(t)
                    ridx.append(i)
        nq = len(qb)
        if not nq:
            return True
        try:
            rows = keylib.encode_keys(qb + qe, self._kw)
        except ValueError:
            return False
        qpfx = _pfx_from_ek(rows)
        # loc_le(b) = bisect_right(b) - 1; loc_lt(e) = bisect_left(e) - 1
        ii = self._g_bisect_rows(rows[:nq], qpfx[:nq], "right") - 1
        jj = self._g_bisect_rows(rows[nq:], qpfx[nq:], "left") - 1
        va_g = self._gcols()[1]
        m = va_g[ii]
        for q in np.flatnonzero(jj > ii):
            m[q] = va_g[ii[q] : jj[q] + 1].max()
        snaps = np.fromiter(
            (transactions[t].read_snapshot for t in owner), np.int64, nq
        )
        # Ascending query order == txn order and range order, so the
        # first hit per txn wins, exactly as the reference loop breaks.
        for q in np.flatnonzero(m > snaps):
            t = owner[q]
            if statuses[t] == COMMITTED:
                statuses[t] = CONFLICT
                witness[t] = (int(m[q]), ridx[q])
        return True

    @hot_path(bound="chunks")
    def apply_batch(
        self,
        transactions: List[TransactionConflictInfo],
        statuses: List[int],
        now: int,
        new_oldest_version: int,
    ) -> None:
        """Adopt an externally-decided batch (the device engine's
        verdicts): merge the committed writes and advance the window
        EXACTLY as detect() would have — one batched chunk sweep, the
        amortized cost ISSUE 9 is about.  With coalesce_window > 1 the
        union is QUEUED (O(ranges), no sweep) and folded at the next
        read barrier or every coalesce_window batches (ISSUE 19)."""
        active = _IntervalSet()
        for t, tr in enumerate(transactions):
            if statuses[t] != COMMITTED:
                continue
            for (wb, we) in tr.write_ranges:
                active.add(wb, we)
        if self.coalesce_window > 1:
            self._pending.append((active, now, new_oldest_version))
            if len(self._pending) >= self.coalesce_window:
                self._flush_pending()
            return
        if self._pending:
            self._flush_pending()  # window shrank mid-stream: drain first
        self._commit_writes(active, now, new_oldest_version)

    def _commit_writes(
        self, active: _IntervalSet, now: int, new_oldest_version: int
    ) -> None:
        """Phases 3-4: one batched overwrite sweep for the whole committed
        write union, then the chunk-skipping window eviction."""
        self._apply_staged()
        if active.begins:
            self._apply_intervals(active.begins, active.ends, now)
        if new_oldest_version > self._oldest:
            self._oldest = new_oldest_version
            self._evict(new_oldest_version)

    # -- phase 3: batched interval overwrite --
    def _apply_intervals(self, begins: list, ends: list, now: int) -> None:
        """Set the step function to `now` on every [begins[i], ends[i]).
        Intervals are sorted, disjoint and non-touching (the _IntervalSet
        invariant), so end values can be resolved against the PRE state
        and the whole union applies as one left-to-right sweep.  Chunks
        no interval touches are reused by reference (identity preserved
        for snapshot diffing and the device encode cache).

        Columnar fast path: one encode_keys call digitizes every
        interval endpoint, locates are searchsorted on the prefix
        column, and surviving boundary runs move as column slices.
        Falls back to the verbatim per-boundary sweep when any chunk or
        endpoint is unencodable at the engine's key_words."""
        if not self._any_long:
            try:
                be = keylib.encode_keys(list(begins) + list(ends), self._kw)
            except ValueError:
                be = None
            if be is not None:
                self._apply_intervals_cols(begins, ends, be, now)
                return
        self._apply_intervals_py(begins, ends, now)

    @hot_path(bound="chunks")
    def _apply_intervals_cols(
        self, begins: list, ends: list, be: np.ndarray, now: int
    ) -> None:
        """The whole union as ONE vectorized assembly — no per-interval
        Python work.  Writing [b, e) deletes every boundary in
        [bisect_left(b), bisect_right(e)) and inserts (b, now) and
        (e, value-in-force-at-e); when a boundary equal to b or e
        already existed the delete+reinsert reproduces it bit-exactly
        (value_at(e) IS the exact boundary's version), so one uniform
        rule covers all the old per-chunk sweep's cases.  Intervals are
        sorted, disjoint and non-touching (_IntervalSet merges adjacent
        spans), so delete ranges never interleave and every output
        position has a closed form: a kept row shifts past two inserted
        rows per interval whose delete range ends at or before it, and
        interval i's pair lands after the kept rows preceding its begin
        plus the 2*i earlier inserts.  Only the chunk span [c0, c1] the
        union touches is reassembled; chunks outside it are reused by
        reference (snapshot-diff + encode-cache identity, the degraded-
        locality lever)."""
        n_int = len(begins)
        bpfx = _pfx_from_ek(be)
        lb = self._g_bisect_rows(be[:n_int], bpfx[:n_int], "left")
        rb = self._g_bisect_rows(be[n_int:], bpfx[n_int:], "right")
        ek_g, va_g, pfx_g, off = self._gcols()
        # Value in force at each e against the PRE state: the greatest
        # boundary <= e is row rb-1 (>= 0: the b"" floor row is <= e).
        end_vals = va_g[rb - 1]
        chunks = self._chunks
        n_chunks = len(chunks)
        c0 = min(n_chunks - 1, int(np.searchsorted(off, lb[0], "right")) - 1)
        c1 = min(n_chunks - 1, int(np.searchsorted(off, rb[-1], "right")) - 1)
        g0 = int(off[c0])
        g1 = int(off[c1 + 1])
        lbl = lb - g0
        rbl = rb - g0
        hs = g1 - g0
        # Keep mask over the span: a row survives iff no delete range
        # covers it (ranges are disjoint, so coverage is a 0/1 fringe).
        d = np.bincount(lbl, minlength=hs + 1).astype(np.int64)
        d -= np.bincount(rbl, minlength=hs + 1)
        kept_idx = np.flatnonzero(np.cumsum(d[:hs]) == 0)
        nk = kept_idx.size
        h2 = nk + 2 * n_int
        out_kept = np.arange(nk) + 2 * np.searchsorted(rbl, kept_idx, "right")
        out_b = np.searchsorted(kept_idx, lbl, "left") + 2 * np.arange(n_int)
        ek2 = np.empty((h2, be.shape[1]), np.uint32)  # perfcheck: ignore[HOT003]: becomes the rebuilt span's chunk columns (retained), so the staging ring cannot serve it
        va2 = np.empty(h2, np.int64)  # perfcheck: ignore[HOT003]: retained as chunk columns, see ek2
        pfx2 = np.empty(h2, np.uint64)  # perfcheck: ignore[HOT003]: retained as chunk columns, see ek2
        sk = kept_idx + g0
        ek2[out_kept] = ek_g[sk]
        va2[out_kept] = va_g[sk]
        pfx2[out_kept] = pfx_g[sk]
        ek2[out_b] = be[:n_int]
        va2[out_b] = now
        pfx2[out_b] = bpfx[:n_int]
        out_e = out_b + 1
        ek2[out_e] = be[n_int:]
        va2[out_e] = end_vals
        pfx2[out_e] = bpfx[n_int:]
        out = list(chunks[:c0])
        self._flush_cols(out, [ek2], [va2], [pfx2])
        out.extend(chunks[c1 + 1 :])
        self._set_chunks(tuple(out))

    def _apply_intervals_py(self, begins: list, ends: list, now: int) -> None:
        """The per-boundary reference sweep (pre-ISSUE-19, verbatim):
        exact for ANY byte keys, including ones past 4*key_words — the
        long-key path and the semantics the columnar path is fuzzed
        against."""
        # Flat-equivalent edit per interval (engine_cpu_flat._overwrite):
        # delete boundaries in [b, e), insert (b, now), insert
        # (e, value_at(e)) unless a boundary already sits at e.
        end_vals = [self._value_at(e) for e in ends]
        chunks = self._chunks
        starts = self._starts
        n_chunks = len(chunks)
        n_int = len(begins)
        out: list = []  # new chunk sequence
        buf_k: list = []  # materialized pairs of the current touched run
        buf_v: list = []
        i = 0  # interval cursor
        in_del = False  # an interval's deletion range is open
        cur_e = b""
        cur_ev = 0
        for c in range(n_chunks):
            ch = chunks[c]
            s = starts[c]
            nxt = starts[c + 1] if c + 1 < n_chunks else None
            if in_del:
                if cur_e <= s:
                    in_del = False
                    i += 1
                elif nxt is not None and cur_e >= nxt:
                    continue
            if not in_del and not (
                i < n_int and (nxt is None or begins[i] < nxt)
            ):
                # Untouched: reuse by reference.
                self._flush_pairs(out, buf_k, buf_v)
                out.append(ch)
                continue
            # Touched (or a deletion closes inside it): materialize.
            keys, vers = ch.keys, ch.vers
            m = len(keys)
            j = 0
            while j < m:
                k = keys[j]
                if in_del:
                    if k < cur_e:
                        j += 1  # deleted
                        continue
                    if k != cur_e:
                        buf_k.append(cur_e)
                        buf_v.append(cur_ev)
                    in_del = False
                    i += 1
                    continue  # re-examine k outside the deletion
                if i < n_int and begins[i] <= k:
                    buf_k.append(begins[i])
                    buf_v.append(now)
                    in_del = True
                    cur_e = ends[i]
                    cur_ev = end_vals[i]
                    continue  # re-examine k under the new deletion
                buf_k.append(k)
                buf_v.append(vers[j])
                j += 1
            # Tail: intervals starting after the chunk's last boundary but
            # before the next chunk (or anywhere, for the last chunk).
            while True:
                if in_del:
                    if nxt is not None and cur_e >= nxt:
                        break  # deletion spans into the next chunk
                    buf_k.append(cur_e)
                    buf_v.append(cur_ev)
                    in_del = False
                    i += 1
                elif i < n_int and (nxt is None or begins[i] < nxt):
                    buf_k.append(begins[i])
                    buf_v.append(now)
                    in_del = True
                    cur_e = ends[i]
                    cur_ev = end_vals[i]
                else:
                    break
        self._flush_pairs(out, buf_k, buf_v)
        assert not in_del and i == n_int, "interval sweep failed to converge"
        self._set_chunks(tuple(out))

    def _flush_pairs(self, out: list, buf_k: list, buf_v: list) -> None:
        """Re-chunk a run's accumulated (key, ver) pairs into
        ~chunk_size even pieces, append them to `out`, clear the
        buffers, and count the rebuilds — the shared tail of both
        per-boundary sweeps (_apply_intervals_py, _evict_py)."""
        if not buf_k:
            return
        c = self.chunk_size
        pieces = max(1, (len(buf_k) + c - 1) // c)
        step = (len(buf_k) + pieces - 1) // pieces
        for o in range(0, len(buf_k), step):
            out.append(
                self._new_chunk(buf_k[o : o + step], buf_v[o : o + step])
            )
            self.chunks_rebuilt += 1
        del buf_k[:], buf_v[:]

    def _flush_cols(self, out: list, rek: list, rva: list, rpfx: list) -> None:
        """Columnar twin of _flush_pairs: concatenate a touched run's
        column segments and split into ~chunk_size even pieces.  Same
        piece arithmetic, same rebuild counting — the chunk sequences
        the two paths produce are identical."""
        if not rva:
            return
        if len(rva) == 1:
            ek, va, pfx = rek[0], rva[0], rpfx[0]
        else:
            ek = np.concatenate(rek)
            va = np.concatenate(rva)
            pfx = np.concatenate(rpfx)
        rek.clear(), rva.clear(), rpfx.clear()
        n = len(va)
        if n == 0:
            return  # e.g. an eviction span whose every row dropped
        c = self.chunk_size
        pieces = max(1, (n + c - 1) // c)
        step = (n + pieces - 1) // pieces
        starts = np.arange(0, n, step, dtype=np.int64)
        # Per-piece stats in two bulk reduceat passes (per-chunk numpy
        # reductions would dominate at ~10^3 pieces per flush).  The
        # pair column is masked at piece borders so each segment min
        # sees only INTERNAL adjacent pairs; a final piece of one row
        # has no pair slot and stays at the sentinel.  INT64_MAX stands
        # in for _PAIR_INF inside the arrays (2**63 does not fit int64;
        # min_pair is only ever compared with >=, so both sentinels
        # read as "nothing provably droppable").
        i64max = np.iinfo(np.int64).max
        mx = np.maximum.reduceat(va, starts)
        mp = np.full(len(starts), i64max, np.int64)
        if n > 1:
            pair = np.maximum(va[:-1], va[1:])
            if len(starts) > 1:
                pair[starts[1:] - 1] = i64max
            ps = starts[starts < n - 1]
            mp[: len(ps)] = np.minimum.reduceat(pair, ps)
        for j, o in enumerate(starts.tolist()):
            out.append(
                self._new_chunk_cols(
                    ek[o : o + step], va[o : o + step], pfx[o : o + step],
                    int(mx[j]), int(mp[j]),
                )
            )
            self.chunks_rebuilt += 1

    # -- phase 4: window eviction --
    def _evict(self, old: int) -> None:
        """Drop boundary i (i > 0) iff vers[i] < old and ORIGINAL
        vers[i-1] < old (ref SkipList::removeBefore).  Columnar fast
        path: ONE vectorized keep mask over the global version column —
        a window advance with no droppable boundary anywhere rebuilds
        NOTHING (evict_skips, O(H) compare but zero allocation churn),
        and otherwise only the chunk span bracketing the dropped rows
        is reassembled (chunks outside it keep identity)."""
        if self._any_long:
            self._evict_py(old)
            return
        self.evict_scans += 1
        ek_g, va_g, pfx_g, off = self._gcols()
        prev = np.empty_like(va_g)
        prev[1:] = va_g[:-1]
        # Row 0 (prev is None in the reference rule) is unconditionally
        # kept: force it via prev >= old.
        prev[0] = old
        keep = (va_g >= old) | (prev >= old)
        drop = np.flatnonzero(~keep)
        if drop.size == 0:
            self.evict_skips += 1
            # No chunk changed, but oldest_version DID advance (the
            # caller's gate): bump the stamp so "equal stamps mean
            # identical state" stays true for snapshot consumers.
            self._stamp += 1
            return
        # Chunks strictly before the first and after the last dropped
        # row are reused by reference; the span between is reassembled
        # in one flush (survivors re-chunked TOGETHER — the Jiffy node
        # merge, so heavy eviction coalesces shrunken chunks instead of
        # fragmenting toward per-boundary chunks).
        chunks = self._chunks
        c0 = int(np.searchsorted(off, drop[0], "right")) - 1
        c1 = int(np.searchsorted(off, drop[-1], "right")) - 1
        g0 = int(off[c0])
        g1 = int(off[c1 + 1])
        idx = g0 + np.flatnonzero(keep[g0:g1])
        out = list(chunks[:c0])
        self._flush_cols(out, [ek_g[idx]], [va_g[idx]], [pfx_g[idx]])
        out.extend(chunks[c1 + 1 :])
        self._set_chunks(tuple(out))

    def _evict_py(self, old: int) -> None:
        """Per-boundary reference eviction (pre-ISSUE-19, verbatim) —
        the long-key path."""
        chunks = self._chunks
        self.evict_scans += 1
        out: list = []
        buf_k: list = []  # survivors of the current rewritten run
        buf_v: list = []
        changed = False
        prev_last: Optional[int] = None  # original last version of prev chunk
        for ch in chunks:
            first_pair = _PAIR_INF
            if prev_last is not None:
                v0 = ch.vers[0]
                first_pair = prev_last if prev_last > v0 else v0
            if ch.min_pair >= old and first_pair >= old:
                self._flush_pairs(out, buf_k, buf_v)
                out.append(ch)
            else:
                keys, vers = ch.keys, ch.vers
                for idx in range(len(keys)):
                    v = vers[idx]
                    prev = prev_last if idx == 0 else vers[idx - 1]
                    if prev is None or v >= old or prev >= old:
                        buf_k.append(keys[idx])
                        buf_v.append(v)
                changed = True
            prev_last = ch.vers[-1]
        self._flush_pairs(out, buf_k, buf_v)
        if changed:
            self._set_chunks(tuple(out))
        else:
            self.evict_skips += 1
            self._stamp += 1

    def clear(self, version: int):
        self._staged_keys = None  # clear overrides a pending adoption
        self._pending = []  # ... and any queued coalesced batches
        self._set_chunks((self._new_chunk([b""], [FLOOR_VERSION]),))
        self._oldest = version

    @property
    def boundary_count(self) -> int:
        """O(1): maintained alongside the chunk sequence (ISSUE 9
        satellite; the flat engine pays len(keys)).  Settles first so a
        queued coalesced batch can't make the count lie."""
        self._settle()
        return self._count

    # -- columnar views (ISSUE 19): boundary order without the flat
    # keys/vers byte materialization; the sharded balancer's occupancy
    # quantiles read these instead of the O(rows) getters.
    def boundary_locate(self, key: bytes, side: str = "left") -> int:
        """Global index of `key` in boundary order (bisect_left/
        bisect_right semantics per `side`): one chunk bisect + one
        in-chunk column bisect, plus an O(chunks) offset walk — no
        bytes decoded outside a prefix-tie run."""
        self._settle()
        c = bisect_right(self._starts, key) - 1
        base = 0
        for ch in self._chunks[:c]:
            base += len(ch)
        return base + _ch_bisect_key(self._chunks[c], key, side)

    def boundary_key_at(self, i: int) -> bytes:
        """The i-th boundary key — decodes ONE row (O(chunks) to locate)."""
        self._settle()
        for ch in self._chunks:
            if i < len(ch):
                if ch._keys is not None or ch.ek is None:
                    return ch.keys[i]
                return keylib.decode_key(ch.ek[i], ch.kw)
            i -= len(ch)
        raise IndexError("boundary index out of range")


def chunk_encoding(ch, key_words: int):
    """(encoded keys [n, kw1] uint32, abs versions int64) for one
    immutable mirror chunk, cached ON the chunk (computed at most once
    per chunk lifetime — chunks never mutate; the cache is the currency
    that makes probe rehydration O(chunks changed since the last sync)).
    Returns (entry, keys_encoded_now).  Shared by JaxConflictSet and the
    sharded resolver's per-shard mirror slices (ISSUE 15).  Columnar
    chunks whose ``ek`` width already matches return their live columns
    with ZERO keys re-encoded (ISSUE 19)."""
    cache = ch.enc
    if cache is None:
        cache = ch.enc = {}
    ent = cache.get(key_words)
    if ent is not None:
        return ent, 0
    ek = getattr(ch, "ek", None)
    if ek is not None and ek.shape[1] == key_words + 1:
        ent = (ek, ch.va)
        cache[key_words] = ent
        return ent, 0
    ent = (
        keylib.encode_keys(ch.keys, key_words),
        np.asarray(ch.vers, dtype=np.int64),
    )
    cache[key_words] = ent
    return ent, len(ch.keys)


# -- live reshard handoff (ISSUE 18) --
def slice_snapshot_chunks(
    snap: MirrorSnapshot, lo: bytes, hi: Optional[bytes]
) -> Tuple[int, list]:
    """(version in force at `lo`, chunks of `snap` restricted to the open
    interval (lo, hi)); hi=None means +inf.  The reshard handoff
    primitive: chunks wholly inside the interval are adopted BY
    REFERENCE — their identity (and the columnar ``ek`` encoding plus
    any ``_Chunk.enc`` side caches) survives the move, so rehydrating a
    moved shard re-encodes only the split boundary chunks, O(moved
    ranges) — while chunks straddling `lo`/`hi` are split into fresh
    chunks (column slices: no byte round-trip for columnar chunks).
    The snapshot is immutable, so a fault landing mid-handoff cannot
    tear the cut."""
    floor = FLOOR_VERSION
    out: list = []
    for ch in snap.chunks:
        last = ch.last_key
        if last <= lo:
            # Entire chunk at or below lo: only its last version can be
            # the one in force at lo so far.
            floor = int(ch.va[-1])
            continue
        i = 0
        if ch.key0 <= lo:
            i = _ch_bisect_key(ch, lo, "right")  # first boundary > lo
            floor = int(ch.va[i - 1])
        if hi is not None and last >= hi:
            j = _ch_bisect_key(ch, hi, "left")  # first boundary >= hi
        else:
            j = len(ch.va)
        if i == 0 and j == len(ch.va):
            out.append(ch)  # wholly inside: adopt by reference
        elif i < j:
            if ch.ek is not None:
                sl = _Chunk.from_cols(
                    ch.ek[i:j], ch.va[i:j], ch.pfx[i:j], ch.kw
                )
                if ch._keys is not None:
                    sl._keys = ch._keys[i:j]
                    sl._key0 = sl._keys[0]
                out.append(sl)
            else:
                out.append(_Chunk(ch.keys[i:j], ch.vers[i:j], ch.kw))
        if hi is not None and last >= hi:
            break
    return floor, out


def engine_from_handoff(
    parts, oldest_version: int, chunk: Optional[int] = None,
    key_words: Optional[int] = None,
) -> "CpuConflictSet":
    """Build a shard engine for a NEW key range from immutable snapshot
    cuts of the old shards (ISSUE 18 live split-point migration).

    ``parts`` is ``[(snapshot, lo, hi)]`` in global key order, covering
    the new shard's range contiguously (hi=None = +inf); per the
    shard-engine convention the result is re-anchored at ``b""`` with
    the version in force at the first part's ``lo`` as the floor.
    Interior chunks keep their identity (columnar encodings included);
    only boundary chunks at moved split points are rebuilt."""
    eng = CpuConflictSet(oldest_version, chunk=chunk, key_words=key_words)
    chunks: list = []
    first_floor: Optional[int] = None
    for snap, lo, hi in parts:
        floor, chs = slice_snapshot_chunks(snap, lo, hi)
        if first_floor is None:
            first_floor = floor
        chunks.extend(chs)
    head = eng._new_chunk(
        [b""], [FLOOR_VERSION if first_floor is None else first_floor]
    )
    eng._set_chunks(tuple([head] + chunks))
    return eng
