"""Host conflict engine: chunked step function with batch updates and
O(1) immutable snapshots (ISSUE 9, the Jiffy blueprint).

Production CPU path AND the always-authoritative mirror behind the
device circuit breaker (api.ConflictSet).  Same data model as every
other engine — keys[i] starts the range [keys[i], keys[i+1)) whose
last-committed-write version is vers[i]; keys[0] is always b"" (the
floor) — but the flat sorted array is split into a sequence of IMMUTABLE
chunks (the batch-update skip-list nodes of Jiffy, "A Lock-free Skip
List with Batch Updates and Snapshots", PAPERS.md):

  - ``detect``/``apply_batch`` apply a batch's whole committed write
    union as ONE sweep: only chunks an interval touches are rewritten
    (copy-on-write), untouched chunks keep their identity.  No per-range
    O(H) list splices.
  - window eviction (ref SkipList::removeBefore) rewrites only chunks
    that actually hold a droppable boundary, decided from a per-chunk
    ``min_pair`` precomputed at chunk build time — when nothing is below
    the window the advance is an O(chunks) scan with ZERO rebuilds
    (``evict_skips`` counts them), not the flat engine's O(H) keep pass.
  - ``snapshot()`` is O(1): the chunk sequence is already an immutable
    tuple, so a snapshot is just a handle to it.  Snapshots taken every
    batch cost nothing; a handed-off snapshot can never observe a
    half-mutated mirror (the breaker's probe-rehydration safety).
  - ``boundary_count`` is an O(1) maintained count.

Chunk identity is the incremental-sync currency: the device engine
caches per-chunk key encodings on the chunk object itself
(engine_jax.note_synced / load_from), so probe rehydration re-encodes
only chunks created since the last device sync.

The pre-ISSUE-9 flat engine survives as engine_cpu_flat.FlatCpuConflictSet,
the differential oracle this engine is gated bit-identical against
(verdicts AND exported state) and the FDB_TPU_MIRROR_ENGINE=flat A/B arm.
"""

from __future__ import annotations

from bisect import bisect_left, bisect_right
from typing import List, Optional, Tuple

from .engine_cpu_flat import (  # re-exported: the shared pieces
    FLOOR_VERSION,
    FlatCpuConflictSet,
    _IntervalSet,
)
from .types import CONFLICT, COMMITTED, TOO_OLD, TransactionConflictInfo

__all__ = [
    "CpuConflictSet",
    "FlatCpuConflictSet",
    "MirrorSnapshot",
    "FLOOR_VERSION",
    "slice_snapshot_chunks",
    "engine_from_handoff",
]

_PAIR_INF = 1 << 63  # "no droppable pair here" sentinel


class _Chunk:
    """One immutable run of (key, version) boundaries.  ``keys``/``vers``
    are plain lists treated as frozen after construction (copy-on-write:
    a mutation builds a new chunk).  ``min_pair`` is the smallest
    max(vers[i-1], vers[i]) over INTERNAL adjacent pairs — a boundary is
    evictable iff its pair-max is below the window, so a chunk whose
    min_pair is at or above the window provably holds nothing to drop
    (the cross-chunk first pair is checked by the caller, which knows
    the previous chunk's last version).  ``enc`` holds device-encoding
    caches keyed by key_words (engine_jax), computed at most once per
    chunk lifetime because chunks never mutate."""

    __slots__ = ("keys", "vers", "max_ver", "min_pair", "enc")

    def __init__(self, keys: list, vers: list):
        self.keys = keys
        self.vers = vers
        self.max_ver = max(vers)
        mp = _PAIR_INF
        prev = None
        for v in vers:
            if prev is not None:
                p = prev if prev > v else v
                if p < mp:
                    mp = p
            prev = v
        self.min_pair = mp
        self.enc = None

    def __len__(self):
        return len(self.keys)


class MirrorSnapshot:
    """O(1) immutable view of a CpuConflictSet at one instant.  Holding
    one is free (chunk refs are shared with the live engine and with
    every other snapshot); the live engine's later mutations replace
    chunks instead of editing them, so the view never changes.  ``stamp``
    increases with every mutation of the source engine — equal stamps
    mean identical state, and chunk identity across two snapshots means
    that key range did not change (the device sync diff)."""

    __slots__ = ("chunks", "oldest_version", "stamp", "boundary_count")

    def __init__(self, chunks: tuple, oldest_version: int, stamp: int,
                 boundary_count: int):
        self.chunks = chunks
        self.oldest_version = oldest_version
        self.stamp = stamp
        self.boundary_count = boundary_count

    def to_flat(self) -> Tuple[list, list]:
        """Materialize (keys, vers) lists — O(H), diagnostic/diff use."""
        ks: list = []
        vs: list = []
        for ch in self.chunks:
            ks.extend(ch.keys)
            vs.extend(ch.vers)
        return ks, vs


def _default_chunk_size() -> int:
    from ..flow.knobs import g_env

    return max(4, g_env.get_int("FDB_TPU_MIRROR_CHUNK"))


class CpuConflictSet:
    """Exact reference-semantics engine over chunked immutable runs.

    Decision- and state-identical to FlatCpuConflictSet (gated by
    tests/test_mirror_snapshot.py's differential fuzz); only the update
    cost model differs.  ``chunk`` is the target chunk size (default
    FDB_TPU_MIRROR_CHUNK); tests pass tiny values to force multi-chunk
    structures on small histories."""

    def __init__(self, oldest_version: int = 0, chunk: Optional[int] = None):
        self.oldest_version = oldest_version
        self.chunk_size = chunk if chunk is not None else _default_chunk_size()
        self._chunks: tuple = (_Chunk([b""], [FLOOR_VERSION]),)
        self._starts: list = [b""]
        self._count = 1
        self._stamp = 0
        self._flat: Optional[Tuple[list, list]] = None
        # Per-txn abort witness of the most recent detect() (ISSUE 17).
        self.last_witness: list = []
        # Staged halves of a flat (keys, vers) adoption — see the property
        # setters: store_to-style callers assign .keys then .vers.
        self._staged_keys: Optional[list] = None
        # Maintenance telemetry (deterministic ints, read by tests/bench/
        # device_metrics): batches that rewrote at least one chunk, chunks
        # rewritten, window advances that dropped nothing (the flat
        # engine's O(H) keep pass, skipped).
        self.chunks_rebuilt = 0
        self.evict_scans = 0
        self.evict_skips = 0
        # Chunks created since the last take_fresh_chunks(): the device
        # sync hint (engine_jax.note_synced encodes ONLY these instead of
        # walking every chunk).  Bounded: past _FRESH_CAP the list is
        # dropped and the consumer falls back to a full walk.
        self._fresh: list = []
        self._fresh_overflow = False

    _FRESH_CAP = 8192

    def _new_chunk(self, keys: list, vers: list) -> _Chunk:
        ch = _Chunk(keys, vers)
        if not self._fresh_overflow:
            if len(self._fresh) >= self._FRESH_CAP:
                self._fresh_overflow = True
                self._fresh = []
            else:
                self._fresh.append(ch)
        return ch

    def take_fresh_chunks(self):
        """(chunks created since the last take, complete) — the device's
        incremental-sync hint.  complete=False means the backlog
        overflowed _FRESH_CAP and the consumer must fall back to a full
        walk.  Entries may already be dead (replaced/evicted since) —
        consumers treat the list as a superset hint, never as live
        state."""
        self._apply_staged()
        fresh, overflow = self._fresh, self._fresh_overflow
        self._fresh, self._fresh_overflow = [], False
        return fresh, not overflow

    # -- snapshots --
    def snapshot(self) -> MirrorSnapshot:
        """O(1): the chunk tuple is already immutable."""
        self._apply_staged()
        return MirrorSnapshot(
            self._chunks, self.oldest_version, self._stamp, self._count
        )

    @property
    def stamp(self) -> int:
        return self._stamp

    @property
    def chunk_count(self) -> int:
        self._apply_staged()
        return len(self._chunks)

    # -- flat views (compat with the store_to/load_from flat contract) --
    def _apply_staged(self) -> None:
        """Flush a pending keys-only assignment (the vers half never
        arrived before the next read/mutation): pair the staged keys
        with the old versions, padded — the flat engine's transiently-
        torn state, made visible at the same points."""
        if self._staged_keys is None:
            return
        ks, self._staged_keys = self._staged_keys, None
        vs = self._materialize()[1]
        n = len(ks)
        vs = list(vs[:n]) + [FLOOR_VERSION] * (n - len(vs))
        self._rebuild_from_flat(ks, vs)

    def _materialize(self) -> Tuple[list, list]:
        self._apply_staged()
        if self._flat is None:
            ks: list = []
            vs: list = []
            for ch in self._chunks:
                ks.extend(ch.keys)
                vs.extend(ch.vers)
            self._flat = (ks, vs)
        return self._flat

    @property
    def keys(self) -> list:
        """Flat boundary-key list (READ-ONLY view; cached, O(H) on first
        access after a mutation).  Assigning it (store_to-style adoption)
        rebuilds the chunk structure."""
        return self._materialize()[0]

    @property
    def vers(self) -> list:
        return self._materialize()[1]

    @keys.setter
    def keys(self, new_keys):
        # store_to assigns .keys then .vers: STAGE the keys and rebuild
        # once when the matching vers arrive (one O(H) chunk build per
        # adoption, not two).  Any read or mutation before then flushes
        # the stage (_apply_staged), reproducing the flat engine's
        # transiently-torn keys-with-old-vers state at the same points.
        self._staged_keys = list(new_keys)

    @vers.setter
    def vers(self, new_vers):
        new_vers = list(new_vers)
        if (
            self._staged_keys is not None
            and len(self._staged_keys) == len(new_vers)
        ):
            ks, self._staged_keys = self._staged_keys, None
        else:
            self._apply_staged()  # mismatched halves: flush, then pair
            ks = list(self._materialize()[0][: len(new_vers)])
        self._rebuild_from_flat(ks, new_vers)

    def _rebuild_from_flat(self, ks: list, vs: list) -> None:
        assert ks and len(ks) == len(vs), "flat adoption needs paired lists"
        assert ks[0] == b"", "history floor boundary must be b''"
        c = self.chunk_size
        chunks = [
            self._new_chunk(ks[i : i + c], vs[i : i + c])
            for i in range(0, len(ks), c)
        ]
        self._set_chunks(tuple(chunks))

    def _set_chunks(self, chunks: tuple) -> None:
        self._chunks = chunks
        self._starts = [ch.keys[0] for ch in chunks]
        self._count = sum(len(ch) for ch in chunks)
        self._stamp += 1
        self._flat = None

    # -- history step function --
    def _loc_le(self, k: bytes) -> Tuple[int, int]:
        """(chunk, index) of the greatest boundary <= k."""
        self._apply_staged()
        c = bisect_right(self._starts, k) - 1
        ch = self._chunks[c]
        return c, bisect_right(ch.keys, k) - 1

    def _loc_lt(self, k: bytes) -> Tuple[int, int]:
        """(chunk, index) of the greatest boundary < k; requires k > b""."""
        self._apply_staged()
        c = bisect_left(self._starts, k) - 1
        ch = self._chunks[c]
        return c, bisect_left(ch.keys, k) - 1

    def _range_max(self, b: bytes, e: bytes) -> int:
        """Max version over [b, e); requires b < e.  Spanning chunks use
        the precomputed chunk max instead of walking rows."""
        ci, ii = self._loc_le(b)
        cj, jj = self._loc_lt(e)
        chunks = self._chunks
        if ci == cj:
            return max(chunks[ci].vers[ii : jj + 1])
        m = max(chunks[ci].vers[ii:])
        for c in range(ci + 1, cj):
            mv = chunks[c].max_ver
            if mv > m:
                m = mv
        mj = max(chunks[cj].vers[: jj + 1])
        return m if m > mj else mj

    def _value_at(self, k: bytes) -> int:
        c, i = self._loc_le(k)
        return self._chunks[c].vers[i]

    # -- ConflictSet ABI (ref fdbserver/ConflictSet.h) --
    def detect(
        self,
        transactions: List[TransactionConflictInfo],
        now: int,
        new_oldest_version: int,
    ) -> List[int]:
        statuses: list[int] = [COMMITTED] * len(transactions)
        # Abort witness (ISSUE 17): per txn, (conflicting write version,
        # losing read-range index into tr.read_ranges) — None unless the
        # final status is CONFLICT.  The device engine reproduces these
        # bit-identically; history conflicts take the FIRST conflicting
        # range and the max committed version inside it, intra-batch
        # conflicts take the first range intersecting the in-batch write
        # union at version `now`.
        witness: list = [None] * len(transactions)

        # Phase 1: too-old + history conflicts (ref checkReadConflictRanges)
        for t, tr in enumerate(transactions):
            if tr.read_snapshot < self.oldest_version and tr.read_ranges:
                statuses[t] = TOO_OLD
                continue
            for i, (rb, re_) in enumerate(tr.read_ranges):
                if rb < re_:
                    m = self._range_max(rb, re_)
                    if m > tr.read_snapshot:
                        statuses[t] = CONFLICT
                        witness[t] = (m, i)
                        break

        # Phase 2: intra-batch, in order (ref checkIntraBatchConflicts)
        active = _IntervalSet()
        for t, tr in enumerate(transactions):
            if statuses[t] != COMMITTED:
                continue
            hit = next(
                (
                    i
                    for i, (rb, re_) in enumerate(tr.read_ranges)
                    if active.intersects(rb, re_)
                ),
                None,
            )
            if hit is not None:
                statuses[t] = CONFLICT
                witness[t] = (now, hit)
                continue
            for (wb, we) in tr.write_ranges:
                active.add(wb, we)

        self.last_witness = witness
        self._commit_writes(active, now, new_oldest_version)
        return statuses

    def apply_batch(
        self,
        transactions: List[TransactionConflictInfo],
        statuses: List[int],
        now: int,
        new_oldest_version: int,
    ) -> None:
        """Adopt an externally-decided batch (the device engine's
        verdicts): merge the committed writes and advance the window
        EXACTLY as detect() would have — one batched chunk sweep, the
        amortized cost ISSUE 9 is about."""
        active = _IntervalSet()
        for t, tr in enumerate(transactions):
            if statuses[t] != COMMITTED:
                continue
            for (wb, we) in tr.write_ranges:
                active.add(wb, we)
        self._commit_writes(active, now, new_oldest_version)

    def _commit_writes(
        self, active: _IntervalSet, now: int, new_oldest_version: int
    ) -> None:
        """Phases 3-4: one batched overwrite sweep for the whole committed
        write union, then the chunk-skipping window eviction."""
        self._apply_staged()
        if active.begins:
            self._apply_intervals(active.begins, active.ends, now)
        if new_oldest_version > self.oldest_version:
            self.oldest_version = new_oldest_version
            self._evict(new_oldest_version)

    # -- phase 3: batched interval overwrite --
    def _apply_intervals(
        self, begins: list, ends: list, now: int
    ) -> None:
        """Set the step function to `now` on every [begins[i], ends[i]).
        Intervals are sorted, disjoint and non-touching (the _IntervalSet
        invariant), so end values can be resolved against the PRE state
        and the whole union applies as one left-to-right sweep.  Chunks
        no interval touches are reused by reference (identity preserved
        for snapshot diffing and the device encode cache)."""
        # Flat-equivalent edit per interval (engine_cpu_flat._overwrite):
        # delete boundaries in [b, e), insert (b, now), insert
        # (e, value_at(e)) unless a boundary already sits at e.
        end_vals = [self._value_at(e) for e in ends]
        chunks = self._chunks
        starts = self._starts
        n_chunks = len(chunks)
        n_int = len(begins)
        out: list = []  # new chunk sequence
        buf_k: list = []  # materialized pairs of the current touched run
        buf_v: list = []
        i = 0  # interval cursor
        in_del = False  # an interval's deletion range is open
        cur_e = b""
        cur_ev = 0
        for c in range(n_chunks):
            ch = chunks[c]
            s = starts[c]
            nxt = starts[c + 1] if c + 1 < n_chunks else None
            if in_del:
                if cur_e <= s:
                    # The open deletion ends exactly at this chunk's start
                    # boundary (cur_e >= previous nxt == s): that boundary
                    # exists, so no insert — close and fall through.
                    in_del = False
                    i += 1
                elif nxt is not None and cur_e >= nxt:
                    # Every boundary in [s, nxt) is inside [b, e): the
                    # whole chunk is deleted without materializing it.
                    continue
            if not in_del and not (
                i < n_int and (nxt is None or begins[i] < nxt)
            ):
                # Untouched: reuse by reference.
                self._flush_pairs(out, buf_k, buf_v)
                out.append(ch)
                continue
            # Touched (or a deletion closes inside it): materialize.
            keys, vers = ch.keys, ch.vers
            m = len(keys)
            j = 0
            while j < m:
                k = keys[j]
                if in_del:
                    if k < cur_e:
                        j += 1  # deleted
                        continue
                    if k != cur_e:
                        buf_k.append(cur_e)
                        buf_v.append(cur_ev)
                    in_del = False
                    i += 1
                    continue  # re-examine k outside the deletion
                if i < n_int and begins[i] <= k:
                    buf_k.append(begins[i])
                    buf_v.append(now)
                    in_del = True
                    cur_e = ends[i]
                    cur_ev = end_vals[i]
                    continue  # re-examine k under the new deletion
                buf_k.append(k)
                buf_v.append(vers[j])
                j += 1
            # Tail: intervals starting after the chunk's last boundary but
            # before the next chunk (or anywhere, for the last chunk).
            while True:
                if in_del:
                    if nxt is not None and cur_e >= nxt:
                        break  # deletion spans into the next chunk
                    buf_k.append(cur_e)
                    buf_v.append(cur_ev)
                    in_del = False
                    i += 1
                elif i < n_int and (nxt is None or begins[i] < nxt):
                    buf_k.append(begins[i])
                    buf_v.append(now)
                    in_del = True
                    cur_e = ends[i]
                    cur_ev = end_vals[i]
                else:
                    break
        self._flush_pairs(out, buf_k, buf_v)
        assert not in_del and i == n_int, "interval sweep failed to converge"
        self._set_chunks(tuple(out))

    def _flush_pairs(self, out: list, buf_k: list, buf_v: list) -> None:
        """Re-chunk a run's accumulated (key, ver) pairs into
        ~chunk_size even pieces, append them to `out`, clear the
        buffers, and count the rebuilds — the shared tail of both
        sweeps (_apply_intervals, _evict)."""
        if not buf_k:
            return
        c = self.chunk_size
        pieces = max(1, (len(buf_k) + c - 1) // c)
        step = (len(buf_k) + pieces - 1) // pieces
        for o in range(0, len(buf_k), step):
            out.append(
                self._new_chunk(buf_k[o : o + step], buf_v[o : o + step])
            )
            self.chunks_rebuilt += 1
        del buf_k[:], buf_v[:]

    # -- phase 4: window eviction --
    def _evict(self, old: int) -> None:
        """Drop boundary i (i > 0) iff vers[i] < old and ORIGINAL
        vers[i-1] < old (ref SkipList::removeBefore).  Chunks whose
        min_pair (and cross-chunk first pair) are >= old provably drop
        nothing and are reused by reference; a window advance with no
        droppable boundary anywhere rebuilds NOTHING (evict_skips).
        Survivors of a contiguous run of rewritten chunks are re-chunked
        TOGETHER (the Jiffy node-merge), so heavy eviction coalesces
        shrunken chunks instead of fragmenting toward per-boundary
        chunks over a long-running window."""
        chunks = self._chunks
        self.evict_scans += 1
        out: list = []
        buf_k: list = []  # survivors of the current rewritten run
        buf_v: list = []
        changed = False
        prev_last: Optional[int] = None  # original last version of prev chunk
        for ch in chunks:
            first_pair = _PAIR_INF
            if prev_last is not None:
                v0 = ch.vers[0]
                first_pair = prev_last if prev_last > v0 else v0
            if ch.min_pair >= old and first_pair >= old:
                self._flush_pairs(out, buf_k, buf_v)
                out.append(ch)
            else:
                keys, vers = ch.keys, ch.vers
                for idx in range(len(keys)):
                    v = vers[idx]
                    prev = prev_last if idx == 0 else vers[idx - 1]
                    if prev is None or v >= old or prev >= old:
                        buf_k.append(keys[idx])
                        buf_v.append(v)
                changed = True
            prev_last = ch.vers[-1]
        self._flush_pairs(out, buf_k, buf_v)
        if changed:
            self._set_chunks(tuple(out))
        else:
            self.evict_skips += 1
            # No chunk changed, but oldest_version DID advance (the
            # caller's gate): bump the stamp so "equal stamps mean
            # identical state" stays true for snapshot consumers.
            self._stamp += 1

    def clear(self, version: int):
        self._staged_keys = None  # clear overrides a pending adoption
        self._set_chunks((self._new_chunk([b""], [FLOOR_VERSION]),))
        self.oldest_version = version

    @property
    def boundary_count(self) -> int:
        """O(1): maintained alongside the chunk sequence (ISSUE 9
        satellite; the flat engine pays len(keys))."""
        self._apply_staged()
        return self._count


# -- live reshard handoff (ISSUE 18) --
def slice_snapshot_chunks(
    snap: MirrorSnapshot, lo: bytes, hi: Optional[bytes]
) -> Tuple[int, list]:
    """(version in force at `lo`, chunks of `snap` restricted to the open
    interval (lo, hi)); hi=None means +inf.  The reshard handoff
    primitive: chunks wholly inside the interval are adopted BY
    REFERENCE — their identity (and the per-chunk device encode caches
    riding on ``_Chunk.enc``) survives the move, so rehydrating a moved
    shard re-encodes only the split boundary chunks, O(moved ranges) —
    while chunks straddling `lo`/`hi` are split into fresh chunks.  The
    snapshot is immutable, so a fault landing mid-handoff cannot tear
    the cut."""
    floor = FLOOR_VERSION
    out: list = []
    for ch in snap.chunks:
        keys = ch.keys
        if keys[-1] <= lo:
            # Entire chunk at or below lo: only its last version can be
            # the one in force at lo so far.
            floor = ch.vers[-1]
            continue
        i = 0
        if keys[0] <= lo:
            i = bisect_right(keys, lo)  # first boundary strictly > lo
            floor = ch.vers[i - 1]
        if hi is not None and keys[-1] >= hi:
            j = bisect_left(keys, hi)  # first boundary >= hi (next shard's)
        else:
            j = len(keys)
        if i == 0 and j == len(keys):
            out.append(ch)  # wholly inside: adopt by reference
        elif i < j:
            out.append(_Chunk(keys[i:j], ch.vers[i:j]))
        if hi is not None and keys[-1] >= hi:
            break
    return floor, out


def engine_from_handoff(
    parts, oldest_version: int, chunk: Optional[int] = None
) -> "CpuConflictSet":
    """Build a shard engine for a NEW key range from immutable snapshot
    cuts of the old shards (ISSUE 18 live split-point migration).

    ``parts`` is ``[(snapshot, lo, hi)]`` in global key order, covering
    the new shard's range contiguously (hi=None = +inf); per the
    shard-engine convention the result is re-anchored at ``b""`` with
    the version in force at the first part's ``lo`` as the floor.
    Interior chunks keep their identity (encode caches included); only
    boundary chunks at moved split points are rebuilt."""
    eng = CpuConflictSet(oldest_version, chunk=chunk)
    chunks: list = []
    first_floor: Optional[int] = None
    for snap, lo, hi in parts:
        floor, chs = slice_snapshot_chunks(snap, lo, hi)
        if first_floor is None:
            first_floor = floor
        chunks.extend(chs)
    head = eng._new_chunk(
        [b""], [FLOOR_VERSION if first_floor is None else first_floor]
    )
    eng._set_chunks(tuple([head] + chunks))
    return eng
