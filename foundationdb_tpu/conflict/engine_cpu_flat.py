"""Flat-array host conflict engine: the PRE-ISSUE-9 CpuConflictSet.

Kept in-tree verbatim as the differential TEST ORACLE for the chunked
batch-update snapshot engine that replaced it as the production mirror
(engine_cpu.CpuConflictSet): every verdict and every exported (keys,
vers) state of the new engine is gated bit-identical to this one across
seeds (tests/test_mirror_snapshot.py), and FDB_TPU_MIRROR_ENGINE=flat
selects it as the live mirror for A/B runs (bench.py mirror arm) and as
an operational escape hatch.

Data model (shared by every engine): keys[i] starts the range
[keys[i], keys[i+1]) whose last-committed-write version is vers[i]; the
final entry extends to +infinity and keys[0] is always b"" (the floor).
Replaces the reference's versioned skip list (fdbserver/SkipList.cpp
SkipList::detectConflicts :524, addConflictRanges :511) with a flat
sorted boundary array; per-range updates are O(H) list splices and every
window advance pays a full-array keep rebuild — the costs ISSUE 9
amortized away in the chunked engine.
"""

from __future__ import annotations

from bisect import bisect_left, bisect_right
from typing import List

from .types import CONFLICT, COMMITTED, TOO_OLD, TransactionConflictInfo

FLOOR_VERSION = -(2**62)  # never conflicts with any snapshot


class _IntervalSet:
    """Merged, sorted, half-open intervals; the intra-batch committed-write
    accumulator (plays the reference's MiniConflictSet role,
    SkipList.cpp:1028-1131, but keyed on bytes instead of point indices)."""

    __slots__ = ("begins", "ends")

    def __init__(self):
        self.begins: list[bytes] = []
        self.ends: list[bytes] = []

    def intersects(self, b: bytes, e: bytes) -> bool:
        if b >= e:
            return False
        idx = bisect_right(self.begins, b) - 1
        if idx >= 0 and self.ends[idx] > b:
            return True
        nxt = idx + 1
        return nxt < len(self.begins) and self.begins[nxt] < e

    def add(self, b: bytes, e: bytes) -> None:
        if b >= e:
            return
        lo = bisect_right(self.begins, b) - 1
        if lo >= 0 and self.ends[lo] >= b:
            b = self.begins[lo]
        else:
            lo += 1
        hi = bisect_right(self.begins, e)
        if hi > lo:
            e = max(e, self.ends[hi - 1])
        self.begins[lo:hi] = [b]
        self.ends[lo:hi] = [e]


class FlatCpuConflictSet:
    """Exact reference-semantics engine over a flat sorted step function."""

    def __init__(self, oldest_version: int = 0):
        self.oldest_version = oldest_version
        self.keys: list[bytes] = [b""]
        self.vers: list[int] = [FLOOR_VERSION]
        # Per-txn abort witness of the most recent detect() (ISSUE 17).
        self.last_witness: list = []

    # -- history step function --
    def _range_max(self, b: bytes, e: bytes) -> int:
        """Max version over [b, e); requires b < e."""
        i = bisect_right(self.keys, b) - 1
        j = bisect_left(self.keys, e) - 1
        return max(self.vers[i : j + 1])

    def _value_at(self, k: bytes) -> int:
        return self.vers[bisect_right(self.keys, k) - 1]

    def _overwrite(self, b: bytes, e: bytes, version: int) -> None:
        """Set the step function to `version` on [b, e)."""
        end_val = self._value_at(e)
        i0 = bisect_left(self.keys, b)
        i1 = bisect_left(self.keys, e)
        new_keys = [b]
        new_vers = [version]
        if not (i1 < len(self.keys) and self.keys[i1] == e):
            new_keys.append(e)
            new_vers.append(end_val)
        self.keys[i0:i1] = new_keys
        self.vers[i0:i1] = new_vers

    # -- ConflictSet ABI (ref fdbserver/ConflictSet.h) --
    def detect(
        self,
        transactions: List[TransactionConflictInfo],
        now: int,
        new_oldest_version: int,
    ) -> List[int]:
        statuses: list[int] = [COMMITTED] * len(transactions)
        # Abort witness (ISSUE 17): (version, read-range index) per
        # CONFLICT txn, None otherwise — identical rule to CpuConflictSet
        # so the two mirrors stay differential-gate-identical.
        witness: list = [None] * len(transactions)

        # Phase 1: too-old + history conflicts (ref checkReadConflictRanges)
        for t, tr in enumerate(transactions):
            if tr.read_snapshot < self.oldest_version and tr.read_ranges:
                statuses[t] = TOO_OLD
                continue
            for i, (rb, re_) in enumerate(tr.read_ranges):
                if rb < re_:
                    m = self._range_max(rb, re_)
                    if m > tr.read_snapshot:
                        statuses[t] = CONFLICT
                        witness[t] = (m, i)
                        break

        # Phase 2: intra-batch, in order (ref checkIntraBatchConflicts)
        active = _IntervalSet()
        for t, tr in enumerate(transactions):
            if statuses[t] != COMMITTED:
                continue
            hit = next(
                (
                    i
                    for i, (rb, re_) in enumerate(tr.read_ranges)
                    if active.intersects(rb, re_)
                ),
                None,
            )
            if hit is not None:
                statuses[t] = CONFLICT
                witness[t] = (now, hit)
                continue
            for (wb, we) in tr.write_ranges:
                active.add(wb, we)

        self.last_witness = witness
        self._commit_writes(active, now, new_oldest_version)
        return statuses

    def _commit_writes(
        self, active: _IntervalSet, now: int, new_oldest_version: int
    ) -> None:
        """Phases 3-4 on an already-decided batch: merge the committed
        write union into history at `now`, then evict below the window."""
        # Phase 3: merge committed writes at `now` (ref mergeWriteConflictRanges)
        # `active` is exactly the union of committed writes, already merged.
        for b, e in zip(active.begins, active.ends):
            self._overwrite(b, e, now)

        # Phase 4: window eviction (ref SkipList::removeBefore — drop a
        # boundary iff it and its original predecessor are both below window)
        if new_oldest_version > self.oldest_version:
            self.oldest_version = new_oldest_version
            old = self.oldest_version
            keys, vers = self.keys, self.vers
            keep = [
                i == 0 or vers[i] >= old or vers[i - 1] >= old
                for i in range(len(keys))
            ]
            if not all(keep):
                self.keys = [k for k, kp in zip(keys, keep) if kp]
                self.vers = [v for v, kp in zip(vers, keep) if kp]

    def apply_batch(
        self,
        transactions: List[TransactionConflictInfo],
        statuses: List[int],
        now: int,
        new_oldest_version: int,
    ) -> None:
        """Adopt an externally-decided batch (the device engine's verdicts)
        into this engine's history: the committed transactions' writes are
        merged and the window advanced EXACTLY as detect() would have —
        since the device decides bit-identically, the mirrored state is
        indistinguishable from having run the batch here."""
        active = _IntervalSet()
        for t, tr in enumerate(transactions):
            if statuses[t] != COMMITTED:
                continue
            for (wb, we) in tr.write_ranges:
                active.add(wb, we)
        self._commit_writes(active, now, new_oldest_version)

    def clear(self, version: int):
        self.keys = [b""]
        self.vers = [FLOOR_VERSION]
        self.oldest_version = version

    @property
    def boundary_count(self) -> int:
        return len(self.keys)
