"""Pallas TPU kernels for the two remaining hot phases (ISSUE 14,
ROADMAP open item 1): the merge/evict compaction and the phase-1 history
search.

PR 12's in-step phase attribution recorded the inference round-5 made: at
bench shape the two ``_compact_to`` sort-by-target passes (merge 75% +
evict 25% of attributed FLOPs) ARE the device step, and phase 1 is 24
binary-search rounds x kw1 words of random gathers into a ~96MB
HBM-resident history table.  Both are replaced here by streaming Pallas
kernels behind the ``FDB_TPU_KERNELS`` g_env flag (flow/knobs.py):

**Fused merge-evict-compact** (``fused_merge_evict``): the inputs of every
compaction site are ALREADY SORTED — the frozen base tier, the sorted
delta, and the batch's sorted segment rows — and the engine's rank-
inversion prep (streaming cumsums/histograms, no sort) already knows each
row's merged position.  So the rewrite is a single sequential-grid pass:

  phase A/B   locally compact each tier's surviving rows into a dense
              scratch stream (one-hot MXU placement — a (T,T) selection
              matmul on 16-bit-split words, exact for all 32-bit values —
              written at an SMEM write cursor; TPU grids run sequentially,
              so the cursor is race-free)
  merge       for each output tile, DMA one contiguous slice of each
              dense stream (positions partition the tile, so the slice
              starts are pure arithmetic), place rows by position,
              apply the reference removeBefore eviction rule IN-STREAM
              (the predecessor version is a carried SMEM scalar), and
              write the surviving rows at the output cursor

One pass over VMEM-resident tiles replaces the two full-width
sort-by-target passes (O(N) data movement instead of O(N log^2 N) sorting
network passes), and the eviction filter rides the same pass.  The same
kernel serves the flat per-batch merge (width = h_cap), the tiered
steady-state delta merge (width = d_cap), and the major compaction inside
the traced cond (width = h_cap) — so with kernels on there is NO
sort-by-target pass at history width anywhere in the program
(tests/test_perf_smoke.py pins this structurally).

**Fused phase-1 search** (``phase1_ranks``): queries are sorted once
(batch-domain sort), then a sequential grid walks the history ONE TILE AT
A TIME, keeping the tile VMEM-resident and answering every query that
completes inside it with a broadcast compare + row-count — the
tier-combined binary searches become one linear streaming pass over the
table at DMA bandwidth instead of log2(H) rounds of latency-bound HBM
gathers.  Sorted queries resolve in order, so a single SMEM cursor tracks
progress and tiles containing no pending query skip the compare entirely.

Both kernels are bit-identical to the XLA fallback by construction (they
consume the same rank-inversion prep and implement the same removeBefore
rule) and are differential-gated on CPU in interpret mode
(tests/test_kernels.py): verdicts AND exported state across seeds x
flat/tiered/sharded modes, with scripted device faults on kernelized
batches.  The XLA path remains the default fallback and the A/B arm;
``FDB_TPU_KERNELS`` auto-selects kernels on the TPU backend only.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import keys as keylib

POS_MAX = 2**31 - 1  # python int: kernel bodies must not capture tracers
_INF = keylib.INF_WORD


def kernels_requested(flag: str, backend: str) -> bool:
    """Resolve the FDB_TPU_KERNELS g_env value against a jax backend name.

    ''/'auto'  kernels on the TPU backend only (compiled Mosaic)
    '1'        kernels everywhere (interpret-mode Pallas off-TPU — the
               differential-gating arm on CPU)
    'interpret' kernels everywhere, interpreter forced even on TPU
    '0'        XLA fallback everywhere (the default A/B arm)
    """
    if flag in ("", "auto"):
        return backend == "tpu"
    if flag in ("1", "interpret"):
        return True
    return False


def kernel_interpret(flag: str, backend: str) -> bool:
    """Whether pallas_call should run interpreted (trace-time static)."""
    if flag == "interpret":
        return True
    return backend != "tpu"


def resolve_kernel_flag(backend: str) -> tuple:
    """Validate FDB_TPU_KERNELS (g_env) against a jax backend name and
    resolve it to (use_kernels, interpret).  The ONE entry for every
    engine constructor — an unrecognized value raises here, so a typo'd
    flag can never silently select the XLA fallback."""
    from ..flow.knobs import g_env

    flag = g_env.get("FDB_TPU_KERNELS")
    if flag not in ("", "auto", "0", "1", "interpret"):
        raise ValueError(
            f"FDB_TPU_KERNELS={flag!r}: expected ''/'auto'/'0'/'1'"
            f"/'interpret'"
        )
    return kernels_requested(flag, backend), kernel_interpret(flag, backend)


def _tile(width: int, *divisors: int, cap: int = 256) -> int:
    """Largest power-of-two tile <= cap dividing width and every divisor.
    Engine buffer widths are pow2 multiples (PackedBatch bucketing,
    _next_pow2 growth, h_cap defaults), so this is >= 8 in practice."""
    t = 1
    while t * 2 <= cap and width % (t * 2) == 0 and all(
        d % (t * 2) == 0 for d in divisors
    ):
        t *= 2
    return t


def _split16(x_u32):
    """(..., T) uint32 -> (hi, lo) float32 halves, exact for all 32-bit
    values (each half <= 65535 < 2^24)."""
    hi = (x_u32 >> jnp.uint32(16)).astype(jnp.float32)
    lo = (x_u32 & jnp.uint32(0xFFFF)).astype(jnp.float32)
    return hi, lo


def _combine16(hi_f32, lo_f32):
    """Inverse of _split16 (exact integer halves back to uint32)."""
    return (hi_f32.astype(jnp.uint32) * jnp.uint32(65536)
            + lo_f32.astype(jnp.uint32))


def _i32_as_u32(x):
    return jax.lax.bitcast_convert_type(x, jnp.uint32)


def _u32_as_i32(x):
    return jax.lax.bitcast_convert_type(x, jnp.int32)


def _place(lhs_f32, slot, mask, T):
    """One-hot MXU placement: out[:, j] = lhs[:, i] where slot[i] == j and
    mask[i], else 0.  slot/mask are (T,) int32/bool; lhs (R, T) f32 rows
    of 16-bit word halves.  A (T, T) f32 selection matmul — the TPU-native
    form of a unique-target local scatter (slots are unique where masked).

    precision=HIGHEST is load-bearing: the MXU's default f32 precision
    truncates inputs to bf16 (8-bit mantissa), which rounds halves like
    0x8001 — corrupting keys exactly on the one backend where the
    kernels run compiled, invisibly to the CPU interpret-mode gate
    (interpret f32 is exact either way).  HIGHEST keeps every 16-bit
    half exact (<= 65535 < 2^24).
    """
    j = jax.lax.broadcasted_iota(jnp.int32, (T, T), 0)  # out slot per row
    m = ((slot[None, :] == j) & mask[None, :]).astype(jnp.float32)
    return jax.lax.dot_general(
        lhs_f32, m, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )


def _pack_rows(keys_u32, vers_i32, pos_i32=None):
    """Stack (kw1, T) key words + (T,) vers (+ optional pos) into the
    16-bit-split f32 row matrix _place consumes."""
    rows = []
    kw1 = keys_u32.shape[0]
    for w in range(kw1):
        hi, lo = _split16(keys_u32[w])
        rows.append(hi)
        rows.append(lo)
    vh, vl = _split16(_i32_as_u32(vers_i32))
    rows.append(vh)
    rows.append(vl)
    if pos_i32 is not None:
        ph, plo = _split16(_i32_as_u32(pos_i32))
        rows.append(ph)
        rows.append(plo)
    return jnp.stack(rows)


def _unpack_rows(placed, kw1, with_pos=False):
    """Inverse of _pack_rows on the placed (R, T) f32 matrix."""
    keys = jnp.stack([
        _combine16(placed[2 * w], placed[2 * w + 1]) for w in range(kw1)
    ])
    vers = _u32_as_i32(_combine16(placed[2 * kw1], placed[2 * kw1 + 1]))
    if not with_pos:
        return keys, vers, None
    pos = _u32_as_i32(_combine16(placed[2 * kw1 + 2], placed[2 * kw1 + 3]))
    return keys, vers, pos


# ---------------------------------------------------------------------------
# Fused merge-evict-compact
# ---------------------------------------------------------------------------


def _merge_kernel_body(
    kw1, T, nA, nB, nM, width,
    # refs (order mirrors pallas_call wiring below)
    scal, startb,
    a_keys, a_vers, a_keep, a_pos,
    b_keys, b_vers, b_keep, b_pos,
    out_keys, out_vers, out_count,
    da_keys, da_vers, da_pos,
    db_keys, db_vers, db_pos,
    k1, v1, m1, p1, k2, v2, p2, ko, vo, po, cur, sems,
):
    # Explicit int32: program_id traces 64-bit under enable_x64 (the
    # JXP004 audit re-trace), and every cursor/SMEM slot here is int32.
    pid = pl.program_id(0).astype(jnp.int32)
    PA, SA, PB, SB, PM = 0, nA, nA + 1, nA + 1 + nB, nA + 1 + nB + 1
    merged_count = scal[0]
    window = scal[1]

    @pl.when(pid == 0)
    def _init():
        cur[0] = 0  # dense-A write cursor
        cur[1] = 0  # dense-B write cursor
        cur[2] = 0  # output write cursor
        cur[3] = jnp.int32(-(2**30))  # prev merged version carry

    def compact_tile(t, sk, sv, skp, sp, dk, dv, dp, slot):
        """One source tile -> dense stream at the cursor (phase A/B)."""
        c0 = pltpu.make_async_copy(sk.at[:, pl.ds(t * T, T)], k1, sems.at[0])
        c1 = pltpu.make_async_copy(sv.at[pl.ds(t * T, T)], v1, sems.at[1])
        c2 = pltpu.make_async_copy(skp.at[pl.ds(t * T, T)], m1, sems.at[2])
        c3 = pltpu.make_async_copy(sp.at[pl.ds(t * T, T)], p1, sems.at[3])
        c0.start(); c1.start(); c2.start(); c3.start()
        c0.wait(); c1.wait(); c2.wait(); c3.wait()
        keep = m1[:] != 0
        rank = jnp.cumsum(keep, dtype=jnp.int32) - 1
        kcnt = jnp.sum(keep, dtype=jnp.int32)
        placed = _place(_pack_rows(k1[:], v1[:], p1[:]), rank, keep, T)
        pk, pv, pp = _unpack_rows(placed, kw1, with_pos=True)
        iota = jax.lax.broadcasted_iota(jnp.int32, (T, 1), 0)[:, 0]
        ko[:] = pk
        vo[:] = pv
        # Slots past the tile's survivor count carry the placement
        # matmul's zeros — a VALID position — so they are overwritten
        # with the sentinel the merge phase masks on.
        po[:] = jnp.where(iota < kcnt, pp, POS_MAX)
        w = cur[slot]
        o0 = pltpu.make_async_copy(ko, dk.at[:, pl.ds(w, T)], sems.at[4])
        o1 = pltpu.make_async_copy(vo, dv.at[pl.ds(w, T)], sems.at[5])
        o2 = pltpu.make_async_copy(po, dp.at[pl.ds(w, T)], sems.at[6])
        o0.start(); o1.start(); o2.start()
        o0.wait(); o1.wait(); o2.wait()
        cur[slot] = w + kcnt

    def sentinel_tile(dp, slot):
        """After a stream's last tile: one sentinel-position tile at the
        final cursor, so merge-phase reads of [start, start+T) never see
        an unwritten position row (start <= live count <= cursor)."""
        po[:] = jnp.full((T,), POS_MAX, jnp.int32)
        w = cur[slot]
        o2 = pltpu.make_async_copy(po, dp.at[pl.ds(w, T)], sems.at[6])
        o2.start(); o2.wait()

    @pl.when(pid < SA)
    def _phase_a():
        compact_tile(pid - PA, a_keys, a_vers, a_keep, a_pos,
                     da_keys, da_vers, da_pos, 0)

    @pl.when(pid == SA)
    def _sent_a():
        sentinel_tile(da_pos, 0)

    @pl.when((pid > SA) & (pid < SB))
    def _phase_b():
        compact_tile(pid - PB, b_keys, b_vers, b_keep, b_pos,
                     db_keys, db_vers, db_pos, 1)

    @pl.when(pid == SB)
    def _sent_b():
        sentinel_tile(db_pos, 1)

    @pl.when(pid >= PM)
    def _phase_merge():
        t = pid - PM
        base = t * T
        # Positions partition [0, merged_count): the dense-A slice for
        # this tile starts where the dense-B slice leaves off.
        nm_iota = jax.lax.broadcasted_iota(jnp.int32, (nM, 1), 0)[:, 0]
        b0 = jnp.sum(jnp.where(nm_iota == t, startb[:], 0), dtype=jnp.int32)
        a0 = base - b0
        c0 = pltpu.make_async_copy(da_keys.at[:, pl.ds(a0, T)], k1, sems.at[0])
        c1 = pltpu.make_async_copy(da_vers.at[pl.ds(a0, T)], v1, sems.at[1])
        c2 = pltpu.make_async_copy(da_pos.at[pl.ds(a0, T)], p1, sems.at[2])
        c3 = pltpu.make_async_copy(db_keys.at[:, pl.ds(b0, T)], k2, sems.at[3])
        c4 = pltpu.make_async_copy(db_vers.at[pl.ds(b0, T)], v2, sems.at[4])
        c5 = pltpu.make_async_copy(db_pos.at[pl.ds(b0, T)], p2, sems.at[5])
        c0.start(); c1.start(); c2.start(); c3.start(); c4.start(); c5.start()
        c0.wait(); c1.wait(); c2.wait(); c3.wait(); c4.wait(); c5.wait()
        slot_a = p1[:] - base
        slot_b = p2[:] - base
        in_a = (slot_a >= 0) & (slot_a < T)
        in_b = (slot_b >= 0) & (slot_b < T)
        merged = (
            _place(_pack_rows(k1[:], v1[:]), slot_a, in_a, T)
            + _place(_pack_rows(k2[:], v2[:]), slot_b, in_b, T)
        )
        mk, mv, _ = _unpack_rows(merged, kw1)
        iota = jax.lax.broadcasted_iota(jnp.int32, (T, 1), 0)[:, 0]
        gpos = base + iota
        occ = gpos < merged_count
        prev = jnp.concatenate(
            [jnp.broadcast_to(cur[3], (1,)).astype(jnp.int32), mv[:-1]]
        )
        # The reference removeBefore wasAbove rule, streamed: drop row p
        # iff p > 0 and both it and its merged-order predecessor sit
        # below the window.  The no-evict arms pass window = FLOOR (every
        # version >= it), which reduces this to keep = occ — the merge
        # result verbatim.
        ev = occ & (gpos > 0) & (mv < window) & (prev < window)
        keep = occ & ~ev
        cur[3] = jnp.where(occ[T - 1], mv[T - 1], cur[3])
        rank = jnp.cumsum(keep, dtype=jnp.int32) - 1
        n = jnp.sum(keep, dtype=jnp.int32)
        placed = _place(merged, rank, keep, T)
        pk, pv, _ = _unpack_rows(placed, kw1)
        ko[:] = pk
        vo[:] = pv
        w = cur[2]
        o0 = pltpu.make_async_copy(ko, out_keys.at[:, pl.ds(w, T)], sems.at[6])
        o1 = pltpu.make_async_copy(vo, out_vers.at[pl.ds(w, T)], sems.at[7])
        o0.start(); o1.start()
        o0.wait(); o1.wait()
        cur[2] = w + n

        @pl.when(t == nM - 1)
        def _final():
            out_count[0] = cur[2]


def fused_merge_evict(
    a_keys, a_vers, a_keep, a_pos,
    b_keys, b_vers, b_keep, b_pos,
    merged_count, window,
    *, width: int, kw1: int, tile: int = 256, interpret: bool = False,
):
    """Merge two position-annotated sorted streams, evict by the
    removeBefore rule against ``window``, and compact — one streaming
    pass.

    a_*: the big tier (NA rows): keys (kw1, NA) u32, vers (NA,) i32,
    keep (NA,) i32 mask, pos (NA,) i32 pre-eviction merged position
    (only read where keep).  b_*: the small stream likewise.  Kept
    positions must partition [0, merged_count).  window = FLOOR_REL
    disables eviction (keep = merge).  Returns (out_keys (kw1, width)
    u32, out_vers (width,) i32, out_count i32 scalar); rows at and above
    out_count are UNDEFINED — callers mask with the live count exactly
    like the sort path's _compact_to does.
    """
    NA = a_keys.shape[1]
    NB = b_keys.shape[1]
    T = _tile(width, NA, NB, cap=tile)
    nA, nB, nM = NA // T, NB // T, width // T
    # Dense-slice starts: start_b[t] = kept B rows with pos < t*T, via a
    # small histogram (NB items) + exclusive cumsum — never an H-sized
    # scatter.
    b_bins = (
        jnp.zeros((nM + 1,), jnp.int32)
        .at[jnp.where(b_keep != 0, jnp.clip(b_pos // T, 0, nM), nM)]
        .add(jnp.where(b_keep != 0, 1, 0))
    )
    start_b = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(b_bins[:nM], dtype=jnp.int32)]
    )[:nM]
    scal = jnp.stack([merged_count.astype(jnp.int32),
                      window.astype(jnp.int32)])

    grid = (nA + 1 + nB + 1 + nM,)
    kernel = functools.partial(_merge_kernel_body, kw1, T, nA, nB, nM, width)
    out_shapes = (
        jax.ShapeDtypeStruct((kw1, width + T), jnp.uint32),   # out_keys
        jax.ShapeDtypeStruct((width + T,), jnp.int32),        # out_vers
        jax.ShapeDtypeStruct((1,), jnp.int32),                # out_count
        jax.ShapeDtypeStruct((kw1, NA + 2 * T), jnp.uint32),  # dense A
        jax.ShapeDtypeStruct((NA + 2 * T,), jnp.int32),
        jax.ShapeDtypeStruct((NA + 2 * T,), jnp.int32),
        jax.ShapeDtypeStruct((kw1, NB + 2 * T), jnp.uint32),  # dense B
        jax.ShapeDtypeStruct((NB + 2 * T,), jnp.int32),
        jax.ShapeDtypeStruct((NB + 2 * T,), jnp.int32),
    )
    any_spec = pl.BlockSpec(memory_space=pltpu.ANY)
    smem_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    vmem_spec = pl.BlockSpec(memory_space=pltpu.VMEM)
    outs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[smem_spec, vmem_spec] + [any_spec] * 8,
        out_specs=(any_spec, any_spec, smem_spec) + (any_spec,) * 6,
        out_shape=out_shapes,
        scratch_shapes=[
            pltpu.VMEM((kw1, T), jnp.uint32),   # k1
            pltpu.VMEM((T,), jnp.int32),        # v1
            pltpu.VMEM((T,), jnp.int32),        # m1 (keep)
            pltpu.VMEM((T,), jnp.int32),        # p1
            pltpu.VMEM((kw1, T), jnp.uint32),   # k2
            pltpu.VMEM((T,), jnp.int32),        # v2
            pltpu.VMEM((T,), jnp.int32),        # p2
            pltpu.VMEM((kw1, T), jnp.uint32),   # ko
            pltpu.VMEM((T,), jnp.int32),        # vo
            pltpu.VMEM((T,), jnp.int32),        # po
            pltpu.SMEM((4,), jnp.int32),        # cursors + prev carry
            pltpu.SemaphoreType.DMA((8,)),
        ],
        interpret=interpret,
    )(
        scal, start_b,
        a_keys, a_vers, a_keep.astype(jnp.int32), a_pos,
        b_keys, b_vers, b_keep.astype(jnp.int32), b_pos,
    )
    out_keys, out_vers, out_count = outs[0], outs[1], outs[2]
    return out_keys[:, :width], out_vers[:width], out_count[0]


# ---------------------------------------------------------------------------
# Fused phase-1 search
# ---------------------------------------------------------------------------


def _search_kernel_body(
    kw1, TH, TQ, nH, M,
    q_keys, q_side,
    h_keys,
    ranks,
    ht, qk, qs, ro, cur, sems,
):
    pid = pl.program_id(0).astype(jnp.int32)  # int32 under x64 too
    last_tile = pid == nH - 1

    @pl.when(pid == 0)
    def _init():
        cur[0] = 0          # queries fully resolved so far
        cur[1] = 0          # next-pending-query cache valid?
        for w in range(kw1 + 1):
            cur[2 + w] = 0  # next query's words + side

    c0 = pltpu.make_async_copy(h_keys.at[:, pl.ds(pid * TH, TH)], ht,
                               sems.at[0])
    c0.start(); c0.wait()

    # Scalar guard: skip the whole tile when the next pending query
    # cannot complete here (its rank lies beyond this tile).  Lex compare
    # of the cached next-query words against the tile's last key.
    def next_q_completes():
        lt = jnp.bool_(False)
        eq = jnp.bool_(True)
        for w in range(kw1):
            kw_ = ht[w, TH - 1]
            qw = _i32_as_u32(cur[2 + w])
            lt = lt | (eq & (qw < kw_))
            eq = eq & (qw == kw_)
        # side 0 (left, counts <) completes when q <= last; side 1
        # (right, counts <=) needs q < last strictly.
        is_left = cur[2 + kw1] == 0
        return lt | (eq & is_left)

    pending = cur[0] < M
    enter = pending & (last_tile | (cur[1] == 0) | next_q_completes())

    @pl.when(enter)
    def _scan():
        def body(carry):
            qc, _cont = carry
            d0 = pltpu.make_async_copy(q_keys.at[:, pl.ds(qc, TQ)], qk,
                                       sems.at[1])
            d1 = pltpu.make_async_copy(q_side.at[pl.ds(qc, TQ)], qs,
                                       sems.at[2])
            d0.start(); d1.start()
            d0.wait(); d1.wait()
            # (TQ, TH) pairwise lex compares, trailing word first so the
            # most significant word decides last (rangequery.lex_less).
            lt = jnp.zeros((TQ, TH), bool)
            le = jnp.ones((TQ, TH), bool)
            for w in range(kw1 - 1, -1, -1):
                hw = ht[w][None, :]
                qw = qk[w][:, None]
                lt = (hw < qw) | ((hw == qw) & lt)
                le = (hw < qw) | ((hw == qw) & le)
            right = qs[:] != 0
            cnt = jnp.sum(
                jnp.where(right[:, None], le, lt), axis=1, dtype=jnp.int32
            )
            ro[:] = pid * TH + cnt
            iota = jax.lax.broadcasted_iota(jnp.int32, (TQ, 1), 0)[:, 0]
            valid = (qc + iota) < M
            # Completion: strictly-below-last for right-side counts,
            # at-or-below for left — monotone over the sorted query
            # stream, so completions form a prefix of the chunk.
            lt_last = jnp.zeros((TQ,), bool)
            eq_last = jnp.ones((TQ,), bool)
            for w in range(kw1 - 1, -1, -1):
                hw = ht[w, TH - 1]
                qw = qk[w]
                lt_last = (qw < hw) | ((qw == hw) & lt_last)
                eq_last = eq_last & (qw == hw)
            fin = valid & (last_tile | lt_last | (eq_last & ~right))
            n_fin = jnp.sum(fin, dtype=jnp.int32)
            o0 = pltpu.make_async_copy(ro, ranks.at[pl.ds(qc, TQ)],
                                       sems.at[3])
            o0.start(); o0.wait()
            # Cache the first unresolved query for the next tile's guard.
            sel = (iota == n_fin).astype(jnp.int32)
            for w in range(kw1):
                cur[2 + w] = jnp.sum(sel * _u32_as_i32(qk[w]), dtype=jnp.int32)
            cur[2 + kw1] = jnp.sum(sel * qs[:], dtype=jnp.int32)
            cur[1] = 1
            cur[0] = qc + n_fin
            cont = (n_fin == TQ) & (qc + n_fin < M)
            return qc + n_fin, cont

        def cond(carry):
            return carry[1]

        jax.lax.while_loop(cond, body, (cur[0], jnp.bool_(True)))


def phase1_ranks(h_keys, q_keys, q_side, *, tile_h: int = 512,
                 tile_q: int = 128, interpret: bool = False):
    """Insertion ranks of PRE-SORTED queries into sorted history keys by
    one streaming pass over the table.

    h_keys (kw1, N) u32 word-major (INF-padded past the live count, like
    the carried history buffers); q_keys (kw1, M) SORTED ascending with
    q_side as the least-significant sort key; q_side (M,) i32 — 0: left
    rank (count of rows < q), 1: right rank (count of rows <= q).
    Returns ranks (M,) i32 in the sorted order — bit-identical to
    ops.rangequery.searchsorted_words over the same width.
    """
    kw1, N = h_keys.shape
    M = q_keys.shape[1]
    TH = _tile(N, cap=tile_h)
    TQ = _tile(M, cap=tile_q)
    nH = N // TH
    # Pad the query stream by one chunk: the cursor advances by the
    # completed-prefix length, so a chunk DMA at an unaligned cursor may
    # read past M — the pad keeps it in bounds (padded rows are never
    # counted: the in-kernel valid mask cuts at M).
    q_keys = jnp.concatenate(
        [q_keys, jnp.zeros((kw1, TQ), jnp.uint32)], axis=1
    )
    q_side = jnp.concatenate([q_side, jnp.zeros((TQ,), jnp.int32)])
    kernel = functools.partial(_search_kernel_body, kw1, TH, TQ, nH, M)
    any_spec = pl.BlockSpec(memory_space=pltpu.ANY)
    out = pl.pallas_call(
        kernel,
        grid=(nH,),
        in_specs=[any_spec] * 3,
        out_specs=any_spec,
        out_shape=jax.ShapeDtypeStruct((M + TQ,), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((kw1, TH), jnp.uint32),  # history tile
            pltpu.VMEM((kw1, TQ), jnp.uint32),  # query chunk
            pltpu.VMEM((TQ,), jnp.int32),       # query sides
            pltpu.VMEM((TQ,), jnp.int32),       # rank staging
            pltpu.SMEM((2 + kw1 + 1,), jnp.int32),
            pltpu.SemaphoreType.DMA((4,)),
        ],
        interpret=interpret,
    )(q_keys, q_side, h_keys)
    return out[:M]


def phase1_search_tiers(tiers, r_begin, r_end, *, interpret: bool = False):
    """Kernelized phase 1: (i0, j1) rank pairs for EVERY history tier
    from ONE shared batch-domain query sort.

    Matches detect_core's XLA pair bit-for-bit per tier:
      i0 = searchsorted_words(tier, r_begin, 'right') - 1
      j1 = searchsorted_words(tier, r_end, 'left') - 1
    The two query sets are sorted together once (side is the least-
    significant key so equal-key left queries complete first), every
    tier's streaming kernel consumes the same sorted stream, and ONE
    multi-operand small sort un-permutes all tiers' ranks — the tiered
    engine's base+delta searches share both sorts instead of paying
    them per tier.  Returns [(i0, j1), ...] aligned with `tiers`.
    """
    kw1, R = r_begin.shape[0], r_begin.shape[1]
    M = 2 * R
    q = jnp.concatenate([r_end, r_begin], axis=1)
    side = jnp.concatenate(
        [jnp.zeros((R,), jnp.int32), jnp.ones((R,), jnp.int32)]
    )
    iota = jnp.arange(M, dtype=jnp.int32)
    ops = tuple(q[w] for w in range(kw1)) + (side, iota)
    res = jax.lax.sort(ops, num_keys=kw1 + 1, is_stable=True)
    q_sorted = jnp.stack(res[:kw1])
    side_sorted = res[kw1]
    perm = res[kw1 + 1]
    ranks_sorted = [
        phase1_ranks(h, q_sorted, side_sorted, interpret=interpret)
        for h in tiers
    ]
    # Un-permute: sort (perm, ranks...) by perm — one second small sort
    # for every tier together, no scatter.
    back = jax.lax.sort((perm, *ranks_sorted), num_keys=1, is_stable=True)
    out = []
    for t in range(len(tiers)):
        ranks = back[1 + t]
        out.append((ranks[R:] - 1, ranks[:R] - 1))
    return out


def phase1_search(h_keys, r_begin, r_end, *, interpret: bool = False):
    """Single-tier convenience wrapper over phase1_search_tiers."""
    ((i0, j1),) = phase1_search_tiers(
        (h_keys,), r_begin, r_end, interpret=interpret
    )
    return i0, j1
