"""ConflictSet: the unified conflict-engine ABI, mirroring
fdbserver/ConflictSet.h (newConflictSet/ConflictBatch::addTransaction/
detectConflicts) with backend dispatch.

Backends:
  "cpu"    - engine_cpu.CpuConflictSet (host, exact, low latency)
  "jax"    - engine_jax.JaxConflictSet (device, whole-batch vectorized)
  "oracle" - oracle.OracleConflictSet (test-only brute force)
  "hybrid" - jax for large batches, cpu for small ones / oversized keys,
             with state kept authoritative on whichever side last ran
             (the async-offload + fallback design from BASELINE.json)

Usage mirrors the reference ABI:
    cs = ConflictSet(backend="hybrid")
    batch = cs.new_batch()
    for tr in txns: batch.add_transaction(tr)
    statuses = batch.detect_conflicts(now, new_oldest_version)
"""

from __future__ import annotations

from typing import List, Optional

from ..flow.knobs import g_knobs
from .engine_cpu import CpuConflictSet
from .oracle import OracleConflictSet
from .types import TransactionConflictInfo


class ConflictBatch:
    """Ref: ConflictBatch in fdbserver/ConflictSet.h:32."""

    def __init__(self, cs: "ConflictSet"):
        self._cs = cs
        self._txns: list[TransactionConflictInfo] = []

    def add_transaction(self, tr: TransactionConflictInfo):
        self._txns.append(tr)

    @property
    def transaction_count(self) -> int:
        return len(self._txns)

    def detect_conflicts(self, now: int, new_oldest_version: int) -> List[int]:
        return self._cs._detect(self._txns, now, new_oldest_version)


class ConflictSet:
    def __init__(
        self,
        backend: str = "cpu",
        oldest_version: int = 0,
        key_words: Optional[int] = None,
        device=None,
        bucket_mins: tuple = (8, 8, 8),
    ):
        self.backend = backend
        self._cpu: Optional[CpuConflictSet] = None
        self._jax = None
        self._oracle: Optional[OracleConflictSet] = None
        kw = key_words if key_words is not None else g_knobs.server.conflict_device_key_words
        if backend in ("cpu", "hybrid"):
            self._cpu = CpuConflictSet(oldest_version)
        if backend == "oracle":
            self._oracle = OracleConflictSet(oldest_version)
        if backend in ("jax", "hybrid"):
            from .engine_jax import JaxConflictSet  # lazy: jax import is heavy

            self._jax = JaxConflictSet(
                oldest_version=oldest_version,
                key_words=kw,
                device=device,
                bucket_mins=bucket_mins,
            )
        # hybrid: which side holds the authoritative history
        self._authority = "cpu" if backend == "hybrid" else backend
        self._key_words = kw
        # True once a long-key write range may have entered CPU history;
        # the device cannot represent it, so authority stays on CPU.
        self._history_long_keys = False
        # Hysteresis: consecutive sub-threshold batches seen while device
        # authority is held.  Authority only returns to the CPU after
        # AUTHORITY_HYSTERESIS of them — an alternating big/small workload
        # must not pay a full history transfer per flip (ADVICE r1).
        self._small_streak = 0

    AUTHORITY_HYSTERESIS = 8

    def new_batch(self) -> ConflictBatch:
        return ConflictBatch(self)

    @property
    def oldest_version(self) -> int:
        eng = self._engine_for_authority()
        return eng.oldest_version

    def _engine_for_authority(self):
        return {"cpu": self._cpu, "jax": self._jax, "oracle": self._oracle}[
            self._authority
        ]

    def _detect(self, txns, now, new_oldest_version) -> List[int]:
        if self.backend == "hybrid":
            return self._detect_hybrid(txns, now, new_oldest_version)
        return self._engine_for_authority().detect(txns, now, new_oldest_version)

    def _detect_hybrid(self, txns, now, new_oldest_version) -> List[int]:
        srv = g_knobs.server
        max_key = min(srv.conflict_max_device_key_bytes, self._key_words * 4)
        big = len(txns) >= srv.conflict_device_min_batch
        batch_fits = all(
            len(b) <= max_key and len(e) <= max_key
            for tr in txns
            for (b, e) in tr.read_ranges + tr.write_ranges
        )
        if not batch_fits and any(
            len(b) > max_key or len(e) > max_key
            for tr in txns
            for (b, e) in tr.write_ranges
        ):
            # A long-key write may enter history; until the window flushes it
            # the device state cannot represent the step function exactly.
            # Conservative: pin authority to CPU until clear().
            self._history_long_keys = True
        device_ok = batch_fits and not self._history_long_keys
        if device_ok and self._authority == "jax":
            # Already on device: run there even below the size threshold
            # (device dispatch on a warm small bucket beats a full history
            # transfer); only a sustained small streak flips authority back.
            self._small_streak = 0 if big else self._small_streak + 1
            if self._small_streak < self.AUTHORITY_HYSTERESIS:
                return self._jax.detect(txns, now, new_oldest_version)
        if big and device_ok:
            if self._authority == "cpu":
                self._jax.load_from(self._cpu)
                self._authority = "jax"
                self._small_streak = 0
            return self._jax.detect(txns, now, new_oldest_version)
        if self._authority == "jax":
            self._jax.store_to(self._cpu)
            self._authority = "cpu"
            self._small_streak = 0
        return self._cpu.detect(txns, now, new_oldest_version)

    def device_metrics(self, now=None) -> Optional[dict]:
        """Kernel-telemetry snapshot of the device engine (retraces,
        padding occupancy, fixpoint rounds, grow/rebase — see
        engine_jax.JaxConflictSet.metrics), or None for host-only
        backends.  Feeds the status doc's tpu section and `cli metrics`."""
        if self._jax is None:
            return None
        snap = self._jax.metrics.snapshot(now=now)
        snap["last_occupancy"] = dict(self._jax.last_occupancy)
        snap["distinct_shapes"] = len(self._jax._bucket_dispatches)
        snap["h_cap"] = self._jax.h_cap
        return snap

    def clear(self, version: int):
        for eng in (self._cpu, self._jax, self._oracle):
            if eng is not None:
                eng.clear(version)
        if self.backend == "hybrid":
            self._authority = "cpu"
        self._history_long_keys = False
