"""ConflictSet: the unified conflict-engine ABI, mirroring
fdbserver/ConflictSet.h (newConflictSet/ConflictBatch::addTransaction/
detectConflicts) with backend dispatch.

Backends:
  "cpu"    - engine_cpu.CpuConflictSet (host, exact, low latency)
  "jax"    - engine_jax.JaxConflictSet (device, whole-batch vectorized)
  "oracle" - oracle.OracleConflictSet (test-only brute force)
  "hybrid" - jax for large batches, cpu for small ones / oversized keys
             (the async-offload + fallback design from BASELINE.json)

Device resilience (device_faults.py): whenever a device engine exists,
the CPU SkipList stays AUTHORITATIVE — every device-served batch's
committed writes are mirrored into it via apply_batch (cheap: merge +
evict only, no re-detection), and a DeviceCircuitBreaker gates every
device attempt.  A batch interrupted by a DeviceFault is re-run on the
CPU engine inside the same _detect call with bit-identical verdicts (the
two engines decide identically by construction); N consecutive faults
open the circuit and route everything host-side; a half-open probe with
deterministic exponential backoff re-attempts the device and, on
success, rehydrates device state from an immutable mirror SNAPSHOT
(ISSUE 9: load_from takes a MirrorSnapshot handoff — host work
proportional to chunks changed since the last device sync, and a fault
mid-probe can neither observe nor corrupt a half-mutated mirror) before
resuming.  No DeviceFault ever escapes detect_conflicts.  A periodic
consistency check (mirror_check, driven by the resolver's mirror-check
actor and `cli mirror-check`) diffs a live mirror snapshot against the
device export and treats confirmed divergence as a device fault that
opens the breaker.

Usage mirrors the reference ABI:
    cs = ConflictSet(backend="hybrid")
    batch = cs.new_batch()
    for tr in txns: batch.add_transaction(tr)
    statuses = batch.detect_conflicts(now, new_oldest_version)
"""

from __future__ import annotations

from contextlib import nullcontext
from typing import List, Optional

from ..flow.hotpath import hot_path
from ..flow.knobs import g_env, g_knobs
from .device_faults import (
    DeviceCircuitBreaker,
    DeviceFault,
    DeviceUnavailable,
)
from .engine_cpu import CpuConflictSet, FlatCpuConflictSet
from .oracle import OracleConflictSet
from .types import TransactionConflictInfo


def _transfer_guard_ctx():
    """Belt-and-braces half of FDB_TPU_TRANSFER_GUARD (ISSUE 20): arm
    jax's own device->host guard over the dispatch call so REAL
    accelerators also catch transfers on values the GuardedDeviceValue
    proxies (flow/hotpath.py) do not wrap.  On the CPU backend jax's
    guard never fires (device buffers alias host memory, zero-copy reads
    are exempt) — the proxies carry the whole load there.  The engine's
    sanctioned sync scopes open matching "allow" islands inside."""
    if not g_env.get("FDB_TPU_TRANSFER_GUARD"):
        return nullcontext()
    import jax

    return jax.transfer_guard_device_to_host("disallow")


class ConflictBatch:
    """Ref: ConflictBatch in fdbserver/ConflictSet.h:32."""

    def __init__(self, cs: "ConflictSet"):
        self._cs = cs
        self._txns: list[TransactionConflictInfo] = []

    def add_transaction(self, tr: TransactionConflictInfo):
        self._txns.append(tr)

    @property
    def transaction_count(self) -> int:
        return len(self._txns)

    def detect_conflicts(self, now: int, new_oldest_version: int) -> List[int]:
        return self._cs._detect(self._txns, now, new_oldest_version)


class InflightBatch:
    """One batch in the double-buffered resolver pipeline (ISSUE 11).

    Created by ConflictSet.pipeline_submit, completed — always in submit
    order — by pipeline_complete_oldest / pipeline_drain or the breaker's
    mid-pipeline mirror replay.  Callers poll `done` / read `statuses`
    after driving completion (the resolver parks its actor on its own
    _ParkedResolve future; bench and tests read the fields directly).
    CPU-served batches come back pre-completed (the pipeline only parks
    device work)."""

    __slots__ = ("txns", "ticket", "now", "new_oldest_version",
                 "statuses", "degraded", "span", "device_span", "witness")

    def __init__(self, txns, ticket, now, new_oldest_version):
        self.txns = txns
        self.ticket = ticket
        self.now = now
        self.new_oldest_version = new_oldest_version
        self.statuses: Optional[List[int]] = None
        self.degraded = False
        # Per-txn abort witness (ISSUE 17): (version, read-range ordinal)
        # or None per txn, set with statuses at completion; [] when
        # witness emission is off.
        self.witness: list = []
        # Span layer (ISSUE 12): the owning batch span (the resolver's
        # resolve_batch, captured off the hub stack at dispatch) and the
        # device in-flight span [dispatch done -> sync returned] whose
        # overlap with its siblings IS the pipeline overlap metric.
        self.span = None
        self.device_span = None

    @classmethod
    def completed(cls, statuses: List[int], degraded: bool = False,
                  witness: Optional[list] = None):
        e = cls(None, None, 0, 0)
        e.statuses = statuses
        e.degraded = degraded
        e.witness = witness if witness is not None else []
        return e

    @property
    def done(self) -> bool:
        return self.statuses is not None

    def _resolve(self, statuses: List[int], degraded: bool,
                 witness: Optional[list] = None) -> None:
        self.statuses = statuses
        self.degraded = degraded
        self.witness = witness if witness is not None else []


def env_h_cap() -> int:
    """FDB_TPU_H_CAP knob value rounded UP to a 256-row multiple (0 when
    unset).  The Pallas kernels tile at powers of two up to 256
    (conflict/kernels._tile, which requires the tile to divide the
    width); an unrounded odd cap would degrade the tile toward 1 and
    turn the fused merge kernel into a per-row sequential grid — a
    practical hang, not an error.  Rounding up keeps the knob's
    'always safe' contract (more rows never truncates)."""
    cap = g_env.get_int("FDB_TPU_H_CAP")
    return -(-cap // 256) * 256 if cap > 0 else 0


def env_coalesce_window() -> int:
    """FDB_TPU_MIRROR_COALESCE as a fold window K (1 = per-batch apply).
    'auto' ties K to the pipeline depth — one mirror fold per full
    pipeline turn, the default the ISSUE-19 coalescing was sized for."""
    raw = g_env.get("FDB_TPU_MIRROR_COALESCE") or "0"
    if raw == "auto":
        return max(1, g_env.get_int("FDB_TPU_PIPELINE_DEPTH"))
    try:
        k = int(raw)
    except ValueError:
        return 1
    return max(1, k)


class ConflictSet:
    def __init__(
        self,
        backend: str = "cpu",
        oldest_version: int = 0,
        key_words: Optional[int] = None,
        device=None,
        bucket_mins: tuple = (8, 8, 8),
        fault_injector=None,
        h_cap: Optional[int] = None,
    ):
        # Device history capacity: explicit arg > FDB_TPU_H_CAP g_env
        # knob > built-in default.  Dropping the knob is always safe —
        # the engine's must-fit guard syncs the true count and grows
        # before any merge could truncate (PERF_NOTES lever 2;
        # tests/test_kernels.py pins the guard).
        if h_cap is None:
            _env_cap = env_h_cap()
            h_cap = _env_cap if _env_cap > 0 else (1 << 16)
        self.backend = backend
        self._cpu: Optional[CpuConflictSet] = None
        self._jax = None
        self._oracle: Optional[OracleConflictSet] = None
        kw = key_words if key_words is not None else g_knobs.server.conflict_device_key_words
        if backend in ("cpu", "jax", "hybrid"):
            # Device backends keep the CPU engine too: it is the
            # authoritative mirror faulted batches fall back to.  The
            # chunked batch-update snapshot engine is the default
            # (ISSUE 9); FDB_TPU_MIRROR_ENGINE=flat selects the
            # pre-ISSUE-9 flat array (A/B arm + escape hatch) — the two
            # are decision- and state-identical by differential gate,
            # but the flat mirror has no snapshot()/chunk identity, so
            # rehydration degrades to the legacy O(H) encode and the
            # consistency check still works off its flat view.
            if g_env.get("FDB_TPU_MIRROR_ENGINE") == "flat":
                self._cpu = FlatCpuConflictSet(oldest_version)
            else:
                # key_words makes the columnar chunks' primary encoding
                # the device width, so chunk_encoding re-encodes nothing.
                self._cpu = CpuConflictSet(oldest_version, key_words=kw)
                self._cpu.coalesce_window = env_coalesce_window()
        if backend == "oracle":
            self._oracle = OracleConflictSet(oldest_version)
        self._breaker: Optional[DeviceCircuitBreaker] = None
        # Double-buffered pipeline (ISSUE 11): batches dispatched to the
        # device and not yet synced, oldest first.  Depth 1 disables the
        # pipelined path entirely (today's synchronous resolve); read at
        # construction like the other engine-variant env flags.
        from collections import deque as _deque

        self.pipeline_depth = max(1, g_env.get_int("FDB_TPU_PIPELINE_DEPTH"))
        self._pipe: "_deque[InflightBatch]" = _deque()
        if backend in ("jax", "hybrid"):
            from .engine_jax import JaxConflictSet  # lazy: jax import is heavy

            self._jax = JaxConflictSet(
                oldest_version=oldest_version,
                key_words=kw,
                device=device,
                bucket_mins=bucket_mins,
                h_cap=h_cap,
            )
            for _c in ("device_faults", "breaker_opens", "breaker_probes",
                       "breaker_closes", "degraded_batches", "rehydrates",
                       "cpu_fallback_txns", "mirror_checks",
                       "mirror_divergence", "mirror_mismatch_keys",
                       "pipeline_dispatches", "pipeline_replayed_batches"):
                self._jax.metrics.counter(_c)  # pre-create: stable snapshots
            self._breaker = DeviceCircuitBreaker(metrics=self._jax.metrics)
            self._jax.fault_injector = fault_injector
        # hybrid: which side served the last device-eligible batch
        self._authority = "cpu" if backend == "hybrid" else backend
        self._key_words = kw
        # True once a long-key write range may have entered CPU history;
        # the device cannot represent it, so authority stays on CPU.
        # NOT permanent (ISSUE 8): once the last long-key write ages out
        # of the MVCC window and no long key remains as a mirror boundary,
        # the pin lifts and the device path resumes (see _device_eligible)
        # — one oversized write must degrade the device for a window, not
        # for the resolver's lifetime (a DynamicCluster's system-keyspace
        # metadata writes would otherwise disable the device forever).
        self._history_long_keys = False
        self._long_key_version = -1  # version of the last long-key write
        # Device state is stale whenever the CPU engine has absorbed a
        # batch the device did not run (small-batch routing, a fault, or
        # simply never having run); the next device attempt rehydrates
        # with load_from first.
        self._device_stale = True
        # Set when the last batch was device-eligible but served by the
        # CPU because of a fault or an open circuit; the resolver consumes
        # it to tag the commit latency path (consume_degraded).
        self._degraded_last = False
        # Hysteresis: consecutive sub-threshold batches seen while device
        # authority is held.  Authority only returns to the CPU after
        # AUTHORITY_HYSTERESIS of them — an alternating big/small workload
        # must not pay a full history transfer per flip (ADVICE r1).
        self._small_streak = 0
        # CPU-fallback throughput measurement: transactions decided by the
        # CPU mirror BECAUSE the device path was degraded (fault or open
        # circuit — by-design CPU routing doesn't count), and the wall
        # seconds those detects took.  Feeds backend_signal() so admission
        # control can contract the TPS limit to what the mirror actually
        # sustains.  The tps estimate uses a sliding WINDOW of recent
        # fallback batches, not a lifetime average — an early warm-history
        # episode must not inflate the cap during a later, slower one.
        # Wall-derived: never enters a deterministic snapshot.
        from collections import deque

        self._cpu_fallback_txns = 0  # cumulative (deterministic counter)
        self._cpu_fallback_recent = deque(maxlen=32)  # (txns, wall_seconds)
        # Last consistency-check report (mirror_check): surfaced through
        # device_metrics()["mirror"] and `cli mirror-check`.
        self._last_mirror_check: Optional[dict] = None
        # Abort-witness provenance (ISSUE 17): whichever engine serves a
        # batch, its per-txn witness lands here (and on the pipeline
        # entry) — degraded and replayed batches report bit-identical
        # provenance because every engine computes the identical rule.
        self._witness = g_env.get("FDB_TPU_WITNESS") not in ("", "0")
        self.last_witness: list = []

    AUTHORITY_HYSTERESIS = 8

    def install_fault_injector(self, injector) -> None:
        """Attach a DeviceFaultInjector to the device engine (chaos
        workloads); no-op for host-only backends."""
        if self._jax is not None:
            self._jax.fault_injector = injector

    def consume_degraded(self) -> bool:
        """True iff the most recent batch was served by the CPU because
        of a device fault or an open breaker; reading resets the flag."""
        was, self._degraded_last = self._degraded_last, False
        return was

    def new_batch(self) -> ConflictBatch:
        return ConflictBatch(self)

    @property
    def oldest_version(self) -> int:
        # The CPU engine, when present, is the authoritative mirror.
        if self._cpu is not None:
            return self._cpu.oldest_version
        return self._engine_for_authority().oldest_version

    def _engine_for_authority(self):
        return {"cpu": self._cpu, "jax": self._jax, "oracle": self._oracle}[
            self._authority
        ]

    def _detect(self, txns, now, new_oldest_version) -> List[int]:
        if self._pipe:
            # A synchronous detect with batches still parked in the
            # pipeline (mixed-driver safety net): the mirror must be
            # current before it can decide or absorb this batch.
            self.pipeline_drain()
        if self.backend == "hybrid":
            return self._detect_hybrid(txns, now, new_oldest_version)
        if self.backend == "jax":
            return self._detect_device(txns, now, new_oldest_version)
        eng = self._engine_for_authority()
        statuses = eng.detect(txns, now, new_oldest_version)
        self.last_witness = self._witness_of(eng)
        return statuses

    def _witness_of(self, engine) -> list:
        """The serving engine's per-txn witness for the batch it just
        decided — the one place the surface reads it, so every serve
        path (device, mirror fallback, replay) reports identically."""
        return list(engine.last_witness) if self._witness else []

    def _device_eligible(self, txns, now: int = 0) -> bool:
        """Every key in the batch fits the device width and no long-key
        write has pinned history host-side."""
        srv = g_knobs.server
        max_key = min(srv.conflict_max_device_key_bytes, self._key_words * 4)
        if (
            self._history_long_keys
            and self._long_key_version < self._cpu.oldest_version
        ):
            # The last long-key write aged out of the MVCC window.  It may
            # STILL survive as a boundary (removeBefore keeps a below-
            # window boundary whose predecessor is hot — it is the right
            # edge of that range), so verify the mirror is clean before
            # lifting the pin: one O(keys) scan at most per window
            # passage, on the detect path — never the metrics sample loop.
            # Belt-and-braces: load_from raises loudly on any long key.
            if all(len(k) <= max_key for k in self._cpu.keys):
                self._history_long_keys = False
            else:
                self._long_key_version = now  # re-check next window
        batch_fits = all(
            len(b) <= max_key and len(e) <= max_key
            for tr in txns
            for (b, e) in tr.read_ranges + tr.write_ranges
        )
        if not batch_fits and any(
            len(b) > max_key or len(e) > max_key
            for tr in txns
            for (b, e) in tr.write_ranges
        ):
            # A long-key write may enter history; until the window flushes
            # it (and the boundary leaves the mirror) the device state
            # cannot represent the step function exactly.
            self._history_long_keys = True
            self._long_key_version = now
        return batch_fits and not self._history_long_keys

    def _device_serve(self, txns, now, new_oldest_version):
        """One device attempt under the breaker.  Returns the statuses, or
        None when the circuit is open or the attempt faulted — the caller
        then serves the batch from the (authoritative) CPU mirror, which
        decides bit-identically, so a fault never changes a verdict.  A
        successful attempt mirrors the committed writes into the CPU
        engine and is the breaker's half-open probe when one is due."""
        from ..flow.spans import begin_span

        if not self._breaker.allows_device():
            self._degraded_last = True
            return None
        snapshot = getattr(self._cpu, "snapshot", None)
        take_fresh = getattr(self._cpu, "take_fresh_chunks", None)
        # Device span on the synchronous path too (dispatch + sync in
        # one detect): depth-1 streams then carry the same span names as
        # the pipelined path, with zero overlap by construction — the
        # before-arm of the overlap-efficiency bench number.
        dspan = begin_span("device", attrs={"version": now})
        try:
            if self._device_stale:
                self._rehydrate_from_mirror(snapshot, take_fresh)
            statuses = self._jax.detect(txns, now, new_oldest_version)
        except DeviceFault as e:
            dspan.end(attrs={"fault": 1})
            self._breaker.on_failure(e)
            self._device_stale = True
            self._degraded_last = True
            return None
        dspan.end()
        self._breaker.on_success()
        with begin_span("apply", attrs={"version": now,
                                        "n_txn": len(txns)}):
            with begin_span("mirror_apply",
                            attrs={"n_txn": len(txns)}) as msp:
                self._cpu.apply_batch(txns, statuses, now, new_oldest_version)
            self._jax._note_host_span(msp)
            if snapshot is not None and not self._coalesce_pending():
                # The device applied the same batch: record the
                # post-batch mirror snapshot as the synced point and
                # pre-encode the chunks this batch created — O(chunks
                # created this batch) via the mirror's take_fresh_chunks
                # hint — so a fault at ANY later batch leaves the probe a
                # cheap diff.  With coalescing on, a queued (unfolded)
                # batch makes snapshot() force the fold — so the synced
                # point is only recorded on fold boundaries, one
                # snapshot round per K batches.
                self._jax.note_synced(
                    snapshot(),
                    take_fresh() if take_fresh is not None else None,
                )
        return statuses

    def _coalesce_pending(self) -> bool:
        """True while the mirror holds queued coalesced batches — the
        windows where recording a synced snapshot would force the fold
        early (snapshot() is a settle barrier)."""
        return getattr(self._cpu, "pending_batches", 0) > 0

    def _rehydrate_from_mirror(self, snapshot, take_fresh) -> None:
        """Rebuild the device history (every boundary newer than
        oldest_version — older ones were evicted) from the mirror, for
        BOTH serve paths (_device_serve and _pipeline_dispatch — one
        implementation so the probe semantics can never drift).
        Snapshot handoff (ISSUE 9): the immutable MirrorSnapshot means a
        fault mid-probe can neither observe nor corrupt a half-mutated
        mirror, and the chunk encode cache makes the host work
        proportional to chunks changed since the last device sync
        (asserted via rehydrate_keys_encoded telemetry).  load_from can
        itself fault (grow) — a fault here fails the probe (the caller's
        except block handles it)."""
        from ..flow.spans import begin_span

        with begin_span("rehydrate"):
            self._jax.load_from(
                snapshot() if snapshot is not None else self._cpu
            )
        if take_fresh is not None:
            # load_from just encoded every live chunk; the fresh backlog
            # from the degraded window is now moot.
            take_fresh()
        self._breaker.note_rehydrate()
        self._device_stale = False

    def _cpu_detect_fallback(self, txns, now, new_oldest_version):
        """CPU-mirror detect for a DEGRADED device-eligible batch, timed on
        the wall clock so backend_signal() can report the mirror's real
        throughput (wall namespace only — see flow/metrics.py
        record_wall; the deterministic counter tracks txn counts)."""
        from ..flow.metrics import wall_now

        t0 = wall_now()
        statuses = self._cpu.detect(txns, now, new_oldest_version)
        self._cpu_fallback_txns += len(txns)
        self._cpu_fallback_recent.append((len(txns), wall_now() - t0))
        if self._jax is not None:
            self._jax.metrics.counter("cpu_fallback_txns").add(len(txns))
        return statuses

    def _detect_device(self, txns, now, new_oldest_version) -> List[int]:
        """backend="jax": every batch is device-eligible (modulo key
        width); the CPU mirror absorbs faults and open-circuit windows."""
        if self._device_eligible(txns, now):
            statuses = self._device_serve(txns, now, new_oldest_version)
            if statuses is not None:
                self.last_witness = self._witness_of(self._jax)
                return statuses
            self._device_stale = True
            statuses = self._cpu_detect_fallback(txns, now, new_oldest_version)
            self.last_witness = self._witness_of(self._cpu)
            return statuses
        self._device_stale = True
        statuses = self._cpu.detect(txns, now, new_oldest_version)
        self.last_witness = self._witness_of(self._cpu)
        return statuses

    def _hybrid_wants_device(self, txns, now) -> bool:
        """Hybrid routing decision (+ its hysteresis state updates),
        shared by the synchronous path and the pipelined path so the two
        can never drift: True iff a device serve is due for this batch.
        While device authority is held, sub-threshold batches still run
        on device (dispatch on a warm small bucket beats a full history
        transfer); only a sustained small streak flips authority back.
        When this returns False the caller flips authority host-side and
        marks the device stale (the CPU engine absorbs the batch)."""
        big = len(txns) >= g_knobs.server.conflict_device_min_batch
        if not self._device_eligible(txns, now):
            return False
        if self._authority == "jax":
            self._small_streak = 0 if big else self._small_streak + 1
            return self._small_streak < self.AUTHORITY_HYSTERESIS
        if big:
            self._authority = "jax"
            self._small_streak = 0
            return True
        return False

    def _detect_hybrid(self, txns, now, new_oldest_version) -> List[int]:
        attempted = self._hybrid_wants_device(txns, now)
        if attempted:
            statuses = self._device_serve(txns, now, new_oldest_version)
            if statuses is not None:
                self.last_witness = self._witness_of(self._jax)
                return statuses
        if self._authority == "jax":
            # Flip back host-side.  No store_to needed: the mirror already
            # holds exactly the state the device would export.
            self._authority = "cpu"
            self._small_streak = 0
        self._device_stale = True
        if attempted:
            # Degraded serve (not by-design small-batch routing): measure
            # the mirror's throughput for admission control.
            statuses = self._cpu_detect_fallback(txns, now, new_oldest_version)
        else:
            statuses = self._cpu.detect(txns, now, new_oldest_version)
        self.last_witness = self._witness_of(self._cpu)
        return statuses

    # -- double-buffered pipeline (ISSUE 11) ------------------------------
    @property
    def pipeline_inflight(self) -> int:
        """Batches dispatched to the device and not yet synced."""
        return len(self._pipe)

    def pipeline_submit(self, txns, now, new_oldest_version) -> InflightBatch:
        """Admit one batch into the double-buffered pipeline.

        Device-routed batches are packed + dispatched WITHOUT syncing and
        come back as a parked InflightBatch; the caller must complete
        oldest entries (pipeline_complete_oldest) until pipeline_inflight
        is back under its depth bound, and eventually drain the tail.
        CPU-routed batches (host-only backend, hybrid small-batch
        routing, ineligible keys, open circuit, or a dispatch fault)
        first drain the pipeline — the mirror must be current before it
        decides — and return pre-completed.  Routing and hysteresis
        decisions are the exact ones the synchronous path makes
        (_hybrid_wants_device / _device_eligible), so verdict streams are
        bit-identical across depths."""
        wants_device = False
        if self._jax is not None and self.pipeline_depth > 1:
            if self.backend == "jax":
                wants_device = self._device_eligible(txns, now)
            elif self.backend == "hybrid":
                wants_device = self._hybrid_wants_device(txns, now)
        if wants_device:
            entry = self._pipeline_dispatch(txns, now, new_oldest_version)
            if entry is not None:
                return entry
            # A device serve was due but the circuit is open or the
            # dispatch faulted (in-flight batches are already replayed on
            # the mirror): degraded CPU serve, measured for admission
            # control — the synchronous path's exact fallback.
            if self.backend == "hybrid" and self._authority == "jax":
                self._authority = "cpu"
                self._small_streak = 0
            self._device_stale = True
            statuses = self._cpu_detect_fallback(
                txns, now, new_oldest_version
            )
            self.last_witness = self._witness_of(self._cpu)
            self.consume_degraded()  # folded into the entry's flag
            return InflightBatch.completed(
                statuses, degraded=True, witness=self.last_witness
            )
        if self._jax is not None and self.pipeline_depth > 1:
            # Routing above chose the CPU (ineligible keys or hybrid
            # small-batch): do the sync path's post-routing bookkeeping
            # directly — going back through _detect would re-run routing
            # and advance the hysteresis state twice for one batch.  The
            # mirror must be current before it decides, hence the drain.
            self.pipeline_drain()
            if self.backend == "hybrid" and self._authority == "jax":
                self._authority = "cpu"
                self._small_streak = 0
            self._device_stale = True
            statuses = self._cpu.detect(txns, now, new_oldest_version)
            self.last_witness = self._witness_of(self._cpu)
            return InflightBatch.completed(
                statuses, degraded=self.consume_degraded(),
                witness=self.last_witness,
            )
        # Depth 1 or host-only backend: the synchronous path decides,
        # against a drained (current) mirror.
        statuses = self._detect(txns, now, new_oldest_version)
        return InflightBatch.completed(
            statuses, degraded=self.consume_degraded(),
            witness=self.last_witness,
        )

    @hot_path(bound="batch")
    def _pipeline_dispatch(
        self, txns, now, new_oldest_version
    ) -> Optional[InflightBatch]:
        """One device dispatch under the breaker WITHOUT syncing — the
        pipelined twin of _device_serve.  Returns the parked entry, or
        None when the circuit is open or the dispatch faulted (the
        in-flight tail is then already replayed on the mirror).  Injected
        faults raise at the dispatch choke points BEFORE any device or
        host state mutates, so the mirror replay decides every in-flight
        batch against exactly the history it must be decided against."""
        if not self._breaker.allows_device():
            # An open circuit implies the opening fault already drained
            # the pipeline; nothing can be parked here.
            self._degraded_last = True
            return None
        snapshot = getattr(self._cpu, "snapshot", None)
        take_fresh = getattr(self._cpu, "take_fresh_chunks", None)
        try:
            if self._device_stale:
                # Rehydration needs the mirror current: a stale device
                # means the mirror served the preceding batches, so the
                # pipeline is empty (faults drain it; CPU routing drains
                # before deciding).
                assert not self._pipe, "rehydrating around parked batches"
                self._rehydrate_from_mirror(snapshot, take_fresh)
            with _transfer_guard_ctx():
                ticket = self._jax.dispatch_txns(txns, now, new_oldest_version)
        except DeviceFault as e:
            self._breaker.on_failure(e)
            self._device_stale = True
            self._degraded_last = True
            self._pipeline_replay_on_mirror()
            return None
        # NOTE: breaker.on_success is deferred to the SYNC
        # (pipeline_complete_oldest) — on real hardware async failures
        # surface at the readback, and crediting a success at dispatch
        # would reset consecutive_failures before the batch is verified,
        # keeping the circuit from ever opening on a sync-faulting device.
        self._jax.metrics.counter("pipeline_dispatches").add()
        entry = InflightBatch(txns, ticket, now, new_oldest_version)
        # Span layer (ISSUE 12): remember the owning batch span (the
        # resolver pushed it for this synchronous submit) so the deferred
        # completion's sync/apply spans parent correctly, and open the
        # device in-flight span — it closes at sync_ticket, so two of
        # these overlapping on one resolver is the pipeline overlap the
        # efficiency gauge measures.
        from ..flow.spans import begin_span, current_span

        entry.span = current_span()
        entry.device_span = begin_span("device", attrs={"version": now})
        self._pipe.append(entry)
        return entry

    @hot_path(bound="batch")
    def pipeline_complete_oldest(self) -> None:
        """Sync + retire the OLDEST in-flight batch: block until its
        device statuses are ready (later dispatches keep the device
        busy behind it), apply its committed writes to the authoritative
        mirror, and record the post-batch snapshot as the synced point
        for cheap probe rehydration.  A fault surfacing at the sync (a
        real async XLA failure) or a fixpoint divergence drains the
        WHOLE pipeline onto the mirror instead — bit-identical verdicts
        either way, device marked stale for the next submit."""
        from ..flow.spans import begin_span

        entry = self._pipe[0]
        # Sync span under the owning batch span; the device in-flight
        # span (open since dispatch) closes when the sync returns — on
        # every path, so a fault can't leak an open span.
        sspan = begin_span("sync", parent=entry.span,
                           attrs={"version": entry.now})
        try:
            statuses, diverged = self._jax.sync_ticket(entry.ticket)
        except DeviceFault as e:
            sspan.end(attrs={"error": type(e).__name__})
            if entry.device_span is not None:
                entry.device_span.end(attrs={"fault": 1})
            self._breaker.on_failure(e)
            self._device_stale = True
            self._degraded_last = True
            self._pipeline_replay_on_mirror()
            return
        except Exception as e:  # real async XLA failure at the sync point
            import jax as _jax_mod

            if not isinstance(e, _jax_mod.errors.JaxRuntimeError):
                raise  # a Python bug must crash loudly, not degrade
            # site="sync": keep readback-time failures distinguishable
            # from dispatch-time ones in the breaker's fault counters
            # and transition reasons (incident triage).
            sspan.end(attrs={"error": "JaxRuntimeError"})
            if entry.device_span is not None:
                entry.device_span.end(attrs={"fault": 1})
            fault = DeviceUnavailable(f"sync: {e}", site="sync")
            self._breaker.on_failure(fault)
            self._device_stale = True
            self._degraded_last = True
            self._pipeline_replay_on_mirror()
            return
        sspan.end()
        if entry.device_span is not None:
            entry.device_span.end(attrs={"diverged": 1} if diverged else None)
        if diverged:
            # The fixpoint left this batch undecided: detect_core left
            # the device history UNCHANGED for it, so every later
            # dispatch decided against stale history.  The mirror —
            # current through the previous completion — re-decides this
            # batch and the parked tail bit-identically; the next device
            # submit rehydrates from the mirror snapshot.  Like the sync
            # path's _fallback_cpu: no breaker involvement (the device
            # answered, just not decisively) and NOT a degraded serve —
            # depth 1 resolves the same batch as a normal success, and
            # the reply's degraded tag must not depend on depth.
            self._device_stale = True
            self._pipeline_replay_on_mirror(degraded=False)
            return
        # The batch's verdicts are real only now: credit the breaker at
        # the verified sync, never at dispatch (see _pipeline_dispatch).
        self._breaker.on_success()
        self._pipe.popleft()
        statuses_list = [int(s) for s in statuses[: len(entry.txns)]]
        # Mirror apply span (ISSUE 12): the host phase the pipeline hides
        # under a successor's device compute — its seq interval lands
        # inside the successor's device span, which is exactly the
        # "overlapping dispatch/apply sibling spans" the timeline shows.
        with begin_span("apply", parent=entry.span,
                        attrs={"version": entry.now,
                               "n_txn": len(entry.txns)}):
            with begin_span("mirror_apply",
                            attrs={"n_txn": len(entry.txns)}) as msp:
                self._cpu.apply_batch(
                    entry.txns, statuses_list, entry.now,
                    entry.new_oldest_version,
                )
            self._jax._note_host_span(msp)
            snapshot = getattr(self._cpu, "snapshot", None)
            take_fresh = getattr(self._cpu, "take_fresh_chunks", None)
            if snapshot is not None and not self._coalesce_pending():
                self._jax.note_synced(
                    snapshot(),
                    take_fresh() if take_fresh is not None else None,
                )
        self.last_witness = self._witness_of(self._jax)
        entry._resolve(statuses_list, degraded=False,
                       witness=self.last_witness)

    def _pipeline_replay_on_mirror(self, degraded: bool = True) -> None:
        """Drain every in-flight batch onto the authoritative mirror, in
        order (the breaker's mid-pipeline fault path).  The mirror is
        current through the last completed batch and the engines decide
        identically by construction, so the replay is exact — the same
        guarantee the synchronous fault path gives one batch, extended
        to the parked tail.  `degraded` tags the entries' replies: True
        for fault-driven replays (the sync path's degraded fallback),
        False for fixpoint divergence (depth 1 serves that batch as a
        normal success, and the reply's degraded tag must not depend on
        depth)."""
        while self._pipe:
            entry = self._pipe.popleft()
            if entry.device_span is not None:
                # The parked batch never reached its sync: close the
                # in-flight span on the replay path too.
                entry.device_span.end(attrs={"replayed": 1})
            if self._jax is not None:
                self._jax.metrics.counter("pipeline_replayed_batches").add()
            if degraded:
                statuses = self._cpu_detect_fallback(
                    entry.txns, entry.now, entry.new_oldest_version
                )
            else:
                # Divergence replay: a by-design CPU re-decide, not a
                # degraded serve — keep it out of the admission-control
                # fallback window (cpu_mirror_tps honesty: the depth-1
                # path's _fallback_cpu records neither).
                statuses = self._cpu.detect(
                    entry.txns, entry.now, entry.new_oldest_version
                )
            self.last_witness = self._witness_of(self._cpu)
            entry._resolve(statuses, degraded=degraded,
                           witness=self.last_witness)
        self._degraded_last = False  # per-entry flags carry it instead

    def pipeline_drain(self) -> None:
        """Complete every in-flight batch (idle flush / pre-CPU-serve
        barrier / teardown)."""
        while self._pipe:
            self.pipeline_complete_oldest()

    @property
    def host_phase_seq(self) -> int:
        """Cumulative span-seq extent spent in host phases (encode +
        mirror_apply + readback) — deterministic (hub sequence numbers,
        never wall), so the resolver's derived host_fraction gauge is
        byte-identical per seed.  0 for host-only backends."""
        return self._jax.host_phase_seq if self._jax is not None else 0

    def backend_signal(self) -> dict:
        """O(1) admission-control probe (ISSUE 8 satellite): the PR-3
        breaker's backend_state plus measured CPU-fallback throughput —
        NO per-row host work and no histogram snapshotting (contrast
        device_metrics(), which walks every instrument; this follows the
        boundary_count_bound discipline and is safe on every ratekeeper
        sample).  cpu_mirror_tps is wall-clock-derived (0.0 = nothing
        measured yet) and MUST NOT feed deterministic decisions in sim —
        the ratekeeper only consults it under
        ratekeeper_use_measured_cpu_tps."""
        state = self._breaker.state if self._breaker is not None else "ok"
        tps = 0.0
        wall = sum(w for _n, w in self._cpu_fallback_recent)
        if wall > 0.0:
            tps = sum(n for n, _w in self._cpu_fallback_recent) / wall
        return {
            "backend_state": state,
            "cpu_mirror_tps": tps,
            "cpu_fallback_txns": self._cpu_fallback_txns,
            "mirror_divergence": (
                int(self._jax.metrics.counter("mirror_divergence").value)
                if self._jax is not None
                else 0
            ),
        }

    def mirror_check(self) -> Optional[dict]:
        """Consistency check (ISSUE 9): diff a live mirror snapshot
        against the device's exported state without stopping the
        resolver.  Returns None for host-only backends; otherwise a
        report dict ({status: ok|diverged|skipped, ...}).  Confirmed
        divergence is treated as a device fault: counted, traced, and the
        breaker OPENS (the mirror stays authoritative, the device is
        marked stale so recovery rehydrates from a snapshot) — today
        divergence outside the fixpoint check would be silently
        authoritative-by-fiat.  Cost: O(H) host decode of the device
        export, which is why it runs on a period (the resolver's
        mirror-check actor / `cli mirror-check`), never per batch."""
        if self._jax is None:
            return None
        m = self._jax.metrics
        if self._pipe:
            # Batches parked in the pipeline: the mirror is legitimately
            # behind the device by exactly those batches' host applies —
            # nothing to confirm until they complete.  O(1).  Direct
            # callers (cli mirror-check) may hit this under load; the
            # resolver's periodic check actor drains the pipeline first,
            # so the guarantee-bearing path never starves.
            report = {"status": "skipped", "reason": "pipeline_inflight"}
            self._last_mirror_check = report
            return report
        if self._device_stale or (
            self._breaker is not None and self._breaker.state != "ok"
        ):
            # The device is not expected to match the mirror right now
            # (never hydrated, mid-outage, or mid-backoff): nothing to
            # confirm.  O(1) — safe on every period even while degraded.
            report = {
                "status": "skipped",
                "reason": (
                    "device_stale"
                    if self._device_stale
                    else f"breaker_{self._breaker.state}"
                ),
            }
            self._last_mirror_check = report
            return report
        m.counter("mirror_checks").add()
        snap = getattr(self._cpu, "snapshot", None)
        if snap is not None:
            s = snap()
            mk, mv = s.to_flat()
            stamp = s.stamp
            m_oldest = s.oldest_version
        else:  # flat mirror (FDB_TPU_MIRROR_ENGINE=flat): live flat view
            mk, mv = list(self._cpu.keys), list(self._cpu.vers)
            stamp = None
            m_oldest = self._cpu.oldest_version
        dk, dv = self._jax._merged_host_state()
        d_oldest = self._jax.oldest_version
        mismatch = 0
        if m_oldest != d_oldest:
            mismatch += 1
        if mk != dk or mv != dv:
            mirror = dict(zip(mk, mv))
            device = dict(zip(dk, dv))
            for key in mirror.keys() | device.keys():
                if mirror.get(key) != device.get(key):
                    mismatch += 1
        report = {
            "status": "ok" if mismatch == 0 else "diverged",
            "boundaries": len(mk),
            "device_boundaries": len(dk),
            "mismatch_keys": mismatch,
            "stamp": stamp,
        }
        if mismatch:
            from ..flow.trace import TraceEvent

            m.counter("mirror_divergence").add()
            m.counter("mirror_mismatch_keys").add(mismatch)
            TraceEvent("MirrorDivergence", severity=40).detail(
                "mismatch_keys", mismatch
            ).detail("mirror_boundaries", len(mk)).detail(
                "device_boundaries", len(dk)
            ).detail("mirror_oldest", m_oldest).detail(
                "device_oldest", d_oldest
            ).log()
            if self._breaker is not None:
                self._breaker.on_divergence(f"mismatch_keys={mismatch}")
            # Flight-recorder trigger (ISSUE 10): divergence is corrupt
            # state — freeze the telemetry window that led here.  After
            # on_divergence, so the artifact's transition log contains
            # the breaker-open transition this divergence caused.
            from ..flow.flight_recorder import maybe_trigger

            breaker = self._breaker
            maybe_trigger(
                "mirror_divergence",
                detail={"mismatch_keys": mismatch,
                        "mirror_boundaries": len(mk),
                        "device_boundaries": len(dk)},
                # Thunk: copied only if the cooldown admits the capture.
                transitions=(
                    (lambda: [list(t) for t in breaker.transitions])
                    if breaker is not None
                    else None
                ),
                # Per-breaker cooldown, not global (construction-order
                # id: deterministic, never address-reused).
                source=(
                    breaker.breaker_id if breaker is not None else None
                ),
            )
            # The mirror is authoritative by design; the device state is
            # now suspect — force a snapshot rehydration before it serves
            # again (after the breaker's backoff walks to a probe).
            self._device_stale = True
            self._degraded_last = True
        self._last_mirror_check = report
        return report

    def device_metrics(self, now=None) -> Optional[dict]:
        """Kernel-telemetry snapshot of the device engine (retraces,
        padding occupancy, fixpoint rounds, grow/rebase — see
        engine_jax.JaxConflictSet.metrics) plus the degraded-mode state
        machine (backend_state: ok|degraded|probing, and the replayable
        breaker transition log), or None for host-only backends.  Feeds
        the status doc's tpu section and `cli metrics`."""
        if self._jax is None:
            return None
        snap = self._jax.metrics.snapshot(now=now)
        snap["last_occupancy"] = dict(self._jax.last_occupancy)
        snap["distinct_shapes"] = len(self._jax._bucket_dispatches)
        snap["h_cap"] = self._jax.h_cap
        if getattr(self._jax, "_use_kernels", False):
            # Pallas kernel routing (ISSUE 14) — key present only when
            # on, so kernel-off snapshots stay byte-identical to
            # pre-kernel builds.
            snap["kernels"] = {
                "enabled": True,
                "interpret": bool(self._jax._kernel_interpret),
            }
        if getattr(self._jax, "tiered", False):
            # Tier sizes/occupancy (ISSUE 4): delta fill and compaction
            # counts also live in the counters/gauges/histograms above
            # (major_compactions, base_boundaries, delta_boundaries,
            # delta_occupancy); this block carries the host-side shape
            # facts a snapshot can't derive.
            snap["tiers"] = {
                "mode": "tiered",
                "d_cap": self._jax.d_cap,
                "compact_every": self._jax.compact_every,
                "batches_since_major": self._jax._batches_since_major,
                "delta_bound": self._jax._dcount_bound,
            }
        if self._breaker is not None:
            snap["backend_state"] = self._breaker.state
            snap["breaker"] = self._breaker.snapshot()
        # Pipeline facts (ISSUE 11): configured depth + current in-flight
        # occupancy.  O(1) reads.
        snap["pipeline"] = {
            "depth": self.pipeline_depth,
            "inflight": len(self._pipe),
        }
        # Snapshot-mirror block (ISSUE 9): chunked-engine maintenance
        # facts + the last consistency-check report.  All O(1) reads.
        mirror: dict = {
            "engine": type(self._cpu).__name__,
            "last_check": self._last_mirror_check,
        }
        if hasattr(self._cpu, "chunk_count"):
            mirror.update(
                chunks=self._cpu.chunk_count,
                boundary_count=self._cpu.boundary_count,
                stamp=self._cpu.stamp,
                chunks_rebuilt=self._cpu.chunks_rebuilt,
                evict_scans=self._cpu.evict_scans,
                evict_skips=self._cpu.evict_skips,
            )
        snap["mirror"] = mirror
        # Device program cost accounting (ISSUE 10): one block per
        # DEVICE_ENTRY_POINTS entry — carried-buffer bytes, temp/output
        # allocation, FLOPs per batch (engine_jax.program_cost_table).
        # Compiling every program costs ~15s, so the block is included
        # eagerly only under FDB_TPU_PROGRAM_COSTS; otherwise it appears
        # once some surface (perf_experiments --programs, the perf_smoke
        # gate) has computed the cached table.
        from .engine_jax import cached_program_costs, program_cost_table

        if g_env.get("FDB_TPU_PROGRAM_COSTS") not in ("", "0"):
            snap["programs"] = program_cost_table()
        else:
            progs = cached_program_costs()
            if progs is not None:
                snap["programs"] = progs
        return snap

    def clear(self, version: int):
        self.pipeline_drain()  # parked verdicts must land before the wipe
        for eng in (self._cpu, self._jax, self._oracle):
            if eng is not None:
                eng.clear(version)
        if self.backend == "hybrid":
            self._authority = "cpu"
        self._history_long_keys = False
        self._long_key_version = -1
        # Cleared engines agree, but rehydrating from the (tiny) cleared
        # mirror is cheap and keeps one invariant: any CPU-side write the
        # device missed forces a load_from.  Breaker state is NOT reset —
        # clearing data says nothing about device health.
        self._device_stale = True
