"""Device-fault injection + the degraded-mode circuit breaker.

The reference's simulator owns every failure a disk or network can
produce (fdbrpc/simulator.h ISimulator: killProcess :148, clogPair :264)
and the code under test must degrade and recover; a run is replayable
from its seed.  The device path needs the same discipline: XLA dispatch,
jit compile, and history growth can all fail on real hardware
(preemption, OOM, driver resets), and the conflict engine — the
availability-critical serialization point ("The Transactional Conflict
Problem", PAPERS.md) — must keep answering with bit-identical verdicts.

Two pieces:

``DeviceFaultInjector``
    makes ``JaxConflictSet`` raise realistic failures at its three choke
    points — dispatch (``DeviceUnavailable``), compile/retrace
    (``CompileFailed``), ``_grow``/rebase (``DeviceOOM``) — from either a
    scripted plan (tests) or BUGGIFY sites driven by the sim RNG (chaos
    workloads).  Transient faults fire once; persistent faults hold a
    site down for a drawn number of checks (or until ``end_outage``).
    Every decision comes from ``DeterministicRandom``, so a run's fault
    schedule replays from its seed, and ``injected`` logs it.

    Shard targeting (ISSUE 15): every plan/check accepts an optional
    ``shard`` index, scoping the fault to ONE chip of a mesh-sharded
    resolver (``parallel.sharded_resolver.ShardedJaxConflictSet`` checks
    each choke point per shard).  Shard-scoped sites keep their own check
    counters, their own BUGGIFY site names (``device_fault_<site>_s<k>``,
    so per-shard fault coverage shows in the buggify report), and their
    own persistence draws from a ``DeterministicRandom`` forked per shard
    — one shard's draw never perturbs another's schedule, and replays
    stay byte-identical.  ``shard=None`` keeps the exact pre-ISSUE-15
    behavior (the single-device engine's un-scoped sites).

``DeviceCircuitBreaker``
    the degraded-mode state machine ``ConflictSet`` consults around every
    device attempt::

        ok ──(threshold consecutive faults)──> degraded
        degraded ──(backoff device-eligible batches elapse)──> probing
        probing ──(attempt succeeds)──> ok        (backoff resets)
        probing ──(attempt faults)──> degraded    (backoff doubles)

    While not ``ok``, batches are served by the CPU SkipList mirror —
    which stays authoritative at all times, so verdicts never depend on
    device health.  Transitions are counted in the engine's
    MetricsRegistry and appended to a replayable ``transitions`` log
    (same seed => byte-identical), surfaced through
    ``ConflictSet.device_metrics()`` and the status doc's ``tpu``
    section as ``backend_state``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple


class DeviceFault(Exception):
    """Base of every injectable device failure; `site` names the choke
    point that raised (dispatch/compile/grow/rebase)."""

    transient = True

    def __init__(self, message: str = "", site: str = ""):
        super().__init__(message or site)
        self.site = site


class DeviceUnavailable(DeviceFault):
    """XLA dispatch failed (device preempted/reset mid-stream)."""


class CompileFailed(DeviceFault):
    """jit trace/compile of a new static shape failed."""


class DeviceOOM(DeviceFault):
    """Device allocation failed growing or rebasing the history state."""

    transient = False


SITES = ("dispatch", "compile", "grow", "rebase", "reshard")

_SITE_FAULT = {
    "dispatch": DeviceUnavailable,
    "compile": CompileFailed,
    "grow": DeviceOOM,
    "rebase": DeviceOOM,
    # Live split-point migration (ISSUE 18): a fault at the reshard site
    # models the device going away mid-handoff.  The move defers (the old
    # partition stays whole — the snapshot cut is immutable, so nothing
    # is torn) and the shard's breaker counts the failure.
    "reshard": DeviceUnavailable,
}


class DeviceFaultInjector:
    """Deterministic fault source for the JAX engine's choke points.

    Random mode (chaos): each ``check(site)`` consults the BUGGIFY site
    ``device_fault_<site>`` at ``fire_probability`` — so fault-site
    coverage shows up in the buggify coverage report — and on fire draws
    transient-vs-persistent from the injector's own
    ``DeterministicRandom`` (fork the loop rng with ``rng.split()`` so
    the schedule is replayable without perturbing other sim decisions
    mid-batch).

    Scripted mode (tests): ``script(site, at=n, persist=k)`` faults the
    n-th check of a site (1-based) and holds it down for k checks;
    ``begin_outage``/``end_outage`` model an open-ended device loss.

    ``injected`` records every raised fault as ``[seq, site, kind]`` —
    the replay log the differential gate compares across same-seed runs.
    """

    def __init__(
        self,
        rng=None,
        fire_probability: float = 0.0,
        persistent_probability: float = 0.25,
        max_persistent: int = 4,
    ):
        self.rng = rng
        self.fire_probability = fire_probability
        self.persistent_probability = persistent_probability
        self.max_persistent = max_persistent
        self.checks: Dict[str, int] = {s: 0 for s in SITES}
        self.injected: List[list] = []  # [seq, site_key, kind]
        self._seq = 0
        self._outage: Dict[str, Optional[int]] = {}  # site -> remaining (None = open-ended)
        self._scripted: Dict[str, Dict[int, int]] = {}  # site -> {at: persist}
        # Per-shard persistence rngs, forked from self.rng at first touch
        # of each shard (check order is deterministic in sim, so lazy
        # forking replays byte-identically).
        self._shard_rngs: Dict[int, object] = {}

    @staticmethod
    def _site_key(site: str, shard) -> str:
        assert site in SITES, site
        return site if shard is None else f"{site}#s{int(shard)}"

    def _rng_for(self, shard):
        if shard is None or self.rng is None:
            return self.rng
        r = self._shard_rngs.get(int(shard))
        if r is None:
            r = self._shard_rngs[int(shard)] = self.rng.split()
        return r

    # -- plans --
    def script(self, site: str, at: int, persist: int = 1,
               shard=None) -> None:
        """Fault the `at`-th check of `site` (1-based; per-shard counter
        when `shard` is given) and keep the site down for `persist`
        consecutive checks."""
        key = self._site_key(site, shard)
        assert at > self.checks.get(key, 0), "cannot script the past"
        self._scripted.setdefault(key, {})[at] = persist

    def begin_outage(self, site: str, shard=None) -> None:
        """Hold `site` (on one shard when given) down until end_outage (a
        persistent device/chip loss)."""
        self._outage[self._site_key(site, shard)] = None

    def end_outage(self, site: str, shard=None) -> None:
        self._outage.pop(self._site_key(site, shard), None)

    # -- the choke-point hook --
    def check(self, site: str, shard=None) -> None:
        """Called by the engine before mutating state at `site` (scoped to
        one shard of a mesh-sharded engine when `shard` is given); raises
        the site's fault type when the plan says so."""
        key = self._site_key(site, shard)
        self._seq += 1
        n = self.checks[key] = self.checks.get(key, 0) + 1
        kind = None
        # Scripted entries are consumed at their check number even when an
        # outage/persistence window already covers it — overlapping plans
        # EXTEND the window (max-merge), they never silently vanish.
        persist = self._scripted.get(key, {}).pop(n, None)
        remaining = self._outage.get(key, 0)
        if key in self._outage:
            if remaining is None:
                kind = "outage"
            else:
                self._outage[key] = remaining - 1
                if self._outage[key] == 0:
                    del self._outage[key]
                kind = "persistent"
        if persist is not None:
            if persist > 1:
                tail = self._outage.get(key, 0)
                if key in self._outage and tail is None:
                    pass  # open-ended outage already covers everything
                else:
                    self._outage[key] = max(tail, persist - 1)
            if kind is None:
                kind = "persistent" if persist > 1 else "transient"
        if kind is None and self.fire_probability > 0:
            from ..flow.buggify import buggify_with_prob

            suffix = "" if shard is None else f"_s{int(shard)}"
            if buggify_with_prob(
                f"device_fault_{site}{suffix}", self.fire_probability
            ):
                kind = "transient"
                rng = self._rng_for(shard)
                if (
                    rng is not None
                    and rng.random01() < self.persistent_probability
                ):
                    self._outage[key] = int(
                        rng.random_int(1, self.max_persistent)
                    )
                    kind = "persistent"
        if kind is not None:
            self.injected.append([self._seq, key, kind])
            raise _SITE_FAULT[site](f"injected {kind} fault", site=site)


# Breaker states (the status doc's backend_state values).
STATE_OK = "ok"
STATE_DEGRADED = "degraded"
STATE_PROBING = "probing"

_STATE_GAUGE = {STATE_OK: 0, STATE_DEGRADED: 1, STATE_PROBING: 2}


import itertools

# Construction-order ids (deterministic under the sim, unlike id()):
# the flight-recorder cooldown key for concurrent distinct breakers.
_BREAKER_SEQ = itertools.count()


class DeviceCircuitBreaker:
    """Consecutive-failure circuit breaker with deterministic exponential
    backoff, counted in device-eligible batches (the only clock every
    replay of a run agrees on)."""

    def __init__(
        self,
        metrics=None,
        threshold: int = 3,
        backoff_batches: int = 2,
        backoff_cap: int = 64,
        label: str = "",
        counter_prefix: str = "",
    ):
        self.breaker_id = next(_BREAKER_SEQ)
        self.metrics = metrics
        self.threshold = threshold
        self.initial_backoff = backoff_batches
        self.backoff_cap = backoff_cap
        # Shard-granular fault domains (ISSUE 15): `label` names this
        # breaker's domain (e.g. "shard3") in traces/spans/flight-recorder
        # details, `counter_prefix` namespaces its counters/gauge inside a
        # shared registry (e.g. "shard3_breaker_opens").  Both default
        # empty so single-device snapshots stay byte-identical.
        self.label = label
        self._prefix = counter_prefix
        self.state = STATE_OK
        self.consecutive_failures = 0
        self.backoff = backoff_batches
        self._cooldown = 0  # device-eligible batches until the next probe
        self.seq = 0  # device-eligible batches observed
        self.transitions: List[list] = []  # [seq, from, to, reason]
        if metrics is not None:
            metrics.gauge(f"{counter_prefix}backend_state").set(
                _STATE_GAUGE[self.state]
            )

    # -- queries --
    def allows_device(self) -> bool:
        """Gate one device-eligible batch; advances the backoff clock and
        enters `probing` when it elapses.  Call at most once per batch."""
        self.seq += 1
        if self.state == STATE_DEGRADED:
            self._cooldown -= 1
            if self._cooldown > 0:
                self._count("degraded_batches")
                return False
            self._transition(STATE_PROBING, "backoff_elapsed")
            self._count("breaker_probes")
        return True

    # -- outcomes --
    def on_success(self) -> None:
        self.consecutive_failures = 0
        if self.state != STATE_OK:
            self._transition(STATE_OK, "probe_success")
            self._count("breaker_closes")
            self.backoff = self.initial_backoff

    def on_failure(self, fault: DeviceFault) -> None:
        self.consecutive_failures += 1
        self._count("device_faults")
        self._count(f"faults_{fault.site or 'unknown'}")
        reason = f"{type(fault).__name__}:{fault.site or 'unknown'}"
        if self.state == STATE_PROBING:
            self.backoff = min(self.backoff * 2, self.backoff_cap)
            self._cooldown = self.backoff
            self._transition(STATE_DEGRADED, f"probe_failed:{reason}")
        elif (
            self.state == STATE_OK
            and self.consecutive_failures >= self.threshold
        ):
            self._cooldown = self.backoff
            self._transition(STATE_DEGRADED, f"threshold:{reason}")
            self._count("breaker_opens")

    def on_divergence(self, detail: str) -> None:
        """Confirmed mirror/device divergence (the consistency checker's
        verdict, ISSUE 9): treated as a device fault that opens the
        circuit IMMEDIATELY — no consecutive-failure threshold, because
        divergence is corrupt state, never a transient blip.  The caller
        marks the device stale, so the eventual half-open probe
        rehydrates from a mirror snapshot before the device serves
        again.  Only meaningful from `ok` (the checker skips while the
        device is stale or the circuit is already open)."""
        self._count("device_faults")
        self._count("faults_mirror")
        if self.state == STATE_OK:
            self._cooldown = self.backoff
            self._transition(STATE_DEGRADED, f"mirror_divergence:{detail}")
            self._count("breaker_opens")

    def note_rehydrate(self) -> None:
        self._count("rehydrates")

    def count_degraded_batch(self) -> None:
        self._count("degraded_batches")

    # -- plumbing --
    def _count(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.counter(f"{self._prefix}{name}").add()

    def _transition(self, to: str, reason: str) -> None:
        from ..flow.trace import TraceEvent

        frm, self.state = self.state, to
        self.transitions.append([self.seq, frm, to, reason])
        if self.metrics is not None:
            self.metrics.gauge(f"{self._prefix}backend_state").set(
                _STATE_GAUGE[to]
            )
        # Marker span (ISSUE 12): breaker/probe walks on the same
        # timeline as the batch spans they degrade.
        from ..flow.spans import instant

        attrs = {"from": frm, "reason": reason, "seq": self.seq}
        if self.label:
            attrs["domain"] = self.label
        instant(f"breaker.{to}", role="DeviceBreaker", attrs=attrs)
        ev = TraceEvent("DeviceBackendStateChange", severity=20).detail(
            "from", frm
        ).detail("to", to).detail("reason", reason).detail(
            "seq", self.seq
        )
        if self.label:
            ev.detail("domain", self.label)
        ev.log()
        if frm == STATE_OK and to == STATE_DEGRADED:
            # Breaker OPEN (threshold faults or confirmed divergence —
            # not a failed probe re-opening an already-degraded circuit):
            # freeze the flight-recorder window, transitions included, so
            # the incident's lead-up survives the incident.  After the
            # TraceEvent above, so the capture's recent-events ring
            # contains the triggering transition itself.
            from ..flow.flight_recorder import maybe_trigger

            detail = {"reason": reason, "seq": self.seq}
            if self.label:
                # Shard-granular domain (ISSUE 15): a shard-breaker open
                # names the sick shard in the black-box artifact.
                detail["domain"] = self.label
            maybe_trigger(
                "breaker_open",
                detail=detail,
                # Thunk: copied only if the cooldown admits the capture.
                transitions=lambda: [list(t) for t in self.transitions],
                # Two breakers opening at once are two incidents, not a
                # flap — each gets its own cooldown key (construction-
                # order id: deterministic, never address-reused).
                source=self.breaker_id,
            )

    def snapshot(self) -> dict:
        """Replayable view for device_metrics(): same seed => the json
        dump of this dict is byte-identical across runs."""
        return {
            "state": self.state,
            "consecutive_failures": self.consecutive_failures,
            "backoff": self.backoff,
            "transitions": [list(t) for t in self.transitions],
        }
