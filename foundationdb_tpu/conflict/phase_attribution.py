"""Device phase-attribution harness (ISSUE 12): apportion one conflict
step's cost across the engine's phases via the in-step FDB_TPU_ABLATE
discipline, and hang the result off the dispatch span as child spans.

PERF_NOTES' failed-detour rule stands: standalone per-phase microbenches
lie (XLA fuses across phase boundaries, so a phase benched alone prices
materializations the fused program never pays).  The honest form is
subtractive IN-STEP ablation — the seams already cut into the flat
``detect_core`` for the round-5/6 experiments:

    phase      ablation   what the ablated program skips
    search     nosearch   phase 1's history binary searches + range-max
    fixpoint   nofix      phases 2-4's intra-batch fixpoint iteration
    merge      nomerge    phases 5-6 entirely (merge + evict)
    evict      noevict    phase 6's eviction compaction sort
    (kernels)  nokernel   FDB_TPU_KERNELS routing — the ablated program
                          runs the XLA fallback in the SAME step, so the
                          Pallas kernels are priced in-step too (ISSUE
                          14; see the kernel_ab report block)

``attribute_phases`` traces the full program and each ablated twin with
a FRESH jit wrapper per arm (the ablation flag is read at trace time, so
sharing the module-level wrapper's cache would silently reuse the wrong
graph) and attributes per phase as full − ablated, on two axes:

* **static FLOPs** from XLA's cost analysis — deterministic for a fixed
  program + jax version, cross-checked against ``program_cost_table()``
  (same analysis, canonical shapes): these drive the recorded child
  spans and survive the byte-identical artifact gates;
* optionally (``measure=True``) **measured wall seconds** per executed
  arm — the realized-phase-time number ROADMAP item 1's kernel work is
  judged against.  Wall values stay out of the deterministic report
  block (the record_wall discipline).

Tiered mode raises, exactly like the engine does for FDB_TPU_ABLATE:
the ablation seams live in the flat step only.
"""

from __future__ import annotations

import os
from typing import List, Optional

import jax
import jax.numpy as jnp

from ..flow.knobs import g_env
from ..flow.metrics import wall_now
from .types import TransactionConflictInfo

# (phase name, FDB_TPU_ABLATE token).  Order matters: "merge" covers
# phases 5-6, so the evict share is carved out of it below.
PHASE_ABLATIONS = (
    ("search", "nosearch"),
    ("fixpoint", "nofix"),
    ("merge", "nomerge"),
    ("evict", "noevict"),
)

# The kernel A/B token (ISSUE 14): `nokernel` routes a kernels-enabled
# program through the XLA fallback INSIDE the same step, so the harness
# prices the Pallas kernels in-step (the failed-detour rule: standalone
# kernel microbenches lie exactly like standalone phase benches).  When
# the engine runs with kernels, every arm is traced twice — with and
# without the kernels — and the per-phase deltas land in the report's
# `kernel_ab` block.  NOTE off-TPU the kernel arms price interpret-mode
# Pallas (the emulation, not Mosaic) — directional only; the honest
# device numbers come from the bench arms on a live tunnel.
NOKERNEL = "nokernel"


class _ablation:
    """Set FDB_TPU_ABLATE for one arm's trace and restore it after."""

    def __init__(self, token: str):
        self.token = token
        self._prev: Optional[str] = None

    def __enter__(self):
        self._prev = (
            os.environ["FDB_TPU_ABLATE"]  # fdblint: ignore[ENV001]: the harness restores the declared flag it temporarily sets; steady-state reads go through g_env
            if "FDB_TPU_ABLATE" in os.environ  # fdblint: ignore[ENV001]: presence check for exact restore (unset vs empty)
            else None
        )
        os.environ["FDB_TPU_ABLATE"] = self.token  # fdblint: ignore[ENV001]: the ablation arm IS the declared flag's documented use; set around one trace, restored in __exit__
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._prev is None:
            os.environ.pop("FDB_TPU_ABLATE", None)  # fdblint: ignore[ENV001]: restoring the pre-arm state
        else:
            os.environ["FDB_TPU_ABLATE"] = self._prev  # fdblint: ignore[ENV001]: restoring the pre-arm state
        return False


def _synthetic_txns(n: int = 24, keyspace: int = 512) -> List[
        TransactionConflictInfo]:
    """Deterministic batch for shape-only callers (no live stream)."""
    from ..flow.rng import DeterministicRandom

    def k(i: int) -> bytes:
        return b"%08d" % i

    rng = DeterministicRandom(1)
    out = []
    for _ in range(n):
        tr = TransactionConflictInfo(read_snapshot=5)
        a = rng.random_int(0, keyspace)
        tr.read_ranges.append((k(a), k(a + 1 + rng.random_int(0, 16))))
        a = rng.random_int(0, keyspace)
        tr.write_ranges.append((k(a), k(a + 1 + rng.random_int(0, 8))))
        out.append(tr)
    return out


def _cost(lowered) -> dict:
    """{flops, bytes} from XLA's analysis of one lowered arm.  The
    unoptimized-HLO analysis is enough for SUBTRACTIVE attribution and
    avoids a full backend compile per arm; both numbers are
    deterministic for a fixed program + jax version."""
    ca = lowered.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        ca = lowered.compile().cost_analysis()
        if isinstance(ca, list):
            ca = ca[0] if ca else None
    ca = ca or {}
    return {
        "flops": float(ca.get("flops", 0.0) or 0.0),
        "bytes": float(ca.get("bytes accessed", 0.0) or 0.0),
    }


def attribute_phases(engine, transactions=None, *, measure: bool = False,
                     repeats: int = 3, record: bool = True) -> dict:
    """Attribute one step's cost across the engine phases.

    engine: a flat-history JaxConflictSet (tiered raises — the ablation
    seams exist in flat detect_core only).  The engine's CURRENT carried
    state supplies the history arrays; non-donated fresh jit wrappers
    leave them untouched, so running this against a live engine is safe.

    Returns a report whose deterministic block (phases/full/shares/
    cost_table) is byte-stable per seed; measured wall seconds appear
    under "measured" only when measure=True.  With record=True the
    static shares are recorded as ``phase.<name>`` child spans of the
    engine's last dispatch span (the timeline artifact's device
    phase-attribution lanes)."""
    from .engine_jax import (
        EP_H,
        EP_KW1,
        EP_RR,
        EP_TXN,
        EP_WR,
        PackedBatch,
        _blob_core,
        cached_program_costs,
    )

    if getattr(engine, "tiered", False):
        raise ValueError(
            "phase attribution needs the flat engine: the FDB_TPU_ABLATE "
            "seams live in detect_core only (same restriction as the "
            "engine's own tiered+ABLATE rejection)"
        )
    if g_env.get("FDB_TPU_ABLATE"):
        raise ValueError(
            "FDB_TPU_ABLATE is already set — the harness owns the flag "
            "for the duration of its arms"
        )
    mt, mr, mw = engine.bucket_mins
    txns = transactions if transactions is not None else _synthetic_txns()
    pb = PackedBatch.from_transactions(
        txns, engine.key_words, min_txn=mt, min_rr=mr, min_wr=mw
    )
    now = engine.oldest_version + 8
    blob = jnp.asarray(engine._pack_blob(pb, now, engine.oldest_version, 1))
    args = (engine._hkeys, engine._hvers, engine._hcount, engine._oldest,
            blob)
    use_kern = bool(getattr(engine, "_use_kernels", False))
    statics = dict(txn_cap=pb.txn_cap, rr_cap=pb.rr_cap, wr_cap=pb.wr_cap,
                   h_cap=engine.h_cap, kw1=engine.key_words + 1,
                   amortized=False, kernels=use_kern,
                   kernel_interpret=bool(
                       getattr(engine, "_kernel_interpret", False)))
    static_names = tuple(statics)

    arm_list = [("full", "")] + list(PHASE_ABLATIONS)
    if use_kern:
        # The nokernel twins: same arms, XLA fallback in-step.
        arm_list += [
            (f"xla_{ph}", ",".join(t for t in (NOKERNEL, tok) if t))
            for ph, tok in arm_list[: 1 + len(PHASE_ABLATIONS)]
        ]
    arms: dict = {}
    _keep = []  # hold every arm's callable: a GC'd one could recycle
    #             its id() into a later arm's cache key
    for phase, token in arm_list:
        with _ablation(token):
            # Fresh FUNCTION OBJECT per arm, not just a fresh jit
            # wrapper: jax's trace cache keys on the underlying
            # callable's identity, so jit(_blob_core) under a different
            # ablation flag would silently hand back the first arm's
            # graph (the flag is read at TRACE time).

            def _arm_core(*a, **kw):
                return _blob_core(*a, **kw)

            _keep.append(_arm_core)
            step = jax.jit(_arm_core, static_argnames=static_names)
            lowered = step.lower(*args, **statics)
            arm = dict(_cost(lowered))
            if measure:
                compiled = lowered.compile()
                jax.block_until_ready(compiled(*args))  # warm first run
                t0 = wall_now()
                for _ in range(repeats):
                    jax.block_until_ready(compiled(*args))
                arm["wall_seconds"] = (wall_now() - t0) / repeats
            arms[phase] = arm

    full = arms["full"]
    phases = []
    for phase, _token in PHASE_ABLATIONS:
        d_flops = max(0.0, full["flops"] - arms[phase]["flops"])
        phases.append({"phase": phase, "flops": d_flops})
    # merge's ablation skips phases 5-6 wholesale; carve evict out so the
    # shares partition instead of double-counting.
    by_name = {p["phase"]: p for p in phases}
    by_name["merge"]["flops"] = max(
        0.0, by_name["merge"]["flops"] - by_name["evict"]["flops"]
    )
    attributed = sum(p["flops"] for p in phases)
    for p in phases:
        p["share"] = round(p["flops"] / full["flops"], 4) if full[
            "flops"] else 0.0
    report: dict = {
        "shapes": dict(statics),
        "full": full if not measure else {
            k: v for k, v in full.items() if k != "wall_seconds"
        },
        "phases": phases,
        "residual_flops": max(0.0, full["flops"] - attributed),
    }
    if use_kern:
        # Kernel-vs-XLA per phase, priced in-step (satellite of ISSUE
        # 14): for each phase, what the kernels change about its
        # subtractive attribution.  Deterministic (static analysis).
        xla_full = arms["xla_full"]
        per_phase: dict = {}
        for ph, _tok in PHASE_ABLATIONS:
            kf = max(0.0, full["flops"] - arms[ph]["flops"])
            xf = max(0.0, xla_full["flops"] - arms[f"xla_{ph}"]["flops"])
            per_phase[ph] = {"kernels_flops": kf, "xla_flops": xf}
        report["kernel_ab"] = {
            "full_flops": {"kernels": full["flops"],
                           "xla": xla_full["flops"]},
            "phase_flops": per_phase,
            "interpreted": bool(statics["kernel_interpret"]),
        }
        if measure:
            report["kernel_ab"]["measured_full_wall_seconds"] = {
                "kernels": round(arms["full"]["wall_seconds"], 6),
                "xla": round(arms["xla_full"]["wall_seconds"], 6),
            }
    # Cross-check against program_cost_table(): at the registry's
    # canonical trace shapes the two analyses price the SAME program, so
    # the flat_step block's flops must agree with our full arm.
    table = cached_program_costs() or {}
    flat_blk = table.get("flat_step")
    canonical = (pb.txn_cap, pb.rr_cap, pb.wr_cap, engine.h_cap,
                 engine.key_words + 1) == (EP_TXN, EP_RR, EP_WR, EP_H,
                                           EP_KW1)
    if flat_blk and flat_blk.get("flops_per_batch") is not None:
        report["cost_table"] = {
            "flat_step_flops": flat_blk["flops_per_batch"],
            "canonical_shapes": canonical,
            "ratio_vs_full": round(
                full["flops"] / flat_blk["flops_per_batch"], 4
            ) if flat_blk["flops_per_batch"] else None,
        }
    if measure:
        measured = {}
        t_full = arms["full"]["wall_seconds"]
        for phase, _token in PHASE_ABLATIONS:
            measured[phase] = round(
                max(0.0, t_full - arms[phase]["wall_seconds"]), 6
            )
        measured["evict"] = min(measured["evict"], measured["merge"])
        measured["merge"] = round(
            max(0.0, measured["merge"] - measured["evict"]), 6
        )
        report["measured"] = {
            "full_wall_seconds": round(t_full, 6),
            "phase_wall_seconds": measured,
            "repeats": repeats,
        }
    if record:
        _record_phase_spans(engine, phases)
    return report


def _record_phase_spans(engine, phases) -> None:
    """Child spans of the engine's last dispatch span, one per phase,
    carrying the static attribution (deterministic attrs only — wall
    numbers live in the report, never in exported spans)."""
    from ..flow.spans import begin_span

    parent = getattr(engine, "last_dispatch_span", None)
    for p in phases:
        sp = begin_span(
            f"phase.{p['phase']}",
            parent=parent,
            attrs={"flops": p["flops"], "share": p["share"]},
        )
        sp.end()
