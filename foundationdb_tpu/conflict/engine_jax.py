"""Device conflict engine: whole-batch MVCC conflict detection in JAX/XLA.

This is the north-star component (BASELINE.json): the reference resolves a
ResolveTransactionBatchRequest by walking a versioned skip list one range at
a time (fdbserver/SkipList.cpp: detectConflicts :1163, SkipList walkers :524,
MiniConflictSet :1028, insert :511, removeBefore :664).  Here the entire
batch is resolved at once with vectorized primitives, designed for the TPU's
strengths (large static-shaped tensor ops, no data-dependent control flow):

  history        sorted boundary array = step function key -> last-write
                 version; reads answered by multiword binary search +
                 sparse-table range max (ops/rangequery.py)
  intra-batch    all range endpoints sorted once into a point domain; the
                 reference's ordered scan becomes an iterative fixpoint:
                 a txn is finalized once every earlier intersecting writer
                 is finalized, with "earliest covering writer" computed by
                 a dyadic segment-tree stabbing query (ops/stabbing.py).
                 Each fixpoint round finalizes at least the first undecided
                 txn, and in practice converges in 1-3 rounds
  merge+evict    committed write ranges become a coverage cumsum over the
                 point domain; the step function is rewritten by a rank-merge
                 (no re-sort of history), then compacted with the reference's
                 eviction rule (drop boundary i iff vers[i] and vers[i-1]
                 are both below the window)

Versions are int32 offsets from a host-held base (the MVCC window is ~5e6
versions — ServerKnobs.max_write_transaction_life_versions — so offsets fit
comfortably), keeping all device math in native 32-bit.

Decision semantics are bit-identical to engine_cpu/oracle by construction
and verified by differential tests (tests/test_conflict_jax.py).
"""

from __future__ import annotations

import inspect
import math
import os
from functools import partial
from typing import Callable, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..flow.hotpath import GuardedDeviceValue, g_hostguard, hot_path
from ..ops.rangequery import (
    build_max_table,
    build_min_table,
    lex_less,
    range_max,
    range_min,
    searchsorted_1d,
    searchsorted_words,
)
from ..ops.stabbing import INF32, stabbing_min
from . import keys as keylib
from .types import COMMITTED, CONFLICT, TOO_OLD, TransactionConflictInfo

FLOOR_REL = -(2**30)  # below every representable snapshot
REBASE_THRESHOLD = 2**29

# Abort-witness sentinels (ISSUE 17): per-txn witness slots for txns whose
# final status is not CONFLICT carry (FLOOR_REL, WITNESS_NONE_RANGE).
WITNESS_NONE_RANGE = 2**31 - 1

_UNDECIDED = 0
_COMM = 1
_CONF = 2


def _next_pow2(n: int, lo: int) -> int:
    return max(lo, 1 << max(0, math.ceil(math.log2(max(n, 1)))))


def _unpack_transactions(pb: "PackedBatch") -> List[TransactionConflictInfo]:
    """PackedBatch -> TransactionConflictInfo list (CPU-fallback path only;
    keys come back in their packed fixed-width form, which is the key space
    both engines decide over)."""
    txns = [
        TransactionConflictInfo(
            read_snapshot=int(pb.t_snap[t]), read_ranges=[], write_ranges=[]
        )
        for t in range(pb.n_txn)
    ]
    for i in range(pb.n_r):
        t = int(pb.r_txn[i])
        if t < pb.n_txn:
            txns[t].read_ranges.append(
                (
                    keylib.decode_key(pb.r_begin[i], pb.key_words),
                    keylib.decode_key(pb.r_end[i], pb.key_words),
                )
            )
    for i in range(pb.n_w):
        t = int(pb.w_txn[i])
        if t < pb.n_txn:
            txns[t].write_ranges.append(
                (
                    keylib.decode_key(pb.w_begin[i], pb.key_words),
                    keylib.decode_key(pb.w_end[i], pb.key_words),
                )
            )
    return txns


def decode_witness(pb, statuses, w_ver, w_rng, base):
    """Decode device witness vectors to the host form: per live txn,
    (absolute conflicting version, read-range ordinal within that txn) —
    or None for non-CONFLICT txns.  The packed read index is global
    (r_txn is ascending and from_transactions packs EVERY read range,
    empty ones included), so the per-txn ordinal is the global index
    minus the txn's first packed row."""
    wv = np.asarray(w_ver)
    wr = np.asarray(w_rng)
    r_txn = pb.r_txn[: pb.n_r]
    out: list = []
    for t in range(pb.n_txn):
        if int(statuses[t]) == CONFLICT and int(wr[t]) < WITNESS_NONE_RANGE:
            first = int(np.searchsorted(r_txn, t, side="left"))
            out.append((int(wv[t]) + base, int(wr[t]) - first))
        else:
            out.append(None)
    return out


class DispatchTicket:
    """One in-flight dispatched batch (the double-buffered resolver
    pipeline's device-side handle, ISSUE 11): the packed batch plus the
    dispatch's device arrays — statuses/undecided/fixpoint-iteration
    carry and the post-batch history counts.  Holding a ticket costs
    nothing host-side; syncing it (JaxConflictSet.sync_ticket) blocks
    only until ITS program finished, never on later dispatches (the
    arrays are that program's own outputs, and device programs execute
    in dispatch order)."""

    __slots__ = ("pb", "statuses", "undecided", "iters", "hcount",
                 "dcount", "d_cap", "now", "new_oldest_version", "witness")

    def __init__(self, pb, statuses, undecided, iters, hcount, dcount,
                 d_cap, now, new_oldest_version, witness=None):
        self.pb = pb
        self.statuses = statuses
        self.undecided = undecided
        self.iters = iters
        self.hcount = hcount
        self.dcount = dcount
        self.d_cap = d_cap  # delta capacity AT dispatch (may grow later)
        self.now = now
        self.new_oldest_version = new_oldest_version
        # (w_ver_dev, w_rng_dev, base) at dispatch time, or None.  Carries
        # its own base: a later dispatch may rebase before the sync.
        self.witness = witness


class PackedBatch:
    """Host-side (numpy) dense form of a transaction batch.

    The production resolver keeps batches in this form (ranges packed as they
    arrive), so device dispatch is a straight transfer with no Python loops.
    """

    def __init__(self, txn_cap, rr_cap, wr_cap, key_words):
        kw1 = key_words + 1
        inf = keylib.INF_WORD
        self.key_words = key_words
        self.txn_cap, self.rr_cap, self.wr_cap = txn_cap, rr_cap, wr_cap
        self.r_begin = np.full((rr_cap, kw1), inf, np.uint32)
        self.r_end = np.full((rr_cap, kw1), inf, np.uint32)
        self.r_txn = np.full((rr_cap,), txn_cap, np.int32)
        self.r_snap = np.zeros((rr_cap,), np.int64)
        self.w_begin = np.full((wr_cap, kw1), inf, np.uint32)
        self.w_end = np.full((wr_cap, kw1), inf, np.uint32)
        self.w_txn = np.full((wr_cap,), txn_cap, np.int32)
        self.t_snap = np.zeros((txn_cap,), np.int64)
        self.t_has_reads = np.zeros((txn_cap,), bool)
        self.t_valid = np.zeros((txn_cap,), bool)
        self.n_txn = 0
        self.n_r = 0
        self.n_w = 0

    @classmethod
    @hot_path(bound="batch")
    def from_transactions(
        cls,
        txns: List[TransactionConflictInfo],
        key_words: int,
        min_txn: int = 8,
        min_rr: int = 8,
        min_wr: int = 8,
    ) -> "PackedBatch":
        n = len(txns)
        nr = sum(len(t.read_ranges) for t in txns)
        nw = sum(len(t.write_ranges) for t in txns)
        pb = cls(
            _next_pow2(n, min_txn),
            _next_pow2(nr, min_rr),
            _next_pow2(nw, min_wr),
            key_words,
        )
        # One bulk pass (ISSUE 19): per-txn range counts drive np.repeat
        # for the ownership/snapshot columns, and each side's begin+end
        # keys digitize in ONE concatenated encode_keys call — no
        # per-txn/per-range Python loops, no per-range array writes.
        rr_counts = np.fromiter(
            (len(t.read_ranges) for t in txns), np.int64, count=n
        )
        wr_counts = np.fromiter(
            (len(t.write_ranges) for t in txns), np.int64, count=n
        )
        snaps = np.fromiter(
            (t.read_snapshot for t in txns), np.int64, count=n
        )
        pb.t_snap[:n] = snaps
        pb.t_has_reads[:n] = rr_counts > 0
        pb.t_valid[:n] = True
        if nr:
            owner = np.repeat(np.arange(n, dtype=np.int32), rr_counts)
            pb.r_txn[:nr] = owner
            pb.r_snap[:nr] = snaps[owner]
            rkeys = [b for t in txns for (b, _e) in t.read_ranges]
            rkeys += [e for t in txns for (_b, e) in t.read_ranges]
            enc = keylib.encode_keys(rkeys, key_words)
            pb.r_begin[:nr] = enc[:nr]
            pb.r_end[:nr] = enc[nr:]
        if nw:
            pb.w_txn[:nw] = np.repeat(np.arange(n, dtype=np.int32), wr_counts)
            wkeys = [b for t in txns for (b, _e) in t.write_ranges]
            wkeys += [e for t in txns for (_b, e) in t.write_ranges]
            enc = keylib.encode_keys(wkeys, key_words)
            pb.w_begin[:nw] = enc[:nw]
            pb.w_end[:nw] = enc[nw:]
        pb.n_txn, pb.n_r, pb.n_w = n, nr, nw
        return pb

    def bucket(self):
        return (self.txn_cap, self.rr_cap, self.wr_cap)


# ---------------------------------------------------------------------------
# The jitted whole-batch step.  Static: capacities + key width; traced: state
# arrays (donated) + batch tensors.
#
# The batch pipeline is factored into history-independent and per-tier
# pieces so the flat single-tier step (detect_core) and the two-tier step
# (detect_core_tiered, FDB_TPU_HISTORY=tiered) share one implementation:
#   _resolve_batch        phases 2-4: point domain, intra-batch fixpoint,
#                         committed-write segment extraction
#   _merge_new_segments   phase 5: rank-merge a batch's segments into ONE
#                         tier's step function (base for flat, delta for
#                         tiered — the whole point of the tier split is
#                         that this runs at delta size per batch)
#   _evict_rule           phase 6's keep predicate (ref removeBefore)
#   _compact_to           sort-by-target-position compaction
# ---------------------------------------------------------------------------


def _compact_to(pos, valid, words, width, fill_vers=None, vers=None,
                count=None):
    """Reorder columns of `words` [kw1, N] so column i lands at pos[i];
    invalid columns drop off the end.  Returns [kw1, width] (+vers).

    This is SORT-BY-TARGET-POSITION, not scatter: a single-key int32 sort
    carrying the payload words runs ~23x faster than the equivalent
    scatter on TPU (measured v5e, 8M rows: 54ms vs 1250ms).  Rows being
    dropped get a past-the-end position and fall off the trailing slice;
    surviving slots beyond the live count are masked to the INF sentinel
    afterwards (streaming select)."""
    inf32 = jnp.uint32(keylib.INF_WORD)
    n = pos.shape[0]
    dump = jnp.int32(n + width + 2)
    p = jnp.where(valid, pos.astype(jnp.int32), dump)
    ops = (p,) + tuple(words[w] for w in range(words.shape[0]))
    if vers is not None:
        ops = ops + (vers,)
    res = jax.lax.sort(ops, num_keys=1, is_stable=True)
    out = jnp.stack(res[1 : 1 + words.shape[0]])[:, :width]
    if count is not None:
        # Explicit 32-bit index math here and below (jaxcheck JXP004):
        # bare arange/cumsum/sum default to 64-bit under x64 and would
        # silently double every H-sized index buffer.
        live = jnp.arange(width, dtype=jnp.int32) < count
        out = jnp.where(live[None, :], out, inf32)
        if vers is not None:
            v = jnp.where(live, res[-1][:width], fill_vers)
            return out, v
    if vers is not None:
        return out, res[-1][:width]
    return out


def _evict_rule(merged_vers, merged_count, new_oldest, width):
    """Phase-6 window eviction predicate (ref removeBefore wasAbove rule:
    drop boundary i iff vers[i] and vers[i-1] are both below the window).
    Returns (keep2, rank2, out_count)."""
    H = width
    idx = jnp.arange(H, dtype=jnp.int32)
    mvalid = idx < merged_count
    prev_v = jnp.concatenate(
        [jnp.full((1,), FLOOR_REL, jnp.int32), merged_vers[:-1]]
    )
    keep2 = mvalid & (
        (idx == 0)
        | (merged_vers >= new_oldest)
        | (prev_v >= new_oldest)
    )
    rank2 = jnp.cumsum(keep2, dtype=jnp.int32) - 1
    out_count = jnp.sum(keep2, dtype=jnp.int32)
    return keep2, rank2, out_count


def _resolve_batch(
    r_begin, r_end, r_txn, w_begin, w_end, w_txn, t_valid, status0,
    *, txn_cap, rr_cap, wr_cap, ablate=frozenset(), witness=False,
):
    """Phases 2-4: point domain, intra-batch fixpoint, committed-write
    segment extraction.  History-independent — shared verbatim by the flat
    and tiered steps.  Returns (status, iters, undecided_left, ub, ue,
    seg_valid, nseg, ib_flag) — ib_flag is the per-read-range intra-batch
    conflict flag (the abort-witness input, ISSUE 17) when `witness`,
    else None so the default compile is byte-identical."""
    kw1 = r_begin.shape[0]
    TXN, RR, WR = txn_cap, rr_cap, wr_cap
    P = 2 * RR + 2 * WR
    p_log2 = max(1, math.ceil(math.log2(P)))
    r_valid = r_txn < TXN

    # ---- phase 2: point domain (ref sortPoints + KeyInfo ordering) ----
    # categories at equal keys sort end-read(0) < end-write(1) <
    # begin-write(2) < begin-read(3)  (ref SkipList.cpp getCharacter :166-170)
    cat = jnp.concatenate(
        [
            jnp.full((RR,), 3, jnp.uint32),
            jnp.full((RR,), 0, jnp.uint32),
            jnp.full((WR,), 2, jnp.uint32),
            jnp.full((WR,), 1, jnp.uint32),
        ]
    )
    pkeys = jnp.concatenate([r_begin, r_end, w_begin, w_end], axis=1)
    packed_tail = pkeys[kw1 - 1] * 4 + cat  # (length << 2) | category
    iota = jnp.arange(P, dtype=jnp.int32)
    # Sort operands: key words most-significant-first (keys.py layout), then
    # the packed (length,category) word, then the payload iota; stable for
    # determinism.
    word_ops = [pkeys[w] for w in range(kw1 - 1)]
    res = jax.lax.sort(
        tuple(word_ops) + (packed_tail, iota), num_keys=kw1, is_stable=True
    )
    perm = res[-1]
    pos = jnp.zeros((P,), jnp.int32).at[perm].set(iota)
    # Sorted keys come straight off the sort outputs (no permutation
    # gather): words, then length recovered from the packed tail.
    sorted_keys = jnp.stack(list(res[: kw1 - 1]) + [res[kw1 - 1] // 4])

    rb_idx = pos[:RR]
    re_idx = pos[RR : 2 * RR]
    wb_idx = pos[2 * RR : 2 * RR + WR]
    we_idx = pos[2 * RR + WR :]
    w_valid = w_txn < TXN

    # ---- phase 3: intra-batch fixpoint (ref checkIntraBatchConflicts) ----
    r_has_slots = re_idx > rb_idx

    def agg_txn(flags):
        """Per-range bool -> per-txn any() over that txn's read ranges."""
        return (
            jnp.zeros((TXN + 1,), bool)
            .at[jnp.where(flags, r_txn, TXN)]
            .max(flags)[:TXN]
        )

    # The reference resolves intra-batch conflicts by a sequential scan
    # whose vectorized form is a fixpoint; iterating it at FULL width costs
    # ~47ms/round at 64k txns on v5e (the dyadic scatter stabbing
    # dominates).  Restructure into exactly TWO full-width stabbings plus a
    # tiny residual loop:
    #   round 1   needs no committed-stab (nothing is committed yet):
    #             txns with no earlier ACTIVE intersecting writer COMMIT.
    #   frozen    round-1 commits never change; one stabbing over their
    #             writes answers every read's frozen-committed conflict —
    #             reads with a smaller frozen committed writer CONFLICT now.
    #   residual  everything still undecided can only be decided by OTHER
    #             residual txns (a frozen writer either conflicted it above
    #             or can never conflict it).  Re-rank the residual
    #             endpoints into a compact domain and run the fixpoint at
    #             1/16th width, where every op is near-free.
    hi_r = jnp.maximum(re_idx - 1, rb_idx)

    def read_query(stab):
        tab = build_min_table(stab)
        return jnp.where(r_has_slots, range_min(tab, rb_idx, hi_r), INF32)

    # -- round 1 --
    w_stat0 = status0[jnp.clip(w_txn, 0, TXN - 1)]
    act0 = w_valid & (w_stat0 != _CONF)
    e1 = read_query(stabbing_min(wb_idx, we_idx, w_txn, act0, p_log2))
    E1_t = agg_txn(r_valid & (e1 < r_txn))
    status1 = jnp.where(
        status0 != _UNDECIDED,
        status0,
        jnp.where(E1_t, _UNDECIDED, _COMM),
    )

    # -- frozen committed stab + immediate round-2 conflicts --
    w_stat1 = status1[jnp.clip(w_txn, 0, TXN - 1)]
    com1 = w_valid & (w_stat1 == _COMM)
    eF = read_query(stabbing_min(wb_idx, we_idx, w_txn, com1, p_log2))
    CF_t = agg_txn(r_valid & (eF < r_txn))
    status2 = jnp.where(
        (status1 == _UNDECIDED) & CF_t, _CONF, status1
    )

    # -- residual compaction --
    RCAP = min(min(RR, WR), max(64, min(RR, WR) >> 4))
    RP = 4 * RCAP
    rp_log2 = max(1, math.ceil(math.log2(RP)))
    r_res = r_valid & (status2[jnp.clip(r_txn, 0, TXN - 1)] == _UNDECIDED)
    w_res = w_valid & (status2[jnp.clip(w_txn, 0, TXN - 1)] == _UNDECIDED)
    n_rres = jnp.sum(r_res)
    n_wres = jnp.sum(w_res)
    overflow = (n_rres > RCAP) | (n_wres > RCAP)

    def compact_1d(valid, cols, width, fill):
        """Sort-by-target compaction of parallel int32 columns."""
        rank = jnp.where(
            valid, jnp.cumsum(valid) - 1, jnp.int32(valid.shape[0] + width)
        ).astype(jnp.int32)
        res2 = jax.lax.sort(
            (rank,) + tuple(c.astype(jnp.int32) for c in cols),
            num_keys=1,
            is_stable=True,
        )
        out = [c[:width] for c in res2[1:]]
        live = jnp.arange(width) < jnp.sum(valid)
        return [jnp.where(live, c, fill) for c in out], live

    (rb_c, re_c, rt_c), r_live = compact_1d(
        r_res, (rb_idx, re_idx, r_txn), RCAP, jnp.int32(0)
    )
    (wb_c, we_c, wt_c), w_live = compact_1d(
        w_res, (wb_idx, we_idx, w_txn), RCAP, jnp.int32(0)
    )
    # Re-rank endpoints into [0, RP): residual endpoints are distinct slots,
    # so ranking the combined endpoint set preserves every intersection
    # predicate (a < b iff rank(a) < rank(b) for ranked points).
    pts = jnp.concatenate([rb_c, re_c, wb_c, we_c])
    pad = jnp.where(
        jnp.concatenate([r_live, r_live, w_live, w_live]),
        pts,
        jnp.int32(2 ** 30) + jnp.arange(RP, dtype=jnp.int32),
    )
    (spts,) = jax.lax.sort((pad,), num_keys=1, is_stable=True)
    ranks = searchsorted_1d(spts, pad, "left").astype(jnp.int32)
    rb_r, re_r = ranks[:RCAP], ranks[RCAP : 2 * RCAP]
    wb_r, we_r = ranks[2 * RCAP : 3 * RCAP], ranks[3 * RCAP :]
    r_has_c = r_live & (re_r > rb_r)
    hi_c = jnp.maximum(re_r - 1, rb_r)

    def agg_txn_small(flags):
        return (
            jnp.zeros((TXN + 1,), bool)
            .at[jnp.where(flags, rt_c, TXN)]
            .max(flags)[:TXN]
        )

    def fix_body(carry):
        status, it = carry
        ws = status[jnp.clip(wt_c, 0, TXN - 1)]
        act = w_live & (ws != _CONF)
        com = w_live & (ws == _COMM)
        ea = jnp.where(
            r_has_c,
            range_min(
                build_min_table(stabbing_min(wb_r, we_r, wt_c, act, rp_log2)),
                rb_r,
                hi_c,
            ),
            INF32,
        )
        ec = jnp.where(
            r_has_c,
            range_min(
                build_min_table(stabbing_min(wb_r, we_r, wt_c, com, rp_log2)),
                rb_r,
                hi_c,
            ),
            INF32,
        )
        E_t = agg_txn_small(r_live & (ea < rt_c))
        C_t = agg_txn_small(r_live & (ec < rt_c))
        new_status = jnp.where(
            status != _UNDECIDED,
            status,
            jnp.where(C_t, _CONF, jnp.where(~E_t, _COMM, _UNDECIDED)),
        )
        return new_status, it + 1

    def fix_cond(carry):
        status, it = carry
        return jnp.any(status == _UNDECIDED) & (it < RCAP + 2)

    if "nofix" in ablate:
        status, iters = jnp.where(status0 == _UNDECIDED, _COMM, status0), jnp.int32(1)
    else:
        status, iters = jax.lax.while_loop(
            fix_cond, fix_body, (status2, jnp.int32(2))
        )
    # Residual overflow: treated exactly like fixpoint divergence — the
    # host re-runs the batch on the CPU engine against the UNCHANGED
    # history state (see the `ok` guard in the callers).
    undecided_left = jnp.sum(status == _UNDECIDED) + jnp.where(
        overflow, jnp.int32(1), jnp.int32(0)
    )

    # Abort witness input (ISSUE 17): with the fixpoint settled, one more
    # full-width stabbing over the FINAL committed writers answers, per
    # read range, whether an EARLIER committed txn's write intersects it —
    # exactly the CPU engine's phase-2 `active.intersects` predicate
    # (sequentially, the active set when txn t is checked is the write
    # union of committed txns < t, and every final-committed writer < t
    # is in it).
    ib_flag = None
    if witness:
        w_stat_fin = status[jnp.clip(w_txn, 0, TXN - 1)]
        com_fin = w_valid & (w_stat_fin == _COMM)
        e_fin = read_query(stabbing_min(wb_idx, we_idx, w_txn, com_fin, p_log2))
        ib_flag = r_valid & (e_fin < r_txn)

    # ---- phase 4: committed-write union via point-domain coverage ----
    com_w = w_valid & (status[jnp.clip(w_txn, 0, TXN - 1)] == _COMM)
    delta = (
        jnp.zeros((P + 1,), jnp.int32)
        .at[jnp.where(com_w, wb_idx, P)]
        .add(jnp.where(com_w, 1, 0))
        .at[jnp.where(com_w, we_idx, P)]
        .add(jnp.where(com_w, -1, 0))
    )
    cov = jnp.cumsum(delta[:P]) > 0
    prev = jnp.concatenate([jnp.zeros((1,), bool), cov[:-1]])
    is_start = cov & ~prev
    is_end = ~cov & prev
    seg_of_start = jnp.cumsum(is_start) - 1
    seg_of_end = jnp.cumsum(is_end) - 1
    nseg = jnp.sum(is_start)

    ub = _compact_to(seg_of_start, is_start, sorted_keys, WR, count=nseg)
    ue = _compact_to(seg_of_end, is_end, sorted_keys, WR, count=nseg)
    seg_valid = jnp.arange(WR) < nseg

    # Merge touching segments (ue[s-1] == ub[s]): the gap between them is a
    # key-empty slot (same key, different point category), so they are one
    # write range semantically — matches the CPU engine's interval coalescing.
    chain_start = jnp.concatenate(
        [
            jnp.ones((1,), bool),
            ~jnp.all(ue[:, :-1] == ub[:, 1:], axis=0),
        ]
    ) | ~seg_valid
    chain_id = jnp.cumsum(chain_start) - 1
    is_chain_last = jnp.concatenate([chain_start[1:], jnp.ones((1,), bool)])
    nseg2 = jnp.sum(chain_start & seg_valid)
    ub = _compact_to(chain_id, chain_start & seg_valid, ub, WR, count=nseg2)
    ue = _compact_to(chain_id, is_chain_last & seg_valid, ue, WR, count=nseg2)
    nseg = nseg2
    seg_valid = jnp.arange(WR) < nseg
    return status, iters, undecided_left, ub, ue, seg_valid, nseg, ib_flag


def _merge_prep(
    tkeys, tvers, tcount, ub, ue, seg_valid, nseg, now_rel,
    *, width, wr_cap, kw1,
):
    """Phase-5 rank-inversion prep, shared by the sort-by-target path
    (_merge_new_segments) and the fused Pallas kernel path
    (_merge_evict_fused): build the sorted new-boundary rows and derive
    every row's merged position by rank inversion — streaming cumsums
    and small-into-big searches, never a full-width sort.  Returns
    (new_keys_s, new_vers_s, new_valid_s, keep_old, pos_old, pos_new,
    merged_count).

    TWO combined searches over (ub | ue) serve EVERYTHING downstream:
    eq_at_ue, seg_lo/seg_hi, end_val, and — via the new-keys sort
    permutation — the sorted-new-keys ranks (t_rank/t_rank_r), which were
    previously re-searched.  Each full-width multiword search over H
    costs ~10ms at h_cap=4M, so collapsing 5 searches to 2 matters
    (PERF_NOTES)."""
    H = width
    WR = wr_cap
    inf32 = jnp.uint32(keylib.INF_WORD)
    both = jnp.concatenate([ub, ue], axis=1)
    both_left = searchsorted_words(tkeys, both, "left")
    both_right = searchsorted_words(tkeys, both, "right")
    ub_left, ue_left = both_left[:WR], both_left[WR:]
    ub_right, ue_right = both_right[:WR], both_right[WR:]
    rank_right = ue_right
    iv = rank_right - 1
    end_val = tvers[jnp.clip(iv, 0, H - 1)]
    eq_at_ue = (rank_right - ue_left) > 0

    # new boundary entries, interleaved (ub0, ue0, ub1, ue1, ...)
    n_new_cap = 2 * WR
    new_keys = jnp.zeros((kw1, n_new_cap), jnp.uint32)
    new_keys = new_keys.at[:, 0::2].set(ub).at[:, 1::2].set(ue)
    new_vers = (
        jnp.zeros((n_new_cap,), jnp.int32)
        .at[0::2]
        .set(jnp.full((WR,), 0, jnp.int32) + now_rel)
        .at[1::2]
        .set(end_val)
    )
    new_vld = jnp.zeros((n_new_cap,), bool)
    new_vld = new_vld.at[0::2].set(seg_valid).at[1::2].set(seg_valid & ~eq_at_ue)
    nk = jnp.where(new_vld[None, :], new_keys, inf32)
    nw_iota = jnp.arange(n_new_cap, dtype=jnp.int32)
    nres = jax.lax.sort(
        tuple(nk[w] for w in range(kw1)) + (nw_iota,),
        num_keys=kw1,
        is_stable=True,
    )
    nperm = nres[-1]
    new_keys_s = jnp.stack(nres[:kw1])
    new_vers_s = new_vers[nperm]
    nnew = jnp.sum(new_vld, dtype=jnp.int32)
    new_valid_s = jnp.arange(n_new_cap, dtype=jnp.int32) < nnew
    # Ranks of the SORTED new keys by permuting the interleaved ranks
    # (invalid rows carry their raw ub/ue rank instead of an INF rank —
    # harmless, they are masked by new_valid_s at every use).
    ranks_left_interleaved = (
        jnp.zeros((n_new_cap,), jnp.int32).at[0::2].set(ub_left).at[1::2].set(ue_left)
    )
    ranks_right_interleaved = (
        jnp.zeros((n_new_cap,), jnp.int32).at[0::2].set(ub_right).at[1::2].set(ue_right)
    )
    t_rank = ranks_left_interleaved[nperm]
    t_rank_r = ranks_right_interleaved[nperm]

    # Which old boundaries survive (not overwritten by a segment), and where
    # everything lands in the merged order.  All per-old-row quantities are
    # derived by RANK INVERSION: search the (few) segment/new keys into the
    # (huge) history once, then turn the ranks into per-history-row values
    # with difference arrays + cumsums — pure streaming.  Issuing one query
    # PER HISTORY ROW into the small tables instead costs H * log(W) random
    # gathers and dominated the whole batch at h_cap = 8M.
    old_iota = jnp.arange(H, dtype=jnp.int32)
    old_valid = old_iota < tcount
    # in_seg: old key i lies in some segment [ub_s, ue_s).  Mark +1 at the
    # first old index >= ub_s and -1 at the first >= ue_s; coverage > 0 after
    # a cumsum (segments are disjoint).
    seg_lo = ub_left
    seg_hi = ue_left
    seg_diff = (
        jnp.zeros((H + 1,), jnp.int32)
        .at[jnp.where(seg_valid, seg_lo, H)]
        .add(jnp.where(seg_valid, 1, 0))
        .at[jnp.where(seg_valid, seg_hi, H)]
        .add(jnp.where(seg_valid, -1, 0))
    )
    in_seg = jnp.cumsum(seg_diff[:H]) > 0
    keep_old = old_valid & ~in_seg
    cum_keep = jnp.cumsum(keep_old.astype(jnp.int32))  # prefix-inclusive
    kept_rank = cum_keep - 1
    # removed-prefix at rank k = (#valid rows < k) - (#kept rows < k)
    #                          = min(k, tcount) - cum_keep[k-1]
    # — closed form; no second cumsum (PERF_NOTES).

    # count_new_less[i] = #new keys strictly below old key i
    #                   = #j with (#old <= new_j) <= i, via a rank histogram.
    new_hist = (
        jnp.zeros((H + 1,), jnp.int32)
        .at[jnp.where(new_valid_s, t_rank_r, H)]
        .add(jnp.where(new_valid_s, 1, 0))
    )
    count_new_less = jnp.cumsum(new_hist[:H])
    pos_old = kept_rank.astype(jnp.int32) + count_new_less
    removed_at_t = jnp.minimum(t_rank, tcount) - jnp.where(
        t_rank > 0, cum_keep[jnp.clip(t_rank - 1, 0, H - 1)], 0
    )
    count_kept_less = t_rank - removed_at_t
    pos_new = jnp.arange(n_new_cap, dtype=jnp.int32) + count_kept_less

    merged_count = jnp.sum(keep_old, dtype=jnp.int32) + nnew
    return (new_keys_s, new_vers_s, new_valid_s, keep_old, pos_old,
            pos_new, merged_count)


def _merge_new_segments(
    tkeys, tvers, tcount, ub, ue, seg_valid, nseg, now_rel,
    *, width, wr_cap, kw1,
):
    """Phase 5: rewrite ONE tier's step function (ref addConflictRanges) by
    rank-merging the batch's committed segments [ub_s, ue_s) at version
    `now_rel` into the tier (`width`-capped).  For the flat engine the tier
    is the whole history; for the tiered engine it is the DELTA — end
    values come from the tier itself (the delta's floor is FLOOR_REL =
    "uncovered", so max(base, delta) composes exactly; see
    detect_core_tiered).  Returns (merged_keys, merged_vers, merged_count).

    This is the SORT-BY-TARGET arm: positions from _merge_prep feed one
    full-width _compact_to.  The FDB_TPU_KERNELS arm replaces it (and the
    phase-6 eviction sort) with the fused streaming kernel
    (_merge_evict_fused / conflict/kernels.py)."""
    H = width
    (new_keys_s, new_vers_s, new_valid_s, keep_old, pos_old, pos_new,
     merged_count) = _merge_prep(
        tkeys, tvers, tcount, ub, ue, seg_valid, nseg, now_rel,
        width=width, wr_cap=wr_cap, kw1=kw1,
    )
    merged_keys, merged_vers = _compact_to(
        jnp.concatenate([pos_old, pos_new]),
        jnp.concatenate([keep_old, new_valid_s]),
        jnp.concatenate([tkeys, new_keys_s], axis=1),
        H,
        fill_vers=jnp.int32(FLOOR_REL),
        vers=jnp.concatenate([tvers, new_vers_s]),
        count=merged_count,
    )
    return merged_keys, merged_vers, merged_count


def _merge_evict_fused(
    tkeys, tvers, tcount, ub, ue, seg_valid, nseg, now_rel, window,
    *, width, wr_cap, kw1, interpret,
):
    """Kernelized phases 5+6 (ISSUE 14 tentpole): ONE streaming pass —
    merge the batch's segment rows into the tier AND apply the
    removeBefore eviction rule in-stream — instead of the two full-width
    sort-by-target passes.  `window` is the eviction floor as a traced
    value: new_oldest evicts (the default semantics), FLOOR_REL keeps
    everything (the noevict ablation and the amortized do_evict=0 arm —
    the traced-cond eviction skip becomes a plain value select).
    Bit-identical to _merge_new_segments + _evict_rule + _compact_to by
    construction (same prep, same rule; gated by tests/test_kernels.py).
    """
    from .kernels import fused_merge_evict

    (new_keys_s, new_vers_s, new_valid_s, keep_old, pos_old, pos_new,
     merged_count) = _merge_prep(
        tkeys, tvers, tcount, ub, ue, seg_valid, nseg, now_rel,
        width=width, wr_cap=wr_cap, kw1=kw1,
    )
    ok_keys, ok_vers, out_count = fused_merge_evict(
        tkeys, tvers, keep_old, pos_old,
        new_keys_s, new_vers_s, new_valid_s, pos_new,
        merged_count, window,
        width=width, kw1=kw1, interpret=interpret,
    )
    inf32 = jnp.uint32(keylib.INF_WORD)
    live = jnp.arange(width, dtype=jnp.int32) < out_count
    out_keys = jnp.where(live[None, :], ok_keys, inf32)
    out_vers = jnp.where(live, ok_vers, jnp.int32(FLOOR_REL))
    return out_keys, out_vers, out_count.astype(jnp.int32)


def _finish_flat(hkeys, hvers, hcount, oldest, out_keys, out_vers,
                 out_count, new_oldest, too_old, status, undecided_left,
                 iters):
    """Shared tail of the flat step (both the sort and kernel arms):
    statuses in the reference's enum plus the divergence guard — if the
    fixpoint failed to converge the statuses are unreliable and so is the
    write merge derived from them, so the history state reverts UNCHANGED
    and the host re-runs the batch on the CPU engine."""
    out_status = jnp.where(
        too_old,
        TOO_OLD,
        jnp.where(status == _COMM, COMMITTED, CONFLICT),
    ).astype(jnp.int32)
    ok = undecided_left == 0
    out_keys = jnp.where(ok, out_keys, hkeys)
    out_vers = jnp.where(ok, out_vers, hvers)
    out_count = jnp.where(ok, out_count, hcount)
    new_oldest = jnp.where(ok, new_oldest, oldest)
    return (
        out_keys,
        out_vers,
        out_count.astype(jnp.int32),
        new_oldest.astype(jnp.int32),
        out_status,
        undecided_left.astype(jnp.int32),
        iters,
    )


def _witness_vectors(m, r_hist, hist_conf, ib_flag, r_txn, t_valid, too_old,
                     status, now_rel, *, txn_cap, rr_cap, witness,
                     witness_combine=None):
    """Per-txn abort witness (ISSUE 17): (conflicting version, losing
    read-range index) for every final-CONFLICT txn, sentinels elsewhere.

    Selection rule — identical to the CPU engines by construction:
      history conflict     FIRST flagged read range (min packed index;
                           packing is contiguous per txn in order, so the
                           min packed index IS the first per-txn ordinal)
                           at that range's history range-max `m`
      intra-batch conflict first read range intersecting an earlier
                           final-committed writer's write, at `now_rel`
    The two are mutually exclusive per txn (hist-conflicted txns enter
    the fixpoint pre-decided), so the per-range eligibility just selects
    by the txn's hist_conf bit.  `witness_combine`, under shard_map,
    reduces the per-shard vectors into the mesh-global witness (min range
    index across conflicting shards, max version among its holders).
    Returns () when `witness` is off — the default compile is untouched.
    """
    if not witness:
        return ()
    TXN, RR = txn_cap, rr_cap
    BIG = jnp.int32(WITNESS_NONE_RANGE)
    r_idx = jnp.arange(RR, dtype=jnp.int32)
    hist_conf_r = hist_conf[jnp.clip(r_txn, 0, TXN - 1)]
    elig = jnp.where(hist_conf_r, r_hist, ib_flag)
    sel = (
        jnp.full((TXN + 1,), BIG, jnp.int32)
        .at[jnp.where(elig, r_txn, TXN)]
        .min(jnp.where(elig, r_idx, BIG))[:TXN]
    )
    sel_ok = sel < BIG
    m_sel = m[jnp.clip(sel, 0, RR - 1)]
    is_conf = t_valid & ~too_old & (status != _COMM) & sel_ok
    w_ver = jnp.where(
        is_conf,
        jnp.where(hist_conf, m_sel, now_rel),
        jnp.int32(FLOOR_REL),
    ).astype(jnp.int32)
    w_rng = jnp.where(is_conf, sel, BIG)
    if witness_combine is not None:
        w_ver, w_rng = witness_combine(w_ver, w_rng)
    return (w_ver, w_rng)


def detect_core(
    hkeys,
    hvers,
    hcount,
    oldest,
    r_begin,
    r_end,
    r_txn,
    r_snap,
    w_begin,
    w_end,
    w_txn,
    t_snap,
    t_has_reads,
    t_valid,
    now_rel,
    new_oldest_rel,
    do_evict=None,
    *,
    txn_cap: int,
    rr_cap: int,
    wr_cap: int,
    h_cap: int,
    kernels: bool = False,
    kernel_interpret: bool = False,
    undecided_combine=None,
    witness: bool = False,
    witness_combine=None,
):
    from ..flow.knobs import g_env

    _ablate = set(g_env.get("FDB_TPU_ABLATE").split(","))
    # The in-step kernel ablation arm (phase_attribution's `nokernel`):
    # price the Pallas kernels against the XLA fallback INSIDE the same
    # program, never as a standalone microbench.
    _kern = kernels and "nokernel" not in _ablate
    kw1 = hkeys.shape[0]
    H = h_cap
    TXN, RR, WR = txn_cap, rr_cap, wr_cap
    P = 2 * RR + 2 * WR
    p_log2 = max(1, math.ceil(math.log2(P)))

    r_nonempty = lex_less(r_begin, r_end)
    r_valid = r_txn < TXN

    # ---- phase 1: history conflicts (ref checkReadConflictRanges) ----
    if "nosearch" in _ablate:
        i0 = (r_begin[0] % jnp.uint32(H)).astype(jnp.int32)
        j1 = i0
    elif _kern:
        from .kernels import phase1_search

        i0, j1 = phase1_search(hkeys, r_begin, r_end,
                               interpret=kernel_interpret)
    else:
        i0 = searchsorted_words(hkeys, r_begin, "right") - 1
        j1 = searchsorted_words(hkeys, r_end, "left") - 1
    maxtab = build_max_table(hvers)
    m = range_max(maxtab, jnp.clip(i0, 0, H - 1), jnp.clip(j1, 0, H - 1))
    r_hist = r_valid & r_nonempty & (j1 >= i0) & (m > r_snap)
    hist_conf = (
        jnp.zeros((TXN + 1,), bool)
        .at[jnp.where(r_hist, r_txn, TXN)]
        .max(r_hist)[:TXN]
    )
    too_old = t_valid & t_has_reads & (t_snap < oldest)

    # ---- phases 2-4: point domain, fixpoint, committed segments ----
    status0 = jnp.where(
        ~t_valid, _COMM, jnp.where(too_old | hist_conf, _CONF, _UNDECIDED)
    ).astype(jnp.int32)
    status, iters, undecided_left, ub, ue, seg_valid, nseg, ib_flag = (
        _resolve_batch(
            r_begin, r_end, r_txn, w_begin, w_end, w_txn, t_valid, status0,
            txn_cap=TXN, rr_cap=RR, wr_cap=WR, ablate=_ablate,
            witness=witness,
        )
    )
    if undecided_combine is not None:
        # Cross-shard convergence gate (ISSUE 15): under shard_map the
        # caller combines every ACTIVE shard's undecided count (psum), so
        # the divergence revert below is all-or-nothing across the mesh —
        # the host then re-decides the whole batch on the per-shard
        # mirrors consistently.  None (single device) leaves the traced
        # program byte-identical to the pre-hook compile.
        undecided_left = undecided_combine(undecided_left)

    w_extra = _witness_vectors(
        m, r_hist, hist_conf, ib_flag, r_txn, t_valid, too_old, status,
        now_rel, txn_cap=TXN, rr_cap=RR, witness=witness,
        witness_combine=witness_combine,
    )

    # ---- phase 5: rewrite the step function (ref addConflictRanges) ----
    if "nomerge" in _ablate:
        out_status = jnp.where(
            too_old, TOO_OLD, jnp.where(status == _COMM, COMMITTED, CONFLICT)
        ).astype(jnp.int32)
        return (hkeys, hvers, hcount, jnp.maximum(oldest, new_oldest_rel).astype(jnp.int32),
                out_status, undecided_left.astype(jnp.int32), iters) + w_extra
    new_oldest = jnp.maximum(oldest, new_oldest_rel)
    if _kern:
        # Fused kernel arm: merge + evict + compact in one streaming
        # pass.  The amortized-eviction traced cond collapses into a
        # window-value select (window = FLOOR_REL means "evict nothing"
        # — every version is >= the floor, so the rule keeps all rows).
        if "noevict" in _ablate:
            window = jnp.int32(FLOOR_REL)
        elif do_evict is not None:
            window = jnp.where(
                do_evict != 0, new_oldest, jnp.int32(FLOOR_REL)
            ).astype(jnp.int32)
        else:
            window = new_oldest.astype(jnp.int32)
        out_keys, out_vers, out_count = _merge_evict_fused(
            hkeys, hvers, hcount, ub, ue, seg_valid, nseg, now_rel,
            window, width=H, wr_cap=WR, kw1=kw1,
            interpret=kernel_interpret,
        )
        return _finish_flat(
            hkeys, hvers, hcount, oldest, out_keys, out_vers, out_count,
            new_oldest, too_old, status, undecided_left, iters,
        ) + w_extra
    merged_keys, merged_vers, merged_count = _merge_new_segments(
        hkeys, hvers, hcount, ub, ue, seg_valid, nseg, now_rel,
        width=H, wr_cap=WR, kw1=kw1,
    )

    # ---- phase 6: window eviction (ref removeBefore wasAbove rule) ----
    keep2, rank2, out_count = _evict_rule(merged_vers, merged_count,
                                          new_oldest, H)
    if "noevict" in _ablate:
        out_keys, out_vers, out_count = merged_keys, merged_vers, merged_count
    elif do_evict is not None:
        # Amortized eviction (perf experiment; decisions identical —
        # stale sub-window rows can never flip a verdict because any
        # snapshot that could see them is already TOO_OLD): the compaction
        # sort runs only when the traced flag says so, at the cost of
        # h_cap headroom for the unevicted batches in between.
        def _evict(ops):
            mk, mv = ops
            k, v = _compact_to(
                rank2, keep2, mk, H,
                fill_vers=jnp.int32(FLOOR_REL), vers=mv, count=out_count,
            )
            return k, v, out_count.astype(jnp.int32)

        def _keep(ops):
            mk, mv = ops
            return mk, mv, merged_count.astype(jnp.int32)

        out_keys, out_vers, out_count = jax.lax.cond(
            do_evict != 0, _evict, _keep, (merged_keys, merged_vers)
        )
    else:
        out_keys, out_vers = _compact_to(
            rank2,
            keep2,
            merged_keys,
            H,
            fill_vers=jnp.int32(FLOOR_REL),
            vers=merged_vers,
            count=out_count,
        )

    return _finish_flat(
        hkeys, hvers, hcount, oldest, out_keys, out_vers, out_count,
        new_oldest, too_old, status, undecided_left, iters,
    ) + w_extra


# ---------------------------------------------------------------------------
# Two-tier history (FDB_TPU_HISTORY=tiered): a large sorted BASE tier that is
# FROZEN between major compactions (its sparse max-table is carried across
# batches instead of rebuilt), plus a small sorted DELTA tier that absorbs
# each batch's new boundaries with delta-sized sorts.  The delta is a step
# function whose floor value FLOOR_REL means "uncovered"; because every
# >floor delta value is a write version issued while the base was frozen, it
# exceeds every base value, so the logical history is exactly
#
#     merged(x) = max(base(x), delta(x))
#
# and phase-1 range-max queries combine per-tier answers with max.  Phase 5
# merges each batch's segments into the DELTA ONLY (end values come from the
# delta itself — on covered intervals the base is already dominated), so the
# two full-H compact_to sorts PERF_NOTES round-5 names are gone from the
# per-batch path.  A major compaction — merge base+delta, evict sub-window
# rows, rebuild the max-table, reset the delta — runs behind a traced
# lax.cond when the host says so (delta fills, or every FDB_TPU_EVICT_EVERY
# batches: the flag is an alias for the compaction cadence in tiered mode).
# The trigger is computed host-side from deterministic row-count bounds, so
# no device sync is needed and replays stay bit-identical.
# ---------------------------------------------------------------------------


def _major_compact(hk, hv, hc, dk, dv, dc, new_oldest, *, H, D, kw1,
                   kernels: bool = False, kernel_interpret: bool = False):
    """Merge base+delta into a new base tier and evict sub-window rows.

    Covered delta intervals (value > floor) take the delta row verbatim and
    drop every base row inside them; uncovered intervals keep their base
    rows; a floor-valued delta row re-anchors the base's value at its key
    (dropped when an equal-key base row already provides it).  All per-row
    quantities derive by rank inversion — delta-sized searches into the
    base turned into per-base-row values with histograms + cumsums, never
    one query per history row — so the only H-sized non-streaming ops are
    the two compact_to sorts whose amortization is this tier's purpose;
    under FDB_TPU_KERNELS even those collapse into ONE streaming pass of
    the fused merge-evict kernel (conflict/kernels.py)."""
    NEG = jnp.int32(FLOOR_REL)
    dvalid = jnp.arange(D, dtype=jnp.int32) < dc
    dl = searchsorted_words(hk, dk, "left")
    dr = searchsorted_words(hk, dk, "right")
    covered = dvalid & (dv > NEG)
    # Delta interval j spans base ranks [dl[j], dl[j+1]); the last valid
    # row's interval extends to the end of the live base.
    dl_next = jnp.concatenate([dl[1:], jnp.reshape(hc.astype(jnp.int32), (1,))])
    cov_diff = (
        jnp.zeros((H + 1,), jnp.int32)
        .at[jnp.where(covered, dl, H)]
        .add(jnp.where(covered, 1, 0))
        .at[jnp.where(covered, dl_next, H)]
        .add(jnp.where(covered, -1, 0))
    )
    in_cov = jnp.cumsum(cov_diff[:H]) > 0
    base_valid = jnp.arange(H, dtype=jnp.int32) < hc
    keep_base = base_valid & ~in_cov
    ckb = jnp.cumsum(keep_base.astype(jnp.int32))  # prefix-inclusive

    eq = (dr - dl) > 0  # an equal-key base row exists
    base_at = hv[jnp.clip(dr - 1, 0, H - 1)]  # base value at dk[j]
    is_end = dvalid & (dv == NEG)
    keep_delta = dvalid & ((dv > NEG) | ~eq)
    dvals = jnp.where(is_end, base_at, dv)

    # Merge positions by rank inversion (kept keys never tie: the eq rules
    # above drop exactly one side of every key collision).
    dhist = (
        jnp.zeros((H + 1,), jnp.int32)
        .at[jnp.where(keep_delta, dl, H)]
        .add(jnp.where(keep_delta, 1, 0))
    )
    cnt_delta_leq = jnp.cumsum(dhist[:H])
    pos_base = (ckb - 1) + cnt_delta_leq
    cnt_base_less = jnp.where(dl > 0, ckb[jnp.clip(dl - 1, 0, H - 1)], 0)
    pos_delta = (jnp.cumsum(keep_delta.astype(jnp.int32)) - 1) + cnt_base_less
    merged_count = (jnp.sum(keep_base, dtype=jnp.int32)
                    + jnp.sum(keep_delta, dtype=jnp.int32))
    if kernels:
        from .kernels import fused_merge_evict

        k_keys, k_vers, out_count = fused_merge_evict(
            hk, hv, keep_base, pos_base,
            dk, dvals, keep_delta, pos_delta,
            merged_count, new_oldest.astype(jnp.int32),
            width=H, kw1=kw1, interpret=kernel_interpret,
        )
        inf32 = jnp.uint32(keylib.INF_WORD)
        live = jnp.arange(H, dtype=jnp.int32) < out_count
        ok_keys = jnp.where(live[None, :], k_keys, inf32)
        ok_vers = jnp.where(live, k_vers, NEG)
        return ok_keys, ok_vers, out_count.astype(jnp.int32)
    mk, mv = _compact_to(
        jnp.concatenate([pos_base, pos_delta]),
        jnp.concatenate([keep_base, keep_delta]),
        jnp.concatenate([hk, dk], axis=1),
        H,
        fill_vers=NEG,
        vers=jnp.concatenate([hv, dvals]),
        count=merged_count,
    )
    keep2, rank2, out_count = _evict_rule(mv, merged_count, new_oldest, H)
    ok_keys, ok_vers = _compact_to(
        rank2, keep2, mk, H, fill_vers=NEG, vers=mv, count=out_count
    )
    return ok_keys, ok_vers, out_count


def detect_core_tiered(
    hkeys,
    hvers,
    hcount,
    maxtab,
    dkeys,
    dvers,
    dcount,
    oldest,
    r_begin,
    r_end,
    r_txn,
    r_snap,
    w_begin,
    w_end,
    w_txn,
    t_snap,
    t_has_reads,
    t_valid,
    now_rel,
    new_oldest_rel,
    do_major,
    *,
    txn_cap: int,
    rr_cap: int,
    wr_cap: int,
    h_cap: int,
    d_cap: int,
    kernels: bool = False,
    kernel_interpret: bool = False,
    undecided_combine=None,
    witness: bool = False,
    witness_combine=None,
):
    """Two-tier variant of detect_core; decision-identical by construction
    (gated by the differential suites under FDB_TPU_HISTORY=tiered).

    Steady-state non-compaction batches do NO H-sized sort and NO H-sized
    table build: base work is limited to the phase-1 binary-search gathers
    against the frozen base + carried max-table (the perf_smoke jaxpr gate
    pins this structurally).  Under FDB_TPU_KERNELS the phase-1 searches
    run tier-combined through the streaming Pallas kernel and the
    delta-merge/compaction sorts through the fused merge-evict kernel —
    NO sort-by-target pass at any tier width remains anywhere in the
    program (perf_smoke pins that too)."""
    kw1 = hkeys.shape[0]
    H, D = h_cap, d_cap
    TXN = txn_cap
    WR = wr_cap
    NEG = jnp.int32(FLOOR_REL)

    r_nonempty = lex_less(r_begin, r_end)
    r_valid = r_txn < TXN

    # ---- phase 1 over BOTH tiers: merged max = max of per-tier maxes ----
    if kernels:
        from .kernels import phase1_search_tiers

        # Tier-combined: both tiers' streaming searches share ONE
        # query sort and ONE un-permute sort (phase1_search_tiers).
        (i0b, j1b), (i0d, j1d) = phase1_search_tiers(
            (hkeys, dkeys), r_begin, r_end, interpret=kernel_interpret
        )
    else:
        i0b = searchsorted_words(hkeys, r_begin, "right") - 1
        j1b = searchsorted_words(hkeys, r_end, "left") - 1
        i0d = searchsorted_words(dkeys, r_begin, "right") - 1
        j1d = searchsorted_words(dkeys, r_end, "left") - 1
    mb = range_max(maxtab, jnp.clip(i0b, 0, H - 1), jnp.clip(j1b, 0, H - 1))
    dtab = build_max_table(dvers)
    md = range_max(dtab, jnp.clip(i0d, 0, D - 1), jnp.clip(j1d, 0, D - 1))
    m = jnp.maximum(
        jnp.where(j1b >= i0b, mb, NEG), jnp.where(j1d >= i0d, md, NEG)
    )
    r_hist = r_valid & r_nonempty & (m > r_snap)
    hist_conf = (
        jnp.zeros((TXN + 1,), bool)
        .at[jnp.where(r_hist, r_txn, TXN)]
        .max(r_hist)[:TXN]
    )
    too_old = t_valid & t_has_reads & (t_snap < oldest)

    # ---- phases 2-4 (shared) ----
    status0 = jnp.where(
        ~t_valid, _COMM, jnp.where(too_old | hist_conf, _CONF, _UNDECIDED)
    ).astype(jnp.int32)
    status, iters, undecided_left, ub, ue, seg_valid, nseg, ib_flag = (
        _resolve_batch(
            r_begin, r_end, r_txn, w_begin, w_end, w_txn, t_valid, status0,
            txn_cap=txn_cap, rr_cap=rr_cap, wr_cap=wr_cap, witness=witness,
        )
    )
    if undecided_combine is not None:
        # Cross-shard convergence gate (ISSUE 15; see detect_core): the
        # revert below — which runs BEFORE the compaction cond, so a
        # compaction still rewrites the reverted delta physically —
        # becomes all-or-nothing across the mesh's active shards.
        undecided_left = undecided_combine(undecided_left)

    w_extra = _witness_vectors(
        m, r_hist, hist_conf, ib_flag, r_txn, t_valid, too_old, status,
        now_rel, txn_cap=TXN, rr_cap=rr_cap, witness=witness,
        witness_combine=witness_combine,
    )

    # ---- phase 5 into the DELTA tier (delta-sized sorts, or ONE
    # delta-sized streaming pass under FDB_TPU_KERNELS) + phase 6 on the
    # delta only (keeps hot-key deltas compact); the base is evicted at
    # major compactions ----
    new_oldest = jnp.maximum(oldest, new_oldest_rel)
    if kernels:
        d_ok_keys, d_ok_vers, d_oc = _merge_evict_fused(
            dkeys, dvers, dcount, ub, ue, seg_valid, nseg, now_rel,
            new_oldest.astype(jnp.int32),
            width=D, wr_cap=WR, kw1=kw1, interpret=kernel_interpret,
        )
    else:
        d_mk, d_mv, d_mc = _merge_new_segments(
            dkeys, dvers, dcount, ub, ue, seg_valid, nseg, now_rel,
            width=D, wr_cap=WR, kw1=kw1,
        )
        keep2, rank2, d_oc = _evict_rule(d_mv, d_mc, new_oldest, D)
        d_ok_keys, d_ok_vers = _compact_to(
            rank2, keep2, d_mk, D, fill_vers=NEG, vers=d_mv, count=d_oc
        )

    ok = undecided_left == 0

    # Divergence guard (same contract as detect_core): the batch's delta
    # merge and the window advance revert BEFORE the compaction cond, so
    # the host can re-run the batch on the CPU engine against the same
    # logical state.
    d_sel_keys = jnp.where(ok, d_ok_keys, dkeys)
    d_sel_vers = jnp.where(ok, d_ok_vers, dvers)
    d_sel_count = jnp.where(ok, d_oc.astype(jnp.int32), dcount)
    new_oldest = jnp.where(ok, new_oldest, oldest)

    # ---- major compaction behind a traced cond ----
    # The predicate is the HOST's flag alone — never the traced ok — so
    # the host's deterministic bookkeeping (delta bound reset to 1, base
    # bound absorbing the delta, major_compactions count) is true even
    # for a diverged batch: compacting the REVERTED pre-batch delta into
    # the base is a pure physical rewrite of the same logical step
    # function (merged(x) is unchanged), so verdict-identity and the
    # CPU-fallback export both hold.
    def _major(ops):
        hk, hv, hc, mt, dk2, dv2, dc2 = ops
        nk, nv, nc = _major_compact(
            hk, hv, hc, dk2, dv2, dc2, new_oldest, H=H, D=D, kw1=kw1,
            kernels=kernels, kernel_interpret=kernel_interpret,
        )
        nt = build_max_table(nv)
        ek = (
            jnp.full((kw1, D), jnp.uint32(keylib.INF_WORD))
            .at[:, 0]
            .set(jnp.uint32(0))
        )
        ev = jnp.full((D,), FLOOR_REL, jnp.int32)
        return nk, nv, nc.astype(jnp.int32), nt, ek, ev, jnp.ones((), jnp.int32)

    def _minor(ops):
        hk, hv, hc, mt, dk2, dv2, dc2 = ops
        return hk, hv, hc, mt, dk2, dv2, dc2

    out_hk, out_hv, out_hc, out_mt, out_dk, out_dv, out_dc = jax.lax.cond(
        do_major != 0,
        _major,
        _minor,
        (hkeys, hvers, hcount.astype(jnp.int32), maxtab,
         d_sel_keys, d_sel_vers, d_sel_count),
    )

    # ---- final statuses in the reference's enum ----
    out_status = jnp.where(
        too_old,
        TOO_OLD,
        jnp.where(status == _COMM, COMMITTED, CONFLICT),
    ).astype(jnp.int32)

    return (
        out_hk,
        out_hv,
        out_hc.astype(jnp.int32),
        out_mt,
        out_dk,
        out_dv,
        out_dc.astype(jnp.int32),
        new_oldest.astype(jnp.int32),
        out_status,
        undecided_left.astype(jnp.int32),
        iters,
    ) + w_extra


# NOTE detect_core stays undecorated so the sharded resolver
# (parallel/sharded_resolver.py) can call it inside shard_map with
# per-shard clipped inputs; the jitted single-device entries are the blob
# steps below (the old `_detect_step` alias was dead code and is gone).


# ---------------------------------------------------------------------------
# Carried-state maintenance bodies.  These used to be eager jnp ops on the
# host wrapper; as jitted, registered entry points they are (a) donation-
# audited by jaxcheck (JXP003 — rebase now reuses the carried buffer in
# place instead of holding old + temp + new H-sized arrays live at once,
# the HBM-doubling class) and (b) fingerprinted, so a change to their
# compiled shape shows up in the committed baseline diff like any other
# device program.
# ---------------------------------------------------------------------------


def _rebase_core(vers, d):
    """Window rebase: shift a carried version array down by `d`, clamping
    at the floor.  Rebase commutes with max, so one body serves hvers,
    the delta tier, and the carried max-table."""
    return jnp.maximum(vers - d, FLOOR_REL)


_rebase_step = partial(jax.jit, donate_argnames=("vers",))(_rebase_core)


def _grow_core(buf, *, pad, fill):
    """Capacity growth: extend a carried array's minor axis by `pad`
    sentinel-filled columns.  XLA cannot alias a donated buffer into an
    output of a different shape, so the input is deliberately NOT donated
    — the transient old+new residency is inherent to reallocation (see
    the jaxcheck pragma at the registration builder)."""
    ext = jnp.full(buf.shape[:-1] + (pad,), fill, buf.dtype)
    return jnp.concatenate([buf, ext], axis=-1)


_grow_step = partial(jax.jit, static_argnames=("pad", "fill"))(_grow_core)


def _blob_offsets(txn_cap: int, rr_cap: int, wr_cap: int, kw1: int):
    """Field offsets (in uint32 words) of the single-transfer batch blob.

    One contiguous host->device copy per batch instead of ~12: the axon/PCIe
    path has a large per-transfer fixed cost (measured ~136ms for a dozen
    small arrays on this host vs ~20ms for one blob)."""
    sizes = [
        rr_cap * kw1,  # r_begin
        rr_cap * kw1,  # r_end
        wr_cap * kw1,  # w_begin
        wr_cap * kw1,  # w_end
        rr_cap,  # r_txn (i32)
        rr_cap,  # r_snap_rel (i32)
        wr_cap,  # w_txn (i32)
        txn_cap,  # t_snap_rel (i32)
        txn_cap,  # t_flags (bit0 has_reads, bit1 valid)
        3,  # now_rel, new_oldest_rel, do_evict (i32)
    ]
    offs, o = [], 0
    for s in sizes:
        offs.append(o)
        o += s
    return offs, o


def _blob_core(hkeys, hvers, hcount, oldest, blob, *, txn_cap, rr_cap,
               wr_cap, h_cap, kw1, amortized=False, kernels=False,
               kernel_interpret=False, witness=False):
    offs, _total = _blob_offsets(txn_cap, rr_cap, wr_cap, kw1)
    as_i32 = lambda x: jax.lax.bitcast_convert_type(x, jnp.int32)
    # Key fields are packed word-major (kw1, N): see rangequery.py on TPU
    # minor-dim tiling.
    r_begin = blob[offs[0] : offs[0] + rr_cap * kw1].reshape(kw1, rr_cap)
    r_end = blob[offs[1] : offs[1] + rr_cap * kw1].reshape(kw1, rr_cap)
    w_begin = blob[offs[2] : offs[2] + wr_cap * kw1].reshape(kw1, wr_cap)
    w_end = blob[offs[3] : offs[3] + wr_cap * kw1].reshape(kw1, wr_cap)
    r_txn = as_i32(blob[offs[4] : offs[4] + rr_cap])
    r_snap = as_i32(blob[offs[5] : offs[5] + rr_cap])
    w_txn = as_i32(blob[offs[6] : offs[6] + wr_cap])
    t_snap = as_i32(blob[offs[7] : offs[7] + txn_cap])
    t_flags = blob[offs[8] : offs[8] + txn_cap]
    t_has_reads = (t_flags & 1) > 0
    t_valid = (t_flags & 2) > 0
    scalars = as_i32(blob[offs[9] : offs[9] + 3])
    return detect_core(
        hkeys, hvers, hcount, oldest,
        r_begin, r_end, r_txn, r_snap,
        w_begin, w_end, w_txn,
        t_snap, t_has_reads, t_valid,
        scalars[0], scalars[1],
        # Amortized-eviction experiment: the traced flag only enters the
        # graph when enabled, so the default compile is byte-identical.
        scalars[2] if amortized else None,
        txn_cap=txn_cap, rr_cap=rr_cap, wr_cap=wr_cap, h_cap=h_cap,
        kernels=kernels, kernel_interpret=kernel_interpret,
        witness=witness,
    )


_blob_step = partial(
    jax.jit,
    static_argnames=("txn_cap", "rr_cap", "wr_cap", "h_cap", "kw1",
                     "amortized", "kernels", "kernel_interpret", "witness"),
    donate_argnames=("hkeys", "hvers", "hcount", "oldest"),
)(_blob_core)

# Non-donated twins (ISSUE 11): identical jaxpr, XLA just cannot alias
# the carried inputs into the outputs.  Donation stays the contract on
# real accelerators (HBM is scarce; jaxcheck's JXP003 audit + the
# committed fingerprints pin it on the DEVICE_ENTRY_POINTS wrappers
# above) — but jaxlib's CPU runtime executes donated programs
# SYNCHRONOUSLY (the dispatch blocks for the whole step, measured
# ~full-step wall on jax 0.4.37), which would serialize the resolver
# pipeline's dispatch and erase the mirror-apply/encode overlap.  The
# CPU backend therefore dispatches through these twins; see
# _use_donated_steps / FDB_TPU_DONATE.
_blob_step_nodonate = partial(
    jax.jit,
    static_argnames=("txn_cap", "rr_cap", "wr_cap", "h_cap", "kw1",
                     "amortized", "kernels", "kernel_interpret", "witness"),
)(_blob_core)


def _tiered_blob_core(hkeys, hvers, hcount, maxtab, dkeys, dvers, dcount,
                      oldest, blob, *, txn_cap, rr_cap, wr_cap, h_cap, d_cap,
                      kw1, kernels=False, kernel_interpret=False,
                      witness=False):
    """Tiered twin of _blob_core: same single-transfer blob layout; the
    third scalar slot carries the host's major-compaction decision."""
    offs, _total = _blob_offsets(txn_cap, rr_cap, wr_cap, kw1)
    as_i32 = lambda x: jax.lax.bitcast_convert_type(x, jnp.int32)
    r_begin = blob[offs[0] : offs[0] + rr_cap * kw1].reshape(kw1, rr_cap)
    r_end = blob[offs[1] : offs[1] + rr_cap * kw1].reshape(kw1, rr_cap)
    w_begin = blob[offs[2] : offs[2] + wr_cap * kw1].reshape(kw1, wr_cap)
    w_end = blob[offs[3] : offs[3] + wr_cap * kw1].reshape(kw1, wr_cap)
    r_txn = as_i32(blob[offs[4] : offs[4] + rr_cap])
    r_snap = as_i32(blob[offs[5] : offs[5] + rr_cap])
    w_txn = as_i32(blob[offs[6] : offs[6] + wr_cap])
    t_snap = as_i32(blob[offs[7] : offs[7] + txn_cap])
    t_flags = blob[offs[8] : offs[8] + txn_cap]
    t_has_reads = (t_flags & 1) > 0
    t_valid = (t_flags & 2) > 0
    scalars = as_i32(blob[offs[9] : offs[9] + 3])
    return detect_core_tiered(
        hkeys, hvers, hcount, maxtab, dkeys, dvers, dcount, oldest,
        r_begin, r_end, r_txn, r_snap,
        w_begin, w_end, w_txn,
        t_snap, t_has_reads, t_valid,
        scalars[0], scalars[1], scalars[2],
        txn_cap=txn_cap, rr_cap=rr_cap, wr_cap=wr_cap, h_cap=h_cap,
        d_cap=d_cap, kernels=kernels, kernel_interpret=kernel_interpret,
        witness=witness,
    )


_tiered_blob_step = partial(
    jax.jit,
    static_argnames=("txn_cap", "rr_cap", "wr_cap", "h_cap", "d_cap", "kw1",
                     "kernels", "kernel_interpret", "witness"),
    donate_argnames=("hkeys", "hvers", "hcount", "maxtab", "dkeys", "dvers",
                     "dcount", "oldest"),
)(_tiered_blob_core)

_tiered_blob_step_nodonate = partial(
    jax.jit,
    static_argnames=("txn_cap", "rr_cap", "wr_cap", "h_cap", "d_cap", "kw1",
                     "kernels", "kernel_interpret", "witness"),
)(_tiered_blob_core)


def _use_donated_steps() -> bool:
    """Whether runtime dispatch goes through the donated step wrappers.
    FDB_TPU_DONATE=1 forces donation, =0 forces the non-donated twins,
    default '' is platform-auto: donate everywhere except the CPU
    backend, whose runtime turns donated dispatch synchronous (see the
    _blob_step_nodonate comment).  Decision-identical either way."""
    from ..flow.knobs import g_env

    flag = g_env.get("FDB_TPU_DONATE")
    if flag == "1":
        return True
    if flag == "0":
        return False
    return jax.default_backend() != "cpu"


# ---------------------------------------------------------------------------
# Device entry-point registry (jaxcheck, tools/lint/jaxir.py).  Every jitted
# program that runs against carried engine state registers here with enough
# metadata to be traced ON CPU (no device needed), statically audited
# (JXP001-005: H-sized work placement, host transfers, donation, dtype
# widenings, shape bucketing) and structurally fingerprinted against the
# committed baselines in tests/jax_fingerprints/.  Registration records a
# BUILDER and is free at import; tracing happens only when the analysis
# asks for it.
# ---------------------------------------------------------------------------

# Canonical trace shapes for registered entry points: modest, CPU-traceable,
# H strictly above every batch-domain dim so size-classing is unambiguous.
# Tracing cost depends on graph size, not these values.
EP_TXN, EP_RR, EP_WR = 32, 128, 64
EP_H, EP_D, EP_KW1 = 4096, 256, 4
EP_BUCKET_MIN = 8  # PackedBatch bucket floor (bucket_mins default)


class DeviceEntryPoint:
    """One registered device program.

    `builder() -> (fn, jitted_or_None, example_args, static_kwargs)`:
    `fn` is the UNJITTED callable (make_jaxpr), `jitted` the real jit
    wrapper whose lowering is the donation ground truth (None for bodies
    that only run inside another entry, e.g. the compaction body).

    The static contract jaxcheck enforces:
      carried           arg names of mutable carried state: MUST be donated
      pinned            arg names of carried read-only state (reused next
                        step): must NOT be donated
      size_classes      ((name, threshold) descending) for the fingerprint
                        histogram's size-class axis
      h_threshold       the "H-sized" line for JXP001/JXP004
      compaction_gated  True: work prims >= h_threshold must live inside a
                        lax.cond branch (the tiered steady-state bound)
      work_bound        max legitimate work-prim operand dim anywhere
                        (catches per-shard code touching global-width data)
      bucket_dims       {name: (value, pow2_floor)} static dims that form
                        the jit cache key — JXP005 rejects un-bucketed ones

    Findings attach to the builder's def lines, so a
    `# jaxcheck: ignore[JXP...]: reason` pragma anywhere on the builder
    suppresses for exactly that one entry.
    """

    def __init__(self, name: str, builder: Callable, *, arg_names,
                 carried=(), pinned=(), size_classes, h_threshold: int,
                 compaction_gated: bool = False, work_bound=None,
                 bucket_dims=None):
        self.name = name
        self.builder = builder
        self.arg_names = tuple(arg_names)
        self.carried = tuple(carried)
        self.pinned = tuple(pinned)
        self.size_classes = tuple(size_classes)
        self.h_threshold = h_threshold
        self.compaction_gated = compaction_gated
        self.work_bound = work_bound
        self.bucket_dims = dict(bucket_dims or {})
        src = inspect.getsourcefile(builder) or "<unknown>"
        try:
            lines, lineno = inspect.getsourcelines(builder)
        except OSError:
            lines, lineno = [], 0
        pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        rel = os.path.relpath(os.path.abspath(src), pkg_dir)
        self.path = (
            rel.replace(os.sep, "/")
            if not rel.startswith("..")
            else os.path.abspath(src).replace(os.sep, "/")
        )
        self.line = lineno
        self.end_line = lineno + max(0, len(lines) - 1)
        self._built = None
        self._jaxpr = None
        self._jaxpr_x64 = None
        self._donation = None

    def built(self):
        if self._built is None:
            self._built = self.builder()
        return self._built

    def jaxpr(self):
        if self._jaxpr is None:
            fn, _jitted, args, statics = self.built()
            self._jaxpr = jax.make_jaxpr(partial(fn, **statics))(*args)
        return self._jaxpr

    def jaxpr_x64(self):
        """Re-trace under enable_x64 — the widening audit's (JXP004) view:
        dtype-less index math that silently stays 32-bit in the default
        config widens H-sized buffers to 64-bit here."""
        if self._jaxpr_x64 is None:
            fn, _jitted, args, statics = self.built()
            with jax.experimental.enable_x64():
                self._jaxpr_x64 = jax.make_jaxpr(partial(fn, **statics))(*args)
        return self._jaxpr_x64

    def arg_nbytes(self) -> Dict[str, int]:
        """arg name -> buffer bytes, computed from the example args'
        shapes/dtypes (no trace, no compile — pure shape math, so the
        perf_smoke pin can check it against h_cap/d_cap arithmetic on
        CPU)."""
        _fn, _jitted, args, _statics = self.built()
        leaves = jax.tree_util.tree_leaves(args)
        assert len(leaves) == len(self.arg_names), (
            self.name, len(leaves), self.arg_names)
        return {
            n: int(np.prod(x.shape, dtype=np.int64))
            * np.dtype(x.dtype).itemsize
            for n, x in zip(self.arg_names, leaves)
        }

    def carried_bytes(self) -> Dict[str, int]:
        """Per-buffer bytes of the CARRIED (device-resident across steps)
        state — the HBM footprint ROADMAP item 1's Pallas kernels will be
        judged against."""
        sizes = self.arg_nbytes()
        return {n: sizes[n] for n in self.carried}

    def donation(self) -> Optional[Dict[str, bool]]:
        """arg name -> donated, read from the ACTUAL jit wrapper's lowering
        (ground truth, not a redeclaration); None when there is no jit
        wrapper of its own."""
        if self._donation is None:
            import warnings

            _fn, jitted, args, statics = self.built()
            if jitted is None:
                return None
            with warnings.catch_warnings():
                # A mis-donated program is exactly what the audit reports
                # as a JXP003 finding; jax's own donation UserWarning
                # during this analysis lowering is duplicate noise.
                warnings.simplefilter("ignore")
                lowered = jitted.lower(*args, **statics)
            leaves = jax.tree_util.tree_leaves(lowered.args_info)
            assert len(leaves) == len(self.arg_names), (
                self.name, len(leaves), self.arg_names)
            self._donation = {
                n: bool(info.donated)
                for n, info in zip(self.arg_names, leaves)
            }
        return self._donation


DEVICE_ENTRY_POINTS: Dict[str, DeviceEntryPoint] = {}


def register_entry_point(name: str, builder: Callable, *, registry=None,
                         **meta) -> DeviceEntryPoint:
    ep = DeviceEntryPoint(name, builder, **meta)
    (DEVICE_ENTRY_POINTS if registry is None else registry)[name] = ep
    return ep


def _ep_blob_sds():
    _offs, total = _blob_offsets(EP_TXN, EP_RR, EP_WR, EP_KW1)
    return jax.ShapeDtypeStruct((total,), jnp.uint32)


def _ep_flat_step():
    sds = jax.ShapeDtypeStruct
    args = (
        sds((EP_KW1, EP_H), jnp.uint32),   # hkeys
        sds((EP_H,), jnp.int32),           # hvers
        sds((), jnp.int32),                # hcount
        sds((), jnp.int32),                # oldest
        _ep_blob_sds(),                    # blob
    )
    # witness=True is the canonical trace: FDB_TPU_WITNESS defaults on,
    # so the committed fingerprints pin the witness-emitting program.
    statics = dict(txn_cap=EP_TXN, rr_cap=EP_RR, wr_cap=EP_WR, h_cap=EP_H,
                   kw1=EP_KW1, amortized=False, witness=True)
    return _blob_core, _blob_step, args, statics


def _ep_tiered_step():
    sds = jax.ShapeDtypeStruct
    lmax = max(1, math.ceil(math.log2(EP_H)))
    args = (
        sds((EP_KW1, EP_H), jnp.uint32),       # hkeys
        sds((EP_H,), jnp.int32),               # hvers
        sds((), jnp.int32),                    # hcount
        sds((lmax + 1, EP_H), jnp.int32),      # maxtab (carried)
        sds((EP_KW1, EP_D), jnp.uint32),       # dkeys
        sds((EP_D,), jnp.int32),               # dvers
        sds((), jnp.int32),                    # dcount
        sds((), jnp.int32),                    # oldest
        _ep_blob_sds(),                        # blob
    )
    statics = dict(txn_cap=EP_TXN, rr_cap=EP_RR, wr_cap=EP_WR, h_cap=EP_H,
                   d_cap=EP_D, kw1=EP_KW1, witness=True)
    return _tiered_blob_core, _tiered_blob_step, args, statics


def _ep_compact_body():
    sds = jax.ShapeDtypeStruct
    args = (
        sds((EP_KW1, EP_H), jnp.uint32), sds((EP_H,), jnp.int32),
        sds((), jnp.int32),
        sds((EP_KW1, EP_D), jnp.uint32), sds((EP_D,), jnp.int32),
        sds((), jnp.int32),
        sds((), jnp.int32),               # new_oldest
    )
    return _major_compact, None, args, dict(H=EP_H, D=EP_D, kw1=EP_KW1)


def _ep_rebase_body():
    sds = jax.ShapeDtypeStruct
    return _rebase_core, _rebase_step, (
        sds((EP_H,), jnp.int32), sds((), jnp.int32)), {}


def _ep_grow_body():  # jaxcheck: ignore[JXP003]: growth reallocates to a larger shape — XLA cannot alias donated buffers across shapes, so the transient old+new residency is inherent to _grow
    sds = jax.ShapeDtypeStruct
    return _grow_core, _grow_step, (
        sds((EP_KW1, EP_H), jnp.uint32),), dict(pad=EP_H,
                                                fill=int(keylib.INF_WORD))


def _ep_flat_step_kernels():
    """Kernelized flat step (FDB_TPU_KERNELS): same signature, the
    merge/evict sorts and phase-1 searches replaced by the Pallas
    kernels.  Canonically traced in interpret mode (CPU analysis; on a
    real TPU only the pallas_call params differ, never the structure)."""
    fn, _jitted, args, statics = _ep_flat_step()
    statics = dict(statics, kernels=True, kernel_interpret=True)
    return fn, _blob_step, args, statics


def _ep_tiered_step_kernels():
    """Kernelized tiered step: delta merges and the in-cond major
    compaction run through the fused merge-evict kernel, phase 1 through
    the tier-combined streaming search kernel."""
    fn, _jitted, args, statics = _ep_tiered_step()
    statics = dict(statics, kernels=True, kernel_interpret=True)
    return fn, _tiered_blob_step, args, statics


_EP_BUCKETS = {
    "txn_cap": (EP_TXN, EP_BUCKET_MIN),
    "rr_cap": (EP_RR, EP_BUCKET_MIN),
    "wr_cap": (EP_WR, EP_BUCKET_MIN),
    "h_cap": (EP_H, 64),
}

register_entry_point(
    "flat_step", _ep_flat_step,
    arg_names=("hkeys", "hvers", "hcount", "oldest", "blob"),
    carried=("hkeys", "hvers", "hcount", "oldest"),
    size_classes=(("H", EP_H), ("P", 2 * (EP_RR + EP_WR)), ("batch", EP_TXN)),
    h_threshold=EP_H,
    # The flat engine IS full-width by design (merge sorts over H + 2*WR);
    # the bound still rejects anything beyond that legitimate width.
    work_bound=EP_H + 4 * EP_WR,
    bucket_dims=_EP_BUCKETS,
)

register_entry_point(
    "tiered_step", _ep_tiered_step,
    arg_names=("hkeys", "hvers", "hcount", "maxtab", "dkeys", "dvers",
               "dcount", "oldest", "blob"),
    carried=("hkeys", "hvers", "hcount", "maxtab", "dkeys", "dvers",
             "dcount", "oldest"),
    size_classes=(("H", EP_H), ("P", 2 * (EP_RR + EP_WR)), ("D", EP_D),
                  ("batch", EP_TXN)),
    h_threshold=EP_H,
    compaction_gated=True,  # steady state is delta-bounded (perf_smoke)
    work_bound=EP_H + EP_D + 4 * EP_WR,
    bucket_dims=dict(_EP_BUCKETS, d_cap=(EP_D, 64)),
)

register_entry_point(
    "flat_step_kernels", _ep_flat_step_kernels,
    arg_names=("hkeys", "hvers", "hcount", "oldest", "blob"),
    carried=("hkeys", "hvers", "hcount", "oldest"),
    size_classes=(("H", EP_H), ("P", 2 * (EP_RR + EP_WR)), ("batch", EP_TXN)),
    h_threshold=EP_H,
    # The kernelized flat step keeps H-sized STREAMING work (the rank-
    # inversion cumsums) but no H-sized sort; in-kernel work primitives
    # are tile-sized.  Same legitimate width bound as the sort arm.
    work_bound=EP_H + 4 * EP_WR,
    bucket_dims=_EP_BUCKETS,
)

register_entry_point(
    "tiered_step_kernels", _ep_tiered_step_kernels,
    arg_names=("hkeys", "hvers", "hcount", "maxtab", "dkeys", "dvers",
               "dcount", "oldest", "blob"),
    carried=("hkeys", "hvers", "hcount", "maxtab", "dkeys", "dvers",
             "dcount", "oldest"),
    size_classes=(("H", EP_H), ("P", 2 * (EP_RR + EP_WR)), ("D", EP_D),
                  ("batch", EP_TXN)),
    h_threshold=EP_H,
    # Steady state stays delta-bounded with kernels on: the SAME
    # compaction-gating contract as the sort arm, now with zero H-sized
    # sorts even inside the cond (perf_smoke's kernel gate).
    compaction_gated=True,
    work_bound=EP_H + EP_D + 4 * EP_WR,
    bucket_dims=dict(_EP_BUCKETS, d_cap=(EP_D, 64)),
)

register_entry_point(
    "compact_body", _ep_compact_body,
    arg_names=("hk", "hv", "hc", "dk", "dv", "dc", "new_oldest"),
    # Runs only inside the tiered step's cond, which owns donation.
    size_classes=(("H", EP_H), ("D", EP_D), ("batch", EP_TXN)),
    h_threshold=EP_H,
    work_bound=EP_H + EP_D,
    bucket_dims=dict(h_cap=(EP_H, 64), d_cap=(EP_D, 64)),
)

register_entry_point(
    "rebase_body", _ep_rebase_body,
    arg_names=("vers", "d"),
    carried=("vers",),
    size_classes=(("H", EP_H),),
    h_threshold=EP_H,
    work_bound=EP_H,
    bucket_dims=dict(h_cap=(EP_H, 64)),
)

register_entry_point(
    "grow_body", _ep_grow_body,
    arg_names=("buf",),
    carried=("buf",),
    size_classes=(("H", EP_H),),
    h_threshold=EP_H,
    work_bound=2 * EP_H,  # the reallocation concat's output IS old+pad
    bucket_dims=dict(h_cap=(EP_H, 64)),
)


# ---------------------------------------------------------------------------
# Device program cost accounting (ISSUE 10): the baseline dataset the
# Pallas-kernel work (ROADMAP item 1) will be judged against.
# ---------------------------------------------------------------------------

# name -> deterministic cost block.  XLA compile of every entry costs
# ~15s on the 1-core CI host, so the table is computed lazily (first
# program_cost_table() call — tools/perf_experiments.py --programs, the
# perf_smoke gate, or device_metrics under FDB_TPU_PROGRAM_COSTS) and
# cached for the process.
_PROGRAM_COSTS: Dict[str, dict] = {}
# name -> compile wall seconds (REAL clock; kept out of _PROGRAM_COSTS
# so the deterministic blocks never carry wall-derived values — the
# record_wall discipline, flow/metrics.py).
_PROGRAM_COMPILE_WALL: Dict[str, float] = {}
_COMPILE_WALL_HIST = None  # BoundedHistogram, lazy


def compile_wall_histogram():
    """Process-wide histogram of entry-point compile wall costs (wall
    namespace: real-mode tooling only, never a sim-compared surface)."""
    global _COMPILE_WALL_HIST
    if _COMPILE_WALL_HIST is None:
        from ..flow.metrics import BoundedHistogram

        _COMPILE_WALL_HIST = BoundedHistogram("program_compile_wall")
    return _COMPILE_WALL_HIST


def _cost_block(ep: DeviceEntryPoint) -> dict:
    """Compile one registered program at its canonical trace shapes and
    account it: carried/pinned buffer bytes (shape math), XLA
    memory_analysis (temp/output/argument allocation) and cost_analysis
    (FLOPs + bytes accessed per batch).  Deterministic for a fixed
    program + jax version; the compile WALL cost goes to the separate
    wall-namespace histogram."""
    import warnings

    from ..flow.metrics import wall_now

    sizes = ep.arg_nbytes()
    carried = ep.carried_bytes()
    blk: dict = {
        "entry": ep.name,
        "carried_bytes": carried,
        "carried_bytes_total": sum(carried.values()),
        "pinned_bytes_total": sum(sizes[n] for n in ep.pinned),
        "argument_bytes_total": sum(sizes.values()),
    }
    # pallas_call-bearing entries (ISSUE 14): mark them explicitly.  XLA's
    # analyses see the kernel as a black-box custom call, so when they
    # come back empty the block still carries the shape-math byte
    # accounting instead of going silently missing (perf_smoke pins
    # coverage for every entry either way).
    from ..tools.lint.jaxir import walk_jaxpr as _walk

    if any(e.prim == "pallas_call" for e in _walk(ep.jaxpr())):
        blk["kernel"] = True
    fn, jitted, args, statics = ep.built()
    if jitted is None:
        # Inner bodies (e.g. the compaction body) have no jit wrapper of
        # their own; account them as a standalone compile of the body.
        jitted, statics = jax.jit(partial(fn, **statics)), {}
    t0 = wall_now()
    with warnings.catch_warnings():
        # Donation mismatches are JXP003's finding; duplicate noise here.
        warnings.simplefilter("ignore")
        compiled = jitted.lower(*args, **statics).compile()
    dt = wall_now() - t0
    _PROGRAM_COMPILE_WALL[ep.name] = dt
    compile_wall_histogram().add(dt)
    ma = compiled.memory_analysis()
    if ma is not None:
        blk["memory"] = {
            k: int(getattr(ma, f"{k}_size_in_bytes", 0) or 0)
            for k in ("argument", "output", "temp", "alias",
                      "generated_code")
        }
    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0] if ca else None
    if isinstance(ca, dict):
        blk["flops_per_batch"] = ca.get("flops")
        blk["bytes_accessed_per_batch"] = ca.get("bytes accessed")
    return blk


def program_cost_table(registry=None, include_wall: bool = False) -> dict:
    """name -> cost block for every registered device program (cached
    after the first call; entries registered later — e.g. the sharded
    step on parallel import — are accounted on the next call).  A
    builder that cannot run in this environment (the sharded entry
    without enough devices) yields an {"error": ...} block rather than
    sinking the table.  include_wall adds per-entry compile wall seconds
    + the process histogram (real-mode tooling only)."""
    eps = DEVICE_ENTRY_POINTS if registry is None else registry
    for name, ep in sorted(eps.items()):
        if name in _PROGRAM_COSTS:
            continue
        try:
            _PROGRAM_COSTS[name] = _cost_block(ep)
        except Exception as e:  # noqa: BLE001 - recorded in the block itself, per-entry isolation
            _PROGRAM_COSTS[name] = {
                "entry": name,
                "error": f"{type(e).__name__}: {e}",
            }
    out = {n: dict(_PROGRAM_COSTS[n]) for n in sorted(eps) if n in _PROGRAM_COSTS}
    if include_wall:
        for n in out:
            if n in _PROGRAM_COMPILE_WALL:
                out[n]["compile_wall_seconds"] = _PROGRAM_COMPILE_WALL[n]
        out["_compile_wall"] = compile_wall_histogram().summary()
    return out


def cached_program_costs() -> Optional[dict]:
    """The already-computed table (deterministic blocks only), or None
    when nothing has been accounted yet — device_metrics() includes the
    block lazily so a status call never pays the compile."""
    if not _PROGRAM_COSTS:
        return None
    return {n: dict(b) for n, b in sorted(_PROGRAM_COSTS.items())}


def _build_max_table_np(values: np.ndarray) -> np.ndarray:
    """Seed/rebuild the tiered engine's carried base max-table host-side
    without an extra device program (init, load_from, grow).  Delegates to
    the ONE shared table builder in ops.rangequery, so the host layout
    cannot drift from what range_max expects."""
    from ..ops.rangequery import build_max_table_np

    return build_max_table_np(np.asarray(values, dtype=np.int32))


class JaxConflictSet:
    """Host wrapper owning the device-resident history state."""

    def __init__(
        self,
        oldest_version: int = 0,
        key_words: int = 4,
        h_cap: int = 1 << 16,
        device=None,
        bucket_mins: tuple = (8, 8, 8),
    ):
        self.key_words = key_words
        self.h_cap = h_cap
        self.device = device
        self._base = oldest_version  # absolute version of rel 0
        # Floors for (txn, read-range, write-range) capacity buckets: raising
        # them makes varied small batches share one compiled program instead
        # of recompiling per power-of-two shape (compile churn costs more
        # than padded compute on device).
        self.bucket_mins = bucket_mins
        # Eviction cadence (perf experiment; 1 = every batch, the default
        # semantics).  >1 needs h_cap headroom for the unevicted batches.
        from ..flow.knobs import g_env

        self.evict_every = max(1, g_env.get_int("FDB_TPU_EVICT_EVERY"))
        self._batches_since_evict = 0
        # Two-tier history (FDB_TPU_HISTORY=tiered): per-batch work runs at
        # delta size; a major compaction folds the delta into the base when
        # the delta fills or every FDB_TPU_EVICT_EVERY batches (the flag is
        # an ALIAS for the compaction cadence in this mode; unset/1 means
        # fill-triggered only).  Decision-identical to the flat engine —
        # gated by the differential suites under the flag — and the default
        # compile is untouched when the flag is unset (separate jit entry).
        self.history_mode = g_env.get("FDB_TPU_HISTORY")
        # Donated vs non-donated step wrappers, decided once per engine
        # (FDB_TPU_DONATE / platform-auto; see _use_donated_steps).
        self._donate_steps = _use_donated_steps()
        # Pallas kernel routing (ISSUE 14), decided once per engine like
        # the other engine-variant flags: '' / 'auto' selects kernels on
        # the TPU backend only; '1' forces them everywhere (interpret-
        # mode Pallas off-TPU — the CPU differential-gating arm); '0'
        # forces the XLA fallback (the A/B arm).  Static jit args, so a
        # kernels-on engine and a kernels-off engine never share a
        # compiled program.
        from .kernels import resolve_kernel_flag

        self._use_kernels, self._kernel_interpret = resolve_kernel_flag(
            jax.default_backend()
        )
        self.tiered = self.history_mode == "tiered"
        # Abort-witness emission (ISSUE 17): a static jit arg like the
        # other engine-variant flags, read once at construction.  Default
        # ON (FDB_TPU_WITNESS=0 restores the witness-free program).
        self._witness = g_env.get("FDB_TPU_WITNESS") not in ("", "0")
        # Per-txn (absolute version, read-range ordinal) pairs — or None —
        # for the most recent decided batch; [] when witness is off.
        self.last_witness: list = []
        self._last_witness_dev = None
        self.compact_every = 0
        self.d_cap = 0
        if self.tiered:
            self.compact_every = self.evict_every if self.evict_every > 1 else 0
            dc_env = g_env.get_int("FDB_TPU_DELTA_CAP")
            self.d_cap = max(64, dc_env if dc_env > 0 else self.h_cap // 8)
            if g_env.get("FDB_TPU_ABLATE"):
                # Fail FAST: the ablation seams only exist in the flat
                # step; silently ignoring the knob would make an in-step
                # attribution run under the tiered flag report that a
                # phase costs nothing.
                raise ValueError(
                    "FDB_TPU_ABLATE is not supported with "
                    "FDB_TPU_HISTORY=tiered (the ablation seams live in "
                    "the flat detect_core only)"
                )
        self._init_state(oldest_rel=0)
        self.last_iters = 0
        # Kernel telemetry (ISSUE 2 tentpole): every signal that decides
        # whether the device path is winning — retraces per static shape,
        # padding occupancy, fixpoint rounds, grow/rebase events — into a
        # MetricsRegistry.  No rng: aggregates only, deterministic without
        # a loop.  Real dispatch wall cost goes through record_wall (the
        # wall_metrics discipline) and never enters sim snapshots.
        from ..flow.metrics import MetricsRegistry

        self.metrics = MetricsRegistry("JaxConflict")
        for _c in ("retraces", "batches", "transactions", "fixpoint_rounds",
                   "grows", "rebases", "cpu_fallbacks",
                   # Snapshot-mirror sync telemetry (ISSUE 9): probe
                   # rehydration must do work proportional to changes
                   # since the last device sync — rehydrate_keys_encoded
                   # vs rehydrate_keys_total is the asserted evidence.
                   "rehydrate_keys_total", "rehydrate_keys_encoded",
                   "mirror_sync_keys_encoded",
                   # Host-budget telemetry (ISSUE 20): every deliberate
                   # blocking device->host readback enters a
                   # _sanctioned_sync scope (+1 host_syncs), and every
                   # staging-ring miss in _staging_blob is a fresh
                   # per-batch allocation (+1 host_allocs).  perf_smoke
                   # gates both: <=K syncs per healthy pipelined batch,
                   # zero allocs once the ring is warm.
                   "host_syncs", "host_allocs"):
            self.metrics.counter(_c)  # pre-create: snapshots list them all
        if self.tiered:
            # Tier telemetry (only in tiered mode, so flat-mode snapshots
            # stay byte-identical to pre-tier builds).
            self.metrics.counter("major_compactions")
        # Static-shape key -> dispatch count.  A key's FIRST dispatch is an
        # XLA trace+compile (the jit cache misses); the counter equalling
        # len(_bucket_dispatches) is the no-recompile-storm invariant the
        # telemetry test pins.
        self._bucket_dispatches: dict = {}
        # Device-fault hook (conflict/device_faults.py): when set, check()
        # is consulted at the three choke points — dispatch, compile,
        # grow/rebase — BEFORE any state mutation, so a raised fault
        # always leaves the pre-batch history state intact and a host-side
        # retry (the ConflictSet breaker, or _fallback_cpu's store_to) is
        # exact.
        self.fault_injector = None
        # Per-batch padding occupancy (txn/read/write slot utilization of
        # the padded capacities), refreshed on every dispatch.
        self.last_occupancy: dict = {}
        # Most recent completed "dispatch" span (ISSUE 12): the parent
        # the phase-attribution harness attaches its per-phase child
        # spans to.  None until the first dispatch (or spans disabled).
        self.last_dispatch_span = None
        # Mirror-snapshot sync bookkeeping (ISSUE 9): the stamp of the
        # last MirrorSnapshot this device state equals (note_synced /
        # load_from).  Chunk encodings live on the snapshot's immutable
        # chunks, so they are shared across snapshots for free.
        self._synced_stamp: Optional[int] = None
        # Blob staging ring (ISSUE 19): per blob length, a rotation of
        # preallocated uint32 buffers _pack_blob writes into instead of
        # np.concatenate-allocating per batch.  Ring length covers the
        # pipeline depth plus one, so encoding batch N+1 never aliases
        # batch N's in-flight blob (jnp.asarray on the CPU backend may
        # share the host buffer zero-copy).  Sized lazily on first use
        # from FDB_TPU_ENCODE_STAGING.
        self._blob_ring: dict = {}
        self._blob_ring_size: Optional[int] = None
        # Deterministic host-phase accumulator (ISSUE 19): sum of
        # seq-extent of this engine's encode/readback spans.  The
        # resolver folds it (plus the ConflictSet's mirror_apply share)
        # into the host_fraction gauge.
        self.host_phase_seq = 0
        # Transfer guard (ISSUE 20, HOT001's dynamic twin): when armed,
        # dispatch_txns wraps the ticket's device fields in
        # GuardedDeviceValue proxies that raise on any implicit host
        # materialization outside a _sanctioned_sync scope.  Read once:
        # tests re-construct the engine under g_env.override.
        self._transfer_guard = bool(g_env.get("FDB_TPU_TRANSFER_GUARD"))

    # -- state management --
    def _init_state(self, oldest_rel: int):
        kw1 = self.key_words + 1
        # Word-major (kw1, H): see rangequery.py on TPU minor-dim tiling.
        hkeys = np.full((kw1, self.h_cap), keylib.INF_WORD, np.uint32)
        hkeys[:, 0] = 0  # b"" floor boundary
        hvers = np.full((self.h_cap,), FLOOR_REL, np.int32)
        self._hkeys = jnp.asarray(hkeys)
        self._hvers = jnp.asarray(hvers)
        self._hcount = jnp.asarray(1, jnp.int32)
        self._oldest = jnp.asarray(oldest_rel, jnp.int32)
        # Host-side UPPER BOUND on the boundary count (each batch adds at
        # most 2*wr_cap).  Growth checks use the bound so dispatch_packed
        # never blocks on the in-flight batch's real count; the true value
        # is synced only when the bound approaches capacity.
        self._hcount_bound = 1
        if self.tiered:
            self._reset_delta_state(hvers)

    def _reset_delta_state(self, hvers_np=None):
        """(Re)build the tiered extras: carried base max-table + an empty
        delta tier (floor row b"" at FLOOR_REL = "uncovered") + the host
        bounds that drive compaction/growth without device syncs."""
        kw1 = self.key_words + 1
        if hvers_np is None:
            hvers_np = np.asarray(self._hvers)
        self._maxtab = jnp.asarray(_build_max_table_np(hvers_np))
        dkeys = np.full((kw1, self.d_cap), keylib.INF_WORD, np.uint32)
        dkeys[:, 0] = 0  # b"" floor boundary ("uncovered from the start")
        self._dkeys = jnp.asarray(dkeys)
        self._dvers = jnp.asarray(np.full((self.d_cap,), FLOOR_REL, np.int32))
        self._dcount = jnp.asarray(1, jnp.int32)
        self._dcount_bound = 1
        self._batches_since_major = 0

    @property
    def oldest_version(self) -> int:
        return int(self._oldest) + self._base

    @property
    def boundary_count(self) -> int:
        if self.tiered:
            # Exact logical (merged) count: requires folding the delta
            # over the base host-side — O(rows) Python work, a
            # diagnostic/test surface only.  Hot paths (bench logging,
            # gauges) use the cheap base+delta counts instead.
            return len(self._merged_host_state()[0])
        return int(self._hcount)

    @property
    def boundary_count_bound(self) -> int:
        """Cheap upper bound on the logical boundary count (exact when the
        delta is empty — e.g. right after a major compaction)."""
        if self.tiered:
            return int(self._hcount) + int(self._dcount) - 1
        return int(self._hcount)

    def clear(self, version: int):
        self._base = version
        self._init_state(oldest_rel=0)

    def _rel(self, v: int) -> int:
        return int(np.clip(v - self._base, FLOOR_REL + 1, 2**31 - 2))

    def _check_fault(self, site: str):
        if self.fault_injector is not None:
            self.fault_injector.check(site)

    def _sanctioned_sync(self, op: str):
        """One declared blocking device->host readback (ISSUE 20).

        Every deliberate sync on the dispatch/sync path runs inside this
        scope: it counts toward the host_syncs budget perf_smoke gates,
        and — guard mode — it is the ONLY place GuardedDeviceValue
        ticket fields may materialize host-side (plus, on real
        accelerators, a jax.transfer_guard_device_to_host('allow')
        island inside the dispatch window's 'disallow')."""
        from contextlib import ExitStack

        self.metrics.counter("host_syncs").add()
        stack = ExitStack()
        stack.enter_context(g_hostguard.allowed())
        if self._transfer_guard:
            stack.enter_context(jax.transfer_guard_device_to_host("allow"))
        return stack

    def _maybe_grow_or_rebase(self, now: int, wr_cap: int):
        if now - self._base > REBASE_THRESHOLD:
            with self._sanctioned_sync("rebase oldest readback"):
                d = int(self._oldest)
            if d > 0:
                self._check_fault("rebase")
                self.metrics.counter("rebases").add()
                # _rebase_step donates, so the shift rewrites the carried
                # arrays in place instead of holding old+temp+new H-sized
                # buffers live at once (jaxcheck JXP003).
                self._hvers = _rebase_step(self._hvers, d)
                if self.tiered:
                    # Rebase commutes with max, so the carried table and
                    # the delta shift by the same constant — no rebuild.
                    self._dvers = _rebase_step(self._dvers, d)
                    self._maxtab = _rebase_step(self._maxtab, d)
                self._oldest = self._oldest - d
                self._base += d
        if self.tiered:
            return  # tiered growth is decided with the compaction trigger
        if self._hcount_bound + 2 * wr_cap + 2 > self.h_cap:
            # Bound exhausted: sync the true count once (this is the only
            # device round-trip on the dispatch path) and grow if the REAL
            # count is near capacity.
            with self._sanctioned_sync("hcount bound refresh"):
                self._hcount_bound = int(self._hcount)
            if self._hcount_bound + 2 * wr_cap + 2 > self.h_cap:
                self._grow(max(self.h_cap * 2, self.h_cap + 4 * wr_cap))

    def _plan_tiered_batch(self, wr_cap: int) -> int:
        """Host-side compaction/growth planning for one tiered batch;
        returns do_major (0/1).  Deterministic: driven by row-count UPPER
        BOUNDS (delta grows by <= 2*wr_cap per batch; the base only grows
        at compactions, by at most the delta's bound), syncing the true
        counts only when a bound-based trigger fires."""
        add = 2 * wr_cap
        # This batch's merge must fit the delta outright.
        if 2 * add + 8 > self.d_cap:
            self._grow_delta(_next_pow2(2 * add + 8, self.d_cap * 2))
        # Pre-merge must-fit guard for MIXED buckets (review finding): a
        # batch with a larger wr_cap than the batches that filled the
        # delta can arrive with dcount + add + 2 > d_cap even though the
        # same-bucket fill trigger below never fired.  Compaction cannot
        # save it — the merge runs BEFORE the cond — so sync the true
        # count once and grow the delta if this batch still cannot fit
        # (the tiered analog of the flat path's hcount_bound sync+grow).
        if self._dcount_bound + add + 2 > self.d_cap:
            with self._sanctioned_sync("dcount bound refresh"):
                self._dcount_bound = int(self._dcount)
            if self._dcount_bound + add + 2 > self.d_cap:
                self._grow_delta(
                    _next_pow2(self._dcount_bound + add + 2, self.d_cap * 2)
                )
        do_major = 0
        if self.compact_every and (
            self._batches_since_major + 1 >= self.compact_every
        ):
            do_major = 1
        # Fill trigger: compact NOW if the batch AFTER this one might not
        # fit (so the merge below never truncates).
        if self._dcount_bound + 2 * add + 2 > self.d_cap:
            do_major = 1
        if do_major:
            need = self._hcount_bound + self._dcount_bound + add + 2
            if need > self.h_cap:
                # Sync the true counts once before paying a grow.
                with self._sanctioned_sync("compaction bound refresh"):
                    self._hcount_bound = int(self._hcount)
                    self._dcount_bound = int(self._dcount)
                need = self._hcount_bound + self._dcount_bound + add + 2
                if need > self.h_cap:
                    self._grow(max(self.h_cap * 2, _next_pow2(need, self.h_cap)))
        return do_major

    def _grow(self, new_cap: int, rebuild_maxtab: bool = True):
        self._check_fault("grow")
        self.metrics.counter("grows").add()
        pad = new_cap - self.h_cap
        self._hkeys = _grow_step(self._hkeys, pad=pad,
                                 fill=int(keylib.INF_WORD))
        self._hvers = _grow_step(self._hvers, pad=pad, fill=FLOOR_REL)
        self.h_cap = new_cap
        if self.tiered and rebuild_maxtab:
            # The carried table's level count is a function of h_cap —
            # rebuild from the (grown) base versions.  load_from passes
            # rebuild_maxtab=False: it replaces the whole state and
            # rebuilds the table itself, so building one here from the
            # OLD versions would be a discarded device sync + O(H log H)
            # host pass in the middle of fault recovery.
            with self._sanctioned_sync("grow maxtab rebuild"):
                hvers_np = np.asarray(self._hvers)
            self._maxtab = jnp.asarray(_build_max_table_np(hvers_np))

    def _grow_delta(self, new_cap: int):
        """Resize the delta tier (a batch's wr_cap exceeded what the
        current d_cap can absorb).  Counted as a grow: it is the same
        recompile-causing reallocation choke point."""
        self._check_fault("grow")
        self.metrics.counter("grows").add()
        pad = new_cap - self.d_cap
        self._dkeys = _grow_step(self._dkeys, pad=pad,
                                 fill=int(keylib.INF_WORD))
        self._dvers = _grow_step(self._dvers, pad=pad, fill=FLOOR_REL)
        self.d_cap = new_cap

    # -- detection --
    def detect(
        self,
        transactions: List[TransactionConflictInfo],
        now: int,
        new_oldest_version: int,
    ) -> List[int]:
        from ..flow.spans import begin_span

        mt, mr, mw = self.bucket_mins
        with begin_span("encode", attrs={"n_txn": len(transactions)}) as esp:
            pb = PackedBatch.from_transactions(
                transactions, self.key_words,
                min_txn=mt, min_rr=mr, min_wr=mw,
            )
        self._note_host_span(esp)
        statuses = self.detect_packed(pb, now, new_oldest_version)
        return [int(s) for s in statuses[: len(transactions)]]

    def _note_host_span(self, sp) -> None:
        """Fold a host-phase span (encode/readback) into the deterministic
        host_phase_seq accumulator — seq extent only, never wall, so the
        derived host_fraction gauge is byte-identical per seed.  NULL
        spans (FDB_TPU_SPANS=0) contribute nothing."""
        if sp.seq is not None and sp.end_seq is not None:
            self.host_phase_seq += sp.end_seq - sp.seq

    @hot_path(bound="const")
    def _staging_blob(self, nwords: int) -> np.ndarray:
        """Reusable uint32 staging buffer for one blob length, rotated
        round-robin through a ring sized past the pipeline depth
        (ISSUE 19): a buffer is handed out again only after every
        dispatch that could still alias it has been superseded.
        FDB_TPU_ENCODE_STAGING: 'auto' sizes the ring pipeline-depth+1
        (min 2 — double-buffered even unpipelined), an integer forces a
        ring length, '0' disables staging (fresh allocation per blob,
        the pre-ISSUE-19 behavior)."""
        size = self._blob_ring_size
        if size is None:
            from ..flow.knobs import g_env

            raw = g_env.get("FDB_TPU_ENCODE_STAGING") or "auto"
            if raw == "auto":
                depth = max(1, g_env.get_int("FDB_TPU_PIPELINE_DEPTH"))
                size = depth + 1
            else:
                size = int(raw)
            size = self._blob_ring_size = max(0, size)
        if size == 0:
            # Staging explicitly disabled: every blob is a fresh buffer,
            # and host_allocs makes the cost visible to perf_smoke.
            self.metrics.counter("host_allocs").add()
            return np.empty((nwords,), np.uint32)  # perfcheck: ignore[HOT003]: FDB_TPU_ENCODE_STAGING=0 explicitly opts out of the ring; the fresh allocation is the requested behavior and is counted above
        ring = self._blob_ring.get(nwords)
        if ring is None:
            self.metrics.counter("host_allocs").add(max(2, size))
            ring = self._blob_ring[nwords] = (
                [np.empty((nwords,), np.uint32) for _ in range(max(2, size))],  # perfcheck: ignore[HOT003]: one-time ring population per blob length; steady state hands these buffers out with zero allocation
                [0],
            )
        bufs, pos = ring
        buf = bufs[pos[0]]
        pos[0] = (pos[0] + 1) % len(bufs)
        return buf

    @hot_path(bound="batch")
    def _pack_blob(self, pb: PackedBatch, now: int, new_oldest_version: int,
                   do_evict: int = 1):
        """Single contiguous uint32 blob for one-copy dispatch (see
        _blob_offsets).  Field layout (the blob ABI) is unchanged since
        ISSUE 11; since ISSUE 19 the fields are written straight into a
        double-buffered staging ring instead of np.concatenate
        reallocating ~1MB per batch — the word-major key transposes land
        via strided copyto with no intermediate contiguous copy."""
        rel = self._rel
        r_snap = np.clip(
            pb.r_snap - self._base, FLOOR_REL + 1, 2**31 - 2
        ).astype(np.int32)
        t_snap = np.clip(
            pb.t_snap - self._base, FLOOR_REL + 1, 2**31 - 2
        ).astype(np.int32)
        t_flags = pb.t_has_reads.astype(np.uint32) | (
            pb.t_valid.astype(np.uint32) << 1
        )
        kw1 = self.key_words + 1
        rr, wr, tc = pb.rr_cap, pb.wr_cap, pb.txn_cap
        nwords = 2 * kw1 * (rr + wr) + 2 * rr + wr + 2 * tc + 3
        blob = self._staging_blob(nwords)
        o = 0
        for arr in (pb.r_begin, pb.r_end):
            np.copyto(blob[o : o + kw1 * rr].reshape(kw1, rr), arr.T)
            o += kw1 * rr
        for arr in (pb.w_begin, pb.w_end):
            np.copyto(blob[o : o + kw1 * wr].reshape(kw1, wr), arr.T)
            o += kw1 * wr
        for arr in (
            pb.r_txn.view(np.uint32),
            r_snap.view(np.uint32),
            pb.w_txn.view(np.uint32),
            t_snap.view(np.uint32),
            t_flags,
        ):
            blob[o : o + arr.shape[0]] = arr
            o += arr.shape[0]
        blob[o : o + 3] = np.array(
            [rel(now), rel(new_oldest_version), do_evict], np.int32
        ).view(np.uint32)
        assert o + 3 == nwords
        return blob

    def dispatch_packed(self, pb: PackedBatch, now: int, new_oldest_version: int):
        """Asynchronously dispatch one batch; returns (statuses_dev,
        undecided_dev) WITHOUT syncing, so callers can pipeline host packing
        and transfer of batch N+1 under device compute of batch N.  The
        caller must eventually check undecided (see detect_packed)."""
        self._check_fault("dispatch")
        self._maybe_grow_or_rebase(now, pb.wr_cap)
        do_major = 0
        if self.tiered:
            # Host-decided compaction/growth plan (deterministic bounds —
            # no device sync, replays bit-identical).  Runs before the
            # shape key: a grow changes h_cap/d_cap.
            do_major = self._plan_tiered_batch(pb.wr_cap)
        m = self.metrics
        # Retrace accounting: the jit cache key is the full static-arg
        # tuple — the PackedBatch.bucket() capacities plus h_cap (growth
        # recompiles) and the amortized-eviction flag (or, tiered, the
        # delta capacity).  First sight of a key = one XLA trace+compile.
        amortized = self.evict_every > 1
        if self.tiered:
            shape_key = (pb.bucket(), self.h_cap, self.key_words + 1,
                         "tiered", self.d_cap)
        else:
            shape_key = (pb.bucket(), self.h_cap, self.key_words + 1,
                         amortized)
        first_dispatch = shape_key not in self._bucket_dispatches
        if first_dispatch:
            # Compile faults (injected here, or a real XLA compile error
            # below) raise before the key registers — registration happens
            # only after a SUCCESSFUL dispatch — so the retry after
            # recovery is again a first sight: correctly re-classified and
            # its recompile correctly counted.
            self._check_fault("compile")
        m.counter("batches").add()
        m.counter("transactions").add(pb.n_txn)
        # Padding occupancy: live rows / padded capacity per axis.  Low
        # txn occupancy with high retraces = bucket floors set wrong; the
        # exact tradeoff PERF_NOTES tunes bucket_mins against.
        self.last_occupancy = {
            "txn": pb.n_txn / pb.txn_cap,
            "read": pb.n_r / pb.rr_cap,
            "write": pb.n_w / pb.wr_cap,
        }
        if self.tiered:
            # Delta fill (bound-based: no sync on the dispatch path).
            self.last_occupancy["delta"] = self._dcount_bound / self.d_cap
        for axis, occ in self.last_occupancy.items():
            m.histogram(f"{axis}_occupancy").add(occ)
        if not self.tiered:
            self._batches_since_evict += 1
            do_evict = (
                1 if self._batches_since_evict >= self.evict_every else 0
            )
            if do_evict:
                self._batches_since_evict = 0
        blob = self._pack_blob(
            pb, now, new_oldest_version, do_major if self.tiered else do_evict
        )
        from ..flow.metrics import wall_now
        from ..flow.spans import begin_span

        # Dispatch span (ISSUE 12): host transfer enqueue + (on a cache
        # miss) the XLA trace/compile — NOT device compute (no sync
        # here).  Parents to the resolver's batch span when one is on
        # the hub stack; the phase-attribution harness hangs its
        # per-phase child spans off `last_dispatch_span`.
        _dspan = begin_span(
            "dispatch",
            attrs={"n_txn": pb.n_txn, "version": now,
                   "first_dispatch": int(first_dispatch)},
        )
        _t0 = wall_now()
        tiered_step = (
            _tiered_blob_step if self._donate_steps
            else _tiered_blob_step_nodonate
        )
        flat_step = (
            _blob_step if self._donate_steps else _blob_step_nodonate
        )
        try:
            if self.tiered:
                out = tiered_step(
                    self._hkeys,
                    self._hvers,
                    self._hcount,
                    self._maxtab,
                    self._dkeys,
                    self._dvers,
                    self._dcount,
                    self._oldest,
                    jnp.asarray(blob),
                    txn_cap=pb.txn_cap,
                    rr_cap=pb.rr_cap,
                    wr_cap=pb.wr_cap,
                    h_cap=self.h_cap,
                    d_cap=self.d_cap,
                    kw1=self.key_words + 1,
                    kernels=self._use_kernels,
                    kernel_interpret=self._kernel_interpret,
                    witness=self._witness,
                )
                (
                    self._hkeys,
                    self._hvers,
                    self._hcount,
                    self._maxtab,
                    self._dkeys,
                    self._dvers,
                    self._dcount,
                    self._oldest,
                    statuses,
                    undecided,
                    iters,
                ) = out[:11]
                wit = out[11:]
            else:
                out = flat_step(
                    self._hkeys,
                    self._hvers,
                    self._hcount,
                    self._oldest,
                    jnp.asarray(blob),
                    txn_cap=pb.txn_cap,
                    rr_cap=pb.rr_cap,
                    wr_cap=pb.wr_cap,
                    h_cap=self.h_cap,
                    kw1=self.key_words + 1,
                    amortized=amortized,
                    kernels=self._use_kernels,
                    kernel_interpret=self._kernel_interpret,
                    witness=self._witness,
                )
                (
                    self._hkeys,
                    self._hvers,
                    self._hcount,
                    self._oldest,
                    statuses,
                    undecided,
                    iters,
                ) = out[:7]
                wit = out[7:]
        except jax.errors.JaxRuntimeError as e:
            # Real device failures (and ONLY those — a generic Python
            # RuntimeError is a bug and must crash loudly, not vanish
            # into graceful degradation): surface them in the injectable
            # taxonomy so the breaker's degraded path handles hardware
            # exactly like the simulation.  NOTE donated buffers may
            # already be invalidated — callers must treat device state as
            # stale (rehydrate before reuse).
            from .device_faults import CompileFailed, DeviceUnavailable

            _dspan.end(attrs={"error": "JaxRuntimeError"})
            kind = CompileFailed if first_dispatch else DeviceUnavailable
            raise kind(f"xla: {e}", site="compile" if first_dispatch
                       else "dispatch") from e
        _dspan.end()
        self.last_dispatch_span = _dspan
        if first_dispatch:
            self._bucket_dispatches[shape_key] = 0
            m.counter("retraces").add()
        self._bucket_dispatches[shape_key] += 1
        # Async dispatch wall cost: covers host packing + transfer enqueue
        # and — on a cache miss — the XLA trace/compile, NOT device
        # compute (no sync here).  Wall namespace only.
        m.record_wall("dispatch_seconds", wall_now() - _t0)
        self._last_iters_dev = iters
        if self.tiered:
            if do_major:
                # The compaction folded the delta (and this batch's rows)
                # into the base and reset the delta to its floor row.
                m.counter("major_compactions").add()
                self._hcount_bound = min(
                    self._hcount_bound + self._dcount_bound + 2 * pb.wr_cap,
                    self.h_cap,
                )
                self._dcount_bound = 1
                self._batches_since_major = 0
            else:
                self._dcount_bound = min(
                    self._dcount_bound + 2 * pb.wr_cap, self.d_cap
                )
                self._batches_since_major += 1
        else:
            self._hcount_bound = min(
                self._hcount_bound + 2 * pb.wr_cap, self.h_cap
            )
        # Witness device arrays travel with the dispatch-time base: a
        # LATER dispatch may rebase before this batch is synced, and the
        # rel->abs conversion must use the base the program saw.
        self._last_witness_dev = (
            (wit[0], wit[1], self._base) if wit else None
        )
        return statuses, undecided

    def detect_packed(self, pb: PackedBatch, now: int, new_oldest_version: int):
        """Run one packed batch; returns numpy statuses [txn_cap]."""
        from ..flow.spans import begin_span

        statuses, undecided = self.dispatch_packed(pb, now, new_oldest_version)
        rsp = begin_span("readback", attrs={"n_txn": pb.n_txn})
        try:
            return self._readback_packed(pb, statuses, undecided, now, new_oldest_version)
        finally:
            rsp.end()
            self._note_host_span(rsp)

    def _readback_packed(self, pb, statuses, undecided, now, new_oldest_version):
        # THE declared sync point of the unpipelined path: every host
        # materialization of this batch's device outputs happens inside
        # this one sanctioned scope.
        with self._sanctioned_sync("batch readback"):
            return self._readback_packed_body(
                pb, statuses, undecided, now, new_oldest_version
            )

    def _readback_packed_body(self, pb, statuses, undecided, now,
                              new_oldest_version):
        self.last_iters = int(self._last_iters_dev)
        # The sync point: iters/undecided are host ints here, so surfacing
        # the while_loop carry and the true boundary count costs no extra
        # round-trip beyond the one this method already pays.
        self.metrics.counter("fixpoint_rounds").add(self.last_iters)
        self.metrics.histogram("fixpoint_rounds_per_batch").add(
            self.last_iters
        )
        if self.tiered:
            base_n, delta_n = int(self._hcount), int(self._dcount)
            # boundary_count is the merged-history UPPER BOUND in tiered
            # mode (base + delta rows, minus the delta's floor); the exact
            # merged count would need a device pass per sync.
            self.metrics.gauge("boundary_count").set(base_n + delta_n - 1)
            self.metrics.gauge("base_boundaries").set(base_n)
            self.metrics.gauge("delta_boundaries").set(delta_n)
            self.metrics.histogram("delta_occupancy_synced").add(
                delta_n / self.d_cap
            )
            # Tighten the host bounds with the freshly synced truth.
            self._hcount_bound = base_n
            self._dcount_bound = delta_n
        else:
            self.metrics.gauge("boundary_count").set(int(self._hcount))
        if int(undecided) != 0:
            # detect_core left the history state untouched in this case;
            # resolve the batch on the CPU engine against pristine state and
            # adopt its result — the resolver must never die on a
            # pathological batch (BASELINE.json's CPU-fallback requirement).
            return self._fallback_cpu(pb, now, new_oldest_version)
        statuses_np = np.asarray(statuses)
        if self._witness and self._last_witness_dev is not None:
            self.last_witness = self._witness_host(
                pb, statuses_np, *self._last_witness_dev
            )
        else:
            self.last_witness = []
        return statuses_np

    # -- pipelined dispatch (ISSUE 11) --
    @hot_path(bound="batch")
    def dispatch_txns(
        self,
        transactions: List[TransactionConflictInfo],
        now: int,
        new_oldest_version: int,
    ) -> "DispatchTicket":
        """Pack + dispatch one batch WITHOUT syncing: the pipelined twin
        of detect().  Returns a DispatchTicket whose device arrays become
        ready when THIS batch's program finishes — later dispatches keep
        the device busy behind it.  The carried history advances on
        device in dispatch order, so a ticket's successor already decides
        against this batch's committed writes (commit-order exactness);
        only the host-side sync/mirror work is deferred to sync_ticket."""
        from ..flow.spans import begin_span

        mt, mr, mw = self.bucket_mins
        with begin_span("encode", attrs={"n_txn": len(transactions)}) as esp:
            pb = PackedBatch.from_transactions(
                transactions, self.key_words,
                min_txn=mt, min_rr=mr, min_wr=mw,
            )
        self._note_host_span(esp)
        statuses, undecided = self.dispatch_packed(pb, now, new_oldest_version)
        # COPY the carried count scalars: the carried arrays themselves
        # are donated into the next dispatch (reading them after a
        # successor dispatches would hit a deleted buffer); statuses/
        # undecided/iters are per-dispatch outputs, never re-donated.
        iters = self._last_iters_dev
        hcount = jnp.add(self._hcount, 0)
        dcount = jnp.add(self._dcount, 0) if self.tiered else None
        witness = self._last_witness_dev
        if self._transfer_guard:
            # Guard mode (ISSUE 20): the ticket's device fields raise on
            # any implicit host materialization until a sanctioned sync
            # scope reads them back — the HOT001 dynamic twin, and
            # deterministic even on the CPU backend where
            # jax.transfer_guard never fires (zero-copy reads).
            statuses = GuardedDeviceValue(statuses, "DispatchTicket.statuses")
            undecided = GuardedDeviceValue(
                undecided, "DispatchTicket.undecided"
            )
            iters = GuardedDeviceValue(iters, "DispatchTicket.iters")
            hcount = GuardedDeviceValue(hcount, "DispatchTicket.hcount")
            if dcount is not None:
                dcount = GuardedDeviceValue(dcount, "DispatchTicket.dcount")
            if witness is not None:
                w_ver, w_rng, w_base = witness
                witness = (
                    GuardedDeviceValue(w_ver, "DispatchTicket.witness[0]"),
                    GuardedDeviceValue(w_rng, "DispatchTicket.witness[1]"),
                    w_base,
                )
        return DispatchTicket(
            pb=pb,
            statuses=statuses,
            undecided=undecided,
            iters=iters,
            hcount=hcount,
            dcount=dcount,
            d_cap=self.d_cap,
            now=now,
            new_oldest_version=new_oldest_version,
            witness=witness,
        )

    @hot_path(bound="batch")
    def sync_ticket(self, ticket: "DispatchTicket"):
        """Sync ONE in-flight dispatch: blocks until the ticket's program
        finished (not on later dispatches — its arrays are that program's
        own outputs) and performs detect_packed's per-batch telemetry.
        Returns (statuses ndarray [txn_cap], diverged): diverged=True
        means the fixpoint left this batch undecided — detect_core left
        the device history UNCHANGED for it, so every later dispatch
        decided against stale history; the caller (ConflictSet's
        pipeline) must re-decide this batch and the parked tail on the
        authoritative mirror and mark the device stale.  Unlike
        detect_packed, host capacity bounds are NOT tightened here:
        later batches may already be dispatched, so the additive upper
        bounds must stand."""
        from ..flow.spans import begin_span

        rsp = begin_span("readback", attrs={"n_txn": ticket.pb.n_txn})
        try:
            # THE declared sync point of the pipelined path: ticket
            # device fields (GuardedDeviceValue in guard mode) may only
            # materialize host-side inside this sanctioned scope.
            with self._sanctioned_sync("ticket readback"):
                return self._sync_ticket_body(ticket)
        finally:
            rsp.end()
            self._note_host_span(rsp)

    @hot_path(bound="batch")
    def _sync_ticket_body(self, ticket: "DispatchTicket"):
        iters = int(ticket.iters)
        self.last_iters = iters
        m = self.metrics
        m.counter("fixpoint_rounds").add(iters)
        m.histogram("fixpoint_rounds_per_batch").add(iters)
        if self.tiered:
            base_n, delta_n = int(ticket.hcount), int(ticket.dcount)
            m.gauge("boundary_count").set(base_n + delta_n - 1)
            m.gauge("base_boundaries").set(base_n)
            m.gauge("delta_boundaries").set(delta_n)
            # Against the ticket's d_cap, not self.d_cap: a later
            # dispatch may have grown the delta tier mid-pipeline.
            m.histogram("delta_occupancy_synced").add(
                delta_n / ticket.d_cap
            )
        else:
            m.gauge("boundary_count").set(int(ticket.hcount))
        if int(ticket.undecided) != 0:
            from ..flow.trace import TraceEvent

            m.counter("cpu_fallbacks").add()
            TraceEvent("ConflictFixpointDiverged", severity=30).detail(
                "n_txn", ticket.pb.n_txn
            ).detail("now", ticket.now).detail("pipelined", 1).log()
            return None, True
        statuses_np = np.asarray(ticket.statuses)
        if self._witness and ticket.witness is not None:
            self.last_witness = self._witness_host(
                ticket.pb, statuses_np, *ticket.witness
            )
        else:
            self.last_witness = []
        return statuses_np, False

    def _fallback_cpu(self, pb: PackedBatch, now: int, new_oldest_version: int):
        from ..flow.trace import TraceEvent
        from .engine_cpu import CpuConflictSet

        self.metrics.counter("cpu_fallbacks").add()
        TraceEvent("ConflictFixpointDiverged", severity=30).detail(
            "n_txn", pb.n_txn
        ).detail("now", now).log()
        cpu = CpuConflictSet(key_words=self.key_words)
        self.store_to(cpu)
        statuses = cpu.detect(
            _unpack_transactions(pb), now=now, new_oldest_version=new_oldest_version
        )
        self.load_from(cpu)
        # _unpack_transactions preserves read-range order, so the CPU
        # witness ordinals (and its absolute versions) adopt directly.
        self.last_witness = cpu.last_witness if self._witness else []
        out = np.full((pb.txn_cap,), COMMITTED, np.int32)
        out[: pb.n_txn] = statuses
        return out

    def _witness_host(self, pb: PackedBatch, statuses, w_ver, w_rng, base):
        # Witness decode is its own declared readback: w_ver/w_rng are
        # the dispatch's device outputs (guarded in guard mode).
        with self._sanctioned_sync("witness readback"):
            return decode_witness(pb, statuses, w_ver, w_rng, base)

    # -- hybrid state exchange with the CPU mirror --
    def _chunk_encoding(self, ch):
        """See module-level chunk_encoding (shared with the sharded
        resolver's per-shard mirrors, ISSUE 15)."""
        return chunk_encoding(ch, self.key_words)

    @hot_path(bound="chunks")
    def note_synced(self, snap, fresh=None) -> None:
        """Record that this device state now equals MirrorSnapshot `snap`
        (called by ConflictSet after every successful device-served
        batch), pre-encoding any chunk not yet in the encode cache so a
        LATER half-open probe's load_from pays only for chunks created
        after the fault.  `fresh` is the mirror's (chunks, complete)
        hint from take_fresh_chunks(): with it the walk is O(chunks
        created since the last sync) — the hint may include
        already-dead chunks (superset semantics; an unencodable dead
        long-key chunk is skipped, a LIVE one cannot exist while the
        device serves).  Without it, or when the hint overflowed
        (complete=False), falls back to walking every chunk of `snap`.
        An unchanged mirror is an O(1) stamp compare either way."""
        if snap.stamp == self._synced_stamp:
            return
        candidates = snap.chunks
        if fresh is not None:
            chunks, complete = fresh
            if complete:
                candidates = chunks
        encoded = 0
        for ch in candidates:
            cache = ch.enc
            if cache is None or self.key_words not in cache:
                try:
                    _ent, n = self._chunk_encoding(ch)
                except ValueError:
                    continue  # dead long-key chunk from the hint
                encoded += n
        if encoded:
            self.metrics.counter("mirror_sync_keys_encoded").add(encoded)
        self._synced_stamp = snap.stamp

    def load_from(self, src) -> None:
        """Adopt a CPU-mirror state as device state.  `src` is either a
        MirrorSnapshot (engine_cpu.CpuConflictSet.snapshot(): immutable —
        a fault mid-rehydration can neither observe nor corrupt a
        half-mutated mirror — and chunk-cached encodings make the host
        work proportional to chunks changed since the last note_synced)
        or any flat engine exposing keys/vers/oldest_version (the legacy
        O(H)-encode contract, kept for FlatCpuConflictSet mirrors and the
        sharded test rig)."""
        from .engine_cpu import FLOOR_VERSION

        chunks = getattr(src, "chunks", None)
        if chunks is not None:
            n = src.boundary_count
            encoded = 0
            ents = []
            for ch in chunks:
                ent, enc_n = self._chunk_encoding(ch)
                ents.append(ent)
                encoded += enc_n
            self.metrics.counter("rehydrate_keys_total").add(n)
            self.metrics.counter("rehydrate_keys_encoded").add(encoded)
            keys_enc = np.concatenate([e[0] for e in ents], axis=0)
            vers_abs = np.concatenate([e[1] for e in ents])
            synced_stamp = src.stamp
            oldest = src.oldest_version
        else:
            n = len(src.keys)
            keys_enc = keylib.encode_keys(src.keys, self.key_words)
            vers_abs = np.asarray(src.vers, dtype=np.int64)
            self.metrics.counter("rehydrate_keys_total").add(n)
            self.metrics.counter("rehydrate_keys_encoded").add(n)
            synced_stamp = None
            oldest = src.oldest_version
        if n + 8 > self.h_cap:
            # rebuild_maxtab=False: _reset_delta_state below rebuilds the
            # carried table from the ADOPTED state in the same call.
            self._grow(_next_pow2(n + 8, self.h_cap * 2),
                       rebuild_maxtab=False)
        self._base = oldest
        kw1 = self.key_words + 1
        hkeys = np.full((kw1, self.h_cap), keylib.INF_WORD, np.uint32)
        hkeys[:, :n] = keys_enc.T
        hvers = np.full((self.h_cap,), FLOOR_REL, np.int32)
        rel = np.clip(vers_abs - self._base, FLOOR_REL, 2**31 - 2)
        rel[vers_abs == FLOOR_VERSION] = FLOOR_REL
        hvers[:n] = rel.astype(np.int32)
        self._hkeys = jnp.asarray(hkeys)
        self._hvers = jnp.asarray(hvers)
        self._hcount = jnp.asarray(n, jnp.int32)
        self._oldest = jnp.asarray(0, jnp.int32)
        self._hcount_bound = n
        self._synced_stamp = synced_stamp
        if self.tiered:
            # Rehydration resets the tier split: the adopted state becomes
            # the (frozen) base, the delta restarts empty, and the carried
            # max-table is rebuilt — bit-exact regardless of whether the
            # fault interrupted a major compaction.
            self._reset_delta_state(hvers)

    def store_to(self, cpu) -> None:
        """Write device state back into the CPU engine.  In tiered mode
        the exported step function is the MERGED view (delta folded over
        the frozen base with the same rules the on-device major compaction
        applies), so round-tripping through a CPU engine mid-delta is
        exact."""
        keys, vers = self._merged_host_state()
        cpu.keys = keys
        cpu.vers = vers
        cpu.oldest_version = self.oldest_version

    def _merged_host_state(self):
        """Decode the logical step function to host (keys, abs-versions)
        lists.  Flat mode: the base verbatim.  Tiered mode: covered delta
        intervals override the base; floor-valued delta rows re-anchor the
        base's value at their key (dropped when an equal-key base row
        already provides it) — the host twin of _major_compact's rules,
        minus eviction (export preserves current state)."""
        from .engine_cpu import FLOOR_VERSION

        # store_to is a declared sync point (diagnostic / fault-recovery
        # export): O(H) host decode, deliberately outside the hot set.
        with self._sanctioned_sync("merged state export"):
            return self._merged_host_state_body(FLOOR_VERSION)

    def _merged_host_state_body(self, floor_version):
        FLOOR_VERSION = floor_version
        n = int(self._hcount)
        bkeys_np = np.asarray(self._hkeys[:, :n]).T
        bvers_np = np.asarray(self._hvers[:n])
        bkeys = [
            keylib.decode_key(bkeys_np[i], self.key_words) for i in range(n)
        ]

        def absv(rel):
            rel = int(rel)
            return FLOOR_VERSION if rel == FLOOR_REL else rel + self._base

        bvers = [absv(v) for v in bvers_np]
        if not self.tiered:
            return bkeys, bvers
        nd = int(self._dcount)
        dkeys_np = np.asarray(self._dkeys[:, :nd]).T
        dvers_np = np.asarray(self._dvers[:nd])
        dkeys = [
            keylib.decode_key(dkeys_np[j], self.key_words) for j in range(nd)
        ]
        return fold_delta_over_base(
            bkeys, bvers, dkeys, dvers_np, self._base
        )


# chunk_encoding moved to engine_cpu (it is pure numpy over mirror
# chunks — the columnar ek fast path made engine_cpu its natural home);
# re-exported here for the sharded resolver and any older import sites.
from .engine_cpu import chunk_encoding  # noqa: E402


def fold_delta_over_base(bkeys, bvers, dkeys, dvers_rel, base):
    """Fold a decoded delta tier over a decoded base tier into the merged
    logical step function (keys, abs-versions) — the host twin of
    _major_compact's rules, minus eviction.  `bvers` are ABSOLUTE
    versions, `dvers_rel` relative (FLOOR_REL = uncovered).  Shared by
    JaxConflictSet._merged_host_state and the sharded resolver's
    per-shard consistency check (ISSUE 15), so the two folds can never
    drift."""
    from bisect import bisect_left

    n = len(bkeys)
    nd = len(dkeys)
    out_k: list = []
    out_v: list = []
    for j in range(nd):
        lo = dkeys[j]
        hi = dkeys[j + 1] if j + 1 < nd else None
        vrel = int(dvers_rel[j])
        if vrel != FLOOR_REL:
            # Covered interval: the delta value dominates everything
            # beneath (it is a write version issued after base froze).
            out_k.append(lo)
            out_v.append(vrel + base)
            continue
        i0 = bisect_left(bkeys, lo)
        if not (i0 < n and bkeys[i0] == lo):
            out_k.append(lo)
            out_v.append(bvers[max(0, i0 - 1)])
        i1 = n if hi is None else bisect_left(bkeys, hi)
        out_k.extend(bkeys[i0:i1])
        out_v.extend(bvers[i0:i1])
    return out_k, out_v
