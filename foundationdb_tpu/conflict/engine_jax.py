"""Device conflict engine: whole-batch MVCC conflict detection in JAX/XLA.

This is the north-star component (BASELINE.json): the reference resolves a
ResolveTransactionBatchRequest by walking a versioned skip list one range at
a time (fdbserver/SkipList.cpp: detectConflicts :1163, SkipList walkers :524,
MiniConflictSet :1028, insert :511, removeBefore :664).  Here the entire
batch is resolved at once with vectorized primitives, designed for the TPU's
strengths (large static-shaped tensor ops, no data-dependent control flow):

  history        sorted boundary array = step function key -> last-write
                 version; reads answered by multiword binary search +
                 sparse-table range max (ops/rangequery.py)
  intra-batch    all range endpoints sorted once into a point domain; the
                 reference's ordered scan becomes an iterative fixpoint:
                 a txn is finalized once every earlier intersecting writer
                 is finalized, with "earliest covering writer" computed by
                 a dyadic segment-tree stabbing query (ops/stabbing.py).
                 Each fixpoint round finalizes at least the first undecided
                 txn, and in practice converges in 1-3 rounds
  merge+evict    committed write ranges become a coverage cumsum over the
                 point domain; the step function is rewritten by a rank-merge
                 (no re-sort of history), then compacted with the reference's
                 eviction rule (drop boundary i iff vers[i] and vers[i-1]
                 are both below the window)

Versions are int32 offsets from a host-held base (the MVCC window is ~5e6
versions — ServerKnobs.max_write_transaction_life_versions — so offsets fit
comfortably), keeping all device math in native 32-bit.

Decision semantics are bit-identical to engine_cpu/oracle by construction
and verified by differential tests (tests/test_conflict_jax.py).
"""

from __future__ import annotations

import math
from functools import partial
from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..ops.rangequery import (
    build_max_table,
    build_min_table,
    lex_less,
    range_max,
    range_min,
    searchsorted_1d,
    searchsorted_words,
)
from ..ops.stabbing import INF32, stabbing_min
from . import keys as keylib
from .types import COMMITTED, CONFLICT, TOO_OLD, TransactionConflictInfo

FLOOR_REL = -(2**30)  # below every representable snapshot
REBASE_THRESHOLD = 2**29

_UNDECIDED = 0
_COMM = 1
_CONF = 2


def _next_pow2(n: int, lo: int) -> int:
    return max(lo, 1 << max(0, math.ceil(math.log2(max(n, 1)))))


def _unpack_transactions(pb: "PackedBatch") -> List[TransactionConflictInfo]:
    """PackedBatch -> TransactionConflictInfo list (CPU-fallback path only;
    keys come back in their packed fixed-width form, which is the key space
    both engines decide over)."""
    txns = [
        TransactionConflictInfo(
            read_snapshot=int(pb.t_snap[t]), read_ranges=[], write_ranges=[]
        )
        for t in range(pb.n_txn)
    ]
    for i in range(pb.n_r):
        t = int(pb.r_txn[i])
        if t < pb.n_txn:
            txns[t].read_ranges.append(
                (
                    keylib.decode_key(pb.r_begin[i], pb.key_words),
                    keylib.decode_key(pb.r_end[i], pb.key_words),
                )
            )
    for i in range(pb.n_w):
        t = int(pb.w_txn[i])
        if t < pb.n_txn:
            txns[t].write_ranges.append(
                (
                    keylib.decode_key(pb.w_begin[i], pb.key_words),
                    keylib.decode_key(pb.w_end[i], pb.key_words),
                )
            )
    return txns


class PackedBatch:
    """Host-side (numpy) dense form of a transaction batch.

    The production resolver keeps batches in this form (ranges packed as they
    arrive), so device dispatch is a straight transfer with no Python loops.
    """

    def __init__(self, txn_cap, rr_cap, wr_cap, key_words):
        kw1 = key_words + 1
        inf = keylib.INF_WORD
        self.key_words = key_words
        self.txn_cap, self.rr_cap, self.wr_cap = txn_cap, rr_cap, wr_cap
        self.r_begin = np.full((rr_cap, kw1), inf, np.uint32)
        self.r_end = np.full((rr_cap, kw1), inf, np.uint32)
        self.r_txn = np.full((rr_cap,), txn_cap, np.int32)
        self.r_snap = np.zeros((rr_cap,), np.int64)
        self.w_begin = np.full((wr_cap, kw1), inf, np.uint32)
        self.w_end = np.full((wr_cap, kw1), inf, np.uint32)
        self.w_txn = np.full((wr_cap,), txn_cap, np.int32)
        self.t_snap = np.zeros((txn_cap,), np.int64)
        self.t_has_reads = np.zeros((txn_cap,), bool)
        self.t_valid = np.zeros((txn_cap,), bool)
        self.n_txn = 0
        self.n_r = 0
        self.n_w = 0

    @classmethod
    def from_transactions(
        cls,
        txns: List[TransactionConflictInfo],
        key_words: int,
        min_txn: int = 8,
        min_rr: int = 8,
        min_wr: int = 8,
    ) -> "PackedBatch":
        n = len(txns)
        nr = sum(len(t.read_ranges) for t in txns)
        nw = sum(len(t.write_ranges) for t in txns)
        pb = cls(
            _next_pow2(n, min_txn),
            _next_pow2(nr, min_rr),
            _next_pow2(nw, min_wr),
            key_words,
        )
        rb, re_, wb, we = [], [], [], []
        ri, wi = 0, 0
        for t, tr in enumerate(txns):
            pb.t_snap[t] = tr.read_snapshot
            pb.t_has_reads[t] = bool(tr.read_ranges)
            pb.t_valid[t] = True
            for (b, e) in tr.read_ranges:
                rb.append(b)
                re_.append(e)
                pb.r_txn[ri] = t
                pb.r_snap[ri] = tr.read_snapshot
                ri += 1
            for (b, e) in tr.write_ranges:
                wb.append(b)
                we.append(e)
                pb.w_txn[wi] = t
                wi += 1
        if rb:
            pb.r_begin[: len(rb)] = keylib.encode_keys(rb, key_words)
            pb.r_end[: len(re_)] = keylib.encode_keys(re_, key_words)
        if wb:
            pb.w_begin[: len(wb)] = keylib.encode_keys(wb, key_words)
            pb.w_end[: len(we)] = keylib.encode_keys(we, key_words)
        pb.n_txn, pb.n_r, pb.n_w = n, nr, nw
        return pb

    def bucket(self):
        return (self.txn_cap, self.rr_cap, self.wr_cap)


# ---------------------------------------------------------------------------
# The jitted whole-batch step.  Static: capacities + key width; traced: state
# arrays (donated) + batch tensors.
# ---------------------------------------------------------------------------


def detect_core(
    hkeys,
    hvers,
    hcount,
    oldest,
    r_begin,
    r_end,
    r_txn,
    r_snap,
    w_begin,
    w_end,
    w_txn,
    t_snap,
    t_has_reads,
    t_valid,
    now_rel,
    new_oldest_rel,
    do_evict=None,
    *,
    txn_cap: int,
    rr_cap: int,
    wr_cap: int,
    h_cap: int,
):
    import os as _os

    _ablate = set(_os.environ.get("FDB_TPU_ABLATE", "").split(","))
    kw1 = hkeys.shape[0]
    H = h_cap
    TXN, RR, WR = txn_cap, rr_cap, wr_cap
    P = 2 * RR + 2 * WR
    p_log2 = max(1, math.ceil(math.log2(P)))

    r_nonempty = lex_less(r_begin, r_end)
    r_valid = r_txn < TXN

    # ---- phase 1: history conflicts (ref checkReadConflictRanges) ----
    if "nosearch" in _ablate:
        i0 = (r_begin[0] % jnp.uint32(H)).astype(jnp.int32)
        j1 = i0
    else:
        i0 = searchsorted_words(hkeys, r_begin, "right") - 1
        j1 = searchsorted_words(hkeys, r_end, "left") - 1
    maxtab = build_max_table(hvers)
    m = range_max(maxtab, jnp.clip(i0, 0, H - 1), jnp.clip(j1, 0, H - 1))
    r_hist = r_valid & r_nonempty & (j1 >= i0) & (m > r_snap)
    hist_conf = (
        jnp.zeros((TXN + 1,), bool)
        .at[jnp.where(r_hist, r_txn, TXN)]
        .max(r_hist)[:TXN]
    )
    too_old = t_valid & t_has_reads & (t_snap < oldest)

    # ---- phase 2: point domain (ref sortPoints + KeyInfo ordering) ----
    # categories at equal keys sort end-read(0) < end-write(1) <
    # begin-write(2) < begin-read(3)  (ref SkipList.cpp getCharacter :166-170)
    cat = jnp.concatenate(
        [
            jnp.full((RR,), 3, jnp.uint32),
            jnp.full((RR,), 0, jnp.uint32),
            jnp.full((WR,), 2, jnp.uint32),
            jnp.full((WR,), 1, jnp.uint32),
        ]
    )
    pkeys = jnp.concatenate([r_begin, r_end, w_begin, w_end], axis=1)
    packed_tail = pkeys[kw1 - 1] * 4 + cat  # (length << 2) | category
    iota = jnp.arange(P, dtype=jnp.int32)
    # Sort operands: key words most-significant-first (keys.py layout), then
    # the packed (length,category) word, then the payload iota; stable for
    # determinism.
    word_ops = [pkeys[w] for w in range(kw1 - 1)]
    res = jax.lax.sort(
        tuple(word_ops) + (packed_tail, iota), num_keys=kw1, is_stable=True
    )
    perm = res[-1]
    pos = jnp.zeros((P,), jnp.int32).at[perm].set(iota)
    # Sorted keys come straight off the sort outputs (no permutation
    # gather): words, then length recovered from the packed tail.
    sorted_keys = jnp.stack(list(res[: kw1 - 1]) + [res[kw1 - 1] // 4])

    rb_idx = pos[:RR]
    re_idx = pos[RR : 2 * RR]
    wb_idx = pos[2 * RR : 2 * RR + WR]
    we_idx = pos[2 * RR + WR :]
    w_valid = w_txn < TXN

    # ---- phase 3: intra-batch fixpoint (ref checkIntraBatchConflicts) ----
    status0 = jnp.where(
        ~t_valid, _COMM, jnp.where(too_old | hist_conf, _CONF, _UNDECIDED)
    ).astype(jnp.int32)

    r_has_slots = re_idx > rb_idx

    def agg_txn(flags):
        """Per-range bool -> per-txn any() over that txn's read ranges."""
        return (
            jnp.zeros((TXN + 1,), bool)
            .at[jnp.where(flags, r_txn, TXN)]
            .max(flags)[:TXN]
        )

    # The reference resolves intra-batch conflicts by a sequential scan
    # whose vectorized form is a fixpoint; iterating it at FULL width costs
    # ~47ms/round at 64k txns on v5e (the dyadic scatter stabbing
    # dominates).  Restructure into exactly TWO full-width stabbings plus a
    # tiny residual loop:
    #   round 1   needs no committed-stab (nothing is committed yet):
    #             txns with no earlier ACTIVE intersecting writer COMMIT.
    #   frozen    round-1 commits never change; one stabbing over their
    #             writes answers every read's frozen-committed conflict —
    #             reads with a smaller frozen committed writer CONFLICT now.
    #   residual  everything still undecided can only be decided by OTHER
    #             residual txns (a frozen writer either conflicted it above
    #             or can never conflict it).  Re-rank the residual
    #             endpoints into a compact domain and run the fixpoint at
    #             1/16th width, where every op is near-free.
    hi_r = jnp.maximum(re_idx - 1, rb_idx)

    def read_query(stab):
        tab = build_min_table(stab)
        return jnp.where(r_has_slots, range_min(tab, rb_idx, hi_r), INF32)

    # -- round 1 --
    w_stat0 = status0[jnp.clip(w_txn, 0, TXN - 1)]
    act0 = w_valid & (w_stat0 != _CONF)
    e1 = read_query(stabbing_min(wb_idx, we_idx, w_txn, act0, p_log2))
    E1_t = agg_txn(r_valid & (e1 < r_txn))
    status1 = jnp.where(
        status0 != _UNDECIDED,
        status0,
        jnp.where(E1_t, _UNDECIDED, _COMM),
    )

    # -- frozen committed stab + immediate round-2 conflicts --
    w_stat1 = status1[jnp.clip(w_txn, 0, TXN - 1)]
    com1 = w_valid & (w_stat1 == _COMM)
    eF = read_query(stabbing_min(wb_idx, we_idx, w_txn, com1, p_log2))
    CF_t = agg_txn(r_valid & (eF < r_txn))
    status2 = jnp.where(
        (status1 == _UNDECIDED) & CF_t, _CONF, status1
    )

    # -- residual compaction --
    RCAP = min(min(RR, WR), max(64, min(RR, WR) >> 4))
    RP = 4 * RCAP
    rp_log2 = max(1, math.ceil(math.log2(RP)))
    r_res = r_valid & (status2[jnp.clip(r_txn, 0, TXN - 1)] == _UNDECIDED)
    w_res = w_valid & (status2[jnp.clip(w_txn, 0, TXN - 1)] == _UNDECIDED)
    n_rres = jnp.sum(r_res)
    n_wres = jnp.sum(w_res)
    overflow = (n_rres > RCAP) | (n_wres > RCAP)

    def compact_1d(valid, cols, width, fill):
        """Sort-by-target compaction of parallel int32 columns."""
        rank = jnp.where(
            valid, jnp.cumsum(valid) - 1, jnp.int32(valid.shape[0] + width)
        ).astype(jnp.int32)
        res2 = jax.lax.sort(
            (rank,) + tuple(c.astype(jnp.int32) for c in cols),
            num_keys=1,
            is_stable=True,
        )
        out = [c[:width] for c in res2[1:]]
        live = jnp.arange(width) < jnp.sum(valid)
        return [jnp.where(live, c, fill) for c in out], live

    (rb_c, re_c, rt_c), r_live = compact_1d(
        r_res, (rb_idx, re_idx, r_txn), RCAP, jnp.int32(0)
    )
    (wb_c, we_c, wt_c), w_live = compact_1d(
        w_res, (wb_idx, we_idx, w_txn), RCAP, jnp.int32(0)
    )
    # Re-rank endpoints into [0, RP): residual endpoints are distinct slots,
    # so ranking the combined endpoint set preserves every intersection
    # predicate (a < b iff rank(a) < rank(b) for ranked points).
    pts = jnp.concatenate([rb_c, re_c, wb_c, we_c])
    pad = jnp.where(
        jnp.concatenate([r_live, r_live, w_live, w_live]),
        pts,
        jnp.int32(2 ** 30) + jnp.arange(RP, dtype=jnp.int32),
    )
    (spts,) = jax.lax.sort((pad,), num_keys=1, is_stable=True)
    ranks = searchsorted_1d(spts, pad, "left").astype(jnp.int32)
    rb_r, re_r = ranks[:RCAP], ranks[RCAP : 2 * RCAP]
    wb_r, we_r = ranks[2 * RCAP : 3 * RCAP], ranks[3 * RCAP :]
    r_has_c = r_live & (re_r > rb_r)
    hi_c = jnp.maximum(re_r - 1, rb_r)

    def agg_txn_small(flags):
        return (
            jnp.zeros((TXN + 1,), bool)
            .at[jnp.where(flags, rt_c, TXN)]
            .max(flags)[:TXN]
        )

    def fix_body(carry):
        status, it = carry
        ws = status[jnp.clip(wt_c, 0, TXN - 1)]
        act = w_live & (ws != _CONF)
        com = w_live & (ws == _COMM)
        ea = jnp.where(
            r_has_c,
            range_min(
                build_min_table(stabbing_min(wb_r, we_r, wt_c, act, rp_log2)),
                rb_r,
                hi_c,
            ),
            INF32,
        )
        ec = jnp.where(
            r_has_c,
            range_min(
                build_min_table(stabbing_min(wb_r, we_r, wt_c, com, rp_log2)),
                rb_r,
                hi_c,
            ),
            INF32,
        )
        E_t = agg_txn_small(r_live & (ea < rt_c))
        C_t = agg_txn_small(r_live & (ec < rt_c))
        new_status = jnp.where(
            status != _UNDECIDED,
            status,
            jnp.where(C_t, _CONF, jnp.where(~E_t, _COMM, _UNDECIDED)),
        )
        return new_status, it + 1

    def fix_cond(carry):
        status, it = carry
        return jnp.any(status == _UNDECIDED) & (it < RCAP + 2)

    if "nofix" in _ablate:
        status, iters = jnp.where(status0 == _UNDECIDED, _COMM, status0), jnp.int32(1)
    else:
        status, iters = jax.lax.while_loop(
            fix_cond, fix_body, (status2, jnp.int32(2))
        )
    # Residual overflow: treated exactly like fixpoint divergence — the
    # host re-runs the batch on the CPU engine against the UNCHANGED
    # history state (see the `ok` guard below).
    undecided_left = jnp.sum(status == _UNDECIDED) + jnp.where(
        overflow, jnp.int32(1), jnp.int32(0)
    )

    # ---- phase 4: committed-write union via point-domain coverage ----
    com_w = w_valid & (status[jnp.clip(w_txn, 0, TXN - 1)] == _COMM)
    delta = (
        jnp.zeros((P + 1,), jnp.int32)
        .at[jnp.where(com_w, wb_idx, P)]
        .add(jnp.where(com_w, 1, 0))
        .at[jnp.where(com_w, we_idx, P)]
        .add(jnp.where(com_w, -1, 0))
    )
    cov = jnp.cumsum(delta[:P]) > 0
    prev = jnp.concatenate([jnp.zeros((1,), bool), cov[:-1]])
    is_start = cov & ~prev
    is_end = ~cov & prev
    seg_of_start = jnp.cumsum(is_start) - 1
    seg_of_end = jnp.cumsum(is_end) - 1
    nseg = jnp.sum(is_start)

    # Compactions below are SORT-BY-TARGET-POSITION, not scatter: a
    # single-key int32 sort carrying the payload words runs ~23x faster
    # than the equivalent scatter on TPU (measured v5e, 8M rows: 54ms vs
    # 1250ms).  Rows being dropped get a past-the-end position and fall off
    # the trailing slice; surviving slots beyond the live count are masked
    # to the INF sentinel afterwards (streaming select).
    inf32 = jnp.uint32(keylib.INF_WORD)

    def compact_to(pos, valid, words, width, fill_vers=None, vers=None,
                   count=None):
        """Reorder columns of `words` [kw1, N] so column i lands at pos[i];
        invalid columns drop off the end.  Returns [kw1, width] (+vers)."""
        n = pos.shape[0]
        dump = jnp.int32(n + width + 2)
        p = jnp.where(valid, pos.astype(jnp.int32), dump)
        ops = (p,) + tuple(words[w] for w in range(words.shape[0]))
        if vers is not None:
            ops = ops + (vers,)
        res = jax.lax.sort(ops, num_keys=1, is_stable=True)
        out = jnp.stack(res[1 : 1 + words.shape[0]])[:, :width]
        if count is not None:
            live = jnp.arange(width) < count
            out = jnp.where(live[None, :], out, inf32)
            if vers is not None:
                v = jnp.where(live, res[-1][:width], fill_vers)
                return out, v
        if vers is not None:
            return out, res[-1][:width]
        return out

    ub = compact_to(seg_of_start, is_start, sorted_keys, WR, count=nseg)
    ue = compact_to(seg_of_end, is_end, sorted_keys, WR, count=nseg)
    seg_valid = jnp.arange(WR) < nseg

    # Merge touching segments (ue[s-1] == ub[s]): the gap between them is a
    # key-empty slot (same key, different point category), so they are one
    # write range semantically — matches the CPU engine's interval coalescing.
    chain_start = jnp.concatenate(
        [
            jnp.ones((1,), bool),
            ~jnp.all(ue[:, :-1] == ub[:, 1:], axis=0),
        ]
    ) | ~seg_valid
    chain_id = jnp.cumsum(chain_start) - 1
    is_chain_last = jnp.concatenate([chain_start[1:], jnp.ones((1,), bool)])
    nseg2 = jnp.sum(chain_start & seg_valid)
    ub = compact_to(chain_id, chain_start & seg_valid, ub, WR, count=nseg2)
    ue = compact_to(chain_id, is_chain_last & seg_valid, ue, WR, count=nseg2)
    nseg = nseg2
    seg_valid = jnp.arange(WR) < nseg

    # ---- phase 5: rewrite the step function (ref addConflictRanges) ----
    # TWO combined searches over (ub | ue) serve EVERYTHING downstream:
    # eq_at_ue, seg_lo/seg_hi, end_val, and — via the new-keys sort
    # permutation — the sorted-new-keys ranks (t_rank/t_rank_r), which were
    # previously re-searched.  Each full-width multiword search over H
    # costs ~10ms at h_cap=4M, so collapsing 5 searches to 2 matters
    # (PERF_NOTES).
    both = jnp.concatenate([ub, ue], axis=1)
    both_left = searchsorted_words(hkeys, both, "left")
    both_right = searchsorted_words(hkeys, both, "right")
    ub_left, ue_left = both_left[:WR], both_left[WR:]
    ub_right, ue_right = both_right[:WR], both_right[WR:]
    rank_right = ue_right
    iv = rank_right - 1
    end_val = hvers[jnp.clip(iv, 0, H - 1)]
    eq_at_ue = (rank_right - ue_left) > 0

    # new boundary entries, interleaved (ub0, ue0, ub1, ue1, ...)
    n_new_cap = 2 * WR
    new_keys = jnp.zeros((kw1, n_new_cap), jnp.uint32)
    new_keys = new_keys.at[:, 0::2].set(ub).at[:, 1::2].set(ue)
    new_vers = (
        jnp.zeros((n_new_cap,), jnp.int32)
        .at[0::2]
        .set(jnp.full((WR,), 0, jnp.int32) + now_rel)
        .at[1::2]
        .set(end_val)
    )
    new_vld = jnp.zeros((n_new_cap,), bool)
    new_vld = new_vld.at[0::2].set(seg_valid).at[1::2].set(seg_valid & ~eq_at_ue)
    nk = jnp.where(new_vld[None, :], new_keys, inf32)
    nw_iota = jnp.arange(n_new_cap, dtype=jnp.int32)
    nres = jax.lax.sort(
        tuple(nk[w] for w in range(kw1)) + (nw_iota,),
        num_keys=kw1,
        is_stable=True,
    )
    nperm = nres[-1]
    new_keys_s = jnp.stack(nres[:kw1])
    new_vers_s = new_vers[nperm]
    nnew = jnp.sum(new_vld)
    new_valid_s = jnp.arange(n_new_cap) < nnew
    # Ranks of the SORTED new keys by permuting the interleaved ranks
    # (invalid rows carry their raw ub/ue rank instead of an INF rank —
    # harmless, they are masked by new_valid_s at every use).
    ranks_left_interleaved = (
        jnp.zeros((n_new_cap,), jnp.int32).at[0::2].set(ub_left).at[1::2].set(ue_left)
    )
    ranks_right_interleaved = (
        jnp.zeros((n_new_cap,), jnp.int32).at[0::2].set(ub_right).at[1::2].set(ue_right)
    )
    t_rank = ranks_left_interleaved[nperm]
    t_rank_r = ranks_right_interleaved[nperm]

    # Which old boundaries survive (not overwritten by a segment), and where
    # everything lands in the merged order.  All per-old-row quantities are
    # derived by RANK INVERSION: search the (few) segment/new keys into the
    # (huge) history once, then turn the ranks into per-history-row values
    # with difference arrays + cumsums — pure streaming.  Issuing one query
    # PER HISTORY ROW into the small tables instead costs H * log(W) random
    # gathers and dominated the whole batch at h_cap = 8M.
    old_iota = jnp.arange(H, dtype=jnp.int32)
    old_valid = old_iota < hcount
    # in_seg: old key i lies in some segment [ub_s, ue_s).  Mark +1 at the
    # first old index >= ub_s and -1 at the first >= ue_s; coverage > 0 after
    # a cumsum (segments are disjoint).
    seg_lo = ub_left
    seg_hi = ue_left
    seg_diff = (
        jnp.zeros((H + 1,), jnp.int32)
        .at[jnp.where(seg_valid, seg_lo, H)]
        .add(jnp.where(seg_valid, 1, 0))
        .at[jnp.where(seg_valid, seg_hi, H)]
        .add(jnp.where(seg_valid, -1, 0))
    )
    in_seg = jnp.cumsum(seg_diff[:H]) > 0
    keep_old = old_valid & ~in_seg
    cum_keep = jnp.cumsum(keep_old.astype(jnp.int32))  # prefix-inclusive
    kept_rank = cum_keep - 1
    # removed-prefix at rank k = (#valid rows < k) - (#kept rows < k)
    #                          = min(k, hcount) - cum_keep[k-1]
    # — closed form; no second cumsum (PERF_NOTES).

    # count_new_less[i] = #new keys strictly below old key i
    #                   = #j with (#old <= new_j) <= i, via a rank histogram.
    new_hist = (
        jnp.zeros((H + 1,), jnp.int32)
        .at[jnp.where(new_valid_s, t_rank_r, H)]
        .add(jnp.where(new_valid_s, 1, 0))
    )
    count_new_less = jnp.cumsum(new_hist[:H])
    pos_old = kept_rank.astype(jnp.int32) + count_new_less
    removed_at_t = jnp.minimum(t_rank, hcount) - jnp.where(
        t_rank > 0, cum_keep[jnp.clip(t_rank - 1, 0, H - 1)], 0
    )
    count_kept_less = t_rank - removed_at_t
    pos_new = jnp.arange(n_new_cap, dtype=jnp.int32) + count_kept_less

    merged_count = jnp.sum(keep_old) + nnew
    merged_keys, merged_vers = compact_to(
        jnp.concatenate([pos_old, pos_new]),
        jnp.concatenate([keep_old, new_valid_s]),
        jnp.concatenate([hkeys, new_keys_s], axis=1),
        H,
        fill_vers=jnp.int32(FLOOR_REL),
        vers=jnp.concatenate([hvers, new_vers_s]),
        count=merged_count,
    )

    # ---- phase 6: window eviction (ref removeBefore wasAbove rule) ----
    if "nomerge" in _ablate:
        out_status = jnp.where(
            too_old, TOO_OLD, jnp.where(status == _COMM, COMMITTED, CONFLICT)
        ).astype(jnp.int32)
        return (hkeys, hvers, hcount, jnp.maximum(oldest, new_oldest_rel).astype(jnp.int32),
                out_status, undecided_left.astype(jnp.int32), iters)
    new_oldest = jnp.maximum(oldest, new_oldest_rel)
    mvalid = jnp.arange(H) < merged_count
    prev_v = jnp.concatenate([jnp.full((1,), FLOOR_REL, jnp.int32), merged_vers[:-1]])
    keep2 = mvalid & (
        (jnp.arange(H) == 0) | (merged_vers >= new_oldest) | (prev_v >= new_oldest)
    )
    rank2 = jnp.cumsum(keep2) - 1
    out_count = jnp.sum(keep2)
    if "noevict" in _ablate:
        out_keys, out_vers, out_count = merged_keys, merged_vers, merged_count
    elif do_evict is not None:
        # Amortized eviction (perf experiment; decisions identical —
        # stale sub-window rows can never flip a verdict because any
        # snapshot that could see them is already TOO_OLD): the compaction
        # sort runs only when the traced flag says so, at the cost of
        # h_cap headroom for the unevicted batches in between.
        def _evict(ops):
            mk, mv = ops
            k, v = compact_to(
                rank2, keep2, mk, H,
                fill_vers=jnp.int32(FLOOR_REL), vers=mv, count=out_count,
            )
            return k, v, out_count.astype(jnp.int32)

        def _keep(ops):
            mk, mv = ops
            return mk, mv, merged_count.astype(jnp.int32)

        out_keys, out_vers, out_count = jax.lax.cond(
            do_evict != 0, _evict, _keep, (merged_keys, merged_vers)
        )
    else:
        out_keys, out_vers = compact_to(
            rank2,
            keep2,
            merged_keys,
            H,
            fill_vers=jnp.int32(FLOOR_REL),
            vers=merged_vers,
            count=out_count,
        )

    # ---- final statuses in the reference's enum ----
    out_status = jnp.where(
        too_old,
        TOO_OLD,
        jnp.where(status == _COMM, COMMITTED, CONFLICT),
    ).astype(jnp.int32)

    # If the fixpoint failed to converge (cannot happen for well-formed
    # batches — the iteration cap exceeds the longest dependency chain — but
    # guarded anyway), the statuses are unreliable and so is the write merge
    # derived from them: keep the history state UNCHANGED so the host can
    # re-run the batch on the CPU engine against pristine state.
    ok = undecided_left == 0
    out_keys = jnp.where(ok, out_keys, hkeys)
    out_vers = jnp.where(ok, out_vers, hvers)
    out_count = jnp.where(ok, out_count, hcount)
    new_oldest = jnp.where(ok, new_oldest, oldest)

    return (
        out_keys,
        out_vers,
        out_count.astype(jnp.int32),
        new_oldest.astype(jnp.int32),
        out_status,
        undecided_left.astype(jnp.int32),
        iters,
    )


# Jitted single-device entry point; detect_core stays undecorated so the
# sharded resolver (parallel/sharded_resolver.py) can call it inside
# shard_map with per-shard clipped inputs.
_detect_step = partial(
    jax.jit,
    static_argnames=("txn_cap", "rr_cap", "wr_cap", "h_cap"),
    donate_argnames=("hkeys", "hvers", "hcount", "oldest"),
)(detect_core)


def _blob_offsets(txn_cap: int, rr_cap: int, wr_cap: int, kw1: int):
    """Field offsets (in uint32 words) of the single-transfer batch blob.

    One contiguous host->device copy per batch instead of ~12: the axon/PCIe
    path has a large per-transfer fixed cost (measured ~136ms for a dozen
    small arrays on this host vs ~20ms for one blob)."""
    sizes = [
        rr_cap * kw1,  # r_begin
        rr_cap * kw1,  # r_end
        wr_cap * kw1,  # w_begin
        wr_cap * kw1,  # w_end
        rr_cap,  # r_txn (i32)
        rr_cap,  # r_snap_rel (i32)
        wr_cap,  # w_txn (i32)
        txn_cap,  # t_snap_rel (i32)
        txn_cap,  # t_flags (bit0 has_reads, bit1 valid)
        3,  # now_rel, new_oldest_rel, do_evict (i32)
    ]
    offs, o = [], 0
    for s in sizes:
        offs.append(o)
        o += s
    return offs, o


def _blob_core(hkeys, hvers, hcount, oldest, blob, *, txn_cap, rr_cap,
               wr_cap, h_cap, kw1, amortized=False):
    offs, _total = _blob_offsets(txn_cap, rr_cap, wr_cap, kw1)
    as_i32 = lambda x: jax.lax.bitcast_convert_type(x, jnp.int32)
    # Key fields are packed word-major (kw1, N): see rangequery.py on TPU
    # minor-dim tiling.
    r_begin = blob[offs[0] : offs[0] + rr_cap * kw1].reshape(kw1, rr_cap)
    r_end = blob[offs[1] : offs[1] + rr_cap * kw1].reshape(kw1, rr_cap)
    w_begin = blob[offs[2] : offs[2] + wr_cap * kw1].reshape(kw1, wr_cap)
    w_end = blob[offs[3] : offs[3] + wr_cap * kw1].reshape(kw1, wr_cap)
    r_txn = as_i32(blob[offs[4] : offs[4] + rr_cap])
    r_snap = as_i32(blob[offs[5] : offs[5] + rr_cap])
    w_txn = as_i32(blob[offs[6] : offs[6] + wr_cap])
    t_snap = as_i32(blob[offs[7] : offs[7] + txn_cap])
    t_flags = blob[offs[8] : offs[8] + txn_cap]
    t_has_reads = (t_flags & 1) > 0
    t_valid = (t_flags & 2) > 0
    scalars = as_i32(blob[offs[9] : offs[9] + 3])
    return detect_core(
        hkeys, hvers, hcount, oldest,
        r_begin, r_end, r_txn, r_snap,
        w_begin, w_end, w_txn,
        t_snap, t_has_reads, t_valid,
        scalars[0], scalars[1],
        # Amortized-eviction experiment: the traced flag only enters the
        # graph when enabled, so the default compile is byte-identical.
        scalars[2] if amortized else None,
        txn_cap=txn_cap, rr_cap=rr_cap, wr_cap=wr_cap, h_cap=h_cap,
    )


_blob_step = partial(
    jax.jit,
    static_argnames=("txn_cap", "rr_cap", "wr_cap", "h_cap", "kw1",
                     "amortized"),
    donate_argnames=("hkeys", "hvers", "hcount", "oldest"),
)(_blob_core)


class JaxConflictSet:
    """Host wrapper owning the device-resident history state."""

    def __init__(
        self,
        oldest_version: int = 0,
        key_words: int = 4,
        h_cap: int = 1 << 16,
        device=None,
        bucket_mins: tuple = (8, 8, 8),
    ):
        self.key_words = key_words
        self.h_cap = h_cap
        self.device = device
        self._base = oldest_version  # absolute version of rel 0
        # Floors for (txn, read-range, write-range) capacity buckets: raising
        # them makes varied small batches share one compiled program instead
        # of recompiling per power-of-two shape (compile churn costs more
        # than padded compute on device).
        self.bucket_mins = bucket_mins
        # Eviction cadence (perf experiment; 1 = every batch, the default
        # semantics).  >1 needs h_cap headroom for the unevicted batches.
        import os as _os

        self.evict_every = max(
            1, int(_os.environ.get("FDB_TPU_EVICT_EVERY", "1"))
        )
        self._batches_since_evict = 0
        self._init_state(oldest_rel=0)
        self.last_iters = 0
        # Kernel telemetry (ISSUE 2 tentpole): every signal that decides
        # whether the device path is winning — retraces per static shape,
        # padding occupancy, fixpoint rounds, grow/rebase events — into a
        # MetricsRegistry.  No rng: aggregates only, deterministic without
        # a loop.  Real dispatch wall cost goes through record_wall (the
        # wall_metrics discipline) and never enters sim snapshots.
        from ..flow.metrics import MetricsRegistry

        self.metrics = MetricsRegistry("JaxConflict")
        for _c in ("retraces", "batches", "transactions", "fixpoint_rounds",
                   "grows", "rebases", "cpu_fallbacks"):
            self.metrics.counter(_c)  # pre-create: snapshots list them all
        # Static-shape key -> dispatch count.  A key's FIRST dispatch is an
        # XLA trace+compile (the jit cache misses); the counter equalling
        # len(_bucket_dispatches) is the no-recompile-storm invariant the
        # telemetry test pins.
        self._bucket_dispatches: dict = {}
        # Device-fault hook (conflict/device_faults.py): when set, check()
        # is consulted at the three choke points — dispatch, compile,
        # grow/rebase — BEFORE any state mutation, so a raised fault
        # always leaves the pre-batch history state intact and a host-side
        # retry (the ConflictSet breaker, or _fallback_cpu's store_to) is
        # exact.
        self.fault_injector = None
        # Per-batch padding occupancy (txn/read/write slot utilization of
        # the padded capacities), refreshed on every dispatch.
        self.last_occupancy: dict = {}

    # -- state management --
    def _init_state(self, oldest_rel: int):
        kw1 = self.key_words + 1
        # Word-major (kw1, H): see rangequery.py on TPU minor-dim tiling.
        hkeys = np.full((kw1, self.h_cap), keylib.INF_WORD, np.uint32)
        hkeys[:, 0] = 0  # b"" floor boundary
        hvers = np.full((self.h_cap,), FLOOR_REL, np.int32)
        self._hkeys = jnp.asarray(hkeys)
        self._hvers = jnp.asarray(hvers)
        self._hcount = jnp.asarray(1, jnp.int32)
        self._oldest = jnp.asarray(oldest_rel, jnp.int32)
        # Host-side UPPER BOUND on the boundary count (each batch adds at
        # most 2*wr_cap).  Growth checks use the bound so dispatch_packed
        # never blocks on the in-flight batch's real count; the true value
        # is synced only when the bound approaches capacity.
        self._hcount_bound = 1

    @property
    def oldest_version(self) -> int:
        return int(self._oldest) + self._base

    @property
    def boundary_count(self) -> int:
        return int(self._hcount)

    def clear(self, version: int):
        self._base = version
        self._init_state(oldest_rel=0)

    def _rel(self, v: int) -> int:
        return int(np.clip(v - self._base, FLOOR_REL + 1, 2**31 - 2))

    def _check_fault(self, site: str):
        if self.fault_injector is not None:
            self.fault_injector.check(site)

    def _maybe_grow_or_rebase(self, now: int, wr_cap: int):
        if now - self._base > REBASE_THRESHOLD:
            d = int(self._oldest)
            if d > 0:
                self._check_fault("rebase")
                self.metrics.counter("rebases").add()
                self._hvers = jnp.maximum(self._hvers - d, FLOOR_REL)
                self._oldest = self._oldest - d
                self._base += d
        if self._hcount_bound + 2 * wr_cap + 2 > self.h_cap:
            # Bound exhausted: sync the true count once (this is the only
            # device round-trip on the dispatch path) and grow if the REAL
            # count is near capacity.
            self._hcount_bound = int(self._hcount)
            if self._hcount_bound + 2 * wr_cap + 2 > self.h_cap:
                self._grow(max(self.h_cap * 2, self.h_cap + 4 * wr_cap))

    def _grow(self, new_cap: int):
        self._check_fault("grow")
        self.metrics.counter("grows").add()
        kw1 = self.key_words + 1
        pad = new_cap - self.h_cap
        self._hkeys = jnp.concatenate(
            [self._hkeys, jnp.full((kw1, pad), keylib.INF_WORD, jnp.uint32)],
            axis=1,
        )
        self._hvers = jnp.concatenate(
            [self._hvers, jnp.full((pad,), FLOOR_REL, jnp.int32)]
        )
        self.h_cap = new_cap

    # -- detection --
    def detect(
        self,
        transactions: List[TransactionConflictInfo],
        now: int,
        new_oldest_version: int,
    ) -> List[int]:
        mt, mr, mw = self.bucket_mins
        pb = PackedBatch.from_transactions(
            transactions, self.key_words, min_txn=mt, min_rr=mr, min_wr=mw
        )
        statuses = self.detect_packed(pb, now, new_oldest_version)
        return [int(s) for s in statuses[: len(transactions)]]

    def _pack_blob(self, pb: PackedBatch, now: int, new_oldest_version: int,
                   do_evict: int = 1):
        """Single contiguous uint32 blob for one-copy dispatch (see
        _blob_offsets)."""
        rel = self._rel
        r_snap = np.clip(
            pb.r_snap - self._base, FLOOR_REL + 1, 2**31 - 2
        ).astype(np.int32)
        t_snap = np.clip(
            pb.t_snap - self._base, FLOOR_REL + 1, 2**31 - 2
        ).astype(np.int32)
        t_flags = pb.t_has_reads.astype(np.uint32) | (
            pb.t_valid.astype(np.uint32) << 1
        )
        return np.concatenate(
            [
                np.ascontiguousarray(pb.r_begin.T).reshape(-1),
                np.ascontiguousarray(pb.r_end.T).reshape(-1),
                np.ascontiguousarray(pb.w_begin.T).reshape(-1),
                np.ascontiguousarray(pb.w_end.T).reshape(-1),
                pb.r_txn.view(np.uint32),
                r_snap.view(np.uint32),
                pb.w_txn.view(np.uint32),
                t_snap.view(np.uint32),
                t_flags,
                np.array(
                    [rel(now), rel(new_oldest_version), do_evict], np.int32
                ).view(np.uint32),
            ]
        )

    def dispatch_packed(self, pb: PackedBatch, now: int, new_oldest_version: int):
        """Asynchronously dispatch one batch; returns (statuses_dev,
        undecided_dev) WITHOUT syncing, so callers can pipeline host packing
        and transfer of batch N+1 under device compute of batch N.  The
        caller must eventually check undecided (see detect_packed)."""
        self._check_fault("dispatch")
        self._maybe_grow_or_rebase(now, pb.wr_cap)
        m = self.metrics
        # Retrace accounting: the jit cache key is the full static-arg
        # tuple — the PackedBatch.bucket() capacities plus h_cap (growth
        # recompiles) and the amortized-eviction flag.  First sight of a
        # key = one XLA trace+compile.
        amortized = self.evict_every > 1
        shape_key = (pb.bucket(), self.h_cap, self.key_words + 1, amortized)
        first_dispatch = shape_key not in self._bucket_dispatches
        if first_dispatch:
            # Compile faults (injected here, or a real XLA compile error
            # below) raise before the key registers — registration happens
            # only after a SUCCESSFUL dispatch — so the retry after
            # recovery is again a first sight: correctly re-classified and
            # its recompile correctly counted.
            self._check_fault("compile")
        m.counter("batches").add()
        m.counter("transactions").add(pb.n_txn)
        # Padding occupancy: live rows / padded capacity per axis.  Low
        # txn occupancy with high retraces = bucket floors set wrong; the
        # exact tradeoff PERF_NOTES tunes bucket_mins against.
        self.last_occupancy = {
            "txn": pb.n_txn / pb.txn_cap,
            "read": pb.n_r / pb.rr_cap,
            "write": pb.n_w / pb.wr_cap,
        }
        for axis, occ in self.last_occupancy.items():
            m.histogram(f"{axis}_occupancy").add(occ)
        self._batches_since_evict += 1
        do_evict = 1 if self._batches_since_evict >= self.evict_every else 0
        if do_evict:
            self._batches_since_evict = 0
        blob = self._pack_blob(pb, now, new_oldest_version, do_evict)
        from ..flow.metrics import wall_now

        _t0 = wall_now()
        try:
            (
                self._hkeys,
                self._hvers,
                self._hcount,
                self._oldest,
                statuses,
                undecided,
                iters,
            ) = _blob_step(
                self._hkeys,
                self._hvers,
                self._hcount,
                self._oldest,
                jnp.asarray(blob),
                txn_cap=pb.txn_cap,
                rr_cap=pb.rr_cap,
                wr_cap=pb.wr_cap,
                h_cap=self.h_cap,
                kw1=self.key_words + 1,
                amortized=amortized,
            )
        except jax.errors.JaxRuntimeError as e:
            # Real device failures (and ONLY those — a generic Python
            # RuntimeError is a bug and must crash loudly, not vanish
            # into graceful degradation): surface them in the injectable
            # taxonomy so the breaker's degraded path handles hardware
            # exactly like the simulation.  NOTE donated buffers may
            # already be invalidated — callers must treat device state as
            # stale (rehydrate before reuse).
            from .device_faults import CompileFailed, DeviceUnavailable

            kind = CompileFailed if first_dispatch else DeviceUnavailable
            raise kind(f"xla: {e}", site="compile" if first_dispatch
                       else "dispatch") from e
        if first_dispatch:
            self._bucket_dispatches[shape_key] = 0
            m.counter("retraces").add()
        self._bucket_dispatches[shape_key] += 1
        # Async dispatch wall cost: covers host packing + transfer enqueue
        # and — on a cache miss — the XLA trace/compile, NOT device
        # compute (no sync here).  Wall namespace only.
        m.record_wall("dispatch_seconds", wall_now() - _t0)
        self._last_iters_dev = iters
        self._hcount_bound = min(
            self._hcount_bound + 2 * pb.wr_cap, self.h_cap
        )
        return statuses, undecided

    def detect_packed(self, pb: PackedBatch, now: int, new_oldest_version: int):
        """Run one packed batch; returns numpy statuses [txn_cap]."""
        statuses, undecided = self.dispatch_packed(pb, now, new_oldest_version)
        self.last_iters = int(self._last_iters_dev)
        # The sync point: iters/undecided are host ints here, so surfacing
        # the while_loop carry and the true boundary count costs no extra
        # round-trip beyond the one this method already pays.
        self.metrics.counter("fixpoint_rounds").add(self.last_iters)
        self.metrics.histogram("fixpoint_rounds_per_batch").add(
            self.last_iters
        )
        self.metrics.gauge("boundary_count").set(int(self._hcount))
        if int(undecided) != 0:
            # detect_core left the history state untouched in this case;
            # resolve the batch on the CPU engine against pristine state and
            # adopt its result — the resolver must never die on a
            # pathological batch (BASELINE.json's CPU-fallback requirement).
            return self._fallback_cpu(pb, now, new_oldest_version)
        return np.asarray(statuses)

    def _fallback_cpu(self, pb: PackedBatch, now: int, new_oldest_version: int):
        from ..flow.trace import TraceEvent
        from .engine_cpu import CpuConflictSet

        self.metrics.counter("cpu_fallbacks").add()
        TraceEvent("ConflictFixpointDiverged", severity=30).detail(
            "n_txn", pb.n_txn
        ).detail("now", now).log()
        cpu = CpuConflictSet()
        self.store_to(cpu)
        statuses = cpu.detect(
            _unpack_transactions(pb), now=now, new_oldest_version=new_oldest_version
        )
        self.load_from(cpu)
        out = np.full((pb.txn_cap,), COMMITTED, np.int32)
        out[: pb.n_txn] = statuses
        return out

    # -- hybrid state exchange with the CPU engine --
    def load_from(self, cpu) -> None:
        """Adopt the CPU engine's step function as device state."""
        from .engine_cpu import FLOOR_VERSION

        n = len(cpu.keys)
        if n + 8 > self.h_cap:
            self._grow(_next_pow2(n + 8, self.h_cap * 2))
        self._base = cpu.oldest_version
        kw1 = self.key_words + 1
        hkeys = np.full((kw1, self.h_cap), keylib.INF_WORD, np.uint32)
        hkeys[:, :n] = keylib.encode_keys(cpu.keys, self.key_words).T
        hvers = np.full((self.h_cap,), FLOOR_REL, np.int32)
        rel = np.clip(
            np.array(cpu.vers, dtype=np.int64) - self._base, FLOOR_REL, 2**31 - 2
        )
        rel[np.array(cpu.vers) == FLOOR_VERSION] = FLOOR_REL
        hvers[:n] = rel.astype(np.int32)
        self._hkeys = jnp.asarray(hkeys)
        self._hvers = jnp.asarray(hvers)
        self._hcount = jnp.asarray(n, jnp.int32)
        self._oldest = jnp.asarray(0, jnp.int32)
        self._hcount_bound = n

    def store_to(self, cpu) -> None:
        """Write device state back into the CPU engine."""
        from .engine_cpu import FLOOR_VERSION

        n = int(self._hcount)
        hkeys = np.asarray(self._hkeys[:, :n]).T
        hvers = np.asarray(self._hvers[:n])
        cpu.keys = [keylib.decode_key(hkeys[i], self.key_words) for i in range(n)]
        cpu.vers = [
            FLOOR_VERSION if int(v) == FLOOR_REL else int(v) + self._base
            for v in hvers
        ]
        cpu.oldest_version = self.oldest_version
