"""MVCC conflict detection — the TPU north star of the rebuild.

The reference Resolver decides, for every transaction in a
ResolveTransactionBatchRequest, whether its reads conflict with writes
committed after its read snapshot (ref: fdbserver/Resolver.actor.cpp:71
resolveBatch; engine behind the narrow ABI fdbserver/ConflictSet.h, CPU
implementation fdbserver/SkipList.cpp).

Semantics implemented identically by every backend here (see engine docs):
  - history: a step function key -> last-committed-write version; a read
    [b, e) at snapshot v conflicts iff max over the half-open range is > v
  - too old: read_snapshot < oldestVersion and the txn has read ranges
  - intra-batch: txns in batch order; reads checked against writes of
    earlier non-conflicted txns (half-open interval intersection); writes
    of conflicted txns are never visible
  - merge: committed txns' write ranges set the step function to `now`
  - eviction: boundary i is dropped iff vers[i] < oldest and vers[i-1] < oldest
    (exact for all queries with snapshot >= oldestVersion)

Backends:
  oracle          - brute force, obviously correct, test-only
  engine_cpu      - chunked batch-update snapshot engine (ISSUE 9): the
                    production small-batch path AND the always-on
                    authoritative mirror behind the device breaker —
                    O(1) immutable snapshots, copy-on-write batch sweeps
  engine_cpu_flat - the pre-ISSUE-9 flat array, kept as the bit-identical
                    differential oracle + FDB_TPU_MIRROR_ENGINE=flat arm
  engine_jax      - whole-batch vectorized engine for TPU (production
                    large-batch path), differentially tested against the
                    others
"""

from .types import (
    CONFLICT,
    TOO_OLD,
    COMMITTED,
    TransactionConflictInfo,
    result_name,
)
from .api import ConflictSet
from .device_faults import (
    CompileFailed,
    DeviceFault,
    DeviceFaultInjector,
    DeviceOOM,
    DeviceUnavailable,
)

__all__ = [
    "CONFLICT",
    "TOO_OLD",
    "COMMITTED",
    "TransactionConflictInfo",
    "result_name",
    "ConflictSet",
    "DeviceFault",
    "DeviceUnavailable",
    "CompileFailed",
    "DeviceOOM",
    "DeviceFaultInjector",
]
