"""Key digitization: byte-string keys -> fixed-width uint32 word vectors.

A key of <= 4*KW bytes becomes KW big-endian uint32 words (zero padded) plus
a length word; lexicographic order on (words msw-first..., length) equals
bytewise order on the original keys (zero-padded prefixes compare equal on
words, and the genuinely shorter key sorts first via the length word —
matching e.g. b"a" < b"a\\x00").  Keys longer than 4*KW bytes cannot be
represented exactly; the hybrid ConflictSet routes batches containing them
to the CPU engine (SURVEY.md §7 hard-parts list: fixed-width digitization +
fallback).

Word layout: index 0 is the MOST significant word; the length word is last
(the least significant tie-break).  ops.rangequery.lex_less processes the
trailing index first, giving index 0 the highest priority — one convention
shared by comparisons, sorts, and searches.

Host arrays are row-major [N, key_words+1]; the device engine transposes to
word-major [key_words+1, N] at dispatch (TPU tiling pads the minor
dimension to 128 lanes, so (N, 3) arrays would occupy ~43x their size and
turn every row gather into a 512-byte fetch).
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from ..flow.hotpath import hot_path

# Sentinel "plus infinity" key (greater than any real key: real length word
# is < 2**31 and the sentinel is the max uint32).
INF_WORD = np.uint32(0xFFFFFFFF)

# Host-budget telemetry (ISSUE 20): perf_smoke pins "encode re-does zero
# per-key python at n>=64" against these — "perkey" counts keys that took
# the per-key ljust path, "bulk_batches" counts vectorized bulk encodes.
# Plain module counters (not the metrics registry): encode_keys is a free
# function with no registry handle, and tests read deltas around a call.
ENCODE_OPS = {"perkey": 0, "bulk_batches": 0}


@hot_path(bound="batch")
def encode_keys(keys: Sequence[bytes], key_words: int) -> np.ndarray:
    """[N, key_words+1] uint32; words most-significant-FIRST, length last."""
    width = key_words * 4
    n = len(keys)
    out = np.zeros((n, key_words + 1), dtype=np.uint32)  # perfcheck: ignore[HOT003]: result is returned to and retained by the caller, so it cannot ride the staging ring
    if n == 0:
        return out
    lens = np.fromiter((len(k) for k in keys), np.int64, count=n)
    if int(lens.max()) > width:
        raise ValueError(
            f"key longer than {width} bytes cannot be digitized at "
            f"key_words={key_words}; route to the CPU engine"
        )
    if n >= 64:
        # Bulk pad: scatter the concatenated bytes into a zeroed
        # [n, width] buffer at vectorized positions instead of building
        # n ljust'ed copies (the per-key method-call path below) — the
        # batch-encode hot path (one call digitizes every endpoint of a
        # 2500-txn batch).
        ENCODE_OPS["bulk_batches"] += 1
        flat = np.frombuffer(b"".join(keys), np.uint8)  # perfcheck: ignore[HOT003]: zero-copy view over the joined bytes, no buffer is allocated
        buf = np.zeros(n * width, np.uint8)  # perfcheck: ignore[HOT003]: uint8 scatter scratch the uint32 blob ring cannot serve; one zeroed buffer replaces n per-key ljust copies
        starts = np.zeros(n, np.int64)  # perfcheck: ignore[HOT003]: int64 cumsum scratch; the uint32 blob ring cannot serve it and zeroing seeds starts[0]
        np.cumsum(lens[:-1], out=starts[1:])
        pos = (
            np.arange(flat.size, dtype=np.int64)
            + np.repeat(np.arange(n, dtype=np.int64) * width - starts, lens)
        )
        buf[pos] = flat
        words = buf.view(">u4").reshape(n, key_words).astype(np.uint32)
    else:
        joined = b"".join(k.ljust(width, b"\x00") for k in keys)
        words = (
            # perfcheck: ignore[HOT003]: zero-copy view over the joined bytes; this n<64 branch is the small-batch path ENCODE_OPS["perkey"] accounts for
            np.frombuffer(joined, dtype=">u4").reshape(n, key_words)
            .astype(np.uint32)
        )
        ENCODE_OPS["perkey"] += n
    out[:, :key_words] = words
    out[:, key_words] = lens.astype(np.uint32)
    return out


def encode_int_keys(ints: np.ndarray, key_words: int, byte_len: int = 8) -> np.ndarray:
    """Fast path for integer-derived keys (big-endian byte_len-byte keys).

    Equivalent to encode_keys([i.to_bytes(byte_len, 'big') for i in ints]).
    Used by the bench (the reference microbench uses int keys,
    SkipList.cpp:1440) and by any layer storing pre-packed keys.
    """
    assert byte_len <= 8 and byte_len <= key_words * 4
    n = len(ints)
    out = np.zeros((n, key_words + 1), dtype=np.uint32)
    v = ints.astype(np.uint64)
    shifted = v << np.uint64(8 * (8 - byte_len))  # left-align in 8 bytes
    hi = (shifted >> np.uint64(32)).astype(np.uint32)
    lo = (shifted & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    out[:, 0] = hi
    if key_words >= 2:
        out[:, 1] = lo
    out[:, key_words] = byte_len
    return out


def decode_key(row: np.ndarray, key_words: int) -> bytes:
    length = int(row[key_words])
    if length == int(INF_WORD):
        return b"\xff" * (key_words * 4 + 1)  # sentinel, cannot round-trip
    words = row[:key_words].astype(">u4")
    return words.tobytes()[:length]


def decode_keys(rows: np.ndarray, key_words: int) -> List[bytes]:
    """Bulk inverse of encode_keys for REAL keys (no INF sentinels): one
    byte round-trip of the word block plus a per-row length slice — the
    columnar mirror's lazy key materialization (ISSUE 19)."""
    n = len(rows)
    if n == 0:
        return []
    width = key_words * 4
    raw = np.ascontiguousarray(rows[:, :key_words]).astype(">u4").tobytes()
    lens = rows[:, key_words].tolist()
    mv = memoryview(raw)
    return [bytes(mv[i * width : i * width + lens[i]]) for i in range(n)]


def max_sentinel(key_words: int) -> np.ndarray:
    return np.full((key_words + 1,), INF_WORD, dtype=np.uint32)


def fits(keys: List[bytes], key_words: int) -> bool:
    width = key_words * 4
    return all(len(k) <= width for k in keys)
