"""Shared conflict-engine types.

Result codes use the reference's enum values (fdbserver/ConflictSet.h:36-40:
TransactionConflict=0, TransactionTooOld=1, TransactionCommitted=2) so the
min()-combine across sharded resolvers (ref: MasterProxyServer.actor.cpp:492
combines verdicts with min) works unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Tuple

CONFLICT = 0
TOO_OLD = 1
COMMITTED = 2

_NAMES = {CONFLICT: "conflict", TOO_OLD: "too_old", COMMITTED: "committed"}


def result_name(code: int) -> str:
    return _NAMES[code]


Range = Tuple[bytes, bytes]  # half-open [begin, end)


@dataclass
class TransactionConflictInfo:
    """Conflict-relevant slice of a CommitTransactionRef.

    Ref: fdbclient/CommitTransaction.h:89-104 (read_conflict_ranges,
    write_conflict_ranges, read_snapshot).
    """

    read_snapshot: int
    read_ranges: List[Range] = field(default_factory=list)
    write_ranges: List[Range] = field(default_factory=list)

    def validate(self):
        for b, e in self.read_ranges + self.write_ranges:
            assert isinstance(b, bytes) and isinstance(e, bytes)
            assert b <= e, f"inverted range {b!r} > {e!r}"


def intersects(a: Range, b: Range) -> bool:
    """Half-open interval intersection, the engines' common predicate.

    Empty ranges intersect nothing (the reference's sorted-point encoding
    gives an empty range end-before-begin indices, so its MiniConflictSet
    scans are no-ops; engines here ignore empty ranges everywhere).
    """
    return a[0] < b[1] and b[0] < a[1] and a[0] < a[1] and b[0] < b[1]
