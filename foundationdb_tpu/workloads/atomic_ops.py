"""AtomicOps: atomic ADDs under contention must never lose or double-count.

Ref: fdbserver/workloads/AtomicOps.actor.cpp — every transaction both
atomic-adds into a contended per-group sum key AND writes a private log
entry recording the operand; the check re-derives each group's sum from
its log and compares exactly.  Because both writes ride one transaction,
any lost/duplicated atomic op (under retries, recoveries, kills) breaks
the equality.
"""

from __future__ import annotations

from ..client.types import MutationType
from .base import TestWorkload


def _le8(v: int) -> bytes:
    return (v & (1 << 64) - 1).to_bytes(8, "little")


class AtomicOpsWorkload(TestWorkload):
    name = "atomic_ops"

    def __init__(self, groups: int = 2, actors: int = 3, ops: int = 8,
                 prefix: bytes = b"ao/"):
        self.groups = groups
        self.actors = actors
        self.ops = ops
        self.prefix = prefix

    def _sum_key(self, g: int) -> bytes:
        return self.prefix + b"sum/%02d" % g

    def _log_key(self, g: int, aid: int, seq: int) -> bytes:
        return self.prefix + b"log/%02d/%02d_%04d" % (g, aid, seq)

    async def start(self, db, cluster):
        from ..flow.eventloop import all_of

        rng = cluster.loop.rng

        async def actor(aid: int):
            for seq in range(self.ops):
                g = int(rng.random_int(0, self.groups))
                x = 1 + int(rng.random_int(0, 100))

                async def op(tr, g=g, x=x, aid=aid, seq=seq):
                    # Unknown-result idempotence: the log entry doubles as
                    # the per-op marker — if it exists, the earlier attempt
                    # (sum add included, same txn) already landed.
                    lk = self._log_key(g, aid, seq)
                    if await tr.get(lk) is not None:
                        return
                    tr.atomic_op(MutationType.ADD_VALUE, self._sum_key(g), _le8(x))
                    tr.set(lk, _le8(x))

                await db.run(op)

        await all_of(
            [db.process.spawn(actor(a), f"ao{a}") for a in range(self.actors)]
        )

    async def check(self, db, cluster) -> bool:
        out = {}

        async def read(tr):
            out["sums"] = await tr.get_range(
                self.prefix + b"sum/", self.prefix + b"sum0"
            )
            out["logs"] = await tr.get_range(
                self.prefix + b"log/", self.prefix + b"log0"
            )

        await db.run(read)
        expected = {}
        for k, v in out["logs"]:
            g = k.split(b"/")[-2]
            expected[g] = expected.get(g, 0) + int.from_bytes(v, "little")
        actual = {
            k.split(b"/")[-1]: int.from_bytes(v, "little")
            for k, v in out["sums"]
        }
        total_ops = self.actors * self.ops
        return (
            len(out["logs"]) == total_ops
            and actual == expected
        )
