"""DDMetrics: data-distribution activity is observable through status.

Ref: fdbserver/workloads/DDMetrics.actor.cpp — drive enough skewed load
that data distribution must act, then read the DD metrics through the
status document (not by poking the role) and assert they moved.  The
observable surface is what operators and tools depend on; counters that
only live inside the role are invisible regressions waiting to happen.
"""

from __future__ import annotations

from ..flow.knobs import g_knobs
from .base import TestWorkload


class DDMetricsWorkload(TestWorkload):
    name = "dd_metrics"

    def __init__(self, rows: int = 200, value_len: int = 48,
                 prefix: bytes = b"ddm/"):
        self.rows = rows
        self.value_len = value_len
        self.prefix = prefix
        self._old_max = None
        self._old_min = None

    async def setup(self, db, cluster):
        # Sim-scaled threshold so the hot range below actually trips the
        # tracker's split cadence during the run.
        self._old_max = g_knobs.server.dd_shard_max_bytes
        self._old_min = g_knobs.server.dd_shard_min_bytes
        g_knobs.server.dd_shard_max_bytes = 4000
        g_knobs.server.dd_shard_min_bytes = 0

    def _restore_knobs(self):
        if self._old_max is not None:
            g_knobs.server.dd_shard_max_bytes = self._old_max
            self._old_max = None
        if self._old_min is not None:
            g_knobs.server.dd_shard_min_bytes = self._old_min
            self._old_min = None

    async def start(self, db, cluster):
        from ..server.status import cluster_status

        loop = cluster.loop
        self.final = {}
        try:
            for j in range(6):

                async def hot(tr, j=j):
                    for i in range(40):
                        tr.set(
                            self.prefix + b"%d%04d" % (j, i),
                            b"x" * self.value_len,
                        )

                await db.run(hot)
            # Wait for the tracker cadence to observe and split.
            end = loop.now() + 30.0
            while loop.now() < end:
                doc = cluster_status(cluster)
                dd = doc["cluster"].get("data_distribution")
                if dd and (dd["splits"] >= 1 or dd["moves"] >= 1):
                    self.final = dd
                    return
                await loop.delay(0.5)
        finally:
            # Global knobs must not leak past this workload even when
            # start() fails or times out (check() may never run).
            self._restore_knobs()

    async def check(self, db, cluster) -> bool:
        self._restore_knobs()
        assert self.final, (
            "data_distribution status never showed split/move activity"
        )
        for f in ("moves", "heals", "splits", "merges", "queued"):
            assert isinstance(self.final.get(f), int)
        return True
