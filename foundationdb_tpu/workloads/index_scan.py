"""IndexScan: long paged scans stay byte-exact while shards move.

Ref: fdbserver/workloads/IndexScan.actor.cpp — continuous ordered range
scans over a static dataset; composed with shard-moving chaos
(RandomMoveKeys) the scan must stay BYTE-EXACT and dense end to end:
every page boundary crosses whatever shard layout exists at that moment,
so stale location caches, wrong_shard_server reroutes, and mid-scan
handoffs all land inside one logical scan.
"""

from __future__ import annotations

from ..client.types import key_after
from ..flow.error import FdbError
from .base import TestWorkload


class IndexScanWorkload(TestWorkload):
    name = "index_scan"

    def __init__(self, rows: int = 120, scans: int = 12, page: int = 17,
                 prefix: bytes = b"ix/"):
        self.rows = rows
        self.scans = scans
        self.page = page  # deliberately not a divisor of rows
        self.prefix = prefix
        self.completed = 0

    def _key(self, i: int) -> bytes:
        return self.prefix + b"%06d" % i

    def _val(self, i: int) -> bytes:
        return b"row-%d-%d" % (i, (i * 2654435761) % 997)

    async def setup(self, db, cluster):
        for lo in range(0, self.rows, 40):
            async def fill(tr, lo=lo):
                for i in range(lo, min(self.rows, lo + 40)):
                    tr.set(self._key(i), self._val(i))

            await db.run(fill)

    async def start(self, db, cluster):
        loop = cluster.loop
        want = [(self._key(i), self._val(i)) for i in range(self.rows)]
        for s in range(self.scans):
            got = []
            cursor = self.prefix
            ok = True
            while True:
                rows = None

                async def page_read(tr, cursor=cursor):
                    return await tr.get_range(
                        cursor, self.prefix + b"\xff", limit=self.page
                    )

                try:
                    rows = await db.run(page_read)
                except FdbError:
                    ok = False  # scan aborted (recovery); retry whole scan
                    break
                got.extend(rows)
                if len(rows) < self.page:
                    break
                cursor = key_after(rows[-1][0])
            if not ok:
                await loop.delay(0.1)
                continue
            assert got == want, (
                f"scan {s}: {len(got)} rows vs {len(want)}; first diff at "
                f"{next((i for i, (a, b) in enumerate(zip(got, want)) if a != b), 'len')}"
            )
            self.completed += 1
            await loop.delay(0.05)

    async def check(self, db, cluster) -> bool:
        return self.completed >= self.scans // 2
