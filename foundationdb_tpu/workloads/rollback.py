"""Rollback: partial-durability partitions that force version rollback.

Ref: fdbserver/workloads/Rollback.actor.cpp — clog the network between a
commit proxy and all TLogs EXCEPT one for `clog_duration`, so in-flight
commits become durable on a non-quorum subset; a third of the way in, clog
the proxy and the one unclogged TLog entirely.  The cluster controller's
failure detector then drives a recovery whose epoch-end computes the
durable prefix WITHOUT the partitioned log — versions durable only on the
minority must roll back, and no acked commit may be lost (the invariant
workloads running alongside, plus sim_validation's durability promises,
check that).

Runs against DynamicCluster (recruited roles + recovery state machine).
"""

from __future__ import annotations

from .base import TestWorkload


class RollbackWorkload(TestWorkload):
    name = "rollback"

    def __init__(
        self,
        rounds: int = 1,
        clog_duration: float = 2.0,
        delay_between: float = 3.0,
    ):
        self.rounds = rounds
        self.clog_duration = clog_duration
        self.delay_between = delay_between
        self.triggered = 0

    def _role_machines(self, cluster, role: str):
        return [
            wk.process.machine.machine_id
            for wk in cluster.workers
            if role in wk.roles and wk.process.alive
        ]

    async def start(self, db, cluster):
        loop = cluster.loop
        rng = loop.rng
        for _ in range(self.rounds):
            await loop.delay(self.delay_between * (0.5 + rng.random01()))
            proxies = self._role_machines(cluster, "proxy")
            tlogs = self._role_machines(cluster, "tlog")
            if not proxies or len(tlogs) < 2:
                continue  # rollback needs a minority log to strand
            proxy_m = proxies[int(rng.random_int(0, len(proxies)))]
            ut = int(rng.random_int(0, len(tlogs)))
            unclogged = tlogs[ut]
            if proxy_m == unclogged or proxy_m in tlogs:
                # Shared machine would self-clog (the reference gives up
                # in this case too: "proxy-clogged tLog shared IPs").
                continue
            for i, t in enumerate(tlogs):
                if i != ut:
                    cluster.net.partition_pair(proxy_m, t, self.clog_duration)
            self.triggered += 1
            await loop.delay(self.clog_duration / 3)
            # While the partial partition holds, cut off the proxy and the
            # unclogged tlog from EVERYONE: the recovery that follows must
            # proceed without the only log that saw the stranded commits.
            everyone = sorted(cluster.net.machines)
            for m in everyone:
                if m != proxy_m:
                    cluster.net.partition_pair(proxy_m, m, self.clog_duration)
                if m != unclogged:
                    cluster.net.partition_pair(
                        unclogged, m, self.clog_duration
                    )
            await loop.delay(self.clog_duration * 1.5)
        # Let the cluster settle before checks.
        await loop.delay(2.0)
