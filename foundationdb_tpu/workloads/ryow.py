"""RyowCorrectness: ordered op sequences inside ONE transaction match an
in-memory model exactly.

Ref: fdbserver/workloads/RyowCorrectness.actor.cpp — build a random
sequence of mutations and reads, apply it to a ReadYourWrites transaction
AND to a deterministic in-memory model in the same order; every read
(point, range, limited, reverse, selector) must return byte-exactly what
the model predicts, and the committed database state must equal the
model afterwards.  This is the single-transaction ordered-semantics
complement to WriteDuringRead (concurrency) and FuzzApi (error
contracts).
"""

from __future__ import annotations

from typing import Dict, Optional

from ..client.atomic import apply_atomic
from ..client.types import MutationType
from ..flow.error import FdbError
from .base import TestWorkload

_ATOMICS = [
    MutationType.ADD_VALUE,
    MutationType.AND,
    MutationType.OR,
    MutationType.XOR,
    MutationType.APPEND_IF_FITS,
    MutationType.MAX,
    MutationType.MIN,
    MutationType.BYTE_MAX,
    MutationType.BYTE_MIN,
]


class RyowCorrectnessWorkload(TestWorkload):
    name = "ryow"

    def __init__(self, keyspace: int = 40, txns: int = 10,
                 ops_per_txn: int = 25, prefix: bytes = b"ryow/"):
        self.keyspace = keyspace
        self.txns = txns
        self.ops_per_txn = ops_per_txn
        self.prefix = prefix
        self.reads_checked = 0

    def _key(self, i: int) -> bytes:
        return self.prefix + b"%04d" % i

    def _model_range(self, model: Dict[bytes, bytes], b, e, limit, reverse):
        keys = sorted(k for k in model if b <= k < e)
        if reverse:
            keys = keys[::-1]
        return [(k, model[k]) for k in keys[:limit]]

    async def start(self, db, cluster):
        rng = cluster.loop.rng
        model: Dict[bytes, bytes] = {}

        async def seed(tr):
            for i in range(0, self.keyspace, 3):
                v = b"s%d" % i
                tr.set(self._key(i), v)
                model[self._key(i)] = v

        await db.run(seed)

        for t in range(self.txns):
            local = dict(model)  # model of the txn's view
            marker = self.prefix + b"!txn%04d" % t
            tr = db.create_transaction()
            tr.set(marker, b"done")
            local[marker] = b"done"
            try:
                for _ in range(self.ops_per_txn):
                    op = int(rng.random_int(0, 6))
                    i = int(rng.random_int(0, self.keyspace))
                    k = self._key(i)
                    if op == 0:  # set
                        v = b"v%d_%d" % (t, int(rng.random_int(0, 999)))
                        tr.set(k, v)
                        local[k] = v
                    elif op == 1:  # clear
                        tr.clear(k)
                        local.pop(k, None)
                    elif op == 2:  # clear_range
                        j = min(self.keyspace,
                                i + 1 + int(rng.random_int(0, 6)))
                        tr.clear_range(k, self._key(j))
                        for kk in [x for x in local if k <= x < self._key(j)]:
                            del local[kk]
                    elif op == 3:  # atomic op
                        mt = _ATOMICS[int(rng.random_int(0, len(_ATOMICS)))]
                        param = int(rng.random_int(0, 1 << 30)).to_bytes(
                            8, "little"
                        )
                        tr.atomic_op(mt, k, param)
                        local[k] = apply_atomic(mt, local.get(k), param)
                    elif op == 4:  # point read
                        got = await tr.get(k)
                        assert got == local.get(k), (
                            f"txn {t}: get({k}) = {got}, model "
                            f"{local.get(k)}"
                        )
                        self.reads_checked += 1
                    elif op == 5:  # range read (limit, maybe reverse)
                        j = min(self.keyspace,
                                i + 1 + int(rng.random_int(0, 10)))
                        limit = int(rng.random_int(1, 8))
                        reverse = rng.random_int(0, 2) == 0
                        got = await tr.get_range(
                            k, self._key(j), limit=limit, reverse=reverse
                        )
                        want = self._model_range(
                            local, k, self._key(j), limit, reverse
                        )
                        assert got == want, (
                            f"txn {t}: range({k}..{self._key(j)}, "
                            f"limit={limit}, rev={reverse}) = {got[:4]}, "
                            f"model {want[:4]}"
                        )
                        self.reads_checked += 1
                    else:  # snapshot read must see the same (serial txns)
                        got = await tr.get(k, snapshot=True)
                        assert got == local.get(k)
                        self.reads_checked += 1
                await tr.commit()
                model.clear()
                model.update(local)
            except FdbError as e:
                if e.name == "commit_unknown_result":
                    # The txn's marker disambiguates whether it landed.
                    got = {}

                    async def probe(tr2, marker=marker):
                        got["v"] = await tr2.get(marker)

                    await db.run(probe)
                    if got["v"] is not None:
                        model.clear()
                        model.update(local)
                    continue
                if e.name in ("not_committed", "transaction_too_old",
                              "future_version", "broken_promise",
                              "process_behind", "database_locked"):
                    # The same retryable set the client's own on_error
                    # aborts-and-retries on: the txn did NOT commit, the
                    # model keeps the pre-txn state.
                    continue
                raise
        self._final_model = model

    async def check(self, db, cluster) -> bool:
        out = {}

        async def read(tr):
            out["rows"] = await tr.get_range(
                self.prefix, self.prefix + b"\xff"
            )

        await db.run(read)
        got = dict(out["rows"])
        want = self._final_model
        assert got == want, (
            f"committed state diverged from model: "
            f"{sorted(set(got) ^ set(want))[:6]}"
        )
        return self.reads_checked > 0
