"""Workload framework: composable test workloads with setup/start/check
phases, run concurrently against a simulated cluster.

Ref: fdbserver/workloads/workloads.h:55 (TestWorkload's setup/start/check/
getMetrics contract), tester.actor.cpp:239 (CompoundWorkload running the
spec's stacked workloads concurrently), :778 (runTest driving the phases
and the trailing consistency check).
"""

from .base import TestWorkload, run_workloads
from .cycle import CycleWorkload
from .invariants import AtomicLedgerWorkload, WriteSkewWorkload
from .atomic_ops import AtomicOpsWorkload
from .serializability import SerializabilityWorkload
from .versionstamp import VersionStampWorkload
from .configure_db import ConfigureDatabaseWorkload
from .backup_correctness import BackupCorrectnessWorkload
from .lock_database import LockDatabaseWorkload
from .storefront import StorefrontWorkload
from .unreadable import UnreadableWorkload
from .remove_servers import RemoveServersSafelyWorkload
from .targeted_kill import TargetedKillWorkload
from .chaos import (
    AttritionWorkload,
    DeviceChaosWorkload,
    RandomCloggingWorkload,
)
from .consistency import ConsistencyChecker, check_consistency
from .config import SimulationConfig
from .write_during_read import WriteDuringReadWorkload
from .random_read_write import RandomReadWriteWorkload
from .fuzz_api import FuzzApiWorkload
from .rollback import RollbackWorkload
from .random_move_keys import RandomMoveKeysWorkload
from .sideband import SidebandWorkload
from .selector_correctness import SelectorCorrectnessWorkload
from .watches import WatchesWorkload
from .increment import IncrementWorkload
from .conflict_range import ConflictRangeWorkload
from .inventory import InventoryWorkload
from .queue_push import QueuePushWorkload
from .time_keeper import TimeKeeperWorkload
from .ryow import RyowCorrectnessWorkload
from .watch_and_wait import WatchAndWaitWorkload
from .low_latency import LowLatencyWorkload
from .status_workload import StatusWorkload
from .bulk_load import BulkLoadWorkload
from .slow_task import SlowTaskWorkload
from .metric_logging import MetricLoggingWorkload
from .dd_metrics import DDMetricsWorkload
from .commit_bug import CommitBugWorkload
from .background_selectors import BackgroundSelectorsWorkload
from .fast_watches import FastTriggeredWatchesWorkload
from .dd_balance import DDBalanceWorkload
from .atomic_restore import AtomicRestoreWorkload
from .index_scan import IndexScanWorkload
from .perf_metrics import (
    PingWorkload,
    StreamingReadWorkload,
    ThroughputWorkload,
    WriteBandwidthWorkload,
)
from .soak import (
    FaultEvent,
    SoakConfig,
    SoakPhase,
    default_config as default_soak_config,
    run_soak,
)

__all__ = [
    "TestWorkload",
    "run_workloads",
    "CycleWorkload",
    "DeviceChaosWorkload",
    "AtomicLedgerWorkload",
    "WriteSkewWorkload",
    "AtomicOpsWorkload",
    "SerializabilityWorkload",
    "VersionStampWorkload",
    "ConfigureDatabaseWorkload",
    "BackupCorrectnessWorkload",
    "LockDatabaseWorkload",
    "StorefrontWorkload",
    "UnreadableWorkload",
    "RemoveServersSafelyWorkload",
    "TargetedKillWorkload",
    "AttritionWorkload",
    "RandomCloggingWorkload",
    "ConsistencyChecker",
    "check_consistency",
    "SimulationConfig",
    "WriteDuringReadWorkload",
    "RandomReadWriteWorkload",
    "FuzzApiWorkload",
    "RollbackWorkload",
    "RandomMoveKeysWorkload",
    "SidebandWorkload",
    "SelectorCorrectnessWorkload",
    "WatchesWorkload",
    "IncrementWorkload",
    "ConflictRangeWorkload",
    "InventoryWorkload",
    "QueuePushWorkload",
    "TimeKeeperWorkload",
    "RyowCorrectnessWorkload",
    "WatchAndWaitWorkload",
    "LowLatencyWorkload",
    "StatusWorkload",
    "BulkLoadWorkload",
    "SlowTaskWorkload",
    "MetricLoggingWorkload",
    "DDMetricsWorkload",
    "CommitBugWorkload",
    "BackgroundSelectorsWorkload",
    "FastTriggeredWatchesWorkload",
    "DDBalanceWorkload",
    "AtomicRestoreWorkload",
    "IndexScanWorkload",
    "ThroughputWorkload",
    "WriteBandwidthWorkload",
    "StreamingReadWorkload",
    "PingWorkload",
    "FaultEvent",
    "SoakConfig",
    "SoakPhase",
    "default_soak_config",
    "run_soak",
]
