"""QueuePush: a dense ordered queue built under append contention.

Ref: fdbserver/workloads/QueuePush.actor.cpp — many clients append to one
queue by reading the current last key and writing last+1.  Every pair of
concurrent pushes conflicts on the tail read, so the workload hammers the
resolver's hottest pattern (all transactions conflicting on one range);
the invariant is that the final queue is DENSE and ORDERED: indices
0..N-1 each present exactly once, N = number of acknowledged pushes — a
lost update leaves a hole, a double-applied retry leaves a duplicate
value.  Unknown-result retries are disambiguated by writing the pusher's
identity into the value and deduping by marker, exactly the discipline
the reference's versionstamped queue recipes replace this with.
"""

from __future__ import annotations

from ..flow.error import FdbError
from .base import TestWorkload


class QueuePushWorkload(TestWorkload):
    name = "queue_push"

    def __init__(self, actors: int = 4, pushes: int = 8,
                 prefix: bytes = b"qp/"):
        self.actors = actors
        self.pushes = pushes
        self.prefix = prefix
        self.acked = 0

    def _key(self, i: int) -> bytes:
        return self.prefix + b"q%08d" % i

    async def start(self, db, cluster):
        from ..flow.eventloop import all_of

        async def actor(aid: int):
            for seq in range(self.pushes):
                ident = b"%02d:%04d" % (aid, seq)

                async def push(tr, ident=ident):
                    # Full-queue read: the tail registers the serializing
                    # conflict, and scanning all values makes the
                    # unknown-result retry correct even when OTHER pushes
                    # landed between our unacked commit and the retry.
                    rows = await tr.get_range(
                        self.prefix + b"q", self.prefix + b"r"
                    )
                    if any(v == ident for _k, v in rows):
                        return  # unknown-result retry: already landed
                    nxt = (
                        int(rows[-1][0][len(self.prefix) + 1:]) + 1
                        if rows else 0
                    )
                    tr.set(self._key(nxt), ident)

                try:
                    await db.run(push)
                except FdbError:
                    continue  # not acked: may or may not have landed
                self.acked += 1

        await all_of(
            [
                db.process.spawn(actor(a), f"qp{a}")
                for a in range(self.actors)
            ]
        )

    async def check(self, db, cluster) -> bool:
        out = {}

        async def read(tr):
            out["rows"] = await tr.get_range(
                self.prefix + b"q", self.prefix + b"r"
            )

        await db.run(read)
        rows = out["rows"]
        indices = [int(k[len(self.prefix) + 1:]) for k, _v in rows]
        assert indices == list(range(len(rows))), (
            f"queue not dense/ordered: {indices[:20]}"
        )
        values = [v for _k, v in rows]
        assert len(set(values)) == len(values), "duplicate push applied"
        assert len(rows) >= self.acked, (
            f"{self.acked} acked pushes but only {len(rows)} present"
        )
        return True
