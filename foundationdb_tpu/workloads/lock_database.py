"""LockDatabase: lock/unlock cycles racing live traffic.

Ref: fdbserver/workloads/LockDatabase.actor.cpp — lock the database
mid-run, verify non-lock-aware work fails database_locked while lock-aware
reads see consistent data, unlock, verify traffic resumes.  Composed with
other workloads, their db.run retry loops must ride through the locked
window transparently (database_locked is client-retryable).
"""

from __future__ import annotations

from .base import TestWorkload


class LockDatabaseWorkload(TestWorkload):
    name = "lock_database"

    def __init__(self, at: float = 0.5, hold: float = 0.8):
        self.at = at
        self.hold = hold
        self.checked_while_locked = False

    async def start(self, db, cluster):
        from ..client.management import lock_database, unlock_database
        from ..flow.error import FdbError

        loop = cluster.loop
        await loop.delay(self.at)
        uid = await lock_database(db)

        # Lock-aware snapshot read works while locked.
        tr = db.create_transaction()
        tr.options["lock_aware"] = True
        await tr.get_range(b"", b"\xff", limit=10)

        # Plain commits fail database_locked once the lock has reached
        # the proxy this transaction lands on.
        deadline = loop.now() + self.hold
        while loop.now() < deadline:
            tr2 = db.create_transaction()
            tr2.set(b"lockprobe", b"x")
            try:
                await tr2.commit()
            except FdbError as e:
                if e.name == "database_locked":
                    self.checked_while_locked = True
            await loop.delay(0.1)
        await unlock_database(db, uid)

    async def check(self, db, cluster) -> bool:
        if not self.checked_while_locked:
            return False

        # Unlocked: ordinary traffic flows again.
        async def probe(tr):
            tr.set(b"lock_done", b"1")

        await db.run(probe)
        return True
