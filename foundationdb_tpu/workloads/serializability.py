"""Serializability: committed history must equal serial commit-order replay.

Ref: fdbserver/workloads/Serializability.actor.cpp — random transactions
whose observed reads are checked against a serial re-execution.  Here every
transaction reads a few registers, writes unique values, and carries a
versionstamped probe; the check replays all committed transactions in
(commit_version, txn_number) order and asserts every transaction's reads
equal the model state at its read version.  Lost updates, stale reads
inside the MVCC window, or wrong conflict decisions all break the replay.

The probe makes commit_unknown_result exact: a retry that finds its own
probe landed parses the 10-byte stamp to recover the true commit version
and batch position instead of guessing (ref: the reference resolves
unknown commits by re-reading too).
"""

from __future__ import annotations

from ..client.types import MutationType
from .base import TestWorkload


class SerializabilityWorkload(TestWorkload):
    name = "serializability"

    def __init__(self, registers: int = 6, actors: int = 3, ops: int = 8,
                 prefix: bytes = b"ser/"):
        self.registers = registers
        self.actors = actors
        self.ops = ops
        self.prefix = prefix
        self.records: list = []  # (rv, cv, tn, reads{k:v}, writes{k:v})

    def _reg(self, i: int) -> bytes:
        return self.prefix + b"r/%02d" % i

    def _probe(self, ident: bytes) -> bytes:
        return self.prefix + b"p/" + ident

    async def start(self, db, cluster):
        from ..flow.eventloop import all_of
        from ..flow.error import FdbError

        rng = cluster.loop.rng

        async def actor(aid: int):
            for seq in range(self.ops):
                ident = b"%02d_%04d" % (aid, seq)
                n_reads = 2 + int(rng.random_int(0, 3))
                read_ks = [
                    self._reg(int(rng.random_int(0, self.registers)))
                    for _ in range(n_reads)
                ]
                write_ks = sorted(
                    {
                        self._reg(int(rng.random_int(0, self.registers)))
                        for _ in range(1 + int(rng.random_int(0, 2)))
                    }
                )
                writes = {k: ident + b"." + k[-2:] for k in write_ks}
                attempt = {}

                async def op(tr, ident=ident, read_ks=read_ks, writes=writes,
                             attempt=attempt):
                    probe = await tr.get(self._probe(ident))
                    if probe is not None:
                        from ..flow.testprobe import test_probe

                        test_probe("serializability_cv_recovered")
                        return probe  # earlier attempt landed; stamp inside
                    rv = await tr.get_read_version()
                    reads = {}
                    for k in sorted(set(read_ks)):
                        reads[k] = await tr.get(k)
                    attempt["rv"] = rv
                    attempt["reads"] = reads
                    for k, v in writes.items():
                        tr.set(k, v)
                    tr.atomic_op(
                        MutationType.SET_VERSIONSTAMPED_VALUE,
                        self._probe(ident),
                        b"\x00" * 10 + (0).to_bytes(4, "little"),
                    )
                    return None

                tr = db.create_transaction()
                cv = tn = None
                while True:
                    try:
                        landed = await op(tr)
                        if landed is not None:
                            cv = int.from_bytes(landed[:8], "big")
                            tn = int.from_bytes(landed[8:10], "big")
                            break
                        version = await tr.commit()
                        cv = version
                        tn = None  # resolved from the probe in check()
                        break
                    except FdbError as e:
                        await tr.on_error(e)
                if "rv" in attempt:
                    self.records.append(
                        (attempt["rv"], cv, tn, ident, attempt["reads"], writes)
                    )

        await all_of(
            [db.process.spawn(actor(a), f"ser{a}") for a in range(self.actors)]
        )

    async def check(self, db, cluster) -> bool:
        out = {}

        async def read(tr):
            out["probes"] = await tr.get_range(
                self.prefix + b"p/", self.prefix + b"p0"
            )
            out["regs"] = await tr.get_range(
                self.prefix + b"r/", self.prefix + b"r0"
            )

        await db.run(read)
        stamp_of = {
            k[len(self.prefix) + 2:]: (
                int.from_bytes(v[:8], "big"),
                int.from_bytes(v[8:10], "big"),
            )
            for k, v in out["probes"]
        }
        # Final records keyed by ident: every landed probe must belong to a
        # recorded commit, with its batch position resolved from the stamp.
        events = []
        for rv, cv, tn, ident, reads, writes in self.records:
            if ident not in stamp_of:
                return False  # committed per the client, probe missing
            pcv, ptn = stamp_of[ident]
            if cv is not None and pcv != cv:
                return False  # probe stamp disagrees with commit version
            events.append((pcv, ptn, rv, reads, writes))
        if len(events) != len(stamp_of):
            return False  # a probe landed for an unrecorded op
        events.sort(key=lambda e: (e[0], e[1]))
        # Serial replay in (commit_version, txn_number) order.  Reads at rv
        # must equal the model after every txn with cv <= rv.
        history = {}  # key -> list of (cv, tn, value), append-ordered
        for pcv, ptn, rv, reads, writes in events:
            for k, want in reads.items():
                got = None
                for hcv, _htn, hv in history.get(k, ()):
                    if hcv <= rv:
                        got = hv
                    else:
                        break
                if got != want:
                    return False
            for k, v in writes.items():
                history.setdefault(k, []).append((pcv, ptn, v))
        # The final database state must equal the replayed model.
        final = {k[-2:]: v for k, v in out["regs"]}
        model = {k[-2:]: hist[-1][2] for k, hist in history.items()}
        return final == model
