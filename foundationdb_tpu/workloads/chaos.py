"""Chaos injector workloads: swizzled clogging and machine attrition.

Ref: fdbserver/workloads/RandomClogging.actor.cpp (random pairwise clogs;
the "swizzled" variant clogs a changing subset then releases in reverse),
fdbserver/workloads/MachineAttrition.actor.cpp (kill/reboot machines on a
cadence while invariant workloads run).
"""

from __future__ import annotations

from .base import TestWorkload


def revive_worker(cluster, proc):
    """Reboot a killed worker process and re-attach a fresh worker agent.

    Replaces the dead worker in the cluster's bookkeeping: stale
    WorkerServer objects hold FROZEN role instances (e.g. a storage whose
    version never advances again), which would poison any aggregate read
    off cluster.workers (status, quiet_database)."""
    from ..flow.asyncvar import AsyncVar
    from ..server.coordination import monitor_leader
    from ..server.worker import WorkerServer, run_worker_registration

    proc.reboot()
    w = WorkerServer(proc, cluster.fs)
    cluster.workers = [
        x for x in cluster.workers if x.process is not proc
    ] + [w]
    leader_var = AsyncVar(None)
    proc.spawn_observed(
        monitor_leader(proc, getattr(cluster, "coord_set", cluster.coord_ifaces), leader_var),
        "leader_mon",
    )
    proc.spawn(run_worker_registration(w, leader_var), "registration")
    return w


class RandomCloggingWorkload(TestWorkload):
    """Clog random machine pairs for random durations (swizzled: several
    overlapping clogs whose releases interleave).  Half the injections are
    full bidirectional partitions, half one-way clogs — the asymmetric
    grey failures (requests arrive, replies stall) a symmetric-only model
    never exercises."""

    name = "random_clogging"

    def __init__(self, duration: float = 3.0, max_clog: float = 0.4):
        self.duration = duration
        self.max_clog = max_clog

    async def start(self, db, cluster):
        loop = cluster.loop
        rng = loop.rng
        end = loop.now() + self.duration
        machines = sorted(cluster.net.machines)
        while loop.now() < end and len(machines) >= 2:
            i = int(rng.random_int(0, len(machines)))
            j = int(rng.random_int(0, len(machines) - 1))
            if j >= i:
                j += 1
            hold = rng.random01() * self.max_clog
            if rng.coinflip():
                cluster.net.partition_pair(machines[i], machines[j], hold)
            else:
                cluster.net.clog_pair(machines[i], machines[j], hold)
            await loop.delay(0.05 + rng.random01() * 0.2)
        cluster.net.unclog_all()


class DeviceChaosWorkload(TestWorkload):
    """Inject device faults into every resolver's conflict engine while
    the invariant workloads (Cycle, Serializability, ...) run — the
    device-path analog of RandomClogging + Attrition, and composable with
    both.  Random-mode faults fire from BUGGIFY sites
    (``device_fault_<site>``) so the sim-end coverage report names them;
    mid-run a scripted persistent dispatch outage on one victim forces
    the breaker through its full ok -> degraded -> probing -> ok cycle.

    check() validates the degraded-mode invariants, not data (the
    concurrent invariant workloads own that): every breaker transition
    log must be a legal walk of the state machine, and any engine whose
    injector fired must have counted the faults."""

    name = "device_chaos"

    def __init__(
        self,
        duration: float = 3.0,
        fire_probability: float = 0.25,
        outage: bool = True,
    ):
        self.duration = duration
        self.fire_probability = fire_probability
        self.outage = outage
        self.installed: list = []

    def _conflict_sets(self, cluster):
        from ..server.status import role_objects

        out = []
        for r in role_objects(cluster, "resolver"):
            cs = getattr(r, "conflicts", None)
            if cs is not None and getattr(cs, "_jax", None) is not None:
                out.append(cs)
        return out

    async def start(self, db, cluster):
        from ..conflict.device_faults import DeviceFaultInjector

        loop = cluster.loop
        for cs in self._conflict_sets(cluster):
            # Fork the loop rng per injector: the persistence draws replay
            # from the seed without perturbing other sim decisions.
            inj = DeviceFaultInjector(
                rng=loop.rng.split(),
                fire_probability=self.fire_probability,
            )
            cs.install_fault_injector(inj)
            self.installed.append((cs, inj))
        if not self.installed:
            return
        if self.outage:
            await loop.delay(self.duration / 3)
            cs, inj = self.installed[
                int(loop.rng.random_int(0, len(self.installed)))
            ]
            inj.begin_outage("dispatch")
            await loop.delay(self.duration / 3)
            inj.end_outage("dispatch")
            await loop.delay(self.duration / 3)
        else:
            await loop.delay(self.duration)

    async def check(self, db, cluster) -> bool:
        legal = {
            ("ok", "degraded"),
            ("degraded", "probing"),
            ("probing", "ok"),
            ("probing", "degraded"),
        }
        for cs, inj in self.installed:
            cs.install_fault_injector(None)  # stop injecting before checks
            breaker = cs._breaker
            prev = "ok"
            for _seq, frm, to, _reason in breaker.transitions:
                if frm != prev or (frm, to) not in legal:
                    return False
                prev = to
            if inj.injected and not cs._jax.metrics.counter(
                "device_faults"
            ).value:
                return False  # faults raised but never absorbed/counted
        return True


class AttritionWorkload(TestWorkload):
    """Kill a random worker machine (disks crash per the corruption model),
    reboot it, and re-attach its worker agent; repeat.  The cluster must
    recover a new generation each time with zero acked-data loss."""

    name = "attrition"

    def __init__(self, kills: int = 2, delay_between: float = 1.0):
        self.kills = kills
        self.delay_between = delay_between

    async def start(self, db, cluster):
        loop = cluster.loop
        rng = loop.rng
        for _ in range(self.kills):
            await loop.delay(self.delay_between * (0.5 + rng.random01()))
            procs = [p for p in cluster._worker_procs if p.alive]
            if not procs:
                continue
            proc = procs[int(rng.random_int(0, len(procs)))]
            proc.kill()
            cluster.fs.crash_machine(proc.machine.machine_id)
            revive_worker(cluster, proc)
