"""Chaos injector workloads: swizzled clogging and machine attrition.

Ref: fdbserver/workloads/RandomClogging.actor.cpp (random pairwise clogs;
the "swizzled" variant clogs a changing subset then releases in reverse),
fdbserver/workloads/MachineAttrition.actor.cpp (kill/reboot machines on a
cadence while invariant workloads run).
"""

from __future__ import annotations

from .base import TestWorkload


def revive_worker(cluster, proc):
    """Reboot a killed worker process and re-attach a fresh worker agent.

    Replaces the dead worker in the cluster's bookkeeping: stale
    WorkerServer objects hold FROZEN role instances (e.g. a storage whose
    version never advances again), which would poison any aggregate read
    off cluster.workers (status, quiet_database)."""
    from ..flow.asyncvar import AsyncVar
    from ..server.coordination import monitor_leader
    from ..server.worker import WorkerServer, run_worker_registration

    proc.reboot()
    w = WorkerServer(proc, cluster.fs)
    cluster.workers = [
        x for x in cluster.workers if x.process is not proc
    ] + [w]
    leader_var = AsyncVar(None)
    proc.spawn(
        monitor_leader(proc, getattr(cluster, "coord_set", cluster.coord_ifaces), leader_var),
        "leader_mon",
    )
    proc.spawn(run_worker_registration(w, leader_var), "registration")
    return w


class RandomCloggingWorkload(TestWorkload):
    """Clog random machine pairs for random durations (swizzled: several
    overlapping clogs whose releases interleave)."""

    name = "random_clogging"

    def __init__(self, duration: float = 3.0, max_clog: float = 0.4):
        self.duration = duration
        self.max_clog = max_clog

    async def start(self, db, cluster):
        loop = cluster.loop
        rng = loop.rng
        end = loop.now() + self.duration
        machines = sorted(cluster.net.machines)
        while loop.now() < end and len(machines) >= 2:
            i = int(rng.random_int(0, len(machines)))
            j = int(rng.random_int(0, len(machines) - 1))
            if j >= i:
                j += 1
            cluster.net.clog_pair(
                machines[i], machines[j], rng.random01() * self.max_clog
            )
            await loop.delay(0.05 + rng.random01() * 0.2)
        cluster.net.unclog_all()


class AttritionWorkload(TestWorkload):
    """Kill a random worker machine (disks crash per the corruption model),
    reboot it, and re-attach its worker agent; repeat.  The cluster must
    recover a new generation each time with zero acked-data loss."""

    name = "attrition"

    def __init__(self, kills: int = 2, delay_between: float = 1.0):
        self.kills = kills
        self.delay_between = delay_between

    async def start(self, db, cluster):
        loop = cluster.loop
        rng = loop.rng
        for _ in range(self.kills):
            await loop.delay(self.delay_between * (0.5 + rng.random01()))
            procs = [p for p in cluster._worker_procs if p.alive]
            if not procs:
                continue
            proc = procs[int(rng.random_int(0, len(procs)))]
            proc.kill()
            cluster.fs.crash_machine(proc.machine.machine_id)
            revive_worker(cluster, proc)
