"""ConsistencyCheck: cross-replica agreement, shard by shard.

Ref: fdbserver/workloads/ConsistencyCheck.actor.cpp:35, checkDataConsistency
:562 — for every shard, read the full range from EVERY replica in its team
at one version and compare; run after most simulation tests
(tester.actor.cpp:819).  Reads at a fresh read version double as the
QuietDatabase gate: waitForVersion blocks until each replica has applied
the log through that version (a replica that cannot catch up surfaces as
future_version, a loud failure).
"""

from __future__ import annotations

from ..flow.error import FdbError
from ..server.interfaces import GetKeyValuesRequest
from .base import TestWorkload


async def _read_range_from(db, iface, begin: bytes, end: bytes, version: int):
    """Page one replica's view of [begin, end) at `version`."""
    loop = db.process.network.loop
    rows = []
    lo = begin
    while lo < end:
        for attempt in range(200):
            try:
                rep = await iface.get_key_values.get_reply(
                    db.process,
                    GetKeyValuesRequest(
                        begin=lo, end=end, version=version, limit=1000
                    ),
                )
                break
            except FdbError as e:
                # future_version = the replica hasn't caught up yet (the
                # quiet-database wait); anything else is a real failure.
                if e.name not in ("future_version", "broken_promise"):
                    raise
                await loop.delay(0.05)
        else:
            raise FdbError("timed_out")
        rows.extend(rep.data)
        if not rep.more or not rep.data:
            break
        lo = rep.data[-1][0] + b"\x00"
    return rows


async def check_consistency(db, cluster=None) -> int:
    """Compare every multi-replica shard across its team; returns the
    number of (shard, replica-pair) comparisons that matched.  Raises
    AssertionError on divergence (ref: checkDataConsistency :562)."""
    tr = db.create_transaction()
    version = await tr.get_read_version()
    locs = await db.get_locations(b"", b"\xff")
    compared = 0
    for b, e, team in locs:
        if team is None or len(team) < 2:
            continue
        end = e if e is not None else b"\xff"
        baseline = None
        for iface in team:
            rows = await _read_range_from(db, iface, b, end, version)
            if baseline is None:
                baseline = (iface.storage_id, rows)
                continue
            bid, brows = baseline
            assert rows == brows, (
                f"replica divergence in [{b!r}, {end!r}) @ {version}: "
                f"{bid} has {len(brows)} rows, {iface.storage_id} has "
                f"{len(rows)}; first diff: "
                f"{next((x for x in zip(brows, rows) if x[0] != x[1]), None)}"
            )
            compared += 1
    return compared


class ConsistencyChecker(TestWorkload):
    """Workload wrapper: run check_consistency in the check phase."""

    name = "consistency_check"

    def __init__(self, require_comparisons: bool = False):
        self.require_comparisons = require_comparisons
        self.compared = 0

    async def check(self, db, cluster) -> bool:
        self.compared = await check_consistency(db, cluster)
        if self.require_comparisons and self.compared == 0:
            return False
        return True
