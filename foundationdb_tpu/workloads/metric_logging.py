"""MetricLogging: TDMetric series land in the database and read back.

Ref: fdbserver/workloads/MetricLogging.actor.cpp — drive counters while
the metric logger flushes them into the `\xff/metrics` keyspace, then
read the series back with ordinary transactions and check the
multi-resolution contract: level-0 records every flush, level i records
at most one sample per BASE_RESOLUTION*4^i seconds, every level's
series is time-monotone, and the final value equals the counter.
"""

from __future__ import annotations

from .base import TestWorkload


class MetricLoggingWorkload(TestWorkload):
    name = "metric_logging"

    def __init__(self, flushes: int = 6):
        self.flushes = flushes

    async def start(self, db, cluster):
        from ..client.metric_logger import (
            BASE_RESOLUTION,
            log_metrics_once,
        )
        from ..flow.stats import CounterCollection

        loop = cluster.loop
        coll = CounterCollection("wl_metrics")
        self._coll = coll
        for n in range(self.flushes):
            coll.add("ops", 3)
            coll.add("bytes", 100)
            await log_metrics_once(db, [coll])
            await loop.delay(BASE_RESOLUTION)

    async def check(self, db, cluster) -> bool:
        from ..client.metric_logger import (
            BASE_RESOLUTION,
            LEVELS,
            read_metric_levels,
            read_metrics,
        )

        series = await read_metrics(db, "wl_metrics")
        assert set(series) == {"ops", "bytes"}, sorted(series)
        ops0 = series["ops"]
        assert len(ops0) == self.flushes, ops0
        times = [t for t, _v in ops0]
        vals = [v for _t, v in ops0]
        assert times == sorted(times) and vals == sorted(vals)
        assert vals[-1] == self._coll.counters["ops"].value

        levels = await read_metric_levels(db, "wl_metrics", "ops")
        assert len(levels) == LEVELS
        for i, lv in enumerate(levels[1:], start=1):
            period = BASE_RESOLUTION * (4 ** i)
            for (t0, _), (t1, _) in zip(lv, lv[1:]):
                assert t1 - t0 >= period, (
                    f"level {i} sampled faster than {period}: {lv}"
                )
        return True
