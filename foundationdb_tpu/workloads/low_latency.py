"""LowLatency: single-op latency stays bounded while the cluster works.

Ref: fdbserver/workloads/LowLatency.actor.cpp — a probe loop issues one
small read or commit at a time and asserts each completes within a
bound; sustained latency above it means the ratekeeper, batching, or
GRV path is starving interactive work even though throughput looks
fine.  Virtual-time flavor: p95 under `p95_bound` and no more than
`slow_fraction` of ops over `slow_bound` (recoveries mid-chaos are
allowed to blow the max, so the max itself is not asserted).
"""

from __future__ import annotations

from ..flow.error import FdbError
from .base import TestWorkload


class LowLatencyWorkload(TestWorkload):
    name = "low_latency"

    def __init__(self, ops: int = 40, p95_bound: float = 0.5,
                 slow_bound: float = 2.0, slow_fraction: float = 0.15,
                 prefix: bytes = b"ll/"):
        self.ops = ops
        self.p95_bound = p95_bound
        self.slow_bound = slow_bound
        self.slow_fraction = slow_fraction
        self.prefix = prefix
        self.latencies = []

    async def start(self, db, cluster):
        loop = cluster.loop
        for n in range(self.ops):
            t0 = loop.now()
            try:
                if n % 2 == 0:

                    async def w(tr, n=n):
                        tr.set(self.prefix + b"%04d" % (n % 8), b"%d" % n)

                    await db.run(w)
                else:

                    async def r(tr, n=n):
                        await tr.get(self.prefix + b"%04d" % (n % 8))

                    await db.run(r)
                self.latencies.append(loop.now() - t0)
            except FdbError:
                self.latencies.append(loop.now() - t0)
            await loop.delay(0.05)

    async def check(self, db, cluster) -> bool:
        lat = sorted(self.latencies)
        assert len(lat) >= self.ops // 2
        p95 = lat[int(len(lat) * 0.95) - 1]
        slow = sum(1 for x in lat if x > self.slow_bound)
        assert p95 <= self.p95_bound, (
            f"p95 latency {p95:.3f} > {self.p95_bound} "
            f"(worst {lat[-1]:.3f})"
        )
        assert slow <= len(lat) * self.slow_fraction, (
            f"{slow}/{len(lat)} ops slower than {self.slow_bound}"
        )
        return True
