"""VersionStamp: stamped keys/values must carry the real commit version.

Ref: fdbserver/workloads/VersionStamp.actor.cpp — transactions write a
SET_VERSIONSTAMPED_KEY row (stamp embedded in the key) and a
SET_VERSIONSTAMPED_VALUE row (stamp as the value) and the check verifies
the landed stamps agree with the versions the commits actually got —
including commits whose result was unknown, which are resolved by reading
the stamp back (the reference re-reads on commit_unknown_result too).
"""

from __future__ import annotations

from ..client.types import MutationType
from .base import TestWorkload

PLACEHOLDER = b"\x00" * 10


class VersionStampWorkload(TestWorkload):
    name = "versionstamp"

    def __init__(self, actors: int = 3, ops: int = 6, prefix: bytes = b"vs/"):
        self.actors = actors
        self.ops = ops
        self.prefix = prefix
        # id -> commit version when the commit reported one (None for
        # commit_unknown_result resolved later by read-back).
        self.known: dict = {}

    def _vkey(self, ident: bytes) -> bytes:
        return self.prefix + b"v/" + ident

    async def start(self, db, cluster):
        from ..flow.eventloop import all_of

        async def actor(aid: int):
            for seq in range(self.ops):
                ident = b"%02d_%04d" % (aid, seq)

                async def op(tr, ident=ident):
                    # Idempotence: the stamped-value row marks the op done.
                    if await tr.get(self._vkey(ident)) is not None:
                        from ..flow.testprobe import test_probe

                        test_probe("versionstamp_retry_found_landed")
                        return False
                    # Key: vs/k/<10-byte stamp><ident>; placeholder offset
                    # is right after the "vs/k/" prefix.
                    kp = self.prefix + b"k/"
                    key_param = (
                        kp + PLACEHOLDER + ident + len(kp).to_bytes(4, "little")
                    )
                    tr.atomic_op(
                        MutationType.SET_VERSIONSTAMPED_KEY, key_param, ident
                    )
                    val_param = PLACEHOLDER + (0).to_bytes(4, "little")
                    tr.atomic_op(
                        MutationType.SET_VERSIONSTAMPED_VALUE,
                        self._vkey(ident),
                        val_param,
                    )
                    return True

                tr = db.create_transaction()
                while True:
                    try:
                        wrote = await op(tr)
                        version = await tr.commit()
                        if wrote and version is not None:
                            self.known[ident] = version
                        break
                    except Exception as e:  # FdbError incl. unknown result
                        from ..flow.error import FdbError

                        if not isinstance(e, FdbError):
                            raise
                        await tr.on_error(e)

        await all_of(
            [db.process.spawn(actor(a), f"vs{a}") for a in range(self.actors)]
        )

    async def check(self, db, cluster) -> bool:
        out = {}

        async def read(tr):
            out["vals"] = await tr.get_range(
                self.prefix + b"v/", self.prefix + b"v0"
            )
            out["keys"] = await tr.get_range(
                self.prefix + b"k/", self.prefix + b"k0"
            )

        await db.run(read)
        vals = {k[len(self.prefix) + 2:]: v for k, v in out["vals"]}
        total = self.actors * self.ops
        if len(vals) != total:
            return False
        # Each stamped value is a 10-byte stamp whose version half must
        # match the version the commit reported (when it reported one).
        for ident, stamp in vals.items():
            if len(stamp) != 10:
                return False
            v = int.from_bytes(stamp[:8], "big")
            if ident in self.known and v != self.known[ident]:
                return False
        # Exactly one stamped key per ident, embedding the same stamp the
        # value row got (same txn => same version + txn number).
        seen = {}
        for k, _v in out["keys"]:
            body = k[len(self.prefix) + 2:]
            stamp, ident = body[:10], body[10:]
            if ident in seen:
                return False  # an op landed twice
            seen[ident] = stamp
        if set(seen) != set(vals):
            return False
        if any(seen[i] != vals[i] for i in seen):
            return False
        # Key order == stamp order: the range scan already returns keys
        # ascending; stamps are the key prefix so they must be sorted.
        stamps = [k for k, _ in out["keys"]]
        return stamps == sorted(stamps)
