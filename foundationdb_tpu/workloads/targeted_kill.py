"""TargetedKill: destroy the machine hosting a SPECIFIC role, mid-load.

Ref: fdbserver/workloads/TargetedKill.actor.cpp — instead of random
attrition, kill the process serving a named role (proxy, tlog, storage,
the controller) at a chosen time; the cluster must recover a new
generation and every concurrent invariant workload must still check.
Targeting matters because each role exercises a different recovery path
(proxy: commit pipeline re-recruitment; tlog: epoch end + log recovery;
storage: team healing / replica routing; cc: re-election).
"""

from __future__ import annotations

from .base import TestWorkload


class TargetedKillWorkload(TestWorkload):
    name = "targeted_kill"

    def __init__(self, role: str = "storage0", at: float = 0.5,
                 reboot: bool = True):
        self.role = role
        self.at = at
        self.reboot = reboot
        self.killed = False

    async def start(self, db, cluster):
        from .chaos import revive_worker

        loop = cluster.loop
        await loop.delay(self.at)
        try:
            proc = cluster.kill_role_process(self.role)
        except (KeyError, RuntimeError):
            # Role not recruited under this topology, or no controller is
            # leader at kill time (mid-election): nothing to target.
            return
        self.killed = True
        cluster.fs.crash_machine(proc.machine.machine_id)
        if self.reboot:
            revive_worker(cluster, proc)

    async def check(self, db, cluster) -> bool:
        # The cluster must serve a fresh write+read after the kill.
        async def probe(tr):
            tr.set(b"tk_probe/" + self.role.encode(), b"recovered")

        await db.run(probe)
        out = {}

        async def read(tr):
            out["v"] = await tr.get(b"tk_probe/" + self.role.encode())

        await db.run(read)
        return out["v"] == b"recovered"
