"""FuzzApiCorrectness: random API call sequences vs a predictive model.

Ref: fdbserver/workloads/FuzzApiCorrectness.actor.cpp — every client API
entry point is invoked with randomized (frequently illegal) parameters; each
call carries a CONTRACT: either a predicted result (checked byte-exact
against an in-memory model) or a predicted error (checked by name).  The
reference enumerates op classes as TestGet/TestSet/TestClearRange/... with
per-op error tables (e.g. key_outside_legal_range for \\xff.. keys without
ACCESS_SYSTEM_KEYS, key_too_large / value_too_large over the size knobs,
inverted_range for begin > end, client_invalid_operation for malformed
versionstamp params, accessed_unreadable for reading a versionstamped key).

Ops run serially (the concurrency dimension is WriteDuringRead's job);
every txn commits or rolls the model back on conflict, so the model tracks
committed state exactly.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..client.atomic import apply_atomic
from ..client.transaction import KeySelector, key_after
from ..client.types import MutationType
from ..flow.error import FdbError
from ..flow.knobs import g_knobs
from .base import TestWorkload
from .write_during_read import ATOMIC_OPS, clamp_to_prefix, model_get_key


class FuzzApiWorkload(TestWorkload):
    name = "fuzz_api"

    def __init__(
        self,
        nodes: int = 24,
        txns: int = 20,
        ops_per_txn: int = 12,
        prefix: bytes = b"\x02fuzz/",
    ):
        self.nodes = nodes
        self.txns = txns
        self.ops_per_txn = ops_per_txn
        self.prefix = prefix
        self.model: Dict[bytes, bytes] = {}
        self.errors_exercised: set = set()
        self.failures: List[str] = []

    def _key(self, i: int) -> bytes:
        return self.prefix + b"%04d" % i

    def _rand_key(self, rng) -> bytes:
        return self._key(int(rng.random_int(0, self.nodes)))

    def _rand_value(self, rng) -> bytes:
        return bytes(
            int(rng.random_int(0, 256))
            for _ in range(int(rng.random_int(0, 16)))
        )

    def _fail(self, msg: str):
        self.failures.append(msg)

    async def _expect_error(self, name: str, thunk):
        """Run thunk; it must raise FdbError(name) (the op contract)."""
        try:
            r = thunk()
            if hasattr(r, "__await__"):
                await r
            self._fail(f"expected {name}, got success")
        except FdbError as e:
            if e.name != name:
                self._fail(f"expected {name}, got {e.name}")
            else:
                self.errors_exercised.add(name)

    async def _one_op(self, tr, staged: Dict[bytes, Optional[bytes]], rng):
        """One random (possibly illegal) op.  `staged` is this txn's RYW
        overlay on self.model; reads check against model+staged."""

        def view(key):
            return staged[key] if key in staged else self.model.get(key)

        r = rng.random01()
        ck = g_knobs.client
        if r < 0.14:  # legal point read
            key = self._rand_key(rng)
            want = view(key)
            got = await tr.get(key)
            if got != want:
                self._fail(f"get({key!r}) = {got!r}, want {want!r}")
        elif r < 0.26:  # legal set
            key, val = self._rand_key(rng), self._rand_value(rng)
            tr.set(key, val)
            staged[key] = val
        elif r < 0.34:  # legal clear / clear_range
            a = int(rng.random_int(0, self.nodes))
            b = min(self.nodes, a + int(rng.random_int(0, 5)))
            ka, kb = self._key(a), self._key(b)
            tr.clear_range(ka, kb)
            # Clear EVERY key in range — committed versionstamped keys sort
            # between node keys and must be cleared from the model too.
            for k in list(self.model) + list(staged):
                if ka <= k < kb:
                    staged[k] = None
        elif r < 0.44:  # legal atomic
            op = ATOMIC_OPS[int(rng.random_int(0, len(ATOMIC_OPS)))]
            key, operand = self._rand_key(rng), self._rand_value(rng)
            tr.atomic_op(op, key, operand)
            staged[key] = apply_atomic(op, view(key), operand)
        elif r < 0.52:  # legal range read
            a = int(rng.random_int(0, self.nodes))
            b = min(self.nodes, a + int(rng.random_int(0, 8)))
            got = await tr.get_range(self._key(a), self._key(b))
            merged = {
                k: v
                for k, v in list(self.model.items())
                if self._key(a) <= k < self._key(b)
            }
            for k, v in staged.items():
                if self._key(a) <= k < self._key(b):
                    if v is None:
                        merged.pop(k, None)
                    else:
                        merged[k] = v
            want = sorted(merged.items())
            if got != want:
                self._fail(f"get_range[{a}:{b}] {len(got)} != {len(want)}")
        elif r < 0.58:  # system write without the option
            await self._expect_error(
                "key_outside_legal_range",
                lambda: tr.set(b"\xff/fuzz", b"x"),
            )
        elif r < 0.64:  # system read without the option
            await self._expect_error(
                "key_outside_legal_range", lambda: tr.get(b"\xff/fuzz")
            )
        elif r < 0.70:  # oversized key
            big = self.prefix + b"k" * (ck.key_size_limit + 1)
            await self._expect_error("key_too_large", lambda: tr.set(big, b"v"))
        elif r < 0.76:  # oversized value
            await self._expect_error(
                "value_too_large",
                lambda: tr.set(
                    self._rand_key(rng), b"v" * (ck.value_size_limit + 1)
                ),
            )
        elif r < 0.82:  # inverted clear range
            await self._expect_error(
                "inverted_range",
                lambda: tr.clear_range(self._key(5), self._key(2)),
            )
        elif r < 0.88:  # malformed versionstamp param (bad offset)
            await self._expect_error(
                "client_invalid_operation",
                lambda: tr.atomic_op(
                    MutationType.SET_VERSIONSTAMPED_VALUE,
                    self._rand_key(rng),
                    b"short" + (200).to_bytes(4, "little"),
                ),
            )
        elif r < 0.94:  # read of a versionstamped key -> unreadable
            key = self._rand_key(rng)
            stamp_param = key + b"\x00" * 10 + (len(key)).to_bytes(4, "little")
            tr.atomic_op(
                MutationType.SET_VERSIONSTAMPED_KEY, stamp_param, b"v"
            )
            # Any key inside the possible stamp range is unreadable until
            # commit resolves the stamp.
            await self._expect_error(
                "accessed_unreadable", lambda: tr.get(key + b"\x00" * 10)
            )
            # The stamped key is unknowable pre-commit: mark the txn
            # poisoned — start() commits immediately and resyncs the model
            # from the database.
            self._poisoned = True
        else:  # key selector resolution (legal)
            sel = KeySelector(
                key=self._rand_key(rng),
                or_equal=rng.random01() < 0.5,
                offset=int(rng.random_int(-3, 4)),
            )
            got = await tr.get_key(sel)
            merged = dict(self.model)
            for k, v in staged.items():
                if v is None:
                    merged.pop(k, None)
                else:
                    merged[k] = v
            want = model_get_key(merged, sel)
            got_c = clamp_to_prefix(got, self.prefix)
            want_c = clamp_to_prefix(want, self.prefix)
            if got_c != want_c:
                self._fail(
                    f"get_key({sel.key!r},{sel.or_equal},{sel.offset}) = "
                    f"{got!r}, want {want!r}"
                )

    async def start(self, db, cluster):
        rng = cluster.loop.rng
        for _ in range(self.txns):
            tr = db.create_transaction()
            staged: Dict[bytes, Optional[bytes]] = {}
            self._poisoned = False
            try:
                for _ in range(self.ops_per_txn):
                    await self._one_op(tr, staged, rng)
                    if self._poisoned:
                        # A versionstamped key makes part of the keyspace
                        # unreadable for the rest of this txn; commit now
                        # and resync the model (the stamp is unknowable).
                        break
                await tr.commit()
            except FdbError as e:
                if e.is_retryable_in_transaction() or e.name in (
                    "broken_promise",
                    "commit_unknown_result",
                ):
                    # Roll back the model; unknown results would need the
                    # marker protocol (WriteDuringRead has it) — here we
                    # resync the model from the database instead.
                    await self._resync(db)
                    continue
                raise
            if self._poisoned:
                await self._resync(db)
                continue
            for k, v in staged.items():
                if v is None:
                    self.model.pop(k, None)
                else:
                    self.model[k] = v

    async def _resync(self, db):
        out = {}

        async def read(tr):
            out["rows"] = await tr.get_range(self.prefix, self.prefix + b"\xff")

        await db.run(read)
        self.model = dict(out["rows"])

    async def check(self, db, cluster) -> bool:
        out = {}

        async def read(tr):
            out["rows"] = await tr.get_range(self.prefix, self.prefix + b"\xff")

        await db.run(read)
        db_state = {
            k: v for k, v in out["rows"] if not k.startswith(self.prefix + b"!")
        }
        if db_state != self.model:
            self._fail(
                f"final: db {len(db_state)} keys != model {len(self.model)}"
            )
        if self.failures:
            import sys

            for f in self.failures[:10]:
                print(f"[fuzz_api] FAIL: {f}", file=sys.stderr)
        # The sweep must actually exercise several error contracts.
        return not self.failures and len(self.errors_exercised) >= 3
