"""RemoveServersSafely: exclude -> drain -> kill, with zero data loss.

Ref: fdbserver/workloads/RemoveServersSafely.actor.cpp — the safe-removal
discipline: write the exclusion (the operator action), wait for data
distribution to relocate every shard off the excluded server, and only
then destroy it.  The check asserts the shard map no longer references
the victim anywhere, every surviving team serves identical data, and the
client reads everything through normal routing.

Requires the self-driving DD role (server/dd_role.py) to be running: the
workload itself performs no moves.
"""

from __future__ import annotations

from .base import TestWorkload


class RemoveServersSafelyWorkload(TestWorkload):
    name = "remove_servers_safely"

    def __init__(self, victim: str, dd, kill_process=None,
                 drain_timeout: float = 600.0):
        """victim: storage id to remove; dd: a DataDistributor (reader);
        kill_process: the victim's Process, killed once drained."""
        self.victim = victim
        self.dd = dd
        self.kill_process = kill_process
        self.drain_timeout = drain_timeout
        self.drained = False

    async def start(self, db, cluster):
        from ..client.management import exclude_servers

        loop = cluster.loop
        await exclude_servers(db, [self.victim])
        deadline = loop.now() + self.drain_timeout
        while loop.now() < deadline:
            rows = await self.dd.read_shard_map()
            if rows and all(
                self.victim not in set(team) | set(dest)
                for _b, _e, team, dest in rows
            ):
                self.drained = True
                break
            await loop.delay(0.5)
        # Only a DRAINED server is safe to destroy (the workload's whole
        # point); killing early would test attrition instead.
        if self.drained and self.kill_process is not None:
            self.kill_process.kill()

    async def check(self, db, cluster) -> bool:
        if not self.drained:
            return False
        rows = await self.dd.read_shard_map()
        if any(
            self.victim in set(team) | set(dest)
            for _b, _e, team, dest in rows
        ):
            return False

        # Reads still work through normal routing after the kill.
        async def probe(tr):
            return await tr.get_range(b"", b"\xff", limit=1000)

        await db.run(probe)
        return True
