"""Unreadable: reads over pending versionstamped keys must error, never lie.

Ref: fdbserver/workloads/Unreadable.actor.cpp — after a
SET_VERSIONSTAMPED_KEY mutation, any read intersecting the stamp's
placeholder range inside the SAME transaction must raise
accessed_unreadable (the key's final bytes are unknowable before commit);
reads that do not intersect must still succeed.
"""

from __future__ import annotations

from ..client.types import MutationType
from ..flow.error import FdbError
from .base import TestWorkload

PLACEHOLDER = b"\x00" * 10


class UnreadableWorkload(TestWorkload):
    name = "unreadable"

    def __init__(self, rounds: int = 6, prefix: bytes = b"unr/"):
        self.rounds = rounds
        self.prefix = prefix
        self.violations = 0
        self.checked = 0

    async def start(self, db, cluster):
        for r in range(self.rounds):
            await self._round(db, r)

    async def _round(self, db, r: int):
        """One probe round, RETRIED whole on infrastructure errors
        (clogging/recovery/lock windows are not unreadability violations;
        only a read that returns data — or a wrong error — inside a stamp
        range counts)."""
        kp = self.prefix + b"%02d/" % r
        key_param = kp + PLACEHOLDER + len(kp).to_bytes(4, "little")
        tr = db.create_transaction()
        while True:
            probes: list = []
            try:
                if await tr.get(kp + b"!done") is not None:
                    # Unknown-result retry whose first attempt landed: its
                    # probes ran (they precede the commit) but their
                    # outcomes were discarded with the exception; credit
                    # the round so the checked-count gate stays exact.
                    self.checked += 3
                    return
                tr.atomic_op(
                    MutationType.SET_VERSIONSTAMPED_KEY, key_param, b"v"
                )
                # Intersecting reads: point get inside the stamp range and
                # a range scan across it must both raise.
                async def probe_one(op):
                    try:
                        if op == "get":
                            # Inside [kp+\x00*10, kp+\xff*10] — a shorter
                            # key would sort BELOW the range and legally
                            # read.
                            await tr.get(kp + b"\x42" * 10)
                        else:
                            await tr.get_range(kp, kp + b"\xff")
                        return "read_succeeded"  # the violation
                    except FdbError as e:
                        if e.name == "accessed_unreadable":
                            return "ok"
                        raise  # infrastructure error: retry the round

                for op in ("get", "range"):
                    probes.append((op, await probe_one(op)))
                # A disjoint read in the same transaction still works.
                await tr.get(self.prefix + b"elsewhere")
                probes.append(("disjoint", "ok"))
                tr.set(kp + b"!done", b"1")
                await tr.commit()
            except FdbError as e:
                await tr.on_error(e)  # raises if non-retryable
                continue
            for _op, outcome in probes:
                self.checked += 1
                if outcome != "ok":
                    self.violations += 1
            return

    async def check(self, db, cluster) -> bool:
        if self.violations or self.checked != 3 * self.rounds:
            return False

        # Every round's stamped key landed and is readable AFTER commit.
        out = {}

        async def read(tr):
            out["rows"] = await tr.get_range(self.prefix, self.prefix + b"\xff")

        await db.run(read)
        stamped = [
            k for k, _v in out["rows"] if not k.endswith(b"!done")
        ]
        done = [k for k, _v in out["rows"] if k.endswith(b"!done")]
        return len(stamped) >= self.rounds and len(done) == self.rounds
