"""TimeKeeperCorrectness: the CC's wall-clock -> version map is sane.

Ref: fdbserver/workloads/TimeKeeperCorrectness.actor.cpp — the workload
records (time, read version) pairs itself while running, then checks the
timeKeeper map against them: samples must be monotone in BOTH time and
version, and mapping any recorded time through the map must return a
version between the versions the workload observed just before and just
after that time (the map is how `fdbbackup restore --timestamp` picks a
restore version, so an off sample silently restores the wrong state).
"""

from __future__ import annotations

from .base import TestWorkload


class TimeKeeperWorkload(TestWorkload):
    name = "time_keeper"

    def __init__(self, duration: float = 12.0):
        self.duration = duration
        self.observed = []  # (time, read_version) pairs seen by US

    async def start(self, db, cluster):
        loop = cluster.loop
        end = loop.now() + self.duration
        while loop.now() < end:

            async def grv(tr):
                return await tr.get_read_version()

            v = await db.run(grv)
            self.observed.append((loop.now(), v))
            await loop.delay(0.5)

    async def check(self, db, cluster) -> bool:
        from ..client.management import version_from_timestamp
        from ..server.system_keys import (
            TIME_KEEPER_END,
            TIME_KEEPER_PREFIX,
            time_keeper_time,
        )

        out = {}

        async def read(tr):
            tr.options["access_system_keys"] = True
            out["rows"] = await tr.get_range(
                TIME_KEEPER_PREFIX, TIME_KEEPER_END
            )

        await db.run(read)
        samples = [
            (time_keeper_time(k), int(v)) for k, v in out["rows"]
        ]
        assert len(samples) >= 2, f"too few timekeeper samples: {samples}"
        times = [t for t, _v in samples]
        vers = [v for _t, v in samples]
        assert times == sorted(times) and len(set(times)) == len(times)
        assert vers == sorted(vers), "versions not monotone over time"

        # Mapping consistency against our own observations.  Sample keys
        # have ONE-SECOND granularity (int(now), like the reference's
        # epoch-second map keys), so a sample keyed at second ⌊T⌋ may have
        # been taken anywhere inside that second: the tight bound is that
        # mapping time T must not exceed any version we observed after
        # the NEXT second boundary.
        # Snapshot: the observation actor appends while the mapping reads
        # below suspend this check — iterating the live list would chase a
        # moving tail (appends during iteration don't raise, they extend
        # the walk).  The inner `later` comprehension re-reads on purpose.
        for t_obs, _v in list(self.observed):
            if t_obs < times[0]:
                continue
            later = [
                v for t, v in self.observed if t >= int(t_obs) + 1.0
            ]
            if not later:
                continue
            mapped = await version_from_timestamp(db, t_obs)
            assert mapped <= later[0], (
                f"map points past the future: time {t_obs} -> {mapped} "
                f"but we read {later[0]} after second {int(t_obs) + 1}"
            )
        return True
