"""AtomicRestore: a restore lands atomically on a LIVE cluster.

Ref: fdbserver/workloads/AtomicRestore.actor.cpp — traffic runs, a
backup is taken, MORE traffic runs, then atomicRestore() rewinds the
range on the live cluster.  The checks: (1) every observer transaction
sees either entirely-pre-restore or entirely-post-restore state — the
database lock makes a torn observation impossible (non-lock-aware work
fails database_locked during the flip); (2) after the restore the range
is byte-exact the backup image; (3) traffic resumes normally afterwards.
"""

from __future__ import annotations

from ..flow.error import FdbError
from .base import TestWorkload


class AtomicRestoreWorkload(TestWorkload):
    name = "atomic_restore"

    def __init__(self, rows: int = 60, prefix: bytes = b"ar/"):
        self.rows = rows
        self.prefix = prefix
        self.torn = []
        self.locked_seen = 0

    def _key(self, i: int) -> bytes:
        return self.prefix + b"%04d" % i

    async def start(self, db, cluster):
        from ..fileio import SimFileSystem
        from ..layers.backup import ContinuousBackupAgent, BackupContainer

        loop = cluster.loop
        fs = getattr(cluster, "fs", None) or SimFileSystem(cluster.net)

        async def epoch1(tr):
            for i in range(self.rows):
                tr.set(self._key(i), b"epoch1-%d" % i)

        await db.run(epoch1)
        agent = ContinuousBackupAgent(
            db, fs, [t.interface() for t in cluster.tlogs],
            BackupContainer(fs, db.process, "ar_backup"),
        )
        await agent.start(self.prefix, self.prefix + b"\xff")
        await agent.tail_once()

        async def epoch2(tr):
            for i in range(self.rows):
                tr.set(self._key(i), b"epoch2-%d" % i)
            tr.set(self.prefix + b"extra", b"post-backup")

        await db.run(epoch2)

        # Observer: every successful read must be all-epoch1 or
        # all-epoch2 — a mix is a torn restore observation.
        stop = []

        async def observer():
            while not stop:
                tr = db.create_transaction()
                try:
                    # [prefix, prefix+":") covers the %04d keys (":" is
                    # the successor of "9") and excludes the ar/extra and
                    # ar/after sentinels.
                    rows = await tr.get_range(
                        self.prefix, self.prefix + b":"
                    )
                except FdbError as e:
                    if e.name == "database_locked":
                        self.locked_seen += 1
                    await loop.delay(0.005)
                    continue
                epochs = {v.split(b"-")[0] for _k, v in rows}
                if rows:
                    self.observed_scans = getattr(
                        self, "observed_scans", 0
                    ) + 1
                if len(epochs) > 1:
                    self.torn.append(sorted(epochs))
                await loop.delay(0.005)

        obs = db.process.spawn(observer(), "ar_obs")
        await loop.delay(0.2)
        # Tiny batches widen the locked window so the observer
        # demonstrably hits it (the atomicity property under test).
        restored_v = await agent.atomic_restore(batch_rows=5)
        assert restored_v > 0
        stop.append(True)
        # Unconditional await: a ready-but-errored observer must re-raise
        # here, not be silently dropped.
        await obs

        # Post-restore: byte-exact the backup image (epoch1, no extra).
        out = {}

        async def readback(tr):
            out["rows"] = await tr.get_range(self.prefix, self.prefix + b"\xff")

        await db.run(readback)
        want = [(self._key(i), b"epoch1-%d" % i) for i in range(self.rows)]
        assert out["rows"] == want, (
            f"restored range not byte-exact: {out['rows'][:3]} "
            f"({len(out['rows'])} rows vs {len(want)})"
        )

        # Traffic resumes.
        async def after(tr):
            tr.set(self.prefix + b"after", b"ok")

        await db.run(after)

    async def check(self, db, cluster) -> bool:
        assert not self.torn, f"torn restore observations: {self.torn[:3]}"
        out = {}

        async def read(tr):
            out["v"] = await tr.get(self.prefix + b"after")

        await db.run(read)
        return out["v"] == b"ok"
