"""Perf-measurement workloads: Throughput, WriteBandwidth, StreamingRead,
Ping — measure, sanity-gate, and PUBLISH into the metrics keyspace.

Ref: fdbserver/workloads/{Throughput,WriteBandwidth,StreamingRead,
Ping}.actor.cpp — the reference's perf corpus reports metrics through
getMetrics(); here each workload writes its measured rates into
`\xff/metrics` via the TDMetric logger, so the numbers are readable
back through ordinary transactions (and the sanity gates catch a
collapsed data path even in a correctness-focused sim run).  All rates
are virtual-time rates: deterministic per seed, comparable across runs.
"""

from __future__ import annotations

from ..flow.error import FdbError
from .base import TestWorkload


class _PerfBase(TestWorkload):
    def __init__(self, prefix: bytes):
        self.prefix = prefix
        self.metrics: dict = {}

    async def _publish(self, db, cluster):
        from ..client.metric_logger import log_metrics_once
        from ..flow.stats import CounterCollection

        coll = CounterCollection(f"wl_{self.name}")
        for name, value in self.metrics.items():
            coll.add(name, int(value))
        await log_metrics_once(db, [coll])

    async def _verify_published(self, db) -> bool:
        from ..client.metric_logger import read_metrics

        series = await read_metrics(db, f"wl_{self.name}")
        return set(series) == set(self.metrics) and all(
            series[k][-1][1] == int(v) for k, v in self.metrics.items()
        )


class ThroughputWorkload(_PerfBase):
    """Sustained mixed read/write transactions; gates txn/s(vt) > 0 and
    publishes the measured rate (ref: Throughput.actor.cpp)."""

    name = "throughput"

    def __init__(self, actors: int = 3, txns_per_actor: int = 15,
                 prefix: bytes = b"tput/"):
        super().__init__(prefix)
        self.actors = actors
        self.txns_per_actor = txns_per_actor

    async def start(self, db, cluster):
        from ..flow.eventloop import all_of

        loop = cluster.loop
        rng = loop.rng
        t0 = loop.now()
        done = [0]

        async def actor(aid: int):
            for _i in range(self.txns_per_actor):
                async def op(tr, aid=aid):
                    k = self.prefix + b"%02d%04d" % (
                        aid, int(rng.random_int(0, 50))
                    )
                    v = await tr.get(k)
                    tr.set(k, b"%d" % (int(v or b"0") + 1))

                try:
                    await db.run(op)
                    done[0] += 1
                except FdbError:
                    pass

        await all_of([
            db.process.spawn(actor(a), f"tput{a}") for a in range(self.actors)
        ])
        dt = max(loop.now() - t0, 1e-9)
        self.metrics = {
            "transactions": done[0],
            "txn_per_vsec_x100": int(done[0] / dt * 100),
        }
        await self._publish(db, cluster)

    async def check(self, db, cluster) -> bool:
        assert self.metrics["transactions"] >= (
            self.actors * self.txns_per_actor * 3 // 4
        )
        assert self.metrics["txn_per_vsec_x100"] > 0
        return await self._verify_published(db)


class WriteBandwidthWorkload(_PerfBase):
    """Large-value write pressure; gates bytes/vsec > 0 and byte-exact
    readback of the last round (ref: WriteBandwidth.actor.cpp)."""

    name = "write_bandwidth"

    def __init__(self, rounds: int = 6, keys_per_round: int = 8,
                 value_len: int = 512, prefix: bytes = b"wbw/"):
        super().__init__(prefix)
        self.rounds = rounds
        self.keys_per_round = keys_per_round
        self.value_len = value_len

    async def start(self, db, cluster):
        loop = cluster.loop
        t0 = loop.now()
        written = 0
        for r in range(self.rounds):
            async def wr(tr, r=r):
                for i in range(self.keys_per_round):
                    tr.set(
                        self.prefix + b"%04d" % i,
                        (b"r%d-" % r) + b"x" * self.value_len,
                    )

            try:
                await db.run(wr)
                written += self.keys_per_round * (self.value_len + 8)
            except FdbError:
                pass
        dt = max(loop.now() - t0, 1e-9)
        self.metrics = {
            "bytes_written": written,
            "bytes_per_vsec": int(written / dt),
        }
        await self._publish(db, cluster)

    async def check(self, db, cluster) -> bool:
        assert self.metrics["bytes_written"] > 0
        out = {}

        async def rd(tr):
            out["rows"] = await tr.get_range(
                self.prefix, self.prefix + b"\xff"
            )

        await db.run(rd)
        last = b"r%d-" % (self.rounds - 1)
        assert len(out["rows"]) == self.keys_per_round
        assert all(v.startswith(last) for _k, v in out["rows"])
        return await self._verify_published(db)


class StreamingReadWorkload(_PerfBase):
    """Sequential paged streaming over a loaded range; gates rows/vsec
    and byte-exactness (ref: StreamingRead.actor.cpp)."""

    name = "streaming_read"

    def __init__(self, rows: int = 150, page: int = 25,
                 passes: int = 3, prefix: bytes = b"sr/"):
        super().__init__(prefix)
        self.rows = rows
        self.page = page
        self.passes = passes

    async def setup(self, db, cluster):
        for lo in range(0, self.rows, 50):
            async def fill(tr, lo=lo):
                for i in range(lo, min(self.rows, lo + 50)):
                    tr.set(self.prefix + b"%06d" % i, b"s%d" % i)

            await db.run(fill)

    async def start(self, db, cluster):
        from ..client.types import key_after

        loop = cluster.loop
        t0 = loop.now()
        streamed = 0
        for _p in range(self.passes):
            cursor = self.prefix
            while True:
                async def page(tr, cursor=cursor):
                    return await tr.get_range(
                        cursor, self.prefix + b"\xff", limit=self.page
                    )

                try:
                    rows = await db.run(page)
                except FdbError:
                    break
                streamed += len(rows)
                if len(rows) < self.page:
                    break
                cursor = key_after(rows[-1][0])
        dt = max(loop.now() - t0, 1e-9)
        self.metrics = {
            "rows_streamed": streamed,
            "rows_per_vsec": int(streamed / dt),
        }
        await self._publish(db, cluster)

    async def check(self, db, cluster) -> bool:
        assert self.metrics["rows_streamed"] >= self.rows  # >= one full pass
        return await self._verify_published(db)


class PingWorkload(_PerfBase):
    """GRV round-trip latency distribution: the cheapest full-fabric RPC
    (client -> proxy -> [rk/sequencer]) — gates p50 under a bound and
    publishes microsecond percentiles (ref: Ping.actor.cpp)."""

    name = "ping"

    def __init__(self, pings: int = 30):
        super().__init__(b"ping/")
        self.pings = pings

    async def start(self, db, cluster):
        loop = cluster.loop
        lats = []
        for _ in range(self.pings):
            tr = db.create_transaction()
            t0 = loop.now()
            try:
                await tr.get_read_version()
            except FdbError:
                continue
            lats.append(loop.now() - t0)
            await loop.delay(0.02)
        lats.sort()
        if lats:
            self.metrics = {
                "pings": len(lats),
                "p50_us": int(lats[len(lats) // 2] * 1e6),
                "p99_us": int(lats[min(len(lats) - 1, int(len(lats) * 0.99))] * 1e6),
            }
        await self._publish(db, cluster)

    async def check(self, db, cluster) -> bool:
        assert self.metrics.get("pings", 0) >= self.pings // 2
        assert self.metrics["p50_us"] < 1_000_000  # < 1 virtual second
        return await self._verify_published(db)
