"""BackgroundSelectors: key-selector resolution stays snapshot-consistent
while the keyspace churns underneath.

Ref: fdbserver/workloads/BackgroundSelectors.actor.cpp — one actor
resolves randomized relative selectors while others insert and delete
around the probe points; each resolution is validated against a range
read IN THE SAME TRANSACTION (one snapshot), so any cross-shard /
cache-staleness drift in selector resolution shows as a mismatch even
though the global state never stops moving.
"""

from __future__ import annotations

from ..client.transaction import KeySelector
from ..flow.error import FdbError
from .base import TestWorkload


class BackgroundSelectorsWorkload(TestWorkload):
    name = "background_selectors"

    def __init__(self, keyspace: int = 40, probes: int = 25,
                 churners: int = 2, prefix: bytes = b"bsel/"):
        self.keyspace = keyspace
        self.probes = probes
        self.churners = churners
        self.prefix = prefix
        self.checked = 0
        self._stop = False

    def _key(self, i: int) -> bytes:
        return self.prefix + b"%04d" % i

    async def setup(self, db, cluster):
        async def fill(tr):
            for i in range(0, self.keyspace, 2):
                tr.set(self._key(i), b"v%d" % i)

        await db.run(fill)

    async def start(self, db, cluster):
        from ..flow.eventloop import all_of

        rng = cluster.loop.rng
        loop = cluster.loop

        async def churn(aid: int):
            while not self._stop:
                i = int(rng.random_int(0, self.keyspace))

                async def op(tr, i=i):
                    if rng.random_int(0, 2) == 0:
                        tr.set(self._key(i), b"c%d" % aid)
                    else:
                        tr.clear(self._key(i))

                try:
                    await db.run(op)
                except FdbError:
                    pass
                await loop.delay(0.01)

        churners = [
            db.process.spawn(churn(a), f"bsel_churn{a}")
            for a in range(self.churners)
        ]
        try:
            for _p in range(self.probes):
                anchor = self._key(int(rng.random_int(0, self.keyspace)))
                offset = int(rng.random_int(1, 4))
                or_equal = bool(rng.random_int(0, 2))

                async def probe(tr, anchor=anchor, offset=offset,
                                or_equal=or_equal):
                    from .write_during_read import (
                        clamp_to_prefix,
                        model_get_key,
                    )

                    sel = KeySelector(anchor, or_equal, offset)
                    resolved = await tr.get_key(sel)
                    rows = await tr.get_range(
                        self.prefix, self.prefix + b"\xff", snapshot=True
                    )
                    # CLAMPED comparison (the discipline
                    # selector_correctness already uses): get_key resolves
                    # over the WHOLE keyspace, so a probe walking past this
                    # workload's slice may land on a co-running workload's
                    # key — both sides clamp to the prefix so the model
                    # only asserts what this slice determines.
                    want = model_get_key(dict(rows), sel)
                    got_c = clamp_to_prefix(resolved, self.prefix)
                    want_c = clamp_to_prefix(want, self.prefix)
                    assert got_c == want_c, (
                        f"selector({anchor}, or_equal={or_equal}, "
                        f"+{offset}) -> {resolved} (clamped {got_c}), "
                        f"model {want} (clamped {want_c})"
                    )

                try:
                    await db.run(probe)
                    self.checked += 1
                except FdbError:
                    continue
                await loop.delay(0.02)
        finally:
            self._stop = True
            await all_of(churners)

    async def check(self, db, cluster) -> bool:
        return self.checked >= self.probes // 2
