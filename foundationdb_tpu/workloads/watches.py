"""Watches: change notifications fire exactly when values change.

Ref: fdbserver/workloads/Watches.actor.cpp — chains of watchers: setter
writes key N's new value, the watcher on N wakes and propagates to key
N+1, around a ring; the workload measures that every watch FIRES on a
real change and does NOT fire spuriously (a fired watch must observe a
value different from the one it was set against).
"""

from __future__ import annotations

from .base import TestWorkload


class WatchesWorkload(TestWorkload):
    name = "watches"

    def __init__(self, chain: int = 4, rounds: int = 5,
                 prefix: bytes = b"watch/"):
        self.chain = chain
        self.rounds = rounds
        self.prefix = prefix
        self.fired = 0
        self.spurious = 0

    def _key(self, i: int) -> bytes:
        return self.prefix + b"%04d" % i

    async def setup(self, db, cluster):
        async def init(tr):
            for i in range(self.chain):
                tr.set(self._key(i), b"r-1")

        await db.run(init)

    async def start(self, db, cluster):
        from ..flow.eventloop import all_of

        async def propagator(i: int):
            """Watch key i; when it changes to round r, write key i+1."""
            nxt = (i + 1) % self.chain
            for r in range(self.rounds):
                want = b"r%d" % r
                while True:
                    tr = db.create_transaction()
                    cur = await tr.get(self._key(i))
                    if cur == want:
                        break
                    fut = await tr.watch(self._key(i))
                    await tr.commit()  # read-only: registers at read version
                    await fut
                    self.fired += 1
                    tr2 = db.create_transaction()
                    after = await tr2.get(self._key(i))
                    if after == cur:
                        self.spurious += 1
                if nxt != 0:

                    async def push(tr, nxt=nxt, want=want):
                        tr.set(self._key(nxt), want)

                    await db.run(push)

        async def driver():
            loop = cluster.loop
            for r in range(self.rounds):
                async def kick(tr, r=r):
                    tr.set(self._key(0), b"r%d" % r)

                await db.run(kick)
                # Wait until the chain's tail reflects this round.
                tail = self._key(self.chain - 1)
                while True:
                    out = {}

                    async def read(tr):
                        out["v"] = await tr.get(tail)

                    await db.run(read)
                    if out["v"] == b"r%d" % r:
                        break
                    await loop.delay(0.01)

        await all_of(
            [db.process.spawn(driver(), "watch_driver")]
            + [
                db.process.spawn(propagator(i), f"watch_prop{i}")
                for i in range(self.chain)
            ]
        )

    async def check(self, db, cluster) -> bool:
        return self.spurious == 0 and self.fired > 0
