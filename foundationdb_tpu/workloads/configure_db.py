"""ConfigureDatabase: live configuration churn under load.

Ref: fdbserver/workloads/ConfigureDatabase.actor.cpp — random `configure`
commands fired while other workloads run; every change lands as an
ordinary transaction on `\xff/conf`, the cluster controller reacts with a
new generation, and the database must stay correct throughout.  The check
asserts the final configuration matches the last change applied and the
database still commits.
"""

from __future__ import annotations

from .base import TestWorkload


class ConfigureDatabaseWorkload(TestWorkload):
    name = "configure_database"

    def __init__(self, changes: int = 4, delay_between: float = 0.8):
        self.changes = changes
        self.delay_between = delay_between
        self.final: dict = {}

    async def start(self, db, cluster):
        from ..client.management import configure

        loop = cluster.loop
        rng = loop.rng
        for _ in range(self.changes):
            params = {
                "proxies": 1 + int(rng.random_int(0, 3)),
                "resolvers": 1 + int(rng.random_int(0, 2)),
            }
            await configure(db, **params)
            self.final = params
            await loop.delay(self.delay_between * (0.5 + rng.random01()))

    async def check(self, db, cluster) -> bool:
        from ..client.management import get_configuration

        conf = await get_configuration(db)
        for k, v in self.final.items():
            if conf.get(k) != v:
                return False

        # The database must still commit and read through whatever
        # generations the churn caused.
        async def probe(tr):
            tr.set(b"conf_probe", b"alive")

        await db.run(probe)
        out = {}

        async def read(tr):
            out["v"] = await tr.get(b"conf_probe")

        await db.run(read)
        return out["v"] == b"alive"
