"""Seed-randomized simulation topology.

Ref: SimulatedCluster.actor.cpp:673 — SimulationConfig randomizes the
replication mode, machine/process counts, and datacenter layout per seed so
every simulation run exercises a different cluster shape.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..flow.rng import DeterministicRandom


@dataclass
class SimulationConfig:
    n_workers: int = 5
    n_coordinators: int = 3
    n_controllers: int = 2
    n_tlogs: int = 1
    n_storages: int = 1
    n_proxies: int = 1

    @classmethod
    def random(cls, seed: int) -> "SimulationConfig":
        rng = DeterministicRandom(seed ^ 0x5EED)
        n_tlogs = int(rng.random_int(1, 3))
        n_storages = int(rng.random_int(1, 3))
        n_proxies = int(rng.random_int(1, 3))
        # Enough workers that stateful disks, proxies, and the resolver/
        # sequencer can spread out (plus headroom for attrition).
        n_workers = max(n_tlogs + n_storages + 2, int(rng.random_int(5, 9)))
        return cls(
            n_workers=n_workers,
            n_coordinators=int(rng.random_int(0, 2)) * 2 + 1,  # 1 or 3
            n_controllers=int(rng.random_int(1, 3)),
            n_tlogs=n_tlogs,
            n_storages=n_storages,
            n_proxies=n_proxies,
        )

    def build(self, seed: int):
        from ..server.dynamic_cluster import DynamicCluster

        return DynamicCluster(
            seed=seed,
            n_coordinators=self.n_coordinators,
            n_workers=self.n_workers,
            n_controllers=self.n_controllers,
            n_tlogs=self.n_tlogs,
            n_storages=self.n_storages,
            n_proxies=self.n_proxies,
        )
