"""Increment: concurrent read-modify-write counters sum exactly.

Ref: fdbserver/workloads/Increment.actor.cpp — N actors each perform M
serializable increments of random counters; the grand total must equal
exactly N*M through any conflicts and retries (lost updates are the
failure serializability forbids).
"""

from __future__ import annotations

from .base import TestWorkload


class IncrementWorkload(TestWorkload):
    name = "increment"

    def __init__(self, counters: int = 3, actors: int = 3, ops: int = 10,
                 prefix: bytes = b"incr/"):
        self.counters = counters
        self.actors = actors
        self.ops = ops
        self.prefix = prefix

    def _key(self, i: int) -> bytes:
        return self.prefix + b"%03d" % i

    async def start(self, db, cluster):
        from ..flow.eventloop import all_of

        rng = cluster.loop.rng

        async def actor(aid: int):
            for seq in range(self.ops):
                # Per-op idempotence marker: a retry after
                # commit_unknown_result whose original actually LANDED
                # must not increment twice (same discipline as
                # WriteDuringRead's marker probe) — db.run retries
                # unknown results blindly.
                marker = self.prefix + b"!op%02d_%04d" % (aid, seq)

                async def op(tr, marker=marker):
                    if await tr.get(marker) is not None:
                        return  # the earlier attempt committed
                    k = self._key(int(rng.random_int(0, self.counters)))
                    cur = await tr.get(k)
                    tr.set(k, b"%d" % (int(cur or b"0") + 1))
                    tr.set(marker, b"done")

                await db.run(op)

        await all_of(
            [
                db.process.spawn(actor(a), f"incr{a}")
                for a in range(self.actors)
            ]
        )

    async def check(self, db, cluster) -> bool:
        out = {}

        async def read(tr):
            out["rows"] = await tr.get_range(self.prefix, self.prefix + b"\xff")

        await db.run(read)
        total = sum(
            int(v)
            for k, v in out["rows"]
            if not k.startswith(self.prefix + b"!")  # skip op markers
        )
        return total == self.actors * self.ops
