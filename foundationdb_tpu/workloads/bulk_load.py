"""BulkLoad: sequential batched loading lands every row byte-exact.

Ref: fdbserver/workloads/BulkLoad.actor.cpp (+ BulkSetup.actor.h, the
setup helper most reference workloads share) — load N rows in fixed-size
transaction batches, then verify presence, order, and byte-exact values
with ranged reads; a dropped batch, a partially applied batch, or a
shard-move race during loading each break it differently.
"""

from __future__ import annotations

from .base import TestWorkload


class BulkLoadWorkload(TestWorkload):
    name = "bulk_load"

    def __init__(self, rows: int = 400, batch: int = 50,
                 value_len: int = 64, prefix: bytes = b"bulk/"):
        self.rows = rows
        self.batch = batch
        self.value_len = value_len
        self.prefix = prefix

    def _key(self, i: int) -> bytes:
        return self.prefix + b"%08d" % i

    def _val(self, i: int) -> bytes:
        seed = b"%d|" % (i * 2654435761 % (1 << 32))
        return (seed * (self.value_len // len(seed) + 1))[: self.value_len]

    async def start(self, db, cluster):
        for lo in range(0, self.rows, self.batch):
            hi = min(self.rows, lo + self.batch)

            async def load(tr, lo=lo, hi=hi):
                for i in range(lo, hi):
                    tr.set(self._key(i), self._val(i))

            await db.run(load)

    async def check(self, db, cluster) -> bool:
        got = []
        cursor = self.prefix

        async def page(tr):
            nonlocal cursor
            rows = await tr.get_range(
                cursor, self.prefix + b"\xff", limit=128
            )
            got.extend(rows)
            if rows:
                from ..client.types import key_after

                cursor = key_after(rows[-1][0])
            return len(rows)

        while await db.run(page) > 0:
            pass
        assert len(got) == self.rows, f"{len(got)} rows != {self.rows}"
        for i, (k, v) in enumerate(got):
            assert k == self._key(i) and v == self._val(i), (
                f"row {i} wrong: {k[:24]}"
            )
        return True
