"""StatusWorkload: the status document keeps its schema under load.

Ref: fdbserver/workloads/StatusWorkload.actor.cpp — poll status
continuously during the run and validate every document against the
schema; a field that vanishes or changes type during a recovery or
chaos window is exactly the regression a one-shot test misses.
"""

from __future__ import annotations

from .base import TestWorkload

# section -> required field -> type(s)
_SCHEMA = {
    "client": {
        "database_status": dict,
        "coordinators": dict,
    },
    "cluster": {},
}


class StatusWorkload(TestWorkload):
    name = "status"

    def __init__(self, duration: float = 8.0, interval: float = 0.5):
        self.duration = duration
        self.interval = interval
        self.polls = 0

    def _validate(self, doc: dict):
        for section, fields in _SCHEMA.items():
            assert section in doc and isinstance(doc[section], dict), (
                f"status missing section {section}: {sorted(doc)}"
            )
            for f, ty in fields.items():
                assert f in doc[section] and isinstance(
                    doc[section][f], ty
                ), f"status {section}.{f} missing or wrong type"
        av = doc["client"]["database_status"].get("available")
        assert isinstance(av, bool)
        cl = doc["cluster"]
        if "recovery_state" in cl:
            assert isinstance(cl["recovery_state"].get("name"), str)
            assert isinstance(cl["recovery_state"].get("generation"), int)
        if "qos" in cl:
            assert isinstance(cl["qos"], dict)
        if "processes" in cl:
            assert isinstance(cl["processes"], dict)
        if "resolver" in cl:
            r = cl["resolver"]
            assert isinstance(r.get("count"), int) and r["count"] >= 1
            assert isinstance(r.get("total_resolved"), int)
            assert isinstance(r.get("backends"), list)
            assert isinstance(r.get("resolvers"), dict)
            for snap in r["resolvers"].values():
                assert isinstance(snap.get("counters"), dict)

    async def start(self, db, cluster):
        from ..server.status import cluster_status

        loop = cluster.loop
        end = loop.now() + self.duration
        while loop.now() < end:
            doc = cluster_status(cluster)
            self._validate(doc)
            self.polls += 1
            await loop.delay(self.interval)

    async def check(self, db, cluster) -> bool:
        return self.polls >= 3
