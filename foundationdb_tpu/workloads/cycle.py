"""Cycle workload: transactional pointer-chasing ring.

Ref: fdbserver/workloads/Cycle.actor.cpp — N nodes form a permutation
cycle; each transaction rotates three pointers; serializability keeps the
ring a single cycle through any concurrency, kills, or clogging.
"""

from __future__ import annotations

from .base import TestWorkload


class CycleWorkload(TestWorkload):
    name = "cycle"

    def __init__(self, nodes: int = 8, ops: int = 40, actors: int = 3,
                 prefix: bytes = b"cycle/"):
        self.nodes = nodes
        self.ops = ops
        self.actors = actors
        self.prefix = prefix

    def _key(self, i: int) -> bytes:
        return self.prefix + b"%04d" % i

    async def setup(self, db, cluster):
        async def init(tr):
            for i in range(self.nodes):
                tr.set(self._key(i), b"%04d" % ((i + 1) % self.nodes))

        await db.run(init)

    async def start(self, db, cluster):
        from ..flow.eventloop import all_of

        rng = cluster.loop.rng

        async def actor():
            for _ in range(self.ops):

                async def op(tr):
                    a = int(rng.random_int(0, self.nodes))
                    ka = self._key(a)
                    b = int((await tr.get(ka)).decode())
                    kb = self._key(b)
                    c = int((await tr.get(kb)).decode())
                    kc = self._key(c)
                    d = int((await tr.get(kc)).decode())
                    tr.set(ka, b"%04d" % c)
                    tr.set(kc, b"%04d" % b)
                    tr.set(kb, b"%04d" % d)

                await db.run(op)

        await all_of(
            [db.process.spawn(actor(), "cycle_actor") for _ in range(self.actors)]
        )

    async def check(self, db, cluster) -> bool:
        out = {}

        async def read(tr):
            out["ring"] = await tr.get_range(
                self.prefix, self.prefix + b"\xff"
            )

        await db.run(read)
        ring = {k: int(v.decode()) for k, v in out["ring"]}
        if len(ring) != self.nodes:
            return False
        seen, cur = set(), 0
        for _ in range(self.nodes):
            if cur in seen:
                return False
            seen.add(cur)
            cur = ring[self._key(cur)]
        return cur == 0 and len(seen) == self.nodes
