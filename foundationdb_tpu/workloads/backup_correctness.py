"""BackupAndRestoreCorrectness: continuous backup under chaos, verified
by restore.

Ref: fdbserver/workloads/BackupAndRestoreCorrectness.actor.cpp — a backup
runs WHILE other workloads mutate and chaos injectors clog/kill; at check
time the container is restored and the restored image must equal the live
database byte for byte (restoring at the fully-tailed version reproduces
the present state; intermediate targets are the PITR tests' business).
Composes with CycleWorkload et al: list this workload FIRST so its
restore completes before their own checks re-validate the (identical)
restored state.
"""

from __future__ import annotations

from .base import TestWorkload


class BackupCorrectnessWorkload(TestWorkload):
    name = "backup_correctness"

    def __init__(self, path: str = "bk_corr", duration: float = 2.0):
        self.path = path
        self.duration = duration
        self.agent = None
        self.restored_rows = -1

    async def setup(self, db, cluster):
        from ..fileio import SimFileSystem
        from ..layers.backup import ContinuousBackupAgent, open_container

        fs = getattr(cluster, "fs", None) or SimFileSystem(cluster.net)
        container = open_container(
            self.path, fs, cluster.net.process(f"bk:{self.path}")
        )
        self.agent = ContinuousBackupAgent(
            db,
            fs,
            [t.interface() for t in cluster.tlogs],
            container,
            tag=f"_backup/{self.path}",
        )
        await self.agent.start()

    async def start(self, db, cluster):
        loop = cluster.loop
        task = db.process.spawn(self.agent.run(), f"bkc:{self.path}")
        await loop.delay(self.duration)
        # Keep tailing until check() — chaos may still be running.
        self._task = task

    async def check(self, db, cluster) -> bool:
        loop = cluster.loop
        # Drain the tail to quiescence: two consecutive empty pulls.
        self.agent.stopped = True
        empties = 0
        for _ in range(400):
            n = await self.agent.tail_once()
            empties = empties + 1 if n == 0 else 0
            if empties >= 2:
                break
            await loop.delay(0.05)

        async def scan(tr):
            return await tr.get_range(b"", b"\xff", limit=1 << 20)

        before = await db.run(scan)
        await self.agent.restore()  # full restore at logged_through
        after = await db.run(scan)
        self.restored_rows = len(after)
        # Byte-exact: the restored image must reproduce the live state the
        # backup was tailing (ref: the workload's final data comparison).
        return before == after and len(after) > 0
