"""CommitBugCheck: committed writes are exactly-once and immediately
visible to the committer.

Ref: fdbserver/workloads/CommitBugCheck.actor.cpp — regression probes for
two historical commit bugs: (bug2) a client that commits value i+1 and
then reads with a fresh transaction must see EXACTLY i+1 — a smaller
value is a causality violation (GRV behind own commit), a larger one a
double-applied retry; (bug1 flavor) set/clear cycles under
commit_unknown_result must converge to the final committed state, never
a resurrected value.
"""

from __future__ import annotations

from ..flow.error import FdbError
from .base import TestWorkload


class CommitBugWorkload(TestWorkload):
    name = "commit_bug"

    def __init__(self, iterations: int = 30, prefix: bytes = b"cb/"):
        self.iterations = iterations
        self.prefix = prefix

    async def start(self, db, cluster):
        key = self.prefix + b"counter"
        i = 0
        while i < self.iterations:
            tr = db.create_transaction()
            try:
                val = await tr.get(key)
                num = int(val) if val is not None else 0
                assert num == i, (
                    f"iteration {i}: read {num} — "
                    + ("causality violation (own commit invisible)"
                       if num < i else "double-applied commit")
                )
                tr.set(key, b"%d" % (i + 1))
                await tr.commit()
                i += 1
            except FdbError as e:
                if e.name == "commit_unknown_result":
                    # Disambiguate by reading back: the counter IS the
                    # marker (monotone, single writer).
                    out = {}

                    async def probe(t2):
                        out["v"] = await t2.get(key)

                    await db.run(probe)
                    if out["v"] is not None and int(out["v"]) == i + 1:
                        i += 1
                    continue
                if e.name in ("not_committed", "transaction_too_old",
                              "future_version", "broken_promise",
                              "process_behind"):
                    continue
                raise

        # bug1 flavor: set/clear churn converges to the cleared state.
        for r in range(6):
            k = self.prefix + b"sc%d" % (r % 2)

            async def set_it(tr, k=k, r=r):
                tr.set(k, b"v%d" % r)

            async def clear_it(tr, k=k):
                tr.clear(k)

            await db.run(set_it)
            await db.run(clear_it)
        out = {}

        async def final(tr):
            out["rows"] = await tr.get_range(
                self.prefix + b"sc", self.prefix + b"sd"
            )

        await db.run(final)
        assert out["rows"] == [], f"cleared keys resurrected: {out['rows']}"

    async def check(self, db, cluster) -> bool:
        out = {}

        async def read(tr):
            out["v"] = await tr.get(self.prefix + b"counter")

        await db.run(read)
        assert int(out["v"]) == self.iterations
        return True
