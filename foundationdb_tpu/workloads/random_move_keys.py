"""RandomMoveKeys: random shard splits + moves racing live transactions.

Ref: fdbserver/workloads/RandomMoveKeys.actor.cpp — while load workloads
run, repeatedly pick a random key range and a random destination team and
drive the MoveKeys protocol; the invariant is that reads/writes never
break (clients chase wrong_shard_server through the location cache) and
the keyServers map stays well-formed.  check() verifies the final shard
map: contiguous coverage of the keyspace, no dangling in-flight
destinations, every owner a live storage.
"""

from __future__ import annotations

from .base import TestWorkload


class RandomMoveKeysWorkload(TestWorkload):
    name = "random_move_keys"

    def __init__(self, moves: int = 4, split_chance: float = 0.5,
                 prefix: bytes = b"cycle/", nodes: int = 8):
        self.moves = moves
        self.split_chance = split_chance
        self.prefix = prefix
        self.nodes = nodes  # split candidates drawn from the load's keyspace
        self.dd = None
        self.performed = 0

    async def setup(self, db, cluster):
        self.dd = cluster.data_distributor()
        await self.dd.register_storages(self.dd.storages)
        await self.dd.seed(["ss0"])
        # The system keyspace must stay on the seed team: split it off so
        # random moves only relocate user shards (the reference's moves are
        # clamped to normalKeys, RandomMoveKeys.actor.cpp).
        await self.dd.split(b"\xff")

    async def start(self, db, cluster):
        rng = cluster.loop.rng
        sids = sorted(self.dd.storages)
        for _ in range(self.moves):
            await cluster.loop.delay(0.2 + rng.random01() * 0.5)
            if rng.random01() < self.split_chance:
                at = self.prefix + b"%04d" % int(rng.random_int(0, self.nodes))
                await self.dd.split(at)
            shards = [
                (b, e)
                for b, e, _t, _d in await self.dd.read_shard_map()
                if b < b"\xff"
            ]
            if not shards:
                continue
            b, _e = shards[int(rng.random_int(0, len(shards)))]
            team_size = 1 + int(rng.random_int(0, min(2, len(sids))))
            dest = sorted(
                {
                    sids[int(rng.random_int(0, len(sids)))]
                    for _ in range(team_size)
                }
            )
            await self.dd.move(b, dest)
            self.performed += 1

    async def check(self, db, cluster) -> bool:
        shard_map = await self.dd.read_shard_map()
        if not shard_map:
            return False
        # Contiguous cover, settled moves, live owners.
        expect_begin = b""
        for b, e, team, dest in shard_map:
            if b != expect_begin:
                return False
            expect_begin = e
            if dest:  # an in-flight move left dangling
                return False
            if not team or not all(t in self.dd.storages for t in team):
                return False
        return self.performed > 0
