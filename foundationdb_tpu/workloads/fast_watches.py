"""FastTriggeredWatches: watches fire PROMPTLY, round after round.

Ref: fdbserver/workloads/FastTriggeredWatches.actor.cpp — arm a watch,
trigger it, measure the arm->fire latency; repeat.  A watch that fires
eventually-but-slowly (e.g. only on a durability fold or a poll cycle
instead of the mutation apply) passes WatchAndWait but fails here.
"""

from __future__ import annotations

from ..flow.error import FdbError
from .base import TestWorkload


class FastTriggeredWatchesWorkload(TestWorkload):
    name = "fast_watches"

    def __init__(self, rounds: int = 8, latency_bound: float = 1.0,
                 prefix: bytes = b"fw/"):
        self.rounds = rounds
        self.latency_bound = latency_bound
        self.prefix = prefix
        self.latencies = []

    async def start(self, db, cluster):
        loop = cluster.loop
        key = self.prefix + b"k"
        for r in range(self.rounds):
            async def put(tr, r=r):
                tr.set(key, b"base%d" % r)

            await db.run(put)
            tr = db.create_transaction()
            try:
                fut = await tr.watch(key)
                await tr.commit()
            except FdbError:
                continue
            t_armed = loop.now()

            async def trigger(tr2, r=r):
                tr2.set(key, b"trig%d" % r)

            await db.run(trigger)
            await fut
            self.latencies.append(loop.now() - t_armed)

    async def check(self, db, cluster) -> bool:
        assert len(self.latencies) >= self.rounds // 2
        worst = max(self.latencies)
        assert worst <= self.latency_bound, (
            f"watch fire latency {worst:.3f} > {self.latency_bound} "
            f"(all: {[round(x, 3) for x in self.latencies]})"
        )
        return True
