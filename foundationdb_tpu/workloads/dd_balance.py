"""DDBalance: data distribution converges to balanced shard counts.

Ref: fdbserver/workloads/DDBalance.actor.cpp — load spread over many
shards; the check is that DD's placement ends BALANCED: per-storage
serving shard counts within a tolerance, no shard stuck mid-move.  Run
with sim-scaled split thresholds so enough shards exist to balance.

The CALLER sets the sim-scaled split thresholds (dd_shard_max_bytes low,
dd_shard_min_bytes 0) around the run with its own try/finally: a knob
mutation owned by the workload cannot be restored reliably when start()
is abandoned by a runner timeout.
"""

from __future__ import annotations

from .base import TestWorkload


class DDBalanceWorkload(TestWorkload):
    name = "dd_balance"

    def __init__(self, rows: int = 240, value_len: int = 40,
                 tolerance: int = 2, prefix: bytes = b"ddb/"):
        self.rows = rows
        self.value_len = value_len
        self.tolerance = tolerance
        self.prefix = prefix
        self.final_counts = {}

    async def start(self, db, cluster):
        loop = cluster.loop
        for j in range(8):

            async def load(tr, j=j):
                for i in range(self.rows // 8):
                    tr.set(
                        self.prefix + b"%d%04d" % (j, i),
                        b"x" * self.value_len,
                    )

            await db.run(load)
        # Wait for split + rebalance to settle into tolerance.
        end = loop.now() + 40.0
        while loop.now() < end:
            counts = await self._shard_counts(db)
            self.final_counts = counts
            if (
                len(counts) >= 2
                and sum(counts.values()) >= 4
                and max(counts.values()) - min(counts.values())
                <= self.tolerance
            ):
                return
            await loop.delay(1.0)

    async def _shard_counts(self, db):
        from ..server import system_keys as sk

        async def txn(tr):
            tr.options["access_system_keys"] = True
            rows = await tr.get_range(
                sk.KEY_SERVERS_PREFIX, sk.KEY_SERVERS_END
            )
            counts: dict = {}
            for k, v in rows:
                src, dest, _end = sk.decode_key_servers(v)
                if dest:
                    continue  # mid-move; counted next poll
                for sid in src:
                    counts[sid] = counts.get(sid, 0) + 1
            return counts

        return await db.run(txn)

    async def check(self, db, cluster) -> bool:
        counts = self.final_counts
        assert len(counts) >= 2, f"no distribution happened: {counts}"
        spread = max(counts.values()) - min(counts.values())
        assert spread <= self.tolerance, (
            f"unbalanced placement: {counts} (spread {spread})"
        )
        return True
