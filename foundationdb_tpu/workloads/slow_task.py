"""SlowTaskWorkload: the slow-task profiler catches reactor hogs.

Ref: fdbserver/workloads/SlowTaskWorkload.actor.cpp — deliberately burn
the event loop inside one task and assert the runtime's slow-task
profiler surfaced it (a SlowTask trace event with the wall cost).  The
profiler is the production tool for "one actor stalls the whole
process"; this workload is its liveness check.
"""

from __future__ import annotations

import time

from .base import TestWorkload


class SlowTaskWorkload(TestWorkload):
    name = "slow_task"

    def __init__(self, burn_wall_s: float = 0.01):
        self.burn_wall_s = burn_wall_s

    async def start(self, db, cluster):
        from ..flow.trace import global_collector

        loop = cluster.loop
        self._collector = global_collector()
        # Baseline on the COMPLETE per-type tally, not an index into
        # find(): on a file-backed collector find() answers from the
        # bounded recent ring, so index slicing would mis-slice once the
        # ring rotates (flow/trace.py, ISSUE 10).
        self._before = self._collector.counts.get("SlowTask", 0)
        old = loop.slow_task_threshold
        loop.slow_task_threshold = self.burn_wall_s / 4
        try:
            # One loop step that burns real wall clock: exactly what the
            # profiler exists to catch.
            async def hog():
                t0 = time.perf_counter()  # fdblint: ignore[DET001]: the workload's PURPOSE is burning real cpu to trip the slow-task profiler; no virtual-time decision depends on it
                while time.perf_counter() - t0 < self.burn_wall_s:  # fdblint: ignore[DET001]: see above
                    sum(range(500))

            await db.process.spawn(hog(), "deliberate_hog")
            await loop.delay(0.01)
        finally:
            loop.slow_task_threshold = old

    async def check(self, db, cluster) -> bool:
        n_new = self._collector.counts.get("SlowTask", 0) - self._before
        assert n_new > 0, "slow-task profiler missed a deliberate reactor hog"
        # The still-retained tail of the new events (all of them for an
        # in-memory collector; the recent-ring remainder for file-backed).
        events = self._collector.find("SlowTask")
        fresh = events[max(0, len(events) - n_new):]
        assert fresh, "slow-task profiler missed a deliberate reactor hog"
        assert any(
            e.get("wall_seconds", 0) >= self.burn_wall_s / 4
            for e in fresh
        ), f"SlowTask events lack the wall cost: {fresh[:2]}"
        return True
