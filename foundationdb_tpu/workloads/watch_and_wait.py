"""WatchAndWait: mass watches all fire on change, none fire spuriously.

Ref: fdbserver/workloads/WatchAndWait.actor.cpp (a large watch
population all awaiting one trigger) + FastTriggeredWatches.actor.cpp
(watch latency on rapid triggers).  W watches are armed across a
keyspace; a writer then touches HALF the watched keys.  Every watch on a
touched key must fire, and no watch on an untouched key may fire — a
storage server dropping its watch map on a version fold, or waking
watchers on unrelated mutations, breaks one direction each.
"""

from __future__ import annotations

from ..flow.error import FdbError
from .base import TestWorkload


class WatchAndWaitWorkload(TestWorkload):
    name = "watch_and_wait"

    def __init__(self, watches: int = 16, prefix: bytes = b"waw/"):
        self.watches = watches
        self.prefix = prefix
        self.fired = set()

    def _key(self, i: int) -> bytes:
        return self.prefix + b"%04d" % i

    async def setup(self, db, cluster):
        async def init(tr):
            for i in range(self.watches):
                tr.set(self._key(i), b"init")

        await db.run(init)

    async def start(self, db, cluster):
        loop = cluster.loop

        async def watcher(i: int):
            while True:
                try:
                    tr = db.create_transaction()
                    fut = await tr.watch(self._key(i))
                    await tr.commit()
                    await fut
                    self.fired.add(i)
                    return
                except FdbError:
                    # Retryable (recovery, too-old): re-arm; an armed
                    # watch that already fired still counts via re-check.
                    got = {}

                    async def rd(t2, i=i):
                        got["v"] = await t2.get(self._key(i))

                    await db.run(rd)
                    if got["v"] != b"init":
                        self.fired.add(i)
                        return
                    await loop.delay(0.05)

        watchers = [
            db.process.spawn(watcher(i), f"waw{i}")
            for i in range(self.watches)
        ]
        await loop.delay(0.5)  # let the watch population arm

        async def touch(tr):
            for i in range(0, self.watches, 2):
                tr.set(self._key(i), b"changed")

        await db.run(touch)
        # Wait for every touched watch to fire (virtual time bounded by
        # the runner's timeout); untouched watchers stay parked.
        touched = set(range(0, self.watches, 2))
        while not touched <= self.fired:
            await loop.delay(0.1)
        for t in watchers:
            if not t.is_ready():
                t.cancel()

    async def check(self, db, cluster) -> bool:
        touched = set(range(0, self.watches, 2))
        untouched = set(range(1, self.watches, 2))
        assert touched <= self.fired, (
            f"watches never fired: {sorted(touched - self.fired)}"
        )
        spurious = self.fired & untouched
        assert not spurious, f"spurious watch fires: {sorted(spurious)}"
        return True
