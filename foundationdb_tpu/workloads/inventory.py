"""Inventory: stock conservation through concurrent transactional moves.

Ref: fdbserver/workloads/Inventory.actor.cpp — clients transact against a
product inventory; the invariant is CONSERVATION: units are moved, never
created or destroyed, so the grand total after any amount of contention,
retries, and chaos equals the seeded total exactly.  (Same family as
Increment/Cycle but over a two-sided move, which a lost update or a
half-applied transaction breaks in either direction.)
"""

from __future__ import annotations

from .base import TestWorkload


class InventoryWorkload(TestWorkload):
    name = "inventory"

    def __init__(self, products: int = 6, actors: int = 3, moves: int = 12,
                 initial: int = 100, prefix: bytes = b"inv/"):
        self.products = products
        self.actors = actors
        self.moves = moves
        self.initial = initial
        self.prefix = prefix

    def _key(self, i: int) -> bytes:
        return self.prefix + b"%04d" % i

    async def setup(self, db, cluster):
        async def fill(tr):
            for i in range(self.products):
                tr.set(self._key(i), b"%d" % self.initial)

        await db.run(fill)

    async def start(self, db, cluster):
        from ..flow.eventloop import all_of

        rng = cluster.loop.rng

        async def actor(aid: int):
            for seq in range(self.moves):
                src = int(rng.random_int(0, self.products))
                dst = int(rng.random_int(0, self.products))
                amount = int(rng.random_int(1, 10))
                marker = self.prefix + b"!mv%02d_%04d" % (aid, seq)

                async def move(tr, src=src, dst=dst, amount=amount,
                               marker=marker):
                    # Idempotence marker: an unknown-result retry whose
                    # original landed must not move the stock twice.
                    if await tr.get(marker) is not None:
                        return
                    s = int(await tr.get(self._key(src)) or b"0")
                    take = min(s, amount)
                    d = int(await tr.get(self._key(dst)) or b"0")
                    if src != dst:
                        tr.set(self._key(src), b"%d" % (s - take))
                        tr.set(self._key(dst), b"%d" % (d + take))
                    tr.set(marker, b"done")

                await db.run(move)

        await all_of(
            [
                db.process.spawn(actor(a), f"inv{a}")
                for a in range(self.actors)
            ]
        )

    async def check(self, db, cluster) -> bool:
        out = {}

        async def read(tr):
            # [prefix+"0", prefix+":") covers the %04d product keys and
            # excludes the "!mv" idempotence markers ("!" < "0").
            rows = await tr.get_range(self.prefix + b"0", self.prefix + b":")
            out["total"] = sum(int(v) for _k, v in rows)
            out["negative"] = [
                (k, v) for k, v in rows if int(v) < 0
            ]

        await db.run(read)
        expected = self.products * self.initial
        assert out["total"] == expected, (
            f"stock not conserved: {out['total']} != {expected}"
        )
        assert not out["negative"], f"negative stock: {out['negative']}"
        return True
