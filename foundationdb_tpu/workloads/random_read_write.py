"""RandomReadWrite: uniform-key read/write load, low contention.

Ref: fdbserver/workloads/ReadWrite.actor.cpp — N parallel actors each run
transactions with `reads_per_txn` point reads and `writes_per_txn` point
writes over a uniform keyspace; the counter invariant (every write is
`actor_id:seq`, checked for well-formedness at the end) plus throughput
counters.  This is BASELINE.json config 3 ("RandomReadWrite, 1 resolver,
uniform keys, low contention") — the differential acceptance gate runs it
against both conflict backends and compares histories.
"""

from __future__ import annotations

from .base import TestWorkload


class RandomReadWriteWorkload(TestWorkload):
    name = "random_read_write"

    def __init__(
        self,
        nodes: int = 200,
        actors: int = 4,
        txns_per_actor: int = 10,
        reads_per_txn: int = 3,
        writes_per_txn: int = 2,
        prefix: bytes = b"rrw/",
    ):
        self.nodes = nodes
        self.actors = actors
        self.txns_per_actor = txns_per_actor
        self.reads_per_txn = reads_per_txn
        self.writes_per_txn = writes_per_txn
        self.prefix = prefix
        self.committed = 0
        self.conflicts = 0

    def _key(self, i: int) -> bytes:
        return self.prefix + b"%08d" % i

    async def setup(self, db, cluster):
        async def init(tr):
            for i in range(0, self.nodes, 4):  # sparse initial population
                tr.set(self._key(i), b"init")

        await db.run(init)

    async def start(self, db, cluster):
        from ..flow.eventloop import all_of

        rng = cluster.loop.rng

        async def actor(aid: int):
            for seq in range(self.txns_per_actor):

                async def op(tr):
                    for _ in range(self.reads_per_txn):
                        await tr.get(self._key(int(rng.random_int(0, self.nodes))))
                    for _ in range(self.writes_per_txn):
                        tr.set(
                            self._key(int(rng.random_int(0, self.nodes))),
                            b"a%02d:%04d" % (aid, seq),
                        )

                await db.run(op)
                self.committed += 1

        await all_of(
            [
                db.process.spawn(actor(a), f"rrw_{a}")
                for a in range(self.actors)
            ]
        )

    async def check(self, db, cluster) -> bool:
        out = {}

        async def read(tr):
            out["rows"] = await tr.get_range(self.prefix, self.prefix + b"\xff")

        await db.run(read)
        # Every value must be an init marker or a well-formed actor write.
        for k, v in out["rows"]:
            if v == b"init":
                continue
            if not (v.startswith(b"a") and b":" in v):
                return False
        return self.committed == self.actors * self.txns_per_actor
