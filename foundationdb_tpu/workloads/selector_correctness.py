"""SelectorCorrectness: exhaustive KeySelector resolution sweep.

Ref: fdbserver/workloads/SelectorCorrectness.actor.cpp — for a known
keyspace, EVERY selector shape (anchor on/off keys, or_equal both ways,
offsets sweeping negative through positive past both ends) must resolve
exactly as the in-memory model says.  Random workloads sample this space;
this one enumerates it.
"""

from __future__ import annotations

from ..client.types import KeySelector
from .base import TestWorkload
from .write_during_read import clamp_to_prefix, model_get_key


class SelectorCorrectnessWorkload(TestWorkload):
    name = "selector_correctness"

    def __init__(self, nodes: int = 8, max_offset: int = 4,
                 prefix: bytes = b"sel/"):
        self.nodes = nodes
        self.max_offset = max_offset
        self.prefix = prefix
        self.checked = 0
        self.failures = []

    def _key(self, i: int) -> bytes:
        return self.prefix + b"%04d" % i

    async def setup(self, db, cluster):
        async def init(tr):
            tr.clear_range(self.prefix, self.prefix + b"\xff")
            for i in range(0, self.nodes, 2):  # every OTHER key present
                tr.set(self._key(i), b"v")

        await db.run(init)
        self.model = {
            self._key(i): b"v" for i in range(0, self.nodes, 2)
        }

    async def start(self, db, cluster):
        # Anchors: every present key, every ABSENT key, and both edges.
        anchors = [self._key(i) for i in range(self.nodes)]
        anchors += [self.prefix, self.prefix + b"\xff", self._key(0) + b"\x00"]
        tr = db.create_transaction()
        for anchor in anchors:
            for or_equal in (False, True):
                for off in range(-self.max_offset, self.max_offset + 1):
                    sel = KeySelector(key=anchor, or_equal=or_equal, offset=off)
                    got = await tr.get_key(sel)
                    want = model_get_key(self.model, sel)
                    got_c = clamp_to_prefix(got, self.prefix)
                    want_c = clamp_to_prefix(want, self.prefix)
                    self.checked += 1
                    if got_c != want_c:
                        self.failures.append(
                            f"({anchor!r},{or_equal},{off}): "
                            f"db={got!r} model={want!r}"
                        )

    async def check(self, db, cluster) -> bool:
        if self.failures:
            import sys

            for f in self.failures[:10]:
                print(f"[selector_correctness] {f}", file=sys.stderr)
        return not self.failures and self.checked > 0
