"""WriteDuringRead: RYW semantics under concurrent intra-transaction ops.

Ref: fdbserver/workloads/WriteDuringRead.actor.cpp — one client maintains a
byte-exact in-memory model of the database (`memory_db` = what this txn's
reads must see, `last_committed_db` = committed state) while issuing many
CONCURRENT operations inside each transaction: point reads, key-selector
resolutions, range reads (limits/reverse), sets, clears, range clears, and
atomic ops.  Every read's result is compared against the model computed at
the moment the read was ISSUED — a write racing with an in-flight read must
not leak into its result (the issue-time RYW snapshot in
client/transaction.py exists to guarantee exactly this).

Deviations from the reference, by design:
- Commits happen between op waves rather than racing ops (the reference
  tolerates transaction_cancelled/used_during_commit storms from the race;
  the used_during_commit guard itself is unit-tested separately).
- commit_unknown_result is resolved definitively by reading back a
  per-transaction marker key (the reference re-initializes the keyspace);
  the client's dummy-commit fence makes the outcome determinate.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..client.atomic import apply_atomic
from ..client.transaction import KeySelector, key_after
from ..client.types import MutationType
from ..flow.error import FdbError
from .base import TestWorkload

ATOMIC_OPS = [
    MutationType.ADD_VALUE,
    MutationType.AND_V2,
    MutationType.OR,
    MutationType.XOR,
    MutationType.MAX,
    MutationType.MIN_V2,
    MutationType.BYTE_MIN,
    MutationType.BYTE_MAX,
    MutationType.APPEND_IF_FITS,
]


def model_get_key(db: Dict[bytes, bytes], sel: KeySelector) -> bytes:
    """KeySelector resolution against a model dict, matching the client's
    documented semantics: index into the sorted key list at (first key
    {>|>=} sel.key) + offset - 1; b"" before the front, b"\\xff" past the
    end (ref: memoryGetKey WriteDuringRead.actor.cpp:118).  Shared by the
    WriteDuringRead and FuzzApi oracles so selector semantics cannot
    drift between them."""
    import bisect

    keys = sorted(db)
    start = key_after(sel.key) if sel.or_equal else sel.key
    idx = bisect.bisect_left(keys, start) + sel.offset - 1
    if idx < 0:
        return b""
    if idx >= len(keys):
        return b"\xff"
    return keys[idx]


def clamp_to_prefix(key: bytes, prefix: bytes) -> bytes:
    """Clamp a resolved key into a workload's prefix span, the way the
    reference clamps to its node range (WriteDuringRead.actor.cpp:148)."""
    return min(max(key, prefix), prefix + b"\xff")


class WriteDuringReadWorkload(TestWorkload):
    name = "write_during_read"

    def __init__(
        self,
        nodes: int = 40,
        txns: int = 12,
        ops_per_wave: int = 8,
        waves_per_txn: int = 3,
        value_size_max: int = 24,
        initial_key_density: float = 0.5,
        prefix: bytes = b"\x02wdr/",
        contention_actors: int = 0,
    ):
        self.nodes = nodes
        self.txns = txns
        self.ops_per_wave = ops_per_wave
        self.waves_per_txn = waves_per_txn
        self.value_size_max = value_size_max
        self.initial_key_density = initial_key_density
        self.prefix = prefix
        # Adversarial contention WITHOUT corrupting the memory model:
        # contender transactions declare write-CONFLICT ranges over the
        # node keys but carry zero mutations — the resolver aborts the
        # driver's overlapping reads (real not_committed outcomes in the
        # history) while the database bytes stay exactly what the model
        # says.  This is how the acceptance matrix gets high-contention
        # conflict decisions out of a single-driver memory-model workload.
        self.contention_actors = contention_actors
        self.marker = prefix + b"!marker"
        # Model state.
        self.memory_db: Dict[bytes, bytes] = {}
        self.last_committed: Dict[bytes, bytes] = {}
        self.success = True
        self.mismatches: List[str] = []
        self.committed_txns = 0
        self.conflicts = 0
        # Per-txn outcome log: the differential acceptance gate runs the
        # same seed under both conflict backends and compares these
        # histories entry by entry (BASELINE.json acceptance).
        self.history: List[tuple] = []

    # --- keys/values ---
    def _key(self, i: int) -> bytes:
        return self.prefix + b"%06d" % i

    def _rand_key(self, rng) -> bytes:
        return self._key(int(rng.random_int(0, self.nodes)))

    def _rand_value(self, rng) -> bytes:
        n = int(rng.random_int(0, self.value_size_max + 1))
        # Varied bytes so atomic and/or/xor do real work.
        return bytes(int(rng.random_int(0, 256)) for _ in range(n))

    def _rand_range(self, rng) -> Tuple[bytes, bytes]:
        a = int(rng.random_int(0, self.nodes))
        span = int(rng.random_int(0, 1 + min(self.nodes - a, 8)))
        return self._key(a), self._key(a + span)

    def _rand_selector(self, rng) -> KeySelector:
        scale = 1 << int(rng.random_int(0, 4))
        return KeySelector(
            key=self._rand_key(rng),
            or_equal=rng.random01() < 0.5,
            offset=int(rng.random_int(-scale, scale + 1)),
        )

    # --- the memory model (mirrors the reference's memoryGet* helpers) ---
    def _model_get(self, db: Dict[bytes, bytes], key: bytes) -> Optional[bytes]:
        return db.get(key)

    def _model_get_key(self, db: Dict[bytes, bytes], sel: KeySelector) -> bytes:
        return model_get_key(db, sel)

    def _model_get_range(
        self,
        db: Dict[bytes, bytes],
        begin: bytes,
        end: bytes,
        limit: int,
        reverse: bool,
    ) -> List[Tuple[bytes, bytes]]:
        keys = sorted(k for k in db if begin <= k < end)
        if reverse:
            keys = keys[::-1]
        return [(k, db[k]) for k in keys[:limit]]

    # --- op coroutines ---
    # Every op starts with a random stagger so writes land WHILE reads are
    # awaiting storage (the whole point of the workload).  After the
    # stagger, a read computes its expected value from the model and issues
    # the db read in the SAME task step (no await between) — matching the
    # client's issue-time RYW snapshot; a write updates the model and the
    # transaction atomically at its own issue point.
    async def _stagger(self, loop, rng):
        await loop.delay(rng.random01() * 0.003)

    async def _op_get(self, tr, rng, loop):
        await self._stagger(loop, rng)
        key = self._rand_key(rng)
        want = self._model_get(self.memory_db, key)
        got = await tr.get(key)
        if got != want:
            self._fail(f"get({key!r}): db={got!r} model={want!r}")

    async def _op_get_key(self, tr, rng, loop):
        await self._stagger(loop, rng)
        sel = self._rand_selector(rng)
        want = self._model_get_key(self.memory_db, sel)
        got = await tr.get_key(sel)
        # Keys outside the workload's prefix belong to other subsystems:
        # clamp both sides the way the reference clamps to its node range
        # (WriteDuringRead.actor.cpp:148 res > getKeyForIndex(nodes)).
        want = clamp_to_prefix(want, self.prefix)
        got = clamp_to_prefix(got, self.prefix)
        if got != want:
            self._fail(
                f"get_key({sel.key!r},{sel.or_equal},{sel.offset}): "
                f"db={got!r} model={want!r}"
            )

    async def _op_get_range(self, tr, rng, loop):
        await self._stagger(loop, rng)
        begin, end = self._rand_range(rng)
        limit = (
            1 << 30
            if rng.random01() < 0.5
            else int(rng.random_int(0, 2 * self.nodes))
        )
        reverse = rng.random01() < 0.3
        want = self._model_get_range(self.memory_db, begin, end, limit, reverse)
        got = await tr.get_range(begin, end, limit=limit, reverse=reverse)
        if got != want:
            self._fail(
                f"get_range({begin!r},{end!r},lim={limit},rev={reverse}): "
                f"db={len(got)} rows model={len(want)} rows; "
                f"first diff {next((p for p in zip(got, want) if p[0] != p[1]), None)}"
            )

    async def _op_set(self, tr, rng, loop):
        await self._stagger(loop, rng)
        key, value = self._rand_key(rng), self._rand_value(rng)
        self.memory_db[key] = value
        tr.set(key, value)

    async def _op_clear(self, tr, rng, loop):
        await self._stagger(loop, rng)
        key = self._rand_key(rng)
        self.memory_db.pop(key, None)
        tr.clear(key)

    async def _op_clear_range(self, tr, rng, loop):
        await self._stagger(loop, rng)
        begin, end = self._rand_range(rng)
        for k in [k for k in self.memory_db if begin <= k < end]:
            del self.memory_db[k]
        tr.clear_range(begin, end)

    async def _op_atomic(self, tr, rng, loop):
        await self._stagger(loop, rng)
        op = ATOMIC_OPS[int(rng.random_int(0, len(ATOMIC_OPS)))]
        key, operand = self._rand_key(rng), self._rand_value(rng)
        new = apply_atomic(op, self.memory_db.get(key), operand)
        if new is None:
            self.memory_db.pop(key, None)
        else:
            self.memory_db[key] = new
        tr.atomic_op(op, key, operand)

    def _fail(self, msg: str):
        self.success = False
        self.mismatches.append(msg)

    # --- phases ---
    async def setup(self, db, cluster):
        rng = cluster.loop.rng

        async def init(tr):
            tr.clear_range(self.prefix, self.prefix + b"\xff")
            self.memory_db = {}
            for i in range(self.nodes):
                if rng.random01() < self.initial_key_density:
                    k, v = self._key(i), self._rand_value(rng)
                    tr.set(k, v)
                    self.memory_db[k] = v

        await db.run(init)
        self.last_committed = dict(self.memory_db)

    async def start(self, db, cluster):
        from ..flow.eventloop import all_of

        rng = cluster.loop.rng
        proc = db.process
        done = {"driver": False}
        contenders = [
            proc.spawn(self._contender(db, cluster, done, c), f"wdr_cont{c}")
            for c in range(self.contention_actors)
        ]
        try:
            await self._drive(db, cluster, rng, proc)
        finally:
            # Contenders must stop even when the driver dies — leaked
            # actors would spin until the simulation's timeout.
            done["driver"] = True
        if contenders:
            await all_of(contenders)

    async def _drive(self, db, cluster, rng, proc):
        from ..flow.eventloop import all_of

        txn_seq = 0
        while txn_seq < self.txns:
            txn_seq += 1
            tr = db.create_transaction()
            marker_val = b"txn%06d" % txn_seq
            tr.set(self.marker, marker_val)
            self.memory_db[self.marker] = marker_val
            try:
                loop = cluster.loop
                for _wave in range(self.waves_per_txn):
                    ops = []
                    for _ in range(self.ops_per_wave):
                        r = rng.random01()
                        if r < 0.18:
                            ops.append(self._op_get(tr, rng, loop))
                        elif r < 0.30:
                            ops.append(self._op_get_key(tr, rng, loop))
                        elif r < 0.48:
                            ops.append(self._op_get_range(tr, rng, loop))
                        elif r < 0.66:
                            ops.append(self._op_set(tr, rng, loop))
                        elif r < 0.76:
                            ops.append(self._op_clear(tr, rng, loop))
                        elif r < 0.84:
                            ops.append(self._op_clear_range(tr, rng, loop))
                        else:
                            ops.append(self._op_atomic(tr, rng, loop))
                    if ops:
                        await all_of(
                            [proc.spawn(o, "wdr_op") for o in ops]
                        )
                await tr.commit()
                self.committed_txns += 1
                self.last_committed = dict(self.memory_db)  # fdblint: ignore[RACE004]: workload model protocol — ops mutate the model only inside the txn window and _drive reconciles at commit/conflict boundaries
                self.history.append(("commit", txn_seq))
            except FdbError as e:
                if e.name == "not_committed":
                    self.conflicts += 1
                    self.memory_db = dict(self.last_committed)
                    self.history.append(("conflict", txn_seq))
                elif e.name == "commit_unknown_result":
                    # The dummy-commit fence has run: the outcome is frozen.
                    # The marker key tells us which way it went.
                    committed = {}

                    async def probe(tr2):
                        committed["marker"] = await tr2.get(self.marker)

                    await db.run(probe)
                    if committed["marker"] == marker_val:
                        self.committed_txns += 1
                        self.last_committed = dict(self.memory_db)
                        self.history.append(("unknown-committed", txn_seq))
                    else:
                        self.memory_db = dict(self.last_committed)  # fdblint: ignore[RACE004]: workload model protocol — rollback runs only in _drive between op batches, with no op coroutine in flight
                        self.history.append(("unknown-lost", txn_seq))
                elif e.is_retryable_in_transaction() or e.name == "broken_promise":
                    self.memory_db = dict(self.last_committed)
                    self.history.append(("retry", txn_seq))
                    await cluster.loop.delay(0.05)
                else:
                    raise

    async def _contender(self, db, cluster, done, cid: int):
        """Write-conflict-only pressure (see __init__): conflicts with the
        driver's reads at the resolver, mutates nothing."""
        from ..flow.error import FdbError

        rng = cluster.loop.rng
        while not done["driver"]:
            tr = db.create_transaction()
            a = int(rng.random_int(0, self.nodes))
            span = 1 + int(rng.random_int(0, 4))
            tr.add_write_conflict_range(self._key(a), self._key(a + span))
            try:
                await tr.get_read_version()
                await tr.commit()
            except FdbError:
                pass  # contender outcomes are irrelevant
            await cluster.loop.delay(0.002 + rng.random01() * 0.01)

    async def check(self, db, cluster) -> bool:
        final = {}

        async def read(tr):
            final["rows"] = await tr.get_range(
                self.prefix, self.prefix + b"\xff"
            )

        await db.run(read)
        want = sorted(self.last_committed.items())
        if final["rows"] != want:
            self._fail(
                f"final state: db={len(final['rows'])} rows, "
                f"model={len(want)} rows"
            )
        if self.mismatches:
            import sys

            for m in self.mismatches[:10]:
                print(f"[write_during_read] MISMATCH: {m}", file=sys.stderr)
        return self.success and self.committed_txns > 0
